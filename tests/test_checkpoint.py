"""Checkpoint tier tests: best/last policy parity with Lightning's
ModelCheckpoint (jobs/train_lightning_ddp.py:103-110) + full-state resume
(the capability the reference lacks)."""

import glob
import os

import jax
import jax.numpy as jnp
import numpy as np

from dct_tpu.checkpoint.manager import (
    BestLastCheckpointer,
    TrainStateCheckpointer,
    load_checkpoint,
    save_checkpoint,
)
from dct_tpu.config import ModelConfig
from dct_tpu.models.registry import get_model
from dct_tpu.train.state import create_train_state
from dct_tpu.train.steps import make_train_step


def _params():
    model = get_model(ModelConfig(), input_dim=5)
    return model, model.init(jax.random.PRNGKey(0), jnp.zeros((1, 5)))


def test_roundtrip(tmp_path):
    model, params = _params()
    meta = {"input_dim": 5, "feature_names": ["a_norm"], "model": "weather_mlp"}
    path = save_checkpoint(str(tmp_path / "m.ckpt"), params, meta)
    loaded, meta2 = load_checkpoint(path)
    assert meta2["input_dim"] == 5
    assert meta2["feature_names"] == ["a_norm"]
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        jax.device_get(params),
        loaded,
    )


def test_best_last_policy(tmp_path):
    _, params = _params()
    ck = BestLastCheckpointer(str(tmp_path))
    meta = {"input_dim": 5}

    assert ck.update(epoch=0, metrics={"val_loss": 0.9, "val_acc": 0.5}, params=params, meta=meta)
    first_best = ck.best_model_path
    assert os.path.basename(first_best) == "weather-best-00-0.90.ckpt"
    assert os.path.exists(ck.last_path)

    # Worse epoch: last updates, best stays.
    assert not ck.update(epoch=1, metrics={"val_loss": 1.2, "val_acc": 0.4}, params=params, meta=meta)
    assert ck.best_model_path == first_best

    # Better epoch: old best removed (save_top_k=1).
    assert ck.update(epoch=2, metrics={"val_loss": 0.5, "val_acc": 0.8}, params=params, meta=meta)
    assert os.path.basename(ck.best_model_path) == "weather-best-02-0.50.ckpt"
    assert not os.path.exists(first_best)
    ckpts = glob.glob(os.path.join(str(tmp_path), "*.ckpt"))
    assert sorted(os.path.basename(p) for p in ckpts) == [
        "last.ckpt",
        "weather-best-02-0.50.ckpt",
    ]

    # Best-file meta records its epoch metrics.
    _, meta_best = load_checkpoint(ck.best_model_path)
    assert meta_best["epoch"] == 2
    assert abs(meta_best["val_loss"] - 0.5) < 1e-9


def test_train_state_resume(tmp_path, rng):
    model = get_model(ModelConfig(dropout=0.0), input_dim=5)
    state = create_train_state(model, input_dim=5, lr=0.01, seed=1)
    step = make_train_step(donate=False)
    x = jnp.asarray(rng.standard_normal((8, 5)).astype(np.float32))
    y = jnp.asarray(rng.integers(0, 2, 8).astype(np.int32))
    w = jnp.ones(8)
    for _ in range(3):
        state, _ = step(state, x, y, w)

    ckptr = TrainStateCheckpointer(str(tmp_path))
    ckptr.save(state)
    assert ckptr.exists()

    fresh = create_train_state(model, input_dim=5, lr=0.01, seed=1)
    restored = ckptr.restore(fresh)
    assert int(restored.step) == 3
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b)),
        jax.device_get(state.params),
        jax.device_get(restored.params),
    )

    # Resumed training continues identically to uninterrupted training.
    cont_a, _ = step(state, x, y, w)
    cont_b, _ = step(restored, x, y, w)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-7),
        jax.device_get(cont_a.params),
        jax.device_get(cont_b.params),
    )


def test_async_save_overlaps_and_rotates(tmp_path):
    """save_async publishes identical content to save, keeps the rotation
    invariants under back-to-back saves, and restore/exists join the
    in-flight write."""
    from dct_tpu.checkpoint.manager import TrainStateCheckpointer
    from dct_tpu.config import ModelConfig
    from dct_tpu.models.registry import get_model
    from dct_tpu.train.state import create_train_state

    model = get_model(ModelConfig(dropout=0.0), input_dim=5)
    state = create_train_state(model, input_dim=5, lr=0.01, seed=0)
    ck = TrainStateCheckpointer(str(tmp_path))
    ck.save_async(state, meta={"epochs_completed": 1, "target_epochs": 3})
    ck.save_async(
        state.replace(step=state.step + 7),
        meta={"epochs_completed": 2, "target_epochs": 3},
    )
    assert ck.exists()  # joins the write
    assert ck.load_meta() == {"epochs_completed": 2, "target_epochs": 3}
    restored = ck.restore(
        create_train_state(model, input_dim=5, lr=0.01, seed=1)
    )
    assert int(restored.step) == 7
    import os as _os

    assert sorted(_os.listdir(str(tmp_path))) == ["state"]


def test_async_save_failure_is_loud(tmp_path, monkeypatch):
    """A failed background write must raise at the next join, not report
    success over a stale checkpoint."""
    from dct_tpu.checkpoint.manager import TrainStateCheckpointer
    from dct_tpu.config import ModelConfig
    from dct_tpu.models.registry import get_model
    from dct_tpu.train.state import create_train_state

    model = get_model(ModelConfig(dropout=0.0), input_dim=5)
    state = create_train_state(model, input_dim=5, lr=0.01, seed=0)
    ck = TrainStateCheckpointer(str(tmp_path))
    monkeypatch.setattr(
        ck, "_publish",
        lambda *a, **k: (_ for _ in ()).throw(OSError("disk full")),
    )
    ck.save_async(state)
    import pytest as _pytest

    with _pytest.raises(RuntimeError, match="checkpoint write failed"):
        ck.wait()
    # The error is consumed; subsequent operations work again.
    monkeypatch.undo()
    ck.save(state)
    assert ck.exists()
