"""Transformer family: windows, forward, and DP x TP x SP training
equivalence on the virtual 8-device mesh.

The invariant under test is the same one the launcher rig asserts for DDP:
parallelism must be a LAYOUT decision, not a model change — the sharded
train step follows the single-device trajectory."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dct_tpu.config import MeshConfig, ModelConfig
from dct_tpu.data.windows import make_windows
from dct_tpu.models.registry import get_model
from dct_tpu.ops.attention import make_attention_fn
from dct_tpu.parallel.mesh import batch_sharding, make_mesh
from dct_tpu.parallel.sharding_rules import (
    shard_state_with_rules,
    spec_for_path,
    state_shardings,
)
from dct_tpu.train.state import create_train_state
from dct_tpu.train.steps import make_train_step

SEQ, F = 16, 5
CFG = ModelConfig(
    name="weather_transformer", seq_len=SEQ, d_model=32, n_heads=4,
    n_layers=2, d_ff=64, dropout=0.1,
)


def _state(attn_fn=None, seed=42):
    model = get_model(CFG, input_dim=F, attn_fn=attn_fn)
    return create_train_state(
        model, input_dim=F, lr=1e-3, seed=seed, example_shape=(1, SEQ, F)
    )


def _batch(rng, b=16):
    x = rng.standard_normal((b, SEQ, F)).astype(np.float32)
    y = rng.integers(0, 2, b).astype(np.int32)
    w = np.ones(b, np.float32)
    return x, y, w


def test_windows_contract(weather_data):
    win = make_windows(weather_data, seq_len=SEQ)
    assert win.features.shape == (len(weather_data) - SEQ, SEQ, F)
    # Window i = rows [i, i+SEQ); label = row i+SEQ's (next-step target).
    np.testing.assert_array_equal(
        win.features[3], weather_data.features[3 : 3 + SEQ]
    )
    assert win.labels[3] == weather_data.labels[3 + SEQ]


def test_forward_shape_and_dtype(rng):
    state = _state()
    x, _, _ = _batch(rng, b=4)
    logits = state.apply_fn(state.params, x)
    assert logits.shape == (4, 2)
    assert logits.dtype == jnp.float32


def test_remat_is_layout_not_math(rng):
    """DCT_REMAT (activation rematerialization) must change ONLY the
    backward's memory schedule: identical param tree, identical loss,
    identical gradients, and the remat primitive actually present in the
    grad program (i.e. the flag is not silently ignored)."""
    import dataclasses

    cfg_remat = dataclasses.replace(CFG, remat=True)
    model = get_model(CFG, input_dim=F)
    model_r = get_model(cfg_remat, input_dim=F)
    state = create_train_state(
        model, input_dim=F, lr=1e-3, seed=42, example_shape=(1, SEQ, F)
    )
    state_r = create_train_state(
        model_r, input_dim=F, lr=1e-3, seed=42, example_shape=(1, SEQ, F)
    )
    assert jax.tree_util.tree_structure(
        state.params
    ) == jax.tree_util.tree_structure(state_r.params)

    x, y, w = _batch(rng, b=8)
    step = make_train_step(donate=False)
    s1, m1 = step(state, x, y, w)
    s2, m2 = step(state_r, x, y, w)
    assert float(m1["train_loss"]) == pytest.approx(
        float(m2["train_loss"]), rel=1e-6
    )
    # "Identical" up to float32 re-execution: remat RECOMPUTES the
    # forward inside the backward, so XLA fuses/orders the same
    # reductions differently and gradients differ at the few-ulp level
    # (observed ~1e-7 on grads). Adam then NORMALIZES each update by
    # sqrt(v) — near-zero second moments amplify those ulps into the
    # 1e-5 range on the post-step params. atol=1e-4 stays orders of
    # magnitude below any real math change (a wrong loss or a dropped
    # term shifts params at the 1e-2+ level) while tolerating the
    # schedule-induced noise.
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-4
        ),
        jax.device_get(s1.params),
        jax.device_get(s2.params),
    )

    def loss_r(params):
        return state_r.apply_fn(params, x, train=False).sum()

    jaxpr_text = str(jax.make_jaxpr(jax.grad(loss_r))(state_r.params))
    assert "remat" in jaxpr_text or "checkpoint" in jaxpr_text, (
        "remat flag did not reach the grad program"
    )


def test_sharding_rules_specs():
    state = _state()
    shardings = state_shardings(state, make_mesh(MeshConfig(data=2, model=2, seq=2)))
    flat = jax.tree_util.tree_flatten_with_path(shardings)[0]
    specs = {
        "/".join(str(getattr(k, "key", k)) for k in path): s.spec
        for path, s in flat
    }
    qkv = [
        v for k, v in specs.items()
        if "qkv_proj" in k and k.endswith("kernel") and "opt_state" not in k
    ]
    o = [
        v for k, v in specs.items()
        if "o_proj" in k and k.endswith("kernel") and "opt_state" not in k
    ]
    assert qkv and all(s == jax.sharding.PartitionSpec(None, "model") for s in qkv)
    assert o and all(s == jax.sharding.PartitionSpec("model", None) for s in o)
    # Adam moments shard identically to their params (opt_state paths).
    opt_qkv = [
        v for k, v in specs.items()
        if "opt_state" in k and "qkv_proj" in k and k.endswith("kernel")
    ]
    # mu + nu per layer -> twice the param count, same specs.
    assert len(opt_qkv) == 2 * len(qkv)
    assert all(s == jax.sharding.PartitionSpec(None, "model") for s in opt_qkv)


@pytest.mark.slow
@pytest.mark.parametrize("sp_engine", ["ring", "a2a"])
def test_dp_tp_sp_training_matches_single_device(rng, monkeypatch, sp_engine):
    """3 train steps on a (data=2, model=2, seq=2) mesh == 3 single-device
    steps: same losses, same final params (fp tolerance). Runs once per
    SP engine (ring ppermute / Ulysses all-to-all, DCT_SP_ENGINE)."""
    monkeypatch.setenv("DCT_SP_ENGINE", sp_engine)
    mesh = make_mesh(MeshConfig(data=2, model=2, seq=2))

    # Single-device oracle (dense attention).
    s_ref = _state()
    step_ref = make_train_step(donate=False)

    # Sharded run: SP attention over seq, params TP over model, batch DP.
    s_tpu = _state(attn_fn=make_attention_fn(mesh))
    s_tpu = shard_state_with_rules(s_tpu, mesh)
    step_tpu = make_train_step(donate=False)

    losses_ref, losses_tpu = [], []
    for i in range(3):
        x, y, w = _batch(rng, b=16)
        gx = jax.device_put(x, batch_sharding(mesh))
        gy = jax.device_put(y, batch_sharding(mesh))
        gw = jax.device_put(w, batch_sharding(mesh))
        s_ref, m_ref = step_ref(s_ref, jnp.asarray(x), jnp.asarray(y), jnp.asarray(w))
        s_tpu, m_tpu = step_tpu(s_tpu, gx, gy, gw)
        losses_ref.append(float(m_ref["train_loss"]))
        losses_tpu.append(float(m_tpu["train_loss"]))

    np.testing.assert_allclose(losses_tpu, losses_ref, rtol=1e-4)
    p_ref = jax.tree.map(np.asarray, jax.device_get(s_ref.params))
    p_tpu = jax.tree.map(np.asarray, jax.device_get(s_tpu.params))
    # a2a's reduction order perturbs Adam's qkv-bias update by ~1e-4
    # after 3 steps (losses are bit-identical); ring keeps its original
    # strictness.
    atol = 2e-4 if sp_engine == "a2a" else 1e-4
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, atol=atol), p_ref, p_tpu
    )


@pytest.mark.slow
def test_transformer_learns(rng):
    """Sanity: loss decreases on a learnable synthetic relation."""
    model = get_model(CFG, input_dim=F)
    state = create_train_state(
        model, input_dim=F, lr=3e-3, seed=42, example_shape=(1, SEQ, F)
    )
    step = make_train_step(donate=False)
    x = rng.standard_normal((64, SEQ, F)).astype(np.float32)
    y = (x[:, -1, 0] > 0).astype(np.int32)  # label = sign of last row's 1st feature
    w = np.ones(64, np.float32)
    first = None
    for _ in range(100):
        state, m = step(state, jnp.asarray(x), jnp.asarray(y), jnp.asarray(w))
        first = first if first is not None else float(m["train_loss"])
    assert float(m["train_loss"]) < first * 0.5
