"""Platform-contract tests: execute the three import-gated integrations
(real Airflow, pyspark, mlflow) against fakes carrying the REAL APIs'
signatures (VERDICT r2 "What's missing" 1-3).

The production code paths covered here — ``compat``'s real-import branch,
``spark_job.preprocess_with_spark``, ``MlflowTracking`` — are the code
most likely to break against the live platform (a wrong kwarg ships
silently when only the fallback paths run in CI). The fakes live in
``tests/fakes/`` and bind calls the way the real libraries would:
explicit transcribed signatures, evaluated semantics, real return types.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def _module_sandbox():
    """Install/teardown helper: whatever fake module trees a test installs
    are removed (or the originals restored) afterwards, so the rest of the
    suite keeps exercising the ImportError fallback branches."""
    touched: dict[str, object | None] = {}

    def sandbox(installer, *names):
        for n in names:
            if n not in touched:
                touched[n] = sys.modules.get(n)
        installer()

    yield sandbox
    for name, orig in touched.items():
        if orig is None:
            sys.modules.pop(name, None)
        else:
            sys.modules[name] = orig


# --- Airflow: the five DAG files through the real-import branch ---------


def test_dag_files_construct_on_real_airflow_api():
    """With a faithful ``airflow`` package installed, compat re-exports
    the real classes and every DAG file must bind its constructor calls
    against the Airflow 2.7 signatures — the check a production
    scheduler's DagBag import would perform (reference Dockerfile:2)."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tests", "fakes", "drive_airflow_dags.py")],
        capture_output=True,
        text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
        timeout=180,
    )
    assert proc.returncode == 0, proc.stderr
    registry = json.loads(proc.stdout.strip().splitlines()[-1])
    assert set(registry) == {
        "spark_etl_pipeline",
        "pytorch_training_pipeline",
        "distributed_data_pipeline",
        "azure_manual_deploy",
        "azure_automated_rollout",
    }
    etl = registry["spark_etl_pipeline"]
    assert etl["schedule"] == "@daily"
    assert "trigger_training_pipeline" in etl["tasks"]
    # `>>` chaining worked against the real-API operators.
    assert etl["downstream"]["verify_output"] == ["trigger_training_pipeline"]


def test_compat_allowlists_match_real_airflow_surface():
    """Every kwarg the compat shim accepts must exist on the transcribed
    real signatures — a shim allow-list looser than the real API would let
    a DAG file pass CI and fail on the production scheduler."""
    import inspect

    from dct_tpu.orchestration import compat
    from tests.fakes import fake_airflow

    real_dag = set(inspect.signature(fake_airflow.DAG.__init__).parameters) - {
        "self", "dag_id"
    }
    assert compat._DAG_PARAMS <= real_dag, (
        compat._DAG_PARAMS - real_dag
    )

    real_base = set(
        inspect.signature(fake_airflow.BaseOperator.__init__).parameters
    ) - {"self", "task_id"}
    assert compat._BASE_OPERATOR_PARAMS <= real_base, (
        compat._BASE_OPERATOR_PARAMS - real_base
    )

    for name, cls in (
        ("BashOperator", fake_airflow.BashOperator),
        ("PythonOperator", fake_airflow.PythonOperator),
        ("TriggerDagRunOperator", fake_airflow.TriggerDagRunOperator),
    ):
        own = set(inspect.signature(cls.__init__).parameters) - {
            "self", "kwargs", "bash_command", "python_callable",
            "trigger_dag_id",
        }
        extra = compat._OPERATOR_EXTRA_PARAMS[name]
        assert extra <= own, f"{name}: {extra - own}"


def test_fake_airflow_list_on_left_chaining():
    """Real Airflow supports `[t1, t2] >> op` (list dispatches to
    op.__rrshift__); the fake must reproduce it, not AttributeError
    (ADVICE r3)."""
    from tests.fakes import fake_airflow

    with fake_airflow.DAG(dag_id="chain_test") as dag:
        t1 = fake_airflow.BashOperator(task_id="t1", bash_command="true")
        t2 = fake_airflow.BashOperator(task_id="t2", bash_command="true")
        join = fake_airflow.BashOperator(task_id="join", bash_command="true")
        [t1, t2] >> join

    assert join.upstream == [t1, t2]
    assert join in t1.downstream and join in t2.downstream
    assert set(dag.tasks) == {"t1", "t2", "join"}


# --- pyspark: the Spark ETL transform actually executes -----------------


def test_spark_job_runs_and_matches_native_engine(tmp_path, _module_sandbox):
    """``preprocess_with_spark`` executes its full pyspark call sequence
    against the pandas-backed fake and must produce numerically identical
    output (parquet + stats.json + drift report) to the native engine —
    the parity the reference relies on when it swaps engines."""
    from tests.fakes import fake_pyspark

    _module_sandbox(
        fake_pyspark.install, "pyspark", "pyspark.sql", "pyspark.sql.functions"
    )

    from dct_tpu.data.dataset import load_processed_dataset
    from dct_tpu.data.synthetic import generate_weather_csv
    from dct_tpu.etl.preprocess import preprocess_csv_to_parquet
    from dct_tpu.etl.spark_job import preprocess_with_spark

    csv = str(tmp_path / "raw" / "weather.csv")
    generate_weather_csv(csv, rows=500, seed=11)

    native_dir = str(tmp_path / "native")
    spark_dir = str(tmp_path / "spark")
    preprocess_csv_to_parquet(csv, native_dir)
    out = preprocess_with_spark(csv, spark_dir)
    assert out == os.path.join(spark_dir, "data.parquet")
    assert os.path.exists(os.path.join(out, "_SUCCESS"))

    ds_native = load_processed_dataset(native_dir)
    ds_spark = load_processed_dataset(spark_dir)
    np.testing.assert_allclose(
        ds_spark.features, ds_native.features, rtol=1e-6, atol=1e-9
    )
    np.testing.assert_array_equal(ds_spark.labels, ds_native.labels)

    with open(os.path.join(native_dir, "stats.json")) as f:
        st_native = json.load(f)
    with open(os.path.join(spark_dir, "stats.json")) as f:
        st_spark = json.load(f)
    assert st_spark["rows"] == st_native["rows"]
    assert st_spark["label_rate"] == pytest.approx(st_native["label_rate"])
    for name, fs in st_native["features"].items():
        assert st_spark["features"][name]["mean"] == pytest.approx(fs["mean"])
        assert st_spark["features"][name]["std"] == pytest.approx(fs["std"])


def test_spark_job_drift_report_on_second_run(tmp_path, _module_sandbox):
    """Second Spark run against a shifted distribution must write the same
    drift report the native engine does (shared machinery, driver-side)."""
    from tests.fakes import fake_pyspark

    _module_sandbox(
        fake_pyspark.install, "pyspark", "pyspark.sql", "pyspark.sql.functions"
    )

    from dct_tpu.data.synthetic import generate_weather_csv
    from dct_tpu.etl.spark_job import preprocess_with_spark

    out_dir = str(tmp_path / "out")
    csv1 = str(tmp_path / "w1.csv")
    generate_weather_csv(csv1, rows=400, seed=1)
    preprocess_with_spark(csv1, out_dir)
    assert not os.path.exists(os.path.join(out_dir, "drift_report.json"))

    import pandas as pd

    df = pd.read_csv(csv1)
    df["Temperature"] += 5 * float(df["Temperature"].std())
    csv2 = str(tmp_path / "w2.csv")
    df.to_csv(csv2, index=False)
    preprocess_with_spark(csv2, out_dir)
    with open(os.path.join(out_dir, "drift_report.json")) as f:
        report = json.load(f)
    assert report["any_drift"] is True
    assert report["features"]["Temperature"]["drifted"] is True


# --- mlflow: the adapter's full client sequence -------------------------


@pytest.fixture
def mlflow_fake(_module_sandbox):
    from tests.fakes import fake_mlflow

    fake_mlflow.reset()
    _module_sandbox(
        fake_mlflow.install, "mlflow", "mlflow.tracking", "mlflow.artifacts"
    )
    yield fake_mlflow
    # reset() also rmtrees the on-disk artifact root — without the
    # teardown the last test's tempdir (with copied .ckpts) leaks.
    fake_mlflow.reset()


def test_mlflow_tracking_full_round_trip(tmp_path, mlflow_fake):
    """start_run -> log_params -> log_metrics -> log_artifact -> end_run
    -> search_best_run -> download_artifacts, all through the real mlflow
    call signatures (reference jobs/train_lightning_ddp.py:92-96)."""
    from dct_tpu.tracking.client import MlflowTracking

    tracker = MlflowTracking("http://mlflow:5000", experiment="weather_forecasting")
    assert mlflow_fake.STORE.tracking_uri == "http://mlflow:5000"

    run_id = tracker.start_run(params={"lr": 0.01, "batch_size": 4, "skipme": None})
    tracker.log_metrics({"train_loss": 0.8, "val_loss": 0.5, "val_acc": 0.7}, step=0)
    tracker.log_metrics({"train_loss": 0.4, "val_loss": 0.3, "val_acc": 0.9}, step=1)

    ckpt = tmp_path / "weather-best-01-0.30.ckpt"
    ckpt.write_bytes(b"weights")
    tracker.log_artifact(str(ckpt), "best_checkpoints")
    tracker.end_run()

    rec = mlflow_fake.STORE.runs[run_id]
    assert rec["status"] == "FINISHED"
    assert rec["params"] == {"lr": "0.01", "batch_size": "4"}  # None filtered
    assert rec["metrics"]["val_loss"] == pytest.approx(0.3)

    best = tracker.search_best_run("val_loss", "min")
    assert best is not None and best.run_id == run_id
    assert best.metrics["val_loss"] == pytest.approx(0.3)

    dst = str(tmp_path / "dl")
    out = tracker.download_artifacts(run_id, "best_checkpoints", dst)
    assert os.path.exists(os.path.join(out, ckpt.name))


def test_mlflow_search_orders_and_misses(mlflow_fake, tmp_path):
    from dct_tpu.tracking.client import MlflowTracking

    tracker = MlflowTracking("http://mlflow:5000")
    for loss in (0.9, 0.2, 0.5):
        tracker.start_run(params=None)
        tracker.log_metrics({"val_loss": loss}, step=0)
        tracker.end_run()
    best = tracker.search_best_run("val_loss", "min")
    assert best.metrics["val_loss"] == pytest.approx(0.2)
    worst = tracker.search_best_run("val_loss", "max")
    assert worst.metrics["val_loss"] == pytest.approx(0.9)
    # Unknown experiment -> None, not an exception (deploy DAG first run).
    empty = MlflowTracking("http://mlflow:5000", experiment="does_not_exist_yet")
    mlflow_fake.STORE.experiments.pop("does_not_exist_yet")
    assert empty.search_best_run() is None


def test_get_tracker_picks_mlflow_when_configured(mlflow_fake):
    from dct_tpu.tracking.client import MlflowTracking, get_tracker

    t = get_tracker(
        tracking_uri="http://mlflow:5000",
        experiment="weather_forecasting",
        coordinator=True,
    )
    assert isinstance(t, MlflowTracking)


def test_get_tracker_degrades_when_server_down(mlflow_fake, monkeypatch):
    """A down MLflow server must degrade to the local store, never fail
    training (the explicit version of the reference's silent retry)."""
    from dct_tpu.tracking.client import LocalTracking, get_tracker

    def boom(uri):
        raise ConnectionError("server down")

    monkeypatch.setattr(sys.modules["mlflow"], "set_tracking_uri", boom)
    t = get_tracker(
        tracking_uri="http://mlflow:5000",
        experiment="weather_forecasting",
        coordinator=True,
    )
    assert isinstance(t, LocalTracking)


# --- azure-ai-ml: the AzureEndpointClient executes the real SDK shapes --


@pytest.fixture
def azure_fake(_module_sandbox, monkeypatch):
    """Install the transcribed azure-ai-ml fake and the credential env the
    client reads (each var distinct, unlike the reference's clobber bug)."""
    from tests.fakes import fake_azure_ai_ml

    _module_sandbox(fake_azure_ai_ml.install, *(
        "azure", "azure.ai", "azure.ai.ml", "azure.ai.ml.entities",
        "azure.core", "azure.core.exceptions", "azure.identity",
    ))
    fake_azure_ai_ml.reset()
    for var, val in (
        ("AZURE_TENANT_ID", "tenant-1"),
        ("AZURE_CLIENT_ID", "client-1"),
        ("AZURE_CLIENT_SECRET", "s3cret"),
        ("AZURE_SUBSCRIPTION_ID", "sub-1"),
        ("AZURE_RESOURCE_GROUP", "rg-1"),
        ("AZURE_WORKSPACE", "ws-1"),
    ):
        monkeypatch.setenv(var, val)
    yield fake_azure_ai_ml
    fake_azure_ai_ml.reset()


def _tiny_package(tmp_path, name="pkg", seed=0):
    import jax
    import jax.numpy as jnp

    from dct_tpu.checkpoint.manager import save_checkpoint
    from dct_tpu.config import ModelConfig
    from dct_tpu.models.registry import get_model
    from dct_tpu.serving.score_gen import generate_score_package

    model = get_model(ModelConfig(), input_dim=5)
    params = model.init(jax.random.PRNGKey(seed), jnp.zeros((1, 5)))
    meta = {"model": "weather_mlp", "input_dim": 5, "hidden_dim": 64,
            "num_classes": 2, "dropout": 0.2, "feature_names": ["a"] * 5}
    ckpt = save_checkpoint(str(tmp_path / f"{name}.ckpt"), params, meta)
    deploy = str(tmp_path / name)
    generate_score_package(ckpt, deploy)
    return deploy


def test_azure_client_full_blue_green_shadow_canary(azure_fake, tmp_path):
    """The whole rollout machine over AzureEndpointClient against the
    transcribed SDK (VERDICT r3 item 5): first rollout lands blue at
    100%, the second walks shadow (100/0 + 20% mirror) -> canary (90/10,
    mirror cleared) -> full (green 100, blue deployment deleted) with
    every begin_* LRO resolved and every entity kwarg bound the way
    azure-ai-ml 1.x binds them."""
    from dct_tpu.deploy.azure import AzureEndpointClient
    from dct_tpu.deploy.rollout import RolloutOrchestrator

    client = AzureEndpointClient()
    orch = RolloutOrchestrator(
        client, "weather-ep", soak_seconds=0.0, sleep_fn=lambda s: None
    )
    events = orch.run(_tiny_package(tmp_path, "pkg1"))
    assert [e.stage for e in events] == ["deploy_new_slot", "full_rollout"]
    assert client.get_traffic("weather-ep") == {"blue": 100}

    events = orch.run(_tiny_package(tmp_path, "pkg2", seed=1))
    stages = [e.stage for e in events[2:]]
    assert stages == ["deploy_new_slot", "shadow", "canary", "full_rollout"]
    shadow, canary, full = events[3], events[4], events[5]
    assert shadow.traffic == {"blue": 100, "green": 0}
    assert shadow.mirror == {"green": 20}
    assert canary.traffic == {"blue": 90, "green": 10}
    assert canary.mirror == {}
    assert full.traffic == {"green": 100}
    assert client.list_deployments("weather-ep") == ["green"]


def test_azure_client_failed_endpoint_recreated(azure_fake, tmp_path):
    from dct_tpu.deploy.azure import AzureEndpointClient
    from dct_tpu.deploy.rollout import RolloutOrchestrator

    client = AzureEndpointClient()
    client.create_endpoint("weather-ep")
    # Simulate a failed provisioning state on the stored endpoint.
    ws_key = ("sub-1", "rg-1", "ws-1")
    azure_fake._WORKSPACES[ws_key].endpoints[
        "weather-ep"
    ].provisioning_state = "Failed"
    orch = RolloutOrchestrator(
        client, "weather-ep", soak_seconds=0.0, sleep_fn=lambda s: None
    )
    orch.ensure_endpoint()
    assert client.provisioning_state("weather-ep") == "Succeeded"


def test_azure_traffic_to_missing_slot_rejected(azure_fake, tmp_path):
    """The service-side invariant the fake carries: routing live traffic
    to a deployment that does not exist fails the update."""
    from dct_tpu.deploy.azure import AzureEndpointClient

    client = AzureEndpointClient()
    client.create_endpoint("weather-ep")
    with pytest.raises(azure_fake.ResourceNotFoundError):
        client.set_traffic("weather-ep", {"ghost": 100})
    # The rejected update must not have leaked into service-side state
    # through the mutated client-side entity (code-review r4).
    assert client.get_traffic("weather-ep") == {}


def test_azure_deploy_validates_package_contents(azure_fake, tmp_path):
    """A package missing score.py/conda.yaml must fail at deploy time —
    the executable contract between generate_score_package and a managed
    online deployment."""
    from dct_tpu.deploy.azure import AzureEndpointClient

    client = AzureEndpointClient()
    client.create_endpoint("weather-ep")
    bad = tmp_path / "empty_pkg"
    bad.mkdir()
    with pytest.raises(azure_fake.ValidationException, match="score.py"):
        client.deploy("weather-ep", "blue", str(bad))


def test_azure_config_requires_each_env_var(azure_fake, monkeypatch):
    from dct_tpu.deploy.azure import AzureConfig

    monkeypatch.delenv("AZURE_WORKSPACE")
    with pytest.raises(EnvironmentError, match="AZURE_WORKSPACE"):
        AzureConfig.from_env()


def test_mlflow_server_artifact_layout_through_deploy(
    tmp_path, mlflow_fake, weather_data
):
    """The last server-side semantic (VERDICT r3 missing-3): a REAL
    training run logging through the mlflow adapter must lay artifacts
    out as ``<artifact_root>/<experiment_id>/<run_id>/artifacts/
    <artifact_path>/<file>`` — and the deploy DAG's prepare_package
    (best-run query -> download_artifacts -> .ckpt glob -> serving
    package) must work off that tree alone."""
    import numpy as np

    from dct_tpu.config import (
        DataConfig, RunConfig, TrackingConfig, TrainConfig,
    )
    from dct_tpu.deploy.rollout import prepare_package
    from dct_tpu.serving.runtime import score_payload
    from dct_tpu.serving.score_gen import weights_from_checkpoint
    from dct_tpu.tracking.client import MlflowTracking
    from dct_tpu.train.trainer import Trainer

    cfg = RunConfig(
        data=DataConfig(models_dir=str(tmp_path / "models")),
        train=TrainConfig(epochs=2, batch_size=4),
        tracking=TrackingConfig(experiment="weather_forecasting"),
    )
    tracker = MlflowTracking(
        "http://mlflow:5000", experiment="weather_forecasting"
    )
    Trainer(cfg, tracker=tracker).fit(weather_data)

    # Server layout on disk: root/<exp_id>/<run_id>/artifacts/...
    store = mlflow_fake.STORE
    exp_id = store.experiments["weather_forecasting"]
    (run_id, rec), = store.runs.items()
    art = os.path.join(store.artifact_root, exp_id, run_id, "artifacts")
    assert rec["artifact_uri"] == art
    best_files = os.listdir(os.path.join(art, "best_checkpoints"))
    assert any(f.startswith("weather-best-") for f in best_files)
    # log_model parity: MLmodel.json AND the ckpt both under model/
    model_files = sorted(os.listdir(os.path.join(art, "model")))
    assert "MLmodel.json" in model_files and any(
        f.endswith(".ckpt") for f in model_files
    )

    # Deploy side: the DAG flow runs purely off the artifact tree.
    info = prepare_package(tracker, str(tmp_path / "deploy"))
    assert info["run_id"] == run_id
    weights, meta = weights_from_checkpoint(
        os.path.join(info["deploy_dir"], "model.ckpt")
    )
    out = score_payload(
        weights, meta, np.zeros((2, int(meta["input_dim"]))).tolist()
    )
    assert np.asarray(out["probabilities"]).shape == (
        2, int(meta["num_classes"]),
    )
