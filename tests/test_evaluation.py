"""Unit tests for the continuous-evaluation subsystem
(dct_tpu.evaluation): statistical gates, drift detectors, the offline
harness, mirror capture, the gate ledger/metrics surface, and the
gate-driven rollback wiring in the rollout orchestrator."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dct_tpu.checkpoint.manager import save_checkpoint
from dct_tpu.config import EvaluationConfig, ModelConfig
from dct_tpu.deploy.local import LocalEndpointClient
from dct_tpu.deploy.rollout import RolloutOrchestrator, prepare_package
from dct_tpu.evaluation import drift, gates, harness
from dct_tpu.evaluation.gates import (
    GateDecision,
    GateRejection,
    PromotionGate,
    paired_bootstrap,
    sign_test,
)
from dct_tpu.models.registry import get_model
from dct_tpu.serving.score_gen import generate_score_package
from dct_tpu.tracking.client import LocalTracking

FEATURES = [f"f{i}" for i in range(5)]


@pytest.fixture(autouse=True)
def _env_built_observability():
    """Earlier suites' Trainer runs install THEIR config-built event
    log/span recorder as the process defaults; clear them so the tests
    here that monkeypatch DCT_EVENTS_DIR see an env-built sink."""
    from dct_tpu.observability import events as _events_mod
    from dct_tpu.observability import spans as _spans_mod

    _events_mod.set_default(None)
    _spans_mod.set_default(None)
    yield


def _package(tmp_path, name="pkg", seed=0):
    model = get_model(ModelConfig(), input_dim=5)
    params = model.init(jax.random.PRNGKey(seed), jnp.zeros((1, 5)))
    meta = {"model": "weather_mlp", "input_dim": 5, "hidden_dim": 64,
            "num_classes": 2, "dropout": 0.2, "feature_names": FEATURES}
    ckpt = save_checkpoint(str(tmp_path / f"{name}.ckpt"), params, meta)
    deploy = str(tmp_path / name)
    generate_score_package(ckpt, deploy)
    return deploy


# ----------------------------------------------------------------------
# Statistics.

def test_paired_bootstrap_deterministic_and_directional():
    rng = np.random.default_rng(0)
    d = rng.normal(0.2, 1.0, 400)
    a = paired_bootstrap(d, seed=7)
    b = paired_bootstrap(d, seed=7)
    assert a == b  # acceptance: deterministic under a fixed seed
    assert paired_bootstrap(d, seed=8) != a  # the seed is load-bearing
    assert a["p_better"] > 0.95
    flipped = paired_bootstrap(-d, seed=7)
    assert flipped["p_better"] < 0.05
    assert flipped["mean_delta"] == pytest.approx(-a["mean_delta"])


def test_paired_bootstrap_empty():
    out = paired_bootstrap(np.zeros(0))
    assert out["n"] == 0 and out["p_better"] == 0.5


def test_paired_bootstrap_chunking_invariant(monkeypatch):
    """The chunked resampling (bounded memory at dataset-scale splits)
    must be bit-identical to the one-shot matrix for a given seed."""
    rng = np.random.default_rng(2)
    d = rng.normal(0.05, 1.0, 10_000)  # forces multiple chunks
    out = paired_bootstrap(d, n_boot=500, seed=9)
    # Reference: explicit one-shot resampling with the same stream.
    ref_rng = np.random.default_rng(9)
    ref_means = ref_rng.integers(0, len(d), size=(500, len(d)))
    ref_means = d[ref_means].mean(axis=1)
    assert out["p_better"] == pytest.approx(float((ref_means > 0).mean()))
    lo, hi = np.quantile(ref_means, [0.05, 0.95])
    assert out["ci_low"] == pytest.approx(float(lo))
    assert out["ci_high"] == pytest.approx(float(hi))


def test_sign_test_exact_and_approx():
    # Exact binomial: 9 wins of 10 -> P(>=9 | p=.5) = 11/1024.
    d = np.array([1.0] * 9 + [-1.0])
    out = sign_test(d)
    assert out["wins"] == 9 and out["losses"] == 1
    assert out["p_value"] == pytest.approx(11 / 1024)
    # Ties carry no information.
    assert sign_test(np.zeros(10))["p_value"] == 1.0
    # Normal-approx regime agrees in direction with the exact one.
    rng = np.random.default_rng(1)
    big = rng.normal(0.3, 1.0, 1000)
    assert sign_test(big)["p_value"] < 0.01


# ----------------------------------------------------------------------
# Decision logic (pure, no packages needed).

def _report(mean_delta, *, n=400, slices=None, drift_rep=None, seed=3):
    rng = np.random.default_rng(seed)
    deltas = rng.normal(mean_delta, 0.5, n)
    rep = {
        "mean_delta": float(deltas.mean()),
        "paired": True,
        "champion": {"loss_mean": 0.5},
        "challenger": {"loss_mean": 0.5 - float(deltas.mean())},
        "slice_regressions": slices or {},
        "bootstrap": paired_bootstrap(deltas, seed=42),
        "sign_test": sign_test(deltas),
    }
    if drift_rep is not None:
        rep["drift"] = drift_rep
    return rep


def test_decide_rollback_on_significant_regression():
    g = PromotionGate(EvaluationConfig())
    dec = g.decide(_report(-0.4), stage="canary")
    assert dec.decision == "rollback"
    assert dec.reason == "challenger_regression"
    assert dec.evidence["bootstrap"]["p_better"] <= 0.05


def test_decide_promotes_without_regression():
    g = PromotionGate(EvaluationConfig())
    assert g.decide(_report(0.3), stage="canary").promoted
    # Statistically flat is NOT a regression: continuous training
    # promotes the fresh cycle unless it is demonstrably worse.
    assert g.decide(_report(0.0), stage="canary").promoted


def test_decide_unpaired_regression_still_blocks():
    """Family upgrades have no per-example pairing, but the aggregate
    mean comparison must still catch a regression."""
    g = PromotionGate(EvaluationConfig())
    worse = {
        "mean_delta": -0.4, "paired": False,
        "champion": {"loss_mean": 0.3}, "challenger": {"loss_mean": 0.7},
        "slice_regressions": {},
    }
    assert g.decide(worse, stage="canary").decision == "rollback"
    better = {**worse, "mean_delta": 0.2,
              "challenger": {"loss_mean": 0.1}}
    assert g.decide(better, stage="canary").promoted


def test_unpaired_mean_delta_is_aggregate_difference():
    """PairedEval.mean_delta must not collapse to 0 when pairing is
    impossible — the gates' mean thresholds read it."""
    res_a = harness.EvalResult("champion", 10, 0.8, 0.5,
                               np.zeros(0), np.zeros(0))
    res_b = harness.EvalResult("challenger", 10, 0.3, 0.7,
                               np.zeros(0), np.zeros(0))
    pair = harness.PairedEval(res_a, res_b, np.zeros(0), paired=False)
    assert pair.mean_delta == pytest.approx(0.5)
    assert pair.to_dict()["mean_delta"] == pytest.approx(0.5)


def test_decide_sign_test_catches_outlier_dragged_mean():
    """The challenger loses slightly on 99% of examples while a handful
    of huge champion outlier losses drag the mean positive but NOT
    significantly so: the per-example win count flags it — hold. (A
    mean improvement the bootstrap does call significant still
    promotes: fixing catastrophic champion failures is a real win.)"""
    n = 400
    deltas = np.full(n, -0.05)          # challenger a bit worse everywhere
    deltas[:4] = 8.0                    # ...except 4 champion blowups
    assert deltas.mean() > 0
    boot = paired_bootstrap(deltas, seed=42)
    assert boot["p_better"] < 0.95      # mean improvement inconclusive
    rep = {
        "mean_delta": float(deltas.mean()), "paired": True,
        "champion": {"loss_mean": 1.0},
        "challenger": {"loss_mean": 1.0 - float(deltas.mean())},
        "slice_regressions": {},
        "bootstrap": boot,
        "sign_test": sign_test(deltas),
    }
    g = PromotionGate(EvaluationConfig())
    dec = g.decide(rep, stage="canary")
    assert dec.decision == "hold"
    assert dec.reason == "per_example_regression"
    assert dec.evidence["sign_test"]["p_worse"] < 0.05


def test_sign_test_p_worse_tail():
    d = np.array([-1.0] * 9 + [1.0])
    out = sign_test(d)
    assert out["p_worse"] == pytest.approx(11 / 1024)
    assert out["p_value"] == pytest.approx(1023 / 1024)


def test_decide_slice_regression_blocks_aggregate_win():
    g = PromotionGate(EvaluationConfig(max_slice_regression=0.2))
    dec = g.decide(
        _report(0.3, slices={"label_rain": 0.5, "label_no_rain": -0.1}),
        stage="canary",
    )
    assert dec.decision == "rollback"
    assert dec.reason == "slice_regression"


def test_decide_holds_on_drift():
    g = PromotionGate(EvaluationConfig())
    dec = g.decide(
        _report(0.1, drift_rep={"max_psi": 0.8, "any_drift": True}),
        stage="canary",
    )
    assert dec.decision == "hold"
    assert dec.reason == "data_drift"
    assert dec.evidence["drift"]["max_psi"] == 0.8


def test_decide_holds_on_shadow_disagreement():
    g = PromotionGate(EvaluationConfig())
    dec = g.decide(
        _report(0.1), stage="canary",
        disagreement={"n": 50, "rate": 0.6, "mean_tv": 0.4,
                      "exceeded": True},
    )
    assert dec.decision == "hold"
    assert dec.reason == "shadow_disagreement"


def test_decide_require_improvement():
    g = PromotionGate(EvaluationConfig(require_improvement=True))
    assert g.decide(_report(0.0), stage="canary").decision == "hold"
    promoted = g.decide(_report(0.4), stage="canary")
    assert promoted.promoted and promoted.reason == "improvement"


# ----------------------------------------------------------------------
# Drift detectors (acceptance: flag a shifted mean, stay quiet on an
# i.i.d. resample).

def test_drift_flags_shift_quiet_on_iid_resample():
    rng = np.random.default_rng(0)
    train = rng.normal(0.0, 1.0, (4000, 5)).astype(np.float32)
    snap = drift.snapshot_features(train, FEATURES)
    # The snapshot must survive the JSON round trip it takes through
    # the package manifest.
    snap = json.loads(json.dumps(snap))

    iid = rng.normal(0.0, 1.0, (1500, 5)).astype(np.float32)
    quiet = drift.feature_drift(snap, iid, FEATURES)
    assert not quiet["any_drift"]
    assert quiet["max_psi"] < 0.1

    shifted = iid.copy()
    shifted[:, 2] += 1.0  # one sigma of mean shift
    loud = drift.feature_drift(snap, shifted, FEATURES)
    assert loud["any_drift"]
    assert loud["features"]["f2"]["drifted"]
    assert loud["features"]["f2"]["psi"] > 0.2
    assert loud["features"]["f2"]["ks"] > 0.15
    # The untouched features stay quiet.
    assert not loud["features"]["f0"]["drifted"]
    assert loud["max_psi"] == loud["features"]["f2"]["psi"]


def test_drift_schema_change_is_drift():
    rng = np.random.default_rng(0)
    snap = drift.snapshot_features(
        rng.normal(0, 1, (500, 2)).astype(np.float32), ["a", "b"]
    )
    # 'b' renamed to 'c' with the column count unchanged: the added
    # name AND the removed name both read as drift — never a silent
    # positional comparison against the wrong snapshot entry.
    rep = drift.feature_drift(
        snap, rng.normal(0, 1, (500, 2)).astype(np.float32), ["a", "c"]
    )
    assert rep["any_drift"]
    assert rep["features"]["c"]["missing_in_snapshot"]
    assert rep["features"]["b"]["missing_in_current"]
    assert not rep["features"]["a"]["drifted"]


def test_drift_discrete_features_use_psi_not_ks():
    """Binary/low-cardinality features: an i.i.d. resample must stay
    quiet (the bin-uniform KS reconstruction would read D~0.5), while a
    real rate shift is caught by PSI over per-value bins."""
    rng = np.random.default_rng(0)
    binary = (rng.random((4000, 1)) < 0.3).astype(np.float32)
    snap = drift.snapshot_features(binary, ["flag"])
    assert snap["features"]["flag"]["discrete"]

    resample = (rng.random((1500, 1)) < 0.3).astype(np.float32)
    quiet = drift.feature_drift(snap, resample, ["flag"])
    assert not quiet["any_drift"], quiet

    shifted = (rng.random((1500, 1)) < 0.85).astype(np.float32)
    loud = drift.feature_drift(snap, shifted, ["flag"])
    assert loud["features"]["flag"]["drifted"]
    assert loud["features"]["flag"]["psi"] > 0.2


def test_drift_constant_feature_any_change_is_drift():
    const = np.full((500, 1), 3.0, np.float32)
    snap = drift.snapshot_features(const, ["c"])
    quiet = drift.feature_drift(snap, const[:100], ["c"])
    assert not quiet["any_drift"]
    moved = np.full((100, 1), 3.5, np.float32)
    assert drift.feature_drift(snap, moved, ["c"])["features"]["c"]["drifted"]


def test_ks_statistic_bounds():
    rng = np.random.default_rng(0)
    a = rng.normal(0, 1, 500)
    assert drift.ks_statistic(a, a) == 0.0
    assert drift.ks_statistic(a, a + 100.0) == 1.0


def test_prediction_disagreement():
    live = np.array([[0.9, 0.1], [0.2, 0.8], [0.6, 0.4]])
    agree = drift.prediction_disagreement(live, live)
    assert agree["rate"] == 0.0 and agree["mean_tv"] == 0.0
    flipped = live[:, ::-1]
    total = drift.prediction_disagreement(live, flipped)
    assert total["rate"] == 1.0
    assert drift.prediction_disagreement(np.zeros((0, 2)), np.zeros((0, 2)))["n"] == 0


# ----------------------------------------------------------------------
# Harness.

def test_per_example_nll_matches_mean_ce():
    rng = np.random.default_rng(0)
    probs = rng.dirichlet(np.ones(2), size=64)
    labels = rng.integers(0, 2, 64)
    losses = harness.per_example_nll(probs, labels)
    expected = -np.log(probs[np.arange(64), labels])
    np.testing.assert_allclose(losses, expected, rtol=1e-12)


def test_evaluate_pair_paired_deltas_and_slices(tmp_path, processed_dir):
    champ = harness.load_model(_package(tmp_path, "a", seed=0))
    chall = harness.load_model(_package(tmp_path, "b", seed=1))
    pair = harness.evaluate_pair(champ, chall, processed_dir)
    assert pair.paired
    assert len(pair.deltas) == pair.champion.n == pair.challenger.n
    assert pair.mean_delta == pytest.approx(
        pair.champion.loss_mean - pair.challenger.loss_mean, abs=1e-9
    )
    # The reference task's rain/no-rain slices exist and partition n.
    slices = pair.challenger.slices
    assert {"label_rain", "label_no_rain"} <= set(slices)
    assert sum(s["n"] for s in slices.values()) == pair.challenger.n
    regs = pair.slice_regressions()
    assert set(regs) == set(slices)
    # Identical models pair to exactly zero deltas.
    same = harness.evaluate_pair(champ, champ, processed_dir)
    assert float(np.abs(same.deltas).max()) == 0.0


def test_harness_engines_agree(tmp_path, processed_dir):
    w, m = harness.load_model(_package(tmp_path, "eng", seed=2))
    x, y = harness.load_eval_split(processed_dir, m)
    p_np = harness.batched_probs(w, m, x, engine="numpy", batch_size=64)
    p_jax = harness.batched_probs(w, m, x, engine="jax", batch_size=64)
    np.testing.assert_allclose(p_np, p_jax, atol=2e-6)


def test_harness_eval_errors(tmp_path):
    with pytest.raises(harness.EvalError):
        harness.model_from_package(str(tmp_path / "missing"))
    with pytest.raises(harness.EvalError):
        harness.load_eval_split(
            str(tmp_path / "nodata"), {"model": "weather_mlp"}
        )


# ----------------------------------------------------------------------
# prepare_package manifest (satellite): full metrics + data snapshot.

def test_prepare_package_persists_metrics_and_snapshot(
    tmp_path, monkeypatch, processed_dir
):
    monkeypatch.delenv("DCT_RUN_ID", raising=False)
    store = LocalTracking(root=str(tmp_path / "runs"))
    model = get_model(ModelConfig(), input_dim=5)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 5)))
    meta = {"model": "weather_mlp", "input_dim": 5, "hidden_dim": 64,
            "num_classes": 2, "dropout": 0.2, "feature_names": FEATURES}
    ckpt = save_checkpoint(str(tmp_path / "w" / "weather-best-00-0.30.ckpt"),
                           params, meta)
    store.start_run()
    store.log_metrics(
        {"val_loss": 0.3, "val_acc": 0.85, "val_f1": 0.8}, step=1
    )
    store.log_artifact(ckpt, "best_checkpoints")
    store.end_run()

    info = prepare_package(
        store, str(tmp_path / "deploy"), data_dir=processed_dir
    )
    assert info["metrics"]["val_acc"] == pytest.approx(0.85)
    with open(tmp_path / "deploy" / "run_info.json") as f:
        manifest = json.load(f)
    # The selected run's FULL final metrics are in the manifest — what
    # gates (and humans) read back about what was promoted.
    assert manifest["metrics"] == {
        "val_loss": 0.3, "val_acc": 0.85, "val_f1": 0.8,
    }
    # Plus the training-data snapshot the drift detectors compare
    # future ETL output against.
    snap = manifest["data_snapshot"]
    assert snap["rows"] > 0
    assert set(snap["features"]) == {f + "_norm" for f in
                                     ["Temperature", "Humidity", "Wind_Speed",
                                      "Cloud_Cover", "Pressure"]}
    for feat in snap["features"].values():
        assert len(feat["counts"]) == len(feat["edges"]) - 1
    # A packaging host without the data ships None, never a failure.
    info2 = prepare_package(
        store, str(tmp_path / "deploy2"), data_dir=str(tmp_path / "nope")
    )
    with open(tmp_path / "deploy2" / "run_info.json") as f:
        assert json.load(f)["data_snapshot"] is None
    assert info2["val_loss"] == pytest.approx(0.3)


def test_manifest_stamps_split_and_gate_honors_it(
    tmp_path, monkeypatch, processed_dir
):
    """The gate must rebuild the TRAINING run's split from the package
    manifest — the gate process has no env inheritance from the
    training launch, so env parity cannot be assumed."""
    monkeypatch.delenv("DCT_RUN_ID", raising=False)
    store = LocalTracking(root=str(tmp_path / "runs"))
    model = get_model(ModelConfig(), input_dim=5)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 5)))
    meta = {"model": "weather_mlp", "input_dim": 5, "hidden_dim": 64,
            "num_classes": 2, "dropout": 0.2, "feature_names": FEATURES}
    ckpt = save_checkpoint(str(tmp_path / "w" / "weather-best-00-0.30.ckpt"),
                           params, meta)
    # The run trained under seed 7 with a 0.1 val split (both logged by
    # the trainer; the packaging process's env says 42/0.2).
    store.start_run(params={"seed": 7, "val_fraction": 0.1})
    store.log_metrics({"val_loss": 0.3}, step=1)
    store.log_artifact(ckpt, "best_checkpoints")
    store.end_run()
    prepare_package(store, str(tmp_path / "deploy"), data_dir=processed_dir)
    with open(tmp_path / "deploy" / "run_info.json") as f:
        split = json.load(f)["split"]
    assert split["seed"] == 7
    assert split["val_fraction"] == pytest.approx(0.1)
    # The gate reads the stamped split even though ITS env says 42/0.2.
    gate = PromotionGate(EvaluationConfig(), processed_dir=processed_dir)
    assert gate._split_for(str(tmp_path / "deploy")) == (0.1, 7)
    # No stamp -> env fallback, never a crash.
    assert gate._split_for(str(tmp_path / "nope")) == (
        gate.val_fraction, gate.split_seed,
    )


def test_log_eval_report_never_leaks_running_run(tmp_path):
    class _FlakyTracker(LocalTracking):
        def log_artifact(self, local_path, artifact_path):
            raise OSError("artifact store down")

    store = _FlakyTracker(root=str(tmp_path / "runs"))
    report_path = tmp_path / "eval_report.json"
    report_path.write_text(json.dumps({
        "champion": {"loss_mean": 0.3}, "challenger": {"loss_mean": 0.2},
        "mean_delta": 0.1,
    }))
    with pytest.raises(OSError):
        gates.log_eval_report(
            store, json.loads(report_path.read_text()), str(report_path)
        )
    # The half-logged run was closed as FAILED, not leaked RUNNING.
    run_dir = tmp_path / "runs" / "weather_forecasting"
    metas = list(run_dir.glob("*/meta.json"))
    assert metas, "run was never created"
    assert json.loads(metas[0].read_text())["status"] == "FAILED"


# ----------------------------------------------------------------------
# Mirror capture on the local endpoint client.

def test_mirror_capture_records_paired_probs(tmp_path, monkeypatch):
    capture = str(tmp_path / "mirror.jsonl")
    monkeypatch.setenv("DCT_MIRROR_CAPTURE", capture)
    client = LocalEndpointClient()
    ro = RolloutOrchestrator(client, "ep", sleep_fn=lambda s: None)
    ro.run(_package(tmp_path, "v1", seed=0))
    ro2 = RolloutOrchestrator(client, "ep", sleep_fn=lambda s: None)
    new_slot, old_slot = ro2.deploy_new_slot(_package(tmp_path, "v2", seed=9))
    ro2.start_shadow(new_slot, old_slot)
    for i in range(4):
        client.score("ep", {"data": [[float(i)] * 5]})
    live, shadow = drift.read_mirror_capture(capture)
    assert live.shape == shadow.shape == (4, 2)
    rep = drift.disagreement_report(capture, max_disagreement=0.25)
    assert rep is not None and rep["n"] == 4
    with open(capture) as f:
        rec = json.loads(f.readline())
    assert rec["live_slot"] == old_slot and rec["shadow_slot"] == new_slot
    # No capture file -> no evidence (never fabricated agreement).
    assert drift.disagreement_report(str(tmp_path / "none.jsonl")) is None


def test_mirror_capture_scoped_to_current_shadow(tmp_path, monkeypatch):
    """A new shadow stage truncates the capture file, and the reader
    filters by shadow slot — cycle 1's disagreements must not keep
    holding (or excusing) cycle 2's challenger."""
    capture = str(tmp_path / "mirror.jsonl")
    monkeypatch.setenv("DCT_MIRROR_CAPTURE", capture)
    with open(capture, "w") as f:  # stale record from a previous cycle
        f.write(json.dumps({
            "shadow_slot": "green", "live_slot": "blue",
            "live_probs": [[1.0, 0.0]], "shadow_probs": [[0.0, 1.0]],
        }) + "\n")
    client = LocalEndpointClient()
    ro = RolloutOrchestrator(client, "ep", sleep_fn=lambda s: None)
    ro.run(_package(tmp_path, "v1", seed=0))
    ro2 = RolloutOrchestrator(client, "ep", sleep_fn=lambda s: None)
    new_slot, old_slot = ro2.deploy_new_slot(_package(tmp_path, "v2", seed=1))
    ro2.start_shadow(new_slot, old_slot)
    # The stale record is gone; only fresh pairs remain.
    client.score("ep", {"data": [[0.5] * 5]})
    live, _ = drift.read_mirror_capture(capture)
    assert live.shape == (1, 2)
    # Slot filtering: records for other shadow slots are invisible.
    none_live, _ = drift.read_mirror_capture(capture, shadow_slot="nope")
    assert len(none_live) == 0
    scoped = drift.disagreement_report(capture, shadow_slot=new_slot)
    assert scoped is not None and scoped["n"] == 1
    assert scoped["shadow_slot"] == new_slot


def test_mirror_capture_off_by_default(tmp_path, monkeypatch):
    monkeypatch.delenv("DCT_MIRROR_CAPTURE", raising=False)
    client = LocalEndpointClient()
    assert client.mirror_capture_path is None
    # With persistent state, capture defaults beside the state file.
    client2 = LocalEndpointClient(state_path=str(tmp_path / "s.json"))
    assert client2.mirror_capture_path == str(tmp_path / "s.json") + "_mirror.jsonl"


# ----------------------------------------------------------------------
# Gate ledger -> /metrics text.

def test_record_decision_ledger_and_metrics_text(tmp_path):
    ledger = str(tmp_path / "ledger.json")
    gates.record_decision(
        GateDecision("rollback", "canary", "challenger_regression",
                     {"drift": {"max_psi": 0.42}}),
        ledger_path=ledger,
    )
    gates.record_decision(
        GateDecision("promote", "full_rollout", "no_regression"),
        ledger_path=ledger,
    )
    text = gates.render_gate_metrics(ledger)
    assert 'dct_deploy_gate_decisions_total{decision="rollback"} 1' in text
    assert 'dct_deploy_gate_decisions_total{decision="promote"} 1' in text
    assert 'dct_deploy_gate_decisions_total{decision="hold"} 0' in text
    assert "dct_drift_psi 0.42" in text
    # The textfile twin landed next to the ledger.
    prom = tmp_path / "deploy_gate.prom"
    assert prom.exists()
    assert "dct_deploy_gate_decisions_total" in prom.read_text()
    # No ledger -> no series, no error.
    assert gates.render_gate_metrics(str(tmp_path / "none.json")) == ""


# ----------------------------------------------------------------------
# Gate-driven rollback wiring (satellite): the orchestrator reverts and
# records on a blocking decision; a promote gate is invisible.

class _StubGate:
    """Any object with .cfg and .evaluate() is a valid gate."""

    def __init__(self, decision):
        self.cfg = EvaluationConfig()
        self._decision = decision
        self.calls = []

    def evaluate(self, *, challenger_dir, champion_dir, stage,
                 mirror_capture=None, shadow_slot=None):
        self.calls.append((stage, challenger_dir, champion_dir))
        return GateDecision(self._decision, stage, "stub")


def _events_at(events_dir):
    path = os.path.join(events_dir, "events.jsonl")
    if not os.path.exists(path):
        return []
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def test_gate_rollback_reverts_endpoint(tmp_path, monkeypatch):
    monkeypatch.setenv("DCT_EVENTS_DIR", str(tmp_path / "events"))
    monkeypatch.setenv("DCT_GATE_LEDGER", str(tmp_path / "ledger.json"))
    client = LocalEndpointClient()
    RolloutOrchestrator(client, "ep", sleep_fn=lambda s: None).run(
        _package(tmp_path, "v1", seed=0)
    )
    gate = _StubGate("rollback")
    ro = RolloutOrchestrator(
        client, "ep", sleep_fn=lambda s: None, gate=gate
    )
    new_slot, old_slot = ro.deploy_new_slot(_package(tmp_path, "v2", seed=1))
    ro.start_shadow(new_slot, old_slot)
    assert client.get_mirror_traffic("ep") == {new_slot: 20}
    with pytest.raises(GateRejection) as exc:
        ro.start_canary(new_slot, old_slot)
    assert exc.value.decision.decision == "rollback"
    # Auto-revert: old slot back to 100% live, mirror cleared; the
    # challenger never saw live traffic.
    assert client.get_traffic("ep") == {old_slot: 100}
    assert client.get_mirror_traffic("ep") == {}
    # The gate saw the real package dirs.
    assert gate.calls[0][0] == "canary"
    assert gate.calls[0][1].endswith("v2") and gate.calls[0][2].endswith("v1")
    # On the record: deploy.gate (decision) then deploy.rollback.
    events = _events_at(str(tmp_path / "events"))
    gate_evs = [e for e in events if e["event"] == "deploy.gate"]
    rb_evs = [e for e in events if e["event"] == "deploy.rollback"]
    assert gate_evs and gate_evs[-1]["decision"] == "rollback"
    assert gate_evs[-1]["stage"] == "canary"
    assert rb_evs and rb_evs[-1]["failed_stage"] == "gate:canary"
    assert rb_evs[-1]["reverted"] is True
    # And in the metrics ledger.
    text = gates.render_gate_metrics(str(tmp_path / "ledger.json"))
    assert 'decision="rollback"} 1' in text


def test_gate_hold_also_blocks_and_reverts(tmp_path, monkeypatch):
    monkeypatch.setenv("DCT_EVENTS_DIR", str(tmp_path / "events"))
    monkeypatch.setenv("DCT_GATE_LEDGER", str(tmp_path / "ledger.json"))
    client = LocalEndpointClient()
    RolloutOrchestrator(client, "ep", sleep_fn=lambda s: None).run(
        _package(tmp_path, "v1", seed=0)
    )
    ro = RolloutOrchestrator(
        client, "ep", sleep_fn=lambda s: None, gate=_StubGate("hold")
    )
    with pytest.raises(GateRejection):
        ro.run(_package(tmp_path, "v2", seed=1))
    assert client.get_traffic("ep") == {"blue": 100}


def test_gate_promote_walks_to_full_rollout(tmp_path, monkeypatch):
    monkeypatch.setenv("DCT_EVENTS_DIR", str(tmp_path / "events"))
    monkeypatch.setenv("DCT_GATE_LEDGER", str(tmp_path / "ledger.json"))
    client = LocalEndpointClient()
    RolloutOrchestrator(client, "ep", sleep_fn=lambda s: None).run(
        _package(tmp_path, "v1", seed=0)
    )
    gate = _StubGate("promote")
    ro = RolloutOrchestrator(
        client, "ep", sleep_fn=lambda s: None, gate=gate
    )
    events = ro.run(_package(tmp_path, "v2", seed=1))
    assert client.get_traffic("ep") == {"green": 100}
    # Both transitions were gated.
    assert [c[0] for c in gate.calls] == ["canary", "full_rollout"]
    assert [e.stage for e in events] == [
        "deploy_new_slot", "shadow", "gate_canary", "canary",
        "gate_full_rollout", "full_rollout",
    ]


def test_gate_first_deployment_ungated(tmp_path):
    client = LocalEndpointClient()
    gate = _StubGate("rollback")  # would block anything it sees
    ro = RolloutOrchestrator(
        client, "ep", sleep_fn=lambda s: None, gate=gate
    )
    ro.run(_package(tmp_path, "v1", seed=0))
    assert client.get_traffic("ep") == {"blue": 100}
    assert gate.calls == []  # no champion, nothing to consult


def test_gate_consult_crash_fails_closed(tmp_path, monkeypatch):
    monkeypatch.setenv("DCT_EVENTS_DIR", str(tmp_path / "events"))
    monkeypatch.setenv("DCT_GATE_LEDGER", str(tmp_path / "ledger.json"))

    class _Exploding:
        cfg = EvaluationConfig()

        def evaluate(self, **kw):
            raise RuntimeError("gate infrastructure down")

    client = LocalEndpointClient()
    RolloutOrchestrator(client, "ep", sleep_fn=lambda s: None).run(
        _package(tmp_path, "v1", seed=0)
    )
    ro = RolloutOrchestrator(
        client, "ep", sleep_fn=lambda s: None, gate=_Exploding()
    )
    with pytest.raises(GateRejection) as exc:
        ro.run(_package(tmp_path, "v2", seed=1))
    assert exc.value.decision.decision == "hold"
    assert "gate_error" in exc.value.decision.reason
    assert client.get_traffic("ep") == {"blue": 100}


# ----------------------------------------------------------------------
# Offline-eval caching + determinism through the real gate.

def test_offline_eval_cached_and_deterministic(tmp_path, processed_dir):
    champ = _package(tmp_path, "champ", seed=0)
    chall = _package(tmp_path, "chall", seed=1)
    gate = PromotionGate(EvaluationConfig(), processed_dir=processed_dir)
    r1 = gate.offline_eval(chall, champ)
    cache = os.path.join(chall, "eval_report.json")
    assert os.path.exists(cache)
    r2 = gate.offline_eval(chall, champ)  # cache hit
    assert r1 == r2
    os.remove(cache)
    r3 = gate.offline_eval(chall, champ)  # full recompute
    assert r3["bootstrap"] == r1["bootstrap"]  # seeded: bit-identical
    assert r3["mean_delta"] == r1["mean_delta"]
    # A different champion invalidates the cache.
    other = _package(tmp_path, "other", seed=2)
    r4 = gate.offline_eval(chall, other)
    assert r4["champion_dir"] == other


def test_gate_evaluate_no_champion_promotes(tmp_path, processed_dir):
    chall = _package(tmp_path, "chall", seed=1)
    gate = PromotionGate(EvaluationConfig(), processed_dir=processed_dir)
    for champ in (None, str(tmp_path / "gone"), chall):
        dec = gate.evaluate(
            challenger_dir=chall, champion_dir=champ, stage="canary"
        )
        assert dec.promoted and dec.reason == "no_champion"


def test_gate_evaluate_no_data_fail_open_vs_closed(tmp_path):
    champ = _package(tmp_path, "champ", seed=0)
    chall = _package(tmp_path, "chall", seed=1)
    nodata = str(tmp_path / "nodata")
    open_gate = PromotionGate(
        EvaluationConfig(fail_open=True), processed_dir=nodata
    )
    dec = open_gate.evaluate(
        challenger_dir=chall, champion_dir=champ, stage="canary"
    )
    assert dec.promoted and dec.reason.startswith("no_eval_evidence")
    closed_gate = PromotionGate(
        EvaluationConfig(fail_open=False), processed_dir=nodata
    )
    dec = closed_gate.evaluate(
        challenger_dir=chall, champion_dir=champ, stage="canary"
    )
    assert dec.decision == "hold"


# ----------------------------------------------------------------------
# Report CLI renderers.

def test_report_renderers(tmp_path, processed_dir, capsys):
    champ = _package(tmp_path, "champ", seed=0)
    chall = _package(tmp_path, "chall", seed=1)
    gate = PromotionGate(EvaluationConfig(), processed_dir=processed_dir)
    gate.offline_eval(chall, champ)

    from dct_tpu.evaluation import report as report_cli

    events_file = tmp_path / "events" / "events.jsonl"
    events_file.parent.mkdir()
    events_file.write_text(json.dumps({
        "run_id": "dct-x", "component": "deploy", "event": "deploy.gate",
        "stage": "canary", "decision": "promote", "reason": "no_regression",
        "mean_delta": 0.01,
    }) + "\n")
    rc = report_cli.main([str(tmp_path), "--events", str(events_file.parent)])
    assert rc == 0
    out = capsys.readouterr().out
    assert "champion" in out and "challenger" in out
    assert "mean paired delta" in out
    assert "label_rain" in out
    assert "decision=promote" in out
    # Missing root is a clean exit code, not a traceback.
    assert report_cli.main([str(tmp_path / "missing")]) == 2
