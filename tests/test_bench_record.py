"""The bench's evidence-preservation machinery: streamed legs, partial
flushes, and deadline gates. Round 4 lost ~35 min of on-chip scanned-leg
measurements to an exception AFTER the legs had run — these tests pin
the hedges that prevent a recurrence (bench.py:_leg/_flush_partial,
_over_deadline, and the skip markers)."""

import importlib
import json
import os
import time

import pytest


@pytest.fixture()
def bench_mod(tmp_path, monkeypatch):
    # conftest.py already puts the repo root on sys.path for every test.
    monkeypatch.setenv(
        "DCT_BENCH_PARTIAL", str(tmp_path / "BENCH_PARTIAL.json")
    )
    import bench

    bench = importlib.reload(bench)
    yield bench
    # Reload again so the monkeypatched partial path does not leak into
    # other suites that import bench.
    monkeypatch.undo()
    importlib.reload(bench)


def _partial(bench):
    with open(bench._PARTIAL_PATH) as f:
        return json.loads(f.read())


def test_leg_streams_into_live_record(bench_mod):
    rec = {"metric": "m"}
    bench_mod._LIVE_RECORD = rec
    try:
        bench_mod._leg("attn_blockwise_ms", 12.34)
        bench_mod._leg("attn_gqa", {"speedup": 1.5})
    finally:
        bench_mod._LIVE_RECORD = None
    on_disk = _partial(bench_mod)
    assert on_disk["scaled_legs"]["attn_blockwise_ms"] == 12.34
    assert on_disk["scaled_legs"]["attn_gqa"] == {"speedup": 1.5}
    assert rec["scaled_legs"] == on_disk["scaled_legs"]


def test_leg_without_live_record_is_stderr_only(bench_mod, capsys):
    bench_mod._LIVE_RECORD = None
    bench_mod._leg("attn_flash_ms", 7.0)  # must not raise
    assert "attn_flash_ms=7.0" in capsys.readouterr().err
    assert not os.path.exists(bench_mod._PARTIAL_PATH)


def test_partial_flush_is_atomic_and_additive(bench_mod):
    bench_mod._flush_partial({"a": 1})
    bench_mod._flush_partial({"a": 1, "b": 2})
    assert _partial(bench_mod) == {"a": 1, "b": 2}
    assert not os.path.exists(bench_mod._PARTIAL_PATH + ".tmp")


def test_deadline_fraction_gates(bench_mod, monkeypatch):
    monkeypatch.setattr(bench_mod, "_DEADLINE", 100.0)
    # Shift the bench's own epoch so ~60s appear elapsed: over a 55%
    # budget (55s), under the full deadline. (Patching bench state, not
    # the global clock — stdlib perf_counter stays untouched.)
    monkeypatch.setattr(
        bench_mod, "_BENCH_T0", time.perf_counter() - 60.0
    )
    assert bench_mod._over_deadline("x", frac=0.55) is True
    assert bench_mod._over_deadline("x") is False
    # Deadline disabled -> never over, any fraction.
    monkeypatch.setattr(bench_mod, "_DEADLINE", 0.0)
    assert bench_mod._over_deadline("x", frac=0.55) is False


def test_prior_onchip_newer_stash_embedded_beside_latest(
    bench_mod, tmp_path, monkeypatch
):
    """ADVICE r5: a complete BENCH_ONCHIP_LATEST.json wins the headline
    `record` slot, but a pre-run partial stash captured AFTER it must be
    embedded alongside (`newer_partial`) instead of dropped — and an
    OLDER stash must not be."""
    monkeypatch.setattr(bench_mod, "_REPO_ROOT", str(tmp_path))
    latest = {
        "platform": "tpu", "samples": 1.0,
        "generated_utc": "2026-01-01T00:00:00Z",
    }
    with open(tmp_path / "BENCH_ONCHIP_LATEST.json", "w") as f:
        json.dump(latest, f)
    import calendar

    # Same UTC arithmetic as bench._prior_onchip_evidence's _capture_ts.
    latest_ts = calendar.timegm(time.strptime(
        "2026-01-01T00:00:00Z", "%Y-%m-%dT%H:%M:%SZ"
    ))

    newer_stash = {"platform": "tpu", "samples": 2.0}
    out = bench_mod._prior_onchip_evidence((newer_stash, latest_ts + 86400))
    assert out["source"] == "BENCH_ONCHIP_LATEST.json"
    assert out["record"] == latest  # complete record keeps the headline
    assert out["newer_partial"]["record"] == newer_stash
    assert "pre-run stash" in out["newer_partial"]["source"]

    older = bench_mod._prior_onchip_evidence((newer_stash, latest_ts - 86400))
    assert older["record"] == latest
    assert "newer_partial" not in older

    # No LATEST: the stash competes for the headline slot as before.
    os.remove(tmp_path / "BENCH_ONCHIP_LATEST.json")
    alone = bench_mod._prior_onchip_evidence((newer_stash, latest_ts))
    assert alone["record"] == newer_stash
    assert "newer_partial" not in alone


def test_flush_survives_numpy_scalars(bench_mod):
    """A np scalar leaking into a leg value must not raise FROM the
    hedge (a TypeError here would kill the section it protects)."""
    import numpy as np

    bench_mod._flush_partial({
        "v": np.float32(12.5), "flag": np.bool_(True),
        "arr_note": np.int64(3),
    })
    on_disk = _partial(bench_mod)
    assert on_disk["v"] == 12.5 and on_disk["flag"] == 1.0
    assert not os.path.exists(bench_mod._PARTIAL_PATH + ".tmp")
