"""The bench's evidence-preservation machinery: streamed legs, partial
flushes, and deadline gates. Round 4 lost ~35 min of on-chip scanned-leg
measurements to an exception AFTER the legs had run — these tests pin
the hedges that prevent a recurrence (bench.py:_leg/_flush_partial,
_over_deadline, and the skip markers)."""

import importlib
import json
import os
import time

import pytest


@pytest.fixture()
def bench_mod(tmp_path, monkeypatch):
    # conftest.py already puts the repo root on sys.path for every test.
    monkeypatch.setenv(
        "DCT_BENCH_PARTIAL", str(tmp_path / "BENCH_PARTIAL.json")
    )
    import bench

    bench = importlib.reload(bench)
    yield bench
    # Reload again so the monkeypatched partial path does not leak into
    # other suites that import bench.
    monkeypatch.undo()
    importlib.reload(bench)


def _partial(bench):
    with open(bench._PARTIAL_PATH) as f:
        return json.loads(f.read())


def test_leg_streams_into_live_record(bench_mod):
    rec = {"metric": "m"}
    bench_mod._LIVE_RECORD = rec
    try:
        bench_mod._leg("attn_blockwise_ms", 12.34)
        bench_mod._leg("attn_gqa", {"speedup": 1.5})
    finally:
        bench_mod._LIVE_RECORD = None
    on_disk = _partial(bench_mod)
    assert on_disk["scaled_legs"]["attn_blockwise_ms"] == 12.34
    assert on_disk["scaled_legs"]["attn_gqa"] == {"speedup": 1.5}
    assert rec["scaled_legs"] == on_disk["scaled_legs"]


def test_leg_without_live_record_is_stderr_only(bench_mod, capsys):
    bench_mod._LIVE_RECORD = None
    bench_mod._leg("attn_flash_ms", 7.0)  # must not raise
    assert "attn_flash_ms=7.0" in capsys.readouterr().err
    assert not os.path.exists(bench_mod._PARTIAL_PATH)


def test_partial_flush_is_atomic_and_additive(bench_mod):
    bench_mod._flush_partial({"a": 1})
    bench_mod._flush_partial({"a": 1, "b": 2})
    assert _partial(bench_mod) == {"a": 1, "b": 2}
    assert not os.path.exists(bench_mod._PARTIAL_PATH + ".tmp")


def test_deadline_fraction_gates(bench_mod, monkeypatch):
    monkeypatch.setattr(bench_mod, "_DEADLINE", 100.0)
    # Shift the bench's own epoch so ~60s appear elapsed: over a 55%
    # budget (55s), under the full deadline. (Patching bench state, not
    # the global clock — stdlib perf_counter stays untouched.)
    monkeypatch.setattr(
        bench_mod, "_BENCH_T0", time.perf_counter() - 60.0
    )
    assert bench_mod._over_deadline("x", frac=0.55) is True
    assert bench_mod._over_deadline("x") is False
    # Deadline disabled -> never over, any fraction.
    monkeypatch.setattr(bench_mod, "_DEADLINE", 0.0)
    assert bench_mod._over_deadline("x", frac=0.55) is False


def test_prior_onchip_newer_stash_embedded_beside_latest(
    bench_mod, tmp_path, monkeypatch
):
    """ADVICE r5: a complete BENCH_ONCHIP_LATEST.json wins the headline
    `record` slot, but a pre-run partial stash captured AFTER it must be
    embedded alongside (`newer_partial`) instead of dropped — and an
    OLDER stash must not be."""
    monkeypatch.setattr(bench_mod, "_REPO_ROOT", str(tmp_path))
    latest = {
        "platform": "tpu", "samples": 1.0,
        "generated_utc": "2026-01-01T00:00:00Z",
    }
    with open(tmp_path / "BENCH_ONCHIP_LATEST.json", "w") as f:
        json.dump(latest, f)
    import calendar

    # Same UTC arithmetic as bench._prior_onchip_evidence's _capture_ts.
    latest_ts = calendar.timegm(time.strptime(
        "2026-01-01T00:00:00Z", "%Y-%m-%dT%H:%M:%SZ"
    ))

    newer_stash = {"platform": "tpu", "samples": 2.0}
    out = bench_mod._prior_onchip_evidence((newer_stash, latest_ts + 86400))
    assert out["source"] == "BENCH_ONCHIP_LATEST.json"
    assert out["record"] == latest  # complete record keeps the headline
    assert out["newer_partial"]["record"] == newer_stash
    assert "pre-run stash" in out["newer_partial"]["source"]

    older = bench_mod._prior_onchip_evidence((newer_stash, latest_ts - 86400))
    assert older["record"] == latest
    assert "newer_partial" not in older

    # No LATEST: the stash competes for the headline slot as before.
    os.remove(tmp_path / "BENCH_ONCHIP_LATEST.json")
    alone = bench_mod._prior_onchip_evidence((newer_stash, latest_ts))
    assert alone["record"] == newer_stash
    assert "newer_partial" not in alone


def _worst_case_record() -> dict:
    """A record with EVERY section populated at realistic on-chip size —
    the shape that broke r05 (prior_onchip + val_parity + all sections
    at once). Field values mirror real rounds' records."""
    return {
        "metric": "weather_parity_train_samples_per_sec_per_chip",
        "unit": "samples/sec/chip",
        "mfu": 0.2134,
        "generated_utc": "2026-08-04T12:00:00Z",
        "probe": {"requested": "axon", "platform": "tpu", "attempts": 3,
                  "elapsed_s": 612.6, "budget_s": 750.0,
                  "fallback_reason": "probe timeout: backend unreachable"},
        "prior_onchip": {
            "source": "BENCH_PARTIAL.json (pre-run stash)",
            "captured_utc": "2026-07-31T04:00:00Z",
            "record": {"platform": "tpu", "value": 8342288.3,
                       "vs_baseline": 1580.31, "mfu": 0.2134,
                       "scaled": {"step_time_ms": 15.3}},
            "campaign": {"source": "ONCHIP_CAMPAIGN.jsonl",
                         "captured_utc": "2026-07-30T00:00:00Z",
                         "tpu_item_count": 120,
                         "tpu_items": [{"section": "mfu", "item": f"cfg{i}",
                                        "result": {"mfu": 0.2}}
                                       for i in range(120)]},
            "newer_partial": {
                "source": "BENCH_PARTIAL.json (pre-run stash)",
                "captured_utc": "2026-08-01T00:00:00Z",
                "record": {"platform": "tpu", "value": 9000000.0,
                           "mfu": 0.25},
            },
        },
        "baseline_torch_cpu_samples_per_sec": 5278.9,
        "value": 8342288.3,
        "vs_baseline": 1580.31,
        "final_train_loss": 0.0037,
        "platform": "tpu",
        "trainer_loop_samples_per_sec_per_chip": 198817.8,
        "trainer_loop_vs_baseline": 37.66,
        "trainer_gap": {"fused": 8342288.3, "fit": 198817.8,
                        "fused_over_fit": 41.96, "prefetch_spans": 1},
        "trainer_loop_chunked_samples_per_sec_per_chip": 205000.1,
        "trainer_loop_chunked_note": (
            "chunked<per-epoch expected on local CPU (dispatch RTT ~0); "
            "target is a slow control plane — BENCH_NOTES.md"
        ),
        "deadline_skipped": ["scaled_moe", "val_parity", "serving",
                             "host_dataplane"],
        "scaled": {
            "config": {"d_model": 512, "n_heads": 8, "n_layers": 4,
                       "d_ff": 2048, "seq_len": 1024, "batch": 32,
                       "dtype": "bfloat16", "scan_len": 16,
                       "remat": True},
            "step_time_ms": 15.31, "step_time_dispatch_ms": 45.98,
            "flops_per_step": 3305111224320.0, "tflops_per_sec": 215.88,
            "attn_blockwise_ms": 16.76, "attn_flash_ms": 15.31,
            "samples_per_sec_per_chip": 2090.8,
            "attn_window": 256,
            "attn_causal_flash_ms": 9.97, "attn_causal_blockwise_ms": 14.2,
            "attn_window_flash_ms": 5.44, "attn_window_blockwise_ms": 13.9,
            "attn_gqa": {"kv_heads": 2, "mha_ms": 4.021, "gqa_ms": 3.312,
                         "speedup": 1.21},
            "deadline_skipped": ["window_blockwise", "gqa"],
            "chip_peak_bf16_tflops": 197.0, "mfu": 0.2134,
        },
        "moe": {"config": {"d_model": 512, "n_heads": 8, "n_layers": 2,
                           "d_ff": 1024, "seq_len": 512, "n_experts": 32,
                           "batch": 8, "dtype": "bfloat16"},
                "sorted_ms": 21.4, "einsum_ms": 44.1,
                "sorted_speedup": 2.06,
                "deadline_skipped": ["einsum"]},
        "val_parity": {
            "protocol": (
                "10 epochs, batch 4, Adam lr 0.01, seeded 80/20 split, "
                "seed 42 (train_lightning_ddp.py:14,88,117,122,132)"
            ),
            "torch_val_loss": 0.30294, "torch_val_acc": 0.86643,
            "jax_val_loss": 0.31351, "jax_val_acc": 0.86292,
            "abs_diff": 0.01057,
        },
        "serving": {
            "single_row": {"numpy_p50_ms": 0.0518, "torch_p50_ms": 0.1023,
                           "speedup": 1.97},
            "batch64": {"numpy_p50_ms": 0.0671, "torch_p50_ms": 0.1388,
                        "speedup": 2.07},
        },
        "serving_load": {
            "processes": 1,
            "levels": [
                {"mode": "closed", "concurrency": c, "requests": 300,
                 "errors": 0, "duration_s": 0.4, "qps": q,
                 "p50_ms": p50, "p99_ms": p99}
                for c, q, p50, p99 in (
                    (1, 2186.7, 0.3982, 0.9883),
                    (4, 2493.1, 1.4849, 3.7727),
                    (16, 1477.6, 4.5024, 11.4212),
                )
            ],
            "knee_concurrency": 4, "knee_qps": 2493.1,
            "saturated_qps": 2493.1, "saturated_concurrency": 4,
            "baseline_qps": 2186.7, "batched_over_single": 1.14,
            "parity": True, "score_batched_over_single": 15.96,
        },
        # The streamed crash hedges a failed section leaves behind (the
        # r05 shape: the scaled death kept scaled_legs in the record),
        # val_parity hedge with its full protocol prose included.
        "scaled_legs": {
            "attn_blockwise_ms": 16.76, "attn_flash_ms": 15.31,
            "attn_causal_flash_ms": 9.97,
            "attn_gqa": {"kv_heads": 2, "mha_ms": 4.021, "gqa_ms": 3.312,
                         "speedup": 1.21},
            "moe_sorted_ms": 21.4, "moe_einsum_ms": 44.1,
            "val_parity_torch": {"torch_val_loss": 0.30294,
                                 "torch_val_acc": 0.86643},
            "val_parity": {
                "protocol": (
                    "10 epochs, batch 4, Adam lr 0.01, seeded 80/20 "
                    "split, seed 42 "
                    "(train_lightning_ddp.py:14,88,117,122,132)"
                ),
                "torch_val_loss": 0.30294, "jax_val_loss": 0.31351,
                "abs_diff": 0.01057,
            },
        },
        "scaled_mfu_stale": True,
        "scaled_mfu_stale_reason": (
            "JaxRuntimeError: UNAVAILABLE: http://127.0.0.1:8103/"
            "remote_compile: transport: Connection Failed: Connect "
            "error: Connection refused (os error 111)"
        ),
        "restart_spinup": {
            "cold_step_s": 15.828, "warm_step_s": 4.866,
            "cold_compile_s": 10.242, "warm_compile_s": 2.68,
            "warm_cache": ["hit"], "step_speedup": 3.25,
            "cold_score_s": 2.0097, "warm_score_s": 0.8364,
            "score_speedup": 2.4,
        },
        "cycle_freshness": {
            "generations": 2,
            "epochs_per_gen_serial": 200, "loop_round_epochs": 8,
            "soak_s": 0.35,
            "serial": {
                "freshness_s": [7.071, 11.748],
                "mean_freshness_s": 9.41, "cycle_s": 4.597,
                "cycles": 6, "promotions": 4, "held": 2,
                "goodput": 0.1357,
                "train_samples_per_sec_per_chip": 68309.9,
                "wall_s": 28.875,
            },
            "loop": {
                "freshness_s": [2.39, 2.413],
                "mean_freshness_s": 2.402, "rounds": 11,
                "promotions": 8, "held": 0, "goodput": 0.0381,
                "train_samples_per_sec_per_chip": 76164.4,
                "wall_s": 6.46, "stop_reason": "freshness_measured",
            },
            "serial_mean_freshness_s": 9.41,
            "loop_mean_freshness_s": 2.402,
            "goodput_serial": 0.1357, "goodput_loop": 0.0381,
            "freshness_speedup": 3.92, "train_throughput_ratio": 1.11,
        },
        "multi_tenant": {
            "tenants": 2, "rounds": 12, "preempts": 1, "wall_s": 14.8,
            "min_goodput_fraction": 0.0312, "mean_round_wait_s": 0.41,
            "quota_max_rel_err": 0.11,
            "per_tenant": {
                "light": {"weight": 1.0, "priority_rank": 1, "chips": 1,
                          "rounds": 4, "preempted_rounds": 0,
                          "granted_chip_s": 4.91, "goodput_s": 0.19,
                          "badput_s": 4.72, "goodput_fraction": 0.0387,
                          "mean_wait_s": 0.62, "fair_share": 0.3333,
                          "granted_share": 0.3602, "state": "stopped"},
                "heavy": {"weight": 2.0, "priority_rank": 1, "chips": 1,
                          "rounds": 8, "preempted_rounds": 1,
                          "granted_chip_s": 8.72, "goodput_s": 0.27,
                          "badput_s": 8.45, "goodput_fraction": 0.0312,
                          "mean_wait_s": 0.2, "fair_share": 0.6667,
                          "granted_share": 0.6398, "state": "stopped"},
            },
        },
        "model_sharded": {
            "devices": 4,
            "config": {
                "seq_len": 16, "d_model": 64, "n_heads": 2,
                "n_layers": 2, "d_ff": 128, "batch": 32, "scan_len": 8,
            },
            "dp_sps": 2100.5, "sharded_sps": 1772.0,
            "dp_peak_rss_mb": 302.8, "sharded_peak_rss_mb": 315.1,
            "loss_delta": 0.00083673,
            "sharded_sps_ratio": 0.844, "peak_rss_ratio": 0.961,
        },
        "mpmd_pipeline": {
            "stages": 2, "microbatches": 8,
            "config": {"seq_len": 32, "d_model": 128, "n_heads": 4,
                       "n_layers": 2, "d_ff": 512, "mb_rows": 32},
            "gpipe_bubble_fraction": 0.1111,
            "mpmd_steady_bubble": 0.0758,
            "mpmd_step_bubble": 0.1208,
            "mpmd_slope_bubble": 0.0381,
            "mpmd_transfer_wait_s": 0.0977,
            "gpipe_sps": 139.1, "mpmd_sps": 193.7,
            "loss_delta": 2.1e-06,
            "bubble_reduction": 0.3149, "mpmd_sps_ratio": 1.392,
        },
        "host_dataplane": {
            "rows_native_ms": 0.23, "rows_numpy_ms": 0.51,
            "rows_speedup": 2.18, "windows_native_ms": 1.43,
            "windows_numpy_ms": 11.05, "windows_speedup": 7.71,
        },
        "elastic_serving": {
            "trace": {"base_qps": 60.0, "spike_qps": 240.0,
                      "base_s": 1.5, "spike_s": 2.5, "service_ms": 8.0},
            "off": {
                phase: {"mode": "open", "concurrency": 400,
                        "requests": n, "errors": 0, "duration_s": d,
                        "qps": q, "p50_ms": p50, "p99_ms": p99,
                        "target_qps": tq, "dropped": 0}
                for phase, n, d, q, p50, p99, tq in (
                    ("base", 90, 1.5, 59.9, 9.1, 11.0, 60.0),
                    ("spike", 600, 5.01, 119.7, 1272.0, 2497.0, 240.0),
                    ("recover", 90, 1.5, 59.8, 10.2, 14.1, 60.0),
                )
            },
            "on": {
                phase: {"mode": "open", "concurrency": 400,
                        "requests": n, "errors": 0, "duration_s": d,
                        "qps": q, "p50_ms": p50, "p99_ms": p99,
                        "shed": s, "shed_fraction": sf,
                        "shed_p50_ms": 0.65, "target_qps": tq,
                        "dropped": 0}
                for phase, n, d, q, p50, p99, s, sf, tq in (
                    ("base", 90, 1.5, 59.9, 9.3, 11.0, 0, 0.0, 60.0),
                    ("spike", 510, 2.51, 203.4, 13.3, 26.2, 90, 0.15,
                     240.0),
                    ("recover", 90, 1.5, 59.8, 9.8, 13.2, 0, 0.0, 60.0),
                )
            },
            "pre_spike_p99_ms": 10.98, "pre_spike_p99_off_ms": 10.62,
            "spike_p99_off_ms": 2497.01, "spike_p99_on_ms": 26.25,
            "p99_ratio_off": 227.46, "p99_ratio_on": 2.39,
            "overload_p99_s": 0.0262, "shed": 90, "admitted": 690,
            "shed_fraction": 0.1154, "admitted_errors": 0,
            "scale_events": 4, "bounded": True,
        },
        "telemetry_history": {
            "plain_publish_p50_ms": 0.2131, "armed_publish_p50_ms": 0.2298,
            "publish_overhead_ms": 0.0167, "overhead_frac": 0.0784,
            "detected": True, "detect_latency_s": 1.847,
            "rig": {"service_ms": 2.0, "fault_ms": 30.0,
                    "base_qps": 40.0, "spike_qps": 80.0,
                    "baseline_s": 1.6, "budget_s": 12.0},
        },
        "stream_ingest": {
            "n_events": 4000, "burst": 50, "burst_every_s": 0.05,
            "lag_bound_s": 0.25, "stream_poll_s": 0.1, "csv_poll_s": 2.0,
            "stream_events_per_s": 936.6, "poll_events_per_s": 123.7,
            "stream_lag_p99_s": 0.112, "poll_lag_p99_s": 2.0273,
            "stream": {"trainable": 4000, "in_bound": 4000,
                       "in_bound_events_per_s": 936.6,
                       "lag_p99_s": 0.112, "wall_s": 4.27},
            "poll": {"trainable": 4000, "in_bound": 500,
                     "in_bound_events_per_s": 123.7,
                     "lag_p99_s": 2.0273, "wall_s": 4.04},
            "backpressure": {"lag_budget": 64, "produced": 64,
                             "shed": 448, "end_lag_records": 64,
                             "bounded": True},
            "events_per_s_speedup": 7.57, "lag_bounded": True,
        },
        "low_precision": {
            "serving": {
                "f32": {"p50_ms": 0.3161, "batch64_rows_per_s": 5340.4,
                        "max_abs_prob_delta": 0.0},
                "int8": {"p50_ms": 0.4166,
                         "batch64_rows_per_s": 20485.8,
                         "max_abs_prob_delta": 0.004959,
                         "speedup_batch64": 3.84},
                "bf16": {"p50_ms": 0.2808,
                         "batch64_rows_per_s": 5413.1,
                         "max_abs_prob_delta": 0.001306,
                         "speedup_batch64": 1.01},
            },
            "quant_serving_speedup": 3.84,
            "train": {
                "config": {"d_model": 128, "n_heads": 4, "n_layers": 2,
                           "d_ff": 1024, "seq_len": 64, "batch": 64},
                "peak_source": "measured_gemm",
                "f32": {"samples_per_s": 73.2,
                        "bytes_accessed": 5206724608.0,
                        "flops": 17284323328.0, "mfu": 0.169985},
                "bf16_rules": {"samples_per_s": 46.7,
                               "bytes_accessed": 3648292608.0,
                               "flops": 17310842880.0, "mfu": 0.108695},
                "bf16_bytes_ratio": 0.701, "bytes_reduction_pct": 29.9,
                "bf16_sps_ratio": 0.64, "bf16_mfu_delta": -0.06129,
            },
            "bf16_bytes_ratio": 0.701,
            "gate": {"clean": "promote", "corrupted": "rollback",
                     "parity": True},
        },
    }


def test_stdout_record_worst_case_fits_driver_tail(bench_mod):
    """VERDICT r5 item 1 / ISSUE 5 satellite: the PRINTED line, with
    every section populated AND the on-chip carry-forward present, must
    stay under 1,800 B (the driver truncates its parse tail at 2,000 B;
    r05 shipped 2,578 B and parsed null)."""
    record = _worst_case_record()
    line = json.dumps(
        bench_mod._stdout_record(record), default=bench_mod._json_default
    )
    assert len(line.encode()) <= 1800, len(line.encode())
    # The digest keeps provenance + the headline numbers...
    out = json.loads(line)
    po = out["prior_onchip"]
    assert po["value"] == 8342288.3 and po["mfu"] == 0.2134
    assert po["captured_utc"] == "2026-07-31T04:00:00Z"
    assert po["source"] == "BENCH_PARTIAL.json (pre-run stash)"
    # ...while the verbatim embed (with its 120 campaign items) is NOT
    # on stdout — it stays in the partial/BENCH_ONCHIP_LATEST files.
    assert "record" not in po and "tpu_items" not in json.dumps(po)
    # Headline measurements survive every shrink rung.
    assert out["value"] == 8342288.3
    assert out["trainer_loop_samples_per_sec_per_chip"] == 198817.8
    assert out["trainer_gap"]["fused_over_fit"] == 41.96
    assert out["mfu"] == 0.2134
    assert out["scaled"]["attn_blockwise_ms"] == 16.76
    assert out["scaled"]["attn_flash_ms"] == 15.31
    assert out["scaled"]["mfu"] == 0.2134
    assert out["moe"]["sorted_speedup"] == 2.06
    assert out["val_parity"]["abs_diff"] == 0.01057
    assert out["probe"]["platform"] == "tpu"
    assert out["deadline_skipped"] == record["deadline_skipped"]
    # Both low-precision sentinel series survive every shrink rung.
    assert out["low_precision"]["quant_serving_speedup"] == 3.84
    assert out["low_precision"]["bf16_bytes_ratio"] == 0.701


def test_stdout_record_typical_round_is_not_collapsed(bench_mod):
    """A realistic single-platform record (no carry-forward pileup, no
    failure leftovers) must keep every HEADLINE stanza's numbers on
    stdout: the full scaled section, moe timings, val_parity's
    loss-parity numbers, the serving_load columnar digest, and the
    cycle_freshness architecture comparison. The least-headline rungs
    (host_dataplane detail, serving p50 detail, probe prose, the
    val_parity accuracy pair) may yield — every yielded field lives on
    verbatim in BENCH_PARTIAL.json."""
    record = _worst_case_record()
    # A normal round (r05 shape): no carry-forward pileup, no chunked
    # leg, no failed-section leftovers, and the scaled section without
    # the full variant-leg sweep.
    del record["prior_onchip"]
    del record["trainer_loop_chunked_note"]
    del record["trainer_loop_chunked_samples_per_sec_per_chip"]
    del record["deadline_skipped"]
    del record["scaled_legs"]
    del record["scaled_mfu_stale"]
    del record["scaled_mfu_stale_reason"]
    for leg in ("attn_causal_flash_ms", "attn_causal_blockwise_ms",
                "attn_window_flash_ms", "attn_window_blockwise_ms",
                "attn_gqa", "attn_window", "deadline_skipped"):
        del record["scaled"][leg]
    out = bench_mod._stdout_record(record)
    line = json.dumps(out, default=bench_mod._json_default)
    assert len(line.encode()) <= bench_mod._STDOUT_BUDGET
    # Headline stanzas un-collapsed...
    assert out["scaled"]["step_time_dispatch_ms"] == 45.98
    assert out["moe"]["einsum_ms"] == 44.1
    # ...val_parity keeps the north-star LOSS parity (the accuracy pair
    # yields to the partial when the record is fully populated)...
    assert out["val_parity"]["torch_val_loss"] == 0.30294
    assert out["val_parity"]["jax_val_loss"] == 0.31351
    assert out["val_parity"]["abs_diff"] == 0.01057
    # ...the cycle_freshness architecture comparison rides stdout with
    # the sentinel's series (speedup + the loop mean); the serial mean
    # is derivable (loop_mean x speedup — yielded to fund the
    # mpmd_pipeline sentinel series) and the goodput pair yields to the
    # partial when every stanza is populated at once (the late rung
    # funding the stream_ingest sentinel series)...
    cf = out["cycle_freshness"]
    assert cf["freshness_speedup"] == 3.92
    assert "serial_mean_freshness_s" not in cf
    assert cf["loop_mean_freshness_s"] == 2.402
    assert "goodput_serial" not in cf and "goodput_loop" not in cf
    # ...the restart_spinup digest rides stdout with the sentinel's
    # warm series + both ratios (cold controls derivable, detail in
    # the partial)...
    assert out["restart_spinup"] == {
        "warm_step_s": 4.866, "step_speedup": 3.25,
        "warm_score_s": 0.8364, "score_speedup": 2.4,
    }
    # ...the model_sharded digest keeps the sentinel's throughput
    # ratio (the memory ratio/parity delta may yield to the partial
    # under a full-record squeeze)...
    ms = out["model_sharded"]
    assert ms["sharded_sps_ratio"] == 0.844
    assert "config" not in ms and "dp_sps" not in ms
    # ...the mpmd_pipeline digest keeps both sentinel series (steady
    # bubble, sps ratio) + the gpipe comparator (it would yield only
    # under a squeeze the goodput-pair rung did not already satisfy;
    # bubble_reduction = 1 - steady/gpipe recovers it from the
    # partial); the config dict and absolute sps detail stay in the
    # partial...
    mpp = out["mpmd_pipeline"]
    assert mpp["mpmd_steady_bubble"] == 0.0758
    assert mpp["gpipe_bubble_fraction"] == 0.1111
    assert mpp["mpmd_sps_ratio"] == 1.392
    assert "config" not in mpp and "gpipe_sps" not in mpp
    # ...serving keeps (at least) its speedup headlines...
    assert out["serving"]["single_row"] in (
        1.97, record["serving"]["single_row"]
    )
    # ...and serving_load rides stdout as the columnar digest with
    # every level's numbers intact (the per-level dict list stays in
    # the partial).
    sl = out["serving_load"]
    assert sl["levels"]["qps"] == [2186.7, 2493.1, 1477.6]
    assert sl["levels"]["p99_ms"] == [0.9883, 3.7727, 11.4212]
    assert sl["batched_over_single"] == 1.14
    assert sl["score_batched_over_single"] == 15.96
    # ...elastic_serving keeps both sentinel series on stdout (the A/B
    # ratio pair may yield to the partial when every stanza is
    # populated at once — the late rung funding telemetry_history);
    # the per-phase replay dicts stay in the partial.
    es = out["elastic_serving"]
    assert es["overload_p99_s"] == 0.0262
    assert es["shed_fraction"] == 0.1154
    assert "off" not in es and "on" not in es and "trace" not in es
    # ...telemetry_history keeps exactly its two sentinel series; the
    # plain/armed p50 pair and the rig knobs stay in the partial.
    assert out["telemetry_history"] == {
        "detect_latency_s": 1.847, "publish_overhead_ms": 0.0167,
    }
    # ...stream_ingest keeps its two sentinel series on stdout (the
    # vs-polling speedup and the acceptance bits yield to the partial
    # when every stanza is populated at once — the same late rung that
    # funds telemetry_history); the polling comparator's raw numbers,
    # the arrival-schedule shape and the backpressure counters stay in
    # the partial.
    assert out["stream_ingest"] == {
        "stream_events_per_s": 936.6, "stream_lag_p99_s": 0.112,
    }
    # ...and low_precision rides stdout as its digest: both sentinel
    # series + the accuracy-bound evidence + the gate bit (the train
    # A/B ratios may yield under a full-record squeeze; the per-variant
    # p50/bytes detail always stays in the partial).
    lp = out["low_precision"]
    assert lp["quant_serving_speedup"] == 3.84
    assert lp["bf16_bytes_ratio"] == 0.701
    assert lp["int8_prob_delta"] == 0.004959
    assert lp["gate_parity"] is True
    assert "serving" not in lp and "train" not in lp


def test_stdout_record_bounds_error_strings(bench_mod):
    """An on-chip failure embeds XLA error text that can run to
    kilobytes: a record carrying error sections (plus the full
    carry-forward) must still print inside the driver tail — the shrink
    ladder's last rung truncates any long string leaf."""
    record = _worst_case_record()
    xla = ("JaxRuntimeError: UNAVAILABLE: http://127.0.0.1:8103/"
           "remote_compile: transport: Connection Failed: ") + "x" * 4000
    record["serving"] = {"error": xla}
    record["moe"] = {"error": xla}
    record["scaled"]["attn_flash_error"] = xla
    record["scaled"]["attn_gqa"] = {"error": xla}
    line = json.dumps(
        bench_mod._stdout_record(record), default=bench_mod._json_default
    )
    assert len(line.encode()) <= 1800, len(line.encode())
    out = json.loads(line)
    # Headlines still survive alongside the (bounded) error evidence.
    assert out["value"] == 8342288.3
    assert out["trainer_gap"]["fused_over_fit"] == 41.96


def test_stdout_record_passthrough_without_carry_forward(bench_mod):
    """A record with no prior_onchip/val_parity must print unchanged."""
    rec = {"metric": "m", "value": 1.0, "scaled": None}
    assert bench_mod._stdout_record(rec) == rec


def _r05_record() -> dict:
    """The ACTUAL record shape that shipped 2,578 B and ``parsed: null``
    in round 5 (BENCH_r05.json): a CPU driver run whose prior_onchip
    stanza embedded the full verbatim TPU record — including the
    multi-hundred-byte connection-refused scaled error — next to every
    CPU section. Reconstructed field-for-field from the captured tail."""
    xla_err = (
        "JaxRuntimeError: UNAVAILABLE: http://127.0.0.1:8103/"
        "remote_compile: transport: http://127.0.0.1:8103/"
        "remote_compile: Connection Failed: Connect error: "
        "Connection refused (os error 111)"
    )
    inner_tpu = {
        "metric": "weather_parity_train_samples_per_sec_per_chip",
        "unit": "samples/sec/chip", "mfu": None,
        "probe": {"requested": "axon", "platform": "tpu", "attempts": 1,
                  "elapsed_s": 2.6, "budget_s": 750.0,
                  "fallback_reason": None},
        "baseline_torch_cpu_samples_per_sec": 5278.9,
        "value": 8342288.3, "vs_baseline": 1580.31,
        "final_train_loss": 0.0037, "platform": "tpu",
        "trainer_loop_samples_per_sec_per_chip": 198817.8,
        "trainer_loop_vs_baseline": 37.66,
        "scaled": {"error": xla_err},
        "moe": None, "serving": None, "host_dataplane": None,
    }
    return {
        "metric": "weather_parity_train_samples_per_sec_per_chip",
        "unit": "samples/sec/chip", "mfu": None,
        "generated_utc": "2026-08-01T09:00:00Z",
        "probe": {"requested": "axon", "platform": "cpu", "attempts": 5,
                  "elapsed_s": 750.0, "budget_s": 750.0,
                  "fallback_reason": (
                      "backend 'axon' failed to initialize: 5 probe "
                      "attempt(s) over 750s (budget 750s, per-attempt "
                      "cap 150s)"
                  )},
        "prior_onchip": {
            "source": "BENCH_PARTIAL.json (pre-run stash)",
            "captured_utc": "2026-07-31T04:47:00Z",
            "record": inner_tpu,
        },
        "baseline_torch_cpu_samples_per_sec": 5609.3,
        "value": 239743.4, "vs_baseline": 42.74,
        "final_train_loss": 0.0023, "platform": "cpu",
        "trainer_loop_samples_per_sec_per_chip": 211724.6,
        "trainer_loop_vs_baseline": 37.75,
        "scaled": {
            "config": {"d_model": 128, "n_heads": 8, "n_layers": 2,
                       "d_ff": 256, "seq_len": 256, "batch": 4,
                       "dtype": "bfloat16", "scan_len": 2,
                       "remat": False},
            "step_time_ms": 162.76, "step_time_dispatch_ms": 194.98,
            "flops_per_step": 2421424128.0, "tflops_per_sec": 0.01,
            "attn_blockwise_ms": 162.76, "attn_flash_ms": None,
            "samples_per_sec_per_chip": 24.6,
        },
        "moe": {"config": {"d_model": 64, "n_heads": 4, "n_layers": 1,
                           "d_ff": 128, "seq_len": 64, "n_experts": 4,
                           "batch": 4, "dtype": "bfloat16"},
                "sorted_ms": 5.47, "einsum_ms": 5.88,
                "sorted_speedup": 1.07},
        "val_parity": {
            "protocol": (
                "10 epochs, batch 4, Adam lr 0.01, seeded 80/20 split, "
                "seed 42 (train_lightning_ddp.py:14,88,117,122,132)"
            ),
            "torch_val_loss": 0.30294, "torch_val_acc": 0.85675,
            "jax_val_loss": 0.31351, "jax_val_acc": 0.85425,
            "abs_diff": 0.01057,
        },
        "serving": {
            "single_row": {"numpy_p50_ms": 0.0161, "torch_p50_ms": 0.0297,
                           "speedup": 1.85},
            "batch64": {"numpy_p50_ms": 0.0469, "torch_p50_ms": 0.0652,
                        "speedup": 1.39},
        },
        "host_dataplane": {
            "rows_native_ms": 0.458, "rows_numpy_ms": 0.999,
            "rows_speedup": 2.18, "windows_native_ms": 1.148,
            "windows_numpy_ms": 8.848, "windows_speedup": 7.71,
        },
    }


def test_stdout_record_r05_regression(bench_mod):
    """ISSUE 7 satellite: the round-5 record that actually shipped
    2,578 B and landed ``parsed: null`` must print inside the cap —
    the shrink ladder enforced on the REAL record shape, not just the
    synthetic fixture."""
    record = _r05_record()
    raw = len(json.dumps(record, default=bench_mod._json_default).encode())
    assert raw > 2000, raw  # the shape genuinely overflows un-shrunk
    line = json.dumps(
        bench_mod._stdout_record(record), default=bench_mod._json_default
    )
    assert len(line.encode()) <= 1800, len(line.encode())
    out = json.loads(line)
    # The carried TPU evidence survives as the digest...
    assert out["prior_onchip"]["value"] == 8342288.3
    assert out["prior_onchip"]["platform"] == "tpu"
    # ...and the verbatim inner record (with its XLA error) does not.
    assert "record" not in out["prior_onchip"]
    # This run's own headline numbers are intact.
    assert out["value"] == 239743.4
    assert out["trainer_loop_samples_per_sec_per_chip"] == 211724.6
    assert out["val_parity"]["abs_diff"] == 0.01057


def test_stdout_record_r05_shape_with_restart_spinup_pinned(bench_mod):
    """ISSUE 9 satellite: the restart_spinup stanza riding the exact
    r05 overflow shape must stay inside the driver tail, with the
    sentinel's warm series surviving the ladder (regressing the
    parsed:null overflow via the new stanza is the failure mode this
    test exists to block)."""
    record = _r05_record()
    record["restart_spinup"] = {
        "cold_step_s": 15.828, "warm_step_s": 4.866,
        "cold_compile_s": 10.242, "warm_compile_s": 2.68,
        "warm_cache": ["hit"], "step_speedup": 3.25,
        "cold_score_s": 2.0097, "warm_score_s": 0.8364,
        "score_speedup": 2.4,
    }
    line = json.dumps(
        bench_mod._stdout_record(record), default=bench_mod._json_default
    )
    assert len(line.encode()) <= 1800, len(line.encode())
    out = json.loads(line)
    rs = out["restart_spinup"]
    # The warm series (what observability/report.py tracks) survives.
    assert rs["warm_step_s"] == 4.866
    assert rs["warm_score_s"] == 0.8364
    assert rs["step_speedup"] == 3.25 and rs["score_speedup"] == 2.4
    # The cold controls + cache detail live in the partial, not stdout.
    assert "cold_step_s" not in rs and "warm_cache" not in rs


def test_stdout_record_failed_scaled_leaves_bounded_legs(bench_mod):
    """When the scaled section dies, its streamed scaled_legs hedge
    stays in the record (the r05 on-chip shape) — the ladder must now
    reach it, and the staleness flag + reason must survive every
    rung."""
    record = _worst_case_record()
    record["scaled"] = {"error": "JaxRuntimeError: UNAVAILABLE: " + "x" * 400}
    record["mfu"] = None
    line = json.dumps(
        bench_mod._stdout_record(record), default=bench_mod._json_default
    )
    assert len(line.encode()) <= 1800, len(line.encode())
    out = json.loads(line)
    assert out["scaled_mfu_stale"] is True
    assert "Connection refused" in out["scaled_mfu_stale_reason"]
    # The legs hedge survives in digest form (headline kernels only).
    assert out["scaled_legs"]["attn_blockwise_ms"] == 16.76


def test_truncate_recurses_into_lists(bench_mod):
    """Probe attempts / loadgen levels are LISTS of dicts; a huge
    string inside one must still be bounded by the last rung."""
    record = _worst_case_record()
    record["probe"] = {
        "platform": "cpu",
        "attempts": [
            {"n": i, "error": "Connection refused " + "y" * 3000}
            for i in range(4)
        ],
        "fallback_reason": "z" * 3000,
    }
    line = json.dumps(
        bench_mod._stdout_record(record), default=bench_mod._json_default
    )
    assert len(line.encode()) <= 1800, len(line.encode())


def test_scaled_retry_satellite_transient_retries(bench_mod, monkeypatch):
    """A transient (relay-class) failure retries through the platform
    retry policy and succeeds without staleness flags."""
    monkeypatch.setenv("DCT_RETRY_MAX_ATTEMPTS", "3")
    monkeypatch.setenv("DCT_RETRY_BACKOFF_S", "0")
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise ConnectionRefusedError("Connection refused (os error 111)")
        return {"mfu": 0.21, "step_time_ms": 15.3}

    monkeypatch.setattr(bench_mod, "bench_scaled_transformer", flaky)
    record = {}
    out = bench_mod._run_scaled_with_retries(record)
    assert out == {"mfu": 0.21, "step_time_ms": 15.3}
    assert len(calls) == 3
    assert "scaled_mfu_stale" not in record


def test_scaled_retry_satellite_relay_down_stamps_stale(
    bench_mod, monkeypatch
):
    """Retries exhausted on a dead relay: the record carries
    scaled_mfu_stale + the failure reason instead of a silent null
    (r05's scaled leg shape)."""
    monkeypatch.setenv("DCT_RETRY_MAX_ATTEMPTS", "2")
    monkeypatch.setenv("DCT_RETRY_BACKOFF_S", "0")
    calls = []

    def dead_relay():
        calls.append(1)
        raise RuntimeError(
            "UNAVAILABLE: http://127.0.0.1:8103/remote_compile: "
            "Connection refused (os error 111)"
        )

    monkeypatch.setattr(bench_mod, "bench_scaled_transformer", dead_relay)
    record = {}
    out = bench_mod._run_scaled_with_retries(record)
    assert len(calls) == 2  # retried once, then exhausted
    assert "error" in out and "UNAVAILABLE" in out["error"]
    assert record["scaled_mfu_stale"] is True
    assert "Connection refused" in record["scaled_mfu_stale_reason"]


def test_scaled_retry_satellite_fatal_does_not_retry(
    bench_mod, monkeypatch
):
    """A real compile error is not transient: no retry, no staleness
    claim — the number is absent because the code is broken, not
    because the relay ate it."""
    monkeypatch.setenv("DCT_RETRY_MAX_ATTEMPTS", "3")
    monkeypatch.setenv("DCT_RETRY_BACKOFF_S", "0")
    calls = []

    def broken():
        calls.append(1)
        raise ValueError("Mosaic lowering failed: bad block shape")

    monkeypatch.setattr(bench_mod, "bench_scaled_transformer", broken)
    record = {}
    out = bench_mod._run_scaled_with_retries(record)
    assert len(calls) == 1
    assert "error" in out
    assert "scaled_mfu_stale" not in record


def test_deadline_gate_subtracts_probe_elapsed(bench_mod, monkeypatch):
    """VERDICT r5 item 3: the leg budget clock starts AFTER the probe —
    a dead relay's 750 s probe must not consume the frac-gated legs'
    budgets."""
    monkeypatch.setattr(bench_mod, "_DEADLINE", 100.0)
    monkeypatch.setattr(
        bench_mod, "_BENCH_T0", time.perf_counter() - 800.0
    )
    # Without the probe credit, 800s elapsed >> any budget.
    assert bench_mod._over_deadline("x") is True
    # With 750s attributed to the probe, only 50s of bench time has
    # passed: inside the full budget, over a 30% fraction.
    monkeypatch.setattr(bench_mod, "_PROBE_ELAPSED", 750.0)
    assert bench_mod._over_deadline("x") is False
    assert bench_mod._over_deadline("x", frac=0.3) is True


def test_flush_survives_numpy_scalars(bench_mod):
    """A np scalar leaking into a leg value must not raise FROM the
    hedge (a TypeError here would kill the section it protects)."""
    import numpy as np

    bench_mod._flush_partial({
        "v": np.float32(12.5), "flag": np.bool_(True),
        "arr_note": np.int64(3),
    })
    on_disk = _partial(bench_mod)
    assert on_disk["v"] == 12.5 and on_disk["flag"] == 1.0
    assert not os.path.exists(bench_mod._PARTIAL_PATH + ".tmp")
