"""Pallas flash-attention kernel vs the dense oracle (interpret mode on the
CPU rig; the same kernel compiles via Mosaic on TPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dct_tpu.ops.attention import dense_attention
from dct_tpu.ops.pallas_attention import flash_attention

B, H, T, D = 2, 2, 128, 16


@pytest.fixture()
def qkv(rng):
    return tuple(
        jnp.asarray(rng.standard_normal((B, H, T, D)), jnp.float32)
        for _ in range(3)
    )


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_dense(qkv, causal):
    q, k, v = qkv
    ref = dense_attention(q, k, v, causal=causal)
    out = flash_attention(
        q, k, v, block_q=32, block_k=32, causal=causal, interpret=True
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_grad_matches_dense(qkv, causal):
    """The Pallas backward kernels (dQ / dK+dV) against AD through the
    dense oracle."""
    q, k, v = qkv

    def loss_flash(q, k, v):
        return flash_attention(
            q, k, v, block_q=32, block_k=32, causal=causal, interpret=True
        ).sum()

    def loss_dense(q, k, v):
        return dense_attention(q, k, v, causal=causal).sum()

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for gf, gd in zip(g_flash, g_dense):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gd), atol=1e-4)


def test_flash_grad_weighted_cotangent(qkv):
    """Non-uniform output cotangents (a real loss, not .sum()) flow
    correctly through the backward kernels."""
    q, k, v = qkv
    w = jnp.asarray(
        np.random.default_rng(3).standard_normal((B, H, T, D)), jnp.float32
    )

    def loss(f):
        return lambda q, k, v: (f(q, k, v) * w).sum()

    flash = loss(
        lambda q, k, v: flash_attention(
            q, k, v, block_q=32, block_k=64, causal=True, interpret=True
        )
    )
    dense = loss(lambda q, k, v: dense_attention(q, k, v, causal=True))
    g_flash = jax.grad(flash, argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(dense, argnums=(0, 1, 2))(q, k, v)
    for gf, gd in zip(g_flash, g_dense):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gd), atol=1e-4)


def test_flash_bwd_remat_escape_hatch(qkv, monkeypatch):
    """DCT_FLASH_BWD=remat must produce the same gradients as the kernel
    backward (it differentiates the numerically-identical blockwise path)."""
    q, k, v = qkv

    def loss(q, k, v):
        return flash_attention(
            q, k, v, block_q=32, block_k=32, causal=True, interpret=True
        ).sum()

    g_kernel = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    monkeypatch.setenv("DCT_FLASH_BWD", "remat")
    g_remat = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    for gk, gr in zip(g_kernel, g_remat):
        np.testing.assert_allclose(np.asarray(gk), np.asarray(gr), atol=1e-5)


def test_flash_bf16_io(qkv):
    q, k, v = (x.astype(jnp.bfloat16) for x in qkv)
    out = flash_attention(q, k, v, block_q=32, block_k=32, interpret=True)
    assert out.dtype == jnp.bfloat16
    ref = dense_attention(
        q.astype(jnp.float32), k.astype(jnp.float32), v.astype(jnp.float32)
    )
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref), atol=2e-2
    )


def test_flash_short_seq_default_blocks(rng):
    """T shorter than the default block size: forward clamps the blocks,
    and the backward must clamp identically instead of crashing."""
    q, k, v = (
        jnp.asarray(rng.standard_normal((1, 2, 64, 16)), jnp.float32)
        for _ in range(3)
    )
    out = flash_attention(q, k, v, causal=True, interpret=True)
    ref = dense_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)

    g = jax.grad(
        lambda q, k, v: flash_attention(q, k, v, causal=True, interpret=True).sum(),
        argnums=(0, 1, 2),
    )(q, k, v)
    g_ref = jax.grad(
        lambda q, k, v: dense_attention(q, k, v, causal=True).sum(),
        argnums=(0, 1, 2),
    )(q, k, v)
    for gf, gd in zip(g, g_ref):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gd), atol=1e-4)


def test_flash_rejects_bad_blocks(qkv):
    q, k, v = qkv
    with pytest.raises(ValueError):
        flash_attention(q, k, v, block_q=96, block_k=32, interpret=True)


def test_flash_under_jit(qkv):
    q, k, v = qkv
    out = jax.jit(
        lambda q, k, v: flash_attention(
            q, k, v, block_q=32, block_k=32, interpret=True
        )
    )(q, k, v)
    ref = dense_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


# --- sliding window in the kernel ----------------------------------------


@pytest.mark.parametrize("window", [1, 17, 32, 100, 128])
def test_flash_window_matches_dense(qkv, window):
    """The in-kernel band mask (incl. the tile-skip conditions: blocks
    entirely behind the band execute nothing) against the masked dense
    oracle, at windows inside one tile, spanning tiles, and >= T."""
    q, k, v = qkv
    ref = dense_attention(q, k, v, causal=True, window=window)
    out = flash_attention(
        q, k, v, block_q=32, block_k=32, causal=True, interpret=True,
        window=window,
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


@pytest.mark.parametrize("bwd_mode", ["kernel", "remat"])
@pytest.mark.parametrize("window", [17, 64])
def test_flash_window_grad_matches_dense(qkv, window, bwd_mode, monkeypatch):
    """Windowed backward: both the FA2 backward kernels (band mask +
    tile skip) and the blockwise remat escape against dense AD."""
    monkeypatch.setenv("DCT_FLASH_BWD", bwd_mode)
    q, k, v = qkv

    def loss_flash(q, k, v):
        return flash_attention(
            q, k, v, block_q=32, block_k=32, causal=True, interpret=True,
            window=window,
        ).sum()

    def loss_dense(q, k, v):
        return dense_attention(q, k, v, causal=True, window=window).sum()

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for gf, gd in zip(g_flash, g_dense):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gd), atol=1e-4)


def test_flash_window_requires_causal(qkv):
    q, k, v = qkv
    with pytest.raises(ValueError, match="causal"):
        flash_attention(
            q, k, v, block_q=32, block_k=32, causal=False, interpret=True,
            window=8,
        )


@pytest.mark.parametrize("offset_blocks", [1, 3])
def test_flash_lse_q_offset_matches_blockwise(qkv, offset_blocks):
    """The static q_offset (the windowed ring's inter-shard distance)
    against the JAX-level blockwise twin with the same offset — forward
    o AND lse, since the ring's merge weights come from the lse."""
    from dct_tpu.ops.attention import blockwise_attention_lse
    from dct_tpu.ops.pallas_attention import flash_attention_lse

    q, k, v = qkv
    window = 100
    q_offset = offset_blocks * T  # whole-shard distances like the ring's
    o_k, lse_k = flash_attention_lse(
        q, k, v, 32, 32, True, None, True, window, q_offset
    )
    o_b, lse_b = blockwise_attention_lse(
        q, k, v, block_size=32, causal=True, window=window,
        q_offset=q_offset,
    )
    # Rows fully out of band produce o=0 and lse ~ -inf in both paths;
    # compare only the finite-lse rows for lse equality.
    finite = np.asarray(lse_b) > -1e29
    np.testing.assert_allclose(
        np.asarray(o_k), np.asarray(o_b), atol=1e-5
    )
    if finite.any():
        np.testing.assert_allclose(
            np.asarray(lse_k)[finite], np.asarray(lse_b)[finite], atol=1e-5
        )
