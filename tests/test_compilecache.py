"""Compile cache + AOT executables (ISSUE 9, docs/OBSERVABILITY.md
§compile cache): artifact round trips, loud-miss degradation, cache
labels on the compile accounting, bit-identity of cache-hit runs, and
the supervised-relaunch e2e.

The correctness contract under test:

- a HIT deserializes the exact executable the miss path built — same
  machine code, bit-identical losses and checkpoint bytes;
- a corrupted / fingerprint-skewed / foreign artifact is a LOUD miss
  (``compile.cache_miss`` with the reason) that falls back to a normal
  jit compile — never a crash, never a wrong result;
- ``compile.window`` events carry ``cache=hit|miss|disabled`` so a
  warm relaunch can PROVE it paid zero fresh XLA compiles.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from dct_tpu.compilecache import cache as cc_cache  # noqa: E402
from dct_tpu.compilecache.aot import (  # noqa: E402
    ExecutableStore,
    signature_of,
    store_from_env,
)


def _collect(events: list):
    def emit(component, event, **fields):
        events.append({"component": component, "event": event, **fields})

    return emit


def _mk_store(root, events=None, **identity):
    identity.setdefault("family", "weather_mlp")
    identity.setdefault("config_hash", "abcd1234")
    identity.setdefault("mesh", "data1_model1_seq1_pipe1")
    return ExecutableStore(
        str(root), identity=identity, enabled=True,
        emit=_collect(events) if events is not None else None,
    )


def _jit_fn():
    def f(x, y):
        return jnp.tanh(x @ y).sum(axis=-1)

    return jax.jit(f)


ARGS = (
    jnp.asarray(np.random.default_rng(0).normal(size=(8, 16)), jnp.float32),
    jnp.asarray(np.random.default_rng(1).normal(size=(16, 4)), jnp.float32),
)


# ======================================================================
# store unit semantics


def test_miss_publishes_artifact_then_fresh_store_hits(tmp_path):
    events: list = []
    store = _mk_store(tmp_path, events)
    prog = store.wrap(_jit_fn(), program="p")
    out_miss = np.asarray(prog(*ARGS))
    assert store.states == {"p": "miss"}
    files = [f for f in os.listdir(tmp_path) if f.endswith(".aotx")]
    assert len(files) == 1
    assert not any(".tmp." in f for f in os.listdir(tmp_path))

    # A fresh store + wrapper (a "new process"): loads, same bits.
    events2: list = []
    store2 = _mk_store(tmp_path, events2)
    prog2 = store2.wrap(_jit_fn(), program="p")
    out_hit = np.asarray(prog2(*ARGS))
    assert store2.states == {"p": "hit"}
    # The hit reads the roofline provenance off the artifact header
    # (ISSUE 14) before announcing the hit.
    assert [e["event"] for e in events2] == [
        "roofline.program", "compile.cache_hit",
    ]
    assert store2.costs["p"]["source"] == "header"
    np.testing.assert_array_equal(out_miss, out_hit)
    # Steady state: the in-memory entry dispatches without re-loading.
    np.testing.assert_array_equal(np.asarray(prog2(*ARGS)), out_hit)


def test_corrupt_artifact_is_loud_miss_with_identical_results(tmp_path):
    store = _mk_store(tmp_path)
    ref = np.asarray(store.wrap(_jit_fn(), program="p")(*ARGS))
    (art,) = [f for f in os.listdir(tmp_path) if f.endswith(".aotx")]
    path = os.path.join(tmp_path, art)
    blob = bytearray(open(path, "rb").read())
    blob[-20] ^= 0xFF  # flip a payload byte
    open(path, "wb").write(bytes(blob))

    events: list = []
    store2 = _mk_store(tmp_path, events)
    out = np.asarray(store2.wrap(_jit_fn(), program="p")(*ARGS))
    np.testing.assert_array_equal(ref, out)
    assert store2.states == {"p": "miss"}
    misses = [e for e in events if e["event"] == "compile.cache_miss"]
    assert misses and "sha256" in misses[0]["reason"]


def test_fingerprint_skew_is_loud_miss(tmp_path):
    store = _mk_store(tmp_path)
    store.wrap(_jit_fn(), program="p")(*ARGS)
    (art,) = [f for f in os.listdir(tmp_path) if f.endswith(".aotx")]
    path = os.path.join(tmp_path, art)
    raw = open(path, "rb").read()
    magic, rest = raw[:8], raw[8:]
    nl = rest.find(b"\n")
    header = json.loads(rest[:nl])
    header["jaxlib"] = "0.0.0"  # a foreign build's artifact
    open(path, "wb").write(
        magic + json.dumps(header, sort_keys=True).encode()
        + b"\n" + rest[nl + 1:]
    )

    events: list = []
    store2 = _mk_store(tmp_path, events)
    out = np.asarray(store2.wrap(_jit_fn(), program="p")(*ARGS))
    assert store2.states == {"p": "miss"}
    misses = [e for e in events if e["event"] == "compile.cache_miss"]
    assert misses and misses[0]["reason"] == "fingerprint skew"
    assert "jaxlib" in misses[0]["skew"]
    assert np.isfinite(out).all()


def test_identity_mismatch_never_loads_foreign_program(tmp_path):
    """Same shapes, different baked constants (config_hash): the
    artifact filename/header keying must keep them apart."""
    a = _mk_store(tmp_path, config_hash="aaaa0000")
    a.wrap(_jit_fn(), program="p")(*ARGS)
    b = _mk_store(tmp_path, config_hash="bbbb1111")
    b.wrap(_jit_fn(), program="p")(*ARGS)
    assert a.states == {"p": "miss"}
    assert b.states == {"p": "miss"}  # own compile, not a's artifact
    assert len([f for f in os.listdir(tmp_path) if f.endswith(".aotx")]) == 2


def test_disabled_store_is_transparent(tmp_path):
    store = ExecutableStore(str(tmp_path), enabled=False)
    prog = store.wrap(_jit_fn(), program="p")
    out = np.asarray(prog(*ARGS))
    assert np.isfinite(out).all()
    assert store.states == {"p": "disabled"}
    assert not os.listdir(tmp_path)


def test_signature_separates_shapes_and_weak_types(tmp_path):
    store = _mk_store(tmp_path)
    prog = store.wrap(_jit_fn(), program="p")
    prog(*ARGS)
    x2 = jnp.asarray(np.zeros((4, 16)), jnp.float32)
    prog(x2, ARGS[1])
    assert len([f for f in os.listdir(tmp_path) if f.endswith(".aotx")]) == 2
    assert signature_of(ARGS) != signature_of((x2, ARGS[1]))


def test_non_jit_callable_degrades_to_plain_call(tmp_path):
    store = _mk_store(tmp_path)
    prog = store.wrap(lambda x, y: np.asarray(x) @ np.asarray(y))
    out = prog(*ARGS)
    assert out.shape == (8, 4)
    assert not [f for f in os.listdir(tmp_path) if f.endswith(".aotx")]


# ======================================================================
# env contract


def test_cache_mode_resolution(monkeypatch):
    monkeypatch.delenv("DCT_COMPILE_CACHE", raising=False)
    monkeypatch.delenv("DCT_COMPILE_CACHE_DIR", raising=False)
    assert cc_cache.cache_mode() == "auto"
    assert cc_cache.resolve_cache_dir() is None
    assert not cc_cache.enabled() and not cc_cache.aot_enabled()
    monkeypatch.setenv("DCT_COMPILE_CACHE_DIR", "/tmp/cc")
    assert cc_cache.resolve_cache_dir() == "/tmp/cc"
    assert cc_cache.enabled() and cc_cache.aot_enabled()
    monkeypatch.setenv("DCT_COMPILE_CACHE", "off")
    assert not cc_cache.enabled()
    monkeypatch.setenv("DCT_COMPILE_CACHE", "on")
    monkeypatch.delenv("DCT_COMPILE_CACHE_DIR", raising=False)
    assert cc_cache.resolve_cache_dir() == cc_cache.DEFAULT_CACHE_DIR
    monkeypatch.setenv("DCT_COMPILE_CACHE_AOT", "0")
    assert cc_cache.enabled() and not cc_cache.aot_enabled()


def test_store_from_env_gating(tmp_path, monkeypatch):
    monkeypatch.setenv("DCT_COMPILE_CACHE", "off")
    assert not store_from_env(str(tmp_path)).enabled
    monkeypatch.setenv("DCT_COMPILE_CACHE", "on")
    assert store_from_env(str(tmp_path)).enabled
    assert not store_from_env(None).enabled
    monkeypatch.setenv("DCT_COMPILE_CACHE_AOT", "0")
    assert not store_from_env(str(tmp_path)).enabled


def test_export_env_pins_resolved_dir(monkeypatch):
    monkeypatch.delenv("DCT_COMPILE_CACHE", raising=False)
    monkeypatch.delenv("DCT_COMPILE_CACHE_DIR", raising=False)
    child: dict = {}
    cc_cache.export_env(child)
    assert "DCT_COMPILE_CACHE_DIR" not in child  # cache off -> no-op
    child = {"DCT_COMPILE_CACHE": "on"}
    cc_cache.export_env(child)
    assert child["DCT_COMPILE_CACHE_DIR"] == os.path.abspath(
        cc_cache.DEFAULT_CACHE_DIR
    )
    # An explicit parent-env dir is pinned verbatim (absolute), so
    # every relaunch attempt resolves the SAME directory even if the
    # supervisor and ranks run from different cwds.
    monkeypatch.setenv("DCT_COMPILE_CACHE_DIR", "/tmp/mine")
    child = {"DCT_COMPILE_CACHE": "on"}
    cc_cache.export_env(child)
    assert child["DCT_COMPILE_CACHE_DIR"] == os.path.abspath("/tmp/mine")
    monkeypatch.setenv("DCT_COMPILE_CACHE", "off")
    child = {"DCT_COMPILE_CACHE": "off"}
    cc_cache.export_env(child)
    assert "DCT_COMPILE_CACHE_DIR" not in child


def test_warm_sizes_parse(monkeypatch):
    monkeypatch.setenv("DCT_COMPILE_CACHE_WARM_SIZES", "64, 1,8,bogus,8")
    assert cc_cache.warm_sizes() == [1, 8, 64]
    monkeypatch.setenv("DCT_COMPILE_CACHE_WARM_SIZES", "")
    assert cc_cache.warm_sizes() == []


# ======================================================================
# compile accounting labels


def test_compile_report_carries_cache_states():
    from dct_tpu.observability.goodput import compile_report

    report = compile_report(
        [("scan_k1", 3.0), ("scan_k4", 1.0), ("eager_step", 0.2)],
        family="weather_mlp", config_hash="ff00", mesh="data1",
        cache_states={"scan_k1": "hit", "scan_k4": "miss"},
    )
    by_prog = {r["program"]: r["cache"] for r in report}
    assert by_prog == {
        "scan_k1": "hit", "scan_k4": "miss", "eager_step": "disabled",
    }


def test_dump_labels_compile_series_with_cache(tmp_path):
    from dct_tpu.observability.dump import write_train_metrics_prom
    from dct_tpu.observability.goodput import GoodputLedger

    led = GoodputLedger()
    led.start()
    path = str(tmp_path / "m.prom")
    write_train_metrics_prom(
        path, led.summary(), run_id="r",
        compile_windows=[{
            "program": "scan_k1", "family": "f", "config_hash": "c",
            "mesh": "m", "cache": "hit", "count": 1, "seconds": 0.01,
        }],
    )
    body = open(path).read()
    assert 'cache="hit"' in body
    assert "dct_compile_windows_total" in body


def test_inspect_compile_section_counts_cache_states():
    from dct_tpu.observability.inspect import build_report

    events = [
        {"ts": 1.0, "run_id": "r", "component": "compile",
         "event": "compile.window", "program": "scan_k1", "family": "f",
         "config_hash": "c", "mesh": "m", "cache": "hit", "count": 2,
         "seconds": 0.04},
        {"ts": 1.1, "run_id": "r", "component": "compile",
         "event": "compile.window", "program": "serve_scorer",
         "family": "f", "config_hash": "c", "mesh": "m", "cache": "miss",
         "count": 1, "seconds": 0.8},
    ]
    report = build_report(events, [], [], "r", None)
    assert "cache=hit" in report and "cache=miss" in report
    assert "hit 2 / miss 1" in report


def test_sentinel_flags_warm_spinup_regressions(tmp_path):
    from dct_tpu.observability import report as rpt

    def rec(path, step_s, score_s):
        with open(path, "w") as f:
            json.dump({"parsed": {
                "metric": "m", "value": 100.0,
                "restart_spinup": {
                    "warm_step_s": step_s, "warm_score_s": score_s,
                },
            }}, f)

    rec(tmp_path / "BENCH_r01.json", 4.0, 0.8)
    rec(tmp_path / "BENCH_r02.json", 6.0, 0.9)  # step +50%, score +12.5%
    rounds = [
        rpt.load_round(str(tmp_path / f"BENCH_r0{i}.json")) for i in (1, 2)
    ]
    findings = rpt.compare_rounds(rounds)
    flagged = {f["series"] for f in findings if f["kind"] == "regression"}
    assert "warm_step_s" in flagged       # > 25% cold-start rise flags
    assert "warm_score_s" not in flagged  # 12.5% stays under threshold


# ======================================================================
# trainer integration: bit-identity + labels (the acceptance core)


def _processed_dir(tmp_path) -> str:
    from dct_tpu.data.synthetic import generate_weather_csv
    from dct_tpu.etl.preprocess import preprocess_csv_to_parquet

    csv = str(tmp_path / "raw.csv")
    processed = str(tmp_path / "processed")
    generate_weather_csv(csv, rows=300, seed=0)
    preprocess_csv_to_parquet(csv, processed)
    return processed


def _fit_once(tmp_path, tag, monkeypatch, processed):
    from dct_tpu.config import RunConfig
    from dct_tpu.train.trainer import Trainer

    monkeypatch.setenv("DCT_PROCESSED_DIR", processed)
    monkeypatch.setenv("DCT_MODELS_DIR", str(tmp_path / f"models_{tag}"))
    monkeypatch.setenv("DCT_EVENTS_DIR", str(tmp_path / f"events_{tag}"))
    monkeypatch.setenv("DCT_TRACKING_DIR", str(tmp_path / f"mlruns_{tag}"))
    monkeypatch.setenv("DCT_HEARTBEAT_DIR", str(tmp_path / f"hb_{tag}"))
    monkeypatch.setenv("DCT_EPOCHS", "2")
    monkeypatch.setenv("DCT_BATCH_SIZE", "16")
    monkeypatch.delenv("DCT_RUN_ID", raising=False)
    result = Trainer(RunConfig.from_env()).fit()
    events = [
        json.loads(line)
        for line in open(tmp_path / f"events_{tag}" / "events.jsonl")
    ]
    windows = [e for e in events if e.get("event") == "compile.window"]
    return result, windows


def test_trainer_warm_rerun_is_bitwise_identical_and_labelled(
    tmp_path, monkeypatch
):
    """Two identical runs sharing one AOT dir: run A misses (and
    publishes), run B hits — with the SAME loss trajectory bit for bit
    and byte-identical deploy checkpoints."""
    processed = _processed_dir(tmp_path)
    monkeypatch.setenv("DCT_COMPILE_CACHE", "on")
    monkeypatch.setenv("DCT_COMPILE_CACHE_DIR", str(tmp_path / "xla"))
    monkeypatch.setenv("DCT_COMPILE_CACHE_AOT_DIR", str(tmp_path / "aot"))
    res_a, win_a = _fit_once(tmp_path, "a", monkeypatch, processed)
    res_b, win_b = _fit_once(tmp_path, "b", monkeypatch, processed)
    assert [w["cache"] for w in win_a] == ["miss"]
    assert [w["cache"] for w in win_b] == ["hit"]
    assert res_a.history == res_b.history  # floats compare exactly
    bytes_a = open(res_a.best_model_path, "rb").read()
    bytes_b = open(res_b.best_model_path, "rb").read()
    assert bytes_a == bytes_b


def test_trainer_corrupt_artifact_degrades_to_identical_compile(
    tmp_path, monkeypatch
):
    """A torn/garbage artifact between runs: run B takes the loud-miss
    path and still reproduces run A bit for bit."""
    processed = _processed_dir(tmp_path)
    monkeypatch.setenv("DCT_COMPILE_CACHE", "on")
    monkeypatch.setenv("DCT_COMPILE_CACHE_DIR", str(tmp_path / "xla"))
    monkeypatch.setenv("DCT_COMPILE_CACHE_AOT_DIR", str(tmp_path / "aot"))
    res_a, _ = _fit_once(tmp_path, "a", monkeypatch, processed)
    for name in os.listdir(tmp_path / "aot"):
        with open(tmp_path / "aot" / name, "r+b") as f:
            f.seek(0)
            f.write(b"garbage!")
    res_b, win_b = _fit_once(tmp_path, "b", monkeypatch, processed)
    assert [w["cache"] for w in win_b] == ["miss"]
    assert res_a.history == res_b.history
    assert (
        open(res_a.best_model_path, "rb").read()
        == open(res_b.best_model_path, "rb").read()
    )


def test_trainer_cache_off_matches_cache_on_bitwise(tmp_path, monkeypatch):
    """The cache must be invisible to the math: a cache-hit run equals
    a no-cache-at-all run bit for bit."""
    processed = _processed_dir(tmp_path)
    monkeypatch.setenv("DCT_COMPILE_CACHE", "off")
    res_off, win_off = _fit_once(tmp_path, "off", monkeypatch, processed)
    monkeypatch.setenv("DCT_COMPILE_CACHE", "on")
    monkeypatch.setenv("DCT_COMPILE_CACHE_DIR", str(tmp_path / "xla"))
    monkeypatch.setenv("DCT_COMPILE_CACHE_AOT_DIR", str(tmp_path / "aot"))
    _fit_once(tmp_path, "warmup", monkeypatch, processed)
    res_hit, win_hit = _fit_once(tmp_path, "hit", monkeypatch, processed)
    assert [w["cache"] for w in win_off] == ["disabled"]
    assert [w["cache"] for w in win_hit] == ["hit"]
    assert res_off.history == res_hit.history
    assert (
        open(res_off.best_model_path, "rb").read()
        == open(res_hit.best_model_path, "rb").read()
    )


# ======================================================================
# serving: package-carried scorer


def test_warm_package_scorer_publishes_and_serves_hits(
    tmp_path, monkeypatch
):
    from dct_tpu.compilecache.aot import _example_batch, warm_package_scorer
    from dct_tpu.serving.batching import _build_jax_scorer
    from dct_tpu.serving.score_gen import generate_score_package

    processed = _processed_dir(tmp_path)
    monkeypatch.setenv("DCT_COMPILE_CACHE", "off")
    res, _ = _fit_once(tmp_path, "pkg", monkeypatch, processed)
    pkg = str(tmp_path / "package")
    generate_score_package(res.best_model_path, pkg)
    assert not os.path.isdir(os.path.join(pkg, "aot"))  # cache off

    done = warm_package_scorer(pkg, sizes=[1, 3])  # 3 pads to 4
    assert done == [1, 4]
    arts = os.listdir(os.path.join(pkg, "aot"))
    assert len(arts) == 2 and all(a.endswith(".aotx") for a in arts)

    # A "fresh worker" with the cache armed loads the packaged
    # executables and answers exactly like the jit path.
    npz = np.load(os.path.join(pkg, "model.npz"))
    weights = {k: npz[k] for k in npz.files}
    meta = json.load(open(os.path.join(pkg, "model_meta.json")))
    x = np.asarray(
        np.random.default_rng(3).normal(size=(3, int(meta["input_dim"]))),
        np.float32,
    )
    monkeypatch.setenv("DCT_COMPILE_CACHE", "on")
    warm_meta = dict(meta, _aot_dir=os.path.join(pkg, "aot"))
    probs_warm = _build_jax_scorer(weights, warm_meta)(x)
    monkeypatch.setenv("DCT_COMPILE_CACHE", "off")
    probs_cold = _build_jax_scorer(weights, dict(meta))(x)
    np.testing.assert_array_equal(probs_warm, probs_cold)


def test_scorer_identity_includes_weights_digest(tmp_path, monkeypatch):
    """The jitted scorer bakes the weights in as constants, so two
    packages with IDENTICAL meta but different weights must never
    share an artifact — the second build misses and serves its own
    model's probabilities."""
    from dct_tpu.serving.batching import _build_jax_scorer

    meta = {
        "model": "weather_mlp", "input_dim": 4, "hidden_dim": 8,
        "num_classes": 2, "dropout": 0.0,
        "_aot_dir": str(tmp_path / "aot"),
    }
    rng = np.random.default_rng(0)

    def mk_weights(seed):
        r = np.random.default_rng(seed)
        return {
            "w0": r.normal(size=(4, 8)).astype(np.float32),
            "b0": np.zeros(8, np.float32),
            "w1": r.normal(size=(8, 2)).astype(np.float32),
            "b1": np.zeros(2, np.float32),
        }

    x = rng.normal(size=(2, 4)).astype(np.float32)
    monkeypatch.setenv("DCT_COMPILE_CACHE", "on")
    monkeypatch.setenv("DCT_COMPILE_CACHE_DIR", str(tmp_path / "xla"))
    w_a, w_b = mk_weights(1), mk_weights(2)
    probs_a = _build_jax_scorer(w_a, dict(meta))(x)
    probs_b = _build_jax_scorer(w_b, dict(meta))(x)
    # Different weights -> different artifacts on disk, and the second
    # scorer's output matches ITS weights' jit reference, not model A.
    arts = os.listdir(tmp_path / "aot")
    assert len(arts) == 2
    monkeypatch.setenv("DCT_COMPILE_CACHE", "off")
    ref_b = _build_jax_scorer(w_b, {
        k: v for k, v in meta.items() if k != "_aot_dir"
    })(x)
    np.testing.assert_array_equal(probs_b, ref_b)
    assert not np.array_equal(probs_a, probs_b)


# ======================================================================
# e2e: supervised SIGKILL-relaunch, warm vs cold (the acceptance)


def test_e2e_supervised_relaunch_warm_vs_cold(tmp_path):
    """Through the REAL supervisor relaunch path: with a pre-warmed
    cache the healed attempt executes zero fresh XLA compiles (every
    compile.window is cache=hit, compile seconds a fraction of the cold
    control's) and the run books a smaller startup_recovery debt than
    the cold control (the crashing attempt itself started warm, so the
    supervisor hands less lost wall clock to the relaunch)."""
    from dct_tpu.compilecache import spinup

    spinup.prepare_processed(str(tmp_path), rows=400)
    model_env = {
        "DCT_MODEL": "weather_transformer",
        "DCT_N_LAYERS": "2", "DCT_D_MODEL": "64", "DCT_N_HEADS": "4",
        "DCT_D_FF": "256", "DCT_SEQ_LEN": "16",
        "DCT_PREFETCH_SPANS": "0",
    }
    cold = spinup.measure_relaunch(
        str(tmp_path), cache_on=False, model_env=model_env
    )
    warm = spinup.measure_relaunch(
        str(tmp_path), cache_on=True, prewarm=True, model_env=model_env
    )
    assert cold["returncode"] == 0, cold["stderr_tail"]
    assert warm["returncode"] == 0, warm["stderr_tail"]
    # Cold control: real compiles, no cache in the loop.
    assert cold["relaunch_cache"] == ["disabled"]
    assert cold["relaunch_compile_s"] > 0.5
    # Warm: zero fresh XLA compiles on the healed attempt — proven by
    # the cache labels — and near-zero compile-window seconds (what
    # remains is the trace + deserialize + first dispatch).
    assert warm["relaunch_cache"] == ["hit"]
    assert warm["relaunch_compile_s"] < 0.5 * cold["relaunch_compile_s"]
    # The healed run reaches its first step sooner...
    assert (
        warm["sigkill_to_first_step_s"] < cold["sigkill_to_first_step_s"]
    )
    # ...and books a smaller startup_recovery debt than the cold
    # control (the crashed attempt's wall, which the supervisor hands
    # to the relaunch as debt, no longer contains an XLA compile).
    assert warm["startup_recovery_s"] < cold["startup_recovery_s"]
