"""End-to-end trainer tests: the minimum slice of SURVEY §7 — parquet in,
checkpoint + tracked metrics out, on the 8-device virtual mesh."""

import os

import pytest

from dct_tpu.config import RunConfig, TrainConfig, DataConfig, ModelConfig, MeshConfig
from dct_tpu.tracking.client import LocalTracking
from dct_tpu.train.trainer import Trainer


@pytest.fixture(scope="module")
def trained(tmp_path_factory, request):
    processed_dir = request.getfixturevalue("processed_dir")
    work = tmp_path_factory.mktemp("train_e2e")
    cfg = RunConfig(
        data=DataConfig(
            processed_dir=processed_dir, models_dir=str(work / "models")
        ),
        train=TrainConfig(epochs=3, batch_size=4, bf16_compute=False),
    )
    tracker = LocalTracking(root=str(work / "mlruns"), experiment="weather_forecasting")
    result = Trainer(cfg, tracker=tracker).fit()
    return cfg, tracker, result


def test_learns_signal(trained):
    _, _, result = trained
    assert result.val_acc > 0.80, f"val_acc {result.val_acc} — model failed to learn"
    assert result.val_loss < 0.5
    # Loss should improve over training.
    assert result.history[-1]["val_loss"] <= result.history[0]["val_loss"]


def test_checkpoints_written(trained):
    _, _, result = trained
    assert os.path.exists(result.best_model_path)
    assert os.path.exists(result.last_model_path)
    assert os.path.basename(result.best_model_path).startswith("weather-best-")

    from dct_tpu.checkpoint.manager import load_checkpoint

    params, meta = load_checkpoint(result.best_model_path)
    assert meta["input_dim"] == 5
    assert meta["model"] == "weather_mlp"
    assert len(meta["feature_names"]) == 5


def test_metrics_tracked_and_queryable(trained):
    _, tracker, result = trained
    best = tracker.search_best_run("val_loss", "min")
    assert best is not None
    assert best.run_id == result.run_id
    assert "val_acc" in best.metrics
    assert "train_loss" in best.metrics  # logged every log_every_n_steps


def test_best_ckpt_uploaded_as_artifact(trained, tmp_path):
    _, tracker, result = trained
    out = tracker.download_artifacts(
        result.run_id, "best_checkpoints", str(tmp_path / "dl")
    )
    files = os.listdir(out)
    assert len(files) == 1 and files[0].endswith(".ckpt")


def test_throughput_recorded(trained):
    _, _, result = trained
    assert result.samples_per_sec > 0


def test_resume_continues_from_state(trained, request):
    """Continuous-training re-run: the first (completed) run trained
    epochs [0, 3); a resumed run with a 4-epoch budget EXTENDS the same
    trajectory through epochs [3, 7)."""
    cfg, _, first = trained
    processed_dir = request.getfixturevalue("processed_dir")
    cfg2 = RunConfig(
        data=DataConfig(processed_dir=processed_dir, models_dir=cfg.data.models_dir),
        train=TrainConfig(epochs=4, batch_size=4, bf16_compute=False, resume=True),
    )
    tracker = LocalTracking(root=str(os.path.join(cfg.data.models_dir, "..", "mlruns2")))
    result = Trainer(cfg2, tracker=tracker).fit()
    assert [h["epoch"] for h in result.history] == [3, 4, 5, 6]


@pytest.mark.slow
def test_transformer_family_e2e(tmp_path_factory, request):
    """The transformer family through the SAME Trainer: windowed data path,
    ring attention + TP sharding over the multi-axis mesh, same tracking/
    checkpoint contract."""
    processed_dir = request.getfixturevalue("processed_dir")
    work = tmp_path_factory.mktemp("train_tf_e2e")
    cfg = RunConfig(
        data=DataConfig(
            processed_dir=processed_dir, models_dir=str(work / "models")
        ),
        model=ModelConfig(
            name="weather_transformer", seq_len=16, d_model=32, n_heads=4,
            n_layers=2, d_ff=64, dropout=0.1,
        ),
        train=TrainConfig(
            epochs=2, batch_size=8, lr=1e-3, bf16_compute=False
        ),
        mesh=MeshConfig(data=2, model=2, seq=2),
    )
    tracker = LocalTracking(root=str(work / "mlruns"), experiment="weather_forecasting")
    result = Trainer(cfg, tracker=tracker).fit()
    import math

    assert math.isfinite(result.val_loss)
    assert os.path.exists(result.last_model_path)
    # The windowed task is harder than row-wise; just demand learning signal
    # beyond coin-flip on the balanced synthetic stream.
    assert result.val_acc > 0.55, f"val_acc {result.val_acc}"
