"""Batch-inference job: checkpoint + processed parquet -> predictions
parquet through the same numpy runtime the deployed score.py embeds."""

import os
import subprocess
import sys

import numpy as np
import pandas as pd
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _train(processed_dir, tmp_path, model_env=None):
    env = {
        **os.environ,
        "PALLAS_AXON_POOL_IPS": "",
        "JAX_PLATFORMS": "cpu",
        "DCT_PROCESSED_DIR": processed_dir,
        "DCT_MODELS_DIR": str(tmp_path / "models"),
        "DCT_TRACKING_DIR": str(tmp_path / "runs"),
        "DCT_EPOCHS": "1",
        "DCT_BATCH_SIZE": "8",
        "DCT_BF16_COMPUTE": "0",
        **(model_env or {}),
    }
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "jobs", "train_tpu.py")],
        env=env, capture_output=True, text=True,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    return env


@pytest.mark.slow
@pytest.mark.parametrize(
    "model_env",
    [
        None,  # flagship MLP
        {"DCT_MODEL": "weather_transformer_causal", "DCT_SEQ_LEN": "8",
         "DCT_D_MODEL": "16", "DCT_N_HEADS": "2", "DCT_N_LAYERS": "1",
         "DCT_D_FF": "32"},
    ],
)
def test_predict_job_end_to_end(processed_dir, tmp_path, model_env):
    env = _train(processed_dir, tmp_path, model_env)
    out = str(tmp_path / "pred" / "predictions.parquet")
    env["DCT_PREDICTIONS"] = out
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "jobs", "predict.py")],
        env=env, capture_output=True, text=True,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    df = pd.read_parquet(out)
    assert {"row", "predicted", "prob_0", "prob_1", "label"} <= set(df.columns)
    assert len(df) > 0
    np.testing.assert_allclose(
        df["prob_0"] + df["prob_1"], np.ones(len(df)), atol=1e-5
    )
    # A trained model must beat coin-flip on its own training stream.
    acc = float((df["predicted"] == df["label"]).mean())
    assert acc > 0.6, acc


@pytest.mark.slow
def test_predict_job_multi_horizon(processed_dir, tmp_path):
    """A horizon=3 causal checkpoint yields per-horizon prediction and
    probability columns; next-step `predicted` keeps the base contract."""
    env = _train(
        processed_dir, tmp_path,
        {"DCT_MODEL": "weather_transformer_causal", "DCT_SEQ_LEN": "8",
         "DCT_D_MODEL": "16", "DCT_N_HEADS": "2", "DCT_N_LAYERS": "1",
         "DCT_D_FF": "32", "DCT_HORIZON": "3"},
    )
    out = str(tmp_path / "pred" / "predictions.parquet")
    env["DCT_PREDICTIONS"] = out
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "jobs", "predict.py")],
        env=env, capture_output=True, text=True,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    df = pd.read_parquet(out)
    expect = {
        "row", "predicted", "label",
        "prob_h1_0", "prob_h1_1", "pred_h2", "prob_h2_0", "prob_h2_1",
        "pred_h3", "prob_h3_0", "prob_h3_1",
    }
    assert expect <= set(df.columns), sorted(df.columns)
    for h in (1, 2, 3):
        np.testing.assert_allclose(
            df[f"prob_h{h}_0"] + df[f"prob_h{h}_1"], np.ones(len(df)),
            atol=1e-5,
        )


def test_predict_job_missing_checkpoint(tmp_path, processed_dir):
    env = {
        **os.environ,
        "PALLAS_AXON_POOL_IPS": "",
        "JAX_PLATFORMS": "cpu",
        "DCT_PROCESSED_DIR": processed_dir,
        "DCT_MODELS_DIR": str(tmp_path / "empty"),
    }
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "jobs", "predict.py")],
        env=env, capture_output=True, text=True,
    )
    assert r.returncode != 0
    assert "No checkpoint" in r.stderr


def test_predict_chunking_matches_single_pass(processed_dir, tmp_path):
    """Chunked scoring (review fix) must equal one whole-dataset pass."""
    env = _train(processed_dir, tmp_path)
    for chunk, sub in (("64", "a"), ("100000", "b")):
        e = dict(env)
        e["DCT_PREDICT_CHUNK"] = chunk
        e["DCT_PREDICTIONS"] = str(tmp_path / sub / "p.parquet")
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, "jobs", "predict.py")],
            env=e, capture_output=True, text=True,
        )
        assert r.returncode == 0, r.stderr[-2000:]
    a = pd.read_parquet(str(tmp_path / "a" / "p.parquet"))
    b = pd.read_parquet(str(tmp_path / "b" / "p.parquet"))
    np.testing.assert_allclose(a["prob_1"], b["prob_1"], atol=1e-6)


def test_predict_picks_newest_best_by_mtime(processed_dir, tmp_path):
    """Review regression: an older-but-lexicographically-later best file
    must not win over the newest best checkpoint."""
    import time

    env = _train(processed_dir, tmp_path)
    models = str(tmp_path / "models")
    import glob as _glob
    import shutil

    best = _glob.glob(os.path.join(models, "weather-best-*.ckpt"))[0]
    decoy = os.path.join(models, "weather-best-99-9.99.ckpt")
    shutil.copy2(best, decoy)
    os.utime(decoy, (time.time() - 3600, time.time() - 3600))  # older
    out = str(tmp_path / "pred2" / "p.parquet")
    env["DCT_PREDICTIONS"] = out
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "jobs", "predict.py")],
        env=env, capture_output=True, text=True,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    assert os.path.basename(best) in r.stdout, r.stdout


@pytest.mark.slow
@pytest.mark.parametrize(
    "model_env",
    [
        None,  # flagship MLP
        {"DCT_MODEL": "weather_transformer", "DCT_SEQ_LEN": "8",
         "DCT_D_MODEL": "16", "DCT_N_HEADS": "2", "DCT_D_FF": "32"},
        # Causal family: the jax engine must slice the last position to
        # match the numpy twin's forecast contract.
        {"DCT_MODEL": "weather_transformer_causal", "DCT_SEQ_LEN": "8",
         "DCT_D_MODEL": "16", "DCT_N_HEADS": "2", "DCT_N_LAYERS": "1",
         "DCT_D_FF": "32"},
        # Multi-horizon causal: probs come back [N, H, C] in BOTH
        # engines (per-horizon prob/pred columns).
        {"DCT_MODEL": "weather_transformer_causal", "DCT_SEQ_LEN": "8",
         "DCT_D_MODEL": "16", "DCT_N_HEADS": "2", "DCT_N_LAYERS": "1",
         "DCT_D_FF": "32", "DCT_HORIZON": "3"},
    ],
    ids=["mlp", "transformer", "causal", "causal_h3"],
)
def test_predict_jax_engine_matches_numpy(processed_dir, tmp_path, model_env):
    """DCT_PREDICT_ENGINE=jax (mesh-sharded accelerator scoring) must
    match the numpy serving twin to f32 tolerance — including across
    the fixed-chunk padding of the last piece."""
    env = _train(processed_dir, tmp_path, model_env)
    outs = {}
    for engine in ("numpy", "jax"):
        out = str(tmp_path / f"pred_{engine}.parquet")
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, "jobs", "predict.py")],
            env={**env, "DCT_PREDICTIONS": out,
                 "DCT_PREDICT_ENGINE": engine,
                 "DCT_PREDICT_CHUNK": "96"},  # forces a padded tail
            capture_output=True, text=True,
        )
        assert r.returncode == 0, r.stderr[-2000:]
        outs[engine] = pd.read_parquet(out)
    a, b = outs["numpy"], outs["jax"]
    assert (a["row"] == b["row"]).all()
    prob_cols = [c for c in a.columns if c.startswith("prob")]
    assert prob_cols
    for c in prob_cols:
        np.testing.assert_allclose(a[c], b[c], atol=2e-5)
    assert (a["predicted"] == b["predicted"]).mean() > 0.999


def test_predict_unknown_engine_fails_loudly(processed_dir, tmp_path):
    env = _train(processed_dir, tmp_path)
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "jobs", "predict.py")],
        env={**env, "DCT_PREDICT_ENGINE": "cuda"},
        capture_output=True, text=True,
    )
    assert r.returncode != 0
    assert "DCT_PREDICT_ENGINE" in r.stderr
