"""Dataset contract tests, mirroring the reference's strict checks
(jobs/train_lightning_ddp.py:22-26,37-46)."""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from dct_tpu.data.dataset import load_processed_dataset
from dct_tpu.data.pipeline import BatchLoader, train_val_split


def test_load(weather_data):
    assert weather_data.input_dim == 5
    assert weather_data.features.dtype == np.float32
    assert weather_data.labels.dtype == np.int32
    assert len(weather_data) == 800
    assert all(n.endswith("_norm") for n in weather_data.feature_names)


def test_missing_dir_raises(tmp_path):
    with pytest.raises(FileNotFoundError, match="CRITICAL"):
        load_processed_dataset(str(tmp_path))


def test_no_norm_columns_raises(tmp_path):
    pdir = tmp_path / "data.parquet"
    pdir.mkdir()
    pq.write_table(
        pa.table({"a": [1.0], "label_encoded": [0]}), pdir / "part-0.parquet"
    )
    with pytest.raises(ValueError, match="_norm"):
        load_processed_dataset(str(tmp_path))


def test_split_is_deterministic_and_80_20():
    t1, v1 = train_val_split(100, val_fraction=0.2, seed=42)
    t2, v2 = train_val_split(100, val_fraction=0.2, seed=42)
    np.testing.assert_array_equal(t1, t2)
    assert len(t1) == 80 and len(v1) == 20
    assert set(t1) | set(v1) == set(range(100))
    t3, _ = train_val_split(100, val_fraction=0.2, seed=43)
    assert not np.array_equal(t1, t3)


def test_batch_loader_shapes_and_masking(weather_data):
    idx = np.arange(10)
    loader = BatchLoader(weather_data, idx, global_batch=4, shuffle=False)
    batches = list(loader.epoch(0))
    assert len(batches) == 3  # ceil(10/4)
    for b in batches:
        assert b.x.shape == (4, 5)
        assert b.y.shape == (4,)
    # Final batch has 2 real rows.
    assert batches[-1].weight.sum() == 2.0
    total_real = sum(b.weight.sum() for b in batches)
    assert total_real == 10.0


def test_batch_loader_shuffles_per_epoch(weather_data):
    idx = np.arange(64)
    loader = BatchLoader(weather_data, idx, global_batch=64, shuffle=True, seed=1)
    e0 = next(loader.epoch(0)).x
    e0_again = next(loader.epoch(0)).x
    e1 = next(loader.epoch(1)).x
    np.testing.assert_array_equal(e0, e0_again)
    assert not np.array_equal(e0, e1)


def test_epoch_stacked_matches_iterator(weather_data):
    """The vectorized whole-epoch gather must produce exactly the batches
    the iterator yields."""
    idx = np.arange(19)
    for nproc, pid in [(1, 0), (2, 1)]:
        loader = BatchLoader(
            weather_data, idx, global_batch=8, shuffle=True, seed=3,
            num_processes=nproc, process_id=pid,
        )
        xs, ys, ws = loader.epoch_stacked(4)
        it = list(loader.epoch(4))
        assert xs.shape[0] == len(it)
        for i, b in enumerate(it):
            np.testing.assert_array_equal(xs[i], b.x)
            np.testing.assert_array_equal(ys[i], b.y)
            np.testing.assert_array_equal(ws[i], b.weight)


def test_process_sharding_partitions_batch(weather_data):
    idx = np.arange(16)
    full = BatchLoader(weather_data, idx, global_batch=8, shuffle=False)
    shard0 = BatchLoader(
        weather_data, idx, global_batch=8, shuffle=False, num_processes=2, process_id=0
    )
    shard1 = BatchLoader(
        weather_data, idx, global_batch=8, shuffle=False, num_processes=2, process_id=1
    )
    for bf, b0, b1 in zip(full.epoch(0), shard0.epoch(0), shard1.epoch(0)):
        assert b0.x.shape == (4, 5) and b1.x.shape == (4, 5)
        # Block sharding: concatenation reproduces the global batch order.
        np.testing.assert_array_equal(np.concatenate([b0.x, b1.x]), bf.x)
        np.testing.assert_array_equal(
            np.concatenate([b0.weight, b1.weight]), bf.weight
        )
