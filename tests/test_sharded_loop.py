"""Pod-scale sharded continuous training (ISSUE 11): the declarative
partition-rule surface and its end-to-end wiring through checkpoint,
eval, publish, and relaunch.

Bit-identity policy (measured on this rig, pinned here so the claims
stay honest):

- SAME layout through different machinery (loop vs serial, save ->
  topology-remap -> restore, gather -> publish) is BIT-identical —
  those paths move data, they do not compute.
- DIFFERENT layouts (DP-replicated vs ZeRO-1/TP) compile DIFFERENT XLA
  programs whose update math can differ by 1 ulp per step (measured:
  5.96e-8 on step 3 of a 5-step MLP run, zero on the other four), so
  cross-layout trajectories pin at <= 1e-6 — a genuinely wrong program
  (dropped term, wrong collective) moves losses by 1e-2+.
"""

import glob
import json
import os

import jax
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from dct_tpu.config import MeshConfig, ModelConfig
from dct_tpu.models.registry import get_model
from dct_tpu.parallel.mesh import make_mesh
from dct_tpu.parallel.sharding_rules import (
    gather_tree,
    layout_mismatches,
    make_shard_and_gather_fns,
    match_partition_rules,
    parse_rules,
    rules_digest,
    rules_for_family,
    shard_state_with_rules,
    state_shardings,
)
from dct_tpu.train.state import create_train_state

F = 5

TRANSFORMER = dict(
    name="weather_transformer", seq_len=8, d_model=16, n_heads=2,
    n_layers=1, d_ff=32,
)


def _transformer_state(mesh, **shard_kwargs):
    cfg = ModelConfig(**TRANSFORMER)
    model = get_model(cfg, input_dim=F)
    state = create_train_state(
        model, input_dim=F, lr=1e-3, seed=0,
        example_shape=(1, cfg.seq_len, F),
    )
    return shard_state_with_rules(
        state, mesh, family="weather_transformer", **shard_kwargs
    )


# ----------------------------------------------------------------------
# Rule table + grammar


def test_parse_rules_grammar():
    rules = parse_rules(".*dense.*/kernel$=-,model; head/bias$=data ;x$=")
    assert rules[0] == (".*dense.*/kernel$", P(None, "model"))
    assert rules[1] == ("head/bias$", P("data"))
    assert rules[2] == ("x$", P())


@pytest.mark.parametrize(
    "bad",
    ["no-equals-clause", "a=(model", "k$=model,upside"],
    ids=["no-eq", "bad-regex", "bad-axis"],
)
def test_parse_rules_rejects_malformed(bad):
    with pytest.raises(ValueError):
        parse_rules(bad)


def test_env_rules_override_family_defaults(monkeypatch):
    base = rules_for_family("weather_transformer")
    d0 = rules_digest("weather_transformer")
    monkeypatch.setenv("DCT_SHARD_RULES", "qkv_proj.*/kernel$=")
    assert rules_for_family("weather_transformer")[0] == (
        "qkv_proj.*/kernel$", P()
    )
    assert rules_for_family("weather_transformer")[1:] == base
    # The digest moves with the table: the AOT identity must recompile.
    assert rules_digest("weather_transformer") != d0
    # And the override actually changes the resolved placement.
    mesh = make_mesh(MeshConfig(data=4, model=2))
    state = _transformer_state(mesh)
    specs = {
        "/".join(str(getattr(k, "key", k)) for k in path): leaf.sharding.spec
        for path, leaf in
        jax.tree_util.tree_flatten_with_path(state.params)[0]
    }
    qkv = {k: v for k, v in specs.items() if "qkv_proj/kernel" in k}
    assert qkv and all(v == P() for v in qkv.values()), qkv


def test_match_partition_rules_covers_trainstate(monkeypatch):
    """One rule table resolves specs for the WHOLE TrainState: the Adam
    moments mirror the param paths, so matched params and their moments
    shard identically while scalars/unmatched leaves replicate."""
    mesh = make_mesh(MeshConfig(data=4, model=2))
    state = _transformer_state(mesh)
    tree = {
        "step": state.step, "params": state.params,
        "opt_state": state.opt_state,
    }
    specs = match_partition_rules(rules_for_family("weather_transformer"), tree)
    flat = jax.tree_util.tree_flatten_with_path(specs)[0]
    by_path = {
        "/".join(str(getattr(k, "key", getattr(k, "name", k))) for k in path): s
        for path, s in flat
    }
    param_qkv = [
        v for k, v in by_path.items()
        if "qkv_proj/kernel" in k and k.startswith("params")
    ]
    moment_qkv = [
        v for k, v in by_path.items()
        if "qkv_proj/kernel" in k and "opt_state" in k
    ]
    assert param_qkv and moment_qkv
    assert set(param_qkv) == set(moment_qkv) == {P(None, "model")}
    assert by_path["step"] == P()


def test_shard_and_gather_fns_round_trip():
    """shard -> gather is the identity, bitwise: the publish path's
    dense arrays are exactly what went onto the mesh."""
    mesh = make_mesh(MeshConfig(data=4, model=2))
    state = _transformer_state(mesh, shard_opt=True)
    shardings = state_shardings(
        state, mesh, shard_opt=True, family="weather_transformer"
    )
    shard_fns, gather_fns = make_shard_and_gather_fns(shardings)
    host = gather_tree(state.params)
    replaced = jax.tree.map(
        lambda fn, a: fn(a), shard_fns.params, host
    )
    back = jax.tree.map(lambda fn, a: fn(a), gather_fns.params, replaced)
    for a, b in zip(jax.tree.leaves(host), jax.tree.leaves(back)):
        assert np.array_equal(a, b)
    # ...and the re-placed leaves carry the declared layout.
    declared = jax.tree.leaves(shardings.params)
    for leaf, want in zip(jax.tree.leaves(replaced), declared):
        assert leaf.sharding.spec == want.spec


# ----------------------------------------------------------------------
# Declared-vs-actual layout (the trainer.py ~L431 wart, fixed)


def test_layout_mismatches_detects_zero1_output_drift(rng):
    """Under ZeRO-1 the jitted step's output params come back
    data-sharded while the declared layout replicates them — the drift
    the ``shard.layout_mismatch`` event names (measured on this rig: 2
    drifted leaves on the parity MLP at data=8)."""
    from dct_tpu.parallel.mesh import batch_sharding
    from dct_tpu.train.steps import make_train_step

    mesh = make_mesh(MeshConfig(data=8))
    model = get_model(ModelConfig(hidden_dim=64), input_dim=F)
    state = shard_state_with_rules(
        create_train_state(model, input_dim=F, lr=0.01, seed=0),
        mesh, shard_opt=True,
    )
    declared = state_shardings(state, mesh, shard_opt=True)
    assert layout_mismatches(state, declared) == []
    x = jax.device_put(
        rng.standard_normal((32, F)).astype(np.float32),
        batch_sharding(mesh),
    )
    y = jax.device_put(
        rng.integers(0, 2, 32).astype(np.int32), batch_sharding(mesh)
    )
    w = jax.device_put(np.ones(32, np.float32), batch_sharding(mesh))
    out, _m = make_train_step(donate=False)(state, x, y, w)
    drift = layout_mismatches(out, declared)
    assert drift, "expected ZeRO-1 output-layout drift on this rig"
    assert all(d["actual"] == ["data"] for d in drift), drift
    # Reconciliation: the re-pin the trainer runs before checkpointing
    # restores the declared layout exactly.
    repinned = jax.device_put(out, declared)
    assert layout_mismatches(repinned, declared) == []


# ----------------------------------------------------------------------
# Trainer end-to-end: sharded vs DP, and the sharded continuous path


def _fit(tmp_path, tag, *, mesh, processed_dir, epochs=2, resume=False,
         shard_opt=False, shard_params=False, batch_size=16):
    from dct_tpu.config import (
        DataConfig, ObservabilityConfig, RunConfig, TrainConfig,
    )
    from dct_tpu.tracking.client import LocalTracking
    from dct_tpu.train.trainer import Trainer

    base = tmp_path / tag
    cfg = RunConfig(
        data=DataConfig(
            processed_dir=processed_dir, models_dir=str(base / "models")
        ),
        model=ModelConfig(**TRANSFORMER),
        train=TrainConfig(
            epochs=epochs, batch_size=batch_size, lr=1e-3,
            bf16_compute=False, resume=resume, shard_opt_state=shard_opt,
            shard_params=shard_params, epoch_chunk=1,
        ),
        mesh=mesh,
        obs=ObservabilityConfig(
            enabled=True, events_dir=str(base / "events"),
            heartbeat_dir="", spans_dir="",
        ),
    )
    tracker = LocalTracking(root=str(base / "mlruns"))
    return Trainer(cfg, tracker=tracker).fit(), cfg


def _read_events(cfg):
    path = os.path.join(cfg.obs.events_dir, "events.jsonl")
    out = []
    with open(path) as f:
        for line in f:
            try:
                out.append(json.loads(line))
            except ValueError:
                continue
    return out


def test_zero_sharded_fit_matches_dp_and_publishes_dense(
    tmp_path, processed_dir
):
    """The tentpole's oracle pin on the SAME mesh: a fully
    rules-sharded fit (ZeRO-1 moments + FSDP params over ``data`` —
    the cross-replica weight-update sharding the motivation cites)
    follows the replicated-DP trajectory to <= 1e-6 per epoch (1-ulp
    layout-compile drift, module docstring) and the PUBLISHED package
    gathers dense — full global shapes, elementwise against the DP
    export at the same bound."""
    from dct_tpu.continuous.evaluator import package_checkpoint

    r_dp, _ = _fit(
        tmp_path, "dp", mesh=MeshConfig(data=8),
        processed_dir=processed_dir,
    )
    r_sh, _cfg = _fit(
        tmp_path, "sharded", mesh=MeshConfig(data=8),
        processed_dir=processed_dir, shard_opt=True, shard_params=True,
    )
    vl_dp = [h["val_loss"] for h in r_dp.history]
    vl_sh = [h["val_loss"] for h in r_sh.history]
    np.testing.assert_allclose(vl_sh, vl_dp, atol=1e-6, rtol=0)

    def pkg(tag, result):
        d = str(tmp_path / f"pkg_{tag}")
        package_checkpoint(result.best_model_path, d)
        npz = np.load(os.path.join(d, "model.npz"))
        return {k: npz[k] for k in npz.files}

    w_dp, w_sh = pkg("dp", r_dp), pkg("sh", r_sh)
    assert sorted(w_dp) == sorted(w_sh)
    qkv = [k for k in w_sh if k.endswith("qkv_proj/kernel")]
    assert qkv and w_sh[qkv[0]].shape == (16, 48)  # dense, not a shard
    for k in w_dp:
        np.testing.assert_allclose(w_sh[k], w_dp[k], atol=1e-6, rtol=0)


def test_tp_sharded_fit_tracks_dp_and_publishes_dense(
    tmp_path, processed_dir
):
    """The model-axis story at matched GLOBAL batch (the mesh data
    axis sizes the global batch, so dp@data=8 runs batch 8/rank vs
    tp@data=4 batch 16/rank = 64 rows either way): a TP+ZeRO-1 mesh
    tracks the DP trajectory to the cross-mesh reduction-order bound
    (1e-3 — the bound test_opt_sharding/test_multihost_tp pin; a wrong
    program moves losses 10x that) and publishes the full dense
    matrices."""
    from dct_tpu.continuous.evaluator import package_checkpoint

    r_dp, _ = _fit(
        tmp_path, "tp_dp", mesh=MeshConfig(data=8),
        processed_dir=processed_dir, batch_size=8,
    )
    r_tp, _cfg = _fit(
        tmp_path, "tp_sh", mesh=MeshConfig(data=4, model=2),
        processed_dir=processed_dir, shard_opt=True, batch_size=16,
    )
    vl_dp = [h["val_loss"] for h in r_dp.history]
    vl_tp = [h["val_loss"] for h in r_tp.history]
    np.testing.assert_allclose(vl_tp, vl_dp, atol=1e-3, rtol=0)
    d = str(tmp_path / "pkg_tp")
    package_checkpoint(r_tp.best_model_path, d)
    npz = np.load(os.path.join(d, "model.npz"))
    qkv = [k for k in npz.files if k.endswith("qkv_proj/kernel")]
    assert qkv and npz[qkv[0]].shape == (16, 48)  # dense, not a shard


def test_sharded_resume_across_mesh_topology_change(tmp_path, processed_dir):
    """The continuous path's topology pivot: train sharded on
    data=4/model=2, RESUME the same trajectory on data=8/model=1 at
    matched global batch — the restore re-maps the saved layout onto
    the new mesh (bit-identity pinned at the checkpoint layer by
    test_topology_remap_restores_bitwise) and the run EXTENDS instead
    of refusing. The control continuation on the unchanged mesh bounds
    the pivoted trajectory at the cross-mesh reduction-order tolerance."""
    import shutil

    _r1, _cfg1 = _fit(
        tmp_path, "pivot", mesh=MeshConfig(data=4, model=2),
        processed_dir=processed_dir, shard_opt=True, batch_size=16,
    )
    # Control: copy the trained state and continue on the SAME mesh.
    shutil.copytree(tmp_path / "pivot", tmp_path / "pivot_ctl")
    r_ctl, _ = _fit(
        tmp_path, "pivot_ctl", mesh=MeshConfig(data=4, model=2),
        processed_dir=processed_dir, shard_opt=True, resume=True,
        batch_size=16,
    )
    # Pivot: same trajectory, NEW topology, same 64-row global batch.
    r2, _cfg2 = _fit(
        tmp_path, "pivot", mesh=MeshConfig(data=8),
        processed_dir=processed_dir, resume=True, batch_size=8,
    )
    assert [h["epoch"] for h in r2.history] == [2, 3]
    vl_new = [h["val_loss"] for h in r2.history]
    vl_ctl = [h["val_loss"] for h in r_ctl.history]
    np.testing.assert_allclose(vl_new, vl_ctl, atol=1e-3, rtol=0)


def test_trainer_emits_layout_mismatch_event(tmp_path, processed_dir):
    """A ZeRO-1 fit whose step output drifts from the declared layout
    puts ``shard.layout_mismatch`` on the event log (reconciled — the
    checkpoint still lands in the declared layout and resumes clean)."""
    _r, cfg = _fit(
        tmp_path, "drift", mesh=MeshConfig(data=8),
        processed_dir=processed_dir, shard_opt=True, epochs=1,
    )
    ev = [
        r for r in _read_events(cfg)
        if r.get("event") == "shard.layout_mismatch"
    ]
    assert ev and ev[0]["reconciled"] is True and ev[0]["leaves"] >= 1
    # The reconciliation is real: the saved resume state restores onto
    # the declared layout without a topology error.
    from dct_tpu.checkpoint.manager import TrainStateCheckpointer

    ck = TrainStateCheckpointer(
        os.path.join(cfg.data.models_dir, "train_state", "p0")
    )
    assert ck.load_layout()["mesh"]["data"] == 8


# ----------------------------------------------------------------------
# Checkpoint layer: layout manifest + topology re-map


def _mlp_state(mesh, **kw):
    model = get_model(ModelConfig(hidden_dim=64), input_dim=F)
    return shard_state_with_rules(
        create_train_state(model, input_dim=F, lr=0.01, seed=0), mesh, **kw
    )


def _state_leaves(state):
    return jax.tree.leaves({
        "step": state.step, "params": state.params,
        "opt_state": state.opt_state, "rng": state.rng,
    })


def test_layout_manifest_written_and_loadable(tmp_path):
    from dct_tpu.checkpoint.manager import TrainStateCheckpointer

    mesh = make_mesh(MeshConfig(data=4, model=2))
    state = _transformer_state(mesh, shard_opt=True)
    ck = TrainStateCheckpointer(str(tmp_path / "ts" / "p0"))
    ck.save(state, meta={"epochs_completed": 1})
    layout = ck.load_layout()
    assert layout["mesh"] == {"data": 4, "model": 2, "seq": 1, "pipe": 1}
    assert layout["process_count"] == 1
    specs = {tuple(e["spec"] or []) for e in layout["leaves"] if e["spec"]}
    assert ("model",) in specs or (None, "model") in {
        tuple(s) for s in
        [tuple(x) for x in (e["spec"] for e in layout["leaves"] if e["spec"])]
    }
    # Async save writes the manifest too.
    ck.save_async(state, meta={"epochs_completed": 2})
    ck.wait()
    assert ck.load_meta()["epochs_completed"] == 2
    assert ck.load_layout()["leaves"]


def _split_leaf_into_shards(npz_path: str, *, parts: int = 2) -> str:
    """Rewrite a live state.npz turning one whole 2-D leaf into
    offset-keyed shard entries — the on-disk shape a DIFFERENT saving
    topology (cross-process sharded leaves) produces."""
    npz = np.load(npz_path)
    entries = {k: npz[k] for k in npz.files}
    key = next(
        k for k in entries
        if "_s" not in k and entries[k].ndim == 2
        and entries[k].shape[0] % parts == 0
    )
    arr = entries.pop(key)
    h = arr.shape[0] // parts
    for p in range(parts):
        entries[f"{key}_s{p * h}x0"] = arr[p * h:(p + 1) * h]
    with open(npz_path, "wb") as f:
        np.savez(f, **entries)
    return key


def test_topology_remap_restores_bitwise_and_emits_event(tmp_path):
    """Shard entries whose offsets match NO current-topology position
    re-map through the dense assembly: restored values bit-identical,
    ``shard.topology_remap`` on the event log, last_remap populated."""
    from dct_tpu.checkpoint.manager import TrainStateCheckpointer
    from dct_tpu.observability import events as _events

    mesh = make_mesh(MeshConfig(data=8))
    state = _mlp_state(mesh, shard_opt=True)
    ck = TrainStateCheckpointer(str(tmp_path / "ts" / "p0"))
    ck.save(state, meta={"epochs_completed": 3})
    _split_leaf_into_shards(
        os.path.join(ck.dirpath, "state", "state.npz")
    )

    log_path = str(tmp_path / "events.jsonl")
    prev = _events.get_default()
    _events.set_default(_events.EventLog(log_path, run_id="remap-test"))
    try:
        restored = ck.restore(_mlp_state(mesh, shard_opt=True))
    finally:
        _events.set_default(prev)
    for a, b in zip(_state_leaves(state), _state_leaves(restored)):
        assert np.array_equal(
            np.asarray(jax.device_get(a)), np.asarray(jax.device_get(b))
        )
    assert ck.last_remap["leaves"] == 1
    assert ck.last_remap["from_mesh"]["data"] == 8
    with open(log_path) as f:
        recs = [json.loads(line) for line in f]
    assert any(r["event"] == "shard.topology_remap" for r in recs)


def test_topology_remap_refuses_untileable_shards(tmp_path):
    """Missing shards (a private-disk pod's lone local file) still fail
    LOUDLY — a partial tiling must never restore zero-filled weights."""
    from dct_tpu.checkpoint.manager import TrainStateCheckpointer

    mesh = make_mesh(MeshConfig(data=8))
    state = _mlp_state(mesh, shard_opt=True)
    ck = TrainStateCheckpointer(str(tmp_path / "ts" / "p0"))
    ck.save(state)
    npz_path = os.path.join(ck.dirpath, "state", "state.npz")
    key = _split_leaf_into_shards(npz_path)
    npz = np.load(npz_path)
    entries = {k: npz[k] for k in npz.files}
    # Drop one of the two shards: the leaf can no longer be tiled.
    entries.pop(next(k for k in entries if k.startswith(f"{key}_s0")))
    with open(npz_path, "wb") as f:
        np.savez(f, **entries)
    with pytest.raises(ValueError, match="do not tile"):
        ck.restore(_mlp_state(mesh, shard_opt=True))


def test_process_growth_restores_from_siblings(tmp_path):
    """A rank with NO checkpoint of its own (process-count growth)
    restores whole leaves and shard halves from sibling p<rank>/ files:
    exists() says yes, meta rides along, values bitwise."""
    from dct_tpu.checkpoint.manager import TrainStateCheckpointer

    mesh = make_mesh(MeshConfig(data=8))
    state = _mlp_state(mesh, shard_opt=True)
    ck0 = TrainStateCheckpointer(str(tmp_path / "ts" / "p0"))
    ck0.save(state, meta={"epochs_completed": 5})
    key = _split_leaf_into_shards(
        os.path.join(ck0.dirpath, "state", "state.npz")
    )
    # Move ONE shard into a sibling rank's file: p0 alone cannot tile.
    npz_path = os.path.join(ck0.dirpath, "state", "state.npz")
    npz = np.load(npz_path)
    entries = {k: npz[k] for k in npz.files}
    shard_key = next(k for k in entries if k.startswith(f"{key}_s0"))
    p1_dir = str(tmp_path / "ts" / "p1" / "state")
    os.makedirs(p1_dir)
    with open(os.path.join(p1_dir, "state.npz"), "wb") as f:
        np.savez(f, **{shard_key: entries.pop(shard_key)})
    # Siblings are admitted to the shard pool only when their saved
    # generation matches (epochs_completed consistency gate).
    with open(os.path.join(p1_dir, "meta.json"), "w") as f:
        json.dump({"epochs_completed": 5}, f)
    with open(npz_path, "wb") as f:
        np.savez(f, **entries)

    # p0 itself now needs the sibling's shard...
    restored0 = ck0.restore(_mlp_state(mesh, shard_opt=True))
    for a, b in zip(_state_leaves(state), _state_leaves(restored0)):
        assert np.array_equal(
            np.asarray(jax.device_get(a)), np.asarray(jax.device_get(b))
        )
    # ...and a brand-new rank with NO dir restores entirely from
    # siblings, meta included.
    ck2 = TrainStateCheckpointer(str(tmp_path / "ts" / "p2"))
    assert ck2.exists()
    assert ck2.load_meta()["epochs_completed"] == 5
    restored2 = ck2.restore(_mlp_state(mesh, shard_opt=True))
    for a, b in zip(_state_leaves(state), _state_leaves(restored2)):
        assert np.array_equal(
            np.asarray(jax.device_get(a)), np.asarray(jax.device_get(b))
        )


def test_stale_sibling_shards_are_refused(tmp_path):
    """A sibling whose checkpoint is one save GENERATION behind (its
    rank died before publishing the last rotation) must not contribute
    shards: tiling epoch-N shards next to epoch-N-1 shards would
    silently restore a parameter array mixed across two optimizer
    steps. The consistency gate drops the stale sibling and the re-map
    fails loudly instead."""
    from dct_tpu.checkpoint.manager import TrainStateCheckpointer

    mesh = make_mesh(MeshConfig(data=8))
    state = _mlp_state(mesh, shard_opt=True)
    ck = TrainStateCheckpointer(str(tmp_path / "ts" / "p0"))
    ck.save(state, meta={"epochs_completed": 5})
    npz_path = os.path.join(ck.dirpath, "state", "state.npz")
    key = _split_leaf_into_shards(npz_path)
    npz = np.load(npz_path)
    entries = {k: npz[k] for k in npz.files}
    shard_key = next(k for k in entries if k.startswith(f"{key}_s0"))
    p1_dir = str(tmp_path / "ts" / "p1" / "state")
    os.makedirs(p1_dir)
    with open(os.path.join(p1_dir, "state.npz"), "wb") as f:
        np.savez(f, **{shard_key: entries.pop(shard_key)})
    with open(os.path.join(p1_dir, "meta.json"), "w") as f:
        json.dump({"epochs_completed": 4}, f)  # one save behind
    with open(npz_path, "wb") as f:
        np.savez(f, **entries)
    with pytest.raises(ValueError, match="do not tile"):
        ck.restore(_mlp_state(mesh, shard_opt=True))


# ----------------------------------------------------------------------
# Gather-on-publish + the eval harness under rules


def test_weights_from_state_gathers_dense_bitwise(tmp_path):
    """The live-state publish path: a TP+ZeRO-1-sharded TrainState
    exports byte-identical weights to the checkpoint-file path — the
    gather fns make the layout invisible to serving."""
    from dct_tpu.checkpoint.manager import save_checkpoint
    from dct_tpu.serving.score_gen import (
        weights_from_checkpoint, weights_from_state,
    )

    mesh = make_mesh(MeshConfig(data=4, model=2))
    state = _transformer_state(mesh, shard_opt=True)
    meta = dict(TRANSFORMER, model="weather_transformer", input_dim=F)
    meta.pop("name")
    w_live, _ = weights_from_state(state, meta)
    ckpt = str(tmp_path / "m.ckpt")
    save_checkpoint(ckpt, state.params, meta)
    w_file, _ = weights_from_checkpoint(ckpt)
    assert sorted(w_live) == sorted(w_file)
    for k in w_live:
        assert np.array_equal(w_live[k], w_file[k]), k
        assert isinstance(w_live[k], np.ndarray)


def test_harness_jax_engine_scores_under_rules(tmp_path, monkeypatch):
    """The jax engine places challenger params by the family rule table
    on the env-configured mesh: on model=2 the scored probabilities
    match the replicated numpy twin to engine tolerance (2e-6 — the
    documented jax/numpy parity bound)."""
    from dct_tpu.checkpoint.manager import save_checkpoint
    from dct_tpu.evaluation.harness import batched_probs
    from dct_tpu.serving.score_gen import weights_from_checkpoint

    mesh = make_mesh(MeshConfig(data=4, model=2))
    state = _transformer_state(mesh)
    meta = dict(TRANSFORMER, model="weather_transformer", input_dim=F)
    meta.pop("name")
    ckpt = str(tmp_path / "m.ckpt")
    save_checkpoint(ckpt, state.params, meta)
    weights, meta = weights_from_checkpoint(ckpt)

    rng = np.random.default_rng(0)
    x = rng.standard_normal((24, TRANSFORMER["seq_len"], F)).astype(
        np.float32
    )
    p_np = batched_probs(weights, meta, x, engine="numpy")
    monkeypatch.setenv("DCT_MESH_DATA", "4")
    monkeypatch.setenv("DCT_MESH_MODEL", "2")
    p_jax = batched_probs(weights, meta, x, engine="jax", batch_size=8)
    np.testing.assert_allclose(p_jax, p_np, atol=2e-6)


# ----------------------------------------------------------------------
# Loop + AOT wiring


def test_loop_forwards_sharding_knobs_to_child_ranks(tmp_path, monkeypatch):
    """Supervised rounds must rebuild the loop's mesh/sharding config
    in every child rank: the env the launcher receives carries the
    DCT_MESH_* / DCT_SHARD_* knobs from the loop's RunConfig."""
    from dct_tpu.config import (
        DataConfig, LoopConfig, RunConfig, TrainConfig,
    )
    from dct_tpu.continuous.loop import AlwaysOnLoop

    captured = {}

    class FakeLauncher:
        def supervise(self, cmd, *, world_size, env, **kw):
            captured.update(env)

            class R:
                success = True
                classification = "clean"
                restarts = 0
            return R()

    import dct_tpu.launch.launcher as launcher_mod

    monkeypatch.setattr(
        launcher_mod, "LocalProcessLauncher", lambda: FakeLauncher()
    )
    monkeypatch.setenv("DCT_SHARD_RULES", "qkv_proj.*/kernel$=")
    cfg = RunConfig(
        data=DataConfig(
            processed_dir=str(tmp_path / "proc"),
            models_dir=str(tmp_path / "models"),
            raw_csv=str(tmp_path / "raw.csv"),
        ),
        train=TrainConfig(shard_opt_state=True),
        mesh=MeshConfig(data=2, model=2),
        loop=LoopConfig(
            train_mode="supervised", packages_dir=str(tmp_path / "pkgs"),
        ),
    )
    loop = AlwaysOnLoop(cfg, client=object())
    loop._run_round_supervised()
    assert captured["DCT_MESH_DATA"] == "2"
    assert captured["DCT_MESH_MODEL"] == "2"
    assert captured["DCT_SHARD_OPT_STATE"] == "1"
    assert captured["DCT_SHARD_PARAMS"] == "0"
    assert captured["DCT_SHARD_RULES"] == "qkv_proj.*/kernel$="


def test_rules_digest_partitions_aot_identity(tmp_path):
    """Two stores differing only in the rule-table digest mint DISJOINT
    artifact paths: a layout change can never load the other layout's
    executable."""
    from dct_tpu.compilecache.aot import ExecutableStore

    a = ExecutableStore(
        str(tmp_path / "aot"),
        identity={"family": "f", "mesh": "m", "extra": "rules=aaaa"},
    )
    b = ExecutableStore(
        str(tmp_path / "aot"),
        identity={"family": "f", "mesh": "m", "extra": "rules=bbbb"},
    )
    assert a._path("scan_k1", "sig") != b._path("scan_k1", "sig")


@pytest.mark.slow
def test_sharded_two_process_relaunch_hits_aot_cache(tmp_path):
    """ISSUE 11 acceptance: a REAL 2-process sharded world (transformer
    TP spanning the ranks), SIGKILLed by a crash fault and healed by
    the PR 3 supervisor, warm-relaunches through the AOT store — the
    healed attempt's compile windows all carry cache=hit, and each rank
    minted its own artifact (per-rank identity)."""
    from dct_tpu.compilecache import spinup

    work = str(tmp_path / "spin")
    os.makedirs(work)
    spinup.prepare_processed(work, rows=400)
    menv = {
        "PALLAS_AXON_POOL_IPS": "",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
        "DCT_MODEL": "weather_transformer",
        "DCT_SEQ_LEN": "8", "DCT_D_MODEL": "16", "DCT_N_HEADS": "2",
        "DCT_N_LAYERS": "1", "DCT_D_FF": "32", "DCT_BF16_COMPUTE": "0",
        "DCT_MESH_DATA": "1", "DCT_MESH_MODEL": "2",
        # Serial donation keeps the crashed (fault-armed, auto-serial)
        # and healed attempts on ONE program identity, same as the DP
        # warm-relaunch e2e (test_compilecache).
        "DCT_PREFETCH_SPANS": "0",
    }
    warm = spinup.measure_relaunch(
        work, cache_on=True, world_size=2, model_env=menv, prewarm=True,
    )
    assert warm["returncode"] == 0, warm
    assert warm["relaunch_cache"] == ["hit"], warm
    artifacts = os.listdir(os.path.join(work, "aot"))
    # Per-rank identities: two ranks, each minted its own artifact.
    assert len({a.split("-")[1] for a in artifacts}) >= 2, artifacts


@pytest.mark.slow
def test_sharded_resume_after_cross_process_save(
    tmp_path, processed_dir
):
    """The cross-process topology pivot: train on a REAL 2-process
    model=2 world (params shard-saved per rank), then resume the SAME
    trajectory in ONE process on the 8-device mesh — the restore
    re-maps rank-local shards (pulling p1's halves via the sibling
    pool) onto the new topology, emits ``shard.topology_remap``, and
    the run extends."""
    from tests.test_multihost_tp import launch_training

    from dct_tpu.config import (
        DataConfig, ObservabilityConfig, RunConfig, TrainConfig,
    )
    from dct_tpu.train.trainer import Trainer

    launch_training(
        processed_dir, tmp_path, world_size=2, port=29573,
        models_sub="m_flow", runs_sub="r_flow",
        env_overrides={
            "DCT_MODEL": "weather_transformer",
            "DCT_N_LAYERS": "1",
            "DCT_MESH_MODEL": "2",
        },
    )
    models_dir = str(tmp_path / "m_flow")
    p0 = os.path.join(
        models_dir, "train_state", "p0", "state", "state.npz"
    )
    assert any("_s" in k for k in np.load(p0).files)

    cfg = RunConfig(
        data=DataConfig(
            processed_dir=processed_dir, models_dir=models_dir
        ),
        model=ModelConfig(**TRANSFORMER),
        train=TrainConfig(
            epochs=1, batch_size=16, lr=1e-3, bf16_compute=False,
            resume=True, epoch_chunk=1,
        ),
        mesh=MeshConfig(data=8),
        obs=ObservabilityConfig(
            enabled=True, events_dir=str(tmp_path / "ev_flow"),
            heartbeat_dir="", spans_dir="",
        ),
    )
    from dct_tpu.tracking.client import LocalTracking

    tracker = LocalTracking(root=str(tmp_path / "mlruns_flow"))
    result = Trainer(cfg, tracker=tracker).fit()
    assert np.isfinite(result.val_loss)
    # epoch 0 ran in the 2-proc world; this is its continuation.
    assert [h["epoch"] for h in result.history] == [1]
    ev_path = os.path.join(str(tmp_path / "ev_flow"), "events.jsonl")
    with open(ev_path) as f:
        recs = [json.loads(line) for line in f if line.strip()]
    assert any(r.get("event") == "shard.topology_remap" for r in recs)
