"""MoE family: routing invariants, learning, and expert parallelism on the
virtual 8-device mesh (EP completes the DP x TP x SP x EP matrix)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dct_tpu.config import MeshConfig, ModelConfig
from dct_tpu.models.moe import MoEFFN, WeatherMoE
from dct_tpu.models.registry import get_model, is_sequence_model
from dct_tpu.parallel.mesh import batch_sharding, make_mesh
from dct_tpu.parallel.sharding_rules import (
    shard_state_with_rules,
    state_shardings,
)
from dct_tpu.train.state import create_train_state
from dct_tpu.train.steps import make_train_step

SEQ, F = 8, 5
CFG = ModelConfig(
    name="weather_moe", seq_len=SEQ, d_model=16, n_heads=2, n_layers=2,
    d_ff=32, n_experts=4,
)


def test_registry_traits():
    assert is_sequence_model("weather_moe")
    model = get_model(CFG, input_dim=F)
    assert isinstance(model, WeatherMoE)
    assert model.n_experts == 4


def test_forward_shape_and_params(rng):
    model = get_model(CFG, input_dim=F)
    x = jnp.asarray(rng.standard_normal((3, SEQ, F)), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), x)
    logits, _ = model.apply(variables, x, mutable=["aux_loss"])
    assert logits.shape == (3, 2)
    w_in = variables["params"]["block_0"]["moe"]["experts_in_kernel"]
    assert w_in.shape == (4, 16, 32)


def test_moe_ffn_capacity_and_aux(rng):
    """Full-capacity routing reconstructs every token; the sown aux loss is
    >= the uniform-routing lower bound of aux_weight * 1.0."""
    ffn = MoEFFN(d_model=8, d_ff=16, n_experts=2, capacity_factor=2.0,
                 aux_weight=0.5)
    x = jnp.asarray(rng.standard_normal((2, 4, 8)), jnp.float32)
    variables = ffn.init(jax.random.PRNGKey(1), x)
    # init() also sows; feed back only params (as create_train_state does).
    out, updates = ffn.apply(
        {"params": variables["params"]}, x, mutable=["aux_loss"]
    )
    assert out.shape == x.shape
    (aux,) = jax.tree.leaves(updates)
    # Switch aux = w * E * sum(frac_e * mean_prob_e) >= w * 1 at uniform.
    assert float(aux) >= 0.4


def test_train_step_folds_aux_loss(rng):
    """The generic train step must include the sown load-balance term: with
    a huge aux weight, the loss visibly exceeds plain CE."""
    x = rng.standard_normal((8, SEQ, F)).astype(np.float32)
    y = rng.integers(0, 2, 8).astype(np.int32)
    w = np.ones(8, np.float32)
    step = make_train_step(donate=False)

    losses = {}
    for weight in (0.0, 100.0):
        cfg = ModelConfig(
            name="weather_moe", seq_len=SEQ, d_model=16, n_heads=2,
            n_layers=1, d_ff=32, n_experts=4, router_aux_weight=weight,
            dropout=0.0,
        )
        model = get_model(cfg, input_dim=F)
        state = create_train_state(
            model, input_dim=F, lr=1e-3, seed=0, example_shape=(1, SEQ, F)
        )
        _, m = step(state, jnp.asarray(x), jnp.asarray(y), jnp.asarray(w))
        losses[weight] = float(m["train_loss"])
    assert losses[100.0] > losses[0.0] + 10.0


@pytest.mark.slow
def test_moe_learns(rng):
    cfg = ModelConfig(
        name="weather_moe", seq_len=SEQ, d_model=16, n_heads=2, n_layers=1,
        d_ff=32, n_experts=4, dropout=0.0, capacity_factor=2.0,
    )
    model = get_model(cfg, input_dim=F)
    state = create_train_state(
        model, input_dim=F, lr=3e-3, seed=0, example_shape=(1, SEQ, F)
    )
    step = make_train_step(donate=False)
    x = rng.standard_normal((64, SEQ, F)).astype(np.float32)
    y = (x[:, -1, 0] > 0).astype(np.int32)
    w = np.ones(64, np.float32)
    first = None
    for _ in range(150):
        state, m = step(state, jnp.asarray(x), jnp.asarray(y), jnp.asarray(w))
        first = first if first is not None else float(m["train_loss"])
    assert float(m["train_loss"]) < first * 0.6


def test_expert_parallel_sharding_specs():
    model = get_model(CFG, input_dim=F)
    state = create_train_state(
        model, input_dim=F, lr=1e-3, seed=0, example_shape=(1, SEQ, F)
    )
    mesh = make_mesh(MeshConfig(data=4, model=2))
    shardings = state_shardings(state, mesh)
    flat = jax.tree_util.tree_flatten_with_path(shardings)[0]
    specs = {
        "/".join(str(getattr(k, "key", k)) for k in path): s.spec
        for path, s in flat
    }
    from jax.sharding import PartitionSpec as P

    ek = [v for k, v in specs.items() if k.endswith("experts_in_kernel")]
    assert ek and all(s == P("model", None, None) for s in ek)
    routers = [
        v for k, v in specs.items()
        if "router" in k and k.endswith("kernel") and "opt_state" not in k
    ]
    assert routers and all(s == P() for s in routers)


def test_ep_training_matches_single_device(rng):
    """One train step with experts sharded over the model axis == the
    single-device step (EP is layout, not math)."""
    mesh = make_mesh(MeshConfig(data=4, model=2))
    cfg = ModelConfig(
        name="weather_moe", seq_len=SEQ, d_model=16, n_heads=2, n_layers=1,
        d_ff=32, n_experts=4, dropout=0.0,
    )
    x = rng.standard_normal((8, SEQ, F)).astype(np.float32)
    y = rng.integers(0, 2, 8).astype(np.int32)
    w = np.ones(8, np.float32)
    step = make_train_step(donate=False)

    def make(seed=0):
        model = get_model(cfg, input_dim=F)
        return create_train_state(
            model, input_dim=F, lr=1e-3, seed=seed, example_shape=(1, SEQ, F)
        )

    s_ref, m_ref = step(make(), jnp.asarray(x), jnp.asarray(y), jnp.asarray(w))

    s_ep = shard_state_with_rules(make(), mesh)
    gx = jax.device_put(x, batch_sharding(mesh))
    gy = jax.device_put(y, batch_sharding(mesh))
    gw = jax.device_put(w, batch_sharding(mesh))
    s_ep, m_ep = step(s_ep, gx, gy, gw)

    np.testing.assert_allclose(
        float(m_ep["train_loss"]), float(m_ref["train_loss"]), rtol=1e-5
    )
    # Sharded einsums reduce in a different order (per-shard partial sums +
    # all-to-all), and Adam's 1/sqrt(nu) normalizer amplifies the fp-level
    # gradient differences — tolerance is looser than the TP/DP tests'.
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-4
        ),
        jax.device_get(s_ref.params),
        jax.device_get(s_ep.params),
    )


@pytest.mark.parametrize("sp_engine", ["ring", "a2a"])
def test_ep_sp_composed_training_matches_single_device(
    rng, monkeypatch, sp_engine
):
    """EP x SP x DP x TP in one step: experts AND attention heads over
    ``model``, SP attention over ``seq`` (both engines), batch over
    ``data`` — the full 2x2x2 mesh — matching the single-device
    trajectory (ample capacity -> no drops -> parallelism is layout, not
    math)."""
    from dct_tpu.ops.attention import make_attention_fn
    from dct_tpu.parallel.mesh import make_global_batch

    monkeypatch.setenv("DCT_SP_ENGINE", sp_engine)
    mesh = make_mesh(MeshConfig(data=2, model=2, seq=2))
    cfg = ModelConfig(
        name="weather_moe", seq_len=SEQ, d_model=16,
        # a2a additionally needs H/tp to tile sp (4 heads); ring keeps
        # the original 2-head shape.
        n_heads=4 if sp_engine == "a2a" else 2,
        n_layers=1,
        d_ff=32, n_experts=4, dropout=0.0, capacity_factor=8.0,
        # Force the sorted engine: at these tiny shapes "auto" picks the
        # einsum path, which would silently skip the explicit
        # lax.all_to_all expert exchange this composition test exists
        # to cover.
        moe_dispatch="sorted",
    )
    x = rng.standard_normal((8, SEQ, F)).astype(np.float32)
    y = rng.integers(0, 2, 8).astype(np.int32)
    w = np.ones(8, np.float32)
    step = make_train_step(donate=False)

    m_ref = get_model(cfg, input_dim=F)
    s_ref = create_train_state(
        m_ref, input_dim=F, lr=1e-3, seed=0, example_shape=(1, SEQ, F)
    )
    s_ref, met_ref = step(s_ref, jnp.asarray(x), jnp.asarray(y), jnp.asarray(w))

    # The Trainer's wiring: a mesh-aware attention kernel (ring over seq)
    # plus mesh-aware dispatch, same params as the reference state.
    m_sp = get_model(
        cfg, input_dim=F, attn_fn=make_attention_fn(mesh), mesh=mesh
    )
    s_sp = create_train_state(
        m_sp, input_dim=F, lr=1e-3, seed=0, example_shape=(1, SEQ, F)
    )
    s_sp = shard_state_with_rules(s_sp, mesh)
    gx, gy, gw = make_global_batch(mesh, x, y, w)
    s_sp, met_sp = step(s_sp, gx, gy, gw)

    np.testing.assert_allclose(
        float(met_sp["train_loss"]), float(met_ref["train_loss"]), rtol=1e-4
    )
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-4
        ),
        jax.device_get(s_ref.params),
        jax.device_get(s_sp.params),
    )
