"""Serving-package tests: numpy inference parity with the JAX model and the
generated score.py's operational contract (reference
dags/azure_manual_deploy.py:54-125 analog, minus the hardcoded input_dim)."""

import importlib.util
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dct_tpu.checkpoint.manager import save_checkpoint
from dct_tpu.config import ModelConfig
from dct_tpu.models.registry import get_model
from dct_tpu.serving.runtime import mlp_forward_numpy, score_payload, softmax_numpy
from dct_tpu.serving.score_gen import generate_score_package


def _ckpt(tmp_path, input_dim=5):
    model = get_model(ModelConfig(), input_dim=input_dim)
    params = model.init(jax.random.PRNGKey(3), jnp.zeros((1, input_dim)))
    meta = {
        "model": "weather_mlp",
        "input_dim": input_dim,
        "hidden_dim": 64,
        "num_classes": 2,
        "dropout": 0.2,
        "feature_names": [f"f{i}_norm" for i in range(input_dim)],
    }
    path = save_checkpoint(str(tmp_path / "model.ckpt"), params, meta)
    return model, params, path


def test_numpy_forward_matches_jax(tmp_path, rng):
    model, params, ckpt = _ckpt(tmp_path)
    deploy = str(tmp_path / "pkg")
    generate_score_package(ckpt, deploy)

    npz = np.load(os.path.join(deploy, "model.npz"))
    weights = {k: npz[k] for k in npz.files}
    x = rng.standard_normal((10, 5)).astype(np.float32)

    np_logits = mlp_forward_numpy(weights, x)
    jax_logits = np.asarray(model.apply(params, jnp.asarray(x), train=False))
    np.testing.assert_allclose(np_logits, jax_logits, atol=1e-5)

    probs = softmax_numpy(np_logits)
    np.testing.assert_allclose(probs.sum(axis=1), 1.0, rtol=1e-6)


def test_generated_score_py_end_to_end(tmp_path, rng, monkeypatch):
    """Import the generated score.py the way azureml-inference-server would:
    init() then run() on a JSON payload."""
    _, params, ckpt = _ckpt(tmp_path)
    deploy = str(tmp_path / "pkg")
    meta = generate_score_package(ckpt, deploy)
    assert meta["input_dim"] == 5

    for f in ("score.py", "conda.yaml", "model.npz", "model_meta.json"):
        assert os.path.exists(os.path.join(deploy, f)), f

    monkeypatch.setenv("AZUREML_MODEL_DIR", deploy)
    spec = importlib.util.spec_from_file_location(
        "generated_score", os.path.join(deploy, "score.py")
    )
    score = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(score)
    score.init()

    x = rng.standard_normal((3, 5)).astype(np.float32)
    out = score.run(json.dumps({"data": x.tolist()}))
    assert "probabilities" in out
    assert np.asarray(out["probabilities"]).shape == (3, 2)

    # Error contract: bad input returns {"error": ...}, not an exception.
    bad = score.run(json.dumps({"data": [[1.0, 2.0]]}))
    assert "error" in bad and "Expected shape" in bad["error"]


def test_score_py_nested_model_dir_fallback(tmp_path, rng, monkeypatch):
    """The reference's init() walks nested Azure layouts
    (dags/azure_manual_deploy.py:79-114); ours must too."""
    _, _, ckpt = _ckpt(tmp_path)
    deploy = str(tmp_path / "pkg")
    generate_score_package(ckpt, deploy)

    nested = tmp_path / "azure_root" / "INT" / "somehash" / "deploy_package"
    os.makedirs(nested)
    for f in ("model.npz", "model_meta.json"):
        os.rename(os.path.join(deploy, f), str(nested / f))

    monkeypatch.setenv("AZUREML_MODEL_DIR", str(tmp_path / "azure_root"))
    spec = importlib.util.spec_from_file_location(
        "generated_score2", os.path.join(deploy, "score.py")
    )
    score = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(score)
    score.init()
    out = score.run(json.dumps({"data": [[0.0] * 5]}))
    assert "probabilities" in out


def test_input_dim_from_checkpoint_not_hardcoded(tmp_path, rng, monkeypatch):
    """A 7-feature model must serve 7-feature payloads (the reference would
    break: score.py hardcodes input_dim=5)."""
    _, _, ckpt = _ckpt(tmp_path, input_dim=7)
    deploy = str(tmp_path / "pkg7")
    meta = generate_score_package(ckpt, deploy)
    assert meta["input_dim"] == 7

    monkeypatch.setenv("AZUREML_MODEL_DIR", deploy)
    spec = importlib.util.spec_from_file_location(
        "generated_score7", os.path.join(deploy, "score.py")
    )
    score = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(score)
    score.init()
    out = score.run(json.dumps({"data": [[0.1] * 7]}))
    assert np.asarray(out["probabilities"]).shape == (1, 2)


def _seq_ckpt(tmp_path, name, seq_len=10, input_dim=5):
    cfg = ModelConfig(
        name=name, seq_len=seq_len, d_model=16, n_heads=2, n_layers=2, d_ff=32
    )
    model = get_model(cfg, input_dim=input_dim)
    variables = model.init(
        jax.random.PRNGKey(5), jnp.zeros((1, seq_len, input_dim))
    )
    # Models may sow aux collections during init; checkpoints carry only
    # the trainable params (as create_train_state/Trainer do).
    params = {"params": variables["params"]}
    meta = {
        "model": name,
        "input_dim": input_dim,
        "seq_len": seq_len,
        "d_model": 16,
        "n_heads": 2,
        "n_layers": 2,
        "d_ff": 32,
        "n_experts": 4,
        "capacity_factor": 1.25,
        "n_stages": 2,
        "num_classes": 2,
        "dropout": 0.0,
        "feature_names": [f"f{i}_norm" for i in range(input_dim)],
    }
    path = save_checkpoint(str(tmp_path / f"{name}.ckpt"), params, meta)
    return model, params, path, meta


@pytest.mark.parametrize(
    "name",
    ["weather_gru", "weather_transformer", "weather_transformer_causal",
     "weather_transformer_pp", "weather_moe"],
)
def test_sequence_family_numpy_parity(tmp_path, rng, name):
    """Every deployable family's numpy inference must match the JAX model."""
    from dct_tpu.serving.runtime import forward_numpy

    model, params, ckpt, meta = _seq_ckpt(tmp_path, name)
    deploy = str(tmp_path / f"pkg_{name}")
    generate_score_package(ckpt, deploy)

    npz = np.load(os.path.join(deploy, "model.npz"))
    weights = {k: npz[k] for k in npz.files}
    x = rng.standard_normal((4, 10, 5)).astype(np.float32)

    np_logits = forward_numpy(weights, meta, x)
    jax_logits = np.asarray(model.apply(params, jnp.asarray(x), train=False))
    if name == "weather_transformer_causal":
        # Serving returns the LAST position's forecast for the window.
        jax_logits = jax_logits[:, -1]
    np.testing.assert_allclose(np_logits, jax_logits, atol=2e-5)


@pytest.mark.parametrize(
    "name",
    ["weather_gru", "weather_transformer", "weather_transformer_causal",
     "weather_transformer_pp", "weather_moe"],
)
def test_sequence_family_score_py_end_to_end(tmp_path, rng, monkeypatch, name):
    _, _, ckpt, meta = _seq_ckpt(tmp_path, name)
    deploy = str(tmp_path / f"pkg_{name}")
    generate_score_package(ckpt, deploy)

    monkeypatch.setenv("AZUREML_MODEL_DIR", deploy)
    spec = importlib.util.spec_from_file_location(
        f"generated_score_{name}", os.path.join(deploy, "score.py")
    )
    score = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(score)
    score.init()

    win = rng.standard_normal((2, 10, 5)).astype(np.float32)
    out = score.run(json.dumps({"data": win.tolist()}))
    assert np.asarray(out["probabilities"]).shape == (2, 2)

    # One un-batched window is accepted.
    out1 = score.run(json.dumps({"data": win[0].tolist()}))
    assert np.asarray(out1["probabilities"]).shape == (1, 2)

    # Wrong window length -> error contract, not an exception.
    bad = score.run(json.dumps({"data": win[:, :4].tolist()}))
    assert "error" in bad and "Expected shape" in bad["error"]


def test_score_payload_single_vector(tmp_path):
    weights = {
        "w0": np.zeros((5, 4), np.float32),
        "b0": np.zeros(4, np.float32),
        "w1": np.zeros((4, 2), np.float32),
        "b1": np.zeros(2, np.float32),
    }
    out = score_payload(weights, {"input_dim": 5}, [0.0] * 5)
    np.testing.assert_allclose(out["probabilities"], [[0.5, 0.5]])
