"""Low-precision end-to-end satellites (ISSUE 20).

Three planes of the int8/bf16 story, each pinned at the unit level:

- the ``DCT_DTYPE_RULES`` grammar (parallel/sharding_rules.py): the
  accept/reject matrix, the digest that joins AOT program identity, and
  the cast that implements the f32 master-weight contract;
- the f32 master-weight invariant itself, proven over REAL train steps
  (params and optimizer state never leave float32 while the loss body
  computes in bf16);
- the serving pack machinery (serving/quant.py, serving/runtime.py):
  per-channel int8 scales, the bit-exact row-invariant integer GEMM,
  bf16 bit-pattern round-trips, and the ``::q8``/``::scale``/``::bf16``
  package grammar end to end through ``quantize_package``.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dct_tpu.parallel.sharding_rules import (
    cast_params_by_rules,
    dtype_rules_digest,
    make_shard_and_gather_fns,
    parse_dtype_rules,
)
from dct_tpu.serving.quant import (
    prob_bound,
    quantize_array_int8,
    quantize_package,
    quantize_weights,
)
from dct_tpu.serving.runtime import (
    QuantTensor,
    assemble_weights,
    bf16_pack,
    bf16_unpack,
    forward_numpy,
    rows_mm,
    softmax_numpy,
)

F = 5


# ----------------------------------------------------------------------
# DCT_DTYPE_RULES grammar


def test_parse_dtype_rules_accepts_grammar():
    rules = parse_dtype_rules("attn.*/kernel=bf16; .*=f32")
    assert rules == (("attn.*/kernel", "bfloat16"), (".*", "float32"))
    # Aliases and long names canonicalize identically; empty clauses
    # (trailing ';') are skipped.
    assert parse_dtype_rules("k=bfloat16;") == (("k", "bfloat16"),)
    assert parse_dtype_rules("k=F16") == (("k", "float16"),)
    assert parse_dtype_rules("") == ()


@pytest.mark.parametrize(
    "text",
    ["kernel", "k(=bf16", "k=float8"],
    ids=["no-equals-clause", "bad-regex", "bad-dtype"],
)
def test_parse_dtype_rules_rejects(text):
    """A typo'd precision spec must raise, never silently train
    full-width — the ValueError names the offending clause."""
    with pytest.raises(ValueError):
        parse_dtype_rules(text)


def test_dtype_rules_digest_off_and_content_keyed(monkeypatch):
    monkeypatch.delenv("DCT_DTYPE_RULES", raising=False)
    assert dtype_rules_digest() == "off"
    monkeypatch.setenv("DCT_DTYPE_RULES", ".*=bf16")
    d1 = dtype_rules_digest()
    assert len(d1) == 10 and d1 != "off"
    int(d1, 16)  # hex
    monkeypatch.setenv("DCT_DTYPE_RULES", "attn.*=bf16")
    assert dtype_rules_digest() != d1


def test_cast_params_by_rules_matches_and_preserves(monkeypatch):
    params = {
        "dense": {
            "kernel": jnp.ones((3, 2), jnp.float32),
            "bias": jnp.zeros((2,), jnp.float32),
        },
        "step": jnp.zeros((), jnp.int32),
    }
    monkeypatch.setenv("DCT_DTYPE_RULES", "dense/kernel=bf16")
    out = cast_params_by_rules(params)
    assert out["dense"]["kernel"].dtype == jnp.bfloat16
    assert out["dense"]["bias"].dtype == jnp.float32  # unmatched
    assert out["step"].dtype == jnp.int32  # ints never cast
    # No rules -> identity (the bitwise status quo, zero tracing cost).
    monkeypatch.delenv("DCT_DTYPE_RULES")
    assert cast_params_by_rules(params) is params


def test_grad_cotangent_widens_to_f32(monkeypatch):
    """The cast's vjp widens bf16 cotangents back to f32: gradients
    w.r.t. the f32 masters are f32 even when the loss body computes in
    bf16 — accumulation and the optimizer update run full-width."""
    monkeypatch.setenv("DCT_DTYPE_RULES", ".*=bf16")
    p = {"kernel": jnp.full((4, 4), 0.5, jnp.float32)}

    def loss(params):
        q = cast_params_by_rules(params)
        assert q["kernel"].dtype == jnp.bfloat16  # trace-time check
        return jnp.sum(q["kernel"] ** 2).astype(jnp.float32)

    g = jax.grad(loss)(p)
    assert g["kernel"].dtype == jnp.float32


def test_master_weights_stay_f32_under_bf16_rules(monkeypatch, rng):
    """The end-to-end invariant over REAL train steps: under a
    blanket ``.*=bf16`` rule the trained params AND every float leaf of
    the optimizer state stay float32 (the bench leg asserts the same
    contract on the transformer shape before timing)."""
    from dct_tpu.config import ModelConfig
    from dct_tpu.models.registry import get_model
    from dct_tpu.train.state import create_train_state
    from dct_tpu.train.steps import make_train_step

    monkeypatch.setenv("DCT_DTYPE_RULES", ".*=bf16")
    model = get_model(ModelConfig(hidden_dim=16), input_dim=F)
    state = create_train_state(model, input_dim=F, lr=0.01, seed=0)
    step = make_train_step(donate=False)
    x = rng.standard_normal((16, F)).astype(np.float32)
    y = rng.integers(0, 2, 16).astype(np.int32)
    w = np.ones(16, np.float32)
    before = jax.device_get(state.params)
    for _ in range(2):
        state, metrics = step(state, x, y, w)
    assert np.isfinite(float(metrics["train_loss"]))
    for tree in (state.params, state.opt_state):
        for leaf in jax.tree_util.tree_leaves(tree):
            dt = getattr(leaf, "dtype", None)
            if dt is not None and jnp.issubdtype(dt, jnp.floating):
                assert dt == jnp.float32, leaf
    # And the bf16 compute actually trained (not a frozen no-op).
    after = jax.device_get(state.params)
    deltas = jax.tree_util.tree_map(
        lambda a, b: float(np.abs(a - b).max()), before, after
    )
    assert max(jax.tree_util.tree_leaves(deltas)) > 0


# ----------------------------------------------------------------------
# int8/bf16 pack machinery


def test_quantize_array_int8_per_channel_scales(rng):
    a = rng.standard_normal((64, 8)).astype(np.float32)
    a[:, 3] = 0.0  # an all-zero output channel must stay safe
    q, scale = quantize_array_int8(a)
    assert q.dtype == np.int8 and scale.dtype == np.float32
    assert q.shape == a.shape and scale.shape == (8,)
    np.testing.assert_allclose(
        scale, np.abs(a).max(axis=0) / np.float32(127.0)
    )
    assert scale[3] == 0.0 and not q[:, 3].any()
    # Symmetric round-trip: within half a quantization step per channel.
    deq = q.astype(np.float32) * scale[None, :]
    assert (np.abs(deq - a) <= scale[None, :] * 0.5 + 1e-9).all()


def test_quant_tensor_row_invariant_any_stacking(rng):
    """The integer GEMM's contract: row i of a batched matmul is
    BIT-identical to scoring that row alone — at K > _INT8_CHUNK the
    fixed-order chunked reduction must preserve it too."""
    k, m = 1536, 6  # k spans two reduction chunks
    q, scale = quantize_array_int8(
        rng.standard_normal((k, m)).astype(np.float32)
    )
    qt = QuantTensor(q, scale)
    x = rng.standard_normal((8, k)).astype(np.float32)
    batch = x @ qt
    assert batch.shape == (8, m)
    for via in (lambda r: r @ qt, lambda r: np.matmul(r, qt),
                lambda r: rows_mm(r, qt)):
        got = via(x)
        for i in (0, 3, 7):
            alone = via(x[i:i + 1])
            np.testing.assert_array_equal(alone[0], got[i])
            np.testing.assert_array_equal(got[i], batch[i])
    # 3D stacking reshapes through the same kernel: same bits.
    three = (x.reshape(2, 4, k) @ qt).reshape(8, m)
    np.testing.assert_array_equal(three, batch)


def test_bf16_pack_round_trip_matches_jnp(rng):
    a = rng.standard_normal((33,)).astype(np.float32)
    a[0] = 0.0
    u = bf16_pack(a)
    assert u.dtype == np.uint16
    want = np.asarray(
        jnp.asarray(a, jnp.bfloat16).astype(jnp.float32)
    )
    np.testing.assert_array_equal(bf16_unpack(u), want)
    # Values exactly representable in bf16 survive bit-for-bit.
    exact = np.array([0.0, 1.0, -2.5, 0.15625], np.float32)
    np.testing.assert_array_equal(bf16_unpack(bf16_pack(exact)), exact)


def test_assemble_weights_grammar(rng):
    w = rng.standard_normal((16, 4)).astype(np.float32)
    q, scale = quantize_array_int8(w)
    flat = {
        "a::q8": q, "a::scale": scale,
        "b::bf16": bf16_pack(w[:, 0]),
        "c": w,
    }
    out = assemble_weights(flat)
    assert set(out) == {"a", "b", "c"}
    assert isinstance(out["a"], QuantTensor)
    assert out["b"].dtype == np.float32
    np.testing.assert_array_equal(out["b"], bf16_unpack(flat["b::bf16"]))
    assert out["c"] is w
    # An f32 package passes through untouched.
    assert assemble_weights({"c": w})["c"] is w


def test_quantize_weights_selects_matmul_kernels(rng):
    weights = {
        "w0": rng.standard_normal((F, 8)).astype(np.float32),
        "b0": np.zeros(8, np.float32),
        "experts": rng.standard_normal((2, 8, 8)).astype(np.float32),
    }
    flat, meta = quantize_weights(weights, {"input_dim": F}, "int8")
    assert set(flat) == {"w0::q8", "w0::scale", "b0", "experts"}
    assert meta["quant"] == {"dtype": "int8", "prob_bound": prob_bound()}
    # bf16 packs EVERY float leaf, 3D stacks included.
    flat16, _ = quantize_weights(weights, {"input_dim": F}, "bf16")
    assert set(flat16) == {"w0::bf16", "b0::bf16", "experts::bf16"}
    with pytest.raises(ValueError):
        quantize_weights(weights, {}, "fp4")


def test_quantize_package_round_trip_and_refusal(tmp_path, rng):
    """f32 package -> int8 challenger: a COMPLETE sibling package whose
    assembled forward stays inside the documented prob bound — and a
    second quantization pass is refused (rounding must not compound)."""
    src = tmp_path / "champion"
    src.mkdir()
    weights = {
        "w0": (rng.standard_normal((F, 32)) * 0.4).astype(np.float32),
        "b0": np.zeros(32, np.float32),
        "w1": (rng.standard_normal((32, 3)) * 0.4).astype(np.float32),
        "b1": np.zeros(3, np.float32),
    }
    np.savez(src / "model.npz", **weights)
    meta = {"model": "weather_mlp", "input_dim": F}
    (src / "model_meta.json").write_text(json.dumps(meta))

    out = tmp_path / "challenger"
    meta_q = quantize_package(str(src), str(out), dtype="int8")
    assert meta_q["quant"]["dtype"] == "int8"
    for name in ("model.npz", "model_meta.json", "score.py",
                 "conda.yaml"):
        assert (out / name).exists(), name
    with np.load(out / "model.npz") as z:
        flat = {k: z[k] for k in z.files}
    assert "w0::q8" in flat and "w0::scale" in flat
    qw = assemble_weights(flat)
    x = rng.standard_normal((16, F)).astype(np.float32)
    ref = softmax_numpy(forward_numpy(weights, meta, x, mm=rows_mm))
    got = softmax_numpy(forward_numpy(qw, meta_q, x, mm=rows_mm))
    assert np.abs(got - ref).max() <= prob_bound()
    # Re-quantizing the quantized package compounds rounding: refused.
    with pytest.raises(ValueError, match="already quantized"):
        quantize_package(str(out), str(tmp_path / "twice"))


# ----------------------------------------------------------------------
# Per-leaf dtype specs in the shard/gather plumbing


def test_make_shard_gather_dtype_specs():
    from jax.sharding import NamedSharding, PartitionSpec as P

    from dct_tpu.config import MeshConfig
    from dct_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(MeshConfig(data=8))
    ns = NamedSharding(mesh, P())
    shardings = {"kernel": ns, "bias": ns, "step": ns}
    tree = {
        "kernel": np.ones((8, 4), np.float32),
        "bias": np.ones((4,), np.float32),
        "step": np.zeros((), np.int32),
    }

    # ONE dtype-like applied tree-wide (alias strings resolve through
    # DTYPE_ALIASES): floats cast, the int step counter never.
    shard_fns, gather_fns = make_shard_and_gather_fns(shardings, "bf16")
    placed = {k: shard_fns[k](v) for k, v in tree.items()}
    assert placed["kernel"].dtype == jnp.bfloat16
    assert placed["bias"].dtype == jnp.bfloat16
    assert placed["step"].dtype == jnp.int32
    back = gather_fns["kernel"](placed["kernel"])
    assert isinstance(back, np.ndarray)

    # A per-leaf spec tree: None leaves ride through untouched.
    shard_fns, gather_fns = make_shard_and_gather_fns(
        shardings,
        {"kernel": np.float16, "bias": None, "step": "bf16"},
    )
    placed = {k: shard_fns[k](v) for k, v in tree.items()}
    assert placed["kernel"].dtype == jnp.float16
    assert placed["bias"].dtype == jnp.float32
    assert placed["step"].dtype == jnp.int32  # non-float: spec ignored

    # No specs at all: pure placement, bitwise status quo.
    shard_fns, gather_fns = make_shard_and_gather_fns(shardings)
    assert shard_fns["kernel"](tree["kernel"]).dtype == jnp.float32
    got = gather_fns["bias"](shard_fns["bias"](tree["bias"]))
    np.testing.assert_array_equal(got, tree["bias"])


# ----------------------------------------------------------------------
# Roofline dtype stamp


def test_roofline_dtype_summary(monkeypatch):
    from dct_tpu.observability.roofline import dtype_summary

    monkeypatch.delenv("DCT_DTYPE_RULES", raising=False)
    args = {
        "x": jnp.ones((2, 2), jnp.float32),
        "i": jnp.ones((2,), jnp.int32),
    }
    assert dtype_summary(args) == "f32,i32"
    monkeypatch.setenv("DCT_DTYPE_RULES", "kernel=bf16")
    stamped = dtype_summary(args)
    assert stamped == f"f32,i32+rules:{dtype_rules_digest()}"
