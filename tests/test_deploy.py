"""Rollout state-machine tests against the in-memory endpoint, covering the
reference's blue/green + shadow + canary semantics
(dags/azure_auto_deploy.py:118-197) and endpoint recreate-on-failure
(dags/azure_manual_deploy.py:141-150)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dct_tpu.checkpoint.manager import save_checkpoint
from dct_tpu.config import ModelConfig
from dct_tpu.deploy.local import LocalEndpointClient
from dct_tpu.deploy.rollout import (
    BLUE,
    GREEN,
    RolloutOrchestrator,
    choose_slot,
    prepare_package,
)
from dct_tpu.models.registry import get_model
from dct_tpu.serving.score_gen import generate_score_package
from dct_tpu.tracking.client import LocalTracking


def _package(tmp_path, name="pkg", seed=0):
    model = get_model(ModelConfig(), input_dim=5)
    params = model.init(jax.random.PRNGKey(seed), jnp.zeros((1, 5)))
    meta = {"model": "weather_mlp", "input_dim": 5, "hidden_dim": 64,
            "num_classes": 2, "dropout": 0.2, "feature_names": ["a"] * 5}
    ckpt = save_checkpoint(str(tmp_path / f"{name}.ckpt"), params, meta)
    deploy = str(tmp_path / name)
    generate_score_package(ckpt, deploy)
    return deploy


def test_choose_slot():
    assert choose_slot({}) == (BLUE, None)
    assert choose_slot({"blue": 0, "green": 0}) == (BLUE, None)
    assert choose_slot({"blue": 100}) == (GREEN, "blue")
    assert choose_slot({"green": 90, "blue": 10}) == (BLUE, "green")


def test_first_rollout_goes_straight_to_100(tmp_path):
    client = LocalEndpointClient()
    ro = RolloutOrchestrator(client, "weather-ep", sleep_fn=lambda s: None)
    events = ro.run(_package(tmp_path))
    assert [e.stage for e in events] == ["deploy_new_slot", "full_rollout"]
    assert client.get_traffic("weather-ep") == {BLUE: 100}
    out = client.score("weather-ep", {"data": [[0.0] * 5]})
    assert "probabilities" in out


def test_second_rollout_blue_green_shadow_canary(tmp_path):
    client = LocalEndpointClient()
    soaks = []
    ro = RolloutOrchestrator(
        client, "weather-ep", sleep_fn=lambda s: soaks.append(s), soak_seconds=30
    )
    ro.run(_package(tmp_path, "v1", seed=0))
    ro2 = RolloutOrchestrator(
        client, "weather-ep", sleep_fn=lambda s: soaks.append(s), soak_seconds=30
    )
    events = ro2.run(_package(tmp_path, "v2", seed=1))

    stages = {e.stage: e for e in events}
    # Shadow: old serves 100%, new mirrored at 20%.
    assert stages["shadow"].traffic == {BLUE: 100, GREEN: 0}
    assert stages["shadow"].mirror == {GREEN: 20}
    # Canary: mirror cleared, 90/10 live.
    assert stages["canary"].traffic == {BLUE: 90, GREEN: 10}
    assert stages["canary"].mirror == {}
    # Full: green 100%, blue deployment deleted.
    assert stages["full_rollout"].traffic == {GREEN: 100}
    assert client.list_deployments("weather-ep") == [GREEN]
    # Two 30 s soaks happened (shadow->canary->full).
    assert soaks == [30, 30]


def test_third_rollout_flips_back_to_blue(tmp_path):
    client = LocalEndpointClient()
    ro = lambda: RolloutOrchestrator(client, "ep", sleep_fn=lambda s: None)  # noqa: E731
    ro().run(_package(tmp_path, "v1", seed=0))
    ro().run(_package(tmp_path, "v2", seed=1))
    ro().run(_package(tmp_path, "v3", seed=2))
    assert client.get_traffic("ep") == {BLUE: 100}
    assert client.list_deployments("ep") == [BLUE]


def test_failed_endpoint_recreated(tmp_path):
    client = LocalEndpointClient()
    client.create_endpoint("ep")
    client.endpoints["ep"].provisioning_state = "Failed"
    ro = RolloutOrchestrator(client, "ep", sleep_fn=lambda s: None)
    ro.run(_package(tmp_path))
    assert ("delete_endpoint", "ep") in client.ops
    assert client.get_traffic("ep") == {BLUE: 100}


def test_prepare_package_selects_best_run(tmp_path, monkeypatch):
    """End-to-end: tracking store with two runs -> package built from the
    lower-val_loss one (the deploy DAGs' selection policy)."""
    monkeypatch.delenv("DCT_RUN_ID", raising=False)  # restored after
    store = LocalTracking(root=str(tmp_path / "runs"), experiment="weather_forecasting")

    def finished_run(val_loss, seed):
        model = get_model(ModelConfig(), input_dim=5)
        params = model.init(jax.random.PRNGKey(seed), jnp.zeros((1, 5)))
        meta = {"model": "weather_mlp", "input_dim": 5, "hidden_dim": 64,
                "num_classes": 2, "dropout": 0.2, "feature_names": ["a"] * 5}
        ckpt = save_checkpoint(
            str(tmp_path / f"w-{seed}" / f"weather-best-00-{val_loss:.2f}.ckpt"),
            params, meta,
        )
        store.start_run()
        store.log_metrics({"val_loss": val_loss, "val_acc": 0.5}, step=1)
        store.log_artifact(ckpt, "best_checkpoints")
        store.end_run()

    finished_run(0.9, seed=1)
    finished_run(0.2, seed=2)

    info = prepare_package(store, str(tmp_path / "deploy"))
    assert abs(info["val_loss"] - 0.2) < 1e-9
    for f in ("model.ckpt", "model.npz", "model_meta.json", "score.py", "conda.yaml"):
        assert os.path.exists(os.path.join(str(tmp_path / "deploy"), f))
    # Deploy-side correlation channel: the package carries the SHIPPED
    # training cycle's run-correlation ID (each rollout stage runs in
    # its own task process — the package dir is the one shared
    # artifact), and a fresh orchestrator adopts it at deploy time.
    from dct_tpu.deploy.rollout import package_run_correlation_id

    best = store.search_best_run("val_loss", "min")
    assert best.run_correlation_id
    assert info["run_correlation_id"] == best.run_correlation_id
    assert (
        package_run_correlation_id(str(tmp_path / "deploy"))
        == best.run_correlation_id
    )
    ro = RolloutOrchestrator(
        LocalEndpointClient(state_path=str(tmp_path / "ep.json")),
        "ep", sleep_fn=lambda s: None,
    )
    ro.run(str(tmp_path / "deploy"))
    assert ro.run_id == best.run_correlation_id
    # A pre-observability package yields None, never a crash.
    assert package_run_correlation_id(str(tmp_path / "nope")) is None


def test_prepare_package_no_runs_raises(tmp_path):
    store = LocalTracking(root=str(tmp_path / "empty"))
    with pytest.raises(RuntimeError, match="No finished runs"):
        prepare_package(store, str(tmp_path / "deploy"))


def test_shadow_serves_old_model(tmp_path):
    """During shadow, live scoring must still route to the old slot."""
    client = LocalEndpointClient()
    ro = RolloutOrchestrator(client, "ep", sleep_fn=lambda s: None)
    ro.run(_package(tmp_path, "v1", seed=0))
    v1_out = client.score("ep", {"data": [[1.0] * 5]})

    ro2 = RolloutOrchestrator(client, "ep", sleep_fn=lambda s: None)
    new_slot, old_slot = ro2.deploy_new_slot(_package(tmp_path, "v2", seed=9))
    ro2.start_shadow(new_slot, old_slot)
    shadow_out = client.score("ep", {"data": [[1.0] * 5]})
    np.testing.assert_allclose(shadow_out["probabilities"], v1_out["probabilities"])
