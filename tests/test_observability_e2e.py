"""ISSUE 1 acceptance rig: a real CPU-smoke training run
(``jobs/train_tpu.py`` under the LocalProcessLauncher) must produce an
``events.jsonl`` where EVERY record — launcher, trainer, checkpoint,
tracking — carries the launcher-minted run-correlation ID, plus a final
goodput summary whose category seconds sum to within 5% of total wall
time; and a running serving server must answer ``GET /metrics`` with
valid Prometheus text exposition including slot and request-latency
series."""

import json
import os
import sys
import threading
import urllib.request

import pytest

from dct_tpu.launch.launcher import LocalProcessLauncher

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def smoke_run(processed_dir, tmp_path_factory):
    """One launched 2-epoch CPU training run, shared by the assertions."""
    tmp = tmp_path_factory.mktemp("obs_e2e")
    events_dir = tmp / "events"
    hb_dir = tmp / "heartbeats"
    env = {
        # Neutralize the ambient TPU plugin and any minted run id of the
        # pytest process itself (the launcher must be the minter here).
        "PALLAS_AXON_POOL_IPS": "",
        "JAX_PLATFORMS": "cpu",
        "DCT_RUN_ID": "",
        "DCT_PROCESSED_DIR": processed_dir,
        "DCT_MODELS_DIR": str(tmp / "models"),
        "DCT_TRACKING_DIR": str(tmp / "runs"),
        "DCT_EVENTS_DIR": str(events_dir),
        "DCT_HEARTBEAT_DIR": str(hb_dir),
        "DCT_EPOCHS": "2",
        "DCT_BATCH_SIZE": "8",
        "DCT_BF16_COMPUTE": "0",
    }
    launcher = LocalProcessLauncher(
        stagger_seconds=0.0, timeout=300.0, heartbeat_dir=str(hb_dir)
    )
    results = launcher.launch(
        [sys.executable, os.path.join(REPO, "jobs", "train_tpu.py")],
        world_size=1,
        env=env,
    )
    assert LocalProcessLauncher.all_succeeded(results), results
    recs = [
        json.loads(line)
        for line in (events_dir / "events.jsonl").read_text().splitlines()
    ]
    return {"tmp": tmp, "events_dir": events_dir, "hb_dir": hb_dir,
            "recs": recs}


def test_every_record_carries_the_launcher_run_id(smoke_run):
    recs = smoke_run["recs"]
    assert len(recs) >= 8
    run_ids = {r["run_id"] for r in recs}
    assert len(run_ids) == 1, run_ids
    rid = run_ids.pop()
    assert rid.startswith("dct-")
    # Orchestrator records are rank-null; rank records carry rank 0.
    launcher_recs = [r for r in recs if r["component"] == "launcher"]
    assert launcher_recs and all(r["rank"] is None for r in launcher_recs)
    # Every layer of the cycle is present in ONE file: the one-grep
    # reconstruction the event log exists for.
    components = {r["component"] for r in recs}
    assert {"launcher", "trainer", "checkpoint", "tracking"} <= components
    events = {(r["component"], r["event"]) for r in recs}
    for must in (
        ("launcher", "launch_start"),
        ("launcher", "launch_end"),
        ("trainer", "fit_start"),
        ("trainer", "epoch_end"),
        ("trainer", "goodput_summary"),
        ("trainer", "fit_end"),
        ("checkpoint", "resume_state_saved"),
        ("tracking", "run_start"),
        ("tracking", "run_end"),
    ):
        assert must in events, must


def test_goodput_summary_accounts_for_wall_time(smoke_run):
    [summary] = [
        r for r in smoke_run["recs"] if r["event"] == "goodput_summary"
    ]
    wall = summary["wall_seconds"]
    accounted = sum(summary["categories"].values())
    assert wall > 0
    # The acceptance bound: categories sum to within 5% of wall time.
    assert accounted >= 0.95 * wall, summary
    assert accounted <= wall * 1.01 + 0.05, summary
    assert summary["epochs"] == 2
    # A 2-epoch scan run: epoch 0's dispatch is the compile, epoch 1's
    # is a train_step — both categories must have real time in them.
    assert summary["categories"]["compile"] > 0
    assert summary["categories"]["train_step"] > 0
    assert summary["categories"]["startup_recovery"] > 0
    assert 0 < summary["goodput_fraction"] < 1


def test_goodput_logged_to_tracker_next_to_val_loss(smoke_run):
    import glob

    [metrics_path] = glob.glob(
        str(smoke_run["tmp"] / "runs" / "weather_forecasting" / "*" /
            "metrics.jsonl")
    )
    final = {}
    for line in open(metrics_path):
        final.update(json.loads(line))
    # The deploy-DAG query surface now answers goodput questions the
    # same way it answers accuracy ones.
    assert "val_loss" in final
    assert 0 < final["goodput_fraction"] < 1
    assert final["goodput_train_step_seconds"] > 0
    assert final["badput_compile_seconds"] > 0
    # And the tracking meta is stamped with the correlation id.
    meta = json.load(open(os.path.join(
        os.path.dirname(metrics_path), "meta.json"
    )))
    assert meta["run_correlation_id"] == smoke_run["recs"][0]["run_id"]


def test_rank_heartbeat_reaches_done(smoke_run):
    hb = json.load(open(smoke_run["hb_dir"] / "rank_00000.json"))
    assert hb["phase"] == "done"
    assert hb["rank"] == 0
    assert hb["run_id"] == smoke_run["recs"][0]["run_id"]


def test_train_metrics_prom_dump_written(smoke_run):
    from tests.test_observability import _parse_exposition

    text = (smoke_run["events_dir"] / "train_metrics.prom").read_text()
    samples = _parse_exposition(text)  # validates every line's grammar
    frac = [v for k, v in samples.items()
            if k.startswith("dct_train_goodput_fraction")]
    assert frac and 0 < frac[0] < 1
    assert any('category="compile"' in k for k in samples)


@pytest.fixture(scope="module")
def served(smoke_run):
    """Serve the checkpoint the smoke run just produced."""
    import glob

    from dct_tpu.serving.server import make_server

    [ckpt] = glob.glob(str(smoke_run["tmp"] / "models" / "weather-best-*.ckpt"))
    server = make_server(ckpt)
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{server.server_address[1]}"
    server.shutdown()
    server.server_close()


def test_metrics_endpoint_is_valid_exposition(served):
    from tests.test_observability import _parse_exposition

    # Drive a couple of scores so the series are non-trivial.
    for _ in range(3):
        req = urllib.request.Request(
            served + "/score",
            data=json.dumps({"data": [[0.1, -0.2, 0.3, 0.0, 1.0]]}).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req) as r:
            assert r.status == 200
    with urllib.request.urlopen(served + "/metrics") as r:
        assert r.headers["Content-Type"].startswith("text/plain")
        assert "version=0.0.4" in r.headers["Content-Type"]
        text = r.read().decode()
    samples = _parse_exposition(text)  # every line must parse
    assert samples['dct_requests_total{slot="default"}'] == 3
    assert samples['dct_request_errors_total{slot="default"}'] == 0
    assert samples[
        'dct_request_latency_seconds_bucket{slot="default",le="+Inf"}'
    ] == 3
    assert samples['dct_request_latency_seconds_count{slot="default"}'] == 3
    assert samples['dct_request_latency_seconds_sum{slot="default"}'] > 0
    assert "# TYPE dct_request_latency_seconds histogram" in text
