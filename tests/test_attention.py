"""Attention-path equivalence on the virtual 8-device mesh.

The contract: blockwise and ring attention are NUMERICALLY the same
function as dense attention — sequence parallelism must not change the
model, only its layout. (The reference has no attention at all; this is
capability the TPU build adds, SURVEY §5.7.)
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dct_tpu.config import MeshConfig
from dct_tpu.ops.attention import (
    blockwise_attention,
    dense_attention,
    make_attention_fn,
    ring_attention,
)
from dct_tpu.parallel.mesh import make_mesh

B, H, T, D = 2, 4, 64, 8


@pytest.fixture()
def qkv(rng):
    shape = (B, H, T, D)
    return tuple(
        jnp.asarray(rng.standard_normal(shape), jnp.float32) for _ in range(3)
    )


@pytest.mark.parametrize("causal", [False, True])
def test_blockwise_matches_dense(qkv, causal):
    q, k, v = qkv
    ref = dense_attention(q, k, v, causal=causal)
    out = blockwise_attention(q, k, v, block_size=16, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("seq", [2, 4, 8])
def test_ring_matches_dense(qkv, causal, seq):
    q, k, v = qkv
    mesh = make_mesh(MeshConfig(data=1, model=1, seq=seq), allow_subset=True)
    ref = dense_attention(q, k, v, causal=causal)
    out = ring_attention(q, k, v, mesh=mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_ring_composes_with_dp_tp(qkv):
    """DP x TP x SP in one op: batch over data, heads over model, sequence
    over seq — the full mesh at once."""
    q, k, v = qkv
    mesh = make_mesh(MeshConfig(data=2, model=2, seq=2))
    ref = dense_attention(q, k, v)
    out = ring_attention(q, k, v, mesh=mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_ring_under_jit_with_grad(qkv):
    """Ring attention must differentiate and jit (it sits inside the train
    step); grads must match dense attention's."""
    q, k, v = qkv
    mesh = make_mesh(MeshConfig(data=1, model=1, seq=4), allow_subset=True)

    def loss_ring(q):
        return ring_attention(q, k, v, mesh=mesh).sum()

    def loss_dense(q):
        return dense_attention(q, k, v).sum()

    g_ring = jax.jit(jax.grad(loss_ring))(q)
    g_dense = jax.grad(loss_dense)(q)
    np.testing.assert_allclose(
        np.asarray(g_ring), np.asarray(g_dense), atol=1e-4
    )


def test_make_attention_fn_selects_ring():
    mesh = make_mesh(MeshConfig(data=2, model=1, seq=4))
    fn = make_attention_fn(mesh)
    assert fn.func is ring_attention
    assert make_attention_fn(make_mesh(MeshConfig(data=8, model=1, seq=1))) \
        .__name__ == "attn"


def test_long_context_blockwise_memory_path(rng):
    """A context long enough that the dense score matrix would be the
    biggest tensor by far still runs through the blockwise path."""
    t = 4096
    q, k, v = (
        jnp.asarray(rng.standard_normal((1, 2, t, 8)), jnp.float32)
        for _ in range(3)
    )
    out = jax.jit(
        lambda q, k, v: blockwise_attention(q, k, v, block_size=512, causal=True)
    )(q, k, v)
    assert out.shape == (1, 2, t, 8)
    assert bool(jnp.isfinite(out).all())
