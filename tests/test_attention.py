"""Attention-path equivalence on the virtual 8-device mesh.

The contract: blockwise and ring attention are NUMERICALLY the same
function as dense attention — sequence parallelism must not change the
model, only its layout. (The reference has no attention at all; this is
capability the TPU build adds, SURVEY §5.7.)
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dct_tpu.config import MeshConfig
from dct_tpu.ops.attention import (
    blockwise_attention,
    dense_attention,
    make_attention_fn,
    ring_attention,
    striped_layout,
)
from dct_tpu.parallel.mesh import make_mesh

B, H, T, D = 2, 4, 64, 8


@pytest.fixture()
def qkv(rng):
    shape = (B, H, T, D)
    return tuple(
        jnp.asarray(rng.standard_normal(shape), jnp.float32) for _ in range(3)
    )


@pytest.mark.parametrize("causal", [False, True])
def test_blockwise_matches_dense(qkv, causal):
    q, k, v = qkv
    ref = dense_attention(q, k, v, causal=causal)
    out = blockwise_attention(q, k, v, block_size=16, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("seq", [2, 4, 8])
def test_ring_matches_dense(qkv, causal, seq):
    q, k, v = qkv
    mesh = make_mesh(MeshConfig(data=1, model=1, seq=seq), allow_subset=True)
    ref = dense_attention(q, k, v, causal=causal)
    out = ring_attention(q, k, v, mesh=mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_ring_composes_with_dp_tp(qkv):
    """DP x TP x SP in one op: batch over data, heads over model, sequence
    over seq — the full mesh at once."""
    q, k, v = qkv
    mesh = make_mesh(MeshConfig(data=2, model=2, seq=2))
    ref = dense_attention(q, k, v)
    out = ring_attention(q, k, v, mesh=mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_ring_under_jit_with_grad(qkv):
    """Ring attention must differentiate and jit (it sits inside the train
    step); grads must match dense attention's."""
    q, k, v = qkv
    mesh = make_mesh(MeshConfig(data=1, model=1, seq=4), allow_subset=True)

    def loss_ring(q):
        return ring_attention(q, k, v, mesh=mesh).sum()

    def loss_dense(q):
        return dense_attention(q, k, v).sum()

    g_ring = jax.jit(jax.grad(loss_ring))(q)
    g_dense = jax.grad(loss_dense)(q)
    np.testing.assert_allclose(
        np.asarray(g_ring), np.asarray(g_dense), atol=1e-4
    )


def test_make_attention_fn_selects_ring():
    mesh = make_mesh(MeshConfig(data=2, model=1, seq=4))
    fn = make_attention_fn(mesh)
    assert fn.func is ring_attention
    assert make_attention_fn(make_mesh(MeshConfig(data=8, model=1, seq=1))) \
        .__name__ == "attn"


def test_striped_layout_roundtrip():
    perm, inv = striped_layout(32, 4)
    np.testing.assert_array_equal(perm[inv], np.arange(32))
    # Device 1's shard (slots 8..16) holds chunks 1 and 2R-1-1=6.
    np.testing.assert_array_equal(perm[8:16], [4, 5, 6, 7, 24, 25, 26, 27])


@pytest.mark.parametrize("seq", [2, 4])
def test_striped_ring_matches_dense(qkv, seq):
    """The striped (zigzag) causal layout is the SAME function as dense
    causal attention — the permutation and per-chunk masks must cancel
    exactly (JAX-level online-softmax body)."""
    q, k, v = qkv
    mesh = make_mesh(MeshConfig(data=1, model=1, seq=seq), allow_subset=True)
    ref = dense_attention(q, k, v, causal=True)
    out = ring_attention(
        q, k, v, mesh=mesh, causal=True, striped=True, use_flash=False
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


@pytest.mark.parametrize("seq", [2, 4])
def test_striped_flash_ring_matches_dense(qkv, seq):
    """Striped ring with the Pallas flash per-shard compute (interpret
    mode on CPU): the three-case visibility analysis (diag / src<my /
    src>my) must reproduce dense causal attention exactly."""
    q, k, v = qkv
    mesh = make_mesh(MeshConfig(data=1, model=1, seq=seq), allow_subset=True)
    ref = dense_attention(q, k, v, causal=True)
    # use_flash=True resolves to interpret mode off-TPU; striped=None then
    # auto-enables the striped layout for the causal flash ring.
    out = ring_attention(q, k, v, mesh=mesh, causal=True, use_flash=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_striped_flash_ring_grad_matches_dense(qkv):
    """Backward through the striped flash ring (rectangular blocks remat
    through the blockwise twin) == dense causal grads."""
    q, k, v = qkv
    mesh = make_mesh(MeshConfig(data=1, model=1, seq=2), allow_subset=True)

    def loss_striped(q):
        return ring_attention(
            q, k, v, mesh=mesh, causal=True, use_flash=True
        ).sum()

    def loss_dense(q):
        return dense_attention(q, k, v, causal=True).sum()

    g_s = jax.jit(jax.grad(loss_striped))(q)
    g_d = jax.grad(loss_dense)(q)
    np.testing.assert_allclose(np.asarray(g_s), np.asarray(g_d), atol=1e-4)


def test_flash_ring_unaligned_shard_falls_back(rng):
    """t_local > 128 but not a 128-multiple (T=320, ring=2 -> 160): the
    striped auto-policy and the contiguous flash gate must BOTH decline,
    landing on the JAX-level ring body instead of crashing in the kernel
    (regression: interpret-mode gate accepted any half-chunk size)."""
    q, k, v = (
        jnp.asarray(rng.standard_normal((1, 2, 320, 8)), jnp.float32)
        for _ in range(3)
    )
    mesh = make_mesh(MeshConfig(data=1, model=1, seq=2), allow_subset=True)
    ref = dense_attention(q, k, v, causal=True)
    out = ring_attention(q, k, v, mesh=mesh, causal=True, use_flash=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_striped_flash_ring_composes_with_dp_tp(qkv):
    """DP x TP x SP with the striped causal flash ring: batch over data,
    heads over model, zigzag sequence layout over seq — all in one op."""
    q, k, v = qkv
    mesh = make_mesh(MeshConfig(data=2, model=2, seq=2))
    ref = dense_attention(q, k, v, causal=True)
    out = ring_attention(q, k, v, mesh=mesh, causal=True, use_flash=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_ring_striped_env_override(qkv, monkeypatch):
    """DCT_RING_STRIPED forces the layout either way; numerics are the
    oracle's in both."""
    q, k, v = qkv
    mesh = make_mesh(MeshConfig(data=1, model=1, seq=2), allow_subset=True)
    ref = dense_attention(q, k, v, causal=True)
    monkeypatch.setenv("DCT_RING_STRIPED", "on")  # striped JAX-level body
    out_on = ring_attention(q, k, v, mesh=mesh, causal=True, use_flash=False)
    # "off" forces the contiguous layout; at t_local=32 (< 128) the
    # flash request then degrades to the JAX-level contiguous ring.
    monkeypatch.setenv("DCT_RING_STRIPED", "off")
    out_off = ring_attention(q, k, v, mesh=mesh, causal=True, use_flash=True)
    np.testing.assert_allclose(np.asarray(out_on), np.asarray(ref), atol=1e-5)
    np.testing.assert_allclose(np.asarray(out_off), np.asarray(ref), atol=1e-5)


def test_striped_rejects_non_causal(qkv):
    q, k, v = qkv
    mesh = make_mesh(MeshConfig(data=1, model=1, seq=2), allow_subset=True)
    with pytest.raises(ValueError, match="causal"):
        ring_attention(q, k, v, mesh=mesh, causal=False, striped=True)


def test_long_context_blockwise_memory_path(rng):
    """A context long enough that the dense score matrix would be the
    biggest tensor by far still runs through the blockwise path."""
    t = 4096
    q, k, v = (
        jnp.asarray(rng.standard_normal((1, 2, t, 8)), jnp.float32)
        for _ in range(3)
    )
    out = jax.jit(
        lambda q, k, v: blockwise_attention(q, k, v, block_size=512, causal=True)
    )(q, k, v)
    assert out.shape == (1, 2, t, 8)
    assert bool(jnp.isfinite(out).all())


# --- all-to-all (Ulysses-style) SP engine --------------------------------


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("seq", [2, 4])
def test_a2a_matches_dense(qkv, causal, seq):
    from dct_tpu.ops.attention import a2a_attention

    q, k, v = qkv
    mesh = make_mesh(MeshConfig(data=1, model=1, seq=seq), allow_subset=True)
    ref = dense_attention(q, k, v, causal=causal)
    out = a2a_attention(q, k, v, mesh=mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_a2a_composes_with_dp_tp(qkv):
    """dp=2 x tp=2 x sp=2: heads exchange over seq INSIDE the model-axis
    shard — the composed layout the transformer family uses."""
    from dct_tpu.ops.attention import a2a_attention

    q, k, v = qkv
    mesh = make_mesh(MeshConfig(data=2, model=2, seq=2))
    ref = dense_attention(q, k, v)
    out = a2a_attention(q, k, v, mesh=mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_a2a_under_jit_with_grad(qkv):
    from dct_tpu.ops.attention import a2a_attention

    q, k, v = qkv
    mesh = make_mesh(MeshConfig(data=1, model=1, seq=4), allow_subset=True)

    def loss(q, k, v):
        return a2a_attention(q, k, v, mesh=mesh, causal=True).sum()

    def dense_loss(q, k, v):
        return dense_attention(q, k, v, causal=True).sum()

    g = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_a2a_rejects_untileable_heads(qkv):
    """H/(tp*sp) must be integral: H=4 heads cannot tile tp=2 x sp=4."""
    from dct_tpu.ops.attention import a2a_attention

    q, k, v = qkv
    mesh = make_mesh(MeshConfig(data=1, model=2, seq=4), allow_subset=True)
    with pytest.raises(ValueError, match="a2a_attention"):
        a2a_attention(q, k, v, mesh=mesh)


def test_sp_engine_env_selects_a2a(qkv, monkeypatch):
    """DCT_SP_ENGINE routes make_attention_fn (and the
    select_attention_path oracle) to the a2a engine, whose shard_map path
    must actually run: B=2 tiles data=2, so the dense init-trace fallback
    is NOT taken."""
    from dct_tpu.ops.attention import select_attention_path

    q, k, v = qkv
    mesh = make_mesh(MeshConfig(data=2, model=1, seq=2), allow_subset=True)
    monkeypatch.setenv("DCT_SP_ENGINE", "a2a")
    assert select_attention_path(T, mesh=mesh) == "a2a"
    fn = make_attention_fn(mesh, causal=True)
    out = fn(q, k, v)
    ref = dense_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)
    monkeypatch.setenv("DCT_SP_ENGINE", "bogus")
    with pytest.raises(ValueError, match="DCT_SP_ENGINE"):
        make_attention_fn(mesh)


# --- sliding-window (local) attention ------------------------------------


def _windowed_dense_reference(q, k, v, window):
    """Independent oracle: explicit [Tq, Tk] banded mask + softmax."""
    import math as _math

    s = np.einsum(
        "bhqd,bhkd->bhqk", np.asarray(q, np.float64), np.asarray(k, np.float64)
    ) / _math.sqrt(q.shape[-1])
    tq = q.shape[-2]
    pos = np.arange(tq)
    mask = (pos[:, None] >= pos[None, :]) & (pos[:, None] - pos[None, :] < window)
    s = np.where(mask, s, -1e30)
    p = np.exp(s - s.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    return np.einsum("bhqk,bhkd->bhqd", p, np.asarray(v, np.float64))


@pytest.mark.parametrize("window", [1, 8, 64])
def test_windowed_dense_matches_oracle(qkv, window):
    q, k, v = qkv
    ref = _windowed_dense_reference(q, k, v, window)
    out = dense_attention(q, k, v, causal=True, window=window)
    np.testing.assert_allclose(np.asarray(out), ref, atol=1e-5)


@pytest.mark.parametrize("window", [8, 24])
def test_windowed_blockwise_matches_dense(qkv, window):
    q, k, v = qkv
    ref = dense_attention(q, k, v, causal=True, window=window)
    out = blockwise_attention(
        q, k, v, block_size=16, causal=True, window=window
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_windowed_a2a_matches_dense(qkv):
    """Sliding window composes with the a2a SP engine: full sequence per
    device means the window mask is exact across shard boundaries."""
    from dct_tpu.ops.attention import a2a_attention

    q, k, v = qkv
    mesh = make_mesh(MeshConfig(data=1, model=1, seq=4), allow_subset=True)
    ref = dense_attention(q, k, v, causal=True, window=16)
    out = a2a_attention(q, k, v, mesh=mesh, causal=True, window=16)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_window_requires_causal(qkv):
    q, k, v = qkv
    with pytest.raises(ValueError, match="causal"):
        dense_attention(q, k, v, causal=False, window=8)
    with pytest.raises(ValueError, match="causal"):
        make_attention_fn(None, causal=False, window=8)
    mesh = make_mesh(MeshConfig(data=2, model=1, seq=4))
    with pytest.raises(ValueError, match="causal"):
        ring_attention(q, k, v, mesh=mesh, causal=False, window=8)


@pytest.mark.parametrize("striped_env", ["off", "on"])
@pytest.mark.parametrize("window", [1, 8, 24, 64])
def test_windowed_ring_matches_dense(qkv, window, striped_env, monkeypatch):
    """Sliding window on the DEFAULT (ring) SP engine (VERDICT r3 item 6):
    the band mask is built from GLOBAL positions, so both the contiguous
    and the striped (zigzag) layouts are exact across shard boundaries —
    windows inside one shard, spanning shards, and >= T (degenerating to
    full causal) all match the dense oracle."""
    monkeypatch.setenv("DCT_RING_STRIPED", striped_env)
    q, k, v = qkv
    mesh = make_mesh(MeshConfig(data=1, model=1, seq=4), allow_subset=True)
    ref = dense_attention(q, k, v, causal=True, window=window)
    out = ring_attention(q, k, v, mesh=mesh, causal=True, window=window)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_windowed_ring_composes_with_dp_tp(qkv, monkeypatch):
    monkeypatch.setenv("DCT_RING_STRIPED", "off")
    q, k, v = qkv
    mesh = make_mesh(MeshConfig(data=2, model=2, seq=2))
    ref = dense_attention(q, k, v, causal=True, window=12)
    out = ring_attention(q, k, v, mesh=mesh, causal=True, window=12)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


@pytest.mark.parametrize("window", [100, 128, 300])
def test_windowed_flash_ring_matches_dense(monkeypatch, rng, window):
    """The flash ring's windowed step analysis (static distance bounds:
    full-band shards run the plain Pallas kernel, partial-band shards run
    the windowed kernel with the inter-shard distance as q_offset,
    out-of-band steps are truncated) is exact at kernel-aligned shard
    sizes."""
    monkeypatch.setenv("DCT_FLASH", "interpret")
    monkeypatch.setenv("DCT_RING_STRIPED", "off")
    shape = (1, 2, 512, 8)  # t_local = 128: the flash ring engages
    q, k, v = (
        jnp.asarray(rng.standard_normal(shape), jnp.float32) for _ in range(3)
    )
    mesh = make_mesh(MeshConfig(data=1, model=1, seq=4), allow_subset=True)
    ref = dense_attention(q, k, v, causal=True, window=window)
    out = ring_attention(
        q, k, v, mesh=mesh, causal=True, window=window, use_flash=True
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


@pytest.mark.parametrize("window", [100, 200])
def test_windowed_flash_ring_grad_matches_dense(monkeypatch, rng, window):
    """Gradients through the windowed flash ring — the kernel q_offset
    forward plus the remat backward's window/q_offset plumbing and its
    static KV front-slice — against dense AD (code-review r4)."""
    monkeypatch.setenv("DCT_FLASH", "interpret")
    monkeypatch.setenv("DCT_RING_STRIPED", "off")
    shape = (1, 2, 256, 8)  # seq=2 -> t_local=128: flash ring engages
    q, k, v = (
        jnp.asarray(rng.standard_normal(shape), jnp.float32) for _ in range(3)
    )
    mesh = make_mesh(MeshConfig(data=1, model=1, seq=2), allow_subset=True)

    def loss_ring(q, k, v):
        return ring_attention(
            q, k, v, mesh=mesh, causal=True, window=window, use_flash=True
        ).sum()

    def loss_dense(q, k, v):
        return dense_attention(q, k, v, causal=True, window=window).sum()

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for gr, gd in zip(g_ring, g_dense):
        np.testing.assert_allclose(
            np.asarray(gr), np.asarray(gd), atol=2e-4
        )


def test_ring_window_step_truncation():
    """Out-of-band ring hops are not executed at all: the step count is
    O(window / t_local), and the lowered contiguous ring contains the
    correspondingly fewer (or zero) ppermute collectives."""
    from dct_tpu.ops.attention import _ring_window_steps

    assert _ring_window_steps(None, 16, 8) == 8
    assert _ring_window_steps(1, 16, 8) == 1  # diagonal only
    assert _ring_window_steps(16, 16, 8) == 2
    assert _ring_window_steps(17, 16, 8) == 2  # step 2's min distance = 17
    assert _ring_window_steps(18, 16, 8) == 3
    assert _ring_window_steps(10_000, 16, 8) == 8  # capped at the ring

    mesh = make_mesh(MeshConfig(data=1, model=1, seq=4), allow_subset=True)
    shape = (1, 2, 64, 8)

    def lowered(window):
        fn = lambda q, k, v: ring_attention(
            q, k, v, mesh=mesh, causal=True, window=window, striped=False,
            use_flash=False,
        )
        args = [jax.ShapeDtypeStruct(shape, jnp.float32)] * 3
        return str(jax.make_jaxpr(fn)(*args))

    # window=1 -> 1 step -> no KV rotation at all; full causal -> 3 hops.
    assert lowered(1).count("ppermute") == 0
    assert lowered(None).count("ppermute") == 3 * 2  # k and v per hop


def test_window_zero_rejected_at_op_layer(qkv):
    """'0 = off' is a CONFIG-layer convention; the op layer must reject
    window<1 loudly (a 0 band would silently softmax-uniform over all
    positions, breaking causality)."""
    q, k, v = qkv
    for bad in (0, -3):
        with pytest.raises(ValueError, match="window must be >= 1"):
            dense_attention(q, k, v, causal=True, window=bad)
        with pytest.raises(ValueError, match="window must be >= 1"):
            blockwise_attention(q, k, v, block_size=16, causal=True, window=bad)
        with pytest.raises(ValueError, match="window must be >= 1"):
            make_attention_fn(None, causal=True, window=bad)
