"""MPMD pipeline-parallel trainer (ISSUE 13): spec grammar, 1F1B/GPipe
schedules, bubble math, the SPMD<->MPMD state pivots, oracle parity,
per-stage AOT identity, cross-topology resume, and the transfer plane.

The SPMD pipeline oracle is the sequential stack (the PP family's
documented oracle — tests/test_pipeline*.py prove GPipe == sequential);
the MPMD pin is the LOSS trajectory within 1e-5 (measured ~6e-8 over 20
steps: microbatch accumulation reorders float reductions, so bitwise is
not promised — docs/PARALLELISM.md §MPMD tolerance policy). Pivot paths
are pure data movement and pin BITWISE.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dct_tpu.config import ModelConfig, MpmdConfig, RunConfig
from dct_tpu.parallel import mpmd
from dct_tpu.parallel import mpmd_transfer
from dct_tpu.train import mpmd_trainer as mt

SMALL = dict(
    name="weather_transformer_pp", dropout=0.0, seq_len=8, d_model=16,
    n_heads=2, n_layers=2, d_ff=32, n_stages=2,
)
INPUT_DIM = 5


def _small_cfg(tmp_path=None, **model_over):
    cfg = RunConfig()
    cfg.model = ModelConfig(**{**SMALL, **model_over})
    cfg.train.bf16_compute = False
    cfg.train.batch_size = 8
    cfg.mpmd = MpmdConfig(stages="1,1", microbatches=4)
    if tmp_path is not None:
        cfg.data.models_dir = str(tmp_path / "models")
    return cfg


def _full_state(cfg):
    return mt.build_full_state(cfg, INPUT_DIM, compute_dtype=jnp.float32)


def _runner(cfg, full=None):
    spec = cfg.mpmd.to_spec(n_devices=jax.device_count())
    meshes = mpmd.carve_stage_meshes(spec.device_counts, model=1)
    full = full if full is not None else _full_state(cfg)
    states = [
        mt.shard_stage_state(
            mpmd.split_state(full, k, spec.n_stages), meshes[k]
        )
        for k in range(spec.n_stages)
    ]
    fns = mt.build_stage_fns(cfg.model, INPUT_DIM, compute_dtype=jnp.float32)
    progs = [
        mpmd.make_stage_programs(k, spec.n_stages, fns)
        for k in range(spec.n_stages)
    ]
    return mpmd.MpmdRunner(spec, states, progs, meshes)


def _batches(n, b=8, seed=0):
    rng = np.random.default_rng(seed)
    return [
        (
            rng.standard_normal(
                (b, SMALL["seq_len"], INPUT_DIM)
            ).astype(np.float32),
            rng.integers(0, 2, b).astype(np.int32),
            np.ones(b, np.float32),
        )
        for _ in range(n)
    ]


# ----------------------------------------------------------------------
# Spec grammar: loud failures at parse time (satellite 1).


def test_stage_spec_parses_count_and_explicit_counts():
    assert mpmd.parse_stage_spec("2", n_devices=8) == (4, 4)
    assert mpmd.parse_stage_spec("1,1") == (1, 1)
    assert mpmd.parse_stage_spec(" 2 , 1 , 1 ") == (2, 1, 1)


@pytest.mark.parametrize(
    "text,match",
    [
        ("", "empty"),
        ("two", "not an integer"),
        ("1", ">= 2 stages"),
        ("0,1", "must be >= 1"),
        ("3", "does not divide"),  # with n_devices=8
        ("4,4,4", "asks for 12"),  # with n_devices=8
    ],
)
def test_stage_spec_malformed_is_loud(text, match):
    with pytest.raises(mpmd.MpmdSpecError, match=match):
        mpmd.parse_stage_spec(text, n_devices=8)


def test_spec_env_values_validate_loudly():
    with pytest.raises(mpmd.MpmdSpecError, match="DCT_MPMD_SCHEDULE"):
        MpmdConfig(stages="1,1", schedule="zigzag").to_spec()
    with pytest.raises(mpmd.MpmdSpecError, match="MICROBATCHES"):
        MpmdConfig(stages="1,1,1", microbatches=2).to_spec()
    with pytest.raises(mpmd.MpmdSpecError, match="TRANSFER_TIMEOUT"):
        MpmdConfig(stages="1,1", transfer_timeout_s=0).to_spec()
    spec = MpmdConfig(stages="1,1").to_spec()
    assert spec.n_microbatches == 4  # default 2x stages


def test_trainer_mode_refusals():
    cfg = _small_cfg()
    cfg.model.dropout = 0.2
    with pytest.raises(mpmd.MpmdSpecError, match="DCT_DROPOUT"):
        mt._validate_cfg(cfg)
    cfg = _small_cfg()
    cfg.train.grad_clip_norm = 1.0
    with pytest.raises(mpmd.MpmdSpecError, match="GRAD_CLIP"):
        mt._validate_cfg(cfg)
    cfg = _small_cfg()
    cfg.model.name = "weather_mlp"
    with pytest.raises(mpmd.MpmdSpecError, match="pipeline-parallel"):
        mt._validate_cfg(cfg)


def test_untileable_stage_map_is_loud():
    with pytest.raises(mpmd.MpmdSpecError, match="does not tile"):
        mpmd.stage_layers(2, 3)
    # A 2-stage checkpointed tree refuses a 4-stage split.
    cfg = _small_cfg()
    full = _full_state(cfg)
    with pytest.raises(mpmd.MpmdSpecError, match="untileable"):
        mpmd.split_params(full.params, 0, 4)


# ----------------------------------------------------------------------
# Schedules + bubble math (satellite 2's analytic half).


@pytest.mark.parametrize("p,m", [(2, 4), (2, 8), (4, 8)])
def test_1f1b_schedule_properties(p, m):
    ops = mpmd.build_schedule(p, m, "1f1b")
    assert len(ops) == p
    for i, stage_ops in enumerate(ops):
        fwds = [o for o in stage_ops if o.kind == "fwd"]
        bwds = [o for o in stage_ops if o.kind == "bwd"]
        assert [o.mb for o in fwds] == list(range(m))
        assert [o.mb for o in bwds] == list(range(m))
        # fwd(mb) precedes bwd(mb); warmup fills are P-1-i deep.
        pos = {(o.kind, o.mb): j for j, o in enumerate(stage_ops)}
        for mb in range(m):
            assert pos[("fwd", mb)] < pos[("bwd", mb)]
        fills = [o for o in stage_ops if o.phase == "fill"]
        assert len(fills) == min(p - 1 - i, m)
    # The LAST stage has no fill: it alternates f/b from its first op.
    assert all(o.phase != "fill" for o in ops[p - 1])
    # In-flight activations never exceed P - i (1F1B's memory bound).
    for i, stage_ops in enumerate(ops):
        live = peak = 0
        for o in stage_ops:
            live += 1 if o.kind == "fwd" else -1
            peak = max(peak, live)
        assert peak <= p - i


def test_gpipe_schedule_is_all_fwd_then_all_bwd():
    ops = mpmd.build_schedule(2, 4, "gpipe")
    kinds = [o.kind for o in ops[0]]
    assert kinds == ["fwd"] * 4 + ["bwd"] * 4


def test_analytic_bubble_values():
    assert mpmd.analytic_bubble(2, 8) == pytest.approx(1 / 9)
    assert mpmd.analytic_bubble(4, 4) == pytest.approx(3 / 7)


def test_measured_bubble_recovers_analytic_on_ideal_walls():
    # t(M) = a*(M + P - 1): the ideal pipeline's wall.
    p, a = 4, 0.01
    for m in (4, 8):
        t1, t2 = a * (m + p - 1), a * (2 * m + p - 1)
        assert mpmd.measured_bubble(t1, t2, m, 2 * m) == pytest.approx(
            mpmd.analytic_bubble(p, m), abs=1e-9
        )
    with pytest.raises(ValueError):
        mpmd.measured_bubble(1.0, 2.0, 8, 8)


def test_gpipe_measured_vs_analytic_bubble_over_ledger():
    """Satellite 2: the documented ``(P-1)/(M+P-1)`` fraction, asserted
    against a MEASUREMENT of the real GPipe program over the goodput
    ledger — step wall is affine in M at fixed microbatch size, and the
    intercept fraction (slope method) must recover the analytic bubble.
    Chunky stage compute so scheduling noise stays inside the band."""
    import time

    from dct_tpu.observability.goodput import GoodputLedger
    from dct_tpu.parallel.pipeline import (
        gpipe_tick_apply,
        stack_stage_params,
    )

    d, p = 256, 4
    rng = np.random.default_rng(0)
    stacked = stack_stage_params([
        {"w": jnp.asarray(rng.standard_normal((d, d)) * 0.1, jnp.float32)}
        for _ in range(p)
    ])

    def stage_fn(params, x):
        h = x
        for _ in range(4):
            h = jnp.tanh(h @ params["w"])
        return h

    mb_rows = 256
    ledger = GoodputLedger()
    ledger.start()

    def timed(m: int) -> float:
        x = jnp.asarray(
            rng.standard_normal((mb_rows * m, d)), jnp.float32
        )
        f = jax.jit(
            lambda pp, xx: gpipe_tick_apply(
                stage_fn, pp, xx, n_microbatches=m
            )
        )
        with ledger.dispatch("train_step", key=f"gpipe_m{m}"):
            jax.block_until_ready(f(stacked, x))  # compile window
        best = None
        for _ in range(3):
            t0 = ledger.clock()
            with ledger.dispatch("train_step", key=f"gpipe_m{m}"):
                jax.block_until_ready(f(stacked, x))
            dt = ledger.clock() - t0
            best = dt if best is None or dt < best else best
        return best

    m = 4
    t1, t2 = timed(m), timed(2 * m)
    measured = mpmd.measured_bubble(t1, t2, m, 2 * m)
    analytic = mpmd.analytic_bubble(p, m)  # 0.429
    # The compile dispatches billed to `compile`, the timed ones to
    # train_step — the ledger carries the windows the measurement used.
    assert ledger.seconds["compile"] > 0
    assert ledger.seconds["train_step"] >= t1 + t2
    assert measured == pytest.approx(analytic, abs=0.15)


# ----------------------------------------------------------------------
# State pivots: SPMD stacked <-> per-stage, bitwise.


def _assert_trees_equal(a, b):
    la = jax.tree_util.tree_leaves_with_path(a)
    lb = jax.tree_util.tree_leaves_with_path(b)
    assert len(la) == len(lb)
    for (pa, va), (_pb, vb) in zip(la, lb):
        np.testing.assert_array_equal(
            np.asarray(va), np.asarray(vb), err_msg=str(pa)
        )


@pytest.mark.parametrize("optimizer", ["adam", "sgd"])
def test_split_merge_roundtrip_bitwise(optimizer):
    cfg = _small_cfg()
    cfg.train.optimizer = optimizer
    cfg.train.momentum = 0.9 if optimizer == "sgd" else 0.0
    full = _full_state(cfg)
    stages = [mpmd.split_state(full, k, 2) for k in range(2)]
    # Stage 0 carries the embedding, the last stage the head.
    assert "in_proj" in stages[0].params["params"]
    assert "head" in stages[1].params["params"]
    assert "in_proj" not in stages[1].params["params"]
    merged = mpmd.merge_stage_states(stages, template=full)
    _assert_trees_equal(full.params, merged.params)
    _assert_trees_equal(full.opt_state, merged.opt_state)


# ----------------------------------------------------------------------
# Oracle parity: the SPMD pipeline oracle's loss trajectory.


def test_runner_matches_spmd_oracle_loss_trajectory():
    from dct_tpu.train.steps import _eval_body, _train_body

    cfg = _small_cfg()
    full = _full_state(cfg)
    runner = _runner(cfg, full)
    batches = _batches(6)
    oracle = full
    step = jax.jit(_train_body)
    for i, (x, y, w) in enumerate(batches):
        oracle, loss_o, _ = step(oracle, x, y, w)
        loss_m, _wall = runner.train_step(x, y, w)
        assert abs(float(loss_o) - loss_m) < 1e-5, f"step {i}"
    # Eval sums agree too (forward-only microbatch pipeline vs the
    # oracle's eval body on the SAME post-training states).
    x, y, w = batches[0]
    sums_m = runner.eval_pass(x, y, w)
    merged = mpmd.merge_stage_states(runner.states, template=full)
    host = merged.replace(
        params=jax.tree.map(jnp.asarray, merged.params)
    )
    sums_o = jax.jit(_eval_body)(host, x, y, w)
    for a, b in zip(sums_m, sums_o):
        assert abs(float(a) - float(b)) < 1e-4
    # Per-stage step counters advanced together.
    assert all(
        int(jax.device_get(s.step)) == len(batches)
        for s in runner.states
    )


def test_runner_gpipe_schedule_same_math():
    """The gpipe op order on the MPMD substrate computes the identical
    update (schedules reorder execution, not math)."""
    cfg = _small_cfg()
    full = _full_state(cfg)
    r1 = _runner(cfg, full)
    cfg2 = _small_cfg()
    cfg2.mpmd.schedule = "gpipe"
    r2 = _runner(cfg2, full)
    for x, y, w in _batches(3):
        l1, _ = r1.train_step(x, y, w)
        l2, _ = r2.train_step(x, y, w)
        assert l1 == pytest.approx(l2, abs=1e-7)


def test_step_report_attributes_phases():
    cfg = _small_cfg()
    runner = _runner(cfg)
    x, y, w = _batches(1)[0]
    _loss, wall = runner.train_step(x, y, w)
    rep = runner.step_bubble(wall)
    assert rep["schedule"] == "1f1b"
    assert 0.0 <= rep["step_bubble"] <= 1.0
    assert 0.0 <= rep["steady_bubble"] <= 1.0
    assert rep["analytic_bubble"] == pytest.approx(
        mpmd.analytic_bubble(2, 4)
    )
    stages = rep["stages"]
    assert len(stages) == 2
    # Stage 0 warms up (fill > 0); the LAST stage has no fill by
    # construction; everyone has steady work; busy decomposes into the
    # three phases.
    assert stages[0]["fill_s"] > 0
    assert stages[1]["fill_s"] == 0
    for s in stages:
        assert s["steady_s"] > 0
        assert s["busy_s"] >= (
            s["fill_s"] + s["steady_s"] + s["drain_s"]
        ) - 1e-9


def test_transfer_timeout_is_loud():
    ch = mpmd.QueueChannel()
    with pytest.raises(mpmd.MpmdTransferTimeout):
        ch.recv(timeout=0.05)


# ----------------------------------------------------------------------
# Cross-topology resume (satellite 3): MPMD-saved per-stage checkpoints
# restored by the SPMD trainer (and vice versa), bitwise; untileable
# stage maps refuse loudly.


def _save_mpmd_checkpoint(cfg, runner, epochs_completed=1):
    for k in range(runner.spec.n_stages):
        mt.stage_checkpointer(cfg.data.models_dir, k).save(
            runner.states[k],
            {
                "epochs_completed": epochs_completed,
                "target_epochs": epochs_completed,
                "family": cfg.model.name,
                "stage": k,
            },
        )
    mt.write_manifest(cfg.data.models_dir, {
        "version": 1,
        "n_stages": runner.spec.n_stages,
        "device_counts": list(runner.spec.device_counts),
        "schedule": runner.spec.schedule,
        "n_microbatches": runner.spec.n_microbatches,
        "family": cfg.model.name,
        "n_layers": cfg.model.n_layers,
        "epochs_completed": epochs_completed,
    })


def test_mpmd_checkpoint_adopted_by_spmd_bitwise(tmp_path):
    from dct_tpu.checkpoint.manager import TrainStateCheckpointer

    cfg = _small_cfg(tmp_path)
    full = _full_state(cfg)
    runner = _runner(cfg, full)
    for x, y, w in _batches(2):
        runner.train_step(x, y, w)
    _save_mpmd_checkpoint(cfg, runner)
    in_memory = mpmd.merge_stage_states(runner.states, template=full)

    meta = mt.adopt_mpmd_checkpoint(cfg.data.models_dir, full)
    assert meta["epochs_completed"] == 1
    spmd = TrainStateCheckpointer(
        os.path.join(cfg.data.models_dir, "train_state", "p0")
    )
    restored = spmd.restore(full)
    _assert_trees_equal(in_memory.params, restored.params)
    _assert_trees_equal(in_memory.opt_state, restored.opt_state)
    assert int(np.asarray(restored.step)) == 2


def test_spmd_checkpoint_splits_into_mpmd_bitwise(tmp_path):
    from dct_tpu.checkpoint.manager import TrainStateCheckpointer
    from dct_tpu.train.steps import _train_body

    cfg = _small_cfg(tmp_path)
    full = _full_state(cfg)
    step = jax.jit(_train_body)
    for x, y, w in _batches(2):
        full, _loss, _ = step(full, x, y, w)
    spmd = TrainStateCheckpointer(
        os.path.join(cfg.data.models_dir, "train_state", "p0")
    )
    spmd.save(full, {"epochs_completed": 1, "target_epochs": 1})

    template = _full_state(cfg)
    restored, meta = mt._restore_from_spmd(cfg.data.models_dir, template)
    assert meta["epochs_completed"] == 1
    for k in range(2):
        _assert_trees_equal(
            mpmd.split_state(restored, k, 2).params,
            mpmd.split_state(full, k, 2).params,
        )


def test_adopt_refuses_untileable_stage_map(tmp_path):
    cfg = _small_cfg(tmp_path)
    runner = _runner(cfg)
    x, y, w = _batches(1)[0]
    runner.train_step(x, y, w)
    _save_mpmd_checkpoint(cfg, runner)
    # Doctor the manifest to a stage count the template cannot tile.
    man = mt.read_manifest(cfg.data.models_dir)
    man["n_stages"] = 4
    mt.write_manifest(cfg.data.models_dir, man)
    with pytest.raises(mpmd.MpmdSpecError, match="untileable"):
        mt.adopt_mpmd_checkpoint(
            cfg.data.models_dir, _full_state(cfg)
        )


def test_mpmd_trainer_fit_resume_and_pivot(tmp_path, monkeypatch):
    """End-to-end MpmdTrainer.fit over a real processed dataset: fresh
    fit -> per-stage resume extends the trajectory -> the step_report
    events land -> a fresh SPMD-side adoption resumes the same
    trajectory (mpmd.pivot on the log)."""
    from dct_tpu.data.synthetic import generate_weather_csv
    from dct_tpu.etl.preprocess import preprocess_csv_to_parquet

    ev_dir = tmp_path / "events"
    monkeypatch.setenv("DCT_EVENTS_DIR", str(ev_dir))
    raw = str(tmp_path / "weather.csv")
    generate_weather_csv(raw, rows=300, seed=7)
    proc = str(tmp_path / "processed")
    preprocess_csv_to_parquet(raw, proc)

    from dct_tpu.observability import events as _events
    from dct_tpu.observability.buffered import flush_all_appenders

    cfg = _small_cfg(tmp_path)
    cfg.data.processed_dir = proc
    cfg.obs.events_dir = str(ev_dir)
    cfg.obs.metrics_dir = str(tmp_path / "metrics")
    cfg.train.epochs = 2
    res = mt.MpmdTrainer(cfg).fit()
    # The default EventLog batches appends (DCT_TELEMETRY_FLUSH_S);
    # make the records durable before reading them back.
    _events.get_default().flush()
    flush_all_appenders()
    assert len(res.train_losses) == 2
    assert res.epochs_completed == 2
    assert 0.0 <= res.bubble["steady_bubble"] <= 1.0
    assert mt.mpmd_checkpoint_present(cfg.data.models_dir)
    # The metrics plane got a final snapshot with the bubble gauges.
    snaps = list((tmp_path / "metrics").glob("*.metrics.json"))
    assert snaps
    snap = json.loads(snaps[0].read_text())
    blob = json.dumps(snap)
    assert "dct_mpmd_bubble_fraction" in blob
    assert "dct_mpmd_stage_phase_seconds" in blob

    cfg.train.resume = True
    cfg.train.epochs = 1
    res2 = mt.MpmdTrainer(cfg).fit()
    assert res2.epochs_completed == 3
    # The trajectory extended: the resumed epoch improves on the first
    # fit's start.
    assert res2.train_losses[-1] < res.train_losses[0]

    _events.get_default().flush()
    events = [
        json.loads(line)
        for line in open(ev_dir / "events.jsonl")
    ]
    reports = [e for e in events if e["event"] == "mpmd.step_report"]
    assert len(reports) == 3
    assert all("stages" in r for r in reports)

    # The SPMD trainer adopts the per-stage files on resume.
    template = _full_state(cfg)
    mt.adopt_mpmd_checkpoint(cfg.data.models_dir, template)
    _events.get_default().flush()
    events = [
        json.loads(line)
        for line in open(ev_dir / "events.jsonl")
    ]
    pivots = [e for e in events if e["event"] == "mpmd.pivot"]
    assert any(p.get("direction") == "mpmd_to_spmd" for p in pivots)

    # And the inspector renders the MPMD section from the same log.
    from dct_tpu.observability.inspect import build_report

    report = build_report(events, [], [], None, None)
    assert "MPMD pipeline" in report
    assert "steady=" in report


def test_resume_refuses_optimizer_change_and_torn_set(
    tmp_path, processed_dir
):
    """The Trainer's cross-optimizer resume refusal applies to the MPMD
    paths (opt_state trees can be structurally isomorphic across
    configs), and a manifest whose stage files are incomplete is a TORN
    set — loud, never a silent fresh start over surviving progress."""
    import shutil

    cfg = _small_cfg(tmp_path)
    cfg.data.processed_dir = processed_dir
    cfg.train.epochs = 1
    mt.MpmdTrainer(cfg).fit()

    cfg2 = _small_cfg(tmp_path)
    cfg2.data.processed_dir = processed_dir
    cfg2.train.resume = True
    cfg2.train.optimizer = "sgd"
    cfg2.train.momentum = 0.9
    with pytest.raises(RuntimeError, match="Resume refused"):
        mt.MpmdTrainer(cfg2).fit()

    shutil.rmtree(
        os.path.join(
            mt.mpmd_state_root(cfg.data.models_dir), "stage1"
        )
    )
    cfg.train.resume = True
    with pytest.raises(FileNotFoundError, match="torn"):
        mt.MpmdTrainer(cfg).fit()


def test_per_stage_aot_identity_and_warm_hit(tmp_path, monkeypatch):
    """Per-stage programs key into the AOT store with stage id + slice
    topology in the identity: a cold build misses (publishing per-stage
    artifacts with DISTINCT names), a warm rebuild hits every stage."""
    monkeypatch.setenv("DCT_COMPILE_CACHE", "auto")
    monkeypatch.setenv("DCT_COMPILE_CACHE_DIR", str(tmp_path / "xla"))
    from dct_tpu import compilecache as _cc

    cfg = _small_cfg(tmp_path)
    spec = cfg.mpmd.to_spec(n_devices=jax.device_count())
    meshes = mpmd.carve_stage_meshes(spec.device_counts, model=1)
    full = _full_state(cfg)
    fns = mt.build_stage_fns(cfg.model, INPUT_DIM, compute_dtype=jnp.float32)

    def build_runner():
        stores = [
            _cc.store_from_env(
                str(tmp_path / "aot"), family=cfg.model.name,
                config_hash="deadbeef", mesh="data1_model1",
                extra={"mpmd_stage": k, "mpmd_slice": "1x1"},
            )
            for k in range(2)
        ]
        states = [
            mt.shard_stage_state(
                mpmd.split_state(full, k, 2), meshes[k]
            )
            for k in range(2)
        ]
        progs = [
            mpmd.make_stage_programs(k, 2, fns, store=stores[k])
            for k in range(2)
        ]
        return mpmd.MpmdRunner(spec, states, progs, meshes), stores

    x, y, w = _batches(1)[0]
    r1, stores1 = build_runner()
    r1.train_step(x, y, w)
    assert all(
        v == "miss" for st in stores1 for v in st.states.values()
    )
    # Stage identities partition the artifact namespace.
    names = os.listdir(tmp_path / "aot")
    assert any("mpmd_fwd_s0" in n for n in names)
    assert any("mpmd_fwd_s1" in n for n in names)
    assert stores1[0]._identity_key() != stores1[1]._identity_key()

    r2, stores2 = build_runner()
    r2.train_step(x, y, w)
    assert all(
        v == "hit" for st in stores2 for v in st.states.values()
    ), {k: v for st in stores2 for k, v in st.states.items()}


# ----------------------------------------------------------------------
# Transfer plane.


def test_socket_transfer_roundtrip_and_timeout():
    import socket as _socket
    import threading

    a, b = _socket.socketpair()
    ca, cb = (
        mpmd_transfer.SocketChannel(a),
        mpmd_transfer.SocketChannel(b),
    )
    arr = np.arange(24, dtype=np.float32).reshape(2, 3, 4)

    def send():
        ca.send(arr)

    t = threading.Thread(target=send)
    t.start()
    got = cb.recv(timeout=5.0)
    t.join()
    np.testing.assert_array_equal(got, arr)
    assert got.dtype == np.float32
    # An empty link times out loudly, never hangs.
    with pytest.raises(mpmd.MpmdTransferTimeout):
        cb.recv(timeout=0.1)
    ca.close()
    cb.close()


def test_stage_links_establish_and_carry(tmp_path):
    """A 2-stage link ring over loopback: activations down, gradients
    back up, in either start order."""
    import threading

    port_base = 29710
    results = {}

    def stage(k):
        links = mpmd_transfer.connect_stage_links(
            k, 2, port_base=port_base, timeout=20.0
        )
        try:
            if k == 0:
                links["act_out"].send(np.full((2, 2), 7.0, np.float32))
                results["grad"] = links["grad_in"].recv(10.0)
            else:
                act = links["act_in"].recv(10.0)
                links["grad_out"].send(act * 2.0)
        finally:
            mpmd_transfer.close_links(links)

    threads = [
        threading.Thread(target=stage, args=(k,)) for k in (1, 0)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30.0)
    np.testing.assert_array_equal(
        results["grad"], np.full((2, 2), 14.0, np.float32)
    )


@pytest.mark.slow
def test_two_process_worker_matches_in_process_bitwise(tmp_path):
    """The multi-process deployment (one process per stage, socket
    transfers) computes the IDENTICAL loss trajectory as the in-process
    thread-per-stage trainer — same schedule, same programs, different
    transport."""
    import subprocess
    import sys

    from dct_tpu.data.synthetic import generate_weather_csv
    from dct_tpu.etl.preprocess import preprocess_csv_to_parquet

    raw = str(tmp_path / "weather.csv")
    generate_weather_csv(raw, rows=300, seed=7)
    proc = str(tmp_path / "processed")
    preprocess_csv_to_parquet(raw, proc)

    # In-process reference.
    cfg = _small_cfg(tmp_path)
    cfg.data.processed_dir = proc
    cfg.data.models_dir = str(tmp_path / "models_inproc")
    cfg.train.epochs = 2
    res = mt.MpmdTrainer(cfg).fit()

    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        DCT_PROCESSED_DIR=proc,
        DCT_MODELS_DIR=str(tmp_path / "models_proc"),
        DCT_EVENTS_DIR=str(tmp_path / "events_proc"),
        DCT_HEARTBEAT_DIR=str(tmp_path / "hb"),
        DCT_MODEL="weather_transformer_pp", DCT_DROPOUT="0",
        DCT_SEQ_LEN="8", DCT_D_MODEL="16", DCT_N_HEADS="2",
        DCT_N_LAYERS="2", DCT_D_FF="32", DCT_N_STAGES="2",
        DCT_BF16_COMPUTE="0", DCT_EPOCHS="2", DCT_BATCH_SIZE="8",
        DCT_MPMD_STAGES="1,1", DCT_MPMD_MICROBATCHES="4",
        DCT_MPMD_PORT_BASE="29720",
        DCT_MPMD_TRANSFER_TIMEOUT_S="60",
    )
    env.pop("XLA_FLAGS", None)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    procs = [
        subprocess.Popen(
            [sys.executable, "-m", "dct_tpu.train.mpmd_worker"],
            env=dict(env, DCT_MPMD_STAGE_ID=str(k)), cwd=repo,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        for k in range(2)
    ]
    errs = []
    for p in procs:
        _out, err = p.communicate(timeout=240)
        errs.append(err)
    assert [p.returncode for p in procs] == [0, 0], errs
    events = [
        json.loads(line)
        for line in open(tmp_path / "events_proc" / "events.jsonl")
    ]
    losses = [
        e["train_loss"] for e in events
        if e["event"] == "mpmd.step_report"
    ]
    assert losses == pytest.approx(res.train_losses, abs=0.0)
