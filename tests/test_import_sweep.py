"""Import-sweep smoke test: every dct_tpu module imports under CPU JAX.

Rarely-exercised modules (``native/``, ``orchestration/``, DAG-side
helpers) can rot silently — a bad import or syntax error only surfaces
when someone finally runs that path, which on a production platform is
an incident, not a test failure. This sweep imports every module of the
package under ``JAX_PLATFORMS=cpu`` (the conftest rig) so rot is caught
at tier-1 time.

The DAG modules under ``dags/`` are covered separately by
``tests/test_dags.py`` (they need the Airflow-or-stub environment);
this sweep is about the installable package.
"""

from __future__ import annotations

import importlib
import pkgutil

import pytest

import dct_tpu


def _all_modules() -> list[str]:
    return sorted(
        m.name
        for m in pkgutil.walk_packages(dct_tpu.__path__, "dct_tpu.")
    )


def test_sweep_finds_a_meaningful_surface():
    names = _all_modules()
    # The package has ~70 modules; a collapsed walk (empty __path__,
    # renamed package) must fail loudly, not pass on vacuous truth.
    assert len(names) >= 40
    for expected in (
        "dct_tpu.native.build",
        "dct_tpu.orchestration.compat",
        "dct_tpu.analysis.lint",
        "dct_tpu.train.trainer",
    ):
        assert expected in names


@pytest.mark.parametrize("name", _all_modules())
def test_module_imports(name):
    importlib.import_module(name)
