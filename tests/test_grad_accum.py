"""Gradient accumulation: microbatch-summed updates must equal the big
batch they decompose (the weighted-CE sum/total split is linear), in both
the per-batch and whole-epoch-scan compilation paths."""

import jax
import jax.numpy as jnp
import numpy as np

from dct_tpu.config import DataConfig, ModelConfig, RunConfig, TrainConfig
from dct_tpu.models.registry import get_model
from dct_tpu.tracking.client import LocalTracking
from dct_tpu.train.state import create_train_state
from dct_tpu.train.steps import make_epoch_train_step, make_train_step
from dct_tpu.train.trainer import Trainer


def _state(seed=0):
    model = get_model(ModelConfig(dropout=0.0), input_dim=5)
    return create_train_state(model, input_dim=5, lr=0.01, seed=seed)


def _batch(rng, n):
    x = jnp.asarray(rng.standard_normal((n, 5)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 2, n), jnp.int32)
    w = jnp.ones((n,), jnp.float32)
    return x, y, w


def test_accum_step_equals_big_batch(rng):
    x, y, w = _batch(rng, 16)
    s1, m1 = make_train_step(donate=False)(_state(), x, y, w)
    s2, m2 = make_train_step(donate=False, accum_steps=4)(_state(), x, y, w)
    np.testing.assert_allclose(
        float(m1["train_loss"]), float(m2["train_loss"]), atol=1e-6
    )
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-6
        ),
        s1.params,
        s2.params,
    )


def test_accum_respects_weights(rng):
    """Zero-weighted (padding) rows must not influence the update, exactly
    as in the unaccumulated step."""
    x, y, w = _batch(rng, 16)
    w = w.at[12:].set(0.0)
    s1, _ = make_train_step(donate=False)(_state(), x, y, w)
    s2, _ = make_train_step(donate=False, accum_steps=4)(_state(), x, y, w)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-6
        ),
        s1.params,
        s2.params,
    )


def test_epoch_scan_accum_groups_batches(rng):
    """Epoch scan with accum=2 over [4, B] == 2 accumulated updates over
    the concatenated pairs."""
    xs = jnp.asarray(rng.standard_normal((4, 8, 5)), jnp.float32)
    ys = jnp.asarray(rng.integers(0, 2, (4, 8)), jnp.int32)
    ws = jnp.ones((4, 8), jnp.float32)

    s_scan, losses = make_epoch_train_step(donate=False, accum_steps=2)(
        _state(), xs, ys, ws
    )
    assert losses.shape == (2,)

    s_ref = _state()
    step = make_train_step(donate=False, accum_steps=2)
    for g in range(2):
        x = xs[2 * g:2 * g + 2].reshape(16, 5)
        y = ys[2 * g:2 * g + 2].reshape(16)
        w = ws[2 * g:2 * g + 2].reshape(16)
        s_ref, _ = step(s_ref, x, y, w)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-6
        ),
        s_scan.params,
        s_ref.params,
    )


def test_trainer_grad_accum_e2e(processed_dir, tmp_path):
    """Trainer.fit with grad_accum_steps=2: optimizer updates halve, loss
    finite, both compilation paths."""
    for use_scan in (True, False):
        cfg = RunConfig(
            data=DataConfig(
                processed_dir=processed_dir,
                models_dir=str(tmp_path / f"m_{use_scan}"),
            ),
            train=TrainConfig(
                epochs=1, batch_size=8, bf16_compute=False,
                grad_accum_steps=2, use_scan=use_scan,
            ),
        )
        tracker = LocalTracking(root=str(tmp_path / f"runs_{use_scan}"))
        res = Trainer(cfg, tracker=tracker).fit()
        assert np.isfinite(res.val_loss)
        steps = int(jax.device_get(res.state.step))
        # conftest fixture: 800 rows, 80/20 split -> 640 train rows;
        # global batch = 8/device x 8-device data axis = 64 -> 10 batches
        # -> 5 accumulated updates.
        assert steps == 5
