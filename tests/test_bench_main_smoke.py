"""End-to-end smoke of ``bench.main()`` — the exact artifact the driver
runs at end of round. The unit tests in test_val_parity.py /
test_bench_record.py pin the pieces; this pins the WIRING: the one JSON
line must land with the prior-onchip carry-forward, the val-parity
numbers, and the probe stanza all present on a CPU-fallback run (the
round-4 failure mode was precisely good pieces that never reached the
driver's record)."""

import importlib
import json

import pytest


@pytest.mark.slow
def test_bench_main_cpu_record_carries_everything(
    tmp_path, monkeypatch, capsys
):
    monkeypatch.setenv("DCT_BENCH_ROWS", "2000")
    monkeypatch.setenv("DCT_BENCH_EPOCHS", "1")
    monkeypatch.setenv("DCT_BENCH_TORCH_EPOCHS", "1")
    monkeypatch.setenv("DCT_VAL_PARITY_EPOCHS", "1")
    monkeypatch.setenv("DCT_BENCH_SCALED", "0")
    monkeypatch.setenv(
        "DCT_BENCH_PARTIAL", str(tmp_path / "BENCH_PARTIAL.json")
    )
    import bench

    bench = importlib.reload(bench)
    monkeypatch.setattr(bench, "_REPO_ROOT", str(tmp_path))
    # Plant prior on-chip evidence the CPU run must carry forward.
    onchip = {"platform": "tpu", "value": 8342288.0, "mfu": 0.21,
              "generated_utc": "2026-07-31T04:00:00Z"}
    (tmp_path / "BENCH_ONCHIP_LATEST.json").write_text(json.dumps(onchip))
    (tmp_path / "ONCHIP_CAMPAIGN.jsonl").write_text(
        json.dumps({"section": "campaign", "item": "start",
                    "result": {"platform": "tpu"}}) + "\n"
        + json.dumps({"section": "mfu", "item": "base", "t": 1753934400.0,
                      "result": {"mfu": 0.21}}) + "\n"
    )
    try:
        bench.main()
    finally:
        out = capsys.readouterr().out
        monkeypatch.undo()
        importlib.reload(bench)

    record = json.loads(out.strip().splitlines()[-1])
    # The driver's contract: one JSON line, headline fields present.
    assert record["metric"] == "weather_parity_train_samples_per_sec_per_chip"
    assert record["platform"] == "cpu"
    assert record["value"] > 0
    assert record["probe"]["platform"] == "cpu"
    assert "generated_utc" in record
    # Carry-forward: verbatim record + campaign digest, provenance-labeled.
    po = record["prior_onchip"]
    assert po["source"] == "BENCH_ONCHIP_LATEST.json"
    assert po["record"] == onchip
    assert po["captured_utc"] == "2026-07-31T04:00:00Z"
    assert po["campaign"]["tpu_item_count"] == 1
    # North-star val parity: both numbers in the driver record.
    vp = record["val_parity"]
    assert vp["torch_val_loss"] > 0 and vp["jax_val_loss"] > 0
    # The partial on disk must equal the printed record (crash hedge).
    with open(tmp_path / "BENCH_PARTIAL.json") as f:
        assert json.load(f) == record
