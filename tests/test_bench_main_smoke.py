"""End-to-end smoke of ``bench.main()`` — the exact artifact the driver
runs at end of round. The unit tests in test_val_parity.py /
test_bench_record.py pin the pieces; this pins the WIRING: the one JSON
line must land with the prior-onchip carry-forward, the val-parity
numbers, and the probe stanza all present on a CPU-fallback run (the
round-4 failure mode was precisely good pieces that never reached the
driver's record)."""

import importlib
import json

import pytest


@pytest.mark.slow
def test_bench_main_cpu_record_carries_everything(
    tmp_path, monkeypatch, capsys
):
    monkeypatch.setenv("DCT_BENCH_ROWS", "2000")
    monkeypatch.setenv("DCT_BENCH_EPOCHS", "1")
    monkeypatch.setenv("DCT_BENCH_TORCH_EPOCHS", "1")
    monkeypatch.setenv("DCT_VAL_PARITY_EPOCHS", "1")
    monkeypatch.setenv("DCT_BENCH_SCALED", "0")
    # The restart_spinup leg spawns two supervised subprocess worlds
    # (~a minute); the smoke gates the WIRING, and the null marker
    # below proves skipped-not-absent. scripts/compile_cache_smoke.py
    # (the compile-cache CI job) runs the leg's machinery for real.
    monkeypatch.setenv("DCT_BENCH_SPINUP", "0")
    # Likewise cycle_freshness: the serial-vs-loop comparison runs two
    # full continuous-training rigs (~40 s); tests/test_continuous.py
    # exercises the loop machinery for real, the smoke pins the null
    # marker wiring.
    monkeypatch.setenv("DCT_BENCH_FRESHNESS", "0")
    # Likewise multi_tenant: the 2-tenant scheduler session runs in
    # tests/test_scheduler.py and the scheduler CI smoke; the bench
    # smoke pins the null-marker wiring.
    monkeypatch.setenv("DCT_BENCH_TENANTS", "0")
    # And mpmd_pipeline: the MPMD machinery runs for real in
    # tests/test_mpmd.py and the mpmd-pipeline CI smoke; the bench
    # smoke pins the null-marker wiring.
    monkeypatch.setenv("DCT_BENCH_MPMD", "0")
    # And elastic_serving: the overload A/B replay runs for real in
    # tests/test_serving_elastic.py and the elastic-serving CI smoke;
    # the bench smoke pins the null-marker wiring.
    monkeypatch.setenv("DCT_BENCH_ELASTIC", "0")
    monkeypatch.setenv(
        "DCT_BENCH_PARTIAL", str(tmp_path / "BENCH_PARTIAL.json")
    )
    import bench

    bench = importlib.reload(bench)
    monkeypatch.setattr(bench, "_REPO_ROOT", str(tmp_path))
    # Plant prior on-chip evidence the CPU run must carry forward.
    onchip = {"platform": "tpu", "value": 8342288.0, "mfu": 0.21,
              "generated_utc": "2026-07-31T04:00:00Z"}
    (tmp_path / "BENCH_ONCHIP_LATEST.json").write_text(json.dumps(onchip))
    (tmp_path / "ONCHIP_CAMPAIGN.jsonl").write_text(
        json.dumps({"section": "campaign", "item": "start",
                    "result": {"platform": "tpu"}}) + "\n"
        + json.dumps({"section": "mfu", "item": "base", "t": 1753934400.0,
                      "result": {"mfu": 0.21}}) + "\n"
    )
    try:
        bench.main()
    finally:
        out = capsys.readouterr().out
        monkeypatch.undo()
        importlib.reload(bench)

    record = json.loads(out.strip().splitlines()[-1])
    # The driver's contract: ONE JSON line, headline fields present, and
    # short enough to survive the driver's 2,000-byte stdout tail
    # (r05's 2,578 B line parsed null — VERDICT r5 item 1).
    line = out.strip().splitlines()[-1]
    assert len(line.encode()) <= 1800, len(line.encode())
    assert record["metric"] == "weather_parity_train_samples_per_sec_per_chip"
    assert record["platform"] == "cpu"
    assert record["value"] > 0
    assert record["probe"]["platform"] == "cpu"
    assert "generated_utc" in record
    # Dispatch-gap tracker: the ratio rides every record. fused/fit
    # duplicate the top-level value / trainer_loop keys byte for byte,
    # so stdout carries the ratio + mode knob only (the partial keeps
    # the full stanza — asserted below).
    gap = record["trainer_gap"]
    assert gap["fused_over_fit"] > 0
    assert gap["prefetch_spans"] == 1
    assert "fused" not in gap
    # Serving under traffic (ISSUE 7): qps + tails at >= 2 concurrency
    # levels as the columnar stdout digest, knee + both throughput
    # ratios, and the live bit-identity parity check.
    sl = record["serving_load"]
    assert len(sl["levels"]["concurrency"]) >= 2
    assert all(q > 0 for q in sl["levels"]["qps"])
    assert all(p > 0 for p in sl["levels"]["p99_ms"])
    assert sl["knee_concurrency"] in sl["levels"]["concurrency"]
    # baseline_qps is derivable (saturated / batched_over_single) and
    # yielded to fund the elastic_serving series; the partial keeps it
    # verbatim (asserted below).
    assert sl["saturated_qps"] > 0 and "baseline_qps" not in sl
    assert sl["batched_over_single"] > 0
    assert sl["score_batched_over_single"] > 1
    assert sl["parity"] is True
    # Metrics-plane cost bound (ISSUE 8): the snapshot-publish p50
    # overhead is measured every round; the flat scalar rides stdout,
    # the per-variant p50 pair stays in the partial.
    assert isinstance(sl["publish_overhead_ms"], float)
    assert "snapshot_publish" not in sl
    # Carry-forward ON STDOUT is a compact digest (headline numbers +
    # provenance); the verbatim record lives in the partial on disk.
    po = record["prior_onchip"]
    assert po["source"] == "BENCH_ONCHIP_LATEST.json"
    assert po["captured_utc"] == "2026-07-31T04:00:00Z"
    assert po["value"] == onchip["value"]
    assert po["mfu"] == onchip["mfu"]
    assert po["platform"] == "tpu"
    assert "record" not in po  # digest, not the verbatim embed
    assert po["campaign_items"] == 1
    # North-star val parity: both numbers in the driver record; the
    # protocol prose is trimmed to its BASELINE.md pointer on stdout.
    vp = record["val_parity"]
    assert vp["torch_val_loss"] > 0 and vp["jax_val_loss"] > 0
    assert vp["protocol"] == "BASELINE.md row 1"
    # The partial on disk is the VERBATIM record (crash hedge + the
    # carry-forward's full provenance), matching stdout's digest.
    # Skipped-not-absent: the gated restart_spinup / cycle_freshness
    # legs leave their null markers (DCT_BENCH_SPINUP=0 /
    # DCT_BENCH_FRESHNESS=0 above), like every skippable section.
    assert record["restart_spinup"] is None
    assert record["cycle_freshness"] is None
    assert record["multi_tenant"] is None
    assert record["mpmd_pipeline"] is None
    assert record["elastic_serving"] is None
    with open(tmp_path / "BENCH_PARTIAL.json") as f:
        partial = json.load(f)
    assert partial["trainer_gap"]["fused"] == partial["value"]
    assert partial["trainer_gap"]["fit"] > 0
    assert isinstance(partial["serving_load"]["levels"], list)
    assert partial["serving_load"]["baseline_qps"] > 0
    assert partial["serving_load"]["snapshot_publish"]["plain_p50_ms"] > 0
    assert partial["serving_load"]["snapshot_publish"]["publish_p50_ms"] > 0
    assert partial["prior_onchip"]["record"] == onchip
    assert partial["prior_onchip"]["campaign"]["tpu_item_count"] == 1
    assert "train_lightning_ddp" in partial["val_parity"]["protocol"]
    import bench as bench_now

    assert json.loads(json.dumps(
        bench_now._stdout_record(partial), default=bench_now._json_default
    )) == record
