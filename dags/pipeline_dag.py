"""DAG 3: ``distributed_data_pipeline`` — the monolithic ETL+training DAG.

Parity with reference dags/pipeline.py (same DAG id, :29-37): one @daily
graph that supersets DAGs 1+2 — ETL, output verify with size report,
per-host runtime version check, data-visibility check, the SPMD launch,
model verify, logs check (warn-only), summary report, retention cleanup,
end banner, deploy trigger.

Reference bugs intentionally NOT replicated (SURVEY §7):
- the final trigger targets ``azure_automated_rollout``, not the
  nonexistent ``azure_smart_rollout`` (pipeline.py:273);
- the retention cleanup glob matches the checkpoints we actually write
  (``weather-best-*.ckpt``), unlike pipeline.py:253-256 whose
  ``model-*.ckpt`` pattern never matched anything.
"""

from __future__ import annotations

import os
import sys
from datetime import datetime, timedelta

_REPO = os.environ.get("DCT_REPO_ROOT", os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from dct_tpu.launch.launcher import (  # noqa: E402
    build_healthcheck_script,
    build_spmd_launch_script,
    build_zombie_cleanup_script,
)
from dct_tpu.orchestration.compat import (  # noqa: E402
    DAG,
    BashOperator,
    PythonOperator,
    TriggerDagRunOperator,
)

def _abs(p: str) -> str:
    """Anchor relative paths at the repo root — Airflow BashOperators run
    in a per-task temp cwd, so bare relative defaults would never resolve."""
    return p if os.path.isabs(p) else os.path.join(_REPO, p)


HOSTS = os.environ.get("DCT_TRAIN_HOSTS", "local").split(",")
EXEC = os.environ.get("DCT_EXEC_TEMPLATE", "ssh {host} {cmd}")
TRAIN_CMD = os.environ.get("DCT_TRAIN_COMMAND", f"python3 {_REPO}/jobs/train_tpu.py")
# Continuous training: resume the optimizer trajectory each run
# (see dags/training_dag.py for the full rationale).
RESUME = os.environ.get("DCT_RESUME", "1")
# Supervised relaunch-and-resume budget (dct_tpu.resilience; see
# dags/training_dag.py for the contract). 0 = bare launch.
MAX_RESTARTS = os.environ.get("DCT_MAX_RESTARTS", "2")
RAW = _abs(os.environ.get("DCT_RAW_CSV", "data/raw/weather.csv"))
PROCESSED = _abs(os.environ.get("DCT_PROCESSED_DIR", "data/processed"))
MODELS_DIR = _abs(os.environ.get("DCT_MODELS_DIR", "data/models"))
KEEP_CHECKPOINTS = int(os.environ.get("DCT_KEEP_CHECKPOINTS", "3"))
LOCAL_MODE = HOSTS == ["local"]

default_args = {
    "owner": "dct-tpu",
    "retries": 1,
    "retry_delay": timedelta(minutes=5),
}


def print_training_summary(**context):
    """Run-metadata report (reference pipeline.py:17-27,242-246)."""
    from dct_tpu.observability import spans

    with spans.get_default().span(
        "dag.print_training_summary", component="dag"
    ):
        print("=" * 80)
        print("DISTRIBUTED PIPELINE SUMMARY")
        print(f"  execution date: {context.get('ds', 'n/a')}")
        print(f"  run id:         {context.get('run_id', 'n/a')}")
        print(f"  hosts:          {HOSTS}")
        print(f"  models dir:     {MODELS_DIR}")
        print("=" * 80)
    return "summary-complete"


with DAG(
    dag_id="distributed_data_pipeline",
    default_args=default_args,
    description="Full ETL -> TPU SPMD training -> verification pipeline",
    schedule="@daily",
    start_date=datetime(2024, 1, 1),
    catchup=False,
    tags=["etl", "training", "tpu-pipeline"],
) as dag:
    start = BashOperator(
        task_id="start_banner",
        bash_command="echo '=== DISTRIBUTED DATA PIPELINE START ==='",
    )

    etl = BashOperator(
        task_id="run_preprocessing",
        bash_command=(
            f"cd {_REPO} && DCT_RAW_CSV={RAW} DCT_PROCESSED_DIR={PROCESSED} "
            "python3 jobs/preprocess.py"
        ),
        execution_timeout=timedelta(minutes=30),
    )

    verify_etl = BashOperator(
        task_id="verify_processed_output",
        bash_command=(
            f"test -d {PROCESSED}/data.parquet && ls {PROCESSED}/data.parquet "
            f"&& du -sh {PROCESSED}/data.parquet || (echo 'ETL output missing'; exit 1)"
        ),
    )

    if LOCAL_MODE:
        check_versions = BashOperator(
            task_id="check_runtime_versions",
            bash_command=(
                "python3 -c 'import jax, flax, optax; "
                "print(f\"jax={jax.__version__} flax={flax.__version__} "
                "optax={optax.__version__} devices={jax.devices()}\")'"
            ),
        )
        check_data_visible = BashOperator(
            task_id="check_data_visibility",
            bash_command=f"test -d {PROCESSED} && echo 'Data visible'",
        )
        cleanup = BashOperator(
            task_id="cleanup_zombies",
            bash_command="pkill -9 -f '[t]rain_tpu.py' || true; sleep 2",
        )
        launch = BashOperator(
            task_id="tpu_spmd_training",
            # Run-correlation ID minted at task runtime (fresh per DAG
            # run); an externally exported DCT_RUN_ID wins. See
            # dags/training_dag.py.
            bash_command=(
                f"cd {_REPO} && "
                'DCT_RUN_ID="${DCT_RUN_ID:-dct-$(date +%s)-$$}" '
                f"DCT_RESUME={RESUME} "
                + (
                    f"python3 -m dct_tpu.resilience.supervise "
                    f"--max-restarts {MAX_RESTARTS} -- {TRAIN_CMD}"
                    if MAX_RESTARTS != "0"
                    else TRAIN_CMD
                )
            ),
            execution_timeout=timedelta(hours=3),
        )
    else:
        check_versions = BashOperator(
            task_id="check_runtime_versions",
            bash_command=build_healthcheck_script(
                HOSTS,
                exec_template=EXEC,
                check_command=(
                    "python3 -c 'import jax, flax, optax; print(jax.__version__)'"
                ),
            ),
        )
        check_data_visible = BashOperator(
            task_id="check_data_visibility",
            bash_command=build_healthcheck_script(
                HOSTS, exec_template=EXEC, check_command=f"test -d {PROCESSED}"
            ),
        )
        cleanup = BashOperator(
            task_id="cleanup_zombies",
            bash_command=build_zombie_cleanup_script(
                HOSTS, exec_template=EXEC, pattern="train_tpu.py"
            ),
        )
        launch = BashOperator(
            task_id="tpu_spmd_training",
            bash_command=build_spmd_launch_script(
                HOSTS, TRAIN_CMD, exec_template=EXEC,
                extra_env={"DCT_RESUME": RESUME},
            ),
            execution_timeout=timedelta(hours=3),
        )

    verify_model = BashOperator(
        task_id="verify_model",
        bash_command=(
            f"ls {MODELS_DIR}/weather-best-*.ckpt > /dev/null 2>&1 "
            f"|| ls {MODELS_DIR}/*.ckpt > /dev/null 2>&1 "
            "&& echo 'Checkpoint present' || (echo 'No checkpoint'; exit 1)"
        ),
    )

    check_logs = BashOperator(
        task_id="check_tracking_logs",
        bash_command=(
            f"test -d {_abs('mlruns_local')} && echo 'Local tracking runs present' "
            "|| echo 'WARNING: no local tracking dir (MLflow server mode?)'"
        ),
    )

    summary = PythonOperator(
        task_id="training_summary",
        python_callable=print_training_summary,
    )

    cleanup_old = BashOperator(
        task_id="cleanup_old_checkpoints",
        # Keep the newest N best-checkpoints; glob matches real filenames
        # (fixes reference pipeline.py:253-256 whose pattern matched none).
        bash_command=(
            f"ls -t {MODELS_DIR}/weather-best-*.ckpt 2>/dev/null "
            f"| tail -n +{KEEP_CHECKPOINTS + 1} | xargs -r rm -v; "
            "echo 'Retention cleanup done'"
        ),
    )

    end = BashOperator(
        task_id="end_banner",
        bash_command="echo '=== DISTRIBUTED DATA PIPELINE COMPLETE ==='",
    )

    trigger_deploy = TriggerDagRunOperator(
        task_id="trigger_deploy",
        trigger_dag_id="azure_automated_rollout",
        wait_for_completion=False,
    )

    (
        start >> etl >> verify_etl >> check_versions >> check_data_visible
        >> cleanup >> launch >> verify_model >> check_logs >> summary
        >> cleanup_old >> end >> trigger_deploy
    )
