"""DAG 1: ``spark_etl_pipeline`` — daily ETL, then trigger training.

Parity with reference dags/1_spark_etl.py: same DAG id (:14-22), @daily
schedule, retries=1 with 5-min delay, and the task chain
banner -> cluster healthcheck -> preprocess -> verify output -> trigger
``pytorch_training_pipeline`` without waiting (:67-71).

Platform-neutral: ``DCT_ETL_ENGINE=spark`` preserves the reference's
``docker exec spark-master spark-submit`` path (:41-52); the default runs
the native ETL job (same transform, no JVM) in-place. Host access is
templated so the same DAG drives compose containers or TPU-VM hosts.
"""

from __future__ import annotations

import os
import sys
from datetime import datetime, timedelta

_REPO = os.environ.get("DCT_REPO_ROOT", os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from dct_tpu.launch.launcher import remote_command  # noqa: E402
from dct_tpu.orchestration.compat import (  # noqa: E402
    DAG,
    BashOperator,
    TriggerDagRunOperator,
)


def _abs(p: str) -> str:
    """Anchor relative paths at the repo root — Airflow BashOperators run
    in a per-task temp cwd, so bare relative defaults would never resolve."""
    return p if os.path.isabs(p) else os.path.join(_REPO, p)


ENGINE = os.environ.get("DCT_ETL_ENGINE", "native")
SPARK_MASTER = os.environ.get("DCT_SPARK_MASTER_HOST", "spark-master")
EXEC = os.environ.get("DCT_EXEC_TEMPLATE", "docker exec {host} bash -c {cmd}")
RAW = _abs(os.environ.get("DCT_RAW_CSV", "data/raw/weather.csv"))
PROCESSED = _abs(os.environ.get("DCT_PROCESSED_DIR", "data/processed"))

default_args = {
    "owner": "dct-tpu",
    "retries": 1,
    "retry_delay": timedelta(minutes=5),
}

with DAG(
    dag_id="spark_etl_pipeline",
    default_args=default_args,
    description="Weather ETL: raw CSV -> normalized parquet handoff",
    schedule="@daily",
    start_date=datetime(2024, 1, 1),
    catchup=False,
    tags=["etl", "tpu-pipeline"],
) as dag:
    start = BashOperator(
        task_id="start_banner",
        bash_command="echo '=== ETL PIPELINE START ==='",
    )

    if ENGINE == "spark":
        health = BashOperator(
            task_id="check_spark_cluster",
            bash_command=remote_command(
                EXEC,
                SPARK_MASTER,
                "curl -sf http://localhost:8080 > /dev/null && echo 'Spark master healthy'",
            ),
        )
        preprocess = BashOperator(
            task_id="spark_preprocessing",
            bash_command=remote_command(
                EXEC,
                SPARK_MASTER,
                "spark-submit --master spark://spark-master:7077 "
                "--deploy-mode client --conf spark.executor.memory=1g "
                "/opt/spark/jobs/preprocess.py",
            ),
            execution_timeout=timedelta(minutes=30),
        )
    else:
        health = BashOperator(
            task_id="check_etl_runtime",
            bash_command=(
                f"python3 -c 'import pyarrow, numpy' && test -f {RAW} "
                "&& echo 'ETL runtime healthy'"
            ),
        )
        preprocess = BashOperator(
            task_id="native_preprocessing",
            bash_command=(
                f"cd {_REPO} && DCT_RAW_CSV={RAW} DCT_PROCESSED_DIR={PROCESSED} "
                "python3 jobs/preprocess.py"
            ),
            execution_timeout=timedelta(minutes=30),
        )

    verify = BashOperator(
        task_id="verify_output",
        bash_command=(
            f"test -d {PROCESSED}/data.parquet "
            f"&& echo 'Processed output present' || (echo 'ETL output missing'; exit 1)"
        ),
    )

    trigger_training = TriggerDagRunOperator(
        task_id="trigger_training_pipeline",
        trigger_dag_id="pytorch_training_pipeline",
        wait_for_completion=False,
    )

    start >> health >> preprocess >> verify >> trigger_training
