"""DAG 5: ``azure_automated_rollout`` — blue/green + shadow + canary.

Parity with reference dags/azure_auto_deploy.py (same DAG id, :188-196):
unscheduled; chain prepare_package -> evaluate_challenger ->
deploy_new_slot -> start_shadow -> soak -> start_canary -> soak ->
full_rollout, with the reference's stage parameters (mirror 20%, canary
10%, 30 s soaks, :152-197). Slot state flows between tasks via XCom
exactly like the reference (:148-149) when running under real Airflow;
the compat layer passes a shared ``ti`` dict.

Beyond parity: ``evaluate_challenger`` runs the champion/challenger
offline eval harness (dct_tpu.evaluation, docs/EVALUATION.md) and the
stage transitions consult a statistical PromotionGate — a challenger
that regresses against the deployed champion is blocked and the
endpoint auto-reverts, instead of walking to 100% on a timer.

Fixed vs reference: env vars are read individually (no ``client_id``
clobber, :15-19), and the machine itself lives in
:mod:`dct_tpu.deploy.rollout` where it is unit-tested against an in-memory
endpoint — something the reference can only exercise against live Azure.
"""

from __future__ import annotations

import os
import sys
from datetime import datetime

_REPO = os.environ.get("DCT_REPO_ROOT", os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from dct_tpu.orchestration.compat import DAG, BashOperator, PythonOperator  # noqa: E402

DEPLOY_DIR = os.environ.get("DEPLOY_DIR", "/tmp/dct_deploy_package")
ENDPOINT_NAME = os.environ.get("ENDPOINT_NAME", "weather-endpoint")
EXPERIMENT = os.environ.get("DCT_EXPERIMENT", "weather_forecasting")
SOAK_SECONDS = int(os.environ.get("DCT_SOAK_SECONDS", "30"))


def _tracker():
    from dct_tpu.tracking.client import get_tracker

    return get_tracker(
        tracking_uri=os.environ.get("MLFLOW_TRACKING_URI"), experiment=EXPERIMENT
    )


def _client():
    if os.environ.get("DCT_DEPLOY_TARGET", "azure") == "azure":
        from dct_tpu.deploy.azure import AzureEndpointClient

        return AzureEndpointClient()
    from dct_tpu.deploy.local import LocalEndpointClient

    # File-backed state: each stage runs in its own Airflow task process,
    # so the slot/traffic state must outlive any single _client() instance.
    # DCT_LOCAL_ENDPOINT_STATE pins it explicitly — REQUIRED when cycles
    # use versioned DEPLOY_DIRs (docs/EVALUATION.md), or each cycle would
    # derive a fresh empty endpoint and the gate would never see a
    # champion. Default: beside the package dir (prepare_package wipes
    # DEPLOY_DIR itself).
    return LocalEndpointClient(
        state_path=os.environ.get("DCT_LOCAL_ENDPOINT_STATE")
        or DEPLOY_DIR.rstrip("/") + "_endpoint_state.json"
    )


def _gate():
    """Promotion gate for the rollout stages (DCT_GATE=0 restores the
    reference's ungated timer walk). Constructed fresh per task process
    like the client — all state lives in the package dir / ledger."""
    from dct_tpu.evaluation.gates import PromotionGate

    return PromotionGate.from_env()


def _orchestrator():
    from dct_tpu.deploy.rollout import (
        RolloutOrchestrator,
        package_run_correlation_id,
    )

    # Each stage task is its own process; the package dir carries the
    # shipped training cycle's run-correlation ID for its stage events.
    return RolloutOrchestrator(
        _client(), ENDPOINT_NAME, soak_seconds=SOAK_SECONDS,
        run_id=package_run_correlation_id(DEPLOY_DIR),
        gate=_gate(),
    )


def _task_span(task_id: str):
    """Host-side span for one DAG task callable. Each task runs in its
    own Airflow process with no env inheritance from the training
    launch, so the span adopts the SHIPPED package's run-correlation ID
    (same rule as the rollout stage events) — the dag.* spans land on
    the same cycle trace as the deploy.* stages. Before the package
    exists (prepare_package itself) there is nothing to adopt and the
    process default applies."""
    from dct_tpu.deploy.rollout import package_run_correlation_id
    from dct_tpu.observability import spans

    rec = spans.get_default().for_trace(
        package_run_correlation_id(DEPLOY_DIR)
    )
    return rec.span(f"dag.{task_id}", component="dag")


def prepare_package(**context):
    from dct_tpu.deploy.rollout import prepare_package as prep
    from dct_tpu.observability import spans

    # No adoption here: this task CREATES the package (wiping the old
    # one), so reading run_info.json up front would attach the span to
    # the PREVIOUS cycle. The default recorder applies.
    with spans.get_default().span("dag.prepare_package", component="dag"):
        info = prep(_tracker(), DEPLOY_DIR)
    print(f"Package ready: run {info['run_id']} val_loss={info['val_loss']}")


def evaluate_challenger(**context):
    """Offline champion/challenger evaluation (dct_tpu.evaluation): run
    the harness ONCE here — the per-stage gate consults reuse the
    report cached in the package — and log the eval report to tracking
    as an artifact (its own run, tagged kind=evaluation; it logs no
    ``val_loss``, so the best-run selection query cannot see it)."""
    gate = _gate()
    with _task_span("evaluate_challenger"):
        if gate is None:
            print("Promotion gate disabled (DCT_GATE=0) — skipping eval")
            return
        champion = None
        client = _client()
        try:
            if client.endpoint_exists(ENDPOINT_NAME):
                traffic = client.get_traffic(ENDPOINT_NAME)
                live = {k: v for k, v in traffic.items() if v > 0}
                if live:
                    resolver = getattr(client, "deployment_package_dir", None)
                    if resolver is not None:
                        champion = resolver(
                            ENDPOINT_NAME, max(live, key=live.get)
                        )
        except Exception as e:  # noqa: BLE001 — champion resolution is
            print(f"Champion resolution failed: {e}")  # best-effort here;
            # the per-stage gates re-resolve and fail closed themselves.
        if not champion or os.path.abspath(champion) == os.path.abspath(
            DEPLOY_DIR
        ):
            print("No distinct deployed champion — first rollout is ungated")
            return
        from dct_tpu.evaluation.harness import EvalError

        try:
            report = gate.offline_eval(DEPLOY_DIR, champion)
        except EvalError as e:
            print(f"Offline eval unavailable: {e}")
            return
        print(
            f"Eval: champion loss={report['champion']['loss_mean']:.4f} "
            f"challenger loss={report['challenger']['loss_mean']:.4f} "
            f"mean_delta={report['mean_delta']:.4f}"
        )
        from dct_tpu.evaluation.gates import log_eval_report

        log_eval_report(
            _tracker(), report, os.path.join(DEPLOY_DIR, "eval_report.json")
        )


def deploy_new_slot(ti=None, **context):
    with _task_span("deploy_new_slot"):
        new_slot, old_slot = _orchestrator().deploy_new_slot(DEPLOY_DIR)
    if ti is not None:
        ti.xcom_push(key="new_slot", value=new_slot)
        ti.xcom_push(key="old_slot", value=old_slot or "")
    print(f"Deployed to slot {new_slot} (old: {old_slot})")


def _slots(ti):
    new_slot = ti.xcom_pull(task_ids="deploy_new_slot", key="new_slot")
    old_slot = ti.xcom_pull(task_ids="deploy_new_slot", key="old_slot") or None
    return new_slot, old_slot


def start_shadow(ti=None, **context):
    new_slot, old_slot = _slots(ti)
    with _task_span("start_shadow"):
        if old_slot is None:
            print("First deployment — skipping shadow, going straight to 100%")
            _orchestrator().full_rollout(new_slot, None)
            return
        _orchestrator().start_shadow(new_slot, old_slot)
    print(f"Shadow: {old_slot} 100% live, {new_slot} mirroring 20%")


def start_canary(ti=None, **context):
    new_slot, old_slot = _slots(ti)
    if old_slot is None:
        return
    with _task_span("start_canary"):
        _orchestrator().start_canary(new_slot, old_slot)
    print(f"Canary: {old_slot} 90% / {new_slot} 10%")


def full_rollout(ti=None, **context):
    new_slot, old_slot = _slots(ti)
    with _task_span("full_rollout"):
        _orchestrator().full_rollout(new_slot, old_slot)
    print(f"Full rollout: {new_slot} at 100%, old slot removed")


with DAG(
    dag_id="azure_automated_rollout",
    description="Automated blue/green rollout with shadow + canary stages",
    schedule=None,
    start_date=datetime(2024, 1, 1),
    catchup=False,
    tags=["deploy", "tpu-pipeline"],
) as dag:
    t_prepare = PythonOperator(task_id="prepare_package", python_callable=prepare_package)
    t_eval = PythonOperator(
        task_id="evaluate_challenger", python_callable=evaluate_challenger
    )
    t_deploy = PythonOperator(task_id="deploy_new_slot", python_callable=deploy_new_slot)
    t_shadow = PythonOperator(task_id="start_shadow", python_callable=start_shadow)
    t_soak1 = BashOperator(task_id="shadow_soak", bash_command=f"sleep {SOAK_SECONDS}")
    t_canary = PythonOperator(task_id="start_canary", python_callable=start_canary)
    t_soak2 = BashOperator(task_id="canary_soak", bash_command=f"sleep {SOAK_SECONDS}")
    t_full = PythonOperator(task_id="full_rollout", python_callable=full_rollout)

    t_prepare >> t_eval >> t_deploy >> t_shadow >> t_soak1 >> t_canary >> t_soak2 >> t_full
