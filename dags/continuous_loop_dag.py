"""DAG 6: ``continuous_always_on_loop`` — the always-on entrypoint.

The episodic DAGs (1-5) remain the reference-parity surface: one
ETL -> train -> gate -> deploy pass per trigger. This DAG is the
platform's Podracer-style replacement (docs/CONTINUOUS.md): ONE
manually-triggered task that runs ``jobs/loop.py`` — ingest watcher,
continuous training rounds under the PR 3 supervisor, and mid-run
gated promotion, all overlapped — until the task's execution timeout
(or an external SIGTERM) drains it cleanly. Airflow's task-level
SIGTERM on timeout IS the loop's drain signal: the round in flight
checkpoints, the evaluator finishes its pass, exit 0 — so a scheduled
re-trigger resumes the same trajectory and champion.

``schedule=None``: an always-on loop is started deliberately, not on a
clock — the clock is exactly what it retires. ``DCT_LOOP_MAX_WALL_S``
bounds one task occupancy when operators prefer rolling restarts over
an unbounded task.
"""

from __future__ import annotations

import os
import sys
from datetime import datetime, timedelta

_REPO = os.environ.get(
    "DCT_REPO_ROOT",
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
)
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from dct_tpu.orchestration.compat import DAG, BashOperator  # noqa: E402

#: One task occupancy (hours); the loop drains cleanly at the timeout's
#: SIGTERM and the next trigger resumes. Matches the training DAGs'
#: 3-hour execution budget by default.
LOOP_HOURS = int(os.environ.get("DCT_LOOP_DAG_HOURS", "3"))

default_args = {
    "owner": "dct-tpu",
    # No retries-on-failure backoff games: a loop that exited 1 needs an
    # operator (the supervisor already healed everything healable).
    "retries": 0,
}

with DAG(
    dag_id="continuous_always_on_loop",
    default_args=default_args,
    description=(
        "Always-on overlapped cycles: ingest -> incremental ETL -> "
        "continuous training -> mid-run gated promotion (docs/CONTINUOUS.md)"
    ),
    schedule=None,
    start_date=datetime(2024, 1, 1),
    catchup=False,
    tags=["continuous", "always-on", "tpu-pipeline"],
) as dag:
    run_loop = BashOperator(
        task_id="run_always_on_loop",
        # Run-correlation ID minted at task runtime (one per loop
        # session); an externally exported DCT_RUN_ID wins — same
        # contract as the episodic training DAGs.
        bash_command=(
            f"cd {_REPO} && "
            'DCT_RUN_ID="${DCT_RUN_ID:-dct-loop-$(date +%s)-$$}" '
            "python3 jobs/loop.py"
        ),
        execution_timeout=timedelta(hours=LOOP_HOURS),
    )
