"""DAG 4: ``azure_manual_deploy`` — force-deploy the best model at 100%.

Parity with reference dags/azure_manual_deploy.py (same DAG id, :170-173):
unscheduled, two tasks — ``prepare_package`` (best-run query -> deploy
package) and ``force_deploy`` (get-or-recreate endpoint, deploy ``blue``,
100% traffic, :137-167).

The packaging/serving generation lives in :mod:`dct_tpu.deploy.rollout` /
:mod:`dct_tpu.serving.score_gen` (tested, not inline strings like the
reference :54-134), the endpoint comes from a client factory
(``DCT_DEPLOY_TARGET=azure`` -> Azure ML, anything else -> the local
in-memory endpoint for smoke runs), and the reference's env-var clobber bug
(azure_auto_deploy.py:15-19) is structurally gone.
"""

from __future__ import annotations

import os
import sys
from datetime import datetime

_REPO = os.environ.get("DCT_REPO_ROOT", os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from dct_tpu.orchestration.compat import DAG, PythonOperator  # noqa: E402

DEPLOY_DIR = os.environ.get("DEPLOY_DIR", "/tmp/dct_deploy_package")
ENDPOINT_NAME = os.environ.get("ENDPOINT_NAME", "weather-endpoint")
EXPERIMENT = os.environ.get("DCT_EXPERIMENT", "weather_forecasting")


def _tracker():
    from dct_tpu.tracking.client import get_tracker

    return get_tracker(
        tracking_uri=os.environ.get("MLFLOW_TRACKING_URI"), experiment=EXPERIMENT
    )


def _client():
    if os.environ.get("DCT_DEPLOY_TARGET", "azure") == "azure":
        from dct_tpu.deploy.azure import AzureEndpointClient

        return AzureEndpointClient()
    from dct_tpu.deploy.local import LocalEndpointClient

    # File-backed so deploy state survives per-task processes; lives BESIDE
    # the package dir — prepare_package wipes DEPLOY_DIR.
    return LocalEndpointClient(
        state_path=DEPLOY_DIR.rstrip("/") + "_endpoint_state.json"
    )


def prepare_package(**context):
    from dct_tpu.deploy.rollout import prepare_package as prep

    info = prep(_tracker(), DEPLOY_DIR)
    print(f"Package ready: run {info['run_id']} val_loss={info['val_loss']}")
    return info["run_id"]


def force_deploy(**context):
    from dct_tpu.deploy.rollout import (
        RolloutOrchestrator,
        package_run_correlation_id,
    )

    ro = RolloutOrchestrator(
        _client(), ENDPOINT_NAME,
        run_id=package_run_correlation_id(DEPLOY_DIR),
    )
    ro.ensure_endpoint()
    ro.client.deploy(ENDPOINT_NAME, "blue", DEPLOY_DIR)
    ro.client.set_traffic(ENDPOINT_NAME, {"blue": 100})
    print(f"Deployed 'blue' at 100% on {ENDPOINT_NAME}")


with DAG(
    dag_id="azure_manual_deploy",
    description="Manual force-deploy of the best tracked model",
    schedule=None,
    start_date=datetime(2024, 1, 1),
    catchup=False,
    tags=["deploy", "tpu-pipeline"],
) as dag:
    t_prepare = PythonOperator(task_id="prepare_package", python_callable=prepare_package)
    t_deploy = PythonOperator(task_id="force_deploy", python_callable=force_deploy)
    t_prepare >> t_deploy
