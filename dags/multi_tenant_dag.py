"""DAG 7: ``multi_tenant_scheduler`` — N always-on tenants, one task.

DAG 6 (``continuous_always_on_loop``) runs ONE always-on workload per
pod; this DAG is its multi-tenant successor (docs/SCHEDULER.md): one
manually-triggered task running ``jobs/scheduler.py`` — the tenant
roster from ``DCT_TENANTS``, each tenant a full always-on loop with its
own run dirs/registry/endpoints, training rounds time-sharing the chips
through quota- and priority-arbitrated round leases — until the task's
execution timeout (or an external SIGTERM) drains every tenant cleanly.
One tenant parking (crash budget exhausted, health halt) does NOT end
the task: its peers keep their supervisors, and the task's exit code 1
at drain time tells the operator which roster entry needs attention.

``schedule=None`` for the same reason as DAG 6: an always-on session is
started deliberately. ``DCT_SCHED_MAX_WALL_S`` bounds one occupancy when
operators prefer rolling restarts.
"""

from __future__ import annotations

import os
import sys
from datetime import datetime, timedelta

_REPO = os.environ.get(
    "DCT_REPO_ROOT",
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
)
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from dct_tpu.orchestration.compat import DAG, BashOperator  # noqa: E402

#: One task occupancy (hours), matching DAG 6's budget shape.
SCHED_HOURS = int(os.environ.get("DCT_SCHED_DAG_HOURS", "3"))

default_args = {
    "owner": "dct-tpu",
    # A scheduler that exited nonzero has a PARKED tenant on record —
    # retrying the task would re-park it; an operator resolves it.
    "retries": 0,
}

with DAG(
    dag_id="multi_tenant_scheduler",
    default_args=default_args,
    description=(
        "N always-on tenants sharing one pod: quota + priority round "
        "leases, per-tenant fault isolation (docs/SCHEDULER.md)"
    ),
    schedule=None,
    start_date=datetime(2024, 1, 1),
    catchup=False,
    tags=["continuous", "multi-tenant", "tpu-pipeline"],
) as dag:
    run_scheduler = BashOperator(
        task_id="run_multi_tenant_scheduler",
        # Run-correlation ID minted at task runtime (one per session;
        # each tenant namespaces it as <run_id>-<tenant>); an external
        # DCT_RUN_ID wins, same contract as the other DAGs.
        bash_command=(
            f"cd {_REPO} && "
            'DCT_RUN_ID="${DCT_RUN_ID:-dct-sched-$(date +%s)-$$}" '
            "python3 jobs/scheduler.py"
        ),
        execution_timeout=timedelta(hours=SCHED_HOURS),
    )
