"""DAG 2: ``pytorch_training_pipeline`` — the distributed training launch.

Parity with reference dags/2_pytorch_training.py (same DAG id kept for
drop-in compatibility, :13-21): externally triggered, retries=1/5min, and
the task chain banner -> zombie cleanup -> host healthcheck -> SPMD launch
-> checkpoint verification -> trigger ``azure_automated_rollout`` (:94-98).

The launch block semantics are the reference's (:49-78) — identical script
on every host, staggered start, PID join, exit-code conjunction — but the
hosts are TPU-VM workers reached via a templated exec mechanism
(``ssh {host} {cmd}`` by default; ``docker exec {host} {cmd}`` reproduces
the compose topology), and the program is the JAX SPMD trainer
``jobs/train_tpu.py``, with rendezvous via ``jax.distributed.initialize``
instead of a gloo TCP store. ``DCT_TRAIN_HOSTS=local`` collapses the launch
to a single in-place process (single-host TPU slice: all chips on one VM,
no multi-process rendezvous needed).
"""

from __future__ import annotations

import os
import sys
from datetime import datetime, timedelta

_REPO = os.environ.get("DCT_REPO_ROOT", os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from dct_tpu.launch.launcher import (  # noqa: E402
    build_healthcheck_script,
    build_spmd_launch_script,
    build_zombie_cleanup_script,
)
from dct_tpu.orchestration.compat import (  # noqa: E402
    DAG,
    BashOperator,
    TriggerDagRunOperator,
)

def _abs(p: str) -> str:
    """Anchor relative paths at the repo root — Airflow BashOperators run
    in a per-task temp cwd, so bare relative defaults would never resolve."""
    return p if os.path.isabs(p) else os.path.join(_REPO, p)


HOSTS = os.environ.get("DCT_TRAIN_HOSTS", "local").split(",")
EXEC = os.environ.get("DCT_EXEC_TEMPLATE", "ssh {host} {cmd}")
TRAIN_CMD = os.environ.get(
    "DCT_TRAIN_COMMAND", f"python3 {_REPO}/jobs/train_tpu.py"
)
MODELS_DIR = _abs(os.environ.get("DCT_MODELS_DIR", "data/models"))
LOCAL_MODE = HOSTS == ["local"]
# Continuous training: each scheduled run RESUMES the optimizer trajectory
# from the previous run's full train state and extends it by DCT_EPOCHS
# more epochs on the refreshed data (Trainer.fit semantics) — unlike the
# reference, which re-trains from scratch daily (its fit() never gets a
# ckpt_path, reference jobs/train_lightning_ddp.py:143). Set DCT_RESUME=0
# to restore scratch-daily behavior.
RESUME = os.environ.get("DCT_RESUME", "1")
# Supervised relaunch-and-resume (dct_tpu.resilience): in local mode the
# launch runs under `python -m dct_tpu.resilience.supervise`, which
# classifies failures (crash / hang / preempted / health-halt), kills the
# world with SIGTERM->SIGKILL escalation, and relaunches with resume +
# exponential backoff up to DCT_MAX_RESTARTS. 0 disables supervision
# (the bare reference-parity launch). In script mode the same healing
# comes from Airflow's task retries: the launch script exits 75
# (EXIT_PREEMPTED) when the world was preempted gracefully and the
# cleanup/healthcheck tasks exit 22/21 for infra faults, so a red task's
# code already names the failure family.
MAX_RESTARTS = os.environ.get("DCT_MAX_RESTARTS", "2")
# Chaos drills: an exported fault plan reaches the ranks in both modes.
_RANK_EXTRA_ENV = {"DCT_RESUME": RESUME}
if os.environ.get("DCT_FAULT_SPEC"):
    _RANK_EXTRA_ENV["DCT_FAULT_SPEC"] = os.environ["DCT_FAULT_SPEC"]

default_args = {
    "owner": "dct-tpu",
    "retries": 1,
    "retry_delay": timedelta(minutes=5),
}

with DAG(
    dag_id="pytorch_training_pipeline",
    default_args=default_args,
    description="TPU SPMD training (JAX/XLA) on the processed weather data",
    schedule=None,  # externally triggered by the ETL DAG
    start_date=datetime(2024, 1, 1),
    catchup=False,
    tags=["training", "tpu-pipeline"],
) as dag:
    start = BashOperator(
        task_id="start_banner",
        bash_command="echo '=== TPU DISTRIBUTED TRAINING START ==='",
    )

    if LOCAL_MODE:
        cleanup = BashOperator(
            task_id="cleanup_zombies",
            bash_command="pkill -9 -f '[t]rain_tpu.py' || true; sleep 2; echo 'Cleanup complete'",
        )
        health = BashOperator(
            task_id="check_tpu_hosts",
            bash_command="python3 -c 'import jax; print(jax.devices())'",
        )
        launch = BashOperator(
            task_id="tpu_spmd_training",
            # Run-correlation ID minted at TASK runtime (fresh per DAG
            # run, unlike script-build-time minting): every event record
            # of this training cycle — trainer, checkpoint, tracking —
            # carries it. An externally exported DCT_RUN_ID wins. The
            # supervisor wrapper relaunches-and-resumes crashed/hung/
            # preempted runs (DCT_MAX_RESTARTS=0 restores the bare
            # launch).
            bash_command=(
                f"cd {_REPO} && "
                'DCT_RUN_ID="${DCT_RUN_ID:-dct-$(date +%s)-$$}" '
                f"DCT_RESUME={RESUME} "
                + (
                    f"python3 -m dct_tpu.resilience.supervise "
                    f"--max-restarts {MAX_RESTARTS} -- {TRAIN_CMD}"
                    if MAX_RESTARTS != "0"
                    else TRAIN_CMD
                )
            ),
            execution_timeout=timedelta(hours=3),
        )
    else:
        cleanup = BashOperator(
            task_id="cleanup_zombies",
            bash_command=build_zombie_cleanup_script(
                HOSTS, exec_template=EXEC, pattern="train_tpu.py"
            ),
        )
        health = BashOperator(
            task_id="check_tpu_hosts",
            bash_command=build_healthcheck_script(HOSTS, exec_template=EXEC),
        )
        launch = BashOperator(
            task_id="tpu_spmd_training",
            bash_command=build_spmd_launch_script(
                HOSTS, TRAIN_CMD, exec_template=EXEC,
                extra_env=_RANK_EXTRA_ENV,
            ),
            execution_timeout=timedelta(hours=3),
        )

    verify = BashOperator(
        task_id="verify_model",
        bash_command=(
            f"ls {MODELS_DIR}/*.ckpt > /dev/null 2>&1 "
            "&& echo 'Model checkpoint present' "
            "|| (echo 'No checkpoint produced'; exit 1)"
        ),
    )

    trigger_deploy = TriggerDagRunOperator(
        task_id="trigger_azure_rollout",
        trigger_dag_id="azure_automated_rollout",
        wait_for_completion=False,
    )

    start >> cleanup >> health >> launch >> verify >> trigger_deploy
