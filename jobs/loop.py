#!/usr/bin/env python3
"""Always-on loop entry point: ``python3 jobs/loop.py``.

Runs :class:`dct_tpu.continuous.AlwaysOnLoop` from the ``DCT_*`` env
contract (``DCT_LOOP_*`` knobs; docs/CONTINUOUS.md) until SIGTERM/
SIGINT or a stop budget (``DCT_LOOP_MAX_ROUNDS`` / ``_MAX_WALL_S`` /
``_MAX_PROMOTIONS`` — smokes and benches; production leaves them 0).

SIGTERM drains cleanly: the round in flight finishes (mid-fit, the
trainer's PreemptionGuard saves a durable resume snapshot; in
supervised mode the PR 3 supervisor forwards the signal to the world),
the ingest/evaluator threads join, one final evaluator sweep covers the
last published checkpoint, and the process exits 0 with ``loop.stop``
on the event log. A relaunch resumes the trajectory and the deployed
champion unchanged — the loop is restart-transparent by construction.

Exit code: 0 on a clean drain (including SIGTERM and stop budgets),
1 when the loop stopped on an error (supervisor gave up, ETL wedged).
"""

from __future__ import annotations

import os
import signal
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)


def main() -> int:
    from dct_tpu.config import RunConfig
    from dct_tpu.continuous import AlwaysOnLoop
    from dct_tpu.utils.logging import get_logger

    log = get_logger("loop")
    cfg = RunConfig.from_env()
    loop = AlwaysOnLoop(cfg)
    log.info(
        "always-on loop starting: run_id=%s mode=%s endpoint=%s "
        "epochs/round=%d",
        loop.run_id, cfg.loop.train_mode, cfg.loop.endpoint,
        cfg.loop.epochs_per_round,
    )

    def _drain(signum, frame):
        # Idempotent: the first signal requests the drain; the trainer's
        # own PreemptionGuard (inline) or the supervisor (supervised)
        # owns the handler while a round is in flight and restores this
        # one after.
        log.info("signal %d: draining the loop", signum)
        loop.request_stop(f"signal_{signum}")

    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, _drain)

    summary = loop.run()
    log.info(
        "loop stopped: reason=%s rounds=%d promotions=%d held=%d "
        "mean_freshness_s=%s",
        summary["reason"], summary["rounds"], summary["promotions"],
        summary["held"], summary["mean_freshness_s"],
    )
    return 1 if summary.get("error") else 0


if __name__ == "__main__":
    sys.exit(main())
