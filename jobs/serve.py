#!/usr/bin/env python3
"""Local inference server CLI — the Azure endpoint contract without Azure.

Env contract (matching the other job CLIs):
  DCT_MODELS_DIR  — where checkpoints live (default data/models);
                    newest best ckpt is served, else last.ckpt
  DCT_CKPT        — serve a specific checkpoint file instead
  DCT_SERVE_HOST  — bind host (default 0.0.0.0)
  DCT_SERVE_PORT  — bind port (default 8901)

Endpoint mode — serve the LOCAL rollout endpoint instead of a raw
checkpoint (traffic-weighted blue/green routing + mirror shadowing over
the deploy DAG's persisted state):
  DCT_ENDPOINT_NAME         — endpoint to serve (enables this mode)
  DCT_LOCAL_ENDPOINT_STATE  — the rollout state JSON (same env the DAG
                              uses); stage transitions apply live

POST /score {"data": ...} -> {"probabilities": ...}; GET /healthz.
"""

from __future__ import annotations

import os
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)


def main() -> int:
    host = os.environ.get("DCT_SERVE_HOST", "0.0.0.0")
    port = int(os.environ.get("DCT_SERVE_PORT", "8901"))

    endpoint = os.environ.get("DCT_ENDPOINT_NAME")
    if endpoint:
        from dct_tpu.serving.server import make_endpoint_server

        server = make_endpoint_server(endpoint, host=host, port=port)
        print(
            f"serving rollout endpoint {endpoint!r} (state: "
            f"{server.state_path}) on http://{host}:{port} "
            "(POST /score, GET /healthz)",
            flush=True,
        )
        server.serve_forever()
        return 0

    from jobs.predict import _find_checkpoint
    from dct_tpu.serving.server import serve_forever

    models_dir = os.environ.get("DCT_MODELS_DIR", "data/models")
    ckpt = _find_checkpoint(models_dir)
    serve_forever(ckpt, host=host, port=port)
    return 0


if __name__ == "__main__":
    sys.exit(main())
