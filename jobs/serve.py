#!/usr/bin/env python3
"""Local inference server CLI — the Azure endpoint contract without Azure.

Env contract (matching the other job CLIs):
  DCT_MODELS_DIR  — where checkpoints live (default data/models);
                    newest best ckpt is served, else last.ckpt
  DCT_CKPT        — serve a specific checkpoint file instead
  DCT_SERVE_HOST  — bind host (default 0.0.0.0)
  DCT_SERVE_PORT  — bind port (default 8901)

Throughput knobs (docs/SERVING.md; ServingConfig in dct_tpu/config.py):
  DCT_SERVE_PROCS           — SO_REUSEPORT serving processes (>1 forks
                              a ServerPool; this CLI forks EARLY, before
                              any threads, so it is the safe place)
  DCT_SERVE_WORKERS / DCT_SERVE_MAX_BATCH / DCT_SERVE_BATCH_WINDOW_MS
                            — per-process micro-batcher shape
  DCT_METRICS_DIR           — metrics-plane snapshot dir (this CLI arms
                              logs/metrics by default so a /metrics
                              scrape of any pool process reports fleet
                              totals; set empty to disable)

Endpoint mode — serve the LOCAL rollout endpoint instead of a raw
checkpoint (traffic-weighted blue/green routing + mirror shadowing over
the deploy DAG's persisted state):
  DCT_ENDPOINT_NAME         — endpoint to serve (enables this mode)
  DCT_LOCAL_ENDPOINT_STATE  — the rollout state JSON (same env the DAG
                              uses); stage transitions apply live

POST /score {"data": ...} -> {"probabilities": ...}; GET /healthz.
"""

from __future__ import annotations

import os
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)


def _serve_pool(build_server, what: str, serving, host: str,
                port: int) -> int:
    """Run a multi-process ServerPool until SIGTERM/SIGINT (clean exit
    0) or until its restart budget circuit-breaks (exit 1 — a pool
    that cannot hold capacity must not sit behind a healthy-looking
    banner). The dedicated entry point arms the resilience plane by
    default: child deaths are classified and respawned with backoff
    (``DCT_SERVE_MAX_RESTARTS`` budget), and ``DCT_SERVE_AUTOSCALE=1``
    runs the closed-loop proc autoscaler off the fleet queue-depth /
    SLO-burn / shed signals (docs/SERVING.md §elasticity)."""
    import signal

    from dct_tpu.resilience.supervisor import RestartPolicy
    from dct_tpu.serving.server import ServerPool

    pool = ServerPool(
        build_server, processes=serving.processes, host=host, port=port,
        restart_policy=RestartPolicy(max_restarts=serving.max_restarts),
    )
    autoscaler = None
    publisher = None
    history_monitor = None
    if serving.autoscale:
        from dct_tpu.config import ObservabilityConfig

        obs = ObservabilityConfig.from_env()
        if not obs.metrics_dir:
            # A proc autoscaler without the metrics plane is BLIND: it
            # would read "queue 0" forever and drain a loaded pool to
            # the floor. Refuse loudly — no controller thread, no
            # unpublished gauge registry, the process state matches
            # this message.
            print(
                "[serving] DCT_SERVE_AUTOSCALE=1 needs DCT_METRICS_DIR "
                "(the fleet queue/shed signals) — autoscaler disabled",
                file=sys.stderr, flush=True,
            )
    if serving.autoscale and obs.metrics_dir:
        from dct_tpu.observability.metrics import MetricsRegistry
        from dct_tpu.serving import autoscale as _autoscale

        registry = MetricsRegistry()
        publisher = _autoscale.controller_publisher(registry)
        slo_monitor = None
        if obs.slo_spec:
            from dct_tpu.observability.slo import (
                SLOSpecError,
                SLOMonitor,
                parse_slo_spec,
            )

            try:
                specs = parse_slo_spec(obs.slo_spec)
                if specs:
                    # Alerting stays the scrape side's job: the
                    # controller only READS burn state as a signal.
                    slo_monitor = SLOMonitor(
                        specs,
                        fast_window_s=obs.slo_fast_window_s,
                        slow_window_s=obs.slo_slow_window_s,
                        burn_threshold=obs.slo_burn_threshold,
                    )
            except SLOSpecError:
                pass  # the serving children already report it loudly
        # Telemetry history (ISSUE 17): when DCT_TS_DIR arms the store
        # the pool parent runs the fleet-wide anomaly/incident monitor
        # (children each see 1/N of traffic; the parent reads it all),
        # and the autoscaler's queue/shed windows come from the same
        # on-disk history instead of between-poll deltas.
        from dct_tpu.observability import detect as _detect

        history_monitor = _detect.arm_from_env(
            registry=registry, emit=_autoscale.emit_default,
        )
        autoscaler = _autoscale.Autoscaler.from_config(
            _autoscale.PoolScaleTarget(pool), serving,
            signal_fn=_autoscale.pool_signal_fn(
                obs.metrics_dir, stale_s=obs.metrics_stale_s,
                slo_monitor=slo_monitor,
                history=(
                    history_monitor.reader
                    if history_monitor is not None else None
                ),
            ),
            emit=_autoscale.emit_default,
            registry=registry,
        ).start()

    def _term(signum, frame):
        raise SystemExit(0)

    signal.signal(signal.SIGTERM, _term)
    print(
        f"serving {what} with {serving.processes} processes on "
        f"http://{host}:{pool.port} (POST /score, GET /healthz)",
        flush=True,
    )
    try:
        rc = pool.wait()
        if rc:
            print(
                "serving pool: worker deaths exhausted the restart "
                "budget — shutting down",
                file=sys.stderr, flush=True,
            )
        return rc
    finally:
        if autoscaler is not None:
            autoscaler.close()
        if history_monitor is not None:
            history_monitor.close()
        if publisher is not None:
            publisher.close()
        pool.close()


def main() -> int:
    from dct_tpu.config import ServingConfig

    # Persistent compile cache for the jax serving engine: configured
    # BEFORE any compile (the scorer compiles lazily on the first jax
    # flush), so endpoint spin-up disk-hits programs an earlier worker
    # — or the packaging-time warm-up — already compiled. No-op unless
    # DCT_COMPILE_CACHE arms it.
    from dct_tpu import compilecache

    compilecache.enable_from_env()

    host = os.environ.get("DCT_SERVE_HOST", "0.0.0.0")
    port = int(os.environ.get("DCT_SERVE_PORT", "8901"))
    # The dedicated serving entry point ARMS the metrics plane by
    # default (docs/OBSERVABILITY.md "Metrics plane"): every process of
    # a DCT_SERVE_PROCS pool publishes snapshots under this dir, so one
    # /metrics scrape of ANY process reports fleet totals. Library-built
    # servers stay local-only unless DCT_METRICS_DIR opts in; "" (set
    # but empty) disables explicitly.
    os.environ.setdefault("DCT_METRICS_DIR", "logs/metrics")
    serving = ServingConfig.from_env()

    endpoint = os.environ.get("DCT_ENDPOINT_NAME")
    if endpoint:
        from dct_tpu.serving.server import make_endpoint_server

        if serving.processes > 1:
            return _serve_pool(
                lambda h, p, reuse_port: make_endpoint_server(
                    endpoint, host=h, port=p, serving=serving,
                    reuse_port=reuse_port,
                ),
                f"rollout endpoint {endpoint!r}", serving, host, port,
            )
        server = make_endpoint_server(
            endpoint, host=host, port=port, serving=serving
        )
        print(
            f"serving rollout endpoint {endpoint!r} (state: "
            f"{server.state_path}) on http://{host}:{port} "
            "(POST /score, GET /healthz)",
            flush=True,
        )
        server.serve_forever()
        return 0

    from jobs.predict import _find_checkpoint
    from dct_tpu.serving.server import serve_forever

    models_dir = os.environ.get("DCT_MODELS_DIR", "data/models")
    ckpt = _find_checkpoint(models_dir)
    if serving.processes > 1:
        from dct_tpu.serving.server import make_server

        return _serve_pool(
            lambda h, p, reuse_port: make_server(
                ckpt, host=h, port=p, serving=serving,
                reuse_port=reuse_port,
            ),
            ckpt, serving, host, port,
        )
    serve_forever(ckpt, host=host, port=port)
    return 0


if __name__ == "__main__":
    sys.exit(main())
