#!/usr/bin/env python3
"""Batch inference job: processed parquet in, predictions parquet out.

The reference's only inference surface is the Azure endpoint's generated
score.py (one JSON request at a time, reference
dags/azure_manual_deploy.py:116-124); this job is the offline batch
counterpart the pipeline otherwise lacks — score a whole processed
dataset locally with the SAME numpy runtime the deployed score.py embeds
(dct_tpu/serving/runtime.py), so batch and online predictions cannot
diverge.

Env contract (DCT_* like every job):
  DCT_CKPT           checkpoint to score with (default: best weather-*.ckpt,
                     else last.ckpt, under DCT_MODELS_DIR)
  DCT_MODELS_DIR     where checkpoints live              [data/models]
  DCT_PROCESSED_DIR  Spark/native parquet dir to score   [data/processed]
  DCT_PREDICTIONS    output parquet path [data/predictions/predictions.parquet]

Sequence families score sliding windows (prediction i = forecast for the
row after window i); row families score each row. Output columns:
``prob_<class>`` per class and ``predicted`` (argmax). Multi-horizon
causal checkpoints (meta horizon H > 1) instead emit per-horizon columns
``prob_h<k>_<class>`` (k = 1..H) plus ``pred_h<k>`` for k >= 2;
``predicted`` stays the next-step (h1) argmax.
"""

from __future__ import annotations

import glob
import os
import sys

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def _find_checkpoint(models_dir: str) -> str:
    explicit = os.environ.get("DCT_CKPT")
    if explicit:
        if not os.path.exists(explicit):
            raise FileNotFoundError(f"DCT_CKPT={explicit} does not exist")
        return explicit
    best = sorted(
        glob.glob(os.path.join(models_dir, "weather-best-*.ckpt")),
        key=os.path.getmtime,
    )  # newest by mtime — the filename embeds val_loss, so a lexicographic
    # sort would pick the WORST model (the deploy DAG uses `ls -t` too)
    if best:
        return best[-1]
    last = os.path.join(models_dir, "last.ckpt")
    if os.path.exists(last):
        return last
    raise FileNotFoundError(
        f"No checkpoint under {models_dir} (expected weather-best-*.ckpt "
        "or last.ckpt; set DCT_CKPT to score a specific file)"
    )


def _score_jax(params, meta: dict, x, chunk: int):
    """Accelerator batch scoring (``DCT_PREDICT_ENGINE=jax``): rebuild
    the registry model from the checkpoint's self-describing meta, shard
    each chunk's batch over the mesh ``data`` axis (layout from the
    operator's DCT_MESH_* env, like every other entry point), and run
    the jitted forward on whatever backend is live (TPU on the product
    rig). The numpy engine stays the default — it is the serving twin;
    this one is the throughput path for dataset-scale scoring,
    parity-tested against numpy to float32 tolerance
    (tests/test_predict_job.py)."""
    import dataclasses

    import jax
    import jax.numpy as jnp

    from dct_tpu.config import MeshConfig, ModelConfig
    from dct_tpu.models.registry import (
        get_model, is_causal_model, is_sequence_model,
    )
    from dct_tpu.ops.attention import make_attention_fn
    from dct_tpu.parallel.mesh import batch_sharding, make_mesh

    family = meta.get("model", "weather_mlp")
    fields = {f.name for f in dataclasses.fields(ModelConfig)}
    cfg = ModelConfig(name=family, **{
        k: v for k, v in meta.items() if k in fields and k != "name"
    })
    mesh = make_mesh(MeshConfig.from_env())
    dtype = (
        jnp.bfloat16
        if os.environ.get("DCT_PREDICT_DTYPE", "float32") == "bfloat16"
        else jnp.float32
    )
    input_dim = int(meta["input_dim"])
    if is_sequence_model(family):
        model = get_model(
            cfg, input_dim=input_dim, compute_dtype=dtype,
            attn_fn=make_attention_fn(mesh), mesh=mesh,
        )
    else:
        model = get_model(cfg, input_dim=input_dim, compute_dtype=dtype)
    causal = is_causal_model(family)

    @jax.jit
    def forward(p, xb):
        logits = model.apply({"params": p}, xb, train=False)
        if causal:
            # The numpy twin serves the LAST position's forecast
            # (runtime._head_numpy takes h[:, -1, :]): [N, S, C] -> [N, C]
            # and multi-horizon [N, S, H, C] -> [N, H, C]. Slicing here
            # keeps the two engines' output contracts identical.
            logits = logits[:, -1]
        return jax.nn.softmax(logits, axis=-1)

    sharding = batch_sharding(mesh)
    dp = mesh.shape["data"]
    # Fixed-size, data-axis-divisible chunks (last one padded) so the
    # jitted forward traces ONCE and every device_put lays out evenly.
    chunk = max(dp, -(-chunk // dp) * dp)
    parts = []
    for start in range(0, len(x), chunk):
        piece = np.ascontiguousarray(x[start:start + chunk], np.float32)
        real = len(piece)
        pad = (chunk - real) if len(x) > chunk else ((-real) % dp)
        if pad:
            piece = np.concatenate(
                [piece, np.repeat(piece[-1:], pad, axis=0)]
            )
        out = np.asarray(
            jax.device_get(forward(params["params"],
                                   jax.device_put(piece, sharding)))
        )
        parts.append(out[:real])
    return np.concatenate(parts, axis=0)


def main() -> None:
    import pandas as pd

    from dct_tpu.data.dataset import load_processed_dataset
    from dct_tpu.data.windows import make_windows
    from dct_tpu.serving.runtime import forward_numpy, softmax_numpy
    from dct_tpu.serving.score_gen import weights_from_checkpoint

    models_dir = os.environ.get("DCT_MODELS_DIR", "data/models")
    processed = os.environ.get("DCT_PROCESSED_DIR", "data/processed")
    out_path = os.environ.get(
        "DCT_PREDICTIONS", "data/predictions/predictions.parquet"
    )

    ckpt = _find_checkpoint(models_dir)
    # One msgpack restore serves both engines: numpy flattens the tree
    # into serving weights, jax applies it directly.
    from dct_tpu.checkpoint.manager import load_checkpoint

    params, meta = load_checkpoint(ckpt)
    family = meta.get("model", "weather_mlp")
    print(f"Scoring with {ckpt} (family={family})")

    data = load_processed_dataset(processed)
    if data.input_dim != int(meta.get("input_dim", data.input_dim)):
        raise ValueError(
            f"Checkpoint expects input_dim={meta.get('input_dim')} but the "
            f"processed data has {data.input_dim} features"
        )

    from dct_tpu.serving.runtime import _SEQUENCE_FAMILIES

    if family in _SEQUENCE_FAMILIES:
        seq_len = int(meta["seq_len"])
        windows = make_windows(data, seq_len)
        x = windows.features  # strided view; chunks are copied below
        index = np.arange(seq_len, seq_len + len(windows))  # forecast row
        truth = windows.labels
    else:
        x = data.features
        index = np.arange(len(data))
        truth = data.labels

    # Chunked scoring: sequence attention materializes
    # O(chunk * heads * seq^2) scores — a whole-dataset forward would OOM
    # at exactly the scale a batch job exists for.
    chunk = int(os.environ.get("DCT_PREDICT_CHUNK", "8192"))
    engine = os.environ.get("DCT_PREDICT_ENGINE", "numpy").strip().lower()
    if engine == "jax":
        probs = _score_jax(params, meta, x, chunk)
    elif engine == "numpy":
        # The serving twin — bitwise the same math the deployed score.py
        # runs, so batch and online predictions cannot diverge.
        weights, _meta2 = weights_from_checkpoint(ckpt)
        probs_parts = []
        for start in range(0, len(x), chunk):
            piece = np.ascontiguousarray(x[start:start + chunk], np.float32)
            probs_parts.append(
                softmax_numpy(forward_numpy(weights, meta, piece))
            )
        probs = np.concatenate(probs_parts, axis=0)
    else:
        raise ValueError(
            f"DCT_PREDICT_ENGINE={engine!r} not in ('numpy', 'jax')"
        )

    frame = {"row": index}
    if probs.ndim == 3:
        # Multi-horizon causal checkpoint: probs [N, H, C]. `predicted`
        # stays the next-step (h=0) argmax so the column contract is
        # unchanged; each further horizon adds pred_h<k>/prob_h<k>_<c>.
        pred = np.argmax(probs[:, 0], axis=-1)
        frame["predicted"] = pred.astype(np.int32)
        for h in range(probs.shape[1]):
            if h > 0:
                frame[f"pred_h{h + 1}"] = np.argmax(
                    probs[:, h], axis=-1
                ).astype(np.int32)
            for c in range(probs.shape[-1]):
                frame[f"prob_h{h + 1}_{c}"] = probs[:, h, c].astype(
                    np.float32
                )
    else:
        pred = np.argmax(probs, axis=-1)
        frame["predicted"] = pred.astype(np.int32)
        for c in range(probs.shape[-1]):
            frame[f"prob_{c}"] = probs[:, c].astype(np.float32)
    if truth is not None and np.asarray(truth).ndim == 1:
        frame["label"] = np.asarray(truth, np.int32)
        acc = float((pred == np.asarray(truth)).mean())
    else:
        acc = float("nan")

    os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
    pd.DataFrame(frame).to_parquet(out_path, index=False)
    print(
        f"✓ Wrote {len(pred)} predictions to {out_path}"
        + (f" (accuracy vs recorded labels: {acc:.4f})" if acc == acc else "")
    )


if __name__ == "__main__":
    main()
