#!/usr/bin/env python3
"""Batch inference job: processed parquet in, predictions parquet out.

The reference's only inference surface is the Azure endpoint's generated
score.py (one JSON request at a time, reference
dags/azure_manual_deploy.py:116-124); this job is the offline batch
counterpart the pipeline otherwise lacks — score a whole processed
dataset locally with the SAME numpy runtime the deployed score.py embeds
(dct_tpu/serving/runtime.py), so batch and online predictions cannot
diverge.

Env contract (DCT_* like every job):
  DCT_CKPT           checkpoint to score with (default: best weather-*.ckpt,
                     else last.ckpt, under DCT_MODELS_DIR)
  DCT_MODELS_DIR     where checkpoints live              [data/models]
  DCT_PROCESSED_DIR  Spark/native parquet dir to score   [data/processed]
  DCT_PREDICTIONS    output parquet path [data/predictions/predictions.parquet]

Sequence families score sliding windows (prediction i = forecast for the
row after window i); row families score each row. Output columns:
``prob_<class>`` per class and ``predicted`` (argmax). Multi-horizon
causal checkpoints (meta horizon H > 1) instead emit per-horizon columns
``prob_h<k>_<class>`` (k = 1..H) plus ``pred_h<k>`` for k >= 2;
``predicted`` stays the next-step (h1) argmax.
"""

from __future__ import annotations

import glob
import os
import sys

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def _find_checkpoint(models_dir: str) -> str:
    explicit = os.environ.get("DCT_CKPT")
    if explicit:
        if not os.path.exists(explicit):
            raise FileNotFoundError(f"DCT_CKPT={explicit} does not exist")
        return explicit
    best = sorted(
        glob.glob(os.path.join(models_dir, "weather-best-*.ckpt")),
        key=os.path.getmtime,
    )  # newest by mtime — the filename embeds val_loss, so a lexicographic
    # sort would pick the WORST model (the deploy DAG uses `ls -t` too)
    if best:
        return best[-1]
    last = os.path.join(models_dir, "last.ckpt")
    if os.path.exists(last):
        return last
    raise FileNotFoundError(
        f"No checkpoint under {models_dir} (expected weather-best-*.ckpt "
        "or last.ckpt; set DCT_CKPT to score a specific file)"
    )


def main() -> None:
    import pandas as pd

    from dct_tpu.data.dataset import load_processed_dataset
    from dct_tpu.data.windows import make_windows
    from dct_tpu.serving.runtime import forward_numpy, softmax_numpy
    from dct_tpu.serving.score_gen import weights_from_checkpoint

    models_dir = os.environ.get("DCT_MODELS_DIR", "data/models")
    processed = os.environ.get("DCT_PROCESSED_DIR", "data/processed")
    out_path = os.environ.get(
        "DCT_PREDICTIONS", "data/predictions/predictions.parquet"
    )

    ckpt = _find_checkpoint(models_dir)
    weights, meta = weights_from_checkpoint(ckpt)
    family = meta.get("model", "weather_mlp")
    print(f"Scoring with {ckpt} (family={family})")

    data = load_processed_dataset(processed)
    if data.input_dim != int(meta.get("input_dim", data.input_dim)):
        raise ValueError(
            f"Checkpoint expects input_dim={meta.get('input_dim')} but the "
            f"processed data has {data.input_dim} features"
        )

    from dct_tpu.serving.runtime import _SEQUENCE_FAMILIES

    if family in _SEQUENCE_FAMILIES:
        seq_len = int(meta["seq_len"])
        windows = make_windows(data, seq_len)
        x = windows.features  # strided view; chunks are copied below
        index = np.arange(seq_len, seq_len + len(windows))  # forecast row
        truth = windows.labels
    else:
        x = data.features
        index = np.arange(len(data))
        truth = data.labels

    # Chunked scoring: sequence attention materializes
    # O(chunk * heads * seq^2) scores — a whole-dataset forward would OOM
    # at exactly the scale a batch job exists for.
    chunk = int(os.environ.get("DCT_PREDICT_CHUNK", "8192"))
    probs_parts = []
    for start in range(0, len(x), chunk):
        piece = np.ascontiguousarray(x[start:start + chunk], np.float32)
        probs_parts.append(softmax_numpy(forward_numpy(weights, meta, piece)))
    probs = np.concatenate(probs_parts, axis=0)

    frame = {"row": index}
    if probs.ndim == 3:
        # Multi-horizon causal checkpoint: probs [N, H, C]. `predicted`
        # stays the next-step (h=0) argmax so the column contract is
        # unchanged; each further horizon adds pred_h<k>/prob_h<k>_<c>.
        pred = np.argmax(probs[:, 0], axis=-1)
        frame["predicted"] = pred.astype(np.int32)
        for h in range(probs.shape[1]):
            if h > 0:
                frame[f"pred_h{h + 1}"] = np.argmax(
                    probs[:, h], axis=-1
                ).astype(np.int32)
            for c in range(probs.shape[-1]):
                frame[f"prob_h{h + 1}_{c}"] = probs[:, h, c].astype(
                    np.float32
                )
    else:
        pred = np.argmax(probs, axis=-1)
        frame["predicted"] = pred.astype(np.int32)
        for c in range(probs.shape[-1]):
            frame[f"prob_{c}"] = probs[:, c].astype(np.float32)
    if truth is not None and np.asarray(truth).ndim == 1:
        frame["label"] = np.asarray(truth, np.int32)
        acc = float((pred == np.asarray(truth)).mean())
    else:
        acc = float("nan")

    os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
    pd.DataFrame(frame).to_parquet(out_path, index=False)
    print(
        f"✓ Wrote {len(pred)} predictions to {out_path}"
        + (f" (accuracy vs recorded labels: {acc:.4f})" if acc == acc else "")
    )


if __name__ == "__main__":
    main()
