#!/usr/bin/env python3
"""Multi-tenant scheduler entry point: ``python3 jobs/scheduler.py``.

Runs :class:`dct_tpu.scheduler.WorkloadScheduler` from the ``DCT_*``
env contract — the tenant roster from ``DCT_TENANTS`` (inline JSON or
a tenants.json path), arbitration knobs from ``DCT_SCHED_*``
(docs/SCHEDULER.md) — until SIGTERM/SIGINT, a stop budget
(``DCT_SCHED_MAX_WALL_S`` / ``_MAX_ROUNDS``), or every tenant reaching
a terminal state.

SIGTERM drains cleanly: every tenant's in-flight round finishes (or
checkpoints under its own supervisor), each loop runs its final
evaluator sweep, and the process exits 0 with ``sched.stop`` on the
scheduler's event log. A relaunch resumes every tenant's trajectory
and deployed champion unchanged.

Exit code: 0 on a clean drain (including SIGTERM and budgets) with no
tenant parked; 1 when any tenant parked (crash budget exhausted,
health halt) or errored — an operator needs to look at THAT tenant,
the others drained fine.
"""

from __future__ import annotations

import os
import signal
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)


def main() -> int:
    from dct_tpu.scheduler import TenantSpecError, WorkloadScheduler
    from dct_tpu.utils.logging import get_logger

    log = get_logger("scheduler")
    try:
        sched = WorkloadScheduler()
    except TenantSpecError as e:
        log.error("tenant spec rejected: %s", e)
        return 2
    log.info(
        "multi-tenant scheduler starting: run_id=%s tenants=%s "
        "concurrent=%d root=%s",
        sched.run_id, [t.name for t in sched.tenants],
        sched.sched_cfg.concurrent, sched.sched_cfg.root,
    )

    def _drain(signum, frame):
        log.info("signal %d: draining all tenants", signum)
        sched.request_stop(f"signal_{signum}")

    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, _drain)

    try:
        summary = sched.run()
    except TenantSpecError as e:
        # Per-tenant validation that needs the built config (e.g. an
        # inline fault drill) rejects at start(): same contract as a
        # malformed roster — exit 2, clause named, nothing launched.
        log.error("tenant spec rejected: %s", e)
        return 2
    parked = {
        name: t for name, t in summary["tenants"].items()
        if t.get("state") == "parked" or t.get("error")
    }
    log.info(
        "scheduler stopped: reason=%s rounds=%d preempts=%d parked=%s",
        summary["reason"], summary["total_rounds"], summary["preempts"],
        sorted(parked) or "none",
    )
    return 1 if parked else 0


if __name__ == "__main__":
    sys.exit(main())
