#!/usr/bin/env python3
"""ETL job entry: weather.csv -> normalized parquet directory.

The analog of the reference Spark job (jobs/preprocess.py there): same label
encoding, same per-column z-score, same ``<out>/data.parquet`` directory
contract. Uses the real Spark cluster when pyspark is importable and
``DCT_ETL_ENGINE != native`` (the north star keeps Spark); otherwise runs the
native vectorized transform — bit-compatible output either way.
"""

from __future__ import annotations

import os
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)


def _spark_available() -> bool:
    try:
        import pyspark  # noqa: F401

        return True
    except ImportError:
        return False


def main() -> int:
    input_csv = os.environ.get("DCT_RAW_CSV", "data/raw/weather.csv")
    output_dir = os.environ.get("DCT_PROCESSED_DIR", "data/processed")
    engine = os.environ.get("DCT_ETL_ENGINE", "auto")

    print("=" * 80)
    print("Step 1: Weather Data Preprocessing (TPU-native pipeline)")
    print("=" * 80)

    if engine == "spark" or (engine == "auto" and _spark_available()):
        from dct_tpu.etl.spark_job import preprocess_with_spark

        out = preprocess_with_spark(input_csv, output_dir)
    else:
        from dct_tpu.etl.preprocess import preprocess_csv_to_parquet

        out = preprocess_csv_to_parquet(input_csv, output_dir)

    print(f"✓ Preprocessing complete: {out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
