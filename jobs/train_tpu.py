#!/usr/bin/env python3
"""SPMD training entry point — the TPU-native train_lightning_ddp.py.

The orchestrator launches this *identical* script on every TPU-VM host (the
reference launches the identical train_lightning_ddp.py in both containers,
dags/2_pytorch_training.py:49-78). Per-host behavior:

1. read rendezvous + hyperparameters from env (reference contract honored:
   WORLD_SIZE / NODE_RANK / MASTER_ADDR / MASTER_PORT / MLFLOW_TRACKING_URI);
2. ``jax.distributed.initialize()`` when WORLD_SIZE > 1;
3. run the Trainer (jit + mesh; XLA collectives replace gloo);
4. coordinator uploads the best checkpoint to the tracking store under
   ``best_checkpoints`` (jobs/train_lightning_ddp.py:146-164 analog).

Exit code is 0 only on full success — the orchestration layer's exit-code
conjunction over hosts (dags/2_pytorch_training.py:62-75) works unchanged.
"""

from __future__ import annotations

import os
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)


def main() -> int:
    from dct_tpu.config import RunConfig
    from dct_tpu.parallel.distributed import initialize_from_env
    from dct_tpu.train.trainer import Trainer
    from dct_tpu.utils.logging import get_logger

    cfg = RunConfig.from_env()
    initialize_from_env(cfg.dist)

    log = get_logger("train_tpu")
    import jax

    from dct_tpu.observability.events import current_run_id

    # Correlation ID: launcher-minted via DCT_RUN_ID, or minted here for
    # an unlaunched (ad-hoc) run. Logged first so a human can jump from
    # the Airflow task log into the structured event log with one grep.
    run_id = cfg.obs.run_id or current_run_id()
    log.info(
        "run_id=%s devices=%d processes=%d process_index=%d platform=%s",
        run_id,
        jax.device_count(),
        jax.process_count(),
        jax.process_index(),
        jax.devices()[0].platform,
    )

    from dct_tpu.observability.health import TrainingHealthError
    from dct_tpu.resilience import (
        EXIT_HEALTH_HALT,
        EXIT_PREEMPTED,
        PreemptedError,
    )

    trainer = Trainer(cfg)
    try:
        result = trainer.fit()
    except PreemptedError as e:
        # Graceful preemption: the resume checkpoint is durable. The
        # distinct code tells the supervisor "resumable, not failed" —
        # relaunch with DCT_RESUME=1, no restart budget consumed.
        log.warning("preempted: %s", e)
        return EXIT_PREEMPTED
    except TrainingHealthError as e:
        # Health halt: deterministic — a relaunch from the same
        # checkpoint re-diverges, so the supervisor must NOT retry.
        log.error("training-health halt: %s", e)
        return EXIT_HEALTH_HALT

    log.info(
        "done: val_loss=%.4f val_acc=%.4f samples/sec=%.1f best=%s",
        result.val_loss,
        result.val_acc,
        result.samples_per_sec,
        result.best_model_path,
    )
    # Only the coordinator writes checkpoints; workers succeed iff training
    # completed (they'd have raised otherwise). Checking the file on every
    # rank would fail all multi-host runs at the orchestrator's exit-code
    # conjunction.
    if jax.process_index() == 0 and not (
        result.best_model_path and os.path.exists(result.best_model_path)
    ):
        log.error("CRITICAL: no model file produced")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
