"""Airflow compatibility layer.

The DAG files under ``dags/`` define the same control plane as the
reference's five DAGs (SURVEY §2.1). On a real Airflow deployment
(apache/airflow images, reference Dockerfile:2) they import the real
operators; in hermetic environments (this repo's CI, TPU-VM smoke tests)
they fall back to these structural stand-ins, which record the task graph,
commands, and callables so tests can validate DAG wiring and execute Python
tasks without an Airflow installation. The surface covered is exactly what
the five DAGs use: DAG (context manager), BashOperator, PythonOperator,
TriggerDagRunOperator, and ``>>`` chaining.

The stand-ins are STRICT about the API surface: constructor kwargs (and
``default_args`` keys) are validated against the Airflow 2.7 signatures
(the version the Dockerfile pins, reference Dockerfile:2), and the
pre-2.4 ``schedule_interval`` parameter raises the same deprecation
warning the real scheduler logs — so a DAG file that would trip on a real
2.7 DagBag import fails HERE, in tests, not on the production scheduler.
Airflow cannot be installed in hermetic rigs; this validation is the
strongest available stand-in for a real ``airflow dags list`` check (the
Airflow image itself still exists for deployments that can build it).
"""

from __future__ import annotations

import inspect
import subprocess
import warnings
from typing import Any, Callable

# Airflow 2.7 API surfaces (airflow.models.dag.DAG and BaseOperator
# keyword parameters, trimmed to realistic DAG-file usage; an unknown
# kwarg raises TypeError exactly like the real constructors).
_DAG_PARAMS = frozenset({
    "description", "schedule", "schedule_interval", "timetable",
    "start_date", "end_date", "full_filepath", "template_searchpath",
    "template_undefined", "user_defined_macros", "user_defined_filters",
    "default_args", "concurrency", "max_active_tasks", "max_active_runs",
    "dagrun_timeout", "sla_miss_callback", "default_view", "orientation",
    "catchup", "on_success_callback", "on_failure_callback", "doc_md",
    "params", "access_control", "is_paused_upon_creation", "jinja_environment_kwargs",
    "render_template_as_native_obj", "tags", "owner_links", "auto_register",
    "fail_stop",
})
_BASE_OPERATOR_PARAMS = frozenset({
    "owner", "email", "email_on_retry", "email_on_failure", "retries",
    "retry_delay", "retry_exponential_backoff", "max_retry_delay",
    "start_date", "end_date", "depends_on_past", "ignore_first_depends_on_past",
    "wait_for_past_depends_before_skipping", "wait_for_downstream",
    "dag", "params", "default_args", "priority_weight", "weight_rule",
    "queue", "pool", "pool_slots", "sla", "execution_timeout",
    "on_execute_callback", "on_failure_callback", "on_success_callback",
    "on_retry_callback", "pre_execute", "post_execute", "trigger_rule",
    "resources", "run_as_user", "task_concurrency", "max_active_tis_per_dag",
    "max_active_tis_per_dagrun", "executor_config", "do_xcom_push",
    "multiple_outputs", "inlets", "outlets", "task_group", "doc", "doc_md",
    "doc_json", "doc_yaml", "doc_rst",
})
_OPERATOR_EXTRA_PARAMS = {
    "BashOperator": frozenset({
        "env", "append_env", "output_encoding", "skip_on_exit_code", "cwd",
    }),
    "PythonOperator": frozenset({
        "op_args", "op_kwargs", "templates_dict", "templates_exts",
        "show_return_value_in_logs",
    }),
    "TriggerDagRunOperator": frozenset({
        "trigger_run_id", "conf", "logical_date", "execution_date",
        "reset_dag_run", "wait_for_completion", "poke_interval",
        "allowed_states", "failed_states", "deferrable",
    }),
}


def _validate_kwargs(ctor: str, kwargs: dict, allowed: frozenset) -> None:
    unknown = set(kwargs) - allowed
    if unknown:
        raise TypeError(
            f"{ctor}() got unexpected keyword argument(s) "
            f"{sorted(unknown)} — not part of the Airflow 2.7 API"
        )

try:  # pragma: no cover - exercised only on real Airflow images
    from airflow import DAG  # type: ignore
    from airflow.operators.bash import BashOperator  # type: ignore
    from airflow.operators.python import PythonOperator  # type: ignore
    from airflow.operators.trigger_dagrun import TriggerDagRunOperator  # type: ignore

    AIRFLOW_AVAILABLE = True
except ImportError:
    AIRFLOW_AVAILABLE = False

    _DAG_REGISTRY: dict[str, "DAG"] = {}
    _CURRENT: list["DAG"] = []

    class _TaskInstance:
        """Stand-in for Airflow's ``ti``: XCom push/pull against the DAG's
        shared per-run store, so task-to-task state flow (e.g. the rollout
        DAG's slot handoff) works when DAGs execute through this layer."""

        def __init__(self, store: dict, task_id: str):
            self._store = store
            self.task_id = task_id

        def xcom_push(self, key: str, value: Any) -> None:
            self._store[(self.task_id, key)] = value

        def xcom_pull(self, task_ids: str | None = None, key: str = "return_value"):
            return self._store.get((task_ids or self.task_id, key))

    class _Task:
        def __init__(self, task_id: str, **kwargs: Any):
            extra = _OPERATOR_EXTRA_PARAMS.get(type(self).__name__, frozenset())
            _validate_kwargs(
                type(self).__name__, kwargs, _BASE_OPERATOR_PARAMS | extra
            )
            self.task_id = task_id
            self.kwargs = kwargs
            self.downstream: list[_Task] = []
            self.upstream: list[_Task] = []
            self.dag = _CURRENT[-1] if _CURRENT else None
            if _CURRENT:
                _CURRENT[-1].tasks[task_id] = self

        def __rshift__(self, other):
            others = other if isinstance(other, (list, tuple)) else [other]
            for o in others:
                self.downstream.append(o)
                o.upstream.append(self)
            return other

        def __rrshift__(self, other):
            other.__rshift__(self)
            return self

    class BashOperator(_Task):
        def __init__(self, task_id: str, bash_command: str, **kwargs: Any):
            super().__init__(task_id, **kwargs)
            self.bash_command = bash_command

        def execute(self, context: dict | None = None) -> int:
            """Run the command like Airflow's BashOperator (bash -c)."""
            proc = subprocess.run(["bash", "-c", self.bash_command])
            if proc.returncode != 0:
                raise RuntimeError(
                    f"Task {self.task_id} failed with exit {proc.returncode}"
                )
            return proc.returncode

    class PythonOperator(_Task):
        def __init__(
            self, task_id: str, python_callable: Callable, **kwargs: Any
        ):
            super().__init__(task_id, **kwargs)
            self.python_callable = python_callable

        def execute(self, context: dict | None = None):
            """Call like Airflow: supply ``ti`` (backed by the DAG's shared
            XCom store) and pass only the kwargs the callable accepts."""
            ctx = dict(context or {})
            if "ti" not in ctx and self.dag is not None:
                ctx["ti"] = _TaskInstance(self.dag.xcom_store, self.task_id)
            sig = inspect.signature(self.python_callable)
            accepts_var_kw = any(
                p.kind is inspect.Parameter.VAR_KEYWORD
                for p in sig.parameters.values()
            )
            if not accepts_var_kw:
                ctx = {k: v for k, v in ctx.items() if k in sig.parameters}
            return self.python_callable(**ctx)

    class TriggerDagRunOperator(_Task):
        def __init__(self, task_id: str, trigger_dag_id: str, **kwargs: Any):
            super().__init__(task_id, **kwargs)
            self.trigger_dag_id = trigger_dag_id

    class DAG:
        def __init__(self, dag_id: str, **kwargs: Any):
            _validate_kwargs("DAG", kwargs, _DAG_PARAMS)
            if "schedule_interval" in kwargs:
                # Airflow 2.7 still accepts it but logs RemovedInAirflow3;
                # surfacing it as a warning keeps DAG files honest before
                # they meet a real scheduler.
                warnings.warn(
                    "schedule_interval is deprecated since Airflow 2.4; "
                    "use schedule=",
                    DeprecationWarning,
                    stacklevel=2,
                )
            # Real Airflow forwards default_args to EACH operator ctor, so
            # operator-specific keys (env, op_kwargs, conf, ...) are legal
            # there — validate against the union, not BaseOperator alone.
            allowed_defaults = _BASE_OPERATOR_PARAMS.union(
                *_OPERATOR_EXTRA_PARAMS.values()
            )
            bad = set(kwargs.get("default_args") or {}) - allowed_defaults
            if bad:
                raise TypeError(
                    f"DAG default_args contain non-operator key(s) "
                    f"{sorted(bad)} — not part of the Airflow 2.7 "
                    "operator APIs"
                )
            self.dag_id = dag_id
            self.kwargs = kwargs
            self.tasks: dict[str, _Task] = {}
            # Shared XCom store for tasks executed through this layer
            # ((task_id, key) -> value); one logical "run" per process.
            self.xcom_store: dict = {}
            _DAG_REGISTRY[dag_id] = self

        def __enter__(self):
            _CURRENT.append(self)
            return self

        def __exit__(self, *exc):
            _CURRENT.pop()
            return False

        @staticmethod
        def registry() -> dict[str, "DAG"]:
            return _DAG_REGISTRY

        def topological_order(self) -> list[str]:
            order: list[str] = []
            seen: set[str] = set()

            def visit(t):
                if t.task_id in seen:
                    return
                for up in t.upstream:
                    visit(up)
                seen.add(t.task_id)
                order.append(t.task_id)

            for t in self.tasks.values():
                visit(t)
            return order
