from dct_tpu.orchestration.compat import (  # noqa: F401
    DAG,
    BashOperator,
    PythonOperator,
    TriggerDagRunOperator,
    AIRFLOW_AVAILABLE,
)
