"""SPMD launch: the orchestrator-side process-control machinery.

The reference's launcher is an Airflow BashOperator that ``docker exec``s
the identical training script into both trainer containers, backgrounded,
staggered by ``sleep 5``, then ``wait``s on both PIDs and requires exit 0
from each (dags/2_pytorch_training.py:49-78), preceded by a zombie purge
(``pkill -9 -f train_lightning_ddp.py || true``, :29-38) and an
import-healthcheck (:40-46).

Here the same semantics are generated for any host-access mechanism, so
the training DAG's launch block is one call. :class:`LocalProcessLauncher`
applies identical semantics to local subprocesses, giving the multi-process
CPU rig that replaces the reference's two-container test bed (SURVEY §4).

Exec-template quoting contract: ``{cmd}`` is substituted with ONE
shlex-quoted token holding the full shell command, so the template must
hand it to something that parses a shell command string:

- ``ssh {host} {cmd}``                 — sshd's remote shell re-parses the
  joined argv, recovering the original command (this is why the token must
  be quoted exactly once: ssh flattens one quoting level);
- ``docker exec {host} bash -c {cmd}`` — docker passes argv through
  verbatim, so an explicit ``bash -c`` consumes the token;
- ``bash -c {cmd}``                    — in-place execution (tests).
"""

from __future__ import annotations

import os
import shlex
import signal
import subprocess
import time
from dataclasses import dataclass


def remote_command(exec_template: str, host: str, command: str) -> str:
    """Wrap ``command`` for one host per the quoting contract above:
    the raw command becomes a single quoted ``{cmd}`` token."""
    return exec_template.format(host=host, cmd=shlex.quote(command))


def build_zombie_cleanup_script(
    hosts: list[str],
    *,
    exec_template: str = "ssh {host} {cmd}",
    pattern: str = "train_tpu.py",
    settle_seconds: int = 2,
) -> str:
    """Kill stale ranks on every host before relaunch (the reference's
    rendezvous-port hygiene, dags/2_pytorch_training.py:29-38)."""
    lines = ["echo 'Cleaning up zombie training processes...'"]
    # Bracket the first char so the pattern cannot match the shell that
    # carries it (pkill -f would otherwise kill its own wrapping bash).
    safe_pattern = f"[{pattern[0]}]{pattern[1:]}" if pattern else pattern
    for host in hosts:
        kill = f"pkill -9 -f {shlex.quote(safe_pattern)} || true"
        lines.append(remote_command(exec_template, host, kill))
    lines.append(f"sleep {settle_seconds}")
    lines.append("echo 'Cleanup complete'")
    return "\n".join(lines)


def build_healthcheck_script(
    hosts: list[str],
    *,
    exec_template: str = "ssh {host} {cmd}",
    check_command: str = "python3 -c 'import jax; print(jax.devices())'",
) -> str:
    """Verify every host's runtime imports and sees its accelerators
    (analog of the per-node ``import torch`` check,
    dags/2_pytorch_training.py:40-46). ``set -e`` makes any host's failed
    check fail the whole task — without it bash returns the LAST command's
    status and a broken host would slip through to the SPMD launch."""
    lines = ["set -e"]
    for host in hosts:
        lines.append(f"echo 'Checking {host}...'")
        lines.append(remote_command(exec_template, host, check_command))
    lines.append("echo 'All hosts healthy'")
    return "\n".join(lines)


def build_spmd_launch_script(
    hosts: list[str],
    command: str,
    *,
    exec_template: str = "ssh {host} {cmd}",
    coordinator_port: int = 29500,
    stagger_seconds: int = 5,
    extra_env: dict[str, str] | None = None,
    fail_fast_poll_seconds: int = 2,
) -> str:
    """Generate the launch block: same program on every host, coordinator
    env injected, staggered start, fail-fast join, exit-code conjunction.

    Host 0 is the coordinator (MASTER_ADDR), mirroring the reference env
    contract (docker-compose.yml:121-124) so the same script works under
    both topologies.

    Fail-fast join: the reference ``wait``s each rank sequentially
    (dags/2_pytorch_training.py:62-75), so a dead worker leaves the
    coordinator blocked in a collective until the 3-hour task timeout.
    Here a polling loop reaps ranks as they exit and, on the first nonzero
    exit, terminates the remaining launch processes — the failure surfaces
    in seconds. (For ssh templates the kill stops the local client; any
    orphaned remote rank is covered by the next run's zombie purge, the
    same hygiene model as the reference.)
    """
    world = len(hosts)
    master = hosts[0]
    lines = [f"echo 'Launching SPMD training on {world} hosts...'", "set -m"]
    for rank, host in enumerate(hosts):
        env = {
            "MASTER_ADDR": master,
            "MASTER_PORT": str(coordinator_port),
            "NODE_RANK": str(rank),
            "WORLD_SIZE": str(world),
            **(extra_env or {}),
        }
        env_prefix = " ".join(f"{k}={shlex.quote(v)}" for k, v in env.items())
        full = f"{env_prefix} {command}"
        lines.append(remote_command(exec_template, host, full) + " &")
        lines.append(f"PID{rank}=$!")
        lines.append(f"DONE{rank}=0")
        if rank == 0 and world > 1:
            lines.append(f"sleep {stagger_seconds}")
    ranks = range(world)
    # set -m gives each background job its own process group (PGID = leader
    # PID); kill the GROUP — a plain kill of the wrapper shell is deferred
    # by bash until its foreground child finishes, leaving the actual rank
    # running. Reaped ranks are skipped via their DONE flag.
    lines.append("kill_survivors() {")
    for s in ranks:
        lines.append(
            f'  [ "$DONE{s}" -eq 0 ] && kill -- "-$PID{s}" 2>/dev/null'
        )
    lines.append("  :")
    lines.append("}")
    lines.append("FAILED=0")
    lines.append(f"REMAINING={world}")
    lines.append('while [ "$REMAINING" -gt 0 ]; do')
    for r in ranks:
        lines.extend([
            f'  if [ "$DONE{r}" -eq 0 ] && ! kill -0 "$PID{r}" 2>/dev/null; then',
            f'    wait "$PID{r}"; RC{r}=$?; DONE{r}=1; '
            f"REMAINING=$((REMAINING-1))",
            f'    echo "Rank {r} exited with code $RC{r}"',
            f'    if [ "$RC{r}" -ne 0 ] && [ "$FAILED" -eq 0 ]; then',
            "      FAILED=1",
            '      echo "Rank failure detected - terminating remaining ranks (fail-fast)"',
            "      kill_survivors",
            "    fi",
            "  fi",
        ])
    lines.append(
        f'  [ "$REMAINING" -gt 0 ] && sleep {fail_fast_poll_seconds}'
    )
    lines.append("done")
    conj = " && ".join(f'[ "$RC{r}" -eq 0 ]' for r in ranks)
    lines.append(
        f'if {conj}; then echo "All {world} ranks finished successfully"; '
        f'else echo "Training failed: rank exit codes: '
        + " ".join(f"$RC{r}" for r in ranks)
        + '"; exit 1; fi'
    )
    return "\n".join(lines)


def _kill_group(p: "subprocess.Popen") -> None:
    """SIGKILL a rank's whole process group (falls back to the direct
    child if the group is already gone)."""
    try:
        os.killpg(p.pid, signal.SIGKILL)
    except (ProcessLookupError, PermissionError, OSError):
        p.kill()


@dataclass
class RankResult:
    rank: int
    returncode: int


class LocalProcessLauncher:
    """The two-container rig, without containers: N local processes running
    the identical SPMD program with coordinator env, staggered start, join,
    and exit-code conjunction."""

    def __init__(
        self,
        *,
        coordinator_port: int = 29511,
        stagger_seconds: float = 1.0,
        timeout: float = 600.0,
        fail_fast: bool = True,
        poll_seconds: float = 0.2,
    ):
        self.coordinator_port = coordinator_port
        self.stagger_seconds = stagger_seconds
        self.timeout = timeout
        self.fail_fast = fail_fast
        self.poll_seconds = poll_seconds

    def cleanup_zombies(self, pattern: str) -> None:
        subprocess.run(["pkill", "-9", "-f", pattern], check=False)
        time.sleep(0.5)

    def launch(
        self,
        argv: list[str],
        *,
        world_size: int,
        env: dict[str, str] | None = None,
    ) -> list[RankResult]:
        procs: list[subprocess.Popen] = []
        base_env = dict(os.environ)
        base_env.update(env or {})
        try:
            for rank in range(world_size):
                rank_env = dict(base_env)
                rank_env.update(
                    MASTER_ADDR="127.0.0.1",
                    MASTER_PORT=str(self.coordinator_port),
                    NODE_RANK=str(rank),
                    WORLD_SIZE=str(world_size),
                )
                # Own process group per rank so a fail-fast kill reaches the
                # whole rank tree, not just the direct child.
                procs.append(
                    subprocess.Popen(argv, env=rank_env, start_new_session=True)
                )
                if rank == 0 and world_size > 1:
                    time.sleep(self.stagger_seconds)
            # Poll-based join: reap ranks as they exit; with fail_fast, the
            # first nonzero exit kills the survivors immediately instead of
            # leaving them blocked in a collective until the timeout (the
            # reference's sequential wait has exactly that failure mode,
            # dags/2_pytorch_training.py:62-75).
            codes: dict[int, int] = {}
            killed = False
            deadline = time.monotonic() + self.timeout
            while len(codes) < world_size and time.monotonic() < deadline:
                progressed = False
                for rank, p in enumerate(procs):
                    if rank in codes:
                        continue
                    rc = p.poll()
                    if rc is None:
                        continue
                    codes[rank] = rc
                    progressed = True
                    if rc != 0 and self.fail_fast and not killed:
                        killed = True
                        for q in procs:
                            if q.poll() is None:
                                _kill_group(q)
                if not progressed and len(codes) < world_size:
                    time.sleep(self.poll_seconds)
            for rank, p in enumerate(procs):
                if rank not in codes:  # deadline hit
                    # Final poll: a rank that finished during the last
                    # sleep window keeps its real exit code.
                    rc = p.poll()
                    if rc is None:
                        _kill_group(p)
                        p.wait()
                        rc = -signal.SIGKILL
                    codes[rank] = rc
            return [
                RankResult(rank=r, returncode=codes[r])
                for r in range(world_size)
            ]
        finally:
            for p in procs:
                if p.poll() is None:
                    _kill_group(p)

    @staticmethod
    def all_succeeded(results: list[RankResult]) -> bool:
        return all(r.returncode == 0 for r in results)
