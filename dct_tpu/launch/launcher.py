"""SPMD launch: the orchestrator-side process-control machinery.

The reference's launcher is an Airflow BashOperator that ``docker exec``s
the identical training script into both trainer containers, backgrounded,
staggered by ``sleep 5``, then ``wait``s on both PIDs and requires exit 0
from each (dags/2_pytorch_training.py:49-78), preceded by a zombie purge
(``pkill -9 -f train_lightning_ddp.py || true``, :29-38) and an
import-healthcheck (:40-46).

Here the same semantics are generated for any host-access mechanism, so
the training DAG's launch block is one call. :class:`LocalProcessLauncher`
applies identical semantics to local subprocesses, giving the multi-process
CPU rig that replaces the reference's two-container test bed (SURVEY §4).

Exec-template quoting contract: ``{cmd}`` is substituted with ONE
shlex-quoted token holding the full shell command, so the template must
hand it to something that parses a shell command string:

- ``ssh {host} {cmd}``                 — sshd's remote shell re-parses the
  joined argv, recovering the original command (this is why the token must
  be quoted exactly once: ssh flattens one quoting level);
- ``docker exec {host} bash -c {cmd}`` — docker passes argv through
  verbatim, so an explicit ``bash -c`` consumes the token;
- ``bash -c {cmd}``                    — in-place execution (tests).
"""

from __future__ import annotations

import json
import os
import shlex
import signal
import subprocess
import sys
import threading
import time
from dataclasses import dataclass, field

from dct_tpu.observability.events import (
    EventLog,
    mint_run_id,
    observability_enabled,
)
from dct_tpu.observability.heartbeat import HeartbeatMonitor, heartbeat_path
from dct_tpu.resilience.supervisor import (
    EXIT_INFRA_CLEANUP,
    EXIT_INFRA_HEALTHCHECK,
    RestartPolicy,
    classify_failure,
)
from dct_tpu.observability.spans import (
    SpanRecorder,
    span_file_name,
    spans_dir_from,
)


def _launcher_event_log(env: dict) -> EventLog:
    """The orchestrator-side event log, built from the SAME env the ranks
    will inherit so launcher and rank records land in one file under one
    run-correlation ID (rank=None marks orchestrator records)."""
    events_dir = env.get("DCT_EVENTS_DIR", "logs/events")
    enabled = observability_enabled(env) and bool(events_dir)
    return EventLog(
        os.path.join(events_dir, "events.jsonl") if enabled else None,
        run_id=env["DCT_RUN_ID"],
        rank=None,
    )


def _launcher_metrics_publisher(env: dict, proc: str):
    """Metrics-plane publisher for the orchestrator side (None when the
    plane is unarmed): the launcher/supervisor contributes per-rank
    progress-age gauges and restart counters to the same snapshot dir
    the serving pool and trainer publish into, so one ``/metrics``
    scrape sees them all (docs/OBSERVABILITY.md "Metrics plane")."""
    metrics_dir = env.get("DCT_METRICS_DIR") or ""
    if not metrics_dir or not observability_enabled(env):
        return None
    from dct_tpu.observability.aggregate import SnapshotPublisher
    from dct_tpu.observability.metrics import MetricsRegistry

    try:
        interval = float(env.get("DCT_METRICS_PUBLISH_S") or 2.0)
    except ValueError:
        interval = 2.0
    return SnapshotPublisher(
        MetricsRegistry(), metrics_dir, proc=proc, interval_s=interval
    )


def _launcher_span_recorder(env: dict) -> SpanRecorder:
    """Orchestrator-side span recorder over the same env the ranks
    inherit: the launch span and every rank's trainer spans share one
    trace (trace_id = the run-correlation ID)."""
    directory = (
        spans_dir_from(
            env.get("DCT_EVENTS_DIR", "logs/events"),
            env.get("DCT_SPANS_DIR", ""),
        )
        if observability_enabled(env)
        else None
    )
    rec = SpanRecorder(
        os.path.join(directory, span_file_name(None)) if directory else None,
        trace_id=env["DCT_RUN_ID"],
        rank=None,
    )
    # Parent from the SAME merged env the ranks inherit, not bare
    # os.environ: a caller passing DCT_SPAN_ID through launch(env=...)
    # (a DAG task parenting its launch) must see the launch span attach
    # under it.
    from dct_tpu.observability.spans import env_parent_span_id

    rec.root_parent = env_parent_span_id(env)
    return rec


def remote_command(exec_template: str, host: str, command: str) -> str:
    """Wrap ``command`` for one host per the quoting contract above:
    the raw command becomes a single quoted ``{cmd}`` token."""
    return exec_template.format(host=host, cmd=shlex.quote(command))


def build_zombie_cleanup_script(
    hosts: list[str],
    *,
    exec_template: str = "ssh {host} {cmd}",
    pattern: str = "train_tpu.py",
    settle_seconds: int = 2,
) -> str:
    """Kill stale ranks on every host before relaunch (the reference's
    rendezvous-port hygiene, dags/2_pytorch_training.py:29-38).

    "No zombies matched" is success (the remote ``|| true``), but a dead
    exec TRANSPORT (ssh/docker unreachable) exits ``EXIT_INFRA_CLEANUP``
    — distinct from a training failure, so the supervisor/operator sees
    "the control plane is broken", not "training crashed again".
    """
    lines = ["echo 'Cleaning up zombie training processes...'"]
    # Bracket the first char so the pattern cannot match the shell that
    # carries it (pkill -f would otherwise kill its own wrapping bash).
    safe_pattern = f"[{pattern[0]}]{pattern[1:]}" if pattern else pattern
    for host in hosts:
        kill = f"pkill -9 -f {shlex.quote(safe_pattern)} || true"
        lines.append(
            remote_command(exec_template, host, kill)
            + " || { echo "
            + shlex.quote(f"Cleanup exec transport failed on {host}")
            + f"; exit {EXIT_INFRA_CLEANUP}; }}"
        )
    lines.append(f"sleep {settle_seconds}")
    lines.append("echo 'Cleanup complete'")
    return "\n".join(lines)


def build_healthcheck_script(
    hosts: list[str],
    *,
    exec_template: str = "ssh {host} {cmd}",
    check_command: str = "python3 -c 'import jax; print(jax.devices())'",
) -> str:
    """Verify every host's runtime imports and sees its accelerators
    (analog of the per-node ``import torch`` check,
    dags/2_pytorch_training.py:40-46). ``set -e`` makes any host's failed
    check fail the whole task — without it bash returns the LAST command's
    status and a broken host would slip through to the SPMD launch.

    A failed check exits ``EXIT_INFRA_HEALTHCHECK`` (not the remote
    command's arbitrary status): the supervisor's classifier must see
    "a host is unhealthy" as infra, never as a training crash to burn
    restart budget on.
    """
    lines = ["set -e"]
    for host in hosts:
        lines.append(f"echo 'Checking {host}...'")
        lines.append(
            remote_command(exec_template, host, check_command)
            + " || { echo "
            + shlex.quote(f"Healthcheck failed on {host}")
            + f"; exit {EXIT_INFRA_HEALTHCHECK}; }}"
        )
    lines.append("echo 'All hosts healthy'")
    return "\n".join(lines)


def build_spmd_launch_script(
    hosts: list[str],
    command: str,
    *,
    exec_template: str = "ssh {host} {cmd}",
    coordinator_port: int = 29500,
    stagger_seconds: int = 5,
    extra_env: dict[str, str] | None = None,
    fail_fast_poll_seconds: int = 2,
    run_id: str | None = None,
) -> str:
    """Generate the launch block: same program on every host, coordinator
    env injected, staggered start, fail-fast join, exit-code conjunction.

    Every rank additionally receives the same ``DCT_RUN_ID``
    run-correlation ID, so one grep over the structured event log
    reconstructs the whole launch. The ID is resolved when the script
    RUNS, not when it is built (``run_id`` arg pins it; otherwise the
    runtime environment's ``DCT_RUN_ID``, else minted by the script) —
    Airflow renders BashOperator commands at DAG-parse time, and a
    parse-time mint would be shared by every run of the parsed script.
    The value is spliced into each rank's env as an unquoted ``$RUN_ID``
    expansion OUTSIDE the shlex-quoted command token, so it expands on
    the LAUNCHER host for every exec template (ssh flattens one quoting
    level; the remote shell must never see the bare variable).

    Host 0 is the coordinator (MASTER_ADDR), mirroring the reference env
    contract (docker-compose.yml:121-124) so the same script works under
    both topologies.

    Fail-fast join: the reference ``wait``s each rank sequentially
    (dags/2_pytorch_training.py:62-75), so a dead worker leaves the
    coordinator blocked in a collective until the 3-hour task timeout.
    Here a polling loop reaps ranks as they exit and, on the first nonzero
    exit, terminates the remaining launch processes — the failure surfaces
    in seconds. (For ssh templates the kill stops the local client; any
    orphaned remote rank is covered by the next run's zombie purge, the
    same hygiene model as the reference.)
    """
    world = len(hosts)
    master = hosts[0]
    # Placeholder protocol: the env prefix carries a token that survives
    # shlex.quote unchanged; after quoting, the token is replaced by
    # '"$RUN_ID"' — closing the single-quoted command token, splicing a
    # double-quoted launcher-side expansion, and reopening it. Every
    # exec template therefore ships the RESOLVED id, never the variable.
    _PH = "__DCT_RUN_ID__"
    lines = [
        f"echo 'Launching SPMD training on {world} hosts...'",
        (
            f"RUN_ID={shlex.quote(run_id)}"
            if run_id
            else 'RUN_ID="${DCT_RUN_ID:-dct-$(date +%s)-$$}"'
        ),
        # The splice below expands $RUN_ID OUTSIDE the quoted command
        # token and the remote shell re-parses the result, so the value
        # MUST be shell-inert: strip to the id alphabet (an operator's
        # 'run 2026' or a $(...) would otherwise split or execute on
        # every host), and re-mint if nothing survives.
        "RUN_ID=\"$(printf %s \"$RUN_ID\" | tr -cd 'A-Za-z0-9._-')\"",
        'RUN_ID="${RUN_ID:-dct-$$}"',
        'echo "run_id=$RUN_ID"',
        "set -m",
    ]
    for rank, host in enumerate(hosts):
        env = {
            "MASTER_ADDR": master,
            "MASTER_PORT": str(coordinator_port),
            "NODE_RANK": str(rank),
            "WORLD_SIZE": str(world),
            "DCT_RUN_ID": _PH,
            **(extra_env or {}),
        }
        env_prefix = " ".join(f"{k}={shlex.quote(v)}" for k, v in env.items())
        full = f"{env_prefix} {command}"
        launch_line = remote_command(exec_template, host, full).replace(
            _PH, "'\"$RUN_ID\"'"
        )
        lines.append(launch_line + " &")
        lines.append(f"PID{rank}=$!")
        lines.append(f"DONE{rank}=0")
        if rank == 0 and world > 1:
            lines.append(f"sleep {stagger_seconds}")
    ranks = range(world)
    # set -m gives each background job its own process group (PGID = leader
    # PID); kill the GROUP — a plain kill of the wrapper shell is deferred
    # by bash until its foreground child finishes, leaving the actual rank
    # running. Reaped ranks are skipped via their DONE flag.
    lines.append("kill_survivors() {")
    for s in ranks:
        lines.append(
            f'  [ "$DONE{s}" -eq 0 ] && kill -- "-$PID{s}" 2>/dev/null'
        )
    lines.append("  :")
    lines.append("}")
    lines.append("FAILED=0")
    lines.append(f"REMAINING={world}")
    lines.append('while [ "$REMAINING" -gt 0 ]; do')
    for r in ranks:
        lines.extend([
            f'  if [ "$DONE{r}" -eq 0 ] && ! kill -0 "$PID{r}" 2>/dev/null; then',
            f'    wait "$PID{r}"; RC{r}=$?; DONE{r}=1; '
            f"REMAINING=$((REMAINING-1))",
            f'    echo "Rank {r} exited with code $RC{r}"',
            f'    if [ "$RC{r}" -ne 0 ] && [ "$FAILED" -eq 0 ]; then',
            "      FAILED=1",
            '      echo "Rank failure detected - terminating remaining ranks (fail-fast)"',
            "      kill_survivors",
            "    fi",
            "  fi",
        ])
    lines.append(
        f'  [ "$REMAINING" -gt 0 ] && sleep {fail_fast_poll_seconds}'
    )
    lines.append("done")
    conj = " && ".join(f'[ "$RC{r}" -eq 0 ]' for r in ranks)
    # Exit-code classification (resilience.supervisor contract): a rank
    # that exited 75 (EXIT_PREEMPTED) was preempted gracefully; 143 is
    # our own fail-fast SIGTERM (kill_survivors) reaping survivors of
    # the first failure. 137 (SIGKILL) is NOT ours — this script never
    # escalates past SIGTERM — so an OOM-killed rank counts as a hard
    # failure. Only when NO rank failed hard does the script itself exit
    # 75, so Airflow retries (the script-level supervisor) see "resume
    # me" distinctly from "training crashed".
    lines.append("HARD=0; PRE=0")
    for r in ranks:
        lines.append(
            f'case "$RC{r}" in 0|143) ;; 75) PRE=1 ;; *) HARD=1 ;; esac'
        )
    lines.append(
        f'if {conj}; then echo "All {world} ranks finished successfully"; '
        f'else echo "Training failed: rank exit codes: '
        + " ".join(f"$RC{r}" for r in ranks)
        + '"; '
        + 'if [ "$HARD" -eq 0 ] && [ "$PRE" -eq 1 ]; '
        + 'then echo "World preempted - resumable"; exit 75; fi; '
        + "exit 1; fi"
    )
    return "\n".join(lines)


def _kill_group(p: "subprocess.Popen") -> None:
    """SIGKILL a rank's whole process group (falls back to the direct
    child if the group is already gone)."""
    try:
        os.killpg(p.pid, signal.SIGKILL)
    except (ProcessLookupError, PermissionError, OSError):
        p.kill()


def _term_group(p: "subprocess.Popen") -> None:
    """SIGTERM a rank's whole process group — the graceful half of the
    SIGTERM -> SIGKILL escalation: a healthy rank's PreemptionGuard gets
    its chance to save a resume checkpoint and exit EXIT_PREEMPTED; a
    wedged one is SIGKILLed when the grace window expires."""
    try:
        os.killpg(p.pid, signal.SIGTERM)
    except (ProcessLookupError, PermissionError, OSError):
        p.terminate()


@dataclass
class RankResult:
    rank: int
    returncode: int


@dataclass
class AttemptRecord:
    """One supervised launch attempt and how it ended."""

    attempt: int
    results: list
    classification: str
    wall_seconds: float


@dataclass
class SuperviseResult:
    """Outcome of :meth:`LocalProcessLauncher.supervise`."""

    results: list
    attempts: list = field(default_factory=list)
    restarts: int = 0
    success: bool = False
    classification: str = "crash"


class SupervisorTerminated(Exception):
    """The supervisor itself received SIGTERM/SIGINT: raised from the
    signal handler so launch()'s finally-block teardown runs — the
    ranks live in their own sessions (start_new_session), so a
    supervisor that dies on the default signal disposition would orphan
    them past any task-level process-group kill."""


class LocalProcessLauncher:
    """The two-container rig, without containers: N local processes running
    the identical SPMD program with coordinator env, staggered start, join,
    and exit-code conjunction.

    Observability duties (the launcher already babysits the ranks, so it
    is the natural monitor): it MINTS the run-correlation ID and passes
    it to every rank via ``DCT_RUN_ID``; it emits launcher events
    (launch_start / rank_exit / rank_stalled / launch_end) into the same
    event log the ranks write; and while joined on the ranks it scans
    their heartbeat files, REPORTING stalled/dead/straggling ranks and
    progress skew instead of waiting silently. Detection never kills: a
    stalled-but-alive rank may be paying a long compile — the operator
    signal is the point, fail-fast on real exits stays the enforcement.
    """

    def __init__(
        self,
        *,
        coordinator_port: int = 29511,
        stagger_seconds: float = 1.0,
        timeout: float = 600.0,
        fail_fast: bool = True,
        poll_seconds: float = 0.2,
        heartbeat_dir: str | None = None,
        heartbeat_stall_seconds: float = 120.0,
        heartbeat_scan_seconds: float = 5.0,
        preempt_grace_s: float = 15.0,
        stall_kill: bool = False,
    ):
        self.coordinator_port = coordinator_port
        self.stagger_seconds = stagger_seconds
        self.timeout = timeout
        self.fail_fast = fail_fast
        self.poll_seconds = poll_seconds
        self.heartbeat_dir = heartbeat_dir
        self.heartbeat_stall_seconds = heartbeat_stall_seconds
        self.heartbeat_scan_seconds = heartbeat_scan_seconds
        # SIGTERM -> SIGKILL escalation window: how long a rank being
        # torn down (fail-fast, stall-kill) gets to honor its
        # PreemptionGuard (finish the step, save, exit 75) before the
        # group is SIGKILLed.
        self.preempt_grace_s = preempt_grace_s
        # Kill the world when a rank's heartbeat goes stalled/missing
        # (supervision mode): a PID-alive rank wedged in a collective
        # blocks every peer; detection-only reporting stays the default.
        self.stall_kill = stall_kill
        # What the last launch() observed, for supervise()'s classifier.
        self._stall_killed = False
        self._timed_out = False

    def cleanup_zombies(self, pattern: str) -> None:
        subprocess.run(["pkill", "-9", "-f", pattern], check=False)
        time.sleep(0.5)

    def launch(
        self,
        argv: list[str],
        *,
        world_size: int,
        env: dict[str, str] | None = None,
        preempt_event=None,
    ) -> list[RankResult]:
        procs: list[subprocess.Popen] = []
        self._stall_killed = False
        self._timed_out = False
        base_env = dict(os.environ)
        base_env.update(env or {})
        # Correlation: one run ID for the whole launch, minted here (the
        # launcher is the minter of record) unless the caller/DAG already
        # chose one — every rank inherits it via env.
        base_env["DCT_RUN_ID"] = base_env.get("DCT_RUN_ID") or mint_run_id()
        if self.heartbeat_dir:
            base_env.setdefault("DCT_HEARTBEAT_DIR", self.heartbeat_dir)
        events = _launcher_event_log(base_env)
        events.emit(
            "launcher", "launch_start",
            world_size=world_size, argv=list(argv),
        )
        # Trace: one span for the whole launch; every rank gets its own
        # child span (spawn -> reap), and DCT_SPAN_ID hands the launch
        # span to the ranks so their trainer.fit spans nest under it
        # across the process boundary.
        tracer = _launcher_span_recorder(base_env)
        launch_span = tracer.open(
            "launcher.launch", component="launcher", world_size=world_size,
        )
        rank_spans: dict[int, object] = {}
        # Default to the SAME dir ObservabilityConfig defaults the ranks
        # to (they inherit this cwd): out of the box the monitor is
        # ARMED, not waiting for an operator to remember a knob.
        hb_dir = (
            base_env.get("DCT_HEARTBEAT_DIR")
            or self.heartbeat_dir
            or "logs/heartbeats"
        )
        # Gated on the SAME observability switch the ranks honor: with
        # DCT_OBSERVABILITY off no rank writes beats, and an ungated
        # monitor would report every healthy rank missing.
        monitor = (
            HeartbeatMonitor(
                hb_dir,
                world_size,
                stall_seconds=self.heartbeat_stall_seconds,
                run_id=base_env["DCT_RUN_ID"],
            )
            if hb_dir and observability_enabled(base_env)
            else None
        )
        # Metrics plane: per-rank PROGRESS age (seconds since step/epoch
        # last advanced — write age alone cannot tell a beating-but-
        # wedged rank from a healthy one) published as a gauge next to
        # the serving pool's snapshots.
        metrics_pub = (
            _launcher_metrics_publisher(
                base_env, f"launcher-{os.getpid()}"
            )
            if monitor is not None else None
        )
        progress_gauge = (
            metrics_pub.registry.gauge(
                "dct_rank_progress_age_seconds",
                "Seconds since each rank's heartbeat (step, epoch) last "
                "advanced (progress age, not write age).",
                agg="max",
            )
            if metrics_pub is not None else None
        )
        # Telemetry history plane (ISSUE 17): the launcher is the
        # training fleet's natural watcher — when DCT_TS_DIR arms the
        # store, it runs the anomaly detector over the ranks' live
        # metric history (loss spikes, step-time regressions, goodput
        # dips) and assembles incident bundles. None when unarmed.
        anomaly_monitor = None
        if metrics_pub is not None:
            from dct_tpu.observability import detect as _detect

            anomaly_monitor = _detect.arm_from_env(
                registry=metrics_pub.registry, emit=events.emit,
            )
        flagged: set[tuple[int, str]] = set()
        last_scan = 0.0
        try:
            for rank in range(world_size):
                rank_env = dict(base_env)
                rank_env.update(
                    MASTER_ADDR="127.0.0.1",
                    MASTER_PORT=str(self.coordinator_port),
                    NODE_RANK=str(rank),
                    WORLD_SIZE=str(world_size),
                    DCT_SPAN_ID=launch_span.span_id,
                )
                rank_spans[rank] = tracer.start(
                    "launcher.rank", component="launcher",
                    parent_id=launch_span.span_id, launched_rank=rank,
                )
                # Own process group per rank so a fail-fast kill reaches the
                # whole rank tree, not just the direct child.
                procs.append(
                    subprocess.Popen(argv, env=rank_env, start_new_session=True)
                )
                if rank == 0 and world_size > 1:
                    time.sleep(self.stagger_seconds)
            # Poll-based join: reap ranks as they exit; with fail_fast, the
            # first nonzero exit kills the survivors immediately instead of
            # leaving them blocked in a collective until the timeout (the
            # reference's sequential wait has exactly that failure mode,
            # dags/2_pytorch_training.py:62-75).
            codes: dict[int, int] = {}
            killed = False
            kill_deadline = None
            escalated = False

            def _teardown_world() -> None:
                """Graceful half of the escalation: SIGTERM every
                surviving group so healthy ranks can save-and-exit-75;
                the poll loop SIGKILLs whatever outlives the grace."""
                nonlocal killed, kill_deadline
                killed = True
                kill_deadline = time.monotonic() + self.preempt_grace_s
                for q in procs:
                    if q.poll() is None:
                        _term_group(q)

            deadline = time.monotonic() + self.timeout
            while len(codes) < world_size and time.monotonic() < deadline:
                progressed = False
                for rank, p in enumerate(procs):
                    if rank in codes:
                        continue
                    rc = p.poll()
                    if rc is None:
                        continue
                    codes[rank] = rc
                    progressed = True
                    rank_spans[rank].end(returncode=rc)
                    events.emit(
                        "launcher", "rank_exit", exited_rank=rank,
                        returncode=rc,
                    )
                    if rc != 0 and self.fail_fast and not killed:
                        _teardown_world()
                if (
                    preempt_event is not None
                    and preempt_event.is_set()
                    and not killed
                ):
                    # Cooperative preemption (the multi-tenant
                    # scheduler's lease revocation): the graceful half
                    # of the escalation — every rank's PreemptionGuard
                    # saves-and-exits-75, classifying the world
                    # "preempted" with checkpointed progress intact.
                    _teardown_world()
                if killed and not escalated and (
                    time.monotonic() >= kill_deadline
                ):
                    escalated = True
                    for q in procs:
                        if q.poll() is None:
                            _kill_group(q)
                # Liveness beyond PIDs: a rank can be alive and wedged in
                # a collective. Scan heartbeats on a slow cadence and
                # NAME stalled/missing ranks while still joined.
                if monitor is not None and (
                    time.monotonic() - last_scan >= self.heartbeat_scan_seconds
                ):
                    last_scan = time.monotonic()
                    wedged = self._flag_heartbeats(
                        monitor, codes, flagged, events,
                        progress_gauge=progress_gauge,
                        metrics_pub=metrics_pub,
                    )
                    if wedged and self.stall_kill and not killed:
                        # Supervision mode: a stalled rank blocks every
                        # peer's collectives — kill the world (escalating)
                        # and let the supervisor relaunch from checkpoint.
                        self._stall_killed = True
                        events.emit(
                            "launcher", "restart.stall_kill",
                            stalled_ranks=wedged,
                            stall_seconds=self.heartbeat_stall_seconds,
                        )
                        print(
                            f"[launcher] stall-kill: ranks {wedged} wedged "
                            "— terminating the world for relaunch",
                            file=sys.stderr, flush=True,
                        )
                        _teardown_world()
                if not progressed and len(codes) < world_size:
                    time.sleep(self.poll_seconds)
            for rank, p in enumerate(procs):
                if rank not in codes:  # deadline hit
                    # Final poll: a rank that finished during the last
                    # sleep window keeps its real exit code (and is NOT
                    # labelled timed-out — trace and event log agree).
                    rc = p.poll()
                    timed_out = rc is None
                    if timed_out:
                        self._timed_out = True
                        _kill_group(p)
                        p.wait()
                        rc = -signal.SIGKILL
                        events.emit(
                            "launcher", "rank_timeout_killed",
                            exited_rank=rank,
                        )
                    codes[rank] = rc
                    rank_spans[rank].end(returncode=rc, timeout=timed_out)
            skew = monitor.report() if monitor is not None else {}
            events.emit(
                "launcher", "launch_end",
                returncodes=[codes[r] for r in range(world_size)],
                success=all(codes[r] == 0 for r in range(world_size)),
                **{k: skew[k] for k in ("epoch_skew", "step_skew") if k in skew},
            )
            launch_span.end(
                success=all(codes[r] == 0 for r in range(world_size)),
            )
            return [
                RankResult(rank=r, returncode=codes[r])
                for r in range(world_size)
            ]
        finally:
            if anomaly_monitor is not None:
                anomaly_monitor.close()
            if metrics_pub is not None:
                # Progress age is a LIVE signal: retire the snapshot so
                # a post-run scrape never reads a frozen age as current.
                metrics_pub.close()
            live = [p for p in procs if p.poll() is None]
            if live:
                # Exception-path teardown (supervisor terminated, monitor
                # error) uses the SAME SIGTERM -> grace -> SIGKILL
                # escalation as fail-fast: a healthy rank's
                # PreemptionGuard gets its chance to save-and-exit-75
                # before the hard kill. On the normal path every rank is
                # already reaped and this costs nothing.
                for p in live:
                    _term_group(p)
                grace_deadline = time.monotonic() + self.preempt_grace_s
                while any(p.poll() is None for p in live) and (
                    time.monotonic() < grace_deadline
                ):
                    time.sleep(0.1)
                for p in live:
                    if p.poll() is None:
                        _kill_group(p)
                    # Reap: nobody polls again after this, and an
                    # unreaped kill leaves a zombie per rank in a
                    # long-lived supervisor.
                    try:
                        p.wait(timeout=5)
                    except (subprocess.TimeoutExpired, OSError):
                        pass
            # A launch that raised (Popen failure, monitor error) must
            # still record its spans — end() is idempotent, so on the
            # success path (everything already ended) this is a no-op.
            for sp in rank_spans.values():
                sp.end(error=True)
            launch_span.end(error=True)

    def _flag_heartbeats(
        self,
        monitor: HeartbeatMonitor,
        codes: dict[int, int],
        flagged: set,
        events: EventLog,
        progress_gauge=None,
        metrics_pub=None,
    ) -> list[int]:
        """One monitor pass: warn (stderr + event) once per (rank, state)
        for stalled/missing ranks that have not exited, and once per new
        epoch-skew level when ranks visibly diverge. Returns the ranks
        currently stalled/missing (alive but not progressing) so a
        stall-kill supervisor can act on them."""
        wedged: list[int] = []
        statuses = monitor.scan()
        if progress_gauge is not None:
            for s in statuses:
                # "done" ranks and reaped ranks stop advancing by
                # design — publishing their ever-growing age would page
                # on a healthy completion (report() excludes them from
                # max_progress_age_seconds for the same reason).
                if (
                    s.progress_age_seconds is not None
                    and s.state != "done"
                    and s.rank not in codes
                ):
                    progress_gauge.set(
                        round(s.progress_age_seconds, 3),
                        {"rank": s.rank},
                    )
            if metrics_pub is not None:
                metrics_pub.maybe_publish()
        for s in statuses:
            if s.rank in codes or s.state not in ("stalled", "missing"):
                continue
            wedged.append(s.rank)
            key = (s.rank, s.state)
            if key in flagged:
                continue
            flagged.add(key)
            age = f" (last beat {s.age_seconds:.0f}s ago)" if s.age_seconds else ""
            print(
                f"[launcher] rank {s.rank} heartbeat {s.state}{age} — "
                "process alive but not progressing"
                if s.state == "stalled"
                else f"[launcher] rank {s.rank} has written no heartbeat",
                file=sys.stderr, flush=True,
            )
            events.emit(
                "launcher", f"rank_{s.state}", flagged_rank=s.rank,
                age_seconds=s.age_seconds, step=s.step, epoch=s.epoch,
            )
        skew = monitor.skew(statuses)
        if skew["epoch_skew"] > 1 and ("skew", skew["epoch_skew"]) not in flagged:
            flagged.add(("skew", skew["epoch_skew"]))
            print(
                f"[launcher] straggler skew: ranks span {skew['epoch_skew']}"
                f" epochs / {skew['step_skew']} steps",
                file=sys.stderr, flush=True,
            )
            events.emit("launcher", "rank_skew", **skew)
        return wedged

    # ------------------------------------------------------------------
    def supervise(
        self,
        argv: list[str],
        *,
        world_size: int,
        env: dict[str, str] | None = None,
        max_restarts: int = 2,
        backoff_s: float = 1.0,
        backoff_factor: float = 2.0,
        jitter: float = 0.1,
        max_attempts: int = 50,
        sleep_fn=time.sleep,
        clock=time.monotonic,
        preempt_event=None,
    ) -> SuperviseResult:
        """Supervised relaunch-and-resume: run :meth:`launch` until the
        world succeeds, classifying every failure
        (:func:`dct_tpu.resilience.supervisor.classify_failure`) and
        relaunching resumable ones with exponential backoff.

        Healing semantics per classification:

        - ``preempted`` — routine (the ranks saved resume checkpoints and
          exited 75): relaunch immediately, no restart budget consumed,
          bounded only by ``max_attempts``;
        - ``crash`` / ``hang`` / ``infra`` — relaunch with backoff, up to
          ``max_restarts`` times;
        - ``health_halt`` — deterministic (a NaN'd trajectory re-diverges
          from the same checkpoint): give up immediately.

        Every relaunch sets ``DCT_RESUME=1`` so the retried world resumes
        from the last published train-state checkpoint
        (:class:`TrainStateCheckpointer` skips torn rotation dirs), and
        exports the wall clock actually LOST so far as
        ``DCT_STARTUP_RECOVERY_DEBT_S`` — the relaunched trainer books it
        as ``startup_recovery`` badput, so the cycle's goodput accounting
        is honest about what the failure cost. "Lost" means the window
        since the attempt's last durable resume checkpoint (read from its
        ``resume_state_saved`` events): checkpointed progress is RETAINED
        by the resume, not lost — in particular a graceful preemption
        after hours of training costs ~nothing. Stale heartbeat files from
        the dead attempt are cleared so the fresh monitor does not
        stall-kill the new world on yesterday's beats.

        The supervisor also forwards its OWN termination: ranks run in
        their own sessions (``start_new_session``), so a supervisor dying
        on the default SIGTERM disposition would orphan them past any
        task-level process-group kill (Airflow ``execution_timeout``).
        SIGTERM/SIGINT raise :class:`SupervisorTerminated` instead, which
        unwinds through launch()'s finally-block world teardown.
        """
        base_env = dict(env or {})
        merged = dict(os.environ)
        merged.update(base_env)
        # One run-correlation ID across every attempt: the relaunches ARE
        # the story of this cycle, and one grep must reconstruct it.
        run_id = merged.get("DCT_RUN_ID") or mint_run_id()
        base_env["DCT_RUN_ID"] = merged["DCT_RUN_ID"] = run_id
        # Compile-cache continuity across attempts: pin ONE resolved
        # cache dir into every rank env, so a relaunch disk-hits the
        # programs its dead predecessor compiled (the relaunch IS the
        # steady-state cache consumer — ROADMAP item 5). No-op unless
        # DCT_COMPILE_CACHE arms the cache.
        from dct_tpu import compilecache as _compilecache

        _compilecache.export_env(base_env, merged)
        events = _launcher_event_log(merged)
        policy = RestartPolicy(
            max_restarts=max_restarts, backoff_s=backoff_s,
            backoff_factor=backoff_factor, jitter=jitter,
        )
        events.emit(
            "launcher", "supervise_start",
            world_size=world_size, max_restarts=max_restarts,
            argv=list(argv),
        )
        # Restart accounting on the metrics plane: relaunch counts by
        # classification + the cumulative lost wall clock, published as
        # a FINAL snapshot when supervision ends (the restart history
        # outlives the supervisor — ROADMAP item 5's restart-debt
        # numbers next to the trainer's compile series).
        metrics_pub = _launcher_metrics_publisher(
            merged, f"supervisor-{os.getpid()}"
        )
        restarts_ctr = lost_gauge = None
        if metrics_pub is not None:
            restarts_ctr = metrics_pub.registry.counter(
                "dct_restarts_total",
                "Supervised world relaunches, by failure classification.",
            )
            lost_gauge = metrics_pub.registry.gauge(
                "dct_restart_lost_wall_seconds",
                "Wall seconds lost to failed attempts and backoff "
                "(handed to the relaunched trainer as startup_recovery "
                "badput).", agg="sum",
            )
        attempts: list[AttemptRecord] = []
        restarts = 0
        debt = 0.0

        def _raise_terminated(signum, frame):
            raise SupervisorTerminated(f"signal {signum}")

        prev_handlers = {}
        if threading.current_thread() is threading.main_thread():
            for sig in (signal.SIGTERM, signal.SIGINT):
                try:
                    prev_handlers[sig] = signal.signal(sig, _raise_terminated)
                except (ValueError, OSError):
                    pass
        try:
            while True:
                t0 = clock()
                t0_wall = time.time()
                results = self.launch(
                    argv, world_size=world_size, env=base_env,
                    preempt_event=preempt_event,
                )
                wall = clock() - t0
                cls = classify_failure(
                    [r.returncode for r in results],
                    stall_killed=self._stall_killed,
                    timed_out=self._timed_out,
                )
                attempts.append(
                    AttemptRecord(len(attempts) + 1, results, cls, wall)
                )
                if cls == "success":
                    events.emit(
                        "launcher", "restart.recovered" if restarts or
                        len(attempts) > 1 else "supervise_end",
                        attempts=len(attempts), restarts_used=restarts,
                        lost_wall_s=round(debt, 3),
                    )
                    return SuperviseResult(
                        results=results, attempts=attempts,
                        restarts=restarts, success=True, classification=cls,
                    )
                if (
                    preempt_event is not None
                    and preempt_event.is_set()
                    and cls == "preempted"
                ):
                    # Scheduler lease revocation, not a failure: the
                    # world checkpointed and exited 75 by contract.
                    # Returning (instead of the free preempted
                    # relaunch) hands the chips back to the grant loop;
                    # the caller's next lease resumes the trajectory.
                    events.emit(
                        "launcher", "supervise_preempted",
                        attempts=len(attempts), restarts_used=restarts,
                    )
                    return SuperviseResult(
                        results=results, attempts=attempts,
                        restarts=restarts, success=False,
                        classification="preempted",
                    )
                if not policy.allows(restarts, cls) or (
                    len(attempts) >= max_attempts
                ):
                    events.emit(
                        "launcher", "restart.gave_up",
                        classification=cls, restarts_used=restarts,
                        attempts=len(attempts),
                        returncodes=[r.returncode for r in results],
                    )
                    return SuperviseResult(
                        results=results, attempts=attempts,
                        restarts=restarts, success=False,
                        classification=cls,
                    )
                consume = cls != "preempted"
                delay = policy.delay(restarts) if consume else 0.0
                if consume:
                    restarts += 1
                debt += self._attempt_lost_seconds(
                    merged, run_id, cls, t0_wall, wall
                ) + delay
                self._clear_heartbeats(merged, world_size)
                if restarts_ctr is not None:
                    restarts_ctr.inc(1, {"classification": cls})
                    lost_gauge.set(round(debt, 3))
                    metrics_pub.publish()
                events.emit(
                    "launcher", "restart.relaunch",
                    attempt=len(attempts) + 1, classification=cls,
                    backoff_s=round(delay, 3), lost_wall_s=round(debt, 3),
                    restarts_used=restarts,
                    returncodes=[r.returncode for r in results],
                )
                # The retried run RESUMES at the last published step
                # rather than epoch 0, and books the lost window as
                # badput.
                base_env["DCT_RESUME"] = "1"
                base_env["DCT_STARTUP_RECOVERY_DEBT_S"] = f"{debt:.3f}"
                # Fault plans are per-CYCLE drills: the spec applies to
                # the first launch, the healed relaunch runs clean —
                # otherwise a resumed world restarting at the trigger
                # epoch re-fires the same fault forever and the drill can
                # never demonstrate recovery.
                base_env["DCT_FAULT_SPEC"] = ""
                if delay > 0:
                    sleep_fn(delay)
        except SupervisorTerminated:
            # launch()'s finally already tore the world down; put the
            # cause on the record and report resumable-not-failed (a
            # task retry with DCT_RESUME=1 picks the cycle back up).
            events.emit(
                "launcher", "supervise_terminated",
                attempts=len(attempts), restarts_used=restarts,
            )
            return SuperviseResult(
                results=attempts[-1].results if attempts else [],
                attempts=attempts, restarts=restarts, success=False,
                classification="preempted",
            )
        finally:
            if metrics_pub is not None:
                metrics_pub.close(final=True)
            for sig, prev in prev_handlers.items():
                try:
                    signal.signal(sig, prev)
                except (ValueError, OSError):
                    pass

    @staticmethod
    def _attempt_lost_seconds(
        env: dict, run_id: str, classification: str,
        t0_wall: float, wall: float,
    ) -> float:
        """Wall clock the failed attempt actually LOST: the window since
        its last durable resume checkpoint (``resume_state_saved``
        events), because checkpointed progress is retained by the
        resume. A graceful preemption saved at the boundary by contract
        — zero. No readable events / no save seen -> the full attempt
        wall (conservative: nothing provably survived)."""
        if classification == "preempted":
            return 0.0
        path = os.path.join(
            env.get("DCT_EVENTS_DIR") or "logs/events", "events.jsonl"
        )
        last_save = None
        try:
            with open(path) as f:
                for line in f:
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue
                    if (
                        rec.get("run_id") == run_id
                        and rec.get("event") == "resume_state_saved"
                        and rec.get("ts", 0.0) >= t0_wall
                    ):
                        last_save = max(last_save or 0.0, rec["ts"])
        except OSError:
            return wall
        if last_save is None:
            return wall
        return min(wall, max(0.0, t0_wall + wall - last_save))

    def _clear_heartbeats(self, env: dict, world_size: int) -> None:
        """Drop the dead attempt's heartbeat files: they carry the SAME
        run ID as the relaunch (one cycle, one correlation ID), so the
        fresh monitor would read them as instantly-stalled ranks."""
        hb_dir = (
            env.get("DCT_HEARTBEAT_DIR")
            or self.heartbeat_dir
            or "logs/heartbeats"
        )
        for rank in range(world_size):
            try:
                os.remove(heartbeat_path(hb_dir, rank))
            except OSError:
                pass

    @staticmethod
    def all_succeeded(results: list[RankResult]) -> bool:
        return all(r.returncode == 0 for r in results)
