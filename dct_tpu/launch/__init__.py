from dct_tpu.launch.launcher import (  # noqa: F401
    build_spmd_launch_script,
    build_zombie_cleanup_script,
    build_healthcheck_script,
    LocalProcessLauncher,
)
