"""Azure ML implementation of the EndpointClient protocol (import-gated).

Binds the rollout state machine to Azure Managed Online Endpoints with the
same resources the reference uses: ``Standard_DS2_v2`` x1 instances and the
openmpi Ubuntu inference base image (dags/azure_manual_deploy.py:154-162,
azure_auto_deploy.py:134-146). Credentials come from the standard env vars
(AZURE_TENANT_ID / AZURE_CLIENT_ID / AZURE_CLIENT_SECRET via
ClientSecretCredential, plus AZURE_SUBSCRIPTION_ID / AZURE_RESOURCE_GROUP /
AZURE_WORKSPACE) — each read into its own field, fixing the reference bug
where all five getenv results are assigned to one variable
(dags/azure_auto_deploy.py:15-19) and the compose bug that sets
workspace = resource group (docker-compose.yml:22)."""

from __future__ import annotations

import os
from dataclasses import dataclass

INSTANCE_TYPE = "Standard_DS2_v2"
BASE_IMAGE = "mcr.microsoft.com/azureml/openmpi4.1.0-ubuntu20.04:latest"


@dataclass
class AzureConfig:
    tenant_id: str
    client_id: str
    client_secret: str
    subscription_id: str
    resource_group: str
    workspace: str

    @classmethod
    def from_env(cls) -> "AzureConfig":
        vals = {}
        for field_name, env in (
            ("tenant_id", "AZURE_TENANT_ID"),
            ("client_id", "AZURE_CLIENT_ID"),
            ("client_secret", "AZURE_CLIENT_SECRET"),
            ("subscription_id", "AZURE_SUBSCRIPTION_ID"),
            ("resource_group", "AZURE_RESOURCE_GROUP"),
            ("workspace", "AZURE_WORKSPACE"),
        ):
            v = os.environ.get(env)
            if not v:
                raise EnvironmentError(f"Missing required env var {env}")
            vals[field_name] = v
        return cls(**vals)


class AzureEndpointClient:
    """EndpointClient over azure-ai-ml (present on Airflow images, see the
    reference Dockerfile:15-19; not required in this repo)."""

    def __init__(self, cfg: AzureConfig | None = None):
        from azure.ai.ml import MLClient
        from azure.identity import ClientSecretCredential

        cfg = cfg or AzureConfig.from_env()
        self.cfg = cfg
        cred = ClientSecretCredential(
            tenant_id=cfg.tenant_id,
            client_id=cfg.client_id,
            client_secret=cfg.client_secret,
        )
        self.ml = MLClient(cred, cfg.subscription_id, cfg.resource_group, cfg.workspace)

    # -- control plane -------------------------------------------------
    def endpoint_exists(self, endpoint: str) -> bool:
        try:
            self.ml.online_endpoints.get(endpoint)
            return True
        except Exception:
            return False

    def create_endpoint(self, endpoint: str) -> None:
        from azure.ai.ml.entities import ManagedOnlineEndpoint

        ep = ManagedOnlineEndpoint(name=endpoint, auth_mode="key")
        self.ml.online_endpoints.begin_create_or_update(ep).result()

    def delete_endpoint(self, endpoint: str) -> None:
        self.ml.online_endpoints.begin_delete(endpoint).result()

    def provisioning_state(self, endpoint: str) -> str:
        return self.ml.online_endpoints.get(endpoint).provisioning_state or ""

    def get_traffic(self, endpoint: str) -> dict:
        return dict(self.ml.online_endpoints.get(endpoint).traffic or {})

    def set_traffic(self, endpoint: str, traffic: dict) -> None:
        ep = self.ml.online_endpoints.get(endpoint)
        ep.traffic = dict(traffic)
        self.ml.online_endpoints.begin_create_or_update(ep).result()

    def get_mirror_traffic(self, endpoint: str) -> dict:
        return dict(self.ml.online_endpoints.get(endpoint).mirror_traffic or {})

    def set_mirror_traffic(self, endpoint: str, traffic: dict) -> None:
        ep = self.ml.online_endpoints.get(endpoint)
        ep.mirror_traffic = dict(traffic)
        self.ml.online_endpoints.begin_create_or_update(ep).result()

    def deploy(self, endpoint: str, slot: str, package_dir: str) -> None:
        from azure.ai.ml.entities import (
            CodeConfiguration,
            Environment,
            ManagedOnlineDeployment,
            Model,
        )

        deployment = ManagedOnlineDeployment(
            name=slot,
            endpoint_name=endpoint,
            model=Model(path=package_dir),
            code_configuration=CodeConfiguration(
                code=package_dir, scoring_script="score.py"
            ),
            environment=Environment(
                conda_file=os.path.join(package_dir, "conda.yaml"),
                image=BASE_IMAGE,
            ),
            instance_type=INSTANCE_TYPE,
            instance_count=1,
        )
        self.ml.online_deployments.begin_create_or_update(deployment).result()

    def delete_deployment(self, endpoint: str, slot: str) -> None:
        self.ml.online_deployments.begin_delete(
            name=slot, endpoint_name=endpoint
        ).result()

    def list_deployments(self, endpoint: str) -> list[str]:
        return [
            d.name for d in self.ml.online_deployments.list(endpoint_name=endpoint)
        ]
