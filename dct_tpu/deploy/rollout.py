"""Deployment: packaging + blue/green + shadow + canary rollout.

The capability re-implemented here is the reference's two deploy DAGs:

- ``prepare_package`` (dags/azure_auto_deploy.py:26-115 and
  azure_manual_deploy.py:28-134): query the tracking store for the best run
  by ``val_loss ASC``, download its ``best_checkpoints`` artifact, stage
  ``model.ckpt`` and the generated serving files into a deploy dir;
- ``deploy_new_slot`` (azure_auto_deploy.py:118-149): read live traffic,
  pick the idle slot (no traffic -> ``blue``; else the opposite of the
  current-max-traffic slot);
- ``start_shadow`` (:152-161): 100/0 live traffic + 20% mirror to the new
  slot; ``start_canary`` (:163-172): clear mirror, 90/10 live;
  ``full_rollout`` (:174-185): 100% new, delete old deployment.

Differences by design: the cloud surface is a small :class:`EndpointClient`
protocol (Azure impl in :mod:`dct_tpu.deploy.azure`, in-memory impl for
tests/local platforms in :mod:`dct_tpu.deploy.local`) instead of inline SDK
calls, the reference's env-var clobber bug (azure_auto_deploy.py:15-19
assigns five getenvs to one variable) is structurally impossible here, and
state flows between stages as return values instead of Airflow XCom.
"""

from __future__ import annotations

import glob
import os
import shutil
import time
from dataclasses import dataclass, field
from typing import Protocol

from dct_tpu.observability import lineage as _lineage

BLUE, GREEN = "blue", "green"


class EndpointClient(Protocol):
    """Minimal control surface of a managed online endpoint."""

    def endpoint_exists(self, endpoint: str) -> bool: ...
    def create_endpoint(self, endpoint: str) -> None: ...
    def delete_endpoint(self, endpoint: str) -> None: ...
    def provisioning_state(self, endpoint: str) -> str: ...
    def get_traffic(self, endpoint: str) -> dict[str, int]: ...
    def set_traffic(self, endpoint: str, traffic: dict[str, int]) -> None: ...
    def get_mirror_traffic(self, endpoint: str) -> dict[str, int]: ...
    def set_mirror_traffic(self, endpoint: str, traffic: dict[str, int]) -> None: ...
    def deploy(self, endpoint: str, slot: str, package_dir: str) -> None: ...
    def delete_deployment(self, endpoint: str, slot: str) -> None: ...
    def list_deployments(self, endpoint: str) -> list[str]: ...


def prepare_package(
    tracker, deploy_dir: str, *, data_dir: str | None = None
) -> dict:
    """Best-run query -> deploy package. Returns package info.

    Mirrors the reference flow (wipe deploy dir, find best run, download
    ``best_checkpoints``, take the first .ckpt, generate serving files) and
    adds the numpy weight export so serving needs no ML framework.
    """
    from dct_tpu.serving.score_gen import generate_score_package

    if os.path.isdir(deploy_dir):
        shutil.rmtree(deploy_dir)
    os.makedirs(deploy_dir, exist_ok=True)

    best = tracker.search_best_run("val_loss", "min")
    if best is None:
        raise RuntimeError(
            "No finished runs with val_loss found in the tracking store — "
            "did the training pipeline run?"
        )
    art_dir = tracker.download_artifacts(
        best.run_id, "best_checkpoints", os.path.join(deploy_dir, "_dl")
    )
    ckpts = sorted(glob.glob(os.path.join(art_dir, "*.ckpt")))
    if not ckpts:
        raise FileNotFoundError(f"No .ckpt in artifact dir {art_dir}")
    model_ckpt = os.path.join(deploy_dir, "model.ckpt")
    # The download already staged the bytes under _dl/ on the same
    # filesystem: publish by atomic rename, so model.ckpt either holds
    # the complete checkpoint or does not exist — never a torn copy.
    os.replace(ckpts[0], model_ckpt)
    shutil.rmtree(os.path.join(deploy_dir, "_dl"))

    meta = generate_score_package(model_ckpt, deploy_dir)
    # Persist the shipped model's provenance INSIDE the package: each
    # rollout stage runs in its own Airflow task process with no env
    # inheritance from the training launch, and the package dir is the
    # one artifact every stage shares — so it carries the training
    # cycle's run-correlation ID for the stage events to adopt, the
    # selected run's FULL final metrics (what the promotion gates — and
    # humans — compare the next challenger against), and a
    # training-data snapshot for the deploy-side drift detectors.
    import json

    info_path = os.path.join(deploy_dir, "run_info.json")
    info_tmp = f"{info_path}.tmp.{os.getpid()}"
    with open(info_tmp, "w") as f:
        json.dump(
            {
                "tracking_run_id": best.run_id,
                "run_correlation_id": best.run_correlation_id,
                "val_loss": best.metrics.get("val_loss"),
                "metrics": {
                    k: v for k, v in best.metrics.items()
                    if isinstance(v, (int, float))
                },
                "data_snapshot": _training_data_snapshot(data_dir),
                # The split the shipped model was validated on. The
                # eval harness must rebuild EXACTLY this split, and the
                # gate runs in a DAG task process with no env
                # inheritance from the training launch — so the split
                # parameters travel in the artifact. The seed comes
                # from the training run's OWN logged params when
                # available (authoritative), env otherwise.
                "split": _split_params(best.params),  # dct: noqa[gather-on-publish] — tracking-run hyperparameter dict (tracking.client.Run.params), not a TrainState; nothing here is a device array
            },
            f,
            indent=2,
        )
    # The manifest gates every later stage's eval/drift decisions; a
    # half-written one must be unobservable (the gate would fail open
    # on a torn read as "pre-observability package").
    os.replace(info_tmp, info_path)
    lin = _lineage.get_default()
    if lin.enabled:
        # Package lineage: the staged model.ckpt hashes to the SAME node
        # the trainer saved and the tracking store copied (content
        # addressing — no ID plumbing across the three layers), and the
        # package dir node is what the gate verdicts and the serving
        # model-load hang their edges on.
        ckpt_nid = lin.node(
            "checkpoint", path=model_ckpt,
            attrs={"tracking_run_id": best.run_id},
        )
        pkg_nid = lin.node(
            "deploy_package", path=deploy_dir,
            attrs={
                "tracking_run_id": best.run_id,
                "run_correlation_id": best.run_correlation_id,
                "val_loss": best.metrics.get("val_loss"),
            },
        )
        lin.edge("consumed", pkg_nid, ckpt_nid)
    return {
        "run_id": best.run_id,
        "run_correlation_id": best.run_correlation_id,
        "val_loss": best.metrics.get("val_loss"),
        "metrics": dict(best.metrics),
        "deploy_dir": deploy_dir,
        "model_meta": meta,
    }


def _split_params(run_params: dict | None) -> dict:
    """The validation-split parameters to stamp into the manifest: both
    from the training run's OWN logged params when present
    (authoritative — the packaging process's env need not match the
    training launch's), env fallback for runs logged before the trainer
    recorded them."""
    from dct_tpu.config import DataConfig, TrainConfig

    params = run_params or {}
    try:
        seed = int(params["seed"])
    except (KeyError, TypeError, ValueError):
        seed = TrainConfig.from_env().seed
    try:
        val_fraction = float(params["val_fraction"])
    except (KeyError, TypeError, ValueError):
        val_fraction = DataConfig.from_env().val_fraction
    return {"seed": seed, "val_fraction": val_fraction}


def _training_data_snapshot(data_dir: str | None) -> dict | None:
    """Quantile snapshot of the processed training data, stamped into
    the package manifest so the NEXT cycle's drift detectors can
    compare their ETL output against what THIS model learned from.
    Best-effort: a packaging host without the data ships None, never a
    failed deploy."""
    from dct_tpu.config import EvaluationConfig

    data_dir = data_dir or os.environ.get("DCT_PROCESSED_DIR", "data/processed")
    try:
        # Cached by snapshot identity: the always-on loop packages a
        # challenger per promotion against the same processed snapshot —
        # the quantile stamp must not re-pay the parquet IO each time.
        from dct_tpu.data.dataset import load_processed_dataset_cached
        from dct_tpu.evaluation.drift import snapshot_features

        data = load_processed_dataset_cached(data_dir)
        return snapshot_features(
            data.features, data.feature_names,
            bins=EvaluationConfig.from_env().drift_bins,
        )
    except Exception:  # noqa: BLE001 — snapshotting is provenance, not a gate
        return None


def package_manifest(package_dir: str) -> dict:
    """The full ``run_info.json`` manifest of a deploy package ({} for
    pre-observability packages or any read failure)."""
    import json

    try:
        with open(os.path.join(package_dir, "run_info.json")) as f:
            manifest = json.load(f)
        return manifest if isinstance(manifest, dict) else {}
    except (OSError, ValueError):
        return {}


def package_run_correlation_id(package_dir: str) -> str | None:
    """The training cycle's run-correlation ID persisted by
    :func:`prepare_package`; None for pre-observability packages or any
    read failure (correlation is best-effort, never a deploy blocker)."""
    import json

    try:
        with open(os.path.join(package_dir, "run_info.json")) as f:
            return json.load(f).get("run_correlation_id") or None
    except (OSError, ValueError):
        return None


def choose_slot(traffic: dict[str, int]) -> tuple[str, str | None]:
    """(new_slot, old_slot) from the live traffic map.

    Reference logic (dags/azure_auto_deploy.py:124-129): empty/zero traffic
    -> deploy ``blue`` with no old slot; otherwise the slot currently
    holding the most traffic is old, and new is its blue/green opposite.
    """
    live = {k: v for k, v in traffic.items() if v > 0}
    if not live:
        return BLUE, None
    old = max(live, key=live.get)
    return (GREEN if old == BLUE else BLUE), old


@dataclass
class RolloutEvent:
    stage: str
    traffic: dict = field(default_factory=dict)
    mirror: dict = field(default_factory=dict)


class RolloutOrchestrator:
    """The blue/green + shadow + canary state machine.

    ``run()`` executes: deploy_new_slot -> shadow (soak) -> canary (soak)
    -> full rollout, with the reference's stage parameters (mirror 20%,
    canary 10%, 30 s soaks — dags/azure_auto_deploy.py:152-185,189-197).
    Each stage is also callable individually (the DAGs map one task per
    stage).
    """

    def __init__(
        self,
        client: EndpointClient,
        endpoint: str,
        *,
        mirror_percent: int = 20,
        canary_percent: int = 10,
        soak_seconds: float = 30.0,
        sleep_fn=time.sleep,
        run_id: str | None = None,
        retry_max_attempts: int | None = None,
        retry_backoff_s: float | None = None,
        gate=None,
    ):
        from dct_tpu.resilience.retry import Retrier

        self.client = client
        self.endpoint = endpoint
        self.mirror_percent = mirror_percent
        self.canary_percent = canary_percent
        self.soak_seconds = soak_seconds
        self.sleep_fn = sleep_fn
        self.events: list[RolloutEvent] = []
        # Promotion gate (dct_tpu.evaluation.gates.PromotionGate, or any
        # object with its evaluate() signature): consulted between
        # stages — shadow -> canary and canary -> full rollout. None =
        # the reference's ungated timer walk.
        self.gate = gate
        # Run-correlation ID for stage events: pass the shipped
        # package's (package_run_correlation_id); deploy_new_slot adopts
        # it from the package automatically when unset.
        self.run_id = run_id
        # Transient control-plane flakes retry with backoff instead of
        # aborting the rollout; when retries exhaust mid-canary the
        # stage auto-reverts to the prior deployment (`rollback`).
        # Policy defaults come from the same DCT_RETRY_* env contract
        # the tracking client honors; explicit ctor args win.
        overrides: dict = {"sleep_fn": sleep_fn}
        if retry_max_attempts is not None:
            overrides["max_attempts"] = retry_max_attempts
        if retry_backoff_s is not None:
            overrides["backoff_s"] = retry_backoff_s
        self._retry = Retrier.from_env(**overrides)

    def _call(self, fn, *args, op: str):
        """One endpoint-control call under the retry policy."""
        return self._retry(lambda: fn(*args), op=f"deploy.{op}")

    def _stage_span(self, stage: str):
        """Span for one rollout stage, on the SHIPPED training cycle's
        trace (same adoption rule as the stage events): the deploy leg
        appears on the same Perfetto timeline as the training run."""
        from dct_tpu.observability import spans as _spans

        return _spans.get_default().for_trace(self.run_id).span(
            f"deploy.{stage}", component="deploy", endpoint=self.endpoint,
        )

    # -- stages --------------------------------------------------------
    def ensure_endpoint(self) -> None:
        """Get-or-recreate, deleting a failed endpoint first
        (dags/azure_manual_deploy.py:141-150)."""
        c = self.client
        if self._call(c.endpoint_exists, self.endpoint, op="endpoint_exists"):
            state = self._call(
                c.provisioning_state, self.endpoint, op="provisioning_state"
            )
            if state.lower() == "failed":
                self._call(c.delete_endpoint, self.endpoint, op="delete_endpoint")
                self._call(c.create_endpoint, self.endpoint, op="create_endpoint")
        else:
            self._call(c.create_endpoint, self.endpoint, op="create_endpoint")

    def deploy_new_slot(self, package_dir: str) -> tuple[str, str | None]:
        if self.run_id is None:
            self.run_id = package_run_correlation_id(package_dir)
        with self._stage_span("deploy_new_slot"):
            self.ensure_endpoint()
            new_slot, old_slot = choose_slot(
                self._call(self.client.get_traffic, self.endpoint,
                           op="get_traffic")
            )
            self._call(self.client.deploy, self.endpoint, new_slot,
                       package_dir, op="deploy")
            if old_slot is None:
                # First deployment: take 100% immediately (manual-deploy
                # path, dags/azure_manual_deploy.py:164-167).
                self._call(self.client.set_traffic, self.endpoint,
                           {new_slot: 100}, op="set_traffic")
            self._record("deploy_new_slot")
        return new_slot, old_slot

    def start_shadow(self, new_slot: str, old_slot: str) -> None:
        # Fresh evidence window: the capture file carries the PREVIOUS
        # cycle's mirrored pairs (a held challenger's disagreements, a
        # promoted one's agreements) — either would contaminate THIS
        # challenger's shadow->canary disagreement score. The gate also
        # filters by shadow slot, but a blocked cycle's record must not
        # keep punishing (or excusing) every cycle after it.
        capture = getattr(self.client, "mirror_capture_path", None)
        if capture:
            try:
                os.remove(capture)
            except OSError:
                pass
        with self._stage_span("shadow"):
            try:
                self._call(self.client.set_traffic, self.endpoint,
                           {old_slot: 100, new_slot: 0}, op="set_traffic")
                self._call(self.client.set_mirror_traffic, self.endpoint,
                           {new_slot: self.mirror_percent},
                           op="set_mirror_traffic")
                # _record is inside the guard: its traffic reads can
                # flake too, and by now the mirror is live.
                self._record("shadow")
            except Exception:
                self.rollback(new_slot, old_slot, stage="shadow")
                raise

    # -- promotion gates ----------------------------------------------
    def _slot_package_dir(self, slot: str | None) -> str | None:
        """The package dir backing a deployed slot, when the client can
        say (the local client exposes ``deployment_package_dir``; cloud
        clients that cannot resolve it return None and the gate treats
        the champion as unresolvable)."""
        if slot is None:
            return None
        resolver = getattr(self.client, "deployment_package_dir", None)
        if resolver is None:
            return None
        try:
            return resolver(self.endpoint, slot)
        except Exception:  # noqa: BLE001 — unresolvable, not fatal
            return None

    def _consult_gate(self, to_stage: str, new_slot: str, old_slot: str | None) -> None:
        """Gatekeeper between stages: evaluate the challenger (new
        slot's package) against the champion (old slot's), put the
        decision on the record (``deploy.gate`` event + span + metrics
        ledger), and on anything but promote revert traffic to the
        champion and raise :class:`GateRejection`.

        A gate CONSULT failure (the gate itself crashing) blocks the
        rollout too — a safety mechanism that breaks must fail closed.
        """
        if self.gate is None or old_slot is None:
            return
        from dct_tpu.evaluation.gates import (
            GateDecision, GateRejection, record_decision,
        )

        challenger_dir = self._slot_package_dir(new_slot)
        champion_dir = self._slot_package_dir(old_slot)
        mirror_capture = getattr(self.client, "mirror_capture_path", None)
        with self._stage_span(f"gate_{to_stage}") as sp:
            if challenger_dir is None:
                # Cannot even locate what we'd be promoting: fail open
                # only if the gate says so.
                decision = GateDecision(
                    "promote" if self.gate.cfg.fail_open else "hold",
                    to_stage, "no_challenger_package",
                )
            else:
                try:
                    decision = self.gate.evaluate(
                        challenger_dir=challenger_dir,
                        champion_dir=champion_dir,
                        stage=to_stage,
                        mirror_capture=mirror_capture,
                        shadow_slot=new_slot,
                    )
                except Exception as e:  # noqa: BLE001 — fail closed
                    decision = GateDecision(
                        "hold", to_stage, f"gate_error: {type(e).__name__}: {e}"
                    )
            sp.set(decision=decision.decision, reason=decision.reason)
        ev = decision.evidence or {}
        lin = _lineage.get_default()
        if lin.enabled:
            # The verdict joins the graph content-addressed from its own
            # record: ``consumed`` edges to the packages it judged (and
            # the evidence report), plus a ``promoted`` edge into the
            # challenger when it passed — so "is the artifact on disk
            # the one the gate promoted?" is an audit over this node.
            ch_nid = (
                lin.node("deploy_package", path=challenger_dir)
                if challenger_dir else None
            )
            champ_nid = (
                lin.node("deploy_package", path=champion_dir)
                if champion_dir else None
            )
            verdict_nid = lin.node(
                "gate_verdict", content=decision.to_dict(),
                attrs={
                    "stage": to_stage, "decision": decision.decision,
                    "reason": decision.reason, "endpoint": self.endpoint,
                },
            )
            rep_nid = (
                lin.node("eval_report", content=ev,
                         attrs={"stage": to_stage})
                if ev else None
            )
            lin.edge("consumed", verdict_nid, rep_nid)
            lin.edge("consumed", verdict_nid, ch_nid)
            lin.edge("consumed", verdict_nid, champ_nid)
            if decision.promoted:
                lin.edge("promoted", verdict_nid, ch_nid, stage=to_stage)
        self.events.append(RolloutEvent(stage=f"gate_{to_stage}"))
        self._cycle_log().emit(
            "deploy", "deploy.gate", endpoint=self.endpoint,
            stage=to_stage, decision=decision.decision,
            reason=decision.reason, new_slot=new_slot, old_slot=old_slot,
            mean_delta=ev.get("mean_delta"),
            champion_loss=ev.get("champion_loss"),
            challenger_loss=ev.get("challenger_loss"),
            drift=ev.get("drift"), disagreement=ev.get("disagreement"),
        )
        record_decision(
            decision, ledger_path=getattr(self.gate.cfg, "ledger_path", ""),
        )
        if not decision.promoted:
            self.rollback(new_slot, old_slot, stage=f"gate:{to_stage}")
            raise GateRejection(decision)

    def start_canary(self, new_slot: str, old_slot: str) -> None:
        # Shadow -> canary is the first gated transition: offline
        # champion/challenger eval + drift + shadow-traffic
        # disagreement. A failing gate reverts BEFORE any live traffic
        # reaches the challenger.
        self._consult_gate("canary", new_slot, old_slot)
        with self._stage_span("canary"):
            try:
                self._call(self.client.set_mirror_traffic, self.endpoint,
                           {}, op="set_mirror_traffic")
                self._call(
                    self.client.set_traffic, self.endpoint,
                    {
                        old_slot: 100 - self.canary_percent,
                        new_slot: self.canary_percent,
                    },
                    op="set_traffic",
                )
                # Inside the guard: a flake here would otherwise abort
                # the rollout with canary traffic still live.
                self._record("canary")
            except Exception:
                # Retries exhausted mid-canary: auto-revert to the prior
                # deployment, THEN surface the failure (the task goes
                # red, the endpoint stays safe on the old model).
                self.rollback(new_slot, old_slot, stage="canary")
                raise

    def full_rollout(self, new_slot: str, old_slot: str | None) -> None:
        # Canary -> full is the second gated transition (old_slot=None —
        # a first deployment — has no champion and passes ungated).
        self._consult_gate("full_rollout", new_slot, old_slot)
        with self._stage_span("full_rollout"):
            try:
                self._call(self.client.set_traffic, self.endpoint,
                           {new_slot: 100}, op="set_traffic")
            except Exception:
                # The flip itself failed: revert. (A failure AFTER the
                # flip — old-slot deletion — does not revert: the new
                # model is live and healthy; the lingering old slot is
                # an operator cleanup, not a rollback.)
                self.rollback(new_slot, old_slot, stage="full_rollout")
                raise
            if old_slot and old_slot in self._call(
                self.client.list_deployments, self.endpoint,
                op="list_deployments",
            ):
                self._call(self.client.delete_deployment, self.endpoint,
                           old_slot, op="delete_deployment")
            self._record("full_rollout")
        lin = _lineage.get_default()
        if lin.enabled:
            # The flip on the record: package --deployed--> the slot
            # assignment (a model_load node keyed by endpoint/slot/
            # package, which the serving process's own load will attach
            # its ``served_by`` sighting next to).
            pkg_dir = self._slot_package_dir(new_slot)
            if pkg_dir:
                pkg_nid = lin.node("deploy_package", path=pkg_dir)
                slot_nid = lin.node(
                    "model_load",
                    content={
                        "endpoint": self.endpoint, "slot": new_slot,
                        "package": pkg_nid,
                    },
                    attrs={
                        "endpoint": self.endpoint, "slot": new_slot,
                        "stage": "full_rollout",
                    },
                )
                lin.edge("deployed", pkg_nid, slot_nid)

    def rollback(self, new_slot: str, old_slot: str | None, *, stage: str) -> None:
        """Auto-revert to the prior deployment: old slot back to 100%
        live, mirror cleared. Best-effort single-shot calls (no retry
        loop: the control plane just proved flaky, and every failed
        revert attempt is more time the canary serves traffic) — the
        ``deploy.rollback`` event records the attempt either way."""
        reverted = False
        if old_slot:
            try:
                self.client.set_mirror_traffic(self.endpoint, {})
                self.client.set_traffic(self.endpoint, {old_slot: 100})
                reverted = True
            except Exception:  # noqa: BLE001 — recorded below, then re-raised by caller
                pass
        self.events.append(RolloutEvent(stage="rollback"))
        self._cycle_log().emit(
            "deploy", "deploy.rollback", endpoint=self.endpoint,
            failed_stage=stage, new_slot=new_slot, old_slot=old_slot,
            reverted=reverted,
        )

    # -- the full machine ---------------------------------------------
    def run(self, package_dir: str) -> list[RolloutEvent]:
        new_slot, old_slot = self.deploy_new_slot(package_dir)
        if old_slot is not None:
            self.start_shadow(new_slot, old_slot)
            self.sleep_fn(self.soak_seconds)
            self.start_canary(new_slot, old_slot)
            self.sleep_fn(self.soak_seconds)
        self.full_rollout(new_slot, old_slot)
        return self.events

    def _cycle_log(self):
        """Event log stamped with the SHIPPED training cycle's
        run-correlation ID (from the package's run_info.json / ctor) so
        one grep spans train -> deploy; a standalone rollout falls back
        to the process default."""
        from dct_tpu.observability import events as _events

        log = _events.get_default()
        if self.run_id and self.run_id != log.run_id:
            log = _events.EventLog(log.path, run_id=self.run_id, rank=log.rank)
        return log

    def _record(self, stage: str) -> None:
        ev = RolloutEvent(
            stage=stage,
            traffic=dict(self._call(self.client.get_traffic, self.endpoint,
                                    op="get_traffic")),
            mirror=dict(self._call(self.client.get_mirror_traffic,
                                   self.endpoint, op="get_mirror_traffic")),
        )
        self.events.append(ev)
        self._cycle_log().emit(
            "deploy", stage, endpoint=self.endpoint,
            traffic=ev.traffic, mirror=ev.mirror,
        )
