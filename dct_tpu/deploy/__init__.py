from dct_tpu.deploy.rollout import (  # noqa: F401
    EndpointClient,
    choose_slot,
    prepare_package,
    RolloutOrchestrator,
)
from dct_tpu.deploy.local import LocalEndpointClient  # noqa: F401
