"""Local endpoint client: the test/local-platform deployment target.

Implements the full :class:`~dct_tpu.deploy.rollout.EndpointClient` surface
with real serving semantics — ``score()`` actually loads the deployed
package's model.npz and answers inference requests — so the whole
train->track->package->rollout->infer path runs hermetically (the reference
can only exercise this against a live Azure subscription).

With ``state_path`` set (or ``DCT_LOCAL_ENDPOINT_STATE`` in the env), the
control-plane state (endpoints, traffic maps, slot->package bindings) is
persisted as JSON after every mutation and reloaded on construction, so the
rollout DAG's stages see each other's state even when the orchestrator runs
every task in a fresh process (as real Airflow does). Deployment weights are
not serialized — they reload lazily from the deployed package directory.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field


@dataclass
class _Deployment:
    package_dir: str
    _weights: dict | None = None
    _meta: dict | None = None

    def load(self) -> tuple[dict, dict]:
        if self._weights is None:
            import numpy as np

            from dct_tpu.serving.runtime import assemble_weights

            npz = np.load(os.path.join(self.package_dir, "model.npz"))
            # Quantized packages reconstitute (::q8/::scale/::bf16 key
            # pairs -> QuantTensor / widened f32); plain packages pass
            # through unchanged.
            self._weights = assemble_weights({k: npz[k] for k in npz.files})
            with open(os.path.join(self.package_dir, "model_meta.json")) as f:
                self._meta = json.load(f)
            # In-memory only (never persisted back): where this
            # package's pre-compiled scorer executables live, for the
            # jax serving engine's AOT store (serving/batching.py).
            self._meta["_aot_dir"] = os.path.join(self.package_dir, "aot")
        return self._weights, self._meta


@dataclass
class _Endpoint:
    provisioning_state: str = "Succeeded"
    traffic: dict = field(default_factory=dict)
    mirror_traffic: dict = field(default_factory=dict)
    deployments: dict = field(default_factory=dict)


class LocalEndpointClient:
    def __init__(self, state_path: str | None = None):
        self.state_path = state_path or os.environ.get("DCT_LOCAL_ENDPOINT_STATE")
        self.endpoints: dict[str, _Endpoint] = {}
        self.ops: list[tuple] = []  # audit log of control-plane calls
        self._load()

    # -- persistence ---------------------------------------------------
    def _load(self) -> None:
        if not (self.state_path and os.path.exists(self.state_path)):
            return
        with open(self.state_path) as f:
            raw = json.load(f)
        for name, ep in raw.items():
            self.endpoints[name] = _Endpoint(
                provisioning_state=ep["provisioning_state"],
                traffic=dict(ep["traffic"]),
                mirror_traffic=dict(ep["mirror_traffic"]),
                deployments={
                    slot: _Deployment(package_dir=pkg)
                    for slot, pkg in ep["deployments"].items()
                },
            )

    def _save(self) -> None:
        if not self.state_path:
            return
        raw = {
            name: {
                "provisioning_state": ep.provisioning_state,
                "traffic": ep.traffic,
                "mirror_traffic": ep.mirror_traffic,
                "deployments": {
                    slot: dep.package_dir for slot, dep in ep.deployments.items()
                },
            }
            for name, ep in self.endpoints.items()
        }
        os.makedirs(os.path.dirname(os.path.abspath(self.state_path)), exist_ok=True)
        tmp = self.state_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(raw, f, indent=2)
        # Traffic-state bookkeeping, not an artifact: slot-flip lineage
        # is recorded by RolloutOrchestrator (deployed/served_by edges),
        # and this file mutates on every traffic change so a content
        # hash would never be stable.
        os.replace(tmp, self.state_path)  # dct: noqa[lineage-publish]

    # -- control plane -------------------------------------------------
    def endpoint_exists(self, endpoint: str) -> bool:
        return endpoint in self.endpoints

    def create_endpoint(self, endpoint: str) -> None:
        self.ops.append(("create_endpoint", endpoint))
        self.endpoints[endpoint] = _Endpoint()
        self._save()

    def delete_endpoint(self, endpoint: str) -> None:
        self.ops.append(("delete_endpoint", endpoint))
        self.endpoints.pop(endpoint, None)
        self._save()

    def provisioning_state(self, endpoint: str) -> str:
        return self.endpoints[endpoint].provisioning_state

    def get_traffic(self, endpoint: str) -> dict:
        if endpoint not in self.endpoints:
            return {}
        return dict(self.endpoints[endpoint].traffic)

    def set_traffic(self, endpoint: str, traffic: dict) -> None:
        self.ops.append(("set_traffic", endpoint, dict(traffic)))
        ep = self.endpoints[endpoint]
        unknown = set(k for k, v in traffic.items() if v > 0) - set(ep.deployments)
        if unknown:
            raise ValueError(f"Traffic to nonexistent deployments: {unknown}")
        ep.traffic = dict(traffic)
        self._save()

    def get_mirror_traffic(self, endpoint: str) -> dict:
        return dict(self.endpoints[endpoint].mirror_traffic)

    def set_mirror_traffic(self, endpoint: str, traffic: dict) -> None:
        self.ops.append(("set_mirror_traffic", endpoint, dict(traffic)))
        self.endpoints[endpoint].mirror_traffic = dict(traffic)
        self._save()

    def deploy(self, endpoint: str, slot: str, package_dir: str) -> None:
        self.ops.append(("deploy", endpoint, slot, package_dir))
        dep = _Deployment(package_dir=package_dir)
        dep.load()  # fail fast if the package is incomplete
        self.endpoints[endpoint].deployments[slot] = dep
        self._save()

    def delete_deployment(self, endpoint: str, slot: str) -> None:
        self.ops.append(("delete_deployment", endpoint, slot))
        self.endpoints[endpoint].deployments.pop(slot, None)
        self._save()

    def list_deployments(self, endpoint: str) -> list[str]:
        return list(self.endpoints[endpoint].deployments)

    def deployment_package_dir(self, endpoint: str, slot: str) -> str | None:
        """The package dir backing a deployed slot (None for an unknown
        endpoint/slot) — how the promotion gate locates the champion's
        and challenger's artifacts."""
        ep = self.endpoints.get(endpoint)
        dep = ep.deployments.get(slot) if ep else None
        return dep.package_dir if dep else None

    # -- mirror capture (evaluation.drift's shadow-stage evidence) -----
    @property
    def mirror_capture_path(self) -> str | None:
        """Where mirrored request/response pairs are captured as JSONL:
        ``DCT_MIRROR_CAPTURE`` wins; with persistent state the default
        is a sibling of the state file; in-memory clients don't capture
        unless told to."""
        explicit = os.environ.get("DCT_MIRROR_CAPTURE")
        if explicit:
            return explicit
        if self.state_path:
            return self.state_path + "_mirror.jsonl"
        return None

    def append_mirror_record(self, record: dict) -> None:
        """Append one mirrored-pair record (single-line JSON, O_APPEND-
        atomic under PIPE_BUF like the event log). Best-effort: capture
        is evaluation telemetry, never a serving failure."""
        path = self.mirror_capture_path
        if not path:
            return
        try:
            parent = os.path.dirname(path)
            if parent:
                os.makedirs(parent, exist_ok=True)
            with open(path, "a") as f:
                f.write(json.dumps(record) + "\n")
        except (OSError, ValueError, TypeError):
            pass

    # -- data plane (what Azure's scoring URI does) --------------------
    def load_slot(self, endpoint: str, slot: str) -> tuple[dict, dict]:
        """(weights, meta) of a deployed slot; KeyError for an unknown
        endpoint/slot (callers map that to a client-facing 404)."""
        return self.endpoints[endpoint].deployments[slot].load()

    def score(self, endpoint: str, payload: dict, *, slot: str | None = None) -> dict:
        """Route a request like the live endpoint would: to the given slot,
        or to the max-live-traffic slot. With mirror traffic configured
        and a capture path set, the request is ALSO scored against each
        shadow slot and the paired responses land in the mirror-capture
        JSONL (the shadow-stage disagreement evidence). Unlike the HTTP
        endpoint server, this in-process surface captures EVERY request
        (no percent sampling): it is the test/local path, and the
        evaluation wants deterministic evidence."""
        from dct_tpu.serving.runtime import score_payload

        ep = self.endpoints[endpoint]
        if slot is None:
            live = {k: v for k, v in ep.traffic.items() if v > 0}
            if not live:
                raise RuntimeError(f"Endpoint {endpoint} has no live traffic")
            slot = max(live, key=live.get)
        weights, meta = ep.deployments[slot].load()
        result = score_payload(weights, meta, payload["data"])
        if self.mirror_capture_path:
            import time as _time

            for shadow, pct in ep.mirror_traffic.items():
                if pct <= 0 or shadow == slot or shadow not in ep.deployments:
                    continue
                try:
                    w_s, m_s = ep.deployments[shadow].load()
                    shadow_result = score_payload(w_s, m_s, payload["data"])
                except Exception:  # noqa: BLE001 — a broken shadow is itself
                    continue  # a signal, but never a live-path failure
                self.append_mirror_record({
                    "ts": round(_time.time(), 6),
                    "endpoint": endpoint,
                    "live_slot": slot,
                    "shadow_slot": shadow,
                    "live_probs": result["probabilities"],
                    "shadow_probs": shadow_result["probabilities"],
                })
        return result
