"""In-memory endpoint client: the test/local-platform deployment target.

Implements the full :class:`~dct_tpu.deploy.rollout.EndpointClient` surface
with real serving semantics — ``score()`` actually loads the deployed
package's model.npz and answers inference requests — so the whole
train->track->package->rollout->infer path runs hermetically (the reference
can only exercise this against a live Azure subscription)."""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field


@dataclass
class _Deployment:
    package_dir: str
    weights: dict
    meta: dict


@dataclass
class _Endpoint:
    provisioning_state: str = "Succeeded"
    traffic: dict = field(default_factory=dict)
    mirror_traffic: dict = field(default_factory=dict)
    deployments: dict = field(default_factory=dict)


class LocalEndpointClient:
    def __init__(self):
        self.endpoints: dict[str, _Endpoint] = {}
        self.ops: list[tuple] = []  # audit log of control-plane calls

    # -- control plane -------------------------------------------------
    def endpoint_exists(self, endpoint: str) -> bool:
        return endpoint in self.endpoints

    def create_endpoint(self, endpoint: str) -> None:
        self.ops.append(("create_endpoint", endpoint))
        self.endpoints[endpoint] = _Endpoint()

    def delete_endpoint(self, endpoint: str) -> None:
        self.ops.append(("delete_endpoint", endpoint))
        self.endpoints.pop(endpoint, None)

    def provisioning_state(self, endpoint: str) -> str:
        return self.endpoints[endpoint].provisioning_state

    def get_traffic(self, endpoint: str) -> dict:
        if endpoint not in self.endpoints:
            return {}
        return dict(self.endpoints[endpoint].traffic)

    def set_traffic(self, endpoint: str, traffic: dict) -> None:
        self.ops.append(("set_traffic", endpoint, dict(traffic)))
        ep = self.endpoints[endpoint]
        unknown = set(k for k, v in traffic.items() if v > 0) - set(ep.deployments)
        if unknown:
            raise ValueError(f"Traffic to nonexistent deployments: {unknown}")
        ep.traffic = dict(traffic)

    def get_mirror_traffic(self, endpoint: str) -> dict:
        return dict(self.endpoints[endpoint].mirror_traffic)

    def set_mirror_traffic(self, endpoint: str, traffic: dict) -> None:
        self.ops.append(("set_mirror_traffic", endpoint, dict(traffic)))
        self.endpoints[endpoint].mirror_traffic = dict(traffic)

    def deploy(self, endpoint: str, slot: str, package_dir: str) -> None:
        import numpy as np

        self.ops.append(("deploy", endpoint, slot, package_dir))
        npz = np.load(os.path.join(package_dir, "model.npz"))
        with open(os.path.join(package_dir, "model_meta.json")) as f:
            meta = json.load(f)
        self.endpoints[endpoint].deployments[slot] = _Deployment(
            package_dir=package_dir,
            weights={k: npz[k] for k in npz.files},
            meta=meta,
        )

    def delete_deployment(self, endpoint: str, slot: str) -> None:
        self.ops.append(("delete_deployment", endpoint, slot))
        self.endpoints[endpoint].deployments.pop(slot, None)

    def list_deployments(self, endpoint: str) -> list[str]:
        return list(self.endpoints[endpoint].deployments)

    # -- data plane (what Azure's scoring URI does) --------------------
    def score(self, endpoint: str, payload: dict, *, slot: str | None = None) -> dict:
        """Route a request like the live endpoint would: to the given slot,
        or to the max-live-traffic slot."""
        from dct_tpu.serving.runtime import score_payload

        ep = self.endpoints[endpoint]
        if slot is None:
            live = {k: v for k, v in ep.traffic.items() if v > 0}
            if not live:
                raise RuntimeError(f"Endpoint {endpoint} has no live traffic")
            slot = max(live, key=live.get)
        dep = ep.deployments[slot]
        return score_payload(dep.weights, dep.meta, payload["data"])
