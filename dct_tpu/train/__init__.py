from dct_tpu.train.state import TrainState, create_train_state  # noqa: F401
from dct_tpu.train.steps import make_train_step, make_eval_step  # noqa: F401
from dct_tpu.train.trainer import Trainer, TrainResult  # noqa: F401
