"""Train state: params + optimizer state + step + rng, as one pytree.

The reference's equivalent state lives scattered across a LightningModule,
its implicit torch ``Adam`` state, and Lightning's loop counters
(jobs/train_lightning_ddp.py:51-88,131-143). Here it is a single immutable
pytree so the whole update is a pure function ``state -> state`` that XLA
compiles once and shards over the mesh, and that Orbax can checkpoint/restore
atomically (the reference can only save weights, never resume; SURVEY §5.4).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import optax
from flax import struct
from flax.core import FrozenDict


@struct.dataclass
class TrainState:
    step: jnp.ndarray  # scalar int32
    params: Any
    opt_state: Any
    rng: jax.Array  # dropout key, folded per step
    tx: optax.GradientTransformation = struct.field(pytree_node=False)
    apply_fn: Any = struct.field(pytree_node=False)

    def apply_gradients(self, grads) -> "TrainState":
        updates, new_opt_state = self.tx.update(grads, self.opt_state, self.params)
        new_params = optax.apply_updates(self.params, updates)
        return self.replace(
            step=self.step + 1, params=new_params, opt_state=new_opt_state
        )


def make_lr_schedule(
    lr: float, *, schedule: str = "constant", warmup_steps: int = 0,
    decay_steps: int = 0, end_lr_fraction: float = 0.0,
):
    """Learning-rate schedule factory (the reference has only a fixed
    ``Adam(lr=0.01)``, jobs/train_lightning_ddp.py:88 — 'constant' keeps
    that parity default).

    - ``constant``: fixed ``lr`` (optional linear warmup).
    - ``cosine``: optional linear warmup, then cosine decay over
      ``decay_steps`` post-warmup steps down to ``lr*end_lr_fraction``.
    """
    if schedule == "constant":
        if warmup_steps > 0:
            return optax.linear_schedule(0.0, lr, warmup_steps)
        return lr
    if schedule == "cosine":
        if decay_steps <= 0:
            raise ValueError("cosine schedule needs decay_steps > 0")
        cos = optax.cosine_decay_schedule(
            lr, decay_steps, alpha=end_lr_fraction
        )
        if warmup_steps > 0:
            return optax.join_schedules(
                [optax.linear_schedule(0.0, lr, warmup_steps), cos],
                [warmup_steps],
            )
        return cos
    raise ValueError(
        f"Unknown lr schedule '{schedule}' (expected constant|cosine)"
    )


def make_optimizer(
    rate, *, optimizer: str = "adam", weight_decay: float = 0.0,
    momentum: float = 0.0, grad_clip_norm: float = 0.0,
) -> optax.GradientTransformation:
    """Optimizer family selection (``DCT_OPTIMIZER``; reference is locked
    to ``Adam(lr=0.01)``, jobs/train_lightning_ddp.py:88):

    - ``adam`` (parity default): optax.adam; a positive ``weight_decay``
      auto-upgrades to AdamW (decoupled decay) — the long-standing
      behavior, so existing configs keep their trajectory.
    - ``adamw``: AdamW explicitly (decay may be 0).
    - ``sgd``: momentum trace + DECOUPLED weight decay (AdamW-style:
      the decay term joins AFTER the momentum trace and is scaled by
      lr alongside the update, never entering the momentum buffer) —
      deliberately unlike torch SGD's coupled L2.
    - ``adafactor``: factored second moments (rank-1 row/col statistics
      for matrices) — the classic TPU choice when optimizer memory
      matters; decay via ``weight_decay_rate``; ``momentum`` threads
      through natively.
    - ``lion``: sign-momentum optimizer; decay is built in.

    ``momentum`` on a family whose update rule has no such knob
    (adam/adamw/lion use betas) raises instead of silently ignoring the
    operator's intent.
    """
    opt = optimizer.strip().lower()
    if momentum and opt not in ("sgd", "adafactor"):
        raise ValueError(
            f"DCT_MOMENTUM={momentum} is only meaningful for sgd/"
            f"adafactor (got optimizer={optimizer!r}; adam/adamw/lion "
            "are governed by their betas)"
        )
    if opt == "adam":
        tx = (
            optax.adamw(learning_rate=rate, weight_decay=weight_decay)
            if weight_decay > 0.0
            else optax.adam(learning_rate=rate)
        )
    elif opt == "adamw":
        tx = optax.adamw(learning_rate=rate, weight_decay=weight_decay)
    elif opt == "sgd":
        parts = []
        if momentum:
            parts.append(optax.trace(decay=momentum))
        if weight_decay > 0.0:
            parts.append(optax.add_decayed_weights(weight_decay))
        parts.append(optax.scale_by_learning_rate(rate))
        tx = optax.chain(*parts)
    elif opt == "adafactor":
        tx = optax.adafactor(
            learning_rate=rate,
            momentum=momentum or None,
            weight_decay_rate=weight_decay if weight_decay > 0.0 else None,
        )
    elif opt == "lion":
        tx = optax.lion(learning_rate=rate, weight_decay=weight_decay)
    else:
        raise ValueError(
            f"DCT_OPTIMIZER={optimizer!r} not in "
            "('adam', 'adamw', 'sgd', 'adafactor', 'lion')"
        )
    if grad_clip_norm > 0.0:
        # Global-norm clipping BEFORE the optimizer (Lightning's
        # gradient_clip_val semantics); 0 preserves parity exactly.
        tx = optax.chain(optax.clip_by_global_norm(grad_clip_norm), tx)
    return tx


def create_train_state(
    model, *, input_dim: int, lr: float, seed: int,
    example_shape: tuple | None = None, lr_schedule=None,
    weight_decay: float = 0.0, grad_clip_norm: float = 0.0,
    optimizer: str = "adam", momentum: float = 0.0,
) -> TrainState:
    """Initialize params (torch-matching init lives in the model) and Adam.

    optax.adam defaults (b1=0.9, b2=0.999, eps=1e-8) match torch.optim.Adam
    defaults, so the optimizer trajectory is comparable to the reference's
    ``Adam(self.parameters(), lr=0.01)`` (jobs/train_lightning_ddp.py:88).

    ``example_shape`` defaults to the MLP's ``(1, input_dim)`` row; sequence
    models pass ``(1, seq_len, input_dim)``. ``lr_schedule`` (an optax
    schedule or float) overrides the flat ``lr``; resume restores the
    optimizer step count, so schedules continue where they left off.
    """
    root = jax.random.PRNGKey(seed)
    init_key, dropout_key = jax.random.split(root)
    shape = example_shape if example_shape is not None else (1, input_dim)
    # Jitted init: flax runs `init` eagerly by default, but the seq-parallel
    # attention paths gate their batch-1 shape-inference escape on seeing a
    # TRACER (ADVICE r3 — an eager small batch must raise, not silently go
    # dense), so the init computation must be a trace. Jit also skips
    # materializing throwaway init activations op-by-op.
    variables = jax.jit(model.init)(init_key, jnp.zeros(shape, jnp.float32))
    if isinstance(variables, FrozenDict):
        variables = variables.unfreeze()
    # Keep ONLY the trainable collection: models may sow auxiliary outputs
    # (e.g. MoE load-balance losses) into other collections during init,
    # which must not enter the optimizer.
    params = {"params": variables["params"]}
    rate = lr_schedule if lr_schedule is not None else lr
    tx = make_optimizer(
        rate, optimizer=optimizer, weight_decay=weight_decay,
        momentum=momentum, grad_clip_norm=grad_clip_norm,
    )
    return TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        opt_state=tx.init(params),
        rng=dropout_key,
        tx=tx,
        apply_fn=model.apply,
    )
