"""The training engine: SPMD epoch loop with tracking + checkpointing.

Capability-parity map to the reference's ``main()``
(jobs/train_lightning_ddp.py:90-164):

- MLFlowLogger(...)            -> tracking client (coordinator-only, §tracking)
- WeatherDataset + random_split -> load_processed_dataset + train_val_split
- DataLoader(batch_size=4)      -> BatchLoader (fixed-shape, process-sharded)
- pl.Trainer(num_nodes=W, DDPStrategy) + fit()
                                -> jitted train/eval steps over a Mesh; XLA
                                   all-reduces grads over ICI (no strategy
                                   object, no process group)
- ModelCheckpoint(top1+last)    -> BestLastCheckpointer (same filenames)
- sync_dist=True metric logging -> global weighted (sum,count) metrics
- rank-0 artifact upload        -> coordinator-gated log_artifact to
                                   "best_checkpoints"

Plus what the reference lacks: true resume from full optimizer state
(TrainStateCheckpointer) and per-epoch wall-clock/throughput accounting.
"""

from __future__ import annotations

import math
import os
import sys
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp

from dct_tpu.checkpoint.manager import (
    BestLastCheckpointer,
    TrainStateCheckpointer,
    needs_cross_process_gather,
    to_host,
)
from dct_tpu.config import RunConfig
from dct_tpu.data.dataset import WeatherArrays, load_processed_dataset
from dct_tpu.data.pipeline import BatchLoader, contiguous_split, train_val_split
from dct_tpu.models.registry import get_model, is_sequence_model
from dct_tpu.ops.losses import precision_recall_f1
from dct_tpu.parallel.distributed import is_coordinator
from dct_tpu.parallel.mesh import (
    make_global_batch,
    make_global_epoch,
    make_global_epoch_chunk,
    make_mesh,
    process_data_block,
)
from dct_tpu.parallel.sharding_rules import (
    dtype_rules_digest,
    layout_mismatches,
    rules_digest,
    shard_state_with_rules,
    state_shardings,
)
from dct_tpu.observability import lineage as _lineage
from dct_tpu.observability.events import event_log_from_config
from dct_tpu.observability.goodput import GoodputLedger
from dct_tpu.observability.health import HealthMonitor, TrainingHealthError
from dct_tpu.observability.heartbeat import HeartbeatWriter
from dct_tpu.observability.spans import recorder_from_config
from dct_tpu.resilience import faults as _faults
from dct_tpu.resilience.preempt import PreemptedError, PreemptionGuard
from dct_tpu.tracking.client import get_tracker
from dct_tpu.train.state import create_train_state
from dct_tpu.utils.profiling import EpochTimer, Profiler, annotate
from dct_tpu.train.steps import (
    make_epoch_train_eval_step,
    make_eval_step,
    make_train_step,
)


def early_stop_update(
    val_loss: float,
    best: float | None,
    stale: int,
    *,
    patience: int,
    min_delta: float,
) -> tuple[float | None, int, bool]:
    """One early-stopping step (monitor val_loss, min mode): returns the
    updated ``(best, stale, stop)``. A NaN val_loss never counts as an
    improvement — in particular a NaN on the FIRST monitored epoch must
    not seed ``best`` (nothing compares below NaN, which would turn every
    later finite epoch 'stale' and force a spurious stop)."""
    improved = not math.isnan(val_loss) and (
        best is None or val_loss < best - min_delta
    )
    if improved:
        return val_loss, 0, False
    return best, stale + 1, stale + 1 >= patience


def span_shadow_warning(
    history: list, span_end_vl_min: float, chunk: int
) -> str | None:
    """With ``epoch_chunk`` > 1 only span-END params exist on device, so
    the deploy "best" checkpoint can only ever hold a span-end epoch. If
    a mid-span epoch achieved the run's best val_loss, that optimum is
    recorded in history but unreachable by the checkpoint — a silent
    divergence operators should see named (ADVICE r4). Returns the
    warning line, or None."""
    if chunk <= 1 or not history:
        return None
    valid = [
        h["val_loss"] for h in history if not math.isnan(h["val_loss"])
    ]
    if not valid or min(valid) >= span_end_vl_min - 1e-12:
        return None
    return (
        f"[dct_tpu] epoch_chunk={chunk}: the run's best val_loss "
        f"{min(valid):.6f} occurred MID-span; the deploy 'best' "
        f"checkpoint holds the best span-END epoch "
        f"({span_end_vl_min:.6f}). Lower DCT_EPOCH_CHUNK if the deploy "
        "checkpoint must capture the optimum."
    )


def optimizer_identity(train_cfg) -> dict:
    """The knobs that select (and can reshape) the optax state tree
    (train.state.make_optimizer): the name picks the chain, ``momentum``
    > 0 adds the sgd trace leaf, and a positive ``weight_decay`` turns
    adam into adamw. Persisted in the train-state meta and compared
    EXACTLY on resume: two configs can produce structurally isomorphic
    opt_state trees (same leaf count, same shapes — e.g. adam vs adamw,
    whose decay transform holds no state), so the count/shape heuristic
    in checkpoint.manager.restore cannot catch a cross-restore between
    them (ADVICE r4). Values are plain JSON scalars so the comparison
    survives the meta.json round trip."""
    # Same normalization as state.make_optimizer: 'Adam' and ' adam'
    # build the identical chain and must not refuse each other.
    name = str(train_cfg.optimizer).strip().lower()
    wd = float(train_cfg.weight_decay)
    # Mirror make_optimizer's chain selection exactly (state.py): adam
    # with a positive weight_decay IS adamw, and adamw at wd == 0
    # degenerates to adam — spellings that build the identical chain
    # must not refuse each other's checkpoints.
    if name == "adam" and wd > 0:
        name = "adamw"
    elif name == "adamw" and wd == 0:
        name = "adam"
    return {
        "name": name,
        "momentum": float(train_cfg.momentum),
        "weight_decay": wd,
    }


@dataclass
class _SpanInFlight:
    """One dispatched span awaiting host bookkeeping (the pipelined
    loop's unit of deferral): its device result futures, the output
    state both checkpoint tiers will read, and the open trace spans the
    crash sweep must be able to close."""

    epoch0: int
    k: int
    n_steps: int
    state: object
    losses: object = None
    val_sums: object = None
    gnorms: object = None
    t_dispatch: float = 0.0
    # Host seconds the dispatch call itself blocked (jit tracing + XLA
    # compile on a program's first span, ~enqueue cost after). Pipelined
    # billing uses it: see _consume_span's ledger note.
    dispatch_elapsed: float = 0.0
    dispatch_span: object = None
    epoch_span: object = None


@dataclass
class TrainResult:
    val_loss: float
    val_acc: float
    best_model_path: str
    last_model_path: str
    history: list = field(default_factory=list)
    samples_per_sec: float = 0.0
    # Steady-state product throughput: mean per-chip rate over the epochs
    # AFTER the first (epoch 0 pays XLA compilation) — the honest number
    # the bench reports as trainer_loop_samples_per_sec_per_chip.
    steady_samples_per_sec_per_chip: float = 0.0
    run_id: str | None = None
    state: object | None = None
    # Goodput/badput summary (observability.goodput.GoodputLedger) and
    # the run-correlation ID every event record of this run carries.
    goodput: dict = field(default_factory=dict)
    run_correlation_id: str | None = None
    # Training-health summary (observability.health.HealthMonitor):
    # nan/spike event counts and the last loss/grad-norm observed.
    health: dict = field(default_factory=dict)


class Trainer:
    def __init__(
        self, cfg: RunConfig, *, mesh=None, tracker=None,
        preempt_guard=None,
    ):
        self.cfg = cfg
        # Caller-owned PreemptionGuard (the multi-tenant scheduler's
        # lease revocation channel): fit() consults it instead of
        # building its own, so another thread can request() a graceful
        # stop of a fit running off the main thread (where SIGTERM
        # never arrives).
        self._preempt_guard = preempt_guard
        self.mesh = mesh if mesh is not None else make_mesh(cfg.mesh)
        self.coordinator = is_coordinator()
        self.tracker = tracker if tracker is not None else get_tracker(
            tracking_uri=cfg.tracking.tracking_uri,
            experiment=cfg.tracking.experiment,
            coordinator=self.coordinator,
        )

    # ------------------------------------------------------------------
    def fit(self, data: WeatherArrays | None = None) -> TrainResult:
        cfg = self.cfg
        # Persistent compile cache (ROADMAP item 5): point jax at the
        # DCT_COMPILE_CACHE_DIR before this process's FIRST compile
        # (model init below is one) — a supervised relaunch then disk-
        # hits every program its dead predecessor already compiled.
        # No-op unless the env arms it (compilecache.cache docstring).
        from dct_tpu import compilecache as _compilecache

        _compilecache.enable_from_env()
        # Observability plane: structured events (installed as the
        # process default so the checkpoint/tracking layers stamp the
        # same run-correlation ID), the goodput ledger, and this rank's
        # heartbeat. Everything degrades to no-ops when disabled.
        events = event_log_from_config(
            cfg.obs, rank=jax.process_index()
        )
        # Span runtime: this rank's spans join the cycle-wide trace
        # (trace_id = run-correlation ID; if a launcher spawned us, its
        # DCT_SPAN_ID makes fit a child of the launch span).
        tracer = recorder_from_config(cfg.obs, rank=jax.process_index())
        fit_span = tracer.open(
            "trainer.fit", component="trainer",
            model=cfg.model.name, epochs=cfg.train.epochs,
            world_size=jax.process_count(),
        )
        # Training-health telemetry: every step's loss (and grad global
        # norm) flows through the monitor; findings become health.*
        # events and, under a halting policy, stop the run.
        health = HealthMonitor.from_config(cfg.obs, emit=events.emit)
        # Live per-epoch metrics (ISSUE 17): the coordinator publishes
        # val-loss / goodput / step-time gauges to the metrics plane at
        # epoch cadence, so the telemetry history store (DCT_TS_DIR)
        # sees the run WHILE it happens — the final dump replaces this
        # stream at run end. None when the plane is unarmed.
        from dct_tpu.observability.dump import live_train_metrics

        live_metrics = live_train_metrics(
            cfg.obs, run_id=events.run_id, rank=jax.process_index()
        )
        # Resilience plane: the deterministic fault plan (installed as
        # the process default so the checkpoint tiers consult the SAME
        # instance — shared save ordinals and fired flags), and the
        # graceful-preemption guard. The SIGTERM handler only sets a
        # flag; the trainer honors it at the next step/span boundary.
        plan = _faults.FaultPlan.parse(
            cfg.resilience.fault_spec,
            rank=jax.process_index(),
            sleep_s=cfg.resilience.fault_sleep_s,
        )
        _faults.set_default(plan)
        guard = (
            self._preempt_guard
            if self._preempt_guard is not None
            else PreemptionGuard()
        )
        if cfg.resilience.graceful_preemption:
            guard.install()
        ledger = GoodputLedger()
        ledger.start()
        # Supervised-relaunch accounting: the wall clock the failed
        # attempts (and backoff) cost this cycle, booked as
        # startup_recovery badput so the healed run's goodput fraction
        # reflects what the failure actually cost.
        if cfg.resilience.startup_debt_s > 0:
            ledger.add("startup_recovery", cfg.resilience.startup_debt_s)
        heartbeat = None
        if cfg.obs.enabled and cfg.obs.heartbeat_dir:
            heartbeat = HeartbeatWriter(
                cfg.obs.heartbeat_dir,
                jax.process_index(),
                run_id=events.run_id,
                min_interval=cfg.obs.heartbeat_interval,
            )
            heartbeat.beat(phase="startup", force=True)
        events.emit(
            "trainer", "fit_start",
            model=cfg.model.name, epochs=cfg.train.epochs,
            resume=cfg.train.resume, world_size=jax.process_count(),
        )
        _t_startup = ledger.clock()
        startup_span = tracer.start("trainer.startup", component="trainer")
        # Data-generation provenance for the always-on loop's freshness
        # accounting (dct_tpu.continuous): the incremental ETL stamps a
        # generation + arrival_ts into etl_state.json, read here BEFORE
        # the parquet load — so a checkpoint's stamped generation never
        # claims rows a concurrent ETL published after our snapshot.
        # Only when this fit loads the data itself: a caller-provided
        # array set has no provable tie to the processed dir.
        _data_provenance: dict = {}
        # Lineage ledger (installed as the process default alongside the
        # event log): checkpoints this run publishes get ``consumed``
        # edges to the dataset snapshot declared below.
        _lin = _lineage.ledger_from_config(cfg.obs, rank=jax.process_index())
        _lineage.set_run_inputs([])
        if data is None:
            from dct_tpu.etl.preprocess import read_etl_state

            _etl_state = read_etl_state(cfg.data.processed_dir)
            if _etl_state.get("generation"):
                _data_provenance = {
                    "data_generation": int(_etl_state["generation"]),
                    "data_arrival_ts": float(
                        _etl_state.get("arrival_ts") or 0.0
                    ),
                }
                # Stream-fed generations carry the committed offset
                # vector: the checkpoint names the exact log positions
                # its rows came from, the same way ``data_generation``
                # names the parquet snapshot.
                if _etl_state.get("stream_offsets") is not None:
                    _data_provenance["stream_offsets"] = [
                        int(o) for o in _etl_state["stream_offsets"]
                    ]
                # The ETL stamped its snapshot's lineage node id into the
                # state file — adopt it (no parquet re-hash) and put the
                # provenance dict on the graph record. A pre-lineage
                # state file (no stamp) re-addresses the snapshot dir by
                # content, landing on the same node id the ETL would
                # have minted.
                snap_nid = _etl_state.get("lineage_node")
                if _lin.enabled and not snap_nid:
                    snap_nid = _lin.node(
                        "dataset_snapshot",
                        path=os.path.join(
                            cfg.data.processed_dir, "data.parquet"
                        ),
                        attrs={
                            "generation": int(_etl_state["generation"]),
                        },
                    )
                elif _lin.enabled and snap_nid:
                    _lin.node(
                        "dataset_snapshot",
                        sha256=snap_nid.split(":", 1)[-1],
                        attrs=_data_provenance,
                    )
                _lineage.set_run_inputs([snap_nid])
        if data is None:
            data = load_processed_dataset(
                cfg.data.processed_dir,
                feature_suffix=cfg.data.feature_suffix,
                label_column=cfg.data.label_column,
            )

        # Sequence models train on sliding windows of the same stream; the
        # row-wise contract (and everything downstream: split, loader,
        # checkpointing) is unchanged because WindowArrays mirrors
        # WeatherArrays.
        sequence = is_sequence_model(cfg.model.name)
        if sequence:
            from dct_tpu.data.windows import make_windows
            from dct_tpu.models.registry import is_causal_model

            causal = is_causal_model(cfg.model.name)
            data = make_windows(
                data, cfg.model.seq_len,
                per_position_labels=causal,
                horizon=cfg.model.horizon if causal else 1,
            )
            # Overlapping windows leak under a random split; hold out the
            # TAIL of the stream, gapped by seq_len (+ the extra horizon
            # reach: train window i supervises label rows up to
            # i+seq_len+horizon-1) so no val window shares rows — feature
            # OR supervision — with any train window.
            gap = cfg.model.seq_len + (cfg.model.horizon - 1 if causal else 0)
            train_idx, val_idx = contiguous_split(
                len(data),
                val_fraction=cfg.data.val_fraction,
                gap=gap,
            )
        else:
            train_idx, val_idx = train_val_split(
                len(data), val_fraction=cfg.data.val_fraction, seed=cfg.train.seed
            )
        # Reference semantics: batch_size is per-rank (DataLoader(batch_size=4)
        # per container); global batch = per-device batch x data-parallel size.
        global_batch = cfg.train.batch_size * self.mesh.shape["data"]
        # Loader sharding follows the MESH, not the raw process count: DP
        # processes own distinct blocks of each global batch; processes that
        # only split the model/seq axes share their data rows and must feed
        # identical blocks (process_data_block encodes both cases).
        n_blocks, block_id = process_data_block(self.mesh)
        train_loader = BatchLoader(
            data, train_idx, global_batch=global_batch, shuffle=True,
            seed=cfg.train.seed, num_processes=n_blocks, process_id=block_id,
        )
        val_loader = BatchLoader(
            data, val_idx, global_batch=global_batch, shuffle=False,
            seed=cfg.train.seed, num_processes=n_blocks, process_id=block_id,
        )

        compute_dtype = jnp.bfloat16 if cfg.train.bf16_compute else jnp.float32
        if sequence:
            from dct_tpu.ops.attention import make_attention_fn

            model = get_model(
                cfg.model,
                input_dim=data.input_dim,
                compute_dtype=compute_dtype,
                attn_fn=make_attention_fn(self.mesh),
                mesh=self.mesh,
            )
            example_shape = (1, cfg.model.seq_len, data.input_dim)
        else:
            model = get_model(
                cfg.model, input_dim=data.input_dim, compute_dtype=compute_dtype
            )
            example_shape = None
        # Per-process state dir, constructed before the LR schedule: a
        # resumed run must size its cosine horizon from the restored
        # trajectory, not this run's budget alone.
        state_ckptr = TrainStateCheckpointer(
            os.path.join(
                cfg.data.models_dir, "train_state", f"p{jax.process_index()}"
            )
        )
        updates_per_epoch = train_loader.num_batches // max(
            1, cfg.train.grad_accum_steps
        )
        if cfg.train.grad_accum_steps > 1 and updates_per_epoch == 0:
            raise ValueError(
                f"grad_accum_steps={cfg.train.grad_accum_steps} exceeds the "
                f"{train_loader.num_batches} batches per epoch — every "
                "epoch would run ZERO optimizer updates"
            )

        lr_schedule = None
        # The decay horizon actually baked into the schedule (auto mode
        # resolves it from the restored trajectory): part of the AOT
        # store's program identity — the schedule's constants live
        # inside the compiled executable.
        resolved_decay = cfg.train.decay_steps
        if cfg.train.lr_schedule != "constant" or cfg.train.warmup_steps > 0:
            from dct_tpu.train.state import make_lr_schedule

            decay = cfg.train.decay_steps
            if cfg.train.lr_schedule == "cosine" and decay <= 0:
                # Auto: decay over the FULL trajectory. The optimizer's
                # restored update count already includes prior runs, so a
                # continuation sized only to THIS run's budget would start
                # at (or clamp to) the floor LR and train nothing.
                prior_epochs = 0
                if cfg.train.resume and state_ckptr.exists():
                    prior_epochs = int(
                        state_ckptr.load_meta().get("epochs_completed", 0)
                    )
                decay = max(
                    1,
                    (prior_epochs + cfg.train.epochs) * updates_per_epoch
                    - cfg.train.warmup_steps,
                )
            lr_schedule = make_lr_schedule(
                cfg.train.lr,
                schedule=cfg.train.lr_schedule,
                warmup_steps=cfg.train.warmup_steps,
                decay_steps=decay,
                end_lr_fraction=cfg.train.end_lr_fraction,
            )
            resolved_decay = decay
        state = create_train_state(
            model, input_dim=data.input_dim, lr=cfg.train.lr,
            seed=cfg.train.seed, example_shape=example_shape,
            lr_schedule=lr_schedule, weight_decay=cfg.train.weight_decay,
            grad_clip_norm=cfg.train.grad_clip_norm,
            optimizer=cfg.train.optimizer, momentum=cfg.train.momentum,
        )
        # Declarative partition rules: the per-family rule table (env-
        # overridable via DCT_SHARD_RULES) gives tensor-parallel
        # placement for the transformer family, full replication for
        # the MLP (no patterns match). TP/SP axes may span processes:
        # the checkpoint tier assembles such params with a cross-process
        # allgather (checkpoint.manager.to_host), called on EVERY rank
        # before the coordinator-gated write.
        state = shard_state_with_rules(
            state, self.mesh, shard_opt=cfg.train.shard_opt_state,
            shard_params=cfg.train.shard_params, family=cfg.model.name,
        )
        # The DECLARED layout. The jitted step's OUTPUT shardings can
        # drift from it — under ZeRO-1, XLA keeps the weight update (and
        # therefore the output params) sharded over ``data`` instead of
        # all-gathering — and the resume tier saves per-process local
        # shards of whatever layout the state actually has. Checkpoints
        # must be written in the declared layout, or a resumed process
        # (whose fresh template is the declared layout) cannot match the
        # saved shards to its topology. The first consumed span's output
        # is reconciled against this layout and any drift emitted as a
        # loud ``shard.layout_mismatch`` event (see _consume_span).
        declared_shardings = state_shardings(
            state, self.mesh, shard_opt=cfg.train.shard_opt_state,
            shard_params=cfg.train.shard_params, family=cfg.model.name,
        )

        # Continuous-training semantics (the reference re-trains from
        # scratch daily — its fit() never gets a ckpt_path, reference
        # jobs/train_lightning_ddp.py:143):
        # - no checkpoint          -> train epochs [0, cfg.train.epochs)
        # - interrupted prior run  -> finish to its saved target
        # - COMPLETED prior run    -> continue for cfg.train.epochs MORE
        #   epochs on the (possibly refreshed) data, keeping optimizer
        #   state — each DAG run extends the same optimization trajectory.
        start_epoch = 0
        target_epochs = cfg.train.epochs
        opt_identity = optimizer_identity(cfg.train)
        if cfg.train.resume and not state_ckptr.exists():
            # Cross-topology pivot: an MPMD session's per-stage
            # checkpoints (train_state_mpmd/stage<k>/, ISSUE 13) re-map
            # into the stacked SPMD layout — bitwise, pure data movement
            # — and this run resumes the same trajectory. An untileable
            # stage map (manifest stages != this model's n_stages)
            # refuses loudly inside the adoption.
            from dct_tpu.train import mpmd_trainer as _mpmd_tr

            _manifest = _mpmd_tr.read_manifest(cfg.data.models_dir)
            # Family-gated: a manifest left by a PP session must not
            # crash an unrelated family's resume in the same models_dir
            # (that run trains fresh, exactly as before the hook).
            if _manifest and _manifest.get("family") == cfg.model.name:
                _mpmd_tr.adopt_mpmd_checkpoint(cfg.data.models_dir, state)
        if cfg.train.resume and state_ckptr.exists():
            saved = state_ckptr.load_meta()
            saved_opt = saved.get("optimizer")
            if saved_opt is not None and saved_opt != opt_identity:
                # Named refusal BEFORE restore: opt_state trees of
                # different optimizer configs can be structurally
                # isomorphic (same leaf count/shapes), so the manager's
                # count/shape check would let a cross-restore through and
                # the run would train from mismatched moments.
                raise RuntimeError(
                    f"Resume refused: the checkpoint under "
                    f"{state_ckptr.dirpath} was written by optimizer "
                    f"{saved_opt} but this run configures {opt_identity}. "
                    "Restore the original DCT_OPTIMIZER / DCT_MOMENTUM / "
                    "DCT_WEIGHT_DECAY, or clear the train_state dir to "
                    "restart the trajectory."
                )
            # Restore yields host arrays; re-apply the mesh placement.
            state = shard_state_with_rules(
                state_ckptr.restore(state), self.mesh,
                shard_opt=cfg.train.shard_opt_state,
                shard_params=cfg.train.shard_params,
                family=cfg.model.name,
            )
            if "epochs_completed" in saved:
                start_epoch = int(saved["epochs_completed"])
            else:  # pre-meta checkpoint: derive from the step counter
                steps_per_epoch = max(train_loader.num_batches, 1)
                start_epoch = int(jax.device_get(state.step)) // steps_per_epoch
            saved_target = int(saved.get("target_epochs", cfg.train.epochs))
            if start_epoch >= saved_target:
                target_epochs = start_epoch + cfg.train.epochs
            else:
                target_epochs = saved_target
        if cfg.train.resume and jax.process_count() > 1:
            # All ranks must agree on start_epoch or the SPMD step counts
            # diverge and collectives deadlock. Fail loudly instead.
            from jax.experimental import multihost_utils

            epochs_seen = multihost_utils.process_allgather(
                jnp.asarray(start_epoch)
            )
            if int(epochs_seen.min()) != int(epochs_seen.max()):
                raise RuntimeError(
                    f"Resume divergence: per-process start epochs "
                    f"{list(map(int, epochs_seen))} differ. Sync or clear "
                    f"{os.path.join(cfg.data.models_dir, 'train_state')} "
                    "on every host."
                )

        ckptr = BestLastCheckpointer(cfg.data.models_dir)
        params_cross_process = needs_cross_process_gather(state.params)

        if start_epoch >= target_epochs:
            # Only reachable with epochs <= 0: the continuation semantics
            # above always extend the target past a completed run. Fail
            # LOUDLY — returning nan metrics here would let the DAG's
            # verify_model gate "pass" on a stale checkpoint having
            # trained nothing (VERDICT r1 weak-point 6).
            raise RuntimeError(
                f"Nothing to train: start_epoch={start_epoch} >= "
                f"target_epochs={target_epochs} (DCT_EPOCHS="
                f"{cfg.train.epochs}). Set a positive epoch budget."
            )
        use_scan = cfg.train.use_scan
        accum = max(1, cfg.train.grad_accum_steps)
        # Span pipelining (the dispatch-gap work): with prefetch_spans
        # >= 1, span e+1 is DISPATCHED before span e's bookkeeping runs,
        # so metric device_gets, the health pass, tracker/event logging,
        # and both checkpoint tiers' writes all overlap device compute
        # instead of serializing the hot loop. Bounded to ONE span in
        # flight past the bookkeeping (early-stop and health decisions
        # trail the device by at most that span — see _consume_span).
        # Auto-disabled under an armed fault plan: the injection drills
        # assert the exact serial crash/checkpoint ordering.
        pipelined = (
            use_scan
            and cfg.train.prefetch_spans >= 1
            and not plan.enabled
        )
        # AOT executable store (compilecache): the fused epoch programs
        # load-or-miss against <models_dir>/aot (override:
        # DCT_COMPILE_CACHE_AOT_DIR) — a resume snapshot's layout
        # carries its pre-compiled steps. The identity is the compile-
        # accounting key (family, model-config hash, resolved mesh)
        # PLUS the train knobs whose constants are baked into the
        # executable (optimizer chain, lr/schedule with its RESOLVED
        # decay horizon, precision, sharding, accumulation) and the
        # resolved donation mode — serial mode donates the input state,
        # and a donating executable loaded into the pipelined loop
        # would free a buffer the checkpoint tier still reads. Loop-
        # control knobs (epochs, resume, early-stop, logging cadence)
        # are deliberately OUT: a relaunch flips resume=1 and must
        # still hit. Disabled = a transparent pass-through.
        import dataclasses as _dc

        from dct_tpu.observability.goodput import (
            config_hash as _config_hash,
            mesh_descriptor as _mesh_descriptor,
        )

        _train_identity = {
            k: v
            for k, v in _dc.asdict(cfg.train).items()
            if k not in (
                "resume", "epochs", "log_every_n_steps",
                "early_stop_patience", "early_stop_min_delta",
                "prefetch_spans",
            )
        }
        _train_identity["decay_resolved"] = int(resolved_decay)
        # The partition-rule table is part of the program: a layout
        # change (DCT_SHARD_RULES, a family-table edit) compiles a
        # DIFFERENT executable — it must miss; the same layout must
        # warm-relaunch, sharded exactly like DP.
        _train_identity["shard_rules"] = rules_digest(cfg.model.name)
        # Same contract for the PRECISION table: the dtype rules pick
        # which param leaves run the step in bf16 (cast inside the
        # traced loss body, train/steps.py), so the compiled program
        # differs whenever they do — a precision change must be a loud
        # cache miss, never a stale full-width (or half-width)
        # executable. "off" when unset keys identically to every
        # pre-rules artifact.
        _train_identity["dtype_rules"] = dtype_rules_digest()
        aot_store = _compilecache.store_from_env(
            os.environ.get("DCT_COMPILE_CACHE_AOT_DIR")
            or os.path.join(cfg.data.models_dir, "aot"),
            family=cfg.model.name,
            config_hash=_config_hash(_dc.asdict(cfg.model)),
            mesh=_mesh_descriptor(self.mesh),
            extra={
                **_train_identity,
                "donate": not pipelined,
                "input_dim": data.input_dim,
            },
            emit=events.emit,
        )
        if use_scan:
            # Built only for the per-epoch path: with epoch_chunk > 1
            # every span (including k == 1 remainders) dispatches the
            # multi-epoch program instead. Span stacks are single-use in
            # the trainer, so donating them frees a full span of HBM
            # before the step's activations peak. The STATE is donated
            # only in serial mode: pipelined bookkeeping still reads the
            # previous span's output state (checkpoint gather + resume
            # snapshot) while the next span computes from it, so that
            # buffer must survive the dispatch — one extra resident
            # state copy is the documented price of the overlap.
            if max(1, cfg.train.epoch_chunk) == 1:
                epoch_fused = aot_store.wrap(make_epoch_train_eval_step(
                    donate=not pipelined,
                    accum_steps=accum, donate_stacks=True,
                    with_grad_norms=True,
                ))
        else:
            train_step = make_train_step(
                accum_steps=accum, with_grad_norm=True
            )
            eval_step = make_eval_step()

        # Self-describing checkpoint meta: the FULL model config (whichever
        # family), plus the data-derived facts — enough to rebuild the model
        # from the checkpoint alone.
        import dataclasses as _dc

        meta = {
            **_dc.asdict(cfg.model),
            "model": cfg.model.name,
            "input_dim": data.input_dim,
            "feature_names": list(data.feature_names),
            # Which ETL generation this trajectory extension trained on
            # (empty pre-incremental-ETL): the loop's evaluator reads it
            # off the packaged meta to attribute promotion freshness.
            **_data_provenance,
        }
        meta.pop("name", None)
        run_id = self.tracker.start_run(params={**meta, "lr": cfg.train.lr,
                                                "batch_size": cfg.train.batch_size,
                                                "epochs": cfg.train.epochs,
                                                "seed": cfg.train.seed,
                                                # The split this run was
                                                # validated on: the deploy
                                                # side's eval harness must
                                                # rebuild EXACTLY it
                                                # (prepare_package stamps
                                                # both into the package
                                                # manifest).
                                                "val_fraction": cfg.data.val_fraction,
                                                "global_batch": global_batch})

        history: list[dict] = []
        global_step = int(jax.device_get(state.step))
        # Throughput accounting + optional one-epoch jax.profiler trace
        # (SURVEY §5.1: the reference installs TensorBoard but never writes
        # it — here the trace is real TB-compatible profile data).
        from dct_tpu.utils.profiling import (
            chip_peak_flops, transformer_train_flops,
        )

        flops_per_sample = None
        if cfg.model.name in ("weather_transformer", "weather_transformer_pp"):
            flops_per_sample = transformer_train_flops(
                d_model=cfg.model.d_model, d_ff=cfg.model.d_ff,
                seq_len=cfg.model.seq_len, n_heads=cfg.model.n_heads,
                n_layers=cfg.model.n_layers, input_dim=data.input_dim,
                batch=1, num_classes=cfg.model.num_classes,
            )
        timer = EpochTimer(
            n_chips=self.mesh.size,
            flops_per_sample=flops_per_sample,
            peak_flops=chip_peak_flops(),
            ledger=ledger,
        )
        profiler = Profiler(
            cfg.profile.trace_dir,
            enabled=cfg.profile.enabled,
            epoch=min(cfg.profile.epoch, target_epochs - 1),
            coordinator=self.coordinator,
        )
        # On-demand flight recorder (observability/capture.py): a
        # DCT_PROFILE_TRIGGER touch or SIGUSR2 starts a per-rank
        # jax.profiler capture at the next span boundary, mid-run,
        # without stopping training. Polling is one stat per span.
        from dct_tpu.observability.capture import (
            recorder_from_config as _flight_from_config,
        )

        flight = _flight_from_config(
            cfg.profile, rank=jax.process_index(), emit=events.emit,
        )

        # Pre-staged validation arrays (order is fixed): stacked AND
        # transferred to device once, reused every epoch.
        if use_scan:
            val_global = make_global_epoch(
                self.mesh, *self._stack_epoch(val_loader, 0)
            )

        es_best: float | None = None
        es_stale = 0
        # For the epoch_chunk > 1 shadowing diagnostic: only span-END
        # params ever exist on device, so only span-end epochs can become
        # the deploy "best" checkpoint (ADVICE r4).
        span_end_vl_min = float("inf")

        # Epoch chunking (scan path): fuse K epochs into one dispatch.
        # On a slow control plane every epoch pays a host round trip that
        # can dwarf the compute at parity batch sizes; chunking amortizes
        # it to 1/K. Per-epoch metrics are preserved (the fused program
        # returns losses[K, S] and a 6-tuple of [K] eval sums); checkpoints, resume
        # snapshots, and early-stop effects move to chunk boundaries
        # (config.TrainConfig.epoch_chunk documents the trade).
        chunk = max(1, cfg.train.epoch_chunk) if use_scan else 1
        multi_fused = None
        if chunk > 1:
            from dct_tpu.train.steps import make_multi_epoch_train_eval_step

            multi_fused = aot_store.wrap(make_multi_epoch_train_eval_step(
                donate=not pipelined,
                accum_steps=accum, donate_stacks=True,
                with_grad_norms=True,
            ))

        # Epoch-ahead input pipeline (scan path): the next span's host
        # batch assembly + H2D staging runs on a worker thread WHILE the
        # current span computes on device — shuffle/stack/device_put leave
        # the step critical path (device_put is async; the transfer itself
        # also overlaps compute). One span deep: bounded host memory, and
        # the device queue never sees stale epochs after an early stop.
        def _assemble_span(e0: int, k: int):
            # Annotated HERE so the profiler span follows the work onto
            # the prefetch thread (the consumer side only joins a future).
            with annotate("host_epoch_assembly"):
                per = []
                for e in range(e0, e0 + k):
                    xs, ys, ws = self._stack_epoch(train_loader, e)
                    # Data-pipeline fault hook: a `nan` clause poisons
                    # this epoch's staged features, so the non-finite
                    # loss arrives through the REAL compute path and the
                    # health policy (warn/halt) is exercised end-to-end.
                    if plan.enabled and plan.check("data", epoch=e):
                        import numpy as _np

                        xs = _np.array(xs, copy=True)
                        xs[0, ...] = _np.nan
                    if accum > 1:
                        # Whole accumulation groups only; the ragged tail
                        # (< accum batches) is dropped, like drop_last on
                        # the group granularity.
                        s_eff = (xs.shape[0] // accum) * accum
                        xs, ys, ws = xs[:s_eff], ys[:s_eff], ws[:s_eff]
                    per.append((xs, ys, ws))
                if k == 1 and multi_fused is None:
                    xs, ys, ws = per[0]
                    return xs.shape[0], make_global_epoch(
                        self.mesh, xs, ys, ws
                    )
                import numpy as _np

                kxs = _np.stack([p[0] for p in per])
                kys = _np.stack([p[1] for p in per])
                kws = _np.stack([p[2] for p in per])
                return kxs.shape[1], make_global_epoch_chunk(
                    self.mesh, kxs, kys, kws
                )

        prefetch_pool = None
        prefetched = None
        if use_scan and cfg.train.prefetch_spans >= 1:
            from concurrent.futures import ThreadPoolExecutor

            prefetch_pool = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="epoch-prefetch"
            )
        # Everything up to here — dataset load, model init, state
        # creation/sharding, resume restore, validation staging — is the
        # run's startup/recovery cost in the goodput ledger (and the
        # trainer.startup span: the ledger's window, on the timeline).
        ledger.add("startup_recovery", ledger.clock() - _t_startup)
        startup_span.end(resumed=start_epoch > 0)
        completed = False
        preempted = False
        # In-flight phase spans, tracked so a crash mid-epoch still
        # records them (Span.end is idempotent: the success path's own
        # end() wins and the crash-path sweep becomes a no-op).
        epoch_span = dispatch_span = ckpt_span = None
        # Pipelined mode: the one dispatched-but-unbookkept span. Its
        # results are consumed one iteration late, while the NEXT span
        # computes on device; the crash sweep also closes its spans.
        pending = None
        consumed_through = start_epoch
        timer_running = False
        layout_checked = False

        def _bookkeep_span(sp, sub_epochs, epoch_stats, span_updates):
            """Every host-side consequence of a finished span: goodput
            report, per-epoch history/tracker/event records, early-stop
            updates, and BOTH checkpoint tiers. Shared by the scan
            path's consume (where, pipelined, it all overlaps the next
            span's device compute) and the eager path. Returns
            ``stop_early``."""
            nonlocal es_best, es_stale, span_end_vl_min
            nonlocal consumed_through, ckpt_span, layout_checked
            e0, k = sp.epoch0, sp.k
            # Declared-vs-actual layout reconciliation, once, on the
            # FIRST span the jitted step produced: its output shardings
            # can drift from the declared rule layout (ZeRO-1 keeps the
            # updated params data-sharded), and silently checkpointing
            # whatever layout fell out is how a resume refusal is born.
            # The drift goes on the record LOUDLY; the device_put re-pin
            # below reconciles the checkpoint to the declared layout.
            if not layout_checked:
                layout_checked = True
                _drift = layout_mismatches(sp.state, declared_shardings)
                if _drift:
                    events.emit(
                        "shard", "shard.layout_mismatch",
                        leaves=len(_drift),
                        reconciled=True,
                        examples=_drift[:3],
                    )
            # Per-span goodput: category deltas since the previous
            # report, logged to the tracker next to val_loss so a
            # goodput regression is queryable like an accuracy one.
            span_goodput = ledger.epoch_report()
            if heartbeat is not None:
                heartbeat.beat(
                    step=global_step, epoch=e0 + k - 1, phase="train"
                )
            # Per-epoch bookkeeping for every epoch in the span; with
            # k > 1 the chunk is the dispatch unit, so wall time is
            # span-amortized and the metric step is reconstructed per
            # epoch from the update count.
            per_epoch_updates = span_updates // k if k else 0
            last_rec = None
            stop_early = False
            for i, (epoch_loss, val_loss, val_acc, (tp, fp, fn)) in (
                enumerate(sub_epochs)
            ):
                epoch_rec = {
                    "epoch": e0 + i,
                    "train_loss": epoch_loss if epoch_loss is not None else float("nan"),
                    "val_loss": val_loss,
                    "val_acc": val_acc,
                }
                epoch_metrics = {
                    "train_loss_epoch": epoch_rec["train_loss"],
                    "val_loss": val_loss,
                    "val_acc": val_acc,
                    "epoch_time": epoch_stats.seconds / k,
                    "samples_per_sec": epoch_stats.samples_per_sec,
                    "samples_per_sec_per_chip": epoch_stats.samples_per_sec_per_chip,
                    # Span-level fraction (the span is the dispatch
                    # unit; every epoch in it shares the value).
                    "goodput_fraction": span_goodput["goodput_fraction"],
                }
                if cfg.model.num_classes == 2:
                    # Positive class 1 = "rain" (the reference's label
                    # encoding, jobs/preprocess.py:23-25). One-vs-rest
                    # counts would mislead for num_classes > 2, so the
                    # P/R/F1 surface is binary-only.
                    val_precision, val_recall, val_f1 = precision_recall_f1(
                        tp, fp, fn
                    )
                    epoch_rec["val_f1"] = val_f1
                    epoch_metrics.update(
                        val_precision=val_precision,
                        val_recall=val_recall,
                        val_f1=val_f1,
                    )
                history.append(epoch_rec)
                if epoch_stats.mfu is not None:
                    epoch_metrics["mfu"] = epoch_stats.mfu
                metric_step = (
                    global_step - span_updates
                    + (i + 1) * per_epoch_updates
                    if use_scan else global_step
                )
                self.tracker.log_metrics(epoch_metrics, step=metric_step)
                events.emit(
                    "trainer", "epoch_end",
                    epoch=e0 + i,
                    train_loss=epoch_rec["train_loss"],
                    val_loss=val_loss, val_acc=val_acc,
                    goodput_fraction=span_goodput["goodput_fraction"],
                )
                if live_metrics is not None:
                    live_metrics.epoch_end(
                        val_loss=val_loss,
                        goodput_fraction=span_goodput["goodput_fraction"],
                        samples_per_sec=epoch_stats.samples_per_sec,
                        step_seconds=(
                            (epoch_stats.seconds / k)
                            / max(1, per_epoch_updates)
                        ),
                        grad_norm=health.last_grad_norm,
                    )
                last_rec = epoch_rec
                # Early stopping (monitor val_loss, min mode — the
                # companion of the reference's ModelCheckpoint
                # policy). val_loss is a globally-reduced scalar, so
                # every SPMD rank takes the same branch; a nan never
                # counts as an improvement (including as the first
                # es_best). Inside a span the epochs already ran on
                # device; the stop takes effect at the span boundary,
                # and the es state freezes at the trigger point.
                if cfg.train.early_stop_patience > 0 and not stop_early:
                    es_best, es_stale, stop_early = early_stop_update(
                        val_loss, es_best, es_stale,
                        patience=cfg.train.early_stop_patience,
                        min_delta=cfg.train.early_stop_min_delta,
                    )
            _span_end_vl = sub_epochs[-1][1]
            if not math.isnan(_span_end_vl):
                span_end_vl_min = min(span_end_vl_min, _span_end_vl)
            profiler.maybe_stop_span(e0, k)
            # Host-gather BEFORE the coordinator gate: with TP/SP
            # spanning processes this is a collective every rank must
            # join; in the common fully-addressable case only the
            # coordinator pays the device-to-host copy. Pipelined: the
            # gathered state is the NEXT span's live input — valid
            # because the fused step does not donate it in that mode.
            _t_ckpt = ledger.clock()
            # open (stack-pushed), not start: the checkpoint manager's
            # own spans (checkpoint.deploy_write) parent implicitly to
            # this thread's stack top, and they belong under the
            # trainer.checkpoint window. Safe under pipelining — the
            # whole push/end window is synchronous inside this consume,
            # nothing else touches the stack in between.
            ckpt_span = tracer.open(
                "trainer.checkpoint", component="trainer",
                epoch=e0 + k - 1, parent_id=sp.epoch_span.span_id,
            )
            if params_cross_process or self.coordinator:
                host_params = to_host(sp.state.params)
            if self.coordinator:
                # Deploy-checkpoint policy at span granularity: only
                # the span-end params exist on device, so best/last
                # selection sees the span-end epoch's metrics (k == 1
                # reduces to the per-epoch policy exactly).
                _, last_vl, last_va, _ = sub_epochs[-1]
                ckpt_metrics = {"val_loss": last_vl, "val_acc": last_va}
                if "val_f1" in last_rec:
                    ckpt_metrics["val_f1"] = last_rec["val_f1"]
                ckptr.update(
                    epoch=e0 + k - 1,
                    metrics=ckpt_metrics,
                    params=host_params,
                    meta=meta,
                )

            # Every process keeps its own resume state (host-local
            # disk) plus the run facts the next run's continuation
            # semantics are decided from. The write overlaps the next
            # epoch's compute (device->host snapshot is synchronous;
            # the npz/rotation runs on a worker thread). On an early
            # stop the run is marked COMPLETE at the stop point
            # (target_epochs = epochs_completed) so a resumed run
            # EXTENDS (continuous semantics) instead of "finishing"
            # the abandoned target.
            # Re-pin to the declared layout before snapshotting (a
            # no-op for leaves already there; a collective reshard —
            # every rank calls it — for any the step's output layout
            # drifted, e.g. ZeRO-1 output params).
            state_ckptr.save_async(
                jax.device_put(sp.state, declared_shardings),
                meta={
                    "epochs_completed": e0 + k,
                    "target_epochs": (
                        e0 + k if stop_early else target_epochs
                    ),
                    # Exact resume refusal across optimizer configs
                    # whose state trees are isomorphic (ADVICE r4).
                    "optimizer": opt_identity,
                },
            )
            # Both checkpoint tiers' synchronous cost (host gather,
            # deploy-tier writes, the resume snapshot's device->host
            # copy; the npz write itself overlaps on a worker thread).
            ledger.add("checkpoint", ledger.clock() - _t_ckpt)
            ckpt_span.end()
            sp.epoch_span.end(val_loss=sub_epochs[-1][1])
            consumed_through = e0 + k
            return stop_early

        def _consume_span(sp):
            """Join span ``sp``'s device results and run all its host
            bookkeeping. Serial mode calls it right after dispatch;
            pipelined mode one span late, while the NEXT span computes
            on device (so early-stop/health decisions trail the device
            by at most one span — the documented trade). Returns
            ``stop_early``."""
            nonlocal global_step, dispatch_span, epoch_span
            import numpy as _np

            e0, k = sp.epoch0, sp.k
            # Point the crash sweep at the span being joined: if the
            # join or its bookkeeping dies, THESE are the spans still
            # in flight (a pipelined successor's live in pending).
            dispatch_span = sp.dispatch_span
            epoch_span = sp.epoch_span
            _t_join = ledger.clock()
            # The device_get joins the span's program; the D2H copies
            # were started right after its dispatch, so in steady
            # pipelined state the bytes are already on the host.
            if multi_fused is not None:
                # [K, S] losses; val_sums is a 6-tuple of [K] arrays
                # (dtype-preserving per leaf — see
                # make_multi_epoch_train_eval_step). Stack host-side as
                # float64 -> [K, 6]; the upcast only protects the
                # stacking, precision is bounded by the on-device f32
                # accumulation (exact for integral weights up to 2^24
                # per epoch, steps.py).
                losses_host = _np.asarray(jax.device_get(sp.losses))
                gnorms_host = _np.asarray(jax.device_get(sp.gnorms))
                val_host = _np.stack(
                    [
                        _np.asarray(v, dtype=_np.float64)
                        for v in jax.device_get(sp.val_sums)
                    ],
                    axis=1,
                )
            else:  # [S] / 6-tuple — the k == 1 parity layout
                losses_host = _np.asarray(
                    jax.device_get(sp.losses)
                )[None]
                gnorms_host = _np.asarray(
                    jax.device_get(sp.gnorms)
                )[None]
                val_host = _np.asarray(
                    [float(v) for v in jax.device_get(sp.val_sums)]
                )[None]
            # Fused dispatch (train + eval in one program) bills to
            # train_step; its first occurrence per program shape is the
            # compile. Serial: one window, dispatch -> results joined
            # (the historical accounting). Pipelined: the wall interval
            # dispatch(e) -> consume(e) CONTAINS other billed windows
            # (the previous span's checkpoint, the next span's
            # data_wait), so billing it whole would double-count and
            # push goodput_fraction past 1 — bill only the two
            # main-thread-blocking windows instead: the dispatch call
            # itself (trace + compile + enqueue, captured at dispatch)
            # plus the join above. Device time overlapped by host
            # bookkeeping is exactly the overlap the mode buys; it
            # surfaces as the other categories' windows, never twice.
            _billed = (
                (sp.dispatch_elapsed + (ledger.clock() - _t_join))
                if pipelined
                else (ledger.clock() - sp.t_dispatch)
            )
            _billed_cat = ledger.add_dispatch(
                "train_step", f"scan_k{k}", _billed,
            )
            sp.dispatch_span.end()
            # The fused program runs the validation pass(es) inside the
            # timed window; credit them to MFU. Pipelined throughput
            # windows chain consume-to-consume (they tile the loop's
            # wall clock); serial keeps the historical start-to-join
            # window.
            epoch_stats = timer.stop(
                e0, k * sp.n_steps * global_batch,
                eval_samples=k * len(val_idx),
            )
            if pipelined and _billed_cat != "compile":
                # Roofline truth-up: the goodput bill above is only the
                # host-BLOCKING part of the window (the overlap the
                # pipelined mode buys); the per-program MFU join needs
                # the wall window the dispatch actually occupied — the
                # consume-to-consume timer window just closed.
                ledger.amend_dispatch_window(
                    f"scan_k{k}", epoch_stats.seconds - _billed,
                )
            if pipelined:
                timer.start()
            flat = losses_host.reshape(-1)
            # log_every_n_steps cadence without one Python iteration
            # per step: visit only the multiples (identical records).
            n_log = max(1, cfg.train.log_every_n_steps)
            for i in range(
                (-(global_step + 1)) % n_log, flat.size, n_log
            ):
                self.tracker.log_metrics(
                    {"train_loss": float(flat[i])},
                    step=global_step + i + 1,
                )
            global_step += flat.size
            # Step-trigger faults on the scan path fire at the span
            # boundary — steps inside a fused dispatch are not
            # individually interruptible from the host.
            if plan.enabled:
                plan.maybe_fire(
                    "step", step=global_step,
                    pre_exit=state_ckptr.wait,
                )
            # Health pass over the span's per-step losses and grad
            # norms BEFORE any epoch bookkeeping: under a halting
            # policy the run stops here — no epoch_end, no checkpoint
            # of the diverged state. (Pipelined: the successor span
            # already in flight is abandoned by the raise — at most one
            # extra span of device work, never an extra checkpoint.)
            halt_finding = health.observe_span(
                flat, gnorms_host.reshape(-1),
                start_step=global_step - flat.size,
                epoch=e0, steps_per_epoch=max(1, flat.size // k),
            )
            if halt_finding is not None:
                # Close the epoch span BEFORE raising: the halted epoch
                # is exactly the one the operator opens the trace to
                # inspect.
                sp.epoch_span.end(halted=halt_finding.kind)
            HealthMonitor.raise_on(halt_finding)
            # Reference parity: the logged train_loss is the
            # EPOCH-AGGREGATED mean (Lightning epoch aggregation of
            # jobs/train_lightning_ddp.py:70), not the last batch —
            # one (train_loss, val_loss, val_acc, counts) entry per
            # epoch in the span.
            sub_epochs = []
            for i in range(k):
                ls, accs, c, tp, fp, fn = (
                    float(v) for v in val_host[i]
                )
                sub_epochs.append((
                    float(losses_host[i].mean())
                    if losses_host[i].size else None,
                    ls / c if c else float("nan"),
                    accs / c if c else float("nan"),
                    (tp, fp, fn),
                ))
            return _bookkeep_span(sp, sub_epochs, epoch_stats, flat.size)

        try:
            epoch = start_epoch
            stop_early = False
            while epoch < target_epochs:
                # Pipelined early-stop guard: if the un-bookkept span
                # could trip the stop, consume it BEFORE dispatching
                # more work (serial fallback for exactly this span, so
                # the stop decision is never speculated past).
                if (
                    pending is not None
                    and cfg.train.early_stop_patience > 0
                    and es_stale + pending.k
                    >= cfg.train.early_stop_patience
                ):
                    _sp, pending = pending, None
                    stop_early = _consume_span(_sp)
                    if guard.requested:
                        self._preempt_exit(
                            guard, events, state_ckptr,
                            epochs_completed=consumed_through,
                        )
                    if stop_early:
                        break
                # Trainer fault hook at the epoch boundary (`crash` /
                # `hang` / `slow_epoch` clauses). A crash first joins
                # any in-flight resume-snapshot write so the death
                # leaves a deterministic resume point — torn-write
                # recovery has its own injector (`crash_save`).
                # (Pipelining is auto-disabled while a plan is armed,
                # so the hook always sees fully-bookkept prior epochs.)
                if plan.enabled:
                    plan.maybe_fire(
                        "epoch", epoch=epoch, pre_exit=state_ckptr.wait
                    )
                k = min(chunk, target_epochs - epoch) if use_scan else 1
                # Span boundary = the flight recorder's poll point: an
                # operator trigger starts (or a passed deadline stops)
                # a capture here, between dispatches, never inside one.
                flight.poll(epoch=epoch)
                profiler.maybe_start_span(epoch, k)
                # One span per dispatch unit: the trace's "trainer
                # epochs" row. Parenting is EXPLICIT (not thread-stack):
                # pipelined, span e is still open when span e+1 starts,
                # so stack-implicit parenting would chain epochs under
                # each other and leak the stack.
                epoch_span = tracer.start(
                    "trainer.epoch", component="trainer",
                    epoch=epoch, k=k, parent_id=fit_span.span_id,
                )
                # Pipelined throughput windows chain consume-to-consume
                # (started once here, re-armed by each consume); serial
                # keeps one window per span, started at the boundary.
                if not (pipelined and timer_running):
                    timer.start()
                    timer_running = True
                if use_scan:
                    # Goodput: joining the prefetch future (or assembling
                    # inline) is time the DEVICE spends waiting on data.
                    with ledger.span("data_wait"), tracer.span(
                        "trainer.data_wait", component="trainer",
                        epoch=epoch, parent_id=epoch_span.span_id,
                    ):
                        if prefetched is not None:
                            n_steps, globs = prefetched.result()
                        else:
                            n_steps, globs = _assemble_span(epoch, k)
                    # Train span + full eval in ONE dispatch (the saved
                    # host round trips are most of an epoch's wall time
                    # on a slow control plane at the parity batch size).
                    # Beat BEFORE the span's dispatch: the fused program
                    # can legitimately block for minutes (first-span
                    # compile, k fused epochs), and the monitor must see
                    # the rank reached the dispatch rather than ageing
                    # the previous span-end beat across the whole gap.
                    # (Size DCT_HEARTBEAT_STALL_SECONDS above the
                    # longest expected single dispatch.)
                    if heartbeat is not None:
                        heartbeat.beat(
                            step=global_step, epoch=epoch, phase="dispatch",
                        )
                    # The dispatch window closes at block_until_ready
                    # below; a span of k epochs and a ragged remainder
                    # span are DIFFERENT XLA programs, so the ledger's
                    # compile detection keys on k.
                    # dct: begin-no-host-sync — the pipelined dispatch
                    # region: from here until the consume swap, nothing
                    # may join device results (device_get, float()/int()
                    # on arrays, .block_until_ready()) or the one-span
                    # overlap PR 5 bought collapses back to serial. The
                    # join belongs in _consume_span, one span later.
                    # Enforced by dct-lint rule `span-sync`.
                    _t_dispatch = ledger.clock()
                    dispatch_span = tracer.start(
                        "trainer.dispatch", component="trainer",
                        epoch=epoch, k=k, key=f"scan_k{k}",
                        parent_id=epoch_span.span_id,
                    )
                    # `key=` threads the goodput dispatch key into the
                    # AOT store so cache hit/miss states line up 1:1
                    # with the compile.window accounting below.
                    if multi_fused is not None:
                        state, losses, val_sums, gnorms = multi_fused(
                            state, *globs, *val_global, key=f"scan_k{k}"
                        )
                    else:
                        state, losses, val_sums, gnorms = epoch_fused(
                            state, *globs, *val_global, key=f"scan_k{k}"
                        )
                    # Host-blocking cost of the dispatch call itself
                    # (jit trace + XLA compile on the first span of a
                    # program shape; ~enqueue after) — the pipelined
                    # ledger bills this window separately from the
                    # consume-time join so category windows stay
                    # main-thread sequential (never double-counted).
                    _dispatch_elapsed = ledger.clock() - _t_dispatch
                    # Non-blocking bookkeeping: start the D2H copies of
                    # everything consume will read NOW, so by the time
                    # the span is bookkept the bytes are already on the
                    # host and device_get just unblocks.
                    for _buf in (losses, gnorms, *val_sums):
                        try:
                            _buf.copy_to_host_async()
                        except (AttributeError, RuntimeError):
                            break
                    # Prefetch the next span UNLESS early stopping is
                    # armed and could trigger within this span or the
                    # still-unbookkept previous one: the next span may
                    # never run, and a speculative multi-epoch H2D
                    # would sit in HBM through checkpointing/upload
                    # for nothing.
                    speculative_ok = not (
                        cfg.train.early_stop_patience > 0
                        and es_stale
                        + (pending.k if pending is not None else 0)
                        + k
                        >= cfg.train.early_stop_patience
                    )
                    nxt = epoch + k
                    if (
                        prefetch_pool is not None
                        and nxt < target_epochs
                        and speculative_ok
                    ):
                        prefetched = prefetch_pool.submit(
                            _assemble_span, nxt,
                            min(chunk, target_epochs - nxt),
                        )
                    else:
                        prefetched = None
                    cur = _SpanInFlight(
                        epoch0=epoch, k=k, n_steps=n_steps, state=state,
                        losses=losses, val_sums=val_sums, gnorms=gnorms,
                        t_dispatch=_t_dispatch,
                        dispatch_elapsed=_dispatch_elapsed,
                        dispatch_span=dispatch_span,
                        epoch_span=epoch_span,
                    )
                    # dct: end-no-host-sync — the consume below is the
                    # intended join point (serial mode joins its own
                    # span; pipelined joins the PREVIOUS one).
                    if pipelined:
                        # Swap FIRST: if consuming the previous span
                        # raises (health halt), the finally sweep still
                        # finds the in-flight successor via `pending`.
                        _sp, pending = pending, cur
                        stop_early = (
                            _consume_span(_sp) if _sp is not None
                            else False
                        )
                    else:
                        stop_early = _consume_span(cur)
                else:
                    import numpy as _np

                    loss_sum = 0.0
                    n_steps = 0
                    n_updates = 0
                    # Data-pipeline fault hook (eager path): poison the
                    # epoch's first staged group.
                    poison = plan.enabled and bool(
                        plan.check("data", epoch=epoch)
                    )
                    group: list = []
                    for batch in train_loader.epoch(epoch):
                        group.append(batch)
                        if len(group) < accum:
                            continue
                        with annotate("host_batch_staging"), \
                                ledger.span("data_wait"):
                            if accum > 1:
                                bx = _np.concatenate([b.x for b in group])
                                by = _np.concatenate([b.y for b in group])
                                bw = _np.concatenate(
                                    [b.weight for b in group]
                                )
                            else:
                                bx, by, bw = (
                                    group[0].x, group[0].y,
                                    group[0].weight,
                                )
                            if poison:
                                poison = False
                                bx = _np.array(bx, copy=True)
                                bx[0, ...] = _np.nan
                            x, y, w = make_global_batch(self.mesh, bx, by, bw)
                        group = []
                        # The device_get of the loss is the step's real
                        # sync point — include it in the dispatch window.
                        with ledger.dispatch("train_step", key="eager_step"):
                            state, metrics = train_step(state, x, y, w)
                            m_host = jax.device_get(metrics)
                            loss_host = float(m_host["train_loss"])
                        global_step += 1
                        # Step-trigger faults (`crash@...:stepN` /
                        # `hang@...:stepN`): fired after the step's sync
                        # point, before this step's heartbeat — a hung
                        # rank stops beating exactly here, which is what
                        # the stall monitor exists to see.
                        if plan.enabled:
                            plan.maybe_fire(
                                "step", step=global_step,
                                pre_exit=state_ckptr.wait,
                            )
                        # Per-step health: a halting policy stops the
                        # run MID-epoch on the eager path (epoch span
                        # closed first so the halted epoch is on the
                        # trace).
                        finding = health.observe_step(
                            loss_host,
                            grad_norm=float(m_host["grad_norm"]),
                            step=global_step, epoch=epoch,
                        )
                        if finding is not None and finding.halt:
                            epoch_span.end(halted=finding.kind)
                        HealthMonitor.raise_on(finding)
                        n_steps += accum
                        n_updates += 1
                        loss_sum += loss_host
                        # Per-step liveness on the eager path (the
                        # writer's min_interval throttles the I/O).
                        if heartbeat is not None:
                            heartbeat.beat(
                                step=global_step, epoch=epoch, phase="train",
                            )
                        if global_step % cfg.train.log_every_n_steps == 0:
                            self.tracker.log_metrics(
                                {"train_loss": loss_host}, step=global_step
                            )
                        # Graceful preemption (eager path): the in-flight
                        # step just finished and synced — save a resume
                        # checkpoint NOW (epochs_completed = the last
                        # full epoch: resume restarts this one, losing
                        # under one epoch of progress) and exit
                        # PREEMPTED via the entry point.
                        if guard.requested:
                            epoch_span.end(preempted=True)
                            self._preempt_exit(
                                guard, events, state_ckptr,
                                state=jax.device_put(
                                    state, declared_shardings
                                ),
                                epochs_completed=epoch,
                                target_epochs=target_epochs,
                                opt_identity=opt_identity,
                            )
                    # A ragged tail (< accum batches) is dropped, matching
                    # the scan path's group-granular drop_last.
                    jax.block_until_ready(state.params)
                    epoch_stats = timer.stop(epoch, n_steps * global_batch)
                    epoch_loss = loss_sum / n_updates if n_updates else None

                    with ledger.dispatch("eval", key="eager_eval"), \
                            tracer.span(
                                "trainer.eval", component="trainer",
                                epoch=epoch,
                                parent_id=epoch_span.span_id,
                            ):
                        val_loss, val_acc, (tp, fp, fn) = self._evaluate(
                            state, eval_step, val_loader
                        )
                    stop_early = _bookkeep_span(
                        _SpanInFlight(
                            epoch0=epoch, k=1, n_steps=n_steps,
                            state=state, epoch_span=epoch_span,
                        ),
                        [(epoch_loss, val_loss, val_acc, (tp, fp, fn))],
                        epoch_stats, 0,
                    )
                epoch += k
                # Graceful preemption at the span boundary: the last
                # BOOKKEPT span's resume snapshot was just submitted —
                # first drain any still-in-flight span so its progress
                # is durable too (matching serial semantics: everything
                # dispatched gets consumed), then join the write and
                # exit PREEMPTED. With epoch_chunk=1 at most one epoch
                # of progress is in flight when SIGTERM lands, so the
                # resume loses at most that epoch.
                if guard.requested:
                    if pending is not None:
                        _sp, pending = pending, None
                        _consume_span(_sp)
                    self._preempt_exit(
                        guard, events, state_ckptr,
                        epochs_completed=consumed_through,
                    )
                if stop_early:
                    break
            # Pipelined tail: the loop exits on the epoch budget (or an
            # early stop) with the last dispatched span's results still
            # on device — bookkeep them now.
            if pending is not None:
                _sp, pending = pending, None
                stop_early = _consume_span(_sp) or stop_early
                if guard.requested:
                    self._preempt_exit(
                        guard, events, state_ckptr,
                        epochs_completed=consumed_through,
                    )
            completed = True

        except PreemptedError:
            preempted = True
            # Cooperative exit: close the tracking run (a preempt+resume
            # fleet would otherwise accumulate one phantom RUNNING run on
            # the MLflow server per preemption). Best-effort — closing
            # the books must never mask the preemption itself.
            self._end_tracking_quietly("KILLED")
            raise
        except TrainingHealthError:
            # Also a controlled raise (HealthMonitor.raise_on): mark the
            # run failed instead of leaking it as RUNNING.
            self._end_tracking_quietly("FAILED")
            raise
        finally:
            # Crash-path hygiene: never leave a jax.profiler session open,
            # a resume-state write un-joined, or the prefetch thread
            # running (each guarded so one cleanup failing cannot abandon
            # the others).
            try:
                try:
                    flight.close()
                finally:
                    profiler.close()
            finally:
                try:
                    state_ckptr.wait()
                finally:
                    try:
                        if prefetch_pool is not None:
                            prefetch_pool.shutdown(wait=True)
                    finally:
                        # The SIGTERM contract ends here either way:
                        # restore the previous handler so post-training
                        # code (and whatever embeds us) keeps its own
                        # semantics.
                        guard.uninstall()
                        # Terminal heartbeat: "done" stops the monitor
                        # ageing this rank; "preempted" and "failed"
                        # name ends an exit code alone cannot (the rank
                        # may be killed by fail-fast before it can exit).
                        if heartbeat is not None:
                            heartbeat.beat(
                                phase="done" if completed else (
                                    "preempted" if preempted else "failed"
                                ),
                                force=True,
                            )
                        if preempted:
                            events.emit(
                                "trainer", "fit_preempted",
                                epochs_run=len(history),
                            )
                        elif not completed:
                            events.emit(
                                "trainer", "fit_failed",
                                health=health.summary()["events"],
                            )
                        if not completed:
                            # The crashing/preempted epoch is exactly
                            # the window the operator opens the trace to
                            # inspect: record any span still in flight
                            # (pipelined, the un-bookkept successor's
                            # spans live in `pending`).
                            in_flight = [dispatch_span, ckpt_span,
                                         epoch_span]
                            if pending is not None:
                                in_flight += [pending.dispatch_span,
                                              pending.epoch_span]
                            for _sp in in_flight:
                                if _sp is not None:
                                    _sp.end(error=not preempted)
                        # Fit span closes HERE, success or failure: a
                        # post-training tail error (artifact upload,
                        # tracker teardown) must not orphan the whole
                        # rank's span tree from its recorded root.
                        fit_span.end(
                            completed=completed,
                            preempted=preempted,
                            epochs_run=len(history),
                            val_loss=(
                                history[-1]["val_loss"]
                                if history else None
                            ),
                        )
                        # Hot loop over (success, crash, or preempt):
                        # drain buffered telemetry and drop both sinks
                        # to write-through, so every record emitted so
                        # far is durable and post-run emitters through
                        # the installed process defaults get
                        # read-after-emit visibility back.
                        events.set_write_through()
                        tracer.set_write_through()

        # Rank-0 post-train artifact upload, mirroring
        # jobs/train_lightning_ddp.py:146-164 (best, else last.ckpt fallback).
        _t_upload = ledger.clock()
        best_path = ckptr.best_model_path
        if self.coordinator:
            if not os.path.exists(best_path):
                best_path = ckptr.last_path
            if os.path.exists(best_path):
                self.tracker.log_artifact(
                    best_path, artifact_path=self.cfg.tracking.artifact_path
                )
                # log_model parity (MLFlowLogger(log_model=True) logs the
                # model object too, reference jobs/train_lightning_ddp.py:95):
                # the checkpoint plus loader metadata under artifact path
                # "model", so the registry carries a self-describing model
                # artifact, not only the raw .ckpt.
                import json as _json
                import tempfile as _tempfile

                with _tempfile.TemporaryDirectory() as td:
                    mlmodel = os.path.join(td, "MLmodel.json")
                    with open(mlmodel, "w") as f:
                        _json.dump(
                            {
                                "flavor": "dct_tpu",
                                "checkpoint": os.path.basename(best_path),
                                "serving": "dct_tpu.serving.runtime",
                                **meta,
                            },
                            f,
                            indent=2,
                        )
                    self.tracker.log_artifact(mlmodel, artifact_path="model")
                    self.tracker.log_artifact(best_path, artifact_path="model")
        ledger.add("checkpoint", ledger.clock() - _t_upload)

        # Run-end goodput accounting: logged to the tracker NEXT TO
        # val_loss (a goodput regression becomes queryable exactly like
        # an accuracy regression), emitted as a structured event, and
        # dumped in Prometheus text exposition for scrape-less rigs.
        goodput_summary = ledger.summary()
        self.tracker.log_metrics(ledger.tracker_metrics(), step=global_step)
        events.emit("trainer", "goodput_summary", **goodput_summary)
        # Compile/restart accounting (ROADMAP item 5's baseline): the
        # ledger's compile windows become compile.window events keyed by
        # the (family, config-hash, mesh) identity an AOT compilation
        # cache would use, and dct_compile_* series in the prom dump —
        # re-compiles of the SAME identity across restarts/workers are
        # the debt a persistent cache would erase.
        import dataclasses as _dataclasses

        from dct_tpu.observability.goodput import (
            compile_report,
            config_hash,
            mesh_descriptor,
        )

        compile_windows = compile_report(
            ledger.compile_windows,
            family=cfg.model.name,
            config_hash=config_hash(_dataclasses.asdict(cfg.model)),
            mesh=mesh_descriptor(self.mesh),
            # cache="hit" windows were deserialized executables, not XLA
            # compiles — the label a warm-relaunch e2e asserts on.
            cache_states=aot_store.states,
            # Roofline provenance: analytic FLOPs / bytes / peak HBM
            # captured at compile time ride the window record.
            costs=aot_store.costs,
        )
        if self.coordinator:
            for w in compile_windows:
                events.emit("compile", "compile.window", **w)
        # Roofline join (observability.roofline): the cost-model numbers
        # against the ledger's measured steady-state dispatch windows —
        # live per-program MFU, arithmetic intensity, and the compute-
        # vs-memory-bound placement, as roofline.report events and the
        # dct_program_* gauges in the metrics dump below.
        from dct_tpu.observability.roofline import program_report

        roofline_rep = program_report(
            aot_store.costs,
            ledger.dispatch_stats,
            n_chips=self.mesh.size,
            family=cfg.model.name,
            config_hash=config_hash(_dataclasses.asdict(cfg.model)),
            mesh=mesh_descriptor(self.mesh),
        )
        if self.coordinator:
            for r in roofline_rep:
                events.emit("roofline", "roofline.report", **r)
        # Retire the live per-epoch snapshot BEFORE the final dump
        # writes the terminal one under the same proc name — close()
        # removes the live file, the dump re-creates it as final.
        if live_metrics is not None:
            live_metrics.close()
        # An explicit DCT_METRICS_PROM must work even with the event log
        # disabled (textfile-collector-only rigs clear DCT_EVENTS_DIR).
        if self.coordinator and cfg.obs.enabled and (
            cfg.obs.metrics_path or cfg.obs.events_dir
        ):
            from dct_tpu.observability.dump import write_train_metrics_prom

            final_vl = (
                history[-1]["val_loss"] if history else float("nan")
            )
            write_train_metrics_prom(
                cfg.obs.metrics_path
                or os.path.join(cfg.obs.events_dir, "train_metrics.prom"),
                goodput_summary,
                run_id=events.run_id,
                samples_per_sec=timer.samples_per_sec,
                val_loss=final_vl,
                health=health.summary(),
                resilience={
                    "faults_injected": plan.fired_count,
                    "startup_debt_s": cfg.resilience.startup_debt_s,
                },
                compile_windows=compile_windows,
                roofline=roofline_rep,
                # Metrics plane: leave a final snapshot so a /metrics
                # scrape of the serving pool reports this run's goodput
                # and compile debt next to the request series.
                metrics_dir=cfg.obs.metrics_dir,
                proc=f"train-rank{jax.process_index()}",
            )
        self.tracker.end_run()

        if self.coordinator:
            shadow = span_shadow_warning(history, span_end_vl_min, chunk)
            if shadow:
                print(shadow, file=sys.stderr, flush=True)
        final = history[-1] if history else {"val_loss": float("nan"), "val_acc": float("nan")}
        health_summary = health.summary()
        events.emit(
            "trainer", "fit_end",
            val_loss=final["val_loss"], val_acc=final["val_acc"],
            epochs_run=len(history),
            goodput_fraction=goodput_summary["goodput_fraction"],
            health=health_summary["events"],
        )
        steady = timer.history[1:] if len(timer.history) > 1 else timer.history
        return TrainResult(
            val_loss=final["val_loss"],
            val_acc=final["val_acc"],
            best_model_path=best_path,
            last_model_path=ckptr.last_path,
            history=history,
            samples_per_sec=timer.samples_per_sec,
            steady_samples_per_sec_per_chip=(
                sum(s.samples_per_sec_per_chip for s in steady) / len(steady)
                if steady else 0.0
            ),
            run_id=run_id,
            state=state,
            goodput=goodput_summary,
            run_correlation_id=events.run_id,
            health=health_summary,
        )

    # ------------------------------------------------------------------
    def _end_tracking_quietly(self, status: str) -> None:
        try:
            self.tracker.end_run(status=status)
        except Exception:  # noqa: BLE001 — bookkeeping must not mask the exit
            pass

    # ------------------------------------------------------------------
    @staticmethod
    def _preempt_exit(
        guard,
        events,
        ckptr,
        *,
        epochs_completed: int,
        state=None,
        target_epochs: int | None = None,
        opt_identity: dict | None = None,
    ):
        """Honor a SIGTERM: make the resume checkpoint durable, put the
        preemption on the record, raise :class:`PreemptedError` (the
        entry point maps it to ``EXIT_PREEMPTED``).

        ``state=None`` means the span boundary just submitted the right
        snapshot asynchronously — joining it is the synchronous save;
        the eager path passes the live state for an explicit save.
        """
        if state is not None:
            ckptr.save(
                state,
                meta={
                    "epochs_completed": int(epochs_completed),
                    "target_epochs": int(target_epochs),
                    "optimizer": opt_identity,
                },
            )
        else:
            ckptr.wait()
        events.emit(
            "trainer", "preempt.signal_received",
            signal_time=guard.signal_time,
        )
        events.emit(
            "trainer", "preempt.checkpoint_saved",
            epochs_completed=int(epochs_completed), dir=ckptr.dirpath,
        )
        raise PreemptedError(
            f"SIGTERM honored: resume checkpoint durable at "
            f"epochs_completed={int(epochs_completed)}"
        )

    # ------------------------------------------------------------------
    @staticmethod
    def _stack_epoch(loader, epoch: int):
        """One epoch as [S, B_local, ...] host arrays for the scan path."""
        return loader.epoch_stacked(epoch)

    # ------------------------------------------------------------------
    def _evaluate(self, state, eval_step, val_loader):
        """-> (val_loss, val_acc, (tp, fp, fn)) from the global sums."""
        sums = [jnp.zeros(()) for _ in range(6)]
        for batch in val_loader.epoch(0):
            x, y, w = make_global_batch(self.mesh, batch.x, batch.y, batch.weight)
            for i, v in enumerate(eval_step(state, x, y, w)):
                sums[i] = sums[i] + v
        ls, accs, c, tp, fp, fn = (float(v) for v in jax.device_get(sums))
        if c == 0:
            return float("nan"), float("nan"), (0.0, 0.0, 0.0)
        return ls / c, accs / c, (tp, fp, fn)
