"""``python -m dct_tpu.train.mpmd_worker``: one MPMD stage, one process.

The multi-controller deployment of the MPMD trainer: the supervised
launcher (``python -m dct_tpu.resilience.supervise --world-size P``)
babysits P of these — each process owns ONE stage's device slice (its
own single-process jax world; stages never join a global SPMD
collective), builds ONLY its stage's programs, and exchanges
activations/gradients with its neighbors over the explicit transfer
plane (:mod:`dct_tpu.parallel.mpmd_transfer`). The stage index comes
from ``DCT_MPMD_STAGE_ID`` (or the launcher's ``NODE_RANK``), so the
launch block needs no MPMD-specific plumbing — heartbeats, stall-kill,
the PR 3 exit-code classifier, and relaunch-with-resume all apply
unchanged:

- SIGTERM: the PR 3 PreemptionGuard semantics — finish the in-flight
  step, save the stage's resume checkpoint, exit ``EXIT_PREEMPTED``
  (75): the whole world classifies "preempted" and relaunches resumed;
- a crashed stage: fail-fast world teardown; the relaunch restores
  every stage from its own checkpoint tier AND deserializes every
  stage's programs from the PR 9 AOT store (warm relaunch = per-stage
  ``cache=hit``);
- a wedged neighbor: the transfer plane's loud timeout
  (``DCT_MPMD_TRANSFER_TIMEOUT_S``) turns a silent hang into an exit
  the classifier can heal.

Every stage process builds the identical loader stream (same seed,
same order — stage 0 consumes the features, the last stage the
labels/weights), so microbatches line up across processes with no data
plane beyond the activation wire.
"""

from __future__ import annotations

import os
import sys


def _bootstrap_devices() -> int:
    """Pin this process's XLA device count to its stage's slice BEFORE
    jax initializes a backend (CPU rigs: one virtual device per slice
    seat). Returns the stage index. Deliberately jax-free: it must run
    before any jax import touches XLA_FLAGS."""
    stage = int(
        os.environ.get("DCT_MPMD_STAGE_ID")
        or os.environ.get("NODE_RANK")
        or "0"
    )
    raw = (os.environ.get("DCT_MPMD_STAGES") or "2").strip()
    toks = [t.strip() for t in raw.split(",") if t.strip()]
    counts = None
    if all(t.lstrip("-").isdigit() for t in toks):
        vals = [int(t) for t in toks]
        counts = vals if len(vals) > 1 else [1] * max(vals[0], 2)
    n = counts[stage] if counts and 0 <= stage < len(counts) else 1
    # Only the EXPLICIT CPU rig gets virtual devices; an unset
    # JAX_PLATFORMS means accelerator auto-detect (the TPU path) and
    # must stay untouched — pinning cpu here would silently train every
    # stage on the host.
    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={n}"
            ).strip()
    return stage


def main() -> int:
    stage = _bootstrap_devices()
    # The worker is its OWN jax world: neutralize the launcher's SPMD
    # rendezvous env so nothing tries to join a global collective.
    n_stages_env = int(
        os.environ.get("WORLD_SIZE")
        or os.environ.get("DCT_NUM_PROCESSES")
        or "0"
    )
    for k in ("DCT_NUM_PROCESSES", "DCT_PROCESS_ID", "WORLD_SIZE"):
        os.environ.pop(k, None)
    os.environ["DCT_MPMD_STAGE_ID"] = str(stage)

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from dct_tpu.config import RunConfig
    from dct_tpu.observability import events as _events
    from dct_tpu.observability.heartbeat import HeartbeatWriter
    from dct_tpu.parallel import mpmd
    from dct_tpu.parallel import mpmd_transfer
    from dct_tpu.resilience.preempt import PreemptionGuard
    from dct_tpu.resilience.supervisor import EXIT_PREEMPTED
    from dct_tpu.train import mpmd_trainer as mt
    from dct_tpu.compilecache import enable_from_env

    cfg = RunConfig.from_env()
    mt._validate_cfg(cfg)
    if "," not in (cfg.mpmd.stages or "").strip():
        # A bare stage count splits "the pool" evenly — but each
        # worker process is its OWN jax world and cannot see the pod's
        # device total, so the carve would silently differ from the
        # in-process trainer's. Multi-process mode requires explicit
        # per-stage counts (deterministic across processes).
        raise mpmd.MpmdSpecError(
            f"DCT_MPMD_STAGES={cfg.mpmd.stages!r}: multi-process MPMD "
            "needs EXPLICIT per-stage device counts (e.g. '1,1'), not "
            "a bare stage count — each stage process sizes its own "
            "device world from its entry"
        )
    spec = cfg.mpmd.to_spec()
    if n_stages_env and n_stages_env != spec.n_stages:
        raise mpmd.MpmdSpecError(
            f"launcher world size {n_stages_env} != "
            f"{spec.n_stages} stages in DCT_MPMD_STAGES"
        )
    if not (0 <= stage < spec.n_stages):
        raise mpmd.MpmdSpecError(
            f"stage id {stage} out of range for {spec.n_stages} stages"
        )
    enable_from_env()
    events = _events.get_default()
    hb = HeartbeatWriter(
        cfg.obs.heartbeat_dir, stage, run_id=cfg.obs.run_id,
        min_interval=cfg.obs.heartbeat_interval,
    )
    guard = PreemptionGuard().install()

    mesh = mpmd.carve_stage_meshes(
        [spec.device_counts[stage]],
        devices=jax.devices()[: spec.device_counts[stage]],
        model=max(1, cfg.mesh.model),
    )[0]
    placement = NamedSharding(mesh, P())
    ct = jnp.bfloat16 if cfg.train.bf16_compute else jnp.float32

    data, train_loader, val_loader = mt.build_loaders(cfg, spec)
    input_dim = data.input_dim
    full_state = mt.build_full_state(cfg, input_dim, compute_dtype=ct)
    tmpl = mpmd.split_state(full_state, stage, spec.n_stages)

    ckptr = mt.stage_checkpointer(cfg.data.models_dir, stage)
    start_epoch = 0
    target_epochs = cfg.train.epochs
    state = tmpl

    def _continue_target(meta: dict) -> tuple:
        """The Trainer's continuation semantics, shared by every
        resume path: an interrupted run finishes to its saved target;
        a completed one extends by this run's budget."""
        start = int(meta.get("epochs_completed", 0))
        saved_target = int(meta.get("target_epochs", cfg.train.epochs))
        return start, (
            start + cfg.train.epochs
            if start >= saved_target else saved_target
        )

    if cfg.train.resume:
        # Cross-stage agreement BEFORE resolving this stage's path
        # (the SPMD trainer's start-epoch allgather refusal,
        # file-based): a teardown between two stages' saves — or a
        # stage missing its files entirely while peers/the manifest
        # show progress — is a TORN set; resuming it would pair one
        # epoch's features with another's labels. Loud.
        epochs_seen = {}
        for k in range(spec.n_stages):
            peer = mt.stage_checkpointer(cfg.data.models_dir, k)
            if peer.exists():
                epochs_seen[k] = int(
                    peer.load_meta().get("epochs_completed", 0)
                )
        manifest = mt.read_manifest(cfg.data.models_dir)
        torn = len(set(epochs_seen.values())) > 1 or (
            stage not in epochs_seen and (epochs_seen or manifest)
        )
        if torn:
            raise RuntimeError(
                f"Resume divergence: stage {stage} sees per-stage "
                f"epochs_completed {epochs_seen} (manifest: "
                f"{manifest.get('epochs_completed')}) — a teardown "
                "tore the stage checkpoint set. Clear "
                f"{mt.mpmd_state_root(cfg.data.models_dir)} or restore "
                "matching generations on every stage."
            )
        if ckptr.exists():
            saved = ckptr.load_meta()
            mt._check_opt_identity(
                saved, cfg.train, f"stage {stage}'s MPMD checkpoint"
            )
            state = ckptr.restore(tmpl)
            start_epoch, target_epochs = _continue_target(saved)
        else:
            restored, meta = mt._restore_from_spmd(
                cfg.data.models_dir, full_state
            )
            if restored is not None:
                mt._check_opt_identity(
                    meta, cfg.train, "the SPMD train_state checkpoint"
                )
                state = mpmd.split_state(restored, stage, spec.n_stages)
                start_epoch, target_epochs = _continue_target(meta)
                events.emit(
                    "mpmd", "mpmd.pivot", direction="spmd_to_mpmd",
                    n_stages=spec.n_stages, stage=stage,
                    epochs_completed=start_epoch,
                )
    state = mt.shard_stage_state(state, mesh, cfg.model.name)

    store = mt.stage_store(cfg, spec, stage, mesh, input_dim)
    stage_fns = mt.build_stage_fns(cfg.model, input_dim, compute_dtype=ct)
    programs = mpmd.make_stage_programs(
        stage, spec.n_stages, stage_fns, store=store
    )

    events.emit(
        "mpmd", "mpmd.stage_start", stage=stage,
        n_stages=spec.n_stages, devices=spec.device_counts[stage],
        schedule=spec.schedule,
    )
    # Metrics plane (when DCT_METRICS_DIR arms it): this stage's
    # transfer byte/latency histograms record live (timer-refreshed
    # snapshots), and the final snapshot adds the stage programs'
    # roofline gauges — inter-stage comms and per-program cost land on
    # the same aggregated /metrics scrape as the bubble gauges.
    publisher = None
    metrics_reg = None
    if cfg.obs.enabled and cfg.obs.metrics_dir:
        from dct_tpu.observability.aggregate import SnapshotPublisher
        from dct_tpu.observability.metrics import MetricsRegistry

        metrics_reg = MetricsRegistry()
        mpmd_transfer.arm_transfer_metrics(metrics_reg)
        publisher = SnapshotPublisher(
            metrics_reg, cfg.obs.metrics_dir,
            proc=f"mpmd-stage{stage}-{os.getpid()}",
            interval_s=cfg.obs.metrics_publish_s,
        )
    hb.beat(epoch=start_epoch, phase="startup", force=True)
    links = mpmd_transfer.connect_stage_links(
        stage, spec.n_stages, port_base=spec.port_base,
        timeout=spec.transfer_timeout_s,
    )
    executor = mpmd.StageExecutor(
        stage, spec.n_stages, programs, channels=links,
        transfer_timeout_s=spec.transfer_timeout_s,
        place_in=lambda a: jax.device_put(jnp.asarray(a), placement),
    )
    ops = mpmd.build_schedule(
        spec.n_stages, spec.n_microbatches, spec.schedule
    )[stage]
    first, last = stage == 0, stage == spec.n_stages - 1

    def _microbatches(batch):
        m = spec.n_microbatches
        b = batch.x.shape[0]
        mb = b // m
        if first:
            return [
                jax.device_put(
                    jnp.asarray(batch.x[i * mb:(i + 1) * mb], jnp.float32),
                    placement,
                )
                for i in range(m)
            ]
        if last:
            return [
                (
                    jax.device_put(
                        jnp.asarray(batch.y[i * mb:(i + 1) * mb]),
                        placement,
                    ),
                    jax.device_put(
                        jnp.asarray(
                            batch.weight[i * mb:(i + 1) * mb], jnp.float32
                        ),
                        placement,
                    ),
                )
                for i in range(m)
            ]
        return [None] * m

    def _save(epoch_done: int) -> None:
        ckptr.save(state, {
            "epochs_completed": epoch_done,
            "target_epochs": target_epochs,
            "family": cfg.model.name,
            "stage": stage,
            "optimizer": mt._opt_identity(cfg.train),
        })
        if stage == 0:
            mt.write_manifest(cfg.data.models_dir, {
                "version": 1,
                "n_stages": spec.n_stages,
                "device_counts": list(spec.device_counts),
                "schedule": spec.schedule,
                "n_microbatches": spec.n_microbatches,
                "family": cfg.model.name,
                "n_layers": cfg.model.n_layers,
                "epochs_completed": epoch_done,
            })

    rc = 0
    last_rep = None
    try:
        for epoch in range(start_epoch, target_epochs):
            losses = []
            for step_i, batch in enumerate(train_loader.epoch(epoch)):
                # The SAME loss normalizer as MpmdRunner.train_step:
                # weight sum x supervised positions per row (1 for the
                # PP family's pooled head; kept in lockstep so the two
                # deployment modes stay bitwise-identical).
                positions = 1
                for d in np.asarray(batch.y).shape[1:]:
                    positions *= d
                total = max(
                    float(np.asarray(batch.weight, np.float32).sum())
                    * positions,
                    1.0,
                )
                state, rep, loss_sums = executor.run_step(
                    ops, state, _microbatches(batch),
                    jnp.asarray(total, jnp.float32),
                )
                last_rep = rep
                if last and loss_sums:
                    losses.append(
                        sum(float(np.asarray(s)) for s, _ in loss_sums)
                        / total
                    )
                hb.beat(step=step_i, epoch=epoch, phase="train")
            if last:
                events.emit(
                    "mpmd", "mpmd.step_report", epoch=epoch,
                    schedule=spec.schedule, n_stages=spec.n_stages,
                    n_microbatches=spec.n_microbatches,
                    stages=[{
                        "stage": stage,
                        "busy_s": round(rep.busy_s, 6),
                        "transfer_wait_s": round(rep.transfer_wait_s, 6),
                        "fill_s": round(rep.phase_busy["fill"], 6),
                        "steady_s": round(rep.phase_busy["steady"], 6),
                        "drain_s": round(rep.phase_busy["drain"], 6),
                    }],
                    train_loss=(
                        float(np.mean(losses)) if losses else None
                    ),
                )
            _save(epoch + 1)
            hb.beat(epoch=epoch + 1, phase="checkpoint", force=True)
            if guard.requested:
                events.emit(
                    "mpmd", "mpmd.stage_done", stage=stage,
                    preempted=True, epochs_completed=epoch + 1,
                )
                return EXIT_PREEMPTED
        events.emit(
            "mpmd", "mpmd.stage_done", stage=stage, preempted=False,
            epochs_completed=target_epochs,
        )
    except mpmd.MpmdTransferTimeout as e:
        events.emit(
            "mpmd", "mpmd.transfer_timeout", stage=stage, error=str(e),
        )
        print(f"[mpmd_worker s{stage}] {e}", file=sys.stderr, flush=True)
        rc = 1
    finally:
        mpmd_transfer.close_links(links)
        if publisher is not None:
            from dct_tpu.observability.goodput import (
                mesh_descriptor as _mesh_descriptor,
            )
            from dct_tpu.observability.roofline import (
                add_roofline_metrics,
            )

            try:
                from dct_tpu.observability.roofline import (
                    resolve_peak_flops,
                )

                mesh_d = _mesh_descriptor(mesh)
                report = [
                    {
                        "program": program,
                        "family": cfg.model.name,
                        "mesh": mesh_d,
                        **cost,
                    }
                    for program, cost in sorted(store.costs.items())
                ]
                # The live per-stage MFU gauge (the acceptance bar's
                # worker half): this stage's per-step FLOPs over its
                # executor's last measured step busy window.
                mfu_rec = mt.stage_mfu_record(
                    store.costs, stage=stage,
                    n_microbatches=spec.n_microbatches,
                    busy_s=(
                        float(last_rep.busy_s) if last_rep else 0.0
                    ),
                    devices=spec.device_counts[stage],
                    family=cfg.model.name, mesh=mesh_d,
                    peak=resolve_peak_flops()[0],
                )
                if mfu_rec is not None:
                    report.append(mfu_rec)
                    events.emit(
                        "roofline", "roofline.report", **mfu_rec
                    )
                add_roofline_metrics(
                    metrics_reg, report, {"stage": str(stage)},
                )
            except Exception:  # noqa: BLE001 — telemetry never
                pass  # changes the worker's exit code
            publisher.close(final=True)
            mpmd_transfer.disarm_transfer_metrics()
        hb.beat(phase="exit", force=True)
    return rc


if __name__ == "__main__":
    sys.exit(main())
