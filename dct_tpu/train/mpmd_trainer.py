"""MPMD pipeline-parallel continuous trainer (ROADMAP item 3 / ISSUE 13).

The platform side of :mod:`dct_tpu.parallel.mpmd`: train the registry's
pipeline-parallel family (``weather_transformer_pp``) as P DISTINCT
compiled programs on disjoint device slices, wired through the same
continuous-training machinery the SPMD trainer uses —

- **data**: the identical window/split/BatchLoader pipeline as
  ``Trainer.fit`` (same seed, same batch order), so the per-step
  semantics pin against the SPMD pipeline oracle;
- **goodput/spans**: step walls bill the shared
  :class:`~dct_tpu.observability.goodput.GoodputLedger` categories
  (first dispatch = compile, as everywhere); every epoch emits one
  ``mpmd.step_report`` event and an ``mpmd.epoch`` span carrying the
  per-stage fill/steady/drain/transfer-wait attribution, so the run
  inspector can show exactly where the bubble went;
- **checkpoint**: each stage owns a PR 11 resume tier
  (``<models>/train_state_mpmd/stage<k>/p0`` — per-leaf layout.json
  manifests included) under one ``manifest.json`` naming the stage map;
  :func:`adopt_mpmd_checkpoint` re-maps those per-stage files into the
  SPMD trainer's stacked layout (bitwise — pure data movement) and the
  MPMD trainer pivots the other way from a plain SPMD ``train_state``
  (``mpmd.pivot`` events both directions; an untileable stage map is a
  loud refusal);
- **AOT**: every stage program keys into the PR 9 executable store with
  the stage id + slice topology joined to the identity — a warm
  relaunch deserializes EVERY stage's programs cache=hit.

Constraints enforced loudly (documented in docs/PARALLELISM.md §MPMD):
the family must be ``weather_transformer_pp`` with ``dropout == 0``
(stage programs are deterministic; the PP family already keeps dropout
outside the pipelined region), the lr schedule ``constant``, and
``grad_clip_norm == 0`` (global-norm clipping couples stages across
slices — a cross-slice reduction the transfer plane does not carry).
"""

from __future__ import annotations

import dataclasses
import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np

from dct_tpu.config import RunConfig
from dct_tpu.observability import events as _events
from dct_tpu.observability import spans as _spans
from dct_tpu.observability.goodput import (
    GoodputLedger,
    config_hash as _config_hash,
    mesh_descriptor as _mesh_descriptor,
)
from dct_tpu.parallel import mpmd
from dct_tpu.parallel.sharding_rules import rules_digest, rules_for_family

MPMD_FAMILY = "weather_transformer_pp"
MPMD_STATE_DIRNAME = "train_state_mpmd"


# ----------------------------------------------------------------------
# Stage functions: the PP family decomposed into per-stage callables.
# Values come from the FULL registry model's init (split afterwards), so
# the decomposition is bitwise the oracle's parameterization.


def build_stage_fns(model_cfg, input_dim: int, *, compute_dtype=None):
    """Model-level stage callables for :func:`mpmd.make_stage_programs`.

    ``first_fwd`` = in_proj + positions + first stage's blocks;
    ``mid_fwd`` = blocks; ``last_fwd`` = blocks + ln_out + pooled head +
    masked-CE (loss_sum, count); ``last_eval`` = the 6 eval sums. Same
    modules, same names, same math as ``WeatherTransformerPP`` minus
    dropout (MPMD mode requires rate 0 — enforced by the trainer)."""
    from flax import linen as nn

    from dct_tpu.models.mlp import TorchStyleDense
    from dct_tpu.models.transformer import _StageBlocks, sincos_positions
    from dct_tpu.ops.attention import make_attention_fn
    from dct_tpu.ops.losses import (
        masked_accuracy,
        masked_binary_counts,
        masked_cross_entropy,
    )

    ct = compute_dtype or jnp.float32
    n_stages = int(model_cfg.n_stages)
    layers_per = mpmd.stage_layers(model_cfg.n_layers, n_stages)
    stage_mod = _StageBlocks(
        model_cfg.d_model, model_cfg.n_heads, model_cfg.d_ff, layers_per,
        make_attention_fn(None), dtype=ct, remat=model_cfg.remat,
        n_kv_heads=model_cfg.n_kv_heads or None,
        rope=model_cfg.pos_embed == "rope",
    )
    in_mod = TorchStyleDense(model_cfg.d_model, dtype=ct)
    ln_mod = nn.LayerNorm(dtype=ct)
    head_mod = TorchStyleDense(model_cfg.num_classes, dtype=ct)
    pos = (
        sincos_positions(model_cfg.seq_len, model_cfg.d_model)
        if model_cfg.pos_embed != "rope"
        else None
    )

    def first_fwd(p, x):
        h = jnp.asarray(x, ct)
        h = in_mod.apply({"params": p["params"]["in_proj"]}, h)
        if pos is not None:
            h = h + jnp.asarray(pos, ct)
        return stage_mod.apply({"params": p["params"]["stage"]}, h)

    def mid_fwd(p, a):
        return stage_mod.apply({"params": p["params"]["stage"]}, a)

    def _logits(p, a):
        h = stage_mod.apply({"params": p["params"]["stage"]}, a)
        h = ln_mod.apply({"params": p["params"]["ln_out"]}, h)
        pooled = h.mean(axis=1)
        logits = head_mod.apply({"params": p["params"]["head"]}, pooled)
        return jnp.asarray(logits, jnp.float32)

    def last_fwd(p, a, y, w):
        return masked_cross_entropy(_logits(p, a), y, w)

    def last_eval(p, a, y, w):
        logits = _logits(p, a)
        loss_sum, count = masked_cross_entropy(logits, y, w)
        acc_sum, _ = masked_accuracy(logits, y, w)
        tp, fp, fn = masked_binary_counts(logits, y, w)
        return loss_sum, acc_sum, count, tp, fp, fn

    return {
        "first_fwd": first_fwd,
        "mid_fwd": mid_fwd,
        "last_fwd": last_fwd,
        "last_eval": last_eval,
    }


def shard_stage_state(state, mesh, family: str = MPMD_FAMILY):
    """Place one stage's TrainState on its sub-mesh under the family's
    partition rules (per-stage tensor parallelism when the slice has a
    ``model`` axis; leaves whose dims do not tile the axis replicate)."""
    import re

    from jax.sharding import NamedSharding, PartitionSpec as P

    rules = rules_for_family(family)

    def one(path, leaf):
        ndim = getattr(leaf, "ndim", 0)
        if ndim == 0:
            return NamedSharding(mesh, P())
        from dct_tpu.parallel.sharding_rules import path_str

        name = path_str(path)
        for pattern, spec in rules:
            if re.search(pattern, name):
                dims = tuple(spec)
                ok = len(dims) <= ndim
                if ok:
                    for d, ax in enumerate(dims):
                        if ax is None:
                            continue
                        size = dict(mesh.shape).get(str(ax), 1)
                        if size > 1 and leaf.shape[d] % size:
                            ok = False
                            break
                if ok:
                    return NamedSharding(mesh, spec)
                return NamedSharding(mesh, P())
        return NamedSharding(mesh, P())

    shardings = jax.tree_util.tree_map_with_path(one, state)
    return jax.device_put(state, shardings)


# ----------------------------------------------------------------------
# Checkpoint layout + cross-topology pivots.


def mpmd_state_root(models_dir: str) -> str:
    return os.path.join(models_dir, MPMD_STATE_DIRNAME)


def _manifest_path(models_dir: str) -> str:
    return os.path.join(mpmd_state_root(models_dir), "manifest.json")


def read_manifest(models_dir: str) -> dict:
    try:
        with open(_manifest_path(models_dir)) as f:
            return dict(json.load(f))
    except (OSError, ValueError):
        return {}


def write_manifest(models_dir: str, manifest: dict) -> None:  # dct: noqa[rank0-io] — stage-0-gated by BOTH callers (MpmdTrainer is single-process; mpmd_worker writes only from stage 0), and the pid-suffixed tmp + os.replace publish is tear-proof under concurrent writers anyway
    root = mpmd_state_root(models_dir)
    os.makedirs(root, exist_ok=True)
    final = _manifest_path(models_dir)
    tmp = f"{final}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(manifest, f, indent=1, sort_keys=True)
    os.replace(tmp, final)


def stage_checkpointer(models_dir: str, k: int):
    from dct_tpu.checkpoint.manager import TrainStateCheckpointer

    return TrainStateCheckpointer(
        os.path.join(mpmd_state_root(models_dir), f"stage{k}", "p0")
    )


def mpmd_checkpoint_present(models_dir: str) -> bool:
    return bool(read_manifest(models_dir))


def adopt_mpmd_checkpoint(models_dir: str, template_state) -> dict:
    """Re-map an MPMD per-stage checkpoint set into the SPMD trainer's
    stacked layout (the MPMD -> SPMD pivot): restore every stage into
    the template's stage slices, merge (bitwise — pure stacking), and
    publish a normal ``train_state/p<rank>`` rotation the PR 11 restore
    path reads like any other. Returns the meta written. Loud refusal
    when the template cannot tile the saved stage count."""
    from dct_tpu.checkpoint.manager import TrainStateCheckpointer

    manifest = read_manifest(models_dir)
    if not manifest:
        raise FileNotFoundError(
            f"no MPMD manifest under {mpmd_state_root(models_dir)}"
        )
    n_stages = int(manifest["n_stages"])
    stage_states = []
    for k in range(n_stages):
        tmpl_k = mpmd.split_state(template_state, k, n_stages)
        ckptr = stage_checkpointer(models_dir, k)
        if not ckptr.exists():
            raise FileNotFoundError(
                f"MPMD manifest names {n_stages} stages but stage {k} "
                f"has no checkpoint under {ckptr.dirpath}"
            )
        stage_states.append(ckptr.restore(tmpl_k))
    merged = mpmd.merge_stage_states(stage_states, template=template_state)
    meta = dict(stage_checkpointer(models_dir, 0).load_meta())
    meta.pop("stage", None)
    spmd_ckptr = TrainStateCheckpointer(
        os.path.join(
            models_dir, "train_state", f"p{jax.process_index()}"
        )
    )
    spmd_ckptr.save(merged, meta)
    _events.get_default().emit(
        "mpmd", "mpmd.pivot", direction="mpmd_to_spmd",
        n_stages=n_stages,
        epochs_completed=meta.get("epochs_completed"),
    )
    return meta


def _opt_identity(train_cfg) -> dict:
    from dct_tpu.train.trainer import optimizer_identity

    return optimizer_identity(train_cfg)


def _check_opt_identity(saved_meta: dict, train_cfg, where: str) -> None:
    """The Trainer's exact-compare resume refusal, applied to the MPMD
    paths: opt_state trees of different optimizer configs can be
    structurally isomorphic, so a restore must refuse BEFORE training
    from mismatched moments."""
    saved_opt = saved_meta.get("optimizer")
    want = _opt_identity(train_cfg)
    if saved_opt is not None and saved_opt != want:
        raise RuntimeError(
            f"Resume refused: {where} was written by optimizer "
            f"{saved_opt} but this run configures {want}. Restore the "
            "original DCT_OPTIMIZER / DCT_MOMENTUM / DCT_WEIGHT_DECAY, "
            "or clear the checkpoint dir to restart the trajectory."
        )


def _restore_from_spmd(models_dir: str, full_template):
    """The SPMD -> MPMD pivot source: a plain ``train_state/p<rank>``
    rotation restored into the full-model template (host values),
    ready to split per stage."""
    from dct_tpu.checkpoint.manager import TrainStateCheckpointer

    ckptr = TrainStateCheckpointer(
        os.path.join(models_dir, "train_state", f"p{jax.process_index()}")
    )
    if not ckptr.exists():
        return None, {}
    return ckptr.restore(full_template), ckptr.load_meta()


# ----------------------------------------------------------------------
# The trainer.


@dataclasses.dataclass
class MpmdResult:
    train_losses: list
    val_losses: list
    epochs_completed: int
    goodput: dict
    bubble: dict
    cache_states: dict


def _validate_cfg(cfg: RunConfig) -> None:
    if cfg.model.name != MPMD_FAMILY:
        raise mpmd.MpmdSpecError(
            f"MPMD mode trains the pipeline-parallel family only "
            f"(DCT_MODEL={cfg.model.name!r}; expected {MPMD_FAMILY!r})"
        )
    if cfg.model.dropout != 0.0:
        raise mpmd.MpmdSpecError(
            f"MPMD stage programs are deterministic: set DCT_DROPOUT=0 "
            f"(got {cfg.model.dropout}) — the PP family already applies "
            "dropout outside the pipelined region"
        )
    if cfg.train.grad_clip_norm > 0:
        raise mpmd.MpmdSpecError(
            "DCT_GRAD_CLIP_NORM > 0 needs a cross-stage global-norm "
            "reduction the MPMD transfer plane does not carry; disable "
            "clipping for MPMD mode"
        )
    if cfg.train.lr_schedule != "constant" or cfg.train.warmup_steps:
        raise mpmd.MpmdSpecError(
            "MPMD mode supports the constant lr schedule only "
            f"(DCT_LR_SCHEDULE={cfg.train.lr_schedule!r})"
        )


def build_full_state(cfg: RunConfig, input_dim: int, *, compute_dtype=None):
    """The ORACLE's TrainState: the full registry PP model, initialized
    exactly as ``Trainer.fit`` would — the MPMD stage states are slices
    of this, so the decomposition is bitwise the oracle's."""
    from dct_tpu.models.registry import get_model
    from dct_tpu.train.state import create_train_state

    ct = compute_dtype or (
        jnp.bfloat16 if cfg.train.bf16_compute else jnp.float32
    )
    model = get_model(cfg.model, input_dim=input_dim, compute_dtype=ct)
    return create_train_state(
        model, input_dim=input_dim, lr=cfg.train.lr, seed=cfg.train.seed,
        example_shape=(1, cfg.model.seq_len, input_dim),
        weight_decay=cfg.train.weight_decay,
        optimizer=cfg.train.optimizer, momentum=cfg.train.momentum,
    )


def stage_mfu_record(
    costs: dict, *, stage: int, n_microbatches: int, busy_s: float,
    devices: int, family: str, mesh: str, peak: float | None,
) -> dict | None:
    """One ``mpmd_stage<k>`` roofline record: the stage's per-step
    FLOPs (M fwd+bwd passes + the update, off the stage programs'
    cost books) joined with its measured per-step busy seconds — the
    live per-stage MFU both deployment modes publish (the in-process
    trainer off the bubble report, the worker off its executor's last
    step report). None when any ingredient is missing."""
    fwd = (costs.get(f"mpmd_fwd_s{stage}") or {}).get("flops")
    bwd = (costs.get(f"mpmd_bwd_s{stage}") or {}).get("flops")
    upd = (costs.get(f"mpmd_update_s{stage}") or {}).get("flops")
    if not (fwd and bwd and busy_s > 0 and peak):
        return None
    step_flops = n_microbatches * (fwd + bwd) + (upd or 0.0)
    return {
        "program": f"mpmd_stage{stage}",
        "family": family,
        "mesh": mesh,
        "stage": stage,
        "flops": step_flops,
        "seconds": round(busy_s, 6),
        "calls": 1,
        "mfu": round(step_flops / busy_s / max(devices, 1) / peak, 6),
        "bound": "unknown",
    }


def stage_store(cfg: RunConfig, spec, k: int, mesh, input_dim: int):
    """Stage ``k``'s PR 9 AOT store: the stage id and the slice
    topology JOIN the compile identity — the same stage on a different
    carve (or schedule, or layout) is a different program and must
    miss; a warm relaunch of the same shape deserializes cache=hit."""
    from dct_tpu import compilecache as _cc

    root = (
        os.environ.get("DCT_COMPILE_CACHE_AOT_DIR")
        or os.path.join(cfg.data.models_dir, "aot")
    )
    return _cc.store_from_env(
        root,
        family=cfg.model.name,
        config_hash=_config_hash(dataclasses.asdict(cfg.model)),
        mesh=_mesh_descriptor(mesh),
        extra={
            "mpmd_stage": k,
            "mpmd_slice": mpmd.slice_descriptor(spec.device_counts),
            "mpmd_schedule": spec.schedule,
            "mpmd_microbatches": spec.n_microbatches,
            "optimizer": cfg.train.optimizer,
            "lr": cfg.train.lr,
            "weight_decay": cfg.train.weight_decay,
            "bf16": cfg.train.bf16_compute,
            "shard_rules": rules_digest(cfg.model.name),
            "input_dim": input_dim,
        },
        emit=_events.get_default().emit,
    )


def build_loaders(cfg: RunConfig, spec, data=None):
    """The SAME window/split/loader construction as ``Trainer.fit``'s
    sequence-family path (same seed, same order — the oracle pin and
    every per-stage worker process depend on identical batch streams).
    In MPMD mode ``DCT_BATCH_SIZE`` is the GLOBAL batch and must tile
    the microbatch count (loud refusal otherwise)."""
    from dct_tpu.data.dataset import load_processed_dataset
    from dct_tpu.data.pipeline import BatchLoader, contiguous_split
    from dct_tpu.data.windows import make_windows

    if data is None:
        data = load_processed_dataset(
            cfg.data.processed_dir,
            feature_suffix=cfg.data.feature_suffix,
            label_column=cfg.data.label_column,
        )
    data = make_windows(data, cfg.model.seq_len)
    train_idx, val_idx = contiguous_split(
        len(data), val_fraction=cfg.data.val_fraction,
        gap=cfg.model.seq_len,
    )
    global_batch = cfg.train.batch_size
    if global_batch % spec.n_microbatches:
        raise mpmd.MpmdSpecError(
            f"DCT_BATCH_SIZE={global_batch} (the global batch in MPMD "
            f"mode) does not tile DCT_MPMD_MICROBATCHES="
            f"{spec.n_microbatches}"
        )
    train_loader = BatchLoader(
        data, train_idx, global_batch=global_batch, shuffle=True,
        seed=cfg.train.seed,
    )
    val_loader = BatchLoader(
        data, val_idx, global_batch=global_batch, shuffle=False,
        seed=cfg.train.seed,
    )
    return data, train_loader, val_loader


class MpmdTrainer:
    """Multi-controller MPMD trainer, in-process form: one controller
    thread per stage, disjoint device slices, explicit transfers
    (:class:`dct_tpu.parallel.mpmd.MpmdRunner`). The per-stage-process
    form lives in :mod:`dct_tpu.train.mpmd_worker` and shares the
    schedule/executor/checkpoint layout byte for byte."""

    def __init__(self, cfg: RunConfig | None = None):
        self.cfg = cfg or RunConfig.from_env()

    # -- data (mirrors Trainer.fit's sequence-family path exactly) ----
    def _loaders(self, data=None):
        return build_loaders(self.cfg, self._spec, data)

    def _stage_roofline(self, bubble: dict, stores, spec) -> list[dict]:
        """Per-stage roofline records: every stage program's analytic
        cost (from its store's book), plus one ``mpmd_stage<k>`` record
        joining the stage's per-step FLOPs (M fwd+bwd passes + the
        update) with its measured busy seconds from the last step's
        bubble report — the per-stage MFU leg of the acceptance bar."""
        from dct_tpu.observability import roofline as _roofline

        if stores is None or spec is None:
            return []
        peak, _src = _roofline.resolve_peak_flops()
        out: list[dict] = []
        busy = {
            int(st["stage"]): float(st.get("busy_s") or 0.0)
            for st in (bubble.get("stages") or [])
        }
        for k, store in enumerate(stores):
            mesh_d = _mesh_descriptor(self._meshes[k])
            for program in sorted(store.costs):
                out.append({
                    "program": program,
                    "family": self.cfg.model.name,
                    "mesh": mesh_d,
                    "stage": k,
                    **store.costs[program],
                })
            rec = stage_mfu_record(
                store.costs, stage=k,
                n_microbatches=spec.n_microbatches,
                busy_s=busy.get(k, 0.0),
                devices=spec.device_counts[k],
                family=self.cfg.model.name, mesh=mesh_d, peak=peak,
            )
            if rec is not None:
                out.append(rec)
        return out

    def _publish_metrics(self, bubble: dict, stores=None, spec=None,
                         emit=None) -> None:
        """Final metrics-plane snapshot (when ``DCT_METRICS_DIR`` arms
        the plane): the last step's bubble fractions + per-stage phase
        seconds under a ``stage`` label — the /metrics side of "where
        did the bubble go" — plus the per-stage-program roofline gauges
        (``dct_program_flops`` / ``dct_program_mfu`` / ...)."""
        cfg = self.cfg
        emit = emit or _events.get_default().emit
        roofline_rep = self._stage_roofline(bubble, stores, spec)
        for r in roofline_rep:
            emit("roofline", "roofline.report", **r)
        if not (cfg.obs.enabled and cfg.obs.metrics_dir) or not bubble:
            return
        from dct_tpu.observability.aggregate import SnapshotPublisher
        from dct_tpu.observability.metrics import MetricsRegistry

        reg = MetricsRegistry()
        bubble_g = reg.gauge(
            "dct_mpmd_bubble_fraction",
            "MPMD pipeline bubble fraction of the last step, by "
            "window (steady = the 1F1B saturated window; step = whole "
            "step incl. fill/drain).", agg="last",
        )
        bubble_g.set(bubble["steady_bubble"], {"window": "steady"})
        bubble_g.set(bubble["step_bubble"], {"window": "step"})
        phase_g = reg.gauge(
            "dct_mpmd_stage_phase_seconds",
            "Per-stage busy seconds of the last MPMD step, by phase "
            "(fill/steady/drain) plus transfer_wait.", agg="last",
        )
        for st in bubble.get("stages", []):
            labels = {"stage": str(st["stage"])}
            for phase in ("fill", "steady", "drain"):
                phase_g.set(
                    st[f"{phase}_s"], {**labels, "phase": phase}
                )
            phase_g.set(
                st["transfer_wait_s"],
                {**labels, "phase": "transfer_wait"},
            )
        if roofline_rep:
            from dct_tpu.observability.roofline import (
                add_roofline_metrics,
            )

            add_roofline_metrics(reg, roofline_rep, {})
        pub = SnapshotPublisher(
            reg, cfg.obs.metrics_dir, proc=f"mpmd-{os.getpid()}",
            interval_s=cfg.obs.metrics_publish_s, start_timer=False,
        )
        pub.close(final=True)

    def _stage_stores(self, spec, input_dim: int):
        return [
            stage_store(self.cfg, spec, k, self._meshes[k], input_dim)
            for k in range(spec.n_stages)
        ]

    def fit(self, data=None) -> MpmdResult:
        cfg = self.cfg
        _validate_cfg(cfg)
        # Config-built sinks, installed as the process defaults (the
        # Trainer's pattern): the checkpoint tiers and AOT store stamp
        # the same run-correlation ID, and a stale default from an
        # earlier run in this process never shadows cfg.obs.
        events = _events.event_log_from_config(cfg.obs)
        tracer = _spans.recorder_from_config(cfg.obs)
        spec = cfg.mpmd.to_spec(n_devices=jax.device_count())
        self._spec = spec
        self._meshes = mpmd.carve_stage_meshes(
            spec.device_counts,
            model=max(1, cfg.mesh.model),
        )
        ledger = GoodputLedger()
        ledger.start()
        t_setup = ledger.clock()
        data, train_loader, val_loader = self._loaders(data)
        input_dim = data.input_dim
        ct = jnp.bfloat16 if cfg.train.bf16_compute else jnp.float32
        full_state = build_full_state(cfg, input_dim, compute_dtype=ct)

        # Resume: per-stage checkpoints first; a plain SPMD train_state
        # pivots in (mpmd.pivot); else a fresh split of the oracle init.
        start_epoch = 0
        target_epochs = cfg.train.epochs
        stage_ckptrs = [
            stage_checkpointer(cfg.data.models_dir, k)
            for k in range(spec.n_stages)
        ]
        manifest = read_manifest(cfg.data.models_dir)
        stage_states = None
        if cfg.train.resume and manifest:
            if int(manifest.get("n_stages", spec.n_stages)) != spec.n_stages:
                raise mpmd.MpmdSpecError(
                    f"checkpoint manifest holds "
                    f"{manifest.get('n_stages')} stages but the run "
                    f"configures {spec.n_stages} — an untileable stage "
                    "map; restore the saving DCT_MPMD_STAGES or clear "
                    f"{mpmd_state_root(cfg.data.models_dir)}"
                )
            # A manifest with missing stage files is a TORN set: refuse
            # loudly (the adoption path does) — a silent fresh start
            # would overwrite the surviving stages' real progress.
            missing = [
                k for k, c in enumerate(stage_ckptrs) if not c.exists()
            ]
            if missing:
                raise FileNotFoundError(
                    f"MPMD manifest names {spec.n_stages} stages but "
                    f"stage(s) {missing} have no checkpoint under "
                    f"{mpmd_state_root(cfg.data.models_dir)} — a torn "
                    "checkpoint set; restore the files or clear the "
                    "dir to restart the trajectory"
                )
            saved = stage_ckptrs[0].load_meta()
            _check_opt_identity(
                saved, cfg.train,
                f"the MPMD checkpoint set under "
                f"{mpmd_state_root(cfg.data.models_dir)}",
            )
            stage_states = [
                stage_ckptrs[k].restore(
                    mpmd.split_state(full_state, k, spec.n_stages)
                )
                for k in range(spec.n_stages)
            ]
            start_epoch = int(saved.get("epochs_completed", 0))
            saved_target = int(saved.get("target_epochs", cfg.train.epochs))
            target_epochs = (
                start_epoch + cfg.train.epochs
                if start_epoch >= saved_target else saved_target
            )
        elif cfg.train.resume:
            restored, meta = _restore_from_spmd(
                cfg.data.models_dir, full_state
            )
            if restored is not None:
                _check_opt_identity(
                    meta, cfg.train, "the SPMD train_state checkpoint"
                )
                stage_states = [
                    mpmd.split_state(restored, k, spec.n_stages)
                    for k in range(spec.n_stages)
                ]
                start_epoch = int(meta.get("epochs_completed", 0))
                saved_target = int(
                    meta.get("target_epochs", cfg.train.epochs)
                )
                target_epochs = (
                    start_epoch + cfg.train.epochs
                    if start_epoch >= saved_target else saved_target
                )
                events.emit(
                    "mpmd", "mpmd.pivot", direction="spmd_to_mpmd",
                    n_stages=spec.n_stages, epochs_completed=start_epoch,
                )
        if stage_states is None:
            stage_states = [
                mpmd.split_state(full_state, k, spec.n_stages)
                for k in range(spec.n_stages)
            ]
        stage_states = [
            shard_stage_state(s, self._meshes[k], cfg.model.name)
            for k, s in enumerate(stage_states)
        ]

        stores = self._stage_stores(spec, input_dim)
        stage_fns = build_stage_fns(
            cfg.model, input_dim, compute_dtype=ct
        )
        programs = [
            mpmd.make_stage_programs(
                k, spec.n_stages, stage_fns, store=stores[k]
            )
            for k in range(spec.n_stages)
        ]
        runner = mpmd.MpmdRunner(
            spec, stage_states, programs, self._meshes
        )
        ledger.add("startup_recovery", ledger.clock() - t_setup)

        train_losses: list[float] = []
        val_losses: list[float] = []
        bubble: dict = {}
        fit_span = tracer.open(
            "mpmd.fit", component="mpmd", n_stages=spec.n_stages,
            schedule=spec.schedule,
        )
        try:
            for epoch in range(start_epoch, target_epochs):
                ep_span = tracer.start(
                    "mpmd.epoch", component="mpmd", epoch=epoch,
                    parent_id=fit_span.span_id,
                )
                losses = []
                last_wall = 0.0
                for batch in train_loader.epoch(epoch):
                    with ledger.dispatch("train_step", key="mpmd_step"):
                        loss, last_wall = runner.train_step(
                            batch.x, batch.y, batch.weight
                        )
                    losses.append(loss)
                with ledger.span("eval"):
                    sums = np.zeros(6, np.float64)
                    for batch in val_loader.epoch(epoch):
                        sums += np.asarray(
                            runner.eval_pass(
                                batch.x, batch.y, batch.weight
                            ),
                            np.float64,
                        )
                val_loss = float(sums[0] / max(sums[2], 1.0))
                train_losses.append(float(np.mean(losses)))
                val_losses.append(val_loss)
                bubble = runner.step_bubble(last_wall)
                events.emit(
                    "mpmd", "mpmd.step_report", epoch=epoch, **bubble
                )
                agg = {
                    f: round(
                        sum(s[f] for s in bubble["stages"]), 6
                    )
                    for f in (
                        "busy_s", "transfer_wait_s", "fill_s",
                        "steady_s", "drain_s",
                    )
                }
                with ledger.span("checkpoint"):
                    meta = {
                        "epochs_completed": epoch + 1,
                        "target_epochs": target_epochs,
                        "family": cfg.model.name,
                        "val_loss": val_loss,
                        # The Trainer's cross-optimizer refusal key:
                        # carried through the pivots so an SPMD resume
                        # of this trajectory refuses a config change.
                        "optimizer": _opt_identity(cfg.train),
                    }
                    for k in range(spec.n_stages):
                        stage_ckptrs[k].save(
                            runner.states[k], dict(meta, stage=k)
                        )
                    write_manifest(cfg.data.models_dir, {
                        "version": 1,
                        "n_stages": spec.n_stages,
                        "device_counts": list(spec.device_counts),
                        "schedule": spec.schedule,
                        "n_microbatches": spec.n_microbatches,
                        "family": cfg.model.name,
                        "n_layers": cfg.model.n_layers,
                        "shard_rules": rules_digest(cfg.model.name),
                        "epochs_completed": epoch + 1,
                    })
                ep_span.end(
                    train_loss=train_losses[-1], val_loss=val_loss,
                    steady_bubble=bubble.get("steady_bubble"),
                    step_bubble=bubble.get("step_bubble"), **agg,
                )
        finally:
            fit_span.end(epochs=len(train_losses))
        events.emit(
            "mpmd", "mpmd.fit_end",
            epochs_completed=target_epochs,
            steady_bubble=bubble.get("steady_bubble"),
            step_bubble=bubble.get("step_bubble"),
        )
        self._publish_metrics(bubble, stores, spec, emit=events.emit)
        cache_states: dict = {}
        for st in stores:
            cache_states.update(st.states)
        return MpmdResult(
            train_losses=train_losses,
            val_losses=val_losses,
            epochs_completed=target_epochs,
            goodput=ledger.summary(),
            bubble=bubble,
            cache_states=cache_states,
        )
