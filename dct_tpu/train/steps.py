"""Pure-functional train/eval steps, compiled once, sharded over the mesh.

This replaces the reference's per-batch Python call stack
(LightningModule.training_step -> backward -> gloo all-reduce -> Adam.step,
jobs/train_lightning_ddp.py:66-71,88) with a single jitted function:

    loss_fn -> jax.value_and_grad -> optax update  (one XLA program)

Distribution is declarative, not imperative: the batch arrives sharded over
the mesh's ``data`` axis and params arrive replicated, so XLA inserts the
gradient all-reduce (the gloo/NCCL analog) over ICI automatically. Metrics
come back as (weighted_sum, count) pairs — already globally reduced — which
is the exact analog of Lightning's ``sync_dist=True`` logging
(jobs/train_lightning_ddp.py:70,83-84) without a separate collective.

Two compilation granularities over the SAME step bodies (shared helpers
``_train_body``/``_eval_body`` make the equivalence structural, not just
tested): per-batch jit, and whole-epoch ``lax.scan`` — one host dispatch
per epoch, the throughput path at the reference's tiny parity batch size.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax

from dct_tpu.ops.losses import (
    masked_accuracy,
    masked_binary_counts,
    masked_cross_entropy,
)
from dct_tpu.parallel.sharding_rules import cast_params_by_rules
from dct_tpu.train.state import TrainState

# Mixed-precision dispatch (docs/PARALLELISM.md §dtype rules): every
# loss/eval body below applies ``cast_params_by_rules`` to the f32
# MASTER params as the first traced op. With DCT_DTYPE_RULES unset the
# call is the identity (bits unchanged — the contract every resume/
# parity test pins); with rules set, matching param leaves enter the
# forward in bf16 while value_and_grad differentiates w.r.t. the
# UNCAST masters — the cast's vjp widens cotangents back to f32, so
# gradient accumulation and optimizer state stay full-width. The env
# is read at TRACE time: the trainer joins dtype_rules_digest() into
# the AOT program identity so a precision change recompiles loudly.


def _position_weight(logits, y, weight):
    """Per-position supervision support: [B, S, C] logits with [B, S]
    labels (or [B, S, H, C] with [B, S, H] — the multi-horizon causal
    head) broadcast the [B] row weight over the label positions (padded
    rows mask every position; the mean stays per-position)."""
    if logits.ndim == y.ndim + 1 and y.ndim >= 2 and weight.ndim == 1:
        return jnp.broadcast_to(
            weight.reshape(-1, *([1] * (y.ndim - 1))), y.shape
        )
    return weight


def _train_body(state: TrainState, x, y, weight):
    """One optimization step: (state, batch) -> (new_state, loss).

    Computes the global weighted-mean CE (the reference's ``train_loss``,
    jobs/train_lightning_ddp.py:70), its grads, and the Adam update.
    Models may sow extra objective terms into the ``aux_loss`` collection
    (e.g. the MoE family's pre-weighted load-balance loss); every sown
    leaf is added to the objective. For models that sow nothing the
    collection is empty and this is a no-op.
    """
    step_rng = jax.random.fold_in(state.rng, state.step)

    def loss_fn(params):
        logits, updates = state.apply_fn(
            cast_params_by_rules(params), x, train=True,
            rngs={"dropout": step_rng}, mutable=["aux_loss"],
        )
        w = _position_weight(logits, y, weight)
        loss_sum, count = masked_cross_entropy(logits, y, w)
        loss = loss_sum / jnp.maximum(count, 1.0)
        for leaf in jax.tree.leaves(updates):
            loss = loss + leaf
        return loss

    loss, grads = jax.value_and_grad(loss_fn)(state.params)
    # Gradient global norm: the health monitor's drift signal. One fused
    # reduction over leaves XLA already has resident — and dead-code
    # eliminated entirely by factories that do not emit it.
    return state.apply_gradients(grads), loss, optax.global_norm(grads)


def _eval_body(state: TrainState, x, y, weight):
    """One eval step -> (loss_sum, acc_sum, count, tp, fp, fn) running
    sums (the reference's ``val_loss``/``val_acc``,
    jobs/train_lightning_ddp.py:73-85, plus the positive-class counts
    behind precision/recall/F1 — a metric surface the reference's rain
    classifier lacks). Sown aux losses are training regularizers only;
    val_loss stays pure CE."""
    logits, _ = state.apply_fn(
        cast_params_by_rules(state.params), x, train=False,
        mutable=["aux_loss"],
    )
    w = _position_weight(logits, y, weight)
    loss_sum, count = masked_cross_entropy(logits, y, w)
    acc_sum, _ = masked_accuracy(logits, y, w)
    tp, fp, fn = masked_binary_counts(logits, y, w)
    return loss_sum, acc_sum, count, tp, fp, fn


def _train_accum_body(state: TrainState, x, y, weight, accum_steps: int):
    """One optimizer step over ``accum_steps`` microbatches: grads are
    accumulated in a ``lax.scan`` (one resident microbatch of activations
    at a time — effective batch grows without growing live HBM) and
    applied once. Exactly equal to one big-batch step for the CE term
    (the weighted-sum/total decomposition is linear; ``total`` is
    param-independent); sown aux losses average over microbatches."""
    b = x.shape[0]
    step_rng = jax.random.fold_in(state.rng, state.step)
    xs = x.reshape(accum_steps, b // accum_steps, *x.shape[1:])
    ys = y.reshape(accum_steps, b // accum_steps, *y.shape[1:])
    ws = weight.reshape(accum_steps, b // accum_steps)
    # Per-position supervision ([B, S] or [B, S, H] labels) counts every
    # supervised position.
    positions = 1
    for d in y.shape[1:]:
        positions *= d
    total = jnp.maximum(weight.sum() * positions, 1.0)

    def chunk_loss(params, cx, cy, cw, rng):
        logits, updates = state.apply_fn(
            cast_params_by_rules(params), cx, train=True,
            rngs={"dropout": rng}, mutable=["aux_loss"],
        )
        loss_sum, _ = masked_cross_entropy(
            logits, cy, _position_weight(logits, cy, cw)
        )
        loss = loss_sum / total
        for leaf in jax.tree.leaves(updates):
            loss = loss + leaf / accum_steps
        return loss

    grad_fn = jax.value_and_grad(chunk_loss)

    def body(carry, chunk):
        gacc, lacc, i = carry
        cx, cy, cw = chunk
        loss_i, g = grad_fn(
            state.params, cx, cy, cw, jax.random.fold_in(step_rng, i)
        )
        return (jax.tree.map(jnp.add, gacc, g), lacc + loss_i, i + 1), None

    zeros = jax.tree.map(jnp.zeros_like, state.params)
    (grads, loss, _), _ = jax.lax.scan(
        body, (zeros, jnp.zeros(()), jnp.zeros((), jnp.int32)), (xs, ys, ws)
    )
    # Norm of the ACCUMULATED gradient — the update the optimizer sees.
    return state.apply_gradients(grads), loss, optax.global_norm(grads)


def make_train_step(donate: bool = True, accum_steps: int = 1,
                    with_grad_norm: bool = False):
    """Per-batch jitted step: (state, x, y, weight) -> (state, metrics).
    ``accum_steps`` > 1 splits the batch into that many microbatches and
    accumulates gradients before the single optimizer update.
    ``with_grad_norm=True`` adds ``metrics["grad_norm"]`` (the health
    monitor's signal); the default keeps the historical metrics dict so
    bench/step-time consumers measure the exact prior program."""

    def train_step(state: TrainState, x, y, weight):
        if accum_steps > 1:
            new_state, loss, gnorm = _train_accum_body(
                state, x, y, weight, accum_steps
            )
        else:
            new_state, loss, gnorm = _train_body(state, x, y, weight)
        metrics = {"train_loss": loss}
        if with_grad_norm:
            metrics["grad_norm"] = gnorm
        return new_state, metrics

    return jax.jit(train_step, donate_argnums=(0,) if donate else ())


def _epoch_train_scan(state: TrainState, xs, ys, ws, accum_steps: int):
    """Shared whole-epoch train scan body (see make_epoch_train_step):
    -> (state, losses[S'], grad_norms[S']) with S' = optimizer updates.
    The stacked grad norms are free for callers that drop them (XLA
    DCEs unused scan outputs at lowering)."""
    if accum_steps > 1:
        s, b = xs.shape[0], xs.shape[1]
        xs = xs.reshape(s // accum_steps, accum_steps * b, *xs.shape[2:])
        # Trailing label dims survive (per-position [S, B, seq] labels
        # of the causal family).
        ys = ys.reshape(s // accum_steps, accum_steps * b, *ys.shape[2:])
        ws = ws.reshape(s // accum_steps, accum_steps * b)

        def body(st, batch):
            st, loss, gnorm = _train_accum_body(st, *batch, accum_steps)
            return st, (loss, gnorm)
    else:
        def body(st, batch):
            st, loss, gnorm = _train_body(st, *batch)
            return st, (loss, gnorm)

    state, (losses, gnorms) = jax.lax.scan(body, state, (xs, ys, ws))
    return state, losses, gnorms


def _epoch_eval_scan(state: TrainState, xs, ys, ws):
    """Shared whole-valset eval scan body -> the 6 global metric sums
    (loss_sum, acc_sum, count, tp, fp, fn)."""

    def body(carry, batch):
        sums = _eval_body(state, *batch)
        return tuple(a + b for a, b in zip(carry, sums)), None

    zeros = tuple(jnp.zeros(()) for _ in range(6))
    sums, _ = jax.lax.scan(body, zeros, (xs, ys, ws))
    return sums


def make_epoch_train_step(donate: bool = True, accum_steps: int = 1,
                          with_grad_norms: bool = False):
    """Whole-epoch training as one XLA program: ``lax.scan`` of
    ``_train_body`` over the stacked batches [S, B, ...].

    Semantically identical to S calls of the per-batch step (same rng
    folding, same order, same updates) but with ONE host dispatch per epoch
    instead of S — at the reference's parity batch size (4/rank,
    jobs/train_lightning_ddp.py:122) per-step dispatch latency dominates a
    TPU step, so this is where the throughput win over the eager loop
    comes from. Returns (state, losses[S]) so per-step logging cadence
    (log_every_n_steps, :139) is preserved from the host side.

    ``accum_steps`` > 1 groups every ``accum_steps`` consecutive stacked
    batches into ONE optimizer update (gradient accumulation); S must be
    divisible (the Trainer truncates the remainder).

    ``with_grad_norms=True`` appends the per-update gradient global
    norms ``[S']`` to the outputs (the health monitor's drift signal);
    the default keeps the historical (state, losses) signature, and the
    unemitted norms are DCE'd at lowering.
    """

    def epoch_train(state: TrainState, xs, ys, ws):
        state, losses, gnorms = _epoch_train_scan(
            state, xs, ys, ws, accum_steps
        )
        if with_grad_norms:
            return state, losses, gnorms
        return state, losses

    return jax.jit(epoch_train, donate_argnums=(0,) if donate else ())


def _epoch_donate(donate: bool, donate_stacks: bool) -> tuple:
    """Donation sets for the fused train+eval programs: argnum 0 is the
    state; 1-3 are the single-use epoch/span stacks (donating them frees
    a full span of HBM before activations peak). The validation stacks
    (4-6) are NEVER donated — they are reused every span. Callers that
    re-dispatch the same stacks (the bench's timed repeats) must keep
    donate_stacks=False or their second call reads donated buffers."""
    nums = (0,) if donate else ()
    if donate_stacks:
        nums = nums + (1, 2, 3)
    return nums


def make_epoch_train_eval_step(donate: bool = True, accum_steps: int = 1,
                               donate_stacks: bool = False,
                               with_grad_norms: bool = False):
    """Train epoch + full validation pass as ONE XLA program — one host
    dispatch per epoch where train-then-eval would cost two. On a slow
    control plane (tunneled TPU) the saved round trip is most of an
    epoch's wall time at the parity batch size; the numerics are
    identical to make_epoch_train_step followed by make_epoch_eval_step
    (eval runs on the post-epoch state).

    Returns (state, losses[S], the 6 eval sums (val_loss_sum,
    val_acc_sum, val_count, tp, fp, fn)); ``with_grad_norms=True``
    appends the per-update grad global norms [S]. The validation stacks
    are NOT donated — they are reused every epoch.
    """

    def epoch_fused(state: TrainState, xs, ys, ws, vxs, vys, vws):
        state, losses, gnorms = _epoch_train_scan(
            state, xs, ys, ws, accum_steps
        )
        sums = _epoch_eval_scan(state, vxs, vys, vws)
        if with_grad_norms:
            return state, losses, sums, gnorms
        return state, losses, sums

    donate_argnums = _epoch_donate(donate, donate_stacks)
    return jax.jit(epoch_fused, donate_argnums=donate_argnums)


def make_multi_epoch_train_eval_step(donate: bool = True,
                                     accum_steps: int = 1,
                                     donate_stacks: bool = False,
                                     with_grad_norms: bool = False):
    """K training epochs, each followed by a full validation pass, as ONE
    XLA program — an outer ``lax.scan`` over epochs of the fused
    epoch-train+eval body. Numerically identical to K sequential calls of
    make_epoch_train_eval_step (same scan order, same rng folding via the
    step counter), but one host dispatch where K would each pay a control-
    plane round trip — the throughput lever behind
    ``TrainConfig.epoch_chunk`` on tunneled/slow-dispatch rigs.

    Args are the per-epoch stacks with a leading epoch dim:
    xs/ys/ws: [K, S, B, ...]; the validation stacks [S_v, B, ...] are
    shared (fixed order) across epochs and NOT donated.

    Returns (state, losses[K, S], val_sums = 6-tuple of [K] arrays);
    ``with_grad_norms=True`` appends the grad global norms [K, S].
    The sums come back as a TUPLE (the scan stacks each leaf separately)
    rather than one jnp.stack'd [K, 6] array, so every sum keeps its own
    dtype — a single f32 stack would silently coerce any future integer
    count leaf, and hosts that want exactness can upcast each leaf to
    float64 after device_get (ADVICE r4). Today all six are f32 weighted
    sums by design (fractional sample weights), exact for integral
    weights up to 2^24 per epoch — the k == 1 fused path shares that
    bound, it is an accumulation property, not a stacking one.
    """

    def multi_epoch(state: TrainState, xs, ys, ws, vxs, vys, vws):
        def epoch_body(st, stacks):
            exs, eys, ews = stacks
            st, losses, gnorms = _epoch_train_scan(
                st, exs, eys, ews, accum_steps
            )
            sums = _epoch_eval_scan(st, vxs, vys, vws)
            return st, (losses, gnorms, sums)

        state, (losses, gnorms, val_sums) = jax.lax.scan(
            epoch_body, state, (xs, ys, ws)
        )
        if with_grad_norms:
            return state, losses, val_sums, gnorms
        return state, losses, val_sums

    return jax.jit(
        multi_epoch, donate_argnums=_epoch_donate(donate, donate_stacks)
    )


def make_eval_step():
    """Per-batch jitted eval step returning running-sum metrics."""
    return jax.jit(_eval_body)


def make_epoch_eval_step():
    """Whole-valset evaluation as one scan of ``_eval_body``; returns
    the 6 global sums (loss_sum, acc_sum, count, tp, fp, fn)."""
    return jax.jit(_epoch_eval_scan)
