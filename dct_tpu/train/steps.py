"""Pure-functional train/eval steps, compiled once, sharded over the mesh.

This replaces the reference's per-batch Python call stack
(LightningModule.training_step -> backward -> gloo all-reduce -> Adam.step,
jobs/train_lightning_ddp.py:66-71,88) with a single jitted function:

    loss_fn -> jax.value_and_grad -> optax update  (one XLA program)

Distribution is declarative, not imperative: the batch arrives sharded over
the mesh's ``data`` axis and params arrive replicated, so XLA inserts the
gradient all-reduce (the gloo/NCCL analog) over ICI automatically. Metrics
come back as (weighted_sum, count) pairs — already globally reduced — which
is the exact analog of Lightning's ``sync_dist=True`` logging
(jobs/train_lightning_ddp.py:70,83-84) without a separate collective.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from dct_tpu.ops.losses import masked_accuracy, masked_cross_entropy
from dct_tpu.train.state import TrainState


def make_train_step(donate: bool = True):
    """Build the jitted train step: (state, x, y, weight) -> (state, metrics).

    metrics = {"train_loss": global weighted-mean CE} matching the
    reference's logged ``train_loss`` (jobs/train_lightning_ddp.py:70).
    """

    def train_step(state: TrainState, x, y, weight):
        step_rng = jax.random.fold_in(state.rng, state.step)

        def loss_fn(params):
            logits = state.apply_fn(
                params, x, train=True, rngs={"dropout": step_rng}
            )
            loss_sum, count = masked_cross_entropy(logits, y, weight)
            return loss_sum / jnp.maximum(count, 1.0)

        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        new_state = state.apply_gradients(grads)
        return new_state, {"train_loss": loss}

    return jax.jit(train_step, donate_argnums=(0,) if donate else ())


def make_eval_step():
    """Build the jitted eval step returning running-sum metrics.

    Returns (loss_sum, acc_sum, count) so the caller accumulates exact
    global means over the whole validation set — the reference's
    ``val_loss`` / ``val_acc`` (jobs/train_lightning_ddp.py:73-85).
    """

    def eval_step(state: TrainState, x, y, weight):
        logits = state.apply_fn(state.params, x, train=False)
        loss_sum, count = masked_cross_entropy(logits, y, weight)
        acc_sum, _ = masked_accuracy(logits, y, weight)
        return loss_sum, acc_sum, count

    return jax.jit(eval_step)
