"""Synthetic weather.csv generator.

The reference repo ships no data (``data/`` is git-ignored, .gitignore:36-39);
its pipeline expects a user-provided ``data/raw/weather.csv`` with columns
Temperature, Humidity, Wind_Speed, Cloud_Cover, Pressure and a string label
``Rain`` in {"rain", "no rain"} (jobs/preprocess.py:23-29). This module
produces a schema-compatible CSV with a learnable (linearly separable-ish)
rain signal so tests and benchmarks can exercise the full ETL->train->deploy
path hermetically.
"""

from __future__ import annotations

import os

import numpy as np

FEATURE_COLUMNS = ["Temperature", "Humidity", "Wind_Speed", "Cloud_Cover", "Pressure"]
LABEL_COLUMN = "Rain"


def _ar1(rng, rows: int, mu: float, sigma: float, phi: float = 0.85):
    """Stationary AR(1) series: mean ``mu``, std ``sigma``, autocorrelation
    ``phi`` — weather-like temporal persistence, so sequence models can
    actually forecast the next step (i.i.d. rows would make the windowed
    task coin-flip by construction)."""
    eps = rng.normal(0.0, sigma * np.sqrt(1.0 - phi * phi), rows)
    x = np.empty(rows)
    x[0] = rng.normal(mu, sigma)
    for t in range(1, rows):
        x[t] = mu + phi * (x[t - 1] - mu) + eps[t]
    return x


def generate_weather_csv(path: str, *, rows: int = 2500, seed: int = 0) -> str:
    """Write a synthetic weather.csv; returns the path."""
    rng = np.random.default_rng(seed)
    temperature = _ar1(rng, rows, 18.0, 8.0)
    humidity = np.clip(_ar1(rng, rows, 60.0, 20.0), 0, 100)
    wind = np.abs(_ar1(rng, rows, 12.0, 6.0))
    cloud = np.clip(_ar1(rng, rows, 50.0, 25.0), 0, 100)
    pressure = _ar1(rng, rows, 1013.0, 8.0)

    # Rain correlates with humidity + cloud cover - pressure anomaly.
    logit = (
        0.06 * (humidity - 60.0)
        + 0.05 * (cloud - 50.0)
        - 0.08 * (pressure - 1013.0)
        + rng.normal(0.0, 0.8, rows)
    )
    rain = np.where(logit > 0.0, "rain", "no rain")

    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    cols = [temperature, humidity, wind, cloud, pressure]
    with open(path, "w") as f:
        f.write(",".join(FEATURE_COLUMNS + [LABEL_COLUMN]) + "\n")
        for i in range(rows):
            vals = ",".join(f"{c[i]:.4f}" for c in cols)
            f.write(f"{vals},{rain[i]}\n")
    return path


def append_weather_rows(path: str, *, rows: int, seed: int) -> str:
    """Append freshly-generated rows (same schema/distribution) to an
    existing weather CSV — the always-on loop's staging-path growth
    pattern (docs/CONTINUOUS.md). The payload is complete lines written
    in ONE ``write`` call and every generated file ends in a newline,
    so the incremental ETL's append-only digest check holds and a
    concurrent poll can at worst observe a clean prefix. Returns the
    path."""
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        extra = os.path.join(td, "extra.csv")
        generate_weather_csv(extra, rows=rows, seed=seed)
        with open(extra) as f:
            payload = "".join(f.readlines()[1:])  # drop the header
    with open(path, "a") as f:
        f.write(payload)
    return path
