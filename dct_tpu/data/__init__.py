from dct_tpu.data.dataset import WeatherArrays, load_processed_dataset  # noqa: F401
from dct_tpu.data.pipeline import (  # noqa: F401
    train_val_split,
    BatchLoader,
)
from dct_tpu.data.synthetic import generate_weather_csv  # noqa: F401
