"""Sliding-window view over the processed row stream for sequence models.

The reference has no sequence models (SURVEY.md §5.7: inputs are 5-feature
tabular rows, jobs/preprocess.py:29); the transformer family is this
framework's extension. The data contract stays identical to the row path:
:class:`WindowArrays` mirrors :class:`~dct_tpu.data.dataset.WeatherArrays`
(``features`` / ``labels`` / ``feature_names`` / ``__len__`` /
``input_dim``), so the split, :class:`~dct_tpu.data.pipeline.BatchLoader`,
checkpointing, and tracking paths are reused unchanged — only the feature
rank changes ([N, F] -> [N, S, F]).

Windowing is next-step supervision over the time-ordered stream: window
``i`` is rows ``[i, i+seq_len)`` and its label is row ``i+seq_len``'s label
(predict the step after the window). Construction is a zero-copy
``sliding_window_view``; rows are only materialized when the loader gathers
a batch.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np
from numpy.lib.stride_tricks import sliding_window_view

from dct_tpu.data.dataset import WeatherArrays


@dataclass
class WindowArrays:
    """Windowed host arrays; drop-in for WeatherArrays downstream."""

    features: np.ndarray  # [N, S, F] float32 (strided view until gathered)
    labels: np.ndarray  # [N] int32
    feature_names: list[str]
    seq_len: int
    # The un-windowed [rows, F] stream the strided view points into; the
    # native gather path copies seq contiguous rows per window from here
    # instead of fancy-indexing the view.
    base: np.ndarray | None = None

    def __len__(self) -> int:
        return int(self.features.shape[0])

    @property
    def input_dim(self) -> int:
        return int(self.features.shape[2])

    def take(self, indices: np.ndarray) -> np.ndarray:
        """Gather windows: [*indices.shape, S, F]. Window index == start
        row in ``base``, so the native path is one contiguous copy per
        window."""
        if self.base is not None:
            from dct_tpu import native

            return native.gather_windows(self.base, indices, self.seq_len)
        return self.features[np.asarray(indices)]


def make_windows(
    data: WeatherArrays, seq_len: int, *, per_position_labels: bool = False,
    horizon: int = 1,
) -> WindowArrays:
    """[N, F] rows -> [N_w, seq_len, F] windows with next-step labels.

    ``per_position_labels``: labels become [N_w, S] — position ``t`` of
    window ``i`` is supervised with row ``i+t+1``'s label (causal
    next-step prediction at EVERY position, the causal transformer
    family's training signal); the final column equals the default
    window-level label.

    ``horizon`` (per-position only): DIRECT multi-horizon supervision —
    labels become [N_w, S, H] where entry (i, t, h) is row
    ``i+t+1+h``'s label: every position forecasts steps t+1..t+H in one
    forward pass, no autoregressive feedback. The window count shrinks
    to ``N - seq_len - horizon + 1`` so every horizon slot exists.
    """
    n = len(data)
    if seq_len < 1:
        raise ValueError(f"seq_len must be >= 1, got {seq_len}")
    if horizon < 1:
        raise ValueError(f"horizon must be >= 1, got {horizon}")
    if horizon > 1 and not per_position_labels:
        raise ValueError(
            "horizon > 1 requires per_position_labels=True (the causal "
            "family's training signal)"
        )
    n_w = n - seq_len - horizon + 1
    if n_w < 1:
        raise ValueError(
            f"Need more than seq_len+horizon-1={seq_len + horizon - 1} "
            f"rows to build windows; dataset has {n}."
        )
    base = np.ascontiguousarray(data.features, dtype=np.float32)
    # sliding_window_view puts the window axis last: [N-S+1, F, S], zero-copy.
    windows = sliding_window_view(base, seq_len, axis=0)
    windows = np.moveaxis(windows, -1, 1)  # -> [N-S+1, S, F]
    if per_position_labels and horizon > 1:
        lab = data.labels.astype(np.int32)
        # Lh[j] = labels[j : j+H]; position t of window i needs Lh[i+t+1]
        # -> a second sliding window of length S starting at i+1.
        lh = sliding_window_view(lab, horizon)  # [N-H+1, H]
        labels = np.ascontiguousarray(
            sliding_window_view(lh, seq_len, axis=0)[1 : 1 + n_w]
            .transpose(0, 2, 1)
        )  # [N_w, S, H]; (i, t, h) = label of row i+t+1+h
    elif per_position_labels:
        labels = np.ascontiguousarray(
            sliding_window_view(
                data.labels[1:].astype(np.int32), seq_len, axis=0
            )[:n_w]
        )  # [N-S, S]; row i column t = label of row i+t+1
    else:
        labels = data.labels[seq_len:].astype(np.int32)
    return WindowArrays(
        features=windows[:n_w],
        labels=labels,
        feature_names=list(data.feature_names),
        seq_len=int(seq_len),
        base=base,
    )
