"""Deterministic split + explicit sharded batching.

Replaces three implicit mechanisms of the reference with explicit ones:

1. ``random_split`` 80/20 under global seed 42
   (jobs/train_lightning_ddp.py:14,117-119) -> a seeded permutation split.
2. Lightning's auto-injected ``DistributedSampler`` (implicit; every rank
   loads the full dataset at jobs/train_lightning_ddp.py:114 and the sampler
   hands each rank a shard) -> an explicit contiguous per-process block of
   each shuffled global batch.
3. ``DataLoader(batch_size=4, shuffle=True)`` with a ragged final batch
   (:122-123) -> fixed-shape batches padded to the global batch size with a
   weight mask, so a single jit-compiled step serves every batch (XLA traces
   once; no recompilation on the last partial batch, and masked weighting
   reproduces torch's mean-over-real-elements cross entropy exactly).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterator

import numpy as np

from dct_tpu import native
from dct_tpu.data.dataset import WeatherArrays


def train_val_split(
    n: int, *, val_fraction: float = 0.2, seed: int = 42
) -> tuple[np.ndarray, np.ndarray]:
    """Seeded index split. train gets ``int((1-val_fraction)*n)`` elements,
    matching the reference's ``train_size = int(0.8 * len)`` arithmetic
    (jobs/train_lightning_ddp.py:117-118)."""
    train_size = int((1.0 - val_fraction) * n)
    perm = np.random.default_rng(seed).permutation(n)
    return perm[:train_size], perm[train_size:]


def contiguous_split(
    n: int, *, val_fraction: float = 0.2, gap: int = 0
) -> tuple[np.ndarray, np.ndarray]:
    """Time-ordered split for overlapping-window data: train = leading
    block, val = trailing block, with ``gap`` indices dropped between them.

    A random row split (above) is correct for i.i.d. rows but leaks badly
    for sliding windows — window ``i`` and ``i+1`` share ``seq_len-1`` rows,
    so adjacent train/val windows would share almost all content. With
    ``gap >= seq_len`` no val window overlaps any train window's rows."""
    train_size = int((1.0 - val_fraction) * n)
    val_start = min(n, train_size + gap)
    return np.arange(train_size), np.arange(val_start, n)


@dataclass
class Batch:
    """One fixed-shape global batch.

    ``weight`` is 1.0 for real rows, 0.0 for padding; losses/metrics are
    weighted sums divided by ``weight.sum()`` so padding is invisible.
    """

    x: np.ndarray  # [B, F] float32
    y: np.ndarray  # [B] int32
    weight: np.ndarray  # [B] float32


class BatchLoader:
    """Fixed-shape, process-sharded batch stream over host arrays.

    ``global_batch`` is the cross-process, cross-device batch (the reference's
    per-rank batch 4 x world_size). Each call to :meth:`epoch` yields batches
    covering this process's block of each (optionally shuffled) global batch;
    shapes are always ``[global_batch // num_processes, ...]``.

    Sharding is by contiguous block: process ``p`` takes rows
    ``[p*B_local, (p+1)*B_local)`` of every global batch. Unlike torch
    ``DistributedSampler``'s round-robin, block sharding means
    ``jax.make_array_from_process_local_data`` reassembles the global batch
    in EXACTLY single-process row order — so a W-process run is bitwise the
    same program as a 1-process run on the same global batch (same dropout
    mask assignment, same reduction tree), which makes DDP-equivalence
    directly testable. Like the sampler, the stream is padded (by wrapping)
    so every process sees the same number of batches — mandatory for SPMD
    collectives to line up.
    """

    def __init__(
        self,
        data: WeatherArrays,
        indices: np.ndarray,
        *,
        global_batch: int,
        shuffle: bool,
        seed: int = 42,
        num_processes: int = 1,
        process_id: int = 0,
    ):
        if global_batch % num_processes != 0:
            raise ValueError(
                f"global_batch {global_batch} not divisible by "
                f"num_processes {num_processes}"
            )
        self.data = data
        self.indices = np.asarray(indices)
        self.global_batch = int(global_batch)
        self.local_batch = self.global_batch // num_processes
        self.shuffle = shuffle
        self.seed = seed
        self.num_processes = num_processes
        self.process_id = process_id

    @property
    def num_batches(self) -> int:
        n = len(self.indices)
        return max(1, -(-n // self.global_batch)) if n else 0

    def _epoch_indices(self, epoch: int) -> np.ndarray:
        idx = self.indices
        if self.shuffle:
            rng = np.random.default_rng(np.random.SeedSequence([self.seed, epoch]))
            idx = idx[rng.permutation(len(idx))]
        return idx

    def epoch_stacked(self, epoch: int):
        """The whole epoch as three [S, B_local, ...] arrays in one
        vectorized gather — identical indices/weights to :meth:`epoch`,
        built without a per-batch Python loop (the scan path feeds the
        accelerator one epoch at a time; host assembly must not become the
        bottleneck)."""
        idx = self._epoch_indices(epoch)
        n = len(idx)
        lb, gb = self.local_batch, self.global_batch
        if n == 0:
            sample_shape = self.data.features.shape[1:]
            return (
                np.zeros((0, lb, *sample_shape), np.float32),
                np.zeros((0, lb), np.int32),
                np.zeros((0, lb), np.float32),
            )
        steps = -(-n // gb)
        padded = np.resize(idx, steps * gb)  # wrap-pad, like epoch()
        weights = np.zeros(steps * gb, np.float32)
        weights[:n] = 1.0
        sl = slice(self.process_id * lb, (self.process_id + 1) * lb)
        mat = padded.reshape(steps, gb)[:, sl]
        return (
            self.data.take(mat),
            native.gather_i32(self.data.labels, mat),
            weights.reshape(steps, gb)[:, sl],
        )

    def epoch(self, epoch: int) -> Iterator[Batch]:
        idx = self._epoch_indices(epoch)
        n = len(idx)
        if n == 0:
            return
        for start in range(0, n, self.global_batch):
            chunk = idx[start : start + self.global_batch]
            real = len(chunk)
            if real < self.global_batch:
                # Pad by wrapping; padded rows get weight 0.
                pad = np.resize(idx, self.global_batch - real)
                chunk = np.concatenate([chunk, pad])
            weight = np.zeros(self.global_batch, np.float32)
            weight[:real] = 1.0
            # Contiguous per-process block (DistributedSampler analog with
            # order-preserving global reassembly).
            sl = slice(
                self.process_id * self.local_batch,
                (self.process_id + 1) * self.local_batch,
            )
            yield Batch(
                x=self.data.take(chunk[sl]),
                y=native.gather_i32(self.data.labels, chunk[sl]),
                weight=weight[sl],
            )
