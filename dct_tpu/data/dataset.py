"""Processed-dataset loading: the Spark->trainer parquet contract.

Mirrors the behavior of the reference's WeatherDataset
(jobs/train_lightning_ddp.py:16-49):

- the ETL step writes a parquet *directory* named ``data.parquet`` inside the
  processed dir (jobs/preprocess.py:44-51);
- loading hard-fails with a clear message if it is missing (:22-26);
- feature columns are discovered dynamically by the ``_norm`` suffix (:37),
  hard-failing if none exist (:39-40);
- features load as float32, labels as integer class ids (:45-46).

The TPU-native difference: arrays are plain numpy (host RAM), converted to
device arrays only at batch-dispatch time with an explicit
``jax.sharding.NamedSharding`` — there is no per-item Dataset/DataLoader
object graph, because XLA wants large static-shape batches, not Python
iteration per sample.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

import numpy as np


@dataclass
class WeatherArrays:
    """Column-major host arrays for the whole dataset."""

    features: np.ndarray  # [N, F] float32
    labels: np.ndarray  # [N] int32
    feature_names: list[str]

    def __len__(self) -> int:
        return int(self.features.shape[0])

    @property
    def input_dim(self) -> int:
        return int(self.features.shape[1])

    def take(self, indices: np.ndarray) -> np.ndarray:
        """Gather feature rows: [*indices.shape, F]. Uses the native C++
        data plane when available (numpy fancy-index fallback)."""
        from dct_tpu import native

        return native.gather_rows(self.features, indices)


def load_processed_dataset(
    processed_dir: str,
    *,
    feature_suffix: str = "_norm",
    label_column: str = "label_encoded",
    parquet_name: str = "data.parquet",
) -> WeatherArrays:
    """Load the ETL output (a parquet file or directory) into host arrays.

    Accepts both a Spark-style parquet directory and a single parquet file,
    like ``pd.read_parquet`` does in the reference
    (jobs/train_lightning_ddp.py:31).
    """
    parquet_path = os.path.join(processed_dir, parquet_name)
    if not os.path.exists(parquet_path):
        raise FileNotFoundError(
            f"CRITICAL ERROR: Data not found at {parquet_path}. "
            "Did the preprocessing step finish successfully?"
        )

    import pyarrow.parquet as pq

    try:
        table = pq.read_table(parquet_path)
    except Exception as e:  # pragma: no cover - IO failure surface
        raise RuntimeError(f"Failed to read Parquet file: {e}") from e

    names = list(table.column_names)
    feature_cols = [c for c in names if c.endswith(feature_suffix)]
    if not feature_cols:
        raise ValueError(
            f"CRITICAL ERROR: No columns ending with '{feature_suffix}' found. "
            "Check the preprocessing logic."
        )
    if label_column not in names:
        raise ValueError(
            f"CRITICAL ERROR: Label column '{label_column}' not found in "
            f"columns {names}."
        )

    feats = np.stack(
        [table.column(c).to_numpy(zero_copy_only=False) for c in feature_cols],
        axis=1,
    ).astype(np.float32)
    labels = table.column(label_column).to_numpy(zero_copy_only=False).astype(np.int32)
    return WeatherArrays(features=feats, labels=labels, feature_names=feature_cols)


# ----------------------------------------------------------------------
# Snapshot-keyed load cache: the always-on loop's evaluator re-reads the
# SAME processed snapshot on every champion/challenger pass (one pass
# per new best checkpoint, several per ETL generation) — the parquet IO
# dominates those evals at dataset scale. Keyed by the part files'
# (name, mtime_ns, size) set, so an incremental-ETL delta part (or a
# full-rebuild swap) invalidates on the next call.

_LOAD_CACHE: dict[tuple, tuple[tuple, WeatherArrays]] = {}
_LOAD_CACHE_SLOTS = 4


def _snapshot_key(parquet_path: str) -> tuple | None:
    """Stat-derived identity of a parquet file or directory snapshot;
    None when it cannot be stat'd (callers fall through to the loud
    loader)."""
    try:
        if os.path.isdir(parquet_path):
            entries = []
            for name in sorted(os.listdir(parquet_path)):
                if not name.endswith(".parquet"):
                    continue
                st = os.stat(os.path.join(parquet_path, name))
                entries.append((name, st.st_mtime_ns, st.st_size))
            return tuple(entries) or None
        st = os.stat(parquet_path)
        return ((os.path.basename(parquet_path), st.st_mtime_ns, st.st_size),)
    except OSError:
        return None


def load_processed_dataset_cached(
    processed_dir: str,
    *,
    feature_suffix: str = "_norm",
    label_column: str = "label_encoded",
    parquet_name: str = "data.parquet",
) -> WeatherArrays:
    """:func:`load_processed_dataset` behind a snapshot-keyed cache.

    Returns the SAME :class:`WeatherArrays` object for an unchanged
    snapshot — callers must treat it as immutable. Bounded to
    ``_LOAD_CACHE_SLOTS`` snapshots (oldest-inserted evicted), so a
    loop cycling processed dirs cannot grow host RAM unboundedly.
    """
    cache_id = (
        os.path.abspath(processed_dir), feature_suffix, label_column,
        parquet_name,
    )
    key = _snapshot_key(os.path.join(processed_dir, parquet_name))
    if key is not None:
        hit = _LOAD_CACHE.get(cache_id)
        if hit is not None and hit[0] == key:
            return hit[1]
    data = load_processed_dataset(
        processed_dir,
        feature_suffix=feature_suffix,
        label_column=label_column,
        parquet_name=parquet_name,
    )
    if key is not None:
        _LOAD_CACHE[cache_id] = (key, data)
        while len(_LOAD_CACHE) > _LOAD_CACHE_SLOTS:
            _LOAD_CACHE.pop(next(iter(_LOAD_CACHE)))
    return data
