"""Processed-dataset loading: the Spark->trainer parquet contract.

Mirrors the behavior of the reference's WeatherDataset
(jobs/train_lightning_ddp.py:16-49):

- the ETL step writes a parquet *directory* named ``data.parquet`` inside the
  processed dir (jobs/preprocess.py:44-51);
- loading hard-fails with a clear message if it is missing (:22-26);
- feature columns are discovered dynamically by the ``_norm`` suffix (:37),
  hard-failing if none exist (:39-40);
- features load as float32, labels as integer class ids (:45-46).

The TPU-native difference: arrays are plain numpy (host RAM), converted to
device arrays only at batch-dispatch time with an explicit
``jax.sharding.NamedSharding`` — there is no per-item Dataset/DataLoader
object graph, because XLA wants large static-shape batches, not Python
iteration per sample.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

import numpy as np


@dataclass
class WeatherArrays:
    """Column-major host arrays for the whole dataset."""

    features: np.ndarray  # [N, F] float32
    labels: np.ndarray  # [N] int32
    feature_names: list[str]

    def __len__(self) -> int:
        return int(self.features.shape[0])

    @property
    def input_dim(self) -> int:
        return int(self.features.shape[1])

    def take(self, indices: np.ndarray) -> np.ndarray:
        """Gather feature rows: [*indices.shape, F]. Uses the native C++
        data plane when available (numpy fancy-index fallback)."""
        from dct_tpu import native

        return native.gather_rows(self.features, indices)


def load_processed_dataset(
    processed_dir: str,
    *,
    feature_suffix: str = "_norm",
    label_column: str = "label_encoded",
    parquet_name: str = "data.parquet",
) -> WeatherArrays:
    """Load the ETL output (a parquet file or directory) into host arrays.

    Accepts both a Spark-style parquet directory and a single parquet file,
    like ``pd.read_parquet`` does in the reference
    (jobs/train_lightning_ddp.py:31).
    """
    parquet_path = os.path.join(processed_dir, parquet_name)
    if not os.path.exists(parquet_path):
        raise FileNotFoundError(
            f"CRITICAL ERROR: Data not found at {parquet_path}. "
            "Did the preprocessing step finish successfully?"
        )

    import pyarrow.parquet as pq

    try:
        table = pq.read_table(parquet_path)
    except Exception as e:  # pragma: no cover - IO failure surface
        raise RuntimeError(f"Failed to read Parquet file: {e}") from e

    names = list(table.column_names)
    feature_cols = [c for c in names if c.endswith(feature_suffix)]
    if not feature_cols:
        raise ValueError(
            f"CRITICAL ERROR: No columns ending with '{feature_suffix}' found. "
            "Check the preprocessing logic."
        )
    if label_column not in names:
        raise ValueError(
            f"CRITICAL ERROR: Label column '{label_column}' not found in "
            f"columns {names}."
        )

    feats = np.stack(
        [table.column(c).to_numpy(zero_copy_only=False) for c in feature_cols],
        axis=1,
    ).astype(np.float32)
    labels = table.column(label_column).to_numpy(zero_copy_only=False).astype(np.int32)
    return WeatherArrays(features=feats, labels=labels, feature_names=feature_cols)
