// Native host data plane: threaded batch-assembly kernels.
//
// The TPU compute path is XLA/Pallas; this library owns the host side of
// the hot loop — gathering shuffled sample rows / sliding windows from the
// in-RAM dataset into the contiguous [steps, batch, ...] epoch buffers that
// are DMA'd to the chip. The reference delegates its equivalent host loop
// to libtorch's DataLoader collation (C++ under torch, SURVEY §2.2); here
// it is first-party, dependency-free C++ exposed over a C ABI for ctypes.
//
// Contract notes:
// - all arrays are C-contiguous; callers validate indices (the Python
//   wrapper bounds-checks before dispatch);
// - gather_windows copies seq contiguous rows per window start, which is
//   one memcpy per window instead of numpy's per-element strided iteration
//   over a sliding_window_view;
// - work splits across std::thread workers above a size threshold; below
//   it, threading overhead dominates and a single pass wins.

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

// Rows-per-thread threshold below which threads cost more than they save.
constexpr int64_t kMinElemsPerThread = 1 << 16;

int pick_threads(int64_t total_elems, int32_t requested) {
  int hw = static_cast<int>(std::thread::hardware_concurrency());
  if (hw <= 0) hw = 1;
  int cap = requested > 0 ? std::min(requested, hw) : hw;
  int64_t by_work =
      std::max<int64_t>(1, total_elems / kMinElemsPerThread);
  return static_cast<int>(std::min<int64_t>(cap, by_work));
}

template <typename Fn>
void parallel_for(int64_t m, int nthreads, Fn&& body) {
  if (nthreads <= 1) {
    body(0, m);
    return;
  }
  std::vector<std::thread> workers;
  workers.reserve(nthreads);
  int64_t chunk = (m + nthreads - 1) / nthreads;
  for (int t = 0; t < nthreads; ++t) {
    int64_t lo = t * chunk;
    int64_t hi = std::min(m, lo + chunk);
    if (lo >= hi) break;
    workers.emplace_back([&body, lo, hi] { body(lo, hi); });
  }
  for (auto& w : workers) w.join();
}

}  // namespace

extern "C" {

// dst[i, :] = src[idx[i], :] for i in [0, m); rows are row_elems floats.
void dct_gather_rows(const float* src, int64_t row_elems, const int64_t* idx,
                     int64_t m, float* dst, int32_t nthreads) {
  const size_t row_bytes = static_cast<size_t>(row_elems) * sizeof(float);
  int nt = pick_threads(m * row_elems, nthreads);
  parallel_for(m, nt, [&](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) {
      std::memcpy(dst + i * row_elems, src + idx[i] * row_elems, row_bytes);
    }
  });
}

// dst[i, :, :] = base[starts[i] : starts[i]+seq, :] — one contiguous copy
// of seq*row_elems floats per window.
void dct_gather_windows(const float* base, int64_t row_elems,
                        const int64_t* starts, int64_t m, int64_t seq,
                        float* dst, int32_t nthreads) {
  const int64_t win_elems = seq * row_elems;
  const size_t win_bytes = static_cast<size_t>(win_elems) * sizeof(float);
  int nt = pick_threads(m * win_elems, nthreads);
  parallel_for(m, nt, [&](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) {
      std::memcpy(dst + i * win_elems, base + starts[i] * row_elems,
                  win_bytes);
    }
  });
}

// dst[i] = src[idx[i]] for int32 labels.
void dct_gather_i32(const int32_t* src, const int64_t* idx, int64_t m,
                    int32_t* dst) {
  for (int64_t i = 0; i < m; ++i) dst[i] = src[idx[i]];
}

// ABI version guard for the ctypes loader.
int32_t dct_native_abi_version() { return 1; }

}  // extern "C"
