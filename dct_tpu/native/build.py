"""Build driver for the native data-plane library.

Compiles ``src/data_plane.cpp`` with the system ``g++`` into a content-
addressed shared object under ``<pkg>/build/`` (gitignored). No setuptools,
no pybind11 — the ABI is plain C consumed via ctypes, so a single compiler
invocation is the whole build system. Build failures are non-fatal: the
Python fallbacks in :mod:`dct_tpu.native` keep everything working.
"""

from __future__ import annotations

import hashlib
import os
import subprocess
import tempfile

_PKG_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_PKG_DIR, "src", "data_plane.cpp")
_BUILD_DIR = os.path.join(_PKG_DIR, "build")

CXX = os.environ.get("DCT_CXX", "g++")
CXXFLAGS = ["-O3", "-std=c++17", "-shared", "-fPIC", "-pthread"]


def _source_tag() -> str:
    with open(_SRC, "rb") as f:
        return hashlib.sha1(f.read()).hexdigest()[:16]


def so_path() -> str:
    return os.path.join(_BUILD_DIR, f"dct_native_{_source_tag()}.so")


def build(force: bool = False) -> str | None:
    """Compile if needed; returns the .so path or None on failure."""
    out = so_path()
    if os.path.exists(out) and not force:
        return out
    os.makedirs(_BUILD_DIR, exist_ok=True)
    # Atomic publish: compile to a temp name, rename into place, so a
    # concurrent builder (two SPMD processes on one host) never loads a
    # half-written object.
    fd, tmp = tempfile.mkstemp(suffix=".so", dir=_BUILD_DIR)
    os.close(fd)
    try:
        subprocess.run(
            [CXX, *CXXFLAGS, _SRC, "-o", tmp],
            check=True,
            capture_output=True,
            text=True,
            timeout=120,
        )
        os.replace(tmp, out)
        return out
    except (subprocess.SubprocessError, OSError):
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return None


if __name__ == "__main__":
    path = build(force=True)
    print(path if path else "BUILD FAILED")
