"""Native host data plane with transparent Python fallback.

Wraps the C++ batch-assembly kernels (``src/data_plane.cpp``) behind numpy-
in/numpy-out functions. Loading policy:

- first use triggers a (cached, content-addressed) ``g++`` build;
- ``DCT_NATIVE=0`` disables the native path;
- any build/load failure silently selects the numpy fallbacks — the native
  library is a throughput optimization of the host side of the input
  pipeline, never a correctness dependency.

The reference's analog of this layer is libtorch's C++ DataLoader collation
(SURVEY §2.2); here it is first-party and TPU-shaped: it assembles the
contiguous [steps, batch, ...] epoch buffers that ``make_global_epoch``
transfers to device in one DMA.
"""

from __future__ import annotations

import ctypes
import os

import numpy as np

_lib: ctypes.CDLL | None = None
_tried = False


def _load() -> ctypes.CDLL | None:
    global _lib, _tried
    if _tried:
        return _lib
    _tried = True
    if os.environ.get("DCT_NATIVE", "1").strip().lower() in ("0", "false", "no"):
        return None
    try:
        from dct_tpu.native.build import build

        path = build()
        if path is None:
            return None
        lib = ctypes.CDLL(path)
        if lib.dct_native_abi_version() != 1:
            return None
        i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
        f32p = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
        i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
        lib.dct_gather_rows.argtypes = [
            f32p, ctypes.c_int64, i64p, ctypes.c_int64, f32p, ctypes.c_int32,
        ]
        lib.dct_gather_windows.argtypes = [
            f32p, ctypes.c_int64, i64p, ctypes.c_int64, ctypes.c_int64, f32p,
            ctypes.c_int32,
        ]
        lib.dct_gather_i32.argtypes = [i32p, i64p, ctypes.c_int64, i32p]
        _lib = lib
    except Exception:
        _lib = None
    return _lib


def available() -> bool:
    return _load() is not None


def gather_rows(src: np.ndarray, idx: np.ndarray, *, nthreads: int = 0) -> np.ndarray:
    """dst[i] = src[idx[i]]; src [N, F] float32, idx any int shape ->
    [*idx.shape, F]."""
    idx = np.ascontiguousarray(idx, np.int64)
    lib = _load()
    if lib is None or not (
        src.flags.c_contiguous and src.dtype == np.float32 and src.ndim == 2
    ):
        return src[idx]
    if idx.size and (idx.min() < 0 or idx.max() >= src.shape[0]):
        raise IndexError("gather_rows: index out of bounds")
    flat = idx.reshape(-1)
    out = np.empty((flat.size, src.shape[1]), np.float32)
    lib.dct_gather_rows(src, src.shape[1], flat, flat.size, out, nthreads)
    return out.reshape(*idx.shape, src.shape[1])


def gather_windows(
    base: np.ndarray, starts: np.ndarray, seq: int, *, nthreads: int = 0
) -> np.ndarray:
    """dst[i] = base[starts[i]:starts[i]+seq]; base [N, F] float32 ->
    [*starts.shape, seq, F]."""
    starts = np.ascontiguousarray(starts, np.int64)
    lib = _load()
    if lib is None or not (
        base.flags.c_contiguous and base.dtype == np.float32 and base.ndim == 2
    ):
        flat = starts.reshape(-1)
        out = np.stack([base[s : s + seq] for s in flat]) if flat.size else (
            np.empty((0, seq, base.shape[1]), base.dtype)
        )
        return out.reshape(*starts.shape, seq, base.shape[1])
    if starts.size and (
        starts.min() < 0 or starts.max() + seq > base.shape[0]
    ):
        raise IndexError("gather_windows: window out of bounds")
    flat = starts.reshape(-1)
    out = np.empty((flat.size, seq, base.shape[1]), np.float32)
    lib.dct_gather_windows(
        base, base.shape[1], flat, flat.size, seq, out, nthreads
    )
    return out.reshape(*starts.shape, seq, base.shape[1])


def gather_i32(src: np.ndarray, idx: np.ndarray) -> np.ndarray:
    """dst[i] = src[idx[i]] for int32 labels."""
    idx = np.ascontiguousarray(idx, np.int64)
    lib = _load()
    if lib is None or not (
        src.flags.c_contiguous and src.dtype == np.int32 and src.ndim == 1
    ):
        # ndim > 1 (per-position label matrices) must NOT hit the native
        # scalar-gather path — it indexes src as a flat array.
        return src[idx]
    if idx.size and (idx.min() < 0 or idx.max() >= src.shape[0]):
        raise IndexError("gather_i32: index out of bounds")
    flat = idx.reshape(-1)
    out = np.empty(flat.size, np.int32)
    lib.dct_gather_i32(src, flat, flat.size, out)
    return out.reshape(idx.shape)
