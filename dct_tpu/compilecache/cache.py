"""Persistent-XLA-cache wiring and the ``DCT_COMPILE_CACHE_*`` contract.

Mode resolution (``DCT_COMPILE_CACHE``):

- ``off`` (and the usual falsy spellings) — everything disabled;
- ``auto`` (default) — enabled **iff** ``DCT_COMPILE_CACHE_DIR`` names a
  directory: the operator arming a cache dir is the opt-in;
- ``on`` / ``force`` — enabled; the cache dir defaults to
  :data:`DEFAULT_CACHE_DIR` when unset.

The persistent XLA cache must be configured **before this process's
first compile**: JAX memoizes whether the cache is in use at the first
compilation, so a late ``enable_from_env`` silently does nothing for
the rest of the process (the AOT store in :mod:`.aot` has no such
constraint — it is pure file I/O around ``lower().compile()``). Every
long-running entry point (trainer fit, the serving CLI) therefore
calls this before touching jax-compiled code.

Relationship to the older ``DCT_JAX_CACHE`` knob
(:func:`dct_tpu.utils.platform.enable_compilation_cache`): that one is
the bench/campaign measurement hedge, TPU-gated by default. This module
is the platform-wide relaunch/spin-up contract; when both run, the last
``jax.config.update`` wins (they can share a directory safely — entries
are content-keyed).

Cache directories are **per-machine**: XLA:CPU executables are pinned
to the host's CPU features, so a dir shared over NFS across
heterogeneous hosts can produce entries another host cannot run. The
AOT artifact header fingerprints backend/device/arch and degrades to a
loud miss; the XLA cache keys include the compile options but not the
micro-architecture — keep the dir host-local.
"""

from __future__ import annotations

import os
import sys
from collections.abc import Mapping

#: Default persistent-cache dir for mode ``on`` (under the gitignored
#: ``logs/`` convention, shared by every relaunch attempt in a cwd).
DEFAULT_CACHE_DIR = "logs/compile_cache"

_FALSY = ("0", "false", "no", "off", "disable", "none")


def cache_mode(env: Mapping | None = None) -> str:
    """``off`` | ``auto`` | ``on`` (normalized)."""
    env = os.environ if env is None else env
    raw = str(env.get("DCT_COMPILE_CACHE", "auto")).strip().lower()
    if raw in _FALSY:
        return "off"
    if raw in ("on", "force", "1", "true", "yes"):
        return "on"
    return "auto"


def resolve_cache_dir(env: Mapping | None = None) -> str | None:
    """The persistent-XLA-cache dir the env selects (None = cache off)."""
    env = os.environ if env is None else env
    mode = cache_mode(env)
    if mode == "off":
        return None
    explicit = env.get("DCT_COMPILE_CACHE_DIR")
    if explicit:
        return str(explicit)
    return DEFAULT_CACHE_DIR if mode == "on" else None


def enabled(env: Mapping | None = None) -> bool:
    """True when the compile cache (XLA dir + AOT store) is armed."""
    return resolve_cache_dir(env) is not None


def aot_enabled(env: Mapping | None = None) -> bool:
    """AOT executable serialization on top of the enabled cache
    (``DCT_COMPILE_CACHE_AOT``, default on)."""
    env = os.environ if env is None else env
    if not enabled(env):
        return False
    raw = str(env.get("DCT_COMPILE_CACHE_AOT", "1")).strip().lower()
    return raw not in _FALSY


def warm_sizes(env: Mapping | None = None) -> list[int]:
    """Packaging-time scorer pre-compile batch sizes
    (``DCT_COMPILE_CACHE_WARM_SIZES``, comma-separated; empty = skip)."""
    env = os.environ if env is None else env
    raw = str(env.get("DCT_COMPILE_CACHE_WARM_SIZES", ""))
    sizes = []
    for tok in raw.split(","):
        tok = tok.strip()
        if tok.isdigit() and int(tok) > 0:
            sizes.append(int(tok))
    return sorted(set(sizes))


def enable_from_env(cache_dir: str | None = None) -> str | None:
    """Point JAX's persistent compilation cache at the configured dir.

    Returns the dir in use, or None when disabled/unavailable. Never
    raises — the cache is an optimization, not a reason to fail a run.
    ``DCT_COMPILE_CACHE_MIN_COMPILE_S`` (default 0: cache everything)
    maps to ``jax_persistent_cache_min_compile_time_secs``.
    """
    path = cache_dir or resolve_cache_dir()
    if path is None:
        return None
    try:
        import jax

        min_s = float(
            os.environ.get("DCT_COMPILE_CACHE_MIN_COMPILE_S", "0") or 0.0
        )
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", min_s
        )
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception as e:  # noqa: BLE001 — never fail the run for a cache
        sys.stderr.write(
            f"[dct_tpu] persistent compile cache unavailable: {e}\n"
        )
        return None
    return path


def export_env(child_env: dict, current_env: Mapping | None = None) -> None:
    """Pin the resolved cache dir into a child environment (the
    supervised relauncher calls this): every relaunch attempt must
    agree on ONE directory, or attempt 2 cannot hit what attempt 1
    compiled. No-op when the cache is off. ``current_env`` is the
    merged view the children will actually see (defaults to this
    process's environ overlaid with ``child_env``)."""
    merged = dict(os.environ if current_env is None else current_env)
    merged.update({k: v for k, v in child_env.items() if v is not None})
    path = resolve_cache_dir(merged)
    if path is not None:
        child_env.setdefault(
            "DCT_COMPILE_CACHE_DIR", os.path.abspath(path)
        )
