"""Persistent compile cache + AOT executables (ROADMAP item 5).

Restart debt is a steady-state cost of continuous training: every
supervised relaunch (docs/ROBUSTNESS.md) and every serving worker
re-traces and re-compiles XLA programs whose identity — (program,
family, config_hash, mesh) — the compile accounting layer already
fingerprints (docs/OBSERVABILITY.md §compile). This package erases that
debt twice over:

1. :func:`enable_from_env` points JAX's **persistent compilation
   cache** (``jax_compilation_cache_dir``) at ``DCT_COMPILE_CACHE_DIR``
   so any re-trace of an identical program is a disk hit instead of an
   XLA compile — wired into trainer startup, the supervised
   relauncher, and the serving entry point.
2. :class:`ExecutableStore` **AOT-serializes the hot executables**
   (the fused epoch/train-step programs, the jitted batched scorer)
   via ``jax.jit(...).lower().compile()`` + executable serialization,
   keyed by the exact compile-accounting identity, stored
   tmp+``os.replace`` inside the checkpoint/package layout — a resume
   snapshot carries its pre-compiled steps, a deployed package its
   pre-compiled scorer.

Every artifact carries version/jaxlib/backend fingerprints in its
header: a mismatched artifact is a **loud miss** (event + fallback to
a normal jit compile), never a wrong execution. Cache-hit runs are
bit-identical to cache-miss runs — the serialized executable IS the
executable the miss path would have built on this machine.
"""

from dct_tpu.compilecache.cache import (
    DEFAULT_CACHE_DIR,
    aot_enabled,
    cache_mode,
    enable_from_env,
    enabled,
    export_env,
    resolve_cache_dir,
    warm_sizes,
)
from dct_tpu.compilecache.aot import (
    CachedProgram,
    ExecutableStore,
    runtime_fingerprint,
    signature_of,
    store_from_env,
    warm_package_scorer,
)

__all__ = [
    "DEFAULT_CACHE_DIR",
    "CachedProgram",
    "ExecutableStore",
    "aot_enabled",
    "cache_mode",
    "enable_from_env",
    "enabled",
    "export_env",
    "resolve_cache_dir",
    "runtime_fingerprint",
    "signature_of",
    "store_from_env",
    "warm_package_scorer",
    "warm_sizes",
]
