"""AOT executable store: serialized XLA executables, fingerprinted.

The hot programs a relaunch or endpoint spin-up re-pays — the fused
epoch/train-step programs, the jitted batched scorer — are compiled
once via ``jax.jit(...).lower(*args).compile()`` and the **compiled
executable itself** is serialized to disk (the ``jax.export``-style
path: ``jax.experimental.serialize_executable``). A warm process
deserializes instead of compiling: same machine code, bit-identical
results, milliseconds instead of seconds.

Artifact format (one file per (program, signature), published
tmp+``os.replace`` so a reader can never see a torn artifact)::

    DCTAOT1\\n
    {header JSON: fingerprints + identity + payload sha256}\\n
    <raw serialized-executable payload>

The header is the **load-or-miss contract**: every fingerprint
(jax/jaxlib version, backend, device kind/count, process count, CPU
arch) and every identity field (program, family, config_hash, mesh,
extra) must match the loading process exactly, and the payload must
hash to the header's sha256 — anything else is a LOUD miss
(``compile.cache_miss`` event naming the reason) that falls back to a
normal jit compile. A stale, foreign, or corrupted artifact can cost a
compile; it can never produce a wrong execution.

Pytree treedefs are deliberately NOT serialized: a ``TrainState``
treedef carries live closures (the optax transformation, the bound
``apply_fn``) that neither pickle nor belong on disk. Both trees are
rebuilt at load time from the live function and the first call's
arguments — ``tree_flatten((args, {{}}))`` for the input tree,
``jax.eval_shape`` (a trace, no compile) for the output tree — so the
loaded executable is called with metadata that matches the calling
process by construction.
"""

from __future__ import annotations

import hashlib
import json
import os
import sys
import threading

_MAGIC = b"DCTAOT1\n"

#: Artifact-header format version; bump on any layout change (a
#: version mismatch is a loud miss like every other fingerprint).
ARTIFACT_VERSION = 1


def runtime_fingerprint() -> dict:
    """The facts that make a serialized executable loadable HERE and
    nowhere else. Exact-match on load; any drift is a loud miss."""
    import platform as _platform

    import jax
    import jaxlib

    return {
        "version": ARTIFACT_VERSION,
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
        "backend": jax.default_backend(),
        "device_kind": jax.devices()[0].device_kind,
        "device_count": jax.device_count(),
        "process_count": jax.process_count(),
        "machine": _platform.machine(),
    }


def signature_of(args) -> str:
    """Stable digest of the call's abstract signature (leaf shapes,
    dtypes, weak_type flags). Deliberately leaf-only: treedef reprs can
    embed object addresses, which would make the signature unstable
    across processes — the semantic identity (program name, family,
    config_hash, mesh, extra) lives in the store's key instead."""
    import jax

    leaves = jax.tree_util.tree_leaves(args)
    parts = [
        f"{tuple(getattr(a, 'shape', ()))}:"
        f"{getattr(a, 'dtype', type(a).__name__)}:"
        f"{int(bool(getattr(a, 'weak_type', False)))}"
        for a in leaves
    ]
    blob = f"n{len(leaves)}|" + "|".join(parts)
    return hashlib.sha1(blob.encode()).hexdigest()[:12]


def _safe_name(s: str) -> str:
    return "".join(
        c if c.isalnum() or c in "._-" else "_" for c in str(s)
    ) or "program"


def weights_digest(weights: dict) -> str:
    """Content digest of a serving weights dict (sorted keys, shapes,
    dtypes, raw bytes). The jitted scorer CLOSES OVER the weights, so
    they are baked into the serialized executable as constants — an
    identity without this digest would let a meta-identical artifact
    built from different weights load cleanly and serve the stale
    model. One pass at scorer build time (~ms per MB), never on the
    request path."""
    import numpy as np

    h = hashlib.sha256()
    for k in sorted(weights):
        a = np.ascontiguousarray(weights[k])
        h.update(str(k).encode())
        h.update(str(a.shape).encode())
        h.update(str(a.dtype).encode())
        h.update(a.tobytes())
    return h.hexdigest()[:16]


class ExecutableStore:
    """Load-or-miss store of serialized executables under one root.

    ``identity`` carries the compile-accounting key the artifacts are
    minted under — ``family`` / ``config_hash`` / ``mesh`` (the same
    labels ``compile.window`` events use) plus an optional ``extra``
    dict for program-shaping knobs the model config alone does not
    capture (the trainer hashes its optimizer/precision/donation facts
    in; constants like the learning rate are baked into the executable,
    so they MUST be part of the key). ``states`` records, per program
    key, how its executables resolved: ``hit`` (all loaded from disk),
    ``miss`` (at least one fresh compile), or ``disabled``.
    """

    def __init__(
        self,
        root: str | None,
        *,
        identity: dict | None = None,
        enabled: bool = True,
        emit=None,
    ):
        self.root = root
        self.enabled = bool(enabled and root)
        self.identity = dict(identity or {})
        self._emit = emit
        self.states: dict[str, str] = {}
        # Roofline accounting (observability.roofline): per program key,
        # the normalized cost_analysis/memory_analysis record captured
        # at compile (or load) time. Populated regardless of `enabled`
        # — a disabled store is still every CachedProgram's cost book.
        self.costs: dict[str, dict] = {}
        self._lock = threading.Lock()

    # -- bookkeeping ---------------------------------------------------
    def _note(self, program: str, state: str) -> None:
        with self._lock:
            prev = self.states.get(program)
            # A miss outranks a hit: one fresh compile under a program
            # key means the key was not fully served from disk.
            if prev == "miss" and state == "hit":
                return
            self.states[program] = state

    def note_cost(self, program: str, cost: dict | None) -> None:
        """Record a program's analytic cost; first capture per program
        goes on the event log as ``roofline.program`` so efficiency
        accounting has the same audit trail as compile accounting."""
        if not cost:
            return
        with self._lock:
            fresh = program not in self.costs
            self.costs[program] = cost
        if fresh:
            self._event(
                "roofline.program", program, component="roofline",
                **cost,
            )

    def _event(self, event: str, program: str,
               component: str = "compile", **fields) -> None:
        if self._emit is None:
            return
        try:
            self._emit(component, event, program=program, **fields)
        except Exception:  # noqa: BLE001 — telemetry never fails a load
            pass

    def _identity_key(self) -> str:
        blob = json.dumps(self.identity, sort_keys=True, default=str)
        return hashlib.sha1(blob.encode()).hexdigest()[:10]

    def _path(self, program: str, signature: str) -> str:
        name = (
            f"{_safe_name(program)}-{self._identity_key()}-"
            f"{signature}.aotx"
        )
        return os.path.join(self.root, name)

    # -- save ----------------------------------------------------------
    def save(self, program: str, signature: str, compiled, cost: dict | None = None) -> bool:  # dct: noqa[rank0-io] — per-rank BY DESIGN: in a multi-process world store_from_env stamps proc=<rank> into the identity, so every rank writes DISTINCT artifact names (a rank-0 gate would lose all nonzero ranks' executables); the pid-suffixed tmp + os.replace publish also makes concurrent single-host writers (serving workers) tear-proof
        """Serialize ``compiled`` under (program, signature); atomic
        publish. ``cost`` (the roofline analysis captured at compile
        time) rides the header as ``roofline`` — NOT part of the
        load-or-miss contract, just provenance a warm process reads
        back instead of re-deriving. Returns False (with a stderr note)
        when the backend does not support executable serialization or
        the write fails — never raises."""
        if not self.enabled:
            return False
        try:
            from jax.experimental import serialize_executable as _se

            payload, _in_tree, _out_tree = _se.serialize(compiled)
            header = {
                **runtime_fingerprint(),
                **{k: str(v) for k, v in self.identity.items()},
                "program": program,
                "signature": signature,
                "payload_sha256": hashlib.sha256(payload).hexdigest(),
            }
            if cost:
                header["roofline"] = cost
            os.makedirs(self.root, exist_ok=True)
            final = self._path(program, signature)
            tmp = f"{final}.tmp.{os.getpid()}"
            with open(tmp, "wb") as f:
                f.write(_MAGIC)
                f.write(json.dumps(header, sort_keys=True).encode())
                f.write(b"\n")
                f.write(payload)
            os.replace(tmp, final)
            return True
        except Exception as e:  # noqa: BLE001 — a failed save costs the
            # next process a compile, never this one its run
            sys.stderr.write(
                f"[dct_tpu] AOT save failed for {program}: "
                f"{type(e).__name__}: {e}\n"
            )
            return False

    # -- load ----------------------------------------------------------
    def _read(self, path: str) -> tuple[dict | None, bytes, str]:
        """(header, payload, miss_reason) — header None on any defect."""
        try:
            with open(path, "rb") as f:
                raw = f.read()
        except FileNotFoundError:
            return None, b"", "absent"
        except OSError as e:
            return None, b"", f"unreadable: {e}"
        if not raw.startswith(_MAGIC):
            return None, b"", "bad magic (corrupt or foreign file)"
        body = raw[len(_MAGIC):]
        nl = body.find(b"\n")
        if nl < 0:
            return None, b"", "truncated header"
        try:
            header = json.loads(body[:nl].decode())
        except (ValueError, UnicodeDecodeError):
            return None, b"", "unparsable header"
        payload = body[nl + 1:]
        if (
            hashlib.sha256(payload).hexdigest()
            != header.get("payload_sha256")
        ):
            return None, b"", "payload sha256 mismatch (corrupt)"
        return header, payload, ""

    def load(self, program: str, signature: str, fn, args):
        """Deserialize the artifact for (program, signature) into a
        callable ``Compiled``, or None on any mismatch — emitting the
        miss reason so a skewed artifact is on the record. ``fn`` and
        ``args`` rebuild the pytree metadata (module docstring)."""
        if not self.enabled:
            return None
        path = self._path(program, signature)
        header, payload, reason = self._read(path)
        if header is None:
            if reason != "absent":
                self._event(
                    "compile.cache_miss", program,
                    reason=reason, artifact=os.path.basename(path),
                )
            return None
        want = {
            **runtime_fingerprint(),
            **{k: str(v) for k, v in self.identity.items()},
            "program": program,
            "signature": signature,
        }
        skew = {
            k: (header.get(k), v)
            for k, v in want.items()
            if header.get(k) != v
        }
        if skew:
            self._event(
                "compile.cache_miss", program,
                reason="fingerprint skew",
                skew={k: f"{a!r}!={b!r}" for k, (a, b) in skew.items()},
                artifact=os.path.basename(path),
            )
            return None
        try:
            import jax
            from jax.experimental import serialize_executable as _se

            in_tree = jax.tree_util.tree_flatten((tuple(args), {}))[1]
            out_tree = jax.tree_util.tree_structure(
                jax.eval_shape(fn, *args)
            )
            loaded = _se.deserialize_and_load(payload, in_tree, out_tree)
            # Roofline provenance stamped at compile time reads back on
            # the warm path — a hit run reports the same analytic
            # FLOPs/HBM as the run that compiled the artifact. (If the
            # call later demotes this executable, the miss path's fresh
            # analysis overwrites it.) Same DCT_ROOFLINE gate as the
            # capture paths: disabled means NO roofline telemetry,
            # warm or cold.
            saved_cost = header.get("roofline")
            if isinstance(saved_cost, dict):
                from dct_tpu.observability import roofline as _roofline

                if _roofline.roofline_enabled():
                    self.note_cost(
                        program, {**saved_cost, "source": "header"}
                    )
            return loaded
        except Exception as e:  # noqa: BLE001 — any load defect is a miss
            self._event(
                "compile.cache_miss", program,
                reason=f"deserialize failed: {type(e).__name__}: {e}"[:300],
                artifact=os.path.basename(path),
            )
            return None

    # -- the wrapper ----------------------------------------------------
    def wrap(self, fn, program: str | None = None) -> "CachedProgram":
        """Wrap a jitted function in load-or-miss dispatch (see
        :class:`CachedProgram`). Always safe to call — with the store
        disabled the wrapper delegates straight to ``fn``."""
        return CachedProgram(fn, self, program=program)


def _stamp_dtypes(cost: dict | None, args) -> dict | None:
    """Join the dispatch's parameter/activation dtype summary into a
    roofline cost record (``dtypes`` field): precision is program
    identity on the efficiency plane — a bf16-rules step and its f32
    twin must be tellable apart from one scrape."""
    if not cost:
        return cost
    from dct_tpu.observability import roofline as _roofline

    summary = _roofline.dtype_summary(args)
    return {**cost, "dtypes": summary} if summary else cost


class CachedProgram:
    """A jitted function fronted by the executable store.

    First call per (program key, signature): try the store — a **hit**
    deserializes the executable and runs it; a **miss** compiles via
    ``fn.lower(*args).compile()``, publishes the artifact, and runs the
    fresh executable. Later calls dispatch the in-memory executable
    directly. With the store disabled, calls delegate to the jitted
    function untouched (state ``disabled``).

    ``key=`` overrides the program key per call — the trainer passes
    its goodput dispatch key (``scan_k<k>``) so the store's hit/miss
    states line up 1:1 with the ``compile.window`` accounting.

    A loaded executable whose first call is rejected at validation
    (pytree/aval mismatch, before any buffer is consumed) demotes to
    the miss path — stale artifacts degrade to a compile, never a
    crash or a wrong result. Failures DURING execution propagate: a
    donating program's inputs may already be gone, and an error the
    fresh compile would hit too must not be masked.
    """

    def __init__(self, fn, store: ExecutableStore, program: str | None = None):
        self._fn = fn
        self._store = store
        self._program = program or getattr(fn, "__name__", "program")
        self._entries: dict = {}
        self._analyzed: set = set()
        self._lock = threading.Lock()

    def _analyze_disabled(self, program: str, args) -> None:
        """Roofline capture on the store-DISABLED path (the default):
        the plain jit call below never exposes its executable, so the
        cost model is read off a pre-compile ``lower()`` — one extra
        trace per program, no extra compile. Once per program key."""
        with self._lock:
            if program in self._analyzed:
                return
            self._analyzed.add(program)
        from dct_tpu.observability import roofline as _roofline

        if not _roofline.roofline_enabled():
            return
        try:
            lowered = self._fn.lower(*args)
        except Exception:  # noqa: BLE001 — non-jit callables have no HLO
            return
        self._store.note_cost(
            program, _stamp_dtypes(_roofline.analyze_lowered(lowered), args)
        )

    def __call__(self, *args, key: str | None = None):
        program = key or self._program
        if not self._store.enabled:
            self._store._note(program, "disabled")
            self._analyze_disabled(program, args)
            return self._fn(*args)
        sig = signature_of(args)
        with self._lock:
            entry = self._entries.get((program, sig))
        if entry is not None:
            return entry(*args)
        return self._first_call(program, sig, args)

    def _first_call(self, program: str, sig: str, args):
        store = self._store
        loaded = store.load(program, sig, self._fn, args)
        if loaded is not None:
            try:
                out = loaded(*args)
            except (TypeError, ValueError) as e:
                # Pre-execution validation rejections (pytree/aval
                # mismatch — raised BEFORE any buffer is consumed, so
                # re-running args is safe even for donating programs):
                # degrade loudly to a fresh compile. Runtime failures
                # propagate instead — a donating executable may already
                # have consumed its inputs, and an error the fresh
                # compile would hit too must not be masked as a miss.
                store._event(
                    "compile.cache_miss", program,
                    reason=(
                        f"loaded executable rejected the call: "
                        f"{type(e).__name__}: {e}"
                    )[:300],
                )
            else:
                store._note(program, "hit")
                store._event(
                    "compile.cache_hit", program, signature=sig,
                )
                if program not in store.costs:
                    # Pre-roofline artifact (no stamped provenance):
                    # analyze the deserialized executable directly.
                    from dct_tpu.observability import (
                        roofline as _roofline,
                    )

                    if _roofline.roofline_enabled():
                        store.note_cost(
                            program,
                            _stamp_dtypes(
                                _roofline.analyze_compiled(loaded), args
                            ),
                        )
                with self._lock:
                    self._entries[(program, sig)] = loaded
                return out
        store._note(program, "miss")
        try:
            compiled = self._fn.lower(*args).compile()
        except Exception:
            # A function that cannot lower/compile ahead-of-time (e.g.
            # a non-jit callable slipped in) still runs: the plain call
            # is the universal fallback.
            with self._lock:
                self._entries[(program, sig)] = self._fn
            return self._fn(*args)
        from dct_tpu.observability import roofline as _roofline

        cost = (
            _stamp_dtypes(_roofline.analyze_compiled(compiled), args)
            if _roofline.roofline_enabled() else None
        )
        store.note_cost(program, cost)
        store.save(program, sig, compiled, cost=cost)
        with self._lock:
            self._entries[(program, sig)] = compiled
        return compiled(*args)


def store_from_env(
    root: str | None,
    *,
    family: str = "",
    config_hash: str = "",
    mesh: str = "",
    extra: dict | None = None,
    emit=None,
) -> ExecutableStore:
    """An :class:`ExecutableStore` under the env contract: enabled when
    the compile cache is armed (``cache.enabled``), AOT is on, and a
    root is given.

    Multi-process worlds are supported with PER-RANK artifacts: a
    multi-process executable references cross-host topology from its
    own rank's perspective, so ``proc=<rank>`` joins the identity —
    rank 0's artifact can never be loaded by rank 1, and a relaunched
    world's rank N deserializes exactly the executable its dead
    predecessor rank N compiled (the sharded supervised-relaunch path).
    The runtime fingerprint already pins ``process_count``, so a world
    resized between runs is a loud miss, never a wrong execution."""
    from dct_tpu.compilecache.cache import aot_enabled

    on = bool(root) and aot_enabled()
    identity = {"family": family, "config_hash": config_hash, "mesh": mesh}
    if on:
        try:
            import jax

            if jax.process_count() > 1:
                identity["proc"] = jax.process_index()
        except Exception:  # noqa: BLE001 — no backend = nothing to cache
            on = False
    if extra:
        identity["extra"] = json.dumps(extra, sort_keys=True, default=str)
    return ExecutableStore(root, identity=identity, enabled=on, emit=emit)


def warm_package_scorer(
    package_dir: str, sizes: list[int] | None = None
) -> list[int]:
    """Pre-compile the jitted batched scorer into ``<package>/aot/`` at
    the given batch sizes (default: ``DCT_COMPILE_CACHE_WARM_SIZES``),
    so a deployed package carries its executables and an endpoint
    worker spins up pre-compiled. Returns the padded sizes actually
    compiled (deduped to the scorer's power-of-two padding). Best-
    effort: any failure leaves the package valid and un-warmed."""
    from dct_tpu.compilecache.cache import warm_sizes as _warm_sizes

    sizes = _warm_sizes() if sizes is None else sorted(set(sizes))
    if not sizes:
        return []
    try:
        import numpy as np

        from dct_tpu.serving.batching import _build_jax_scorer
        from dct_tpu.serving.runtime import assemble_weights

        npz = np.load(os.path.join(package_dir, "model.npz"))
        weights = assemble_weights({k: npz[k] for k in npz.files})
        with open(os.path.join(package_dir, "model_meta.json")) as f:
            meta = json.load(f)
        meta["_aot_dir"] = os.path.join(package_dir, "aot")
        score = _build_jax_scorer(weights, meta, force_store=True)
        padded_done: list[int] = []
        for n in sizes:
            padded = 1
            while padded < n:
                padded *= 2
            if padded in padded_done:
                continue
            x = _example_batch(meta, padded)
            score(x)
            padded_done.append(padded)
        return padded_done
    except Exception as e:  # noqa: BLE001 — warming is an optimization
        sys.stderr.write(
            f"[dct_tpu] package scorer warm-up skipped: "
            f"{type(e).__name__}: {e}\n"
        )
        return []


def _example_batch(meta: dict, n: int):
    """A shape-correct all-zeros batch for the package's family (row
    families [N, D]; sequence families [N, S, D])."""
    import numpy as np

    from dct_tpu.serving.runtime import _SEQUENCE_FAMILIES

    d = int(meta["input_dim"])
    if meta.get("model", "weather_mlp") in _SEQUENCE_FAMILIES:
        return np.zeros((n, int(meta["seq_len"]), d), np.float32)
    return np.zeros((n, d), np.float32)
