"""Cold/warm spin-up measurement: SIGKILL→first-step and first-score.

The restart/spin-up debt the compile cache erases, measured through the
REAL paths:

- :func:`measure_relaunch` runs ``python -m dct_tpu.resilience.supervise``
  over ``jobs/train_tpu.py`` with a ``crash@rank0:step1`` fault plan —
  attempt 1 compiles, is hard-killed at its first span boundary (before
  any resume snapshot), and the supervisor relaunches. The event log
  then yields **time-from-SIGKILL-to-first-step** (``fault.injected``
  ts → the healed attempt's first ``epoch_end`` ts), the healed
  attempt's ``compile.window`` seconds + cache labels, and its
  ``startup_recovery`` badput.
- :func:`measure_first_score` times an endpoint worker's
  **time-to-first-score** (scorer build → first probabilities) over a
  deployed package's jitted jax scorer, in a fresh subprocess per
  measurement so in-process jit caches cannot flatter the warm number.

Used by three consumers with one implementation: the bench's
``restart_spinup`` leg, the ``compile-cache`` CI smoke
(scripts/compile_cache_smoke.py), and the e2e tests.

Run this module as a CLI for the subprocess halves::

    python -m dct_tpu.compilecache.spinup first-score <package_dir>
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

#: Env keys a measurement must control; everything else passes through.
_CLEARED = (
    "DCT_RESUME", "DCT_STARTUP_RECOVERY_DEBT_S", "DCT_RUN_ID",
    "DCT_SPAN_ID", "DCT_FAULT_SPEC", "DCT_METRICS_DIR",
)


def prepare_processed(workdir: str, *, rows: int = 600, seed: int = 0) -> str:
    """Synthetic weather CSV -> processed parquet dir (the trainer's
    input contract), under ``workdir``."""
    from dct_tpu.data.synthetic import generate_weather_csv
    from dct_tpu.etl.preprocess import preprocess_csv_to_parquet

    csv = os.path.join(workdir, "raw", "weather.csv")
    processed = os.path.join(workdir, "processed")
    if not os.path.isdir(processed):
        generate_weather_csv(csv, rows=rows, seed=seed)
        preprocess_csv_to_parquet(csv, processed)
    return processed


def _measure_env(
    workdir: str, tag: str, *, cache_on: bool, model_env: dict | None,
) -> dict:
    env = dict(os.environ)
    for k in _CLEARED:
        env.pop(k, None)
    env.update(
        JAX_PLATFORMS=env.get("JAX_PLATFORMS", "cpu"),
        DCT_PROCESSED_DIR=os.path.join(workdir, "processed"),
        DCT_MODELS_DIR=os.path.join(workdir, f"models_{tag}"),
        DCT_EVENTS_DIR=os.path.join(workdir, f"events_{tag}"),
        DCT_HEARTBEAT_DIR=os.path.join(workdir, f"hb_{tag}"),
        DCT_TRACKING_DIR=os.path.join(workdir, f"mlruns_{tag}"),
        DCT_COMPILE_CACHE="on" if cache_on else "off",
        DCT_COMPILE_CACHE_DIR=os.path.join(workdir, "xla_cache"),
        DCT_COMPILE_CACHE_AOT_DIR=os.path.join(workdir, "aot"),
        DCT_EPOCHS="1",
        DCT_BATCH_SIZE="32",
        DCT_USE_SCAN="1",
        DCT_EPOCH_CHUNK="1",
        # Telemetry write-through: the event timestamps ARE the
        # measurement, and the crash path must not owe them a flush.
        DCT_TELEMETRY_FLUSH_S="0",
    )
    env.update(model_env or {})
    return env


def _read_events(events_dir: str) -> list[dict]:
    path = os.path.join(events_dir, "events.jsonl")
    records = []
    try:
        with open(path) as f:
            for line in f:
                try:
                    records.append(json.loads(line))
                except ValueError:
                    continue
    except OSError:
        pass
    return records


def measure_relaunch(
    workdir: str,
    *,
    cache_on: bool,
    prewarm: bool = False,
    model_env: dict | None = None,
    backoff_s: float = 0.2,
    timeout: float = 600.0,
    world_size: int = 1,
) -> dict:
    """One supervised crash-and-relaunch cycle; returns the restart
    metrics dict (see module docstring). ``prewarm`` runs a plain
    1-epoch training first (separate models dir, SAME cache dirs) so
    even the crashing attempt starts warm — the configuration the
    steady-state continuous-training loop lives in. ``world_size > 1``
    supervises a real multi-process world (pass the mesh/device knobs
    via ``model_env``) — the sharded-relaunch proof path: per-rank AOT
    artifacts must warm the healed attempt exactly like DP ones."""
    tag = ("warm" if cache_on else "cold") + ("_pw" if prewarm else "")
    env = _measure_env(workdir, tag, cache_on=cache_on, model_env=model_env)
    train = [sys.executable, os.path.join(REPO_ROOT, "jobs", "train_tpu.py")]
    if prewarm:
        pre_env = dict(env)
        pre_env.update(
            DCT_MODELS_DIR=os.path.join(workdir, f"models_{tag}_prewarm"),
            DCT_EVENTS_DIR=os.path.join(workdir, f"events_{tag}_prewarm"),
            DCT_HEARTBEAT_DIR=os.path.join(workdir, f"hb_{tag}_prewarm"),
            DCT_TRACKING_DIR=os.path.join(workdir, f"mlruns_{tag}_prewarm"),
        )
        subprocess.run(
            train, env=pre_env, cwd=REPO_ROOT, capture_output=True,
            timeout=timeout,
        )
    env["DCT_FAULT_SPEC"] = "crash@rank0:step1"
    t0 = time.monotonic()
    proc = subprocess.run(
        [
            sys.executable, "-m", "dct_tpu.resilience.supervise",
            "--world-size", str(world_size), "--max-restarts", "1",
            "--backoff", str(backoff_s), "--jitter", "0",
            "--", *train,
        ],
        env=env, cwd=REPO_ROOT, capture_output=True, text=True,
        timeout=timeout,
    )
    wall = time.monotonic() - t0

    ev = _read_events(env["DCT_EVENTS_DIR"])
    t_kill = next(
        (r["ts"] for r in ev if r.get("event") == "fault.injected"), None
    )
    first_step = next(
        (
            r["ts"] for r in ev
            if r.get("event") == "epoch_end"
            and t_kill is not None and r["ts"] > t_kill
        ),
        None,
    )
    # The crashed attempt dies before its end-of-fit compile report, so
    # every compile.window on the log belongs to the healed attempt.
    windows = [r for r in ev if r.get("event") == "compile.window"]
    goodput = next(
        (r for r in ev if r.get("event") == "goodput_summary"), None
    )
    return {
        "returncode": proc.returncode,
        "wall_s": round(wall, 3),
        "sigkill_to_first_step_s": (
            round(first_step - t_kill, 3)
            if t_kill is not None and first_step is not None else None
        ),
        "relaunch_compile_s": round(
            sum(float(r.get("seconds") or 0.0) for r in windows), 3
        ),
        "relaunch_cache": sorted(
            {str(r.get("cache", "disabled")) for r in windows}
        ),
        "startup_recovery_s": (
            round(
                float(
                    goodput.get("categories", {}).get(
                        "startup_recovery", 0.0
                    )
                ),
                3,
            )
            if goodput else None
        ),
        "stderr_tail": proc.stderr[-500:] if proc.returncode else "",
    }


#: The endpoint worker's warm-up batch ladder: a single-row probe plus
#: the default max-batch flush — the two programs a fresh worker
#: compiles (or loads) before it is serving-ready under real traffic.
FIRST_SCORE_SIZES = (1, 64)


def measure_first_score(
    package_dir: str, *, cache_on: bool,
    sizes: tuple = FIRST_SCORE_SIZES, timeout: float = 300.0,
) -> float | None:
    """Time-to-first-score of a fresh endpoint worker over the deployed
    package's jax scorer, in a subprocess: scorer build +
    compile-or-load + one scored request per batch size in the worker's
    warm-up ladder. Returns seconds, or None on failure."""
    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS=env.get("JAX_PLATFORMS", "cpu"),
        DCT_COMPILE_CACHE="on" if cache_on else "off",
    )
    # The XLA persistent cache would hide the compile on the "cold"
    # control; the measurement isolates the package's own aot/ dir.
    env.pop("DCT_COMPILE_CACHE_DIR", None)
    proc = subprocess.run(
        [
            sys.executable, "-m", "dct_tpu.compilecache.spinup",
            "first-score", package_dir,
            ",".join(str(s) for s in sizes),
        ],
        env=env, cwd=REPO_ROOT, capture_output=True, text=True,
        timeout=timeout,
    )
    if proc.returncode != 0:
        sys.stderr.write(
            f"[spinup] first-score failed: {proc.stderr[-500:]}\n"
        )
        return None
    try:
        return float(json.loads(proc.stdout.splitlines()[-1])["first_score_s"])
    except (ValueError, KeyError, IndexError):
        return None


def _first_score_main(package_dir: str, sizes: tuple) -> int:
    """Subprocess half of :func:`measure_first_score`: load the
    package, build the jitted scorer (AOT store over ``<pkg>/aot`` —
    honored or bypassed per ``DCT_COMPILE_CACHE``), score one request
    per warm-up batch size, report the wall. ``force_store`` is NOT
    set: the measurement obeys exactly the env contract a real
    endpoint worker would."""
    import numpy as np

    from dct_tpu.compilecache.aot import _example_batch
    from dct_tpu.serving.batching import _build_jax_scorer
    from dct_tpu.serving.runtime import assemble_weights

    npz = np.load(os.path.join(package_dir, "model.npz"))
    weights = assemble_weights({k: npz[k] for k in npz.files})
    with open(os.path.join(package_dir, "model_meta.json")) as f:
        meta = json.load(f)
    meta["_aot_dir"] = os.path.join(package_dir, "aot")
    t0 = time.perf_counter()
    score = _build_jax_scorer(weights, meta)
    shape = None
    for n in sizes:
        shape = list(np.asarray(score(_example_batch(meta, n))).shape)
    first = time.perf_counter() - t0
    print(json.dumps({
        "first_score_s": round(first, 4),
        "sizes": list(sizes),
        "probs_shape": shape,
    }))
    return 0


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if argv and argv[0] == "first-score" and len(argv) in (2, 3):
        sizes = tuple(
            int(t) for t in (
                argv[2] if len(argv) == 3 else "1"
            ).split(",") if t.strip().isdigit()
        ) or (1,)
        return _first_score_main(argv[1], sizes)
    print(
        "usage: python -m dct_tpu.compilecache.spinup "
        "first-score <package_dir> [sizes]",
        file=sys.stderr,
    )
    return 2


if __name__ == "__main__":
    sys.exit(main())
