"""Training-health telemetry: NaN/Inf guard, loss-spike and grad-norm
drift detection, with a configurable halt-or-warn policy.

The trainer previously noticed a NaN loss only at best-checkpoint
selection time (a NaN epoch simply never improved ``val_loss``) — the
run kept burning accelerator time on a diverged model. Here every
per-step training loss and gradient global norm flows through a
:class:`HealthMonitor` that:

- flags non-finite losses immediately (``health.nan_loss``);
- flags loss spikes by z-score against a rolling window of recent
  finite losses (``health.loss_spike``) — the standard divergence
  tripwire of large-run babysitting;
- flags gradient-norm blowups the same way (``health.grad_norm_spike``)
  using the global norm the train step already computes;
- emits every finding to the structured event log (and the findings
  feed the end-of-run Prometheus dump), so health incidents are
  greppable by run-correlation ID like everything else;
- optionally HALTS the run (``halt_on_nan`` / ``halt_on_spike`` on
  ``ObservabilityConfig``): the trainer raises
  :class:`TrainingHealthError` before completing the epoch's
  bookkeeping, so a diverged run fails fast instead of training
  garbage to its epoch budget.

Spike detection details: z = (x - mean(window)) / std(window) over the
last ``window`` finite values, requiring ``MIN_HISTORY`` points; only
UPWARD deviations count (a falling loss is the goal, not an incident),
and a relative floor on the deviation (10% of |mean|) suppresses
z-blowups on near-constant histories where std ~ 0. Detection state is
host-side and cheap — no device work beyond the norm the step already
computed.
"""

from __future__ import annotations

import math
from collections import deque
from dataclasses import dataclass

#: Minimum finite history before the z-score detector arms.
MIN_HISTORY = 5

#: Per-kind cap on emitted events: a run with a thousand NaN steps gets
#: the first few named, then a final suppressed-count note, not a
#: thousand-line event log.
MAX_EVENTS_PER_KIND = 10

KINDS = ("nan_loss", "loss_spike", "grad_norm_spike")


class TrainingHealthError(RuntimeError):
    """Raised by the trainer when a halting health policy trips."""


@dataclass
class Finding:
    kind: str  # one of KINDS
    value: float
    step: int | None = None
    epoch: int | None = None
    zscore: float | None = None
    halt: bool = False


class _SpikeDetector:
    """Rolling-window upward z-score detector for one scalar stream."""

    def __init__(self, window: int, zscore: float):
        # Floor at MIN_HISTORY: a smaller maxlen could never satisfy
        # the arming gate below and would silently disable detection.
        self.window = deque(maxlen=max(MIN_HISTORY, int(window)))
        self.zscore = float(zscore)

    def observe(self, x: float) -> float | None:
        """Returns the z-score when ``x`` is an upward spike, else None;
        finite values enter the window AFTER the check (the spike itself
        must not raise the baseline it is judged against)."""
        z = None
        n = len(self.window)
        if n >= MIN_HISTORY:
            mean = sum(self.window) / n
            var = sum((v - mean) ** 2 for v in self.window) / n
            std = math.sqrt(var)
            dev = x - mean
            if (
                std > 0.0
                and dev / std >= self.zscore
                and dev >= 0.1 * max(abs(mean), 1e-8)
            ):
                z = dev / std
        if math.isfinite(x):
            self.window.append(x)
        return z


class HealthMonitor:
    """Per-run health state machine; feed it every step's loss (and
    grad norm when available) and emit what it finds.

    ``emit`` is an event-log callable ``(component, event, **fields)``
    (pass ``EventLog.emit``); None disables emission but keeps counts.
    """

    def __init__(
        self,
        *,
        spike_window: int = 16,
        spike_zscore: float = 8.0,
        halt_on_nan: bool = False,
        halt_on_spike: bool = False,
        emit=None,
    ):
        self.halt_on_nan = bool(halt_on_nan)
        self.halt_on_spike = bool(halt_on_spike)
        self._loss = _SpikeDetector(spike_window, spike_zscore)
        self._gnorm = _SpikeDetector(spike_window, spike_zscore)
        self._emit = emit
        self.counts: dict[str, int] = dict.fromkeys(KINDS, 0)
        self.last_loss: float | None = None
        self.last_grad_norm: float | None = None

    @classmethod
    def from_config(cls, obs_cfg, *, emit=None) -> "HealthMonitor":
        """Build from an ``ObservabilityConfig`` (its health knobs)."""
        return cls(
            spike_window=obs_cfg.spike_window,
            spike_zscore=obs_cfg.spike_zscore,
            halt_on_nan=obs_cfg.halt_on_nan,
            halt_on_spike=obs_cfg.halt_on_spike,
            emit=emit,
        )

    # -- observation ---------------------------------------------------
    def _found(self, finding: Finding) -> Finding:
        self.counts[finding.kind] += 1
        if self._emit is not None and (
            self.counts[finding.kind] <= MAX_EVENTS_PER_KIND
        ):
            fields = {
                "value": finding.value,
                "step": finding.step,
                "epoch": finding.epoch,
                "halt": finding.halt,
            }
            if finding.zscore is not None:
                fields["zscore"] = round(finding.zscore, 3)
            if self.counts[finding.kind] == MAX_EVENTS_PER_KIND:
                fields["note"] = (
                    "further events of this kind are suppressed"
                )
            self._emit("health", f"health.{finding.kind}", **fields)
        return finding

    def observe_step(
        self,
        loss: float,
        *,
        grad_norm: float | None = None,
        step: int | None = None,
        epoch: int | None = None,
    ) -> Finding | None:
        """One training step's scalars -> the most severe finding (or
        None). NaN outranks spikes; a halting finding is returned even
        when a non-halting one also fired (both are counted/emitted)."""
        loss = float(loss)
        self.last_loss = loss
        worst: Finding | None = None
        if not math.isfinite(loss):
            worst = self._found(
                Finding(
                    "nan_loss", loss, step=step, epoch=epoch,
                    halt=self.halt_on_nan,
                )
            )
            if grad_norm is not None:
                gn = float(grad_norm)
                self.last_grad_norm = gn
                # A non-finite grad norm is still ITS OWN finding: with
                # only halt_on_spike set, the grad-norm policy must be
                # able to halt a NaN-loss step (the nan_loss finding
                # alone would not).
                if not math.isfinite(gn):
                    f = self._found(
                        Finding(
                            "grad_norm_spike", gn, step=step,
                            epoch=epoch, halt=self.halt_on_spike,
                        )
                    )
                    if f.halt and not worst.halt:
                        worst = f
            return worst
        z = self._loss.observe(loss)
        if z is not None:
            worst = self._found(
                Finding(
                    "loss_spike", loss, step=step, epoch=epoch,
                    zscore=z, halt=self.halt_on_spike,
                )
            )
        if grad_norm is not None:
            gn = float(grad_norm)
            self.last_grad_norm = gn
            if not math.isfinite(gn):
                f = self._found(
                    Finding(
                        "grad_norm_spike", gn, step=step, epoch=epoch,
                        halt=self.halt_on_spike,
                    )
                )
                worst = worst or f
            else:
                gz = self._gnorm.observe(gn)
                if gz is not None:
                    f = self._found(
                        Finding(
                            "grad_norm_spike", gn, step=step,
                            epoch=epoch, zscore=gz,
                            halt=self.halt_on_spike,
                        )
                    )
                    worst = worst or f
        return worst

    # -- reporting -----------------------------------------------------
    @property
    def total_findings(self) -> int:
        return sum(self.counts.values())

    def summary(self) -> dict:
        """JSON-able run-end record (feeds the Prometheus dump and the
        trainer's fit_end event)."""
        return {
            "events": dict(self.counts),
            "last_loss": self.last_loss,
            "last_grad_norm": self.last_grad_norm,
        }

    @staticmethod
    def raise_on(finding: Finding | None) -> None:
        """The halt policy's teeth: raise for a halting finding."""
        if finding is not None and finding.halt:
            raise TrainingHealthError(
                f"training halted by health policy: {finding.kind} "
                f"(value={finding.value!r}, step={finding.step}, "
                f"epoch={finding.epoch})"
            )
