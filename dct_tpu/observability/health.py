"""Training-health telemetry: NaN/Inf guard, loss-spike and grad-norm
drift detection, with a configurable halt-or-warn policy.

The trainer previously noticed a NaN loss only at best-checkpoint
selection time (a NaN epoch simply never improved ``val_loss``) — the
run kept burning accelerator time on a diverged model. Here every
per-step training loss and gradient global norm flows through a
:class:`HealthMonitor` that:

- flags non-finite losses immediately (``health.nan_loss``);
- flags loss spikes by z-score against a rolling window of recent
  finite losses (``health.loss_spike``) — the standard divergence
  tripwire of large-run babysitting;
- flags gradient-norm blowups the same way (``health.grad_norm_spike``)
  using the global norm the train step already computes;
- emits every finding to the structured event log (and the findings
  feed the end-of-run Prometheus dump), so health incidents are
  greppable by run-correlation ID like everything else;
- optionally HALTS the run (``halt_on_nan`` / ``halt_on_spike`` on
  ``ObservabilityConfig``): the trainer raises
  :class:`TrainingHealthError` before completing the epoch's
  bookkeeping, so a diverged run fails fast instead of training
  garbage to its epoch budget.

Spike detection details: z = (x - mean(window)) / std(window) over the
last ``window`` finite values, requiring ``MIN_HISTORY`` points; only
UPWARD deviations count (a falling loss is the goal, not an incident),
and a relative floor on the deviation (10% of |mean|) suppresses
z-blowups on near-constant histories where std ~ 0. Detection state is
host-side and cheap — no device work beyond the norm the step already
computed.
"""

from __future__ import annotations

import math
from collections import deque
from dataclasses import dataclass

#: Minimum finite history before the z-score detector arms.
MIN_HISTORY = 5

#: Per-kind cap on emitted events: a run with a thousand NaN steps gets
#: the first few named, then a final suppressed-count note, not a
#: thousand-line event log.
MAX_EVENTS_PER_KIND = 10

KINDS = ("nan_loss", "loss_spike", "grad_norm_spike")


class TrainingHealthError(RuntimeError):
    """Raised by the trainer when a halting health policy trips."""


@dataclass
class Finding:
    kind: str  # one of KINDS
    value: float
    step: int | None = None
    epoch: int | None = None
    zscore: float | None = None
    halt: bool = False


class _SpikeDetector:
    """Rolling-window upward z-score detector for one scalar stream."""

    def __init__(self, window: int, zscore: float):
        # Floor at MIN_HISTORY: a smaller maxlen could never satisfy
        # the arming gate below and would silently disable detection.
        self.window = deque(maxlen=max(MIN_HISTORY, int(window)))
        self.zscore = float(zscore)

    def observe(self, x: float) -> float | None:
        """Returns the z-score when ``x`` is an upward spike, else None;
        finite values enter the window AFTER the check (the spike itself
        must not raise the baseline it is judged against)."""
        z = None
        n = len(self.window)
        if n >= MIN_HISTORY:
            mean = sum(self.window) / n
            var = sum((v - mean) ** 2 for v in self.window) / n
            std = math.sqrt(var)
            dev = x - mean
            if (
                std > 0.0
                and dev / std >= self.zscore
                and dev >= 0.1 * max(abs(mean), 1e-8)
            ):
                z = dev / std
        if math.isfinite(x):
            self.window.append(x)
        return z

    def screen(self, values) -> bool:
        """Vectorized conservative spike screen over a FINITE sequence:
        True if any element COULD be an upward spike when fed through
        :meth:`observe` one at a time (callers then replay sequentially
        for exact semantics), False only when provably none can.

        Replicates the rolling mean/std with prefix sums (float64),
        including the carried-over window state, but applies the z and
        relative-deviation thresholds with a 10% safety margin — cumsum
        arithmetic and the per-step windowed sums can differ in the last
        float bits, and a borderline decision must fall to the exact
        path, never be screened away."""
        import numpy as np

        values = np.asarray(values, dtype=np.float64).reshape(-1)
        if values.size == 0:
            return False
        prior = np.asarray(self.window, dtype=np.float64)
        seq = np.concatenate([prior, values])
        w = self.window.maxlen
        c1 = np.concatenate([[0.0], np.cumsum(seq)])
        c2 = np.concatenate([[0.0], np.cumsum(seq * seq)])
        j = np.arange(prior.size, seq.size)
        lo = np.maximum(0, j - w)
        n = (j - lo).astype(np.float64)
        armed = n >= MIN_HISTORY
        n_safe = np.maximum(n, 1.0)
        mean = (c1[j] - c1[lo]) / n_safe
        var = np.maximum((c2[j] - c2[lo]) / n_safe - mean * mean, 0.0)
        std = np.sqrt(var)
        dev = values - mean
        with np.errstate(divide="ignore", invalid="ignore"):
            z = np.where(std > 0.0, dev / np.maximum(std, 1e-300), np.inf)
        candidate = (
            armed
            & (dev >= 0.09 * np.maximum(np.abs(mean), 1e-8))
            & (z >= 0.9 * self.zscore)
        )
        return bool(candidate.any())


class HealthMonitor:
    """Per-run health state machine; feed it every step's loss (and
    grad norm when available) and emit what it finds.

    ``emit`` is an event-log callable ``(component, event, **fields)``
    (pass ``EventLog.emit``); None disables emission but keeps counts.
    """

    def __init__(
        self,
        *,
        spike_window: int = 16,
        spike_zscore: float = 8.0,
        halt_on_nan: bool = False,
        halt_on_spike: bool = False,
        emit=None,
    ):
        self.halt_on_nan = bool(halt_on_nan)
        self.halt_on_spike = bool(halt_on_spike)
        self._loss = _SpikeDetector(spike_window, spike_zscore)
        self._gnorm = _SpikeDetector(spike_window, spike_zscore)
        self._emit = emit
        self.counts: dict[str, int] = dict.fromkeys(KINDS, 0)
        self.last_loss: float | None = None
        self.last_grad_norm: float | None = None

    @classmethod
    def from_config(cls, obs_cfg, *, emit=None) -> "HealthMonitor":
        """Build from an ``ObservabilityConfig`` (its health knobs)."""
        return cls(
            spike_window=obs_cfg.spike_window,
            spike_zscore=obs_cfg.spike_zscore,
            halt_on_nan=obs_cfg.halt_on_nan,
            halt_on_spike=obs_cfg.halt_on_spike,
            emit=emit,
        )

    # -- observation ---------------------------------------------------
    def _found(self, finding: Finding) -> Finding:
        self.counts[finding.kind] += 1
        if self._emit is not None and (
            self.counts[finding.kind] <= MAX_EVENTS_PER_KIND
        ):
            fields = {
                "value": finding.value,
                "step": finding.step,
                "epoch": finding.epoch,
                "halt": finding.halt,
            }
            if finding.zscore is not None:
                fields["zscore"] = round(finding.zscore, 3)
            if self.counts[finding.kind] == MAX_EVENTS_PER_KIND:
                fields["note"] = (
                    "further events of this kind are suppressed"
                )
            self._emit("health", f"health.{finding.kind}", **fields)
        return finding

    def observe_step(
        self,
        loss: float,
        *,
        grad_norm: float | None = None,
        step: int | None = None,
        epoch: int | None = None,
    ) -> Finding | None:
        """One training step's scalars -> the most severe finding (or
        None). NaN outranks spikes; a halting finding is returned even
        when a non-halting one also fired (both are counted/emitted)."""
        loss = float(loss)
        self.last_loss = loss
        worst: Finding | None = None
        if not math.isfinite(loss):
            worst = self._found(
                Finding(
                    "nan_loss", loss, step=step, epoch=epoch,
                    halt=self.halt_on_nan,
                )
            )
            if grad_norm is not None:
                gn = float(grad_norm)
                self.last_grad_norm = gn
                # A non-finite grad norm is still ITS OWN finding: with
                # only halt_on_spike set, the grad-norm policy must be
                # able to halt a NaN-loss step (the nan_loss finding
                # alone would not).
                if not math.isfinite(gn):
                    f = self._found(
                        Finding(
                            "grad_norm_spike", gn, step=step,
                            epoch=epoch, halt=self.halt_on_spike,
                        )
                    )
                    if f.halt and not worst.halt:
                        worst = f
            return worst
        z = self._loss.observe(loss)
        if z is not None:
            worst = self._found(
                Finding(
                    "loss_spike", loss, step=step, epoch=epoch,
                    zscore=z, halt=self.halt_on_spike,
                )
            )
        if grad_norm is not None:
            gn = float(grad_norm)
            self.last_grad_norm = gn
            if not math.isfinite(gn):
                f = self._found(
                    Finding(
                        "grad_norm_spike", gn, step=step, epoch=epoch,
                        halt=self.halt_on_spike,
                    )
                )
                worst = worst or f
            else:
                gz = self._gnorm.observe(gn)
                if gz is not None:
                    f = self._found(
                        Finding(
                            "grad_norm_spike", gn, step=step,
                            epoch=epoch, zscore=gz,
                            halt=self.halt_on_spike,
                        )
                    )
                    worst = worst or f
        return worst

    def observe_span(
        self,
        losses,
        grad_norms=None,
        *,
        start_step: int = 0,
        epoch: int = 0,
        steps_per_epoch: int | None = None,
    ) -> Finding | None:
        """A whole span's per-step scalars in one call — the scan path's
        health pass. Semantically identical to calling
        :meth:`observe_step` for each index ``i`` with
        ``step=start_step+i+1`` and ``epoch=epoch+i//steps_per_epoch``,
        but the healthy common case (every value finite, nothing near a
        spike threshold) is screened with a few vectorized reductions
        instead of ``len(losses)`` Python iterations — on the parity
        config that Python loop was costing more host time per epoch
        than the epoch's device compute. Any non-finite value or
        near-threshold z-score candidate falls back to the exact
        sequential path for the whole span, so findings, event caps,
        and halt decisions match the per-step API bit-for-bit. Returns
        the FIRST halting finding (the one the trainer raises), else
        None; non-halting findings are counted/emitted as always."""
        import numpy as np

        losses = np.asarray(losses, dtype=np.float64).reshape(-1)
        gnorms = (
            None
            if grad_norms is None
            else np.asarray(grad_norms, dtype=np.float64).reshape(-1)
        )
        per_epoch = max(1, int(steps_per_epoch or losses.size or 1))
        fast = bool(np.isfinite(losses).all()) and (
            gnorms is None or bool(np.isfinite(gnorms).all())
        )
        if fast:
            fast = not self._loss.screen(losses)
        if fast and gnorms is not None:
            fast = not self._gnorm.screen(gnorms)
        if fast:
            # No candidate anywhere: advance the detector state exactly
            # as the sequential path would. Only the last window's worth
            # can survive a maxlen-bounded deque, so extending with the
            # tail alone is equivalent — and keeps this path free of a
            # per-step Python iteration (the defect it exists to fix).
            self._loss.window.extend(
                float(v) for v in losses[-self._loss.window.maxlen:]
            )
            if losses.size:
                self.last_loss = float(losses[-1])
            if gnorms is not None:
                self._gnorm.window.extend(
                    float(v) for v in gnorms[-self._gnorm.window.maxlen:]
                )
                if gnorms.size:
                    self.last_grad_norm = float(gnorms[-1])
            return None
        halt_finding: Finding | None = None
        for i in range(losses.size):
            f = self.observe_step(
                float(losses[i]),
                grad_norm=(
                    float(gnorms[i]) if gnorms is not None else None
                ),
                step=start_step + i + 1,
                epoch=epoch + i // per_epoch,
            )
            if halt_finding is None and f is not None and f.halt:
                halt_finding = f
        return halt_finding

    # -- reporting -----------------------------------------------------
    @property
    def total_findings(self) -> int:
        return sum(self.counts.values())

    def summary(self) -> dict:
        """JSON-able run-end record (feeds the Prometheus dump and the
        trainer's fit_end event)."""
        return {
            "events": dict(self.counts),
            "last_loss": self.last_loss,
            "last_grad_norm": self.last_grad_norm,
        }

    @staticmethod
    def raise_on(finding: Finding | None) -> None:
        """The halt policy's teeth: raise for a halting finding."""
        if finding is not None and finding.halt:
            raise TrainingHealthError(
                f"training halted by health policy: {finding.kind} "
                f"(value={finding.value!r}, step={finding.step}, "
                f"epoch={finding.epoch})"
            )
