"""End-of-run Prometheus text dump for the trainer.

Training jobs are batch processes — nothing scrapes them live on a
hermetic TPU-VM. The standard bridge is the textfile pattern (Prometheus
node-exporter ``--collector.textfile.directory``): the run writes its
final metrics as an exposition-format file and any file-shipping agent
turns them into series. Same metric names every run, labelled by the
run-correlation ID, so goodput is chartable across continuous-training
cycles.

Since ISSUE 8 the dump is built on the same
:class:`~dct_tpu.observability.metrics.MetricsRegistry` the serving
tier uses (identical exposition bytes, one metric model), and — when
the metrics plane is armed (``DCT_METRICS_DIR``) — the run ALSO leaves
a **final snapshot** behind: a terminal registry snapshot the
aggregation layer keeps after the trainer pid dies, so a ``/metrics``
scrape of the serving pool reports the training fleet's goodput,
health, and compile debt next to the request series.
"""

from __future__ import annotations

import math
import os

from dct_tpu.observability.metrics import MetricsRegistry


def build_train_registry(
    goodput_summary: dict,
    *,
    run_id: str,
    samples_per_sec: float = 0.0,
    val_loss: float | None = None,
    health: dict | None = None,
    resilience: dict | None = None,
    compile_windows: list | None = None,
    roofline: list | None = None,
) -> MetricsRegistry:
    """The run's final metrics as a registry (shared by the textfile
    dump and the metrics-plane snapshot — one source, two sinks)."""
    labels = {"run_id": run_id}
    reg = MetricsRegistry()
    cat = reg.gauge(
        "dct_train_goodput_seconds",
        "Run wall seconds by goodput/badput category.", agg="sum",
    )
    for c, sec in goodput_summary.get("categories", {}).items():
        cat.set(sec, {**labels, "category": c})
    cat.set(
        goodput_summary.get("unattributed_seconds", 0.0),
        {**labels, "category": "unattributed"},
    )
    reg.gauge(
        "dct_train_goodput_fraction",
        "Productive (train_step + eval) seconds over wall seconds.",
        agg="last",
    ).set(goodput_summary.get("goodput_fraction", 0.0), labels)
    reg.gauge(
        "dct_train_wall_seconds",
        "Total run wall seconds (Trainer.fit entry to summary).",
        agg="sum",
    ).set(goodput_summary.get("wall_seconds", 0.0), labels)
    reg.gauge(
        "dct_train_samples_per_sec",
        "Mean training throughput over the run.", agg="last",
    ).set(samples_per_sec, labels)
    reg.counter(
        "dct_train_epochs_total", "Epochs completed by this run.",
    ).inc(goodput_summary.get("epochs", 0), labels)
    if val_loss is not None and math.isfinite(val_loss):
        reg.gauge(
            "dct_train_val_loss", "Final validation loss of the run.",
            agg="last",
        ).set(val_loss, labels)
    if health is not None:
        # Training-health surface (observability.health.HealthMonitor
        # summary): incident counts by kind + the last grad global norm.
        incidents = reg.counter(
            "dct_train_health_events_total",
            "Training-health incidents (nan_loss / loss_spike / "
            "grad_norm_spike) observed by this run.",
        )
        for kind, n in sorted((health.get("events") or {}).items()):
            incidents.inc(n, {**labels, "kind": kind})
        gn = health.get("last_grad_norm")
        if gn is not None and math.isfinite(gn):
            reg.gauge(
                "dct_train_grad_norm",
                "Last observed gradient global norm.", agg="last",
            ).set(gn, labels)
    if resilience is not None:
        # Resilience surface (dct_tpu.resilience): injected-fault count
        # and the supervised-relaunch debt this run was handed
        # (restart.* counters live with the supervisor's events; the
        # debt itself is also inside the startup_recovery category).
        reg.counter(
            "dct_train_faults_injected_total",
            "Faults the DCT_FAULT_SPEC plan fired in this run.",
        ).inc(resilience.get("faults_injected", 0), labels)
        reg.gauge(
            "dct_train_startup_recovery_debt_seconds",
            "Wall seconds lost to failed attempts before this run "
            "(booked as startup_recovery badput).", agg="sum",
        ).set(resilience.get("startup_debt_s", 0.0), labels)
    if compile_windows:
        # Compile accounting (observability.goodput.compile_report):
        # count + duration per program, keyed by the (family,
        # config-hash, mesh) identity an AOT compilation cache would
        # use — the restart/spin-up debt ROADMAP item 5 attacks.
        n_fam = reg.counter(
            "dct_compile_windows_total",
            "XLA compile windows (first dispatch of a distinct "
            "program) paid by this run.",
        )
        s_fam = reg.counter(
            "dct_compile_seconds_total",
            "Wall seconds inside compile windows, by program identity.",
        )
        for w in compile_windows:
            wl = {
                **labels,
                "program": w.get("program", "?"),
                "family": w.get("family", ""),
                "config_hash": w.get("config_hash", ""),
                "mesh": w.get("mesh", ""),
                # AOT-store resolution: "hit" windows are deserialized
                # executables (disk read, ~ms), "miss"/"disabled" are
                # real XLA compiles — the label that proves a warm
                # relaunch paid zero fresh compiles.
                "cache": w.get("cache", "disabled"),
            }
            n_fam.inc(w.get("count", 0), wl)
            s_fam.inc(w.get("seconds", 0.0), wl)
    if roofline:
        # Roofline accounting (observability.roofline.program_report):
        # cost-model FLOPs/HBM per compiled program joined with the
        # ledger's measured dispatch windows — the dct_program_* gauge
        # families a /metrics scrape reports next to the goodput series.
        from dct_tpu.observability.roofline import add_roofline_metrics

        add_roofline_metrics(reg, roofline, labels)
    return reg


def write_train_metrics_prom(
    path: str,
    goodput_summary: dict,
    *,
    run_id: str,
    samples_per_sec: float = 0.0,
    val_loss: float | None = None,
    health: dict | None = None,
    resilience: dict | None = None,
    compile_windows: list | None = None,
    roofline: list | None = None,
    metrics_dir: str | None = None,
    proc: str | None = None,
) -> str | None:
    """Write the run's final metrics at ``path`` (tmp+rename so a
    shipping agent never reads a torn file); when ``metrics_dir`` is
    set, also publish the registry as a FINAL metrics-plane snapshot
    under ``proc``. Returns the path, or None when the write failed
    (telemetry never fails the run)."""
    reg = build_train_registry(
        goodput_summary,
        run_id=run_id,
        samples_per_sec=samples_per_sec,
        val_loss=val_loss,
        health=health,
        resilience=resilience,
        compile_windows=compile_windows,
        roofline=roofline,
    )
    if metrics_dir:
        from dct_tpu.observability.aggregate import write_snapshot

        snap = reg.snapshot(proc=proc or f"train-{run_id}", final=True)
        write_snapshot(snap, metrics_dir)
        # The telemetry history store records the terminal point too —
        # the sealed segment is the run's last word on the timeline,
        # just as the final snapshot is on the instantaneous plane.
        from dct_tpu.observability.timeseries import writer_from_env

        hist = writer_from_env(proc=str(snap.get("proc")))
        if hist is not None:
            hist.append(snap)
            hist.close()
    tmp = path + ".tmp"
    try:
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        with open(tmp, "w") as f:
            f.write(reg.render())
        os.replace(tmp, path)
    except OSError:
        return None
    return path


# ----------------------------------------------------------------------
# live per-epoch publisher (ISSUE 17)


class LiveTrainMetrics:
    """Per-epoch live metrics for the coordinator rank.

    The final dump above is the batch-process pattern — one terminal
    snapshot after the run. The telemetry history plane needs the
    DURING: per-epoch val-loss, goodput, step time and grad norm flow
    to the metrics plane (and so to the on-disk time-series the
    anomaly detector watches) while the run is still alive. Same
    family names and aggs as :func:`build_train_registry`, same
    ``proc`` as the final snapshot — so the terminal write replaces
    this stream under the plane's same-proc newest-wins rule, and a
    scrape never double-counts a run against itself.

    Telemetry-only by construction: nothing here touches model code,
    RNG or jax state, which is what keeps the loss trajectory bitwise
    identical armed vs off.
    """

    def __init__(self, obs, *, run_id: str, proc: str):
        from dct_tpu.observability.aggregate import SnapshotPublisher

        self._labels = {"run_id": run_id}
        reg = MetricsRegistry()
        self._val_loss = reg.gauge(
            "dct_train_val_loss", "Final validation loss of the run.",
            agg="last",
        )
        self._goodput = reg.gauge(
            "dct_train_goodput_fraction",
            "Productive (train_step + eval) seconds over wall seconds.",
            agg="last",
        )
        self._sps = reg.gauge(
            "dct_train_samples_per_sec",
            "Mean training throughput over the run.", agg="last",
        )
        self._step_s = reg.gauge(
            "dct_train_step_seconds",
            "Mean optimizer-step wall seconds over the last epoch.",
            agg="last",
        )
        self._grad_norm = reg.gauge(
            "dct_train_grad_norm",
            "Last observed gradient global norm.", agg="last",
        )
        self._epochs = reg.counter(
            "dct_train_epochs_total", "Epochs completed by this run.",
        )
        self.registry = reg
        self.publisher = SnapshotPublisher(
            reg, obs.metrics_dir, proc=proc,
            interval_s=obs.metrics_publish_s,
        )

    def epoch_end(
        self,
        *,
        val_loss: float | None = None,
        goodput_fraction: float | None = None,
        samples_per_sec: float | None = None,
        step_seconds: float | None = None,
        grad_norm: float | None = None,
    ) -> None:
        """Record one epoch; never raises (telemetry discipline)."""
        try:
            L = self._labels
            if val_loss is not None and math.isfinite(val_loss):
                self._val_loss.set(float(val_loss), L)
            if goodput_fraction is not None:
                self._goodput.set(float(goodput_fraction), L)
            if samples_per_sec is not None:
                self._sps.set(float(samples_per_sec), L)
            if step_seconds is not None:
                self._step_s.set(float(step_seconds), L)
            if grad_norm is not None and math.isfinite(grad_norm):
                self._grad_norm.set(float(grad_norm), L)
            self._epochs.inc(1, L)
            # Epoch cadence is orders slower than the publish throttle:
            # publish directly so every epoch lands on the timeline.
            self.publisher.publish()
        except Exception:  # noqa: BLE001 — telemetry never fails the run
            pass

    def close(self) -> None:
        """Retire the live snapshot (the final dump, written next,
        re-creates the same proc's snapshot as terminal)."""
        try:
            self.publisher.close(final=False)
        except Exception:  # noqa: BLE001
            pass


def live_train_metrics(obs, *, run_id: str, rank: int):
    """Coordinator-only builder; None when the plane is unarmed."""
    if rank != 0 or not obs.enabled or not obs.metrics_dir:
        return None
    try:
        return LiveTrainMetrics(
            obs, run_id=run_id, proc=f"train-rank{rank}"
        )
    except Exception:  # noqa: BLE001 — telemetry never fails the run
        return None
