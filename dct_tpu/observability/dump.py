"""End-of-run Prometheus text dump for the trainer.

Training jobs are batch processes — nothing scrapes them live on a
hermetic TPU-VM. The standard bridge is the textfile pattern (Prometheus
node-exporter ``--collector.textfile.directory``): the run writes its
final metrics as an exposition-format file and any file-shipping agent
turns them into series. Same metric names every run, labelled by the
run-correlation ID, so goodput is chartable across continuous-training
cycles.
"""

from __future__ import annotations

import math
import os

from dct_tpu.observability.prometheus import MetricFamily, render


def write_train_metrics_prom(
    path: str,
    goodput_summary: dict,
    *,
    run_id: str,
    samples_per_sec: float = 0.0,
    val_loss: float | None = None,
    health: dict | None = None,
    resilience: dict | None = None,
) -> str | None:
    """Write the run's final metrics at ``path`` (tmp+rename so a
    shipping agent never reads a torn file). Returns the path, or None
    when the write failed (telemetry never fails the run)."""
    labels = {"run_id": run_id}
    fams = [
        MetricFamily(
            "dct_train_goodput_seconds", "gauge",
            "Run wall seconds by goodput/badput category.",
        ),
        MetricFamily(
            "dct_train_goodput_fraction", "gauge",
            "Productive (train_step + eval) seconds over wall seconds.",
        ).add(goodput_summary.get("goodput_fraction", 0.0), labels),
        MetricFamily(
            "dct_train_wall_seconds", "gauge",
            "Total run wall seconds (Trainer.fit entry to summary).",
        ).add(goodput_summary.get("wall_seconds", 0.0), labels),
        MetricFamily(
            "dct_train_samples_per_sec", "gauge",
            "Mean training throughput over the run.",
        ).add(samples_per_sec, labels),
        MetricFamily(
            "dct_train_epochs_total", "counter",
            "Epochs completed by this run.",
        ).add(goodput_summary.get("epochs", 0), labels),
    ]
    for cat, sec in goodput_summary.get("categories", {}).items():
        fams[0].add(sec, {**labels, "category": cat})
    fams[0].add(
        goodput_summary.get("unattributed_seconds", 0.0),
        {**labels, "category": "unattributed"},
    )
    if val_loss is not None and math.isfinite(val_loss):
        fams.append(
            MetricFamily(
                "dct_train_val_loss", "gauge",
                "Final validation loss of the run.",
            ).add(val_loss, labels)
        )
    if health is not None:
        # Training-health surface (observability.health.HealthMonitor
        # summary): incident counts by kind + the last grad global norm.
        incidents = MetricFamily(
            "dct_train_health_events_total", "counter",
            "Training-health incidents (nan_loss / loss_spike / "
            "grad_norm_spike) observed by this run.",
        )
        for kind, n in sorted((health.get("events") or {}).items()):
            incidents.add(n, {**labels, "kind": kind})
        fams.append(incidents)
        gn = health.get("last_grad_norm")
        if gn is not None and math.isfinite(gn):
            fams.append(
                MetricFamily(
                    "dct_train_grad_norm", "gauge",
                    "Last observed gradient global norm.",
                ).add(gn, labels)
            )
    if resilience is not None:
        # Resilience surface (dct_tpu.resilience): injected-fault count
        # and the supervised-relaunch debt this run was handed
        # (restart.* counters live with the supervisor's events; the
        # debt itself is also inside the startup_recovery category).
        fams.append(
            MetricFamily(
                "dct_train_faults_injected_total", "counter",
                "Faults the DCT_FAULT_SPEC plan fired in this run.",
            ).add(resilience.get("faults_injected", 0), labels)
        )
        fams.append(
            MetricFamily(
                "dct_train_startup_recovery_debt_seconds", "gauge",
                "Wall seconds lost to failed attempts before this run "
                "(booked as startup_recovery badput).",
            ).add(resilience.get("startup_debt_s", 0.0), labels)
        )
    tmp = path + ".tmp"
    try:
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        with open(tmp, "w") as f:
            f.write(render(fams))
        os.replace(tmp, path)
    except OSError:
        return None
    return path
