"""Cross-process snapshot aggregation: the fleet half of the metrics
plane.

The problem (ISSUE 8): a ``GET /metrics`` scrape of the SO_REUSEPORT
:class:`~dct_tpu.serving.server.ServerPool` lands on ONE of N processes
and reports 1/N of the traffic; trainer ranks dump isolated
``train_metrics.prom`` files nothing joins. The fix is a shared-nothing
snapshot protocol:

1. every participating process (pool workers, trainer coordinator, the
   supervising launcher) periodically publishes its FULL
   :meth:`~dct_tpu.observability.metrics.MetricsRegistry.snapshot` as
   one JSON file under ``DCT_METRICS_DIR`` — written tmp-then-
   ``os.replace`` so a reader never sees a torn snapshot;
2. whichever process answers ``/metrics`` publishes its own snapshot
   first, reads every sibling snapshot in the directory, drops the
   stale ones, and merges: counters and histogram buckets sum, gauges
   combine by their declared ``agg``, and every series is ALSO emitted
   per process under a ``proc`` label so operators can still see skew.

Staleness rules (the part that keeps restarts honest):

- a snapshot whose writing pid is **dead** is dropped unless it is
  marked ``final`` (a batch process's terminal snapshot — the textfile
  pattern: the trainer's numbers outlive the trainer);
- a live-process snapshot older than ``stale_s`` (wall-clock mtime) is
  dropped — a wedged worker must stop contributing yesterday's counts;
- an unparsable file is skipped (a concurrent writer crashed mid-tmp;
  the ``os.replace`` protocol makes this only possible for foreign
  debris).

Two snapshots from the same ``proc`` name keep the newest — a restarted
worker replaces, never double-counts, its predecessor.
"""

from __future__ import annotations

import json
import os
import threading
import time

from dct_tpu.observability.prometheus import (
    HistogramAccumulator,
    MetricFamily,
    render,
)

#: Default seconds after which a live process's snapshot stops counting.
DEFAULT_STALE_S = 30.0


def snapshot_path(directory: str, proc: str) -> str:
    # proc names are platform-minted (serve-<pid>, rank0, launcher-<pid>)
    # but sanitize anyway: a path separator in a label must not escape
    # the snapshot dir.
    safe = "".join(c if c.isalnum() or c in "-_." else "_" for c in proc)
    return os.path.join(directory, f"{safe}.metrics.json")


def write_snapshot(snapshot: dict, directory: str) -> str | None:
    """Atomically publish one snapshot dict; returns the path, or None
    when the write failed (telemetry never fails the caller)."""
    path = snapshot_path(directory, snapshot.get("proc", "proc"))
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        os.makedirs(directory, exist_ok=True)
        with open(tmp, "w") as f:
            json.dump(snapshot, f)
        os.replace(tmp, path)
    except (OSError, ValueError):
        return None
    return path


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except OSError:
        # EPERM and friends: the pid exists but is not ours.
        return True
    return True


def read_snapshots(
    directory: str,
    *,
    stale_s: float = DEFAULT_STALE_S,
    clock=time.time,
) -> list[dict]:
    """Every live sibling snapshot under ``directory`` (staleness rules
    in the module docstring), newest first per ``proc`` name."""
    out: dict[str, tuple[float, dict]] = {}
    try:
        names = sorted(os.listdir(directory))
    except OSError:
        return []
    now = clock()
    for name in names:
        if not name.endswith(".metrics.json"):
            continue
        path = os.path.join(directory, name)
        try:
            mtime = os.stat(path).st_mtime
            with open(path) as f:
                snap = json.load(f)
        except (OSError, ValueError):
            continue
        if not isinstance(snap, dict) or "metrics" not in snap:
            continue
        final = bool(snap.get("final"))
        pid = snap.get("pid")
        if not final:
            if isinstance(pid, int) and not _pid_alive(pid):
                continue
            if stale_s > 0 and now - mtime > stale_s:
                continue
        proc = str(snap.get("proc", name))
        kept = out.get(proc)
        if kept is None or mtime >= kept[0]:
            out[proc] = (mtime, snap)
    return [snap for _mt, snap in sorted(
        out.values(), key=lambda p: str(p[1].get("proc", ""))
    )]


# ----------------------------------------------------------------------
# merge


class MergedMetrics:
    """The fleet view: per-metric totals (the scrape's headline series)
    plus the per-process series preserved under a ``proc`` label.

    ``value(name, labels)`` / ``total(name)`` give the SLO layer its
    aggregated inputs without re-parsing exposition text.
    """

    def __init__(self):
        # name -> {"type", "help", "agg", "buckets",
        #          "totals": {label_key: value|hist-dict},
        #          "per_proc": {(proc, label_key): value|hist-dict}}
        self.metrics: dict[str, dict] = {}
        self.procs: list[str] = []

    # -- queries -------------------------------------------------------
    def value(self, name: str, labels: dict | None = None):
        m = self.metrics.get(name)
        if m is None:
            return None
        key = tuple(sorted((str(k), str(v)) for k, v in (labels or {}).items()))
        return m["totals"].get(key)

    def total(self, name: str) -> float | None:
        """Sum of a counter/gauge family over ALL label sets (what the
        availability SLO wants: requests regardless of slot)."""
        m = self.metrics.get(name)
        if m is None or m["type"] == "histogram":
            return None
        vals = list(m["totals"].values())
        return float(sum(vals)) if vals else None

    def histogram_total(self, name: str) -> dict | None:
        """Bucket-wise sum of a histogram family over all label sets:
        ``{"buckets": [...], "counts": [...], "count": n, "sum": s}``."""
        m = self.metrics.get(name)
        if m is None or m["type"] != "histogram":
            return None
        agg = None
        for h in m["totals"].values():
            if agg is None:
                agg = {
                    "buckets": list(m["buckets"]),
                    "counts": list(h["counts"]),
                    "count": h["count"],
                    "sum": h["sum"],
                }
            else:
                agg["counts"] = [
                    a + b for a, b in zip(agg["counts"], h["counts"])
                ]
                agg["count"] += h["count"]
                agg["sum"] += h["sum"]
        return agg


def _merge_value(mtype: str, agg: str, old, new, old_ts, new_ts):
    if old is None:
        return new
    if mtype == "counter" or agg == "sum":
        return old + new
    if agg == "max":
        return max(old, new)
    if agg == "min":
        return min(old, new)
    # "last": the newest snapshot's value wins.
    return new if new_ts >= old_ts else old


def merge_snapshots(snapshots: list[dict]) -> MergedMetrics:
    """Merge per the metric-type semantics (module docstring). Metric
    families meeting under one name must agree on type and buckets;
    a disagreeing snapshot's family is skipped (one mis-published
    process must not corrupt the fleet view)."""
    out = MergedMetrics()
    ts_by_key: dict[tuple, float] = {}
    for snap in snapshots:
        proc = str(snap.get("proc", "?"))
        ts = float(snap.get("ts", 0.0))
        out.procs.append(proc)
        for m in snap.get("metrics", []):
            name = m.get("name")
            mtype = m.get("type")
            if not name or mtype not in ("counter", "gauge", "histogram"):
                continue
            agg = m.get("agg", "sum")
            ent = out.metrics.get(name)
            if ent is None:
                ent = out.metrics[name] = {
                    "type": mtype,
                    "help": m.get("help", ""),
                    "agg": agg,
                    "buckets": list(m.get("buckets") or []),
                    "totals": {},
                    "per_proc": {},
                }
            if ent["type"] != mtype or (
                mtype == "histogram"
                and ent["buckets"] != list(m.get("buckets") or [])
            ):
                continue
            for s in m.get("samples", []):
                key = tuple(sorted(
                    (str(k), str(v))
                    for k, v in (s.get("labels") or {}).items()
                ))
                if mtype == "histogram":
                    h = {
                        "counts": list(s.get("counts") or []),
                        "count": s.get("count", 0),
                        "sum": s.get("sum", 0.0),
                    }
                    if len(h["counts"]) != len(ent["buckets"]):
                        continue
                    tot = ent["totals"].get(key)
                    if tot is None:
                        ent["totals"][key] = {
                            "counts": list(h["counts"]),
                            "count": h["count"],
                            "sum": h["sum"],
                        }
                    else:
                        tot["counts"] = [
                            a + b for a, b in zip(tot["counts"], h["counts"])
                        ]
                        tot["count"] += h["count"]
                        tot["sum"] += h["sum"]
                    ent["per_proc"][(proc, key)] = h
                else:
                    v = float(s.get("value", 0.0))
                    tkey = (name,) + key
                    ent["totals"][key] = _merge_value(
                        mtype, agg, ent["totals"].get(key), v,
                        ts_by_key.get(tkey, 0.0), ts,
                    )
                    ts_by_key[tkey] = max(ts_by_key.get(tkey, 0.0), ts)
                    ent["per_proc"][(proc, key)] = v
    return out


def render_merged(merged: MergedMetrics, *, per_proc: bool = True) -> str:
    """Text exposition of the fleet view: totals first (no ``proc``
    label — dashboards keep their single-process queries), then every
    per-process series under ``proc`` when ``per_proc`` is set."""
    fams = []
    for name in sorted(merged.metrics):
        m = merged.metrics[name]
        fam = MetricFamily(name, m["type"], m["help"])
        rows = []
        for key, val in sorted(m["totals"].items()):
            rows.append((dict(key) or None, val))
        if per_proc:
            for (proc, key), val in sorted(m["per_proc"].items()):
                rows.append(({**dict(key), "proc": proc}, val))
        for labels, val in rows:
            if m["type"] == "histogram":
                acc = HistogramAccumulator(tuple(m["buckets"]))
                acc.counts = list(val["counts"])
                acc.count = val["count"]
                acc.sum = val["sum"]
                acc.samples_into(fam, labels)
            else:
                fam.add(val, labels)
        fams.append(fam)
    return render(fams) if fams else ""


def aggregate_text(
    directory: str,
    *,
    stale_s: float = DEFAULT_STALE_S,
    per_proc: bool = True,
    clock=time.time,
) -> tuple[str, MergedMetrics]:
    """One scrape's worth of work: read + merge + render. Returns the
    body and the merged view (the SLO layer consumes the latter)."""
    merged = merge_snapshots(
        read_snapshots(directory, stale_s=stale_s, clock=clock)
    )
    return render_merged(merged, per_proc=per_proc), merged


# ----------------------------------------------------------------------
# publisher


class SnapshotPublisher:
    """Per-process publishing loop: throttled on the hot path, kept
    fresh by a daemon timer when idle.

    ``maybe_publish()`` costs one clock read when inside the throttle
    window — cheap enough to ride every request completion. The timer
    thread republishes every ``interval_s`` so an idle-but-alive
    process never goes stale (staleness would drop its historical
    counts from the fleet totals). ``close(final=True)`` writes the
    terminal snapshot batch processes leave behind.
    """

    def __init__(
        self,
        registry,
        directory: str,
        *,
        proc: str,
        interval_s: float = 2.0,
        clock=time.time,
        start_timer: bool = True,
        history="env",
    ):
        self.registry = registry
        self.directory = directory
        self.proc = proc
        self.interval_s = max(0.0, float(interval_s))
        self._clock = clock
        self._last = 0.0
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._closed = False
        self._thread = None
        # The telemetry history store (timeseries.py) rides the publish
        # cadence of EVERY publisher: by default the hook self-arms off
        # DCT_TS_DIR, so no call site needs plumbing. Pass an explicit
        # HistoryWriter (tests) or None (opt out) to override.
        if history == "env":
            from dct_tpu.observability.timeseries import writer_from_env

            history = writer_from_env(proc=proc, clock=clock)
        self.history = history
        if start_timer and self.interval_s > 0:
            self._thread = threading.Thread(
                target=self._loop, name=f"dct-metrics-{proc}", daemon=True
            )
            self._thread.start()

    def publish(self, *, final: bool = False) -> str | None:
        with self._lock:
            if self._closed:
                # A publish landing after close() would resurrect a
                # retired snapshot (or clear a final one's flag).
                return None
            self._last = self._clock()
            snap = self.registry.snapshot(proc=self.proc, final=final)
            path = write_snapshot(snap, self.directory)
            if path is not None and self.history is not None:
                self.history.append(snap)
            return path

    def maybe_publish(self) -> bool:
        """Publish if the throttle window elapsed; True when written."""
        if self._clock() - self._last < self.interval_s:
            return False
        return self.publish() is not None

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s or 2.0):
            try:
                self.maybe_publish()
            except Exception:  # noqa: BLE001 — telemetry never kills a proc
                return

    def close(self, *, final: bool = False) -> None:
        """Stop the timer. ``final=True`` leaves a terminal snapshot
        behind (the batch-process textfile pattern); otherwise the
        snapshot is RETIRED (removed) — an in-process server that shut
        down cleanly has left the fleet, and its pid staying alive must
        not keep its counts contributing.

        The terminal write/remove happens under the publish lock with
        the closed flag already set, so an in-flight ``publish`` (timer
        thread, request path) can neither resurrect a retired snapshot
        nor overwrite a final one as non-final."""
        self._stop.set()
        with self._lock:
            self._closed = True
            try:
                if final:
                    snap = self.registry.snapshot(proc=self.proc, final=True)
                    write_snapshot(snap, self.directory)
                    if self.history is not None:
                        self.history.append(snap)
                else:
                    os.remove(snapshot_path(self.directory, self.proc))
            except OSError:
                pass
            if self.history is not None:
                # Seal the active segment either way: the HISTORY of a
                # retiring process is exactly what must outlive it.
                self.history.close()
