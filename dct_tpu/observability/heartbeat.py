"""Per-rank heartbeats + the launcher-side stall/straggler monitor.

The reference's launcher ``wait``s on rank PIDs: a rank wedged in a
collective (its peer died, the fabric hiccuped) stays "alive" to the
orchestrator until the 3-hour task timeout. Heartbeats make liveness
*semantic*: each rank atomically rewrites a small per-rank JSON file
with its step/epoch progress, and the monitor (run by whoever babysits
the ranks — :class:`dct_tpu.launch.launcher.LocalProcessLauncher`, or
an operator's watch loop over a shared filesystem) classifies each
rank:

- ``starting`` — no file yet, within the startup grace window;
- ``ok``       — file fresh (younger than ``stall_seconds``);
- ``stalled``  — file exists but stale: the process may be alive and
  wedged (exactly the case PID-liveness cannot see);
- ``missing``  — no file after the grace window (crashed before its
  first beat, or heartbeats are mis-rooted);
- ``done``     — final beat (``phase == "done"``) written; age is
  expected to grow, never stalls.

Files are ``rank_<r>.json`` under one directory (shared dir for
single-host / NFS; per-host dirs aggregate by copying — the records are
self-describing). Writes are tmp+rename so readers never see a torn
record. Records from a DIFFERENT run-correlation ID are treated as
absent: a stale file from yesterday's run must not make today's dead
rank look alive.

Clock-injectable throughout; writer failures degrade to silence.
"""

from __future__ import annotations

import json
import os
import time
from dataclasses import dataclass


def heartbeat_path(directory: str, rank: int) -> str:
    return os.path.join(directory, f"rank_{rank:05d}.json")


class HeartbeatWriter:
    """Rank-side: atomically rewrite this rank's heartbeat file."""

    def __init__(
        self,
        directory: str,
        rank: int,
        *,
        run_id: str | None = None,
        min_interval: float = 0.0,
        clock=time.time,
    ):
        self.directory = directory
        self.rank = int(rank)
        self.run_id = run_id
        self.min_interval = float(min_interval)
        self._clock = clock
        self._last_write: float | None = None
        self._last_phase: str | None = None
        self._dead = False
        # Progress tracking: the last (step, epoch) that CHANGED and
        # when. Write age says "the process is alive"; progress age says
        # "the process is getting somewhere" — a rank beating every 5 s
        # while wedged at the same step looks healthy to the first and
        # stalled to the second.
        self._last_progress: tuple | None = None
        self._progress_time: float | None = None

    @property
    def path(self) -> str:
        return heartbeat_path(self.directory, self.rank)

    def beat(
        self,
        *,
        step: int | None = None,
        epoch: int | None = None,
        phase: str = "train",
        force: bool = False,
    ) -> bool:
        """Write a heartbeat; returns True if written. Same-phase beats
        inside ``min_interval`` are throttled (a per-step caller must
        not turn the heartbeat into an I/O hot loop); phase transitions
        and ``force`` always write."""
        if self._dead:
            return False
        now = self._clock()
        if (
            not force
            and phase == self._last_phase
            and self._last_write is not None
            and now - self._last_write < self.min_interval
        ):
            return False
        if self._progress_time is None or (
            (step, epoch) != self._last_progress
            and (step is not None or epoch is not None)
        ):
            # First beat counts as progress (startup IS forward motion);
            # after that only a step/epoch advance refreshes the clock.
            self._progress_time = now
            self._last_progress = (step, epoch)
        rec = {
            "rank": self.rank,
            "run_id": self.run_id,
            "pid": os.getpid(),
            "time": round(now, 3),
            "step": step,
            "epoch": epoch,
            "phase": phase,
            "progress_time": round(self._progress_time, 3),
        }
        tmp = self.path + f".tmp.{os.getpid()}"
        try:
            os.makedirs(self.directory, exist_ok=True)
            with open(tmp, "w") as f:
                json.dump(rec, f)
            os.replace(tmp, self.path)
        except OSError:
            self._dead = True  # liveness telemetry must never kill a rank
            return False
        self._last_write = now
        self._last_phase = phase
        return True

    def close(self, *, step: int | None = None, epoch: int | None = None):
        """Final beat: marks the rank done so the monitor stops ageing it."""
        self.beat(step=step, epoch=epoch, phase="done", force=True)


def read_heartbeat(path: str) -> dict | None:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


@dataclass
class RankStatus:
    rank: int
    state: str  # starting | ok | stalled | missing | done
    age_seconds: float | None = None
    step: int | None = None
    epoch: int | None = None
    phase: str | None = None
    # Seconds since the rank's (step, epoch) last ADVANCED — the
    # progress age a supervisor reports as dct_rank_progress_age_seconds
    # (write age only proves liveness; this proves forward motion).
    progress_age_seconds: float | None = None


class HeartbeatMonitor:
    """Orchestrator-side: classify every expected rank and quantify
    progress skew (the straggler signal)."""

    def __init__(
        self,
        directory: str,
        world_size: int,
        *,
        stall_seconds: float = 60.0,
        run_id: str | None = None,
        clock=time.time,
    ):
        self.directory = directory
        self.world_size = int(world_size)
        self.stall_seconds = float(stall_seconds)
        self.run_id = run_id
        self._clock = clock
        self._started_at = clock()

    def scan(self) -> list[RankStatus]:
        now = self._clock()
        grace = now - self._started_at < self.stall_seconds
        out: list[RankStatus] = []
        for rank in range(self.world_size):
            rec = read_heartbeat(heartbeat_path(self.directory, rank))
            if rec is not None and self.run_id and rec.get("run_id") != self.run_id:
                rec = None  # a previous run's leftover is NOT a heartbeat
            if rec is None:
                out.append(
                    RankStatus(rank, "starting" if grace else "missing")
                )
                continue
            age = max(0.0, now - float(rec.get("time", 0.0)))
            phase = rec.get("phase")
            if phase == "done":
                state = "done"
            elif age > self.stall_seconds:
                state = "stalled"
            else:
                state = "ok"
            # Progress age: older records (pre-ISSUE 8) lack the field —
            # fall back to write age, which can only UNDER-state it.
            ptime = rec.get("progress_time")
            progress_age = (
                max(0.0, now - float(ptime))
                if isinstance(ptime, (int, float)) else age
            )
            out.append(
                RankStatus(
                    rank,
                    state,
                    age_seconds=age,
                    step=rec.get("step"),
                    epoch=rec.get("epoch"),
                    phase=phase,
                    progress_age_seconds=progress_age,
                )
            )
        return out

    @staticmethod
    def skew(statuses: list[RankStatus]) -> dict:
        """Progress spread across ranks that reported any: the live
        straggler signal (a rank 3 epochs behind its peers is about to
        become everyone's collective stall)."""
        epochs = [s.epoch for s in statuses if s.epoch is not None]
        steps = [s.step for s in statuses if s.step is not None]
        return {
            "epoch_skew": max(epochs) - min(epochs) if epochs else 0,
            "step_skew": max(steps) - min(steps) if steps else 0,
        }

    def report(self) -> dict:
        statuses = self.scan()
        progress = [
            s.progress_age_seconds for s in statuses
            if s.progress_age_seconds is not None and s.state != "done"
        ]
        return {
            "ranks": {s.rank: s.state for s in statuses},
            "stalled": [s.rank for s in statuses if s.state == "stalled"],
            "missing": [s.rank for s in statuses if s.state == "missing"],
            "max_progress_age_seconds": (
                round(max(progress), 3) if progress else None
            ),
            **self.skew(statuses),
        }
