"""Platform-wide telemetry: the operator plane the reference lacks.

The reference platform's only observability is stdout prints scraped from
Airflow task logs (SURVEY §5.1). This package is the TPU-scale operator
plane built on four pillars:

- :mod:`events` — append-only structured JSONL event log with a
  run-correlation ID minted by the DAG/launcher and passed via env to
  every rank, so ONE grep reconstructs a whole continuous-training cycle
  (launch -> train -> checkpoint -> tracking -> deploy) across processes.
- :mod:`goodput` — wall-clock ledger classifying run time into
  train_step / eval / compile / checkpoint / data_wait /
  startup_recovery, the "what fraction of the run was productive?"
  accounting the pjit/TPUv4 training reports treat as first-class.
- :mod:`heartbeat` — per-rank liveness files + a launcher-side monitor
  that names stalled/dead/straggling ranks instead of waiting silently
  on join.
- :mod:`prometheus` — text-exposition (0.0.4) rendering for the serving
  server's ``GET /metrics`` and the trainer's end-of-run metrics dump.
- :mod:`spans` — cross-process distributed tracing: per-process span
  JSONL sharing the run-correlation ID as trace_id, parent spans
  propagated to children via ``DCT_SPAN_ID``.
- :mod:`trace_export` — deterministic merge of all ranks' span files
  into one Perfetto-loadable Chrome-trace-event ``trace.json``.
- :mod:`health` — training-health telemetry: NaN/Inf-loss guard,
  loss-spike and grad-norm z-score detectors, warn-or-halt policy.
- :mod:`inspect` — the run-inspector CLI
  (``python -m dct_tpu.observability.inspect <run_dir>``) joining
  events + spans + goodput + heartbeats into a cycle report.
- :mod:`metrics` / :mod:`aggregate` / :mod:`slo` — the metrics plane
  (ISSUE 8): a general registry (counter/gauge/histogram with merge
  semantics) every process publishes as atomic snapshot files, scrape-
  time aggregation into fleet totals + per-``proc`` series, and SLO
  burn-rate monitoring (``slo.alert`` events, ``dct_slo_*`` gauges)
  over the aggregated view.
- :mod:`report` — the bench-trajectory regression sentinel
  (``python -m dct_tpu.observability.report BENCH_r0*.json``).

Everything here is dependency-free, failure-isolated (a full disk or an
unwritable dir degrades telemetry to a no-op, never fails training), and
clock-injectable for tests.
"""

from dct_tpu.observability.events import (  # noqa: F401
    EventLog,
    current_run_id,
    event_log_from_config,
    get_default,
    mint_run_id,
    set_default,
)
from dct_tpu.observability.goodput import (  # noqa: F401
    CATEGORIES,
    GoodputLedger,
)
from dct_tpu.observability.heartbeat import (  # noqa: F401
    HeartbeatMonitor,
    HeartbeatWriter,
    RankStatus,
)
from dct_tpu.observability.prometheus import (  # noqa: F401
    LATENCY_BUCKETS,
    HistogramAccumulator,
    MetricFamily,
    render,
)
