"""On-disk metric time-series: the longitudinal half of the metrics
plane.

The PR 8 snapshot protocol is deliberately *instantaneous*: every
process publishes its current
:meth:`~dct_tpu.observability.metrics.MetricsRegistry.snapshot` and a
scrape merges whatever is live right now. Nothing retains what the
fleet looked like thirty seconds ago, so an SLO burn, a queue-depth
ramp or a loss spike can only be judged against in-memory state that
dies with the process (ISSUE 17). This module adds the missing axis:

1. :class:`HistoryWriter` rides the existing
   :class:`~dct_tpu.observability.aggregate.SnapshotPublisher` cadence
   (the publisher calls :meth:`HistoryWriter.append` with every
   snapshot it just published) and records the selected ``dct_*``
   families into per-process SEGMENT files under ``DCT_TS_DIR``:

       <ts_dir>/<proc>/active.seg.json     in-progress segment
       <ts_dir>/<proc>/raw-00000003.seg.json   sealed, immutable
       <ts_dir>/<proc>/ds-00000001.seg.json    downsampled tier

   Points are buffered in memory and the active segment is republished
   (tmp then ``os.replace``, per the atomic-publish lint) only every
   ``flush_s`` / ``flush_points`` — and the segment writes themselves
   run on a background flusher thread (``append`` snapshots the
   buffer under the lock and enqueues a write job), so the publishing
   thread never pays disk I/O at all. The common ``append`` is a list
   push, which is what keeps the armed publish path within the
   15%-of-plain overhead budget at p50 *and* keeps the flush windows
   out of its tail.

2. Sealed raw segments older than ``downsample_s`` are folded into a
   coarse tier (``ds_res_s``-wide bins of min/max/mean/last/count for
   gauges; last cumulative value for counters and histograms) and the
   raw file removed; anything whose newest point is older than
   ``retention_s`` is deleted. Compaction runs opportunistically at
   seal time, so its cost is amortised over a whole segment of
   appends.

3. :class:`HistoryReader` answers bounded-overhead window queries —
   ``range`` / ``gauge_last`` / ``counter_rate`` / ``counter_delta`` /
   ``hist_mean`` / ``hist_percentile`` — across every process's
   segments, with parsed segments cached by ``(mtime_ns, size)`` so a
   poll loop re-reads only files that actually changed. Counter and
   histogram deltas are reset-tolerant: a restarted process's
   cumulative value dropping to zero contributes its new total, never
   a negative delta.

Like every other telemetry surface here, the store never fails the
run: any OSError flips the writer dead and appends become no-ops.
"""

from __future__ import annotations

import fnmatch
import json
import os
import threading
import time

#: Families recorded by default: the signals the anomaly detector and
#: the control loops (autoscaler, SLO monitor) actually consume.
DEFAULT_FAMILIES = (
    "dct_train_*,dct_serve_*,dct_request*,dct_program_*,"
    "dct_slo_*,dct_anomaly_*,dct_tenant_*,dct_sched_*"
)

_SEG_SUFFIX = ".seg.json"


def _proc_dir(directory: str, proc: str) -> str:
    safe = "".join(c if c.isalnum() or c in "-_." else "_" for c in proc)
    return os.path.join(directory, safe)


def _label_key(labels: dict | None) -> str:
    if not labels:
        return ""
    return json.dumps(labels, sort_keys=True, separators=(",", ":"))


def _write_json(path: str, obj: dict) -> bool:
    """tmp + ``os.replace`` publish (a reader never sees a torn
    segment); False when the write failed."""
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(tmp, "w") as f:
            json.dump(obj, f)
        os.replace(tmp, path)
    except (OSError, ValueError):
        try:
            os.remove(tmp)
        except OSError:
            pass
        return False
    return True


def parse_families(spec: str | None) -> tuple[str, ...]:
    """``DCT_TS_FAMILIES`` grammar: comma-separated fnmatch patterns."""
    out = []
    for part in (spec or DEFAULT_FAMILIES).split(","):
        part = part.strip()
        if part:
            out.append(part)
    return tuple(out)


# ----------------------------------------------------------------------
# writer


class HistoryWriter:
    """Per-process segment writer fed at publisher cadence."""

    def __init__(
        self,
        directory: str,
        *,
        proc: str,
        families: str | tuple[str, ...] | None = None,
        seg_points: int = 240,
        seg_s: float = 600.0,
        flush_s: float = 10.0,
        flush_points: int = 8,
        retention_s: float = 10800.0,
        downsample_s: float = 900.0,
        ds_res_s: float = 60.0,
        clock=time.time,
    ):
        self.directory = directory
        self.proc = proc
        self.proc_dir = _proc_dir(directory, proc)
        if isinstance(families, str) or families is None:
            families = parse_families(families)
        self.families = tuple(families)
        self.seg_points = max(1, int(seg_points))
        self.seg_s = float(seg_s)
        self.flush_s = float(flush_s)
        self.flush_points = max(1, int(flush_points))
        self.retention_s = float(retention_s)
        self.downsample_s = float(downsample_s)
        self.ds_res_s = max(1.0, float(ds_res_s))
        self._clock = clock
        self._lock = threading.Lock()
        self._dead = False
        self._match_cache: dict[str, bool] = {}
        self._points: list[dict] = []
        self._meta: dict[str, dict] = {}
        self._start_ts: float | None = None
        self._since_flush = 0
        self._last_flush = 0.0
        self._seq = self._scan_seq()
        # Disk I/O rides a background flusher: append/flush/seal enqueue
        # write jobs here (FIFO) and the io thread drains them, so the
        # publishing thread never blocks on a segment write. Points and
        # meta entries are immutable once appended, which is what makes
        # the under-lock shallow snapshot in the enqueue path safe.
        self._io_jobs: list[tuple] = []
        self._io_cv = threading.Condition()
        self._io_stop = False
        self._io_busy = False
        self._io_thread: threading.Thread | None = None

    def _scan_seq(self) -> int:
        """Continue numbering after the segments a predecessor with the
        same proc name left behind (restart = same stream)."""
        top = 0
        try:
            for name in os.listdir(self.proc_dir):
                if name.endswith(_SEG_SUFFIX) and "-" in name:
                    try:
                        top = max(top, int(name.split("-")[1].split(".")[0]))
                    except ValueError:
                        continue
        except OSError:
            pass
        return top + 1

    def _selected(self, name: str) -> bool:
        hit = self._match_cache.get(name)
        if hit is None:
            hit = any(fnmatch.fnmatchcase(name, p) for p in self.families)
            self._match_cache[name] = hit
        return hit

    # -- ingest ---------------------------------------------------------

    def append(self, snapshot: dict) -> None:
        """Record one published snapshot; never raises."""
        if self._dead:
            return
        try:
            self._append(snapshot)
        except Exception:  # noqa: BLE001 — telemetry never fails the run
            self._dead = True

    def _append(self, snapshot: dict) -> None:
        ts = float(snapshot.get("ts") or self._clock())
        point: dict = {}
        for m in snapshot.get("metrics", ()):
            name = m.get("name")
            if not name or not self._selected(name):
                continue
            mtype = m.get("type")
            meta = self._meta.get(name)
            if meta is None:
                meta = {"type": mtype}
                if mtype == "gauge":
                    meta["agg"] = m.get("agg", "sum")
                elif mtype == "histogram":
                    meta["buckets"] = list(m.get("buckets") or ())
                self._meta[name] = meta
            series: dict = {}
            for s in m.get("samples", ()):
                lk = _label_key(s.get("labels"))
                if mtype == "histogram":
                    series[lk] = {
                        "counts": list(s.get("counts") or ()),
                        "count": s.get("count", 0),
                        "sum": s.get("sum", 0.0),
                    }
                else:
                    series[lk] = s.get("value", 0.0)
            if series:
                point[name] = series
        if not point:
            return
        with self._lock:
            if self._start_ts is None:
                self._start_ts = ts
                self._last_flush = ts
            self._points.append({"ts": ts, "m": point})
            self._since_flush += 1
            if (
                len(self._points) >= self.seg_points
                or ts - self._start_ts >= self.seg_s
            ):
                self._seal_locked(ts)
            elif (
                self._since_flush >= self.flush_points
                or ts - self._last_flush >= self.flush_s
            ):
                self._flush_locked(ts)

    # -- segment lifecycle ----------------------------------------------

    def _segment_obj(self, tier: str) -> dict:
        # Shallow copies: points and meta entries are immutable once
        # appended, so the io thread can serialise this object while
        # the publisher keeps appending to the live buffers.
        return {
            "v": 1,
            "tier": tier,
            "proc": self.proc,
            "pid": os.getpid(),
            "seq": self._seq,
            "start_ts": self._start_ts,
            "end_ts": self._points[-1]["ts"] if self._points else None,
            "meta": dict(self._meta),
            "points": list(self._points),
        }

    def _flush_locked(self, now: float) -> None:
        if not self._points:
            return
        self._enqueue(("active", self._segment_obj("raw")))
        self._last_flush = now
        self._since_flush = 0

    def _seal_locked(self, now: float) -> None:
        if not self._points:
            return
        self._enqueue(("seal", self._segment_obj("raw"), now))
        self._seq += 1
        self._points = []
        self._start_ts = None
        self._since_flush = 0
        self._last_flush = now

    # -- background flusher ---------------------------------------------

    def _enqueue(self, job: tuple) -> None:
        with self._io_cv:
            if not self._io_stop and (
                self._io_thread is None or not self._io_thread.is_alive()
            ):
                try:
                    t = threading.Thread(
                        target=self._io_loop, name="dct-ts-flush",
                        daemon=True,
                    )
                    t.start()
                    self._io_thread = t
                except RuntimeError:
                    self._io_thread = None
            if (
                not self._io_stop
                and self._io_thread is not None
                and self._io_thread.is_alive()
            ):
                if (
                    job[0] == "active"
                    and self._io_jobs
                    and self._io_jobs[-1][0] == "active"
                ):
                    # A full-state active write supersedes a pending
                    # one — the queue never grows past one flush per
                    # seal boundary.
                    self._io_jobs[-1] = job
                else:
                    self._io_jobs.append(job)
                self._io_cv.notify_all()
                return
        # No io thread (interpreter shutdown, or closed): write inline.
        self._run_job(job)

    def _io_loop(self) -> None:
        while True:
            with self._io_cv:
                while not self._io_jobs and not self._io_stop:
                    self._io_cv.wait()
                if not self._io_jobs:
                    return
                job = self._io_jobs.pop(0)
                self._io_busy = True
            try:
                self._run_job(job)
            finally:
                with self._io_cv:
                    self._io_busy = False
                    self._io_cv.notify_all()

    def _run_job(self, job: tuple) -> None:
        kind, obj = job[0], job[1]
        if kind == "active":
            path = os.path.join(self.proc_dir, f"active{_SEG_SUFFIX}")
            if not _write_json(path, obj):
                self._dead = True
            return
        path = os.path.join(
            self.proc_dir, f"raw-{obj['seq']:08d}{_SEG_SUFFIX}"
        )
        if not _write_json(path, obj):
            self._dead = True
            return
        try:
            os.remove(os.path.join(self.proc_dir, f"active{_SEG_SUFFIX}"))
        except OSError:
            pass
        self.compact(now=job[2])

    def _drain(self, timeout: float = 5.0) -> None:
        """Wait until every enqueued write has hit disk."""
        deadline = time.monotonic() + timeout
        with self._io_cv:
            while self._io_jobs or self._io_busy:
                left = deadline - time.monotonic()
                if left <= 0 or self._io_thread is None:
                    return
                if not self._io_thread.is_alive():
                    return
                self._io_cv.wait(timeout=left)

    def flush(self) -> None:
        """Force the active segment to disk (tests, clean shutdown).
        Synchronous: returns only after the write has landed."""
        if self._dead:
            return
        with self._lock:
            self._flush_locked(self._clock())
        self._drain()

    def close(self) -> None:
        """Seal whatever is buffered; the stream survives the process.
        Drains the flusher and stops its thread."""
        try:
            if not self._dead:
                with self._lock:
                    self._seal_locked(self._clock())
        except Exception:  # noqa: BLE001
            self._dead = True
        self._drain()
        with self._io_cv:
            self._io_stop = True
            self._io_cv.notify_all()
        t = self._io_thread
        if t is not None:
            t.join(timeout=2.0)

    # -- compaction -----------------------------------------------------

    def compact(self, *, now: float | None = None) -> dict:
        """Downsample sealed raw segments past ``downsample_s`` and
        delete anything past ``retention_s``. Returns counts (tests and
        the incident CLI report them); safe to call any time."""
        out = {"downsampled": 0, "deleted": 0}
        if now is None:
            now = self._clock()
        try:
            names = sorted(os.listdir(self.proc_dir))
        except OSError:
            return out
        for name in names:
            if not name.endswith(_SEG_SUFFIX) or name.startswith("active"):
                continue
            path = os.path.join(self.proc_dir, name)
            seg = _load_segment(path)
            if seg is None:
                continue
            end_ts = seg.get("end_ts") or 0.0
            if self.retention_s > 0 and now - end_ts > self.retention_s:
                try:
                    os.remove(path)
                    out["deleted"] += 1
                except OSError:
                    pass
                continue
            if (
                name.startswith("raw-")
                and self.downsample_s > 0
                and now - end_ts > self.downsample_s
            ):
                ds = downsample_segment(seg, res_s=self.ds_res_s)
                ds_path = os.path.join(
                    self.proc_dir, f"ds-{seg.get('seq', 0):08d}{_SEG_SUFFIX}"
                )
                # ds written BEFORE raw removed: a crash between the
                # two leaves both tiers and the reader prefers raw.
                if _write_json(ds_path, ds):
                    try:
                        os.remove(path)
                        out["downsampled"] += 1
                    except OSError:
                        pass
        return out


def downsample_segment(seg: dict, *, res_s: float = 60.0) -> dict:
    """Fold a raw segment into ``res_s``-wide bins: gauges keep
    min/max/mean/last/n, counters and histograms keep the last
    cumulative value (rates stay computable; bucket detail is the
    price of the coarse tier)."""
    res_s = max(1.0, float(res_s))
    bins: dict[int, dict] = {}
    meta = seg.get("meta", {})
    for pt in seg.get("points", ()):
        ts = pt.get("ts", 0.0)
        b = int(ts // res_s)
        bm = bins.setdefault(b, {})
        for name, series in pt.get("m", {}).items():
            mtype = meta.get(name, {}).get("type")
            nm = bm.setdefault(name, {})
            for lk, val in series.items():
                if mtype == "gauge":
                    agg = nm.get(lk)
                    v = float(val)
                    if agg is None:
                        nm[lk] = {
                            "min": v, "max": v, "mean": v, "last": v, "n": 1,
                        }
                    else:
                        n = agg["n"] + 1
                        agg["min"] = min(agg["min"], v)
                        agg["max"] = max(agg["max"], v)
                        agg["mean"] += (v - agg["mean"]) / n
                        agg["last"] = v
                        agg["n"] = n
                elif mtype == "histogram":
                    nm[lk] = {
                        "count": val.get("count", 0),
                        "sum": val.get("sum", 0.0),
                    }
                else:
                    nm[lk] = {"last": float(val)}
    points = [
        {"ts": (b + 1) * res_s, "m": bm} for b, bm in sorted(bins.items())
    ]
    return {
        "v": 1,
        "tier": "ds",
        "proc": seg.get("proc"),
        "pid": seg.get("pid"),
        "seq": seg.get("seq"),
        "res_s": res_s,
        "start_ts": seg.get("start_ts"),
        "end_ts": seg.get("end_ts"),
        "meta": meta,
        "points": points,
    }


def _load_segment(path: str) -> dict | None:
    try:
        with open(path) as f:
            seg = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(seg, dict) or "points" not in seg:
        return None
    return seg


# ----------------------------------------------------------------------
# reader


class HistoryReader:
    """Window queries over every process's segments under ``ts_dir``."""

    def __init__(self, directory: str, *, clock=time.time):
        self.directory = directory
        self._clock = clock
        # path -> (mtime_ns, size, parsed-or-None)
        self._cache: dict[str, tuple[int, int, dict | None]] = {}

    def _segments(self) -> list[dict]:
        segs: list[dict] = []
        seen: set[str] = set()
        try:
            proc_names = sorted(os.listdir(self.directory))
        except OSError:
            return segs
        for pn in proc_names:
            pdir = os.path.join(self.directory, pn)
            try:
                names = sorted(os.listdir(pdir))
            except OSError:
                continue
            raw_seqs = {
                n.split("-")[1].split(".")[0]
                for n in names
                if n.startswith("raw-") and n.endswith(_SEG_SUFFIX)
            }
            for name in names:
                if not name.endswith(_SEG_SUFFIX):
                    continue
                # crash between ds-write and raw-remove leaves both
                # tiers for one seq: the raw one wins (full detail).
                if name.startswith("ds-"):
                    seq = name.split("-")[1].split(".")[0]
                    if seq in raw_seqs:
                        continue
                path = os.path.join(pdir, name)
                seen.add(path)
                seg = self._load_cached(path)
                if seg is not None:
                    segs.append(seg)
        for stale in set(self._cache) - seen:
            del self._cache[stale]
        return segs

    def _load_cached(self, path: str) -> dict | None:
        try:
            st = os.stat(path)
        except OSError:
            return None
        hit = self._cache.get(path)
        if hit is not None and hit[0] == st.st_mtime_ns and hit[1] == st.st_size:
            return hit[2]
        seg = _load_segment(path)
        self._cache[path] = (st.st_mtime_ns, st.st_size, seg)
        return seg

    # -- series assembly ------------------------------------------------

    def _series(
        self, name: str, start: float, end: float
    ) -> dict[tuple[str, str], dict]:
        """``(proc, label_key) -> {"meta", "tier", "points"}`` with
        points ``(ts, value)`` sorted, clipped to [start, end]."""
        out: dict[tuple[str, str], dict] = {}
        for seg in self._segments():
            if name not in seg.get("meta", {}):
                continue
            seg_start = seg.get("start_ts") or 0.0
            seg_end = seg.get("end_ts") or seg_start
            if seg_end < start or seg_start > end:
                continue
            proc = str(seg.get("proc", "?"))
            meta = seg["meta"][name]
            tier = seg.get("tier", "raw")
            for pt in seg.get("points", ()):
                ts = pt.get("ts", 0.0)
                if ts < start or ts > end:
                    continue
                series = pt.get("m", {}).get(name)
                if not series:
                    continue
                for lk, val in series.items():
                    ent = out.setdefault(
                        (proc, lk),
                        {"meta": meta, "points": []},
                    )
                    ent["points"].append((ts, val, tier))
        for ent in out.values():
            ent["points"].sort(key=lambda p: p[0])
        return out

    @staticmethod
    def _scalar(meta: dict, val, tier: str) -> float | None:
        mtype = meta.get("type")
        if mtype == "histogram":
            return None
        if tier == "ds":
            if isinstance(val, dict):
                v = val.get("mean", val.get("last"))
                return None if v is None else float(v)
            return None
        try:
            return float(val)
        except (TypeError, ValueError):
            return None

    # -- queries --------------------------------------------------------

    def range(
        self, name: str, *, window_s: float, now: float | None = None
    ) -> list[tuple[float, float]]:
        """All scalar points of ``name`` inside the window, merged
        across processes and label sets, time-sorted. Gauges and
        counters; histograms have no single scalar (use
        :meth:`hist_mean` / :meth:`hist_percentile`)."""
        if now is None:
            now = self._clock()
        pts: list[tuple[float, float]] = []
        for ent in self._series(name, now - window_s, now).values():
            for ts, val, tier in ent["points"]:
                v = self._scalar(ent["meta"], val, tier)
                if v is not None:
                    pts.append((ts, v))
        pts.sort(key=lambda p: p[0])
        return pts

    def gauge_last(
        self, name: str, *, window_s: float, now: float | None = None
    ) -> float | None:
        """Latest value per (proc, labels) series combined by the
        family's declared agg (mirrors the merge semantics of the
        instantaneous plane)."""
        if now is None:
            now = self._clock()
        lasts: list[float] = []
        agg = "sum"
        for ent in self._series(name, now - window_s, now).values():
            agg = ent["meta"].get("agg", "sum")
            pts = ent["points"]
            if not pts:
                continue
            ts, val, tier = pts[-1]
            if tier == "ds" and isinstance(val, dict):
                val = val.get("last", val.get("mean"))
            if val is None:
                continue
            try:
                lasts.append(float(val))
            except (TypeError, ValueError):
                continue
        if not lasts:
            return None
        if agg == "max":
            return max(lasts)
        if agg == "min":
            return min(lasts)
        if agg == "last":
            return lasts[-1]
        return sum(lasts)

    @staticmethod
    def _cum_delta(points: list, pick) -> float:
        """Reset-tolerant delta over one series of cumulative values:
        a drop means the process restarted from zero, so the new
        cumulative value IS the post-reset delta."""
        delta = 0.0
        prev = None
        for _ts, val, tier in points:
            v = pick(val, tier)
            if v is None:
                continue
            if prev is None:
                prev = v
                continue
            delta += (v - prev) if v >= prev else v
            prev = v
        return delta

    def counter_delta(
        self, name: str, *, window_s: float, now: float | None = None
    ) -> float | None:
        if now is None:
            now = self._clock()

        def pick(val, tier):
            if tier == "ds" and isinstance(val, dict):
                val = val.get("last")
            try:
                return float(val)
            except (TypeError, ValueError):
                return None

        series = self._series(name, now - window_s, now)
        if not series:
            return None
        return sum(
            self._cum_delta(ent["points"], pick) for ent in series.values()
        )

    def counter_rate(
        self, name: str, *, window_s: float, now: float | None = None
    ) -> float | None:
        d = self.counter_delta(name, window_s=window_s, now=now)
        return None if d is None else d / max(1e-9, window_s)

    def hist_mean(
        self, name: str, *, window_s: float, now: float | None = None
    ) -> float | None:
        """Mean observed value over the window: Σ delta(sum) over
        Σ delta(count) across all series."""
        if now is None:
            now = self._clock()
        d_count = d_sum = 0.0
        found = False
        for ent in self._series(name, now - window_s, now).values():
            if ent["meta"].get("type") != "histogram":
                continue
            found = True
            d_count += self._cum_delta(
                ent["points"],
                lambda v, t: float(v.get("count", 0))
                if isinstance(v, dict) else None,
            )
            d_sum += self._cum_delta(
                ent["points"],
                lambda v, t: float(v.get("sum", 0.0))
                if isinstance(v, dict) else None,
            )
        if not found or d_count <= 0:
            return None
        return d_sum / d_count

    def hist_counts(
        self, name: str, *, window_s: float, now: float | None = None
    ) -> tuple[tuple[float, ...], list[float], float] | None:
        """``(buckets, cumulative-count deltas, total delta)`` over the
        window (raw tier only — the ds tier drops buckets by design).
        The SLO monitor's over-threshold math and :meth:`hist_percentile`
        both stand on this."""
        if now is None:
            now = self._clock()
        buckets: tuple[float, ...] | None = None
        deltas: list[float] | None = None
        total = 0.0
        for ent in self._series(name, now - window_s, now).values():
            meta = ent["meta"]
            if meta.get("type") != "histogram":
                continue
            bks = tuple(meta.get("buckets") or ())
            if not bks:
                continue
            if buckets is None:
                buckets = bks
                deltas = [0.0] * len(bks)
            if bks != buckets:
                continue
            for i in range(len(bks)):
                deltas[i] += self._cum_delta(
                    ent["points"],
                    lambda v, t, i=i: float(v["counts"][i])
                    if isinstance(v, dict) and len(v.get("counts") or ()) > i
                    else None,
                )
            total += self._cum_delta(
                ent["points"],
                lambda v, t: float(v.get("count", 0))
                if isinstance(v, dict) else None,
            )
        if buckets is None or deltas is None:
            return None
        return buckets, deltas, total

    def hist_percentile(
        self,
        name: str,
        q: float,
        *,
        window_s: float,
        now: float | None = None,
    ) -> float | None:
        got = self.hist_counts(name, window_s=window_s, now=now)
        if got is None:
            return None
        buckets, deltas, total = got
        if total <= 0:
            return None
        target = max(0.0, min(1.0, q)) * total
        for le, c in zip(buckets, deltas):
            if c >= target:
                return le
        return buckets[-1]

    # -- surface for the incident bundle / CLI --------------------------

    def procs(self) -> list[str]:
        return sorted({str(s.get("proc", "?")) for s in self._segments()})

    def families(self) -> list[str]:
        fams: set[str] = set()
        for seg in self._segments():
            fams.update(seg.get("meta", {}).keys())
        return sorted(fams)

    def slice(
        self, *, window_s: float, now: float | None = None
    ) -> dict:
        """Everything in the window, as one JSON-able dict — the
        ``timeseries.json`` payload of an incident bundle."""
        if now is None:
            now = self._clock()
        start = now - window_s
        out: dict = {"start_ts": start, "end_ts": now, "procs": {}}
        for seg in self._segments():
            seg_start = seg.get("start_ts") or 0.0
            seg_end = seg.get("end_ts") or seg_start
            if seg_end < start or seg_start > now:
                continue
            proc = str(seg.get("proc", "?"))
            ent = out["procs"].setdefault(
                proc, {"meta": {}, "points": []}
            )
            ent["meta"].update(seg.get("meta", {}))
            for pt in seg.get("points", ()):
                ts = pt.get("ts", 0.0)
                if start <= ts <= now:
                    ent["points"].append(pt)
        for ent in out["procs"].values():
            ent["points"].sort(key=lambda p: p.get("ts", 0.0))
        return out


# ----------------------------------------------------------------------
# env plumbing


def writer_from_env(
    *, proc: str, clock=time.time
) -> HistoryWriter | None:
    """The per-process writer ``DCT_TS_DIR`` arms, or None. Every
    SnapshotPublisher asks here, so arming the store is one env var —
    no per-call-site wiring."""
    from dct_tpu.config import ObservabilityConfig

    obs = ObservabilityConfig.from_env()
    if not obs.ts_dir:
        return None
    try:
        return HistoryWriter(
            obs.ts_dir,
            proc=proc,
            families=obs.ts_families,
            seg_points=obs.ts_seg_points,
            seg_s=obs.ts_seg_s,
            flush_s=obs.ts_flush_s,
            retention_s=obs.ts_retention_s,
            downsample_s=obs.ts_downsample_s,
            ds_res_s=obs.ts_ds_res_s,
            clock=clock,
        )
    except Exception:  # noqa: BLE001 — telemetry never fails the run
        return None
