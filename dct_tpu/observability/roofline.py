"""Roofline introspection: XLA cost-model accounting per compiled program.

The platform could see *wall-clock* (goodput ledger, spans, SLO burn)
but not *hardware efficiency*: MFU came from a hand-rolled analytic
estimator covering one model family, and nothing knew whether a program
was compute- or memory-bound. This module grounds efficiency accounting
in the compiler's own cost model instead:

- **Analytic cost** — every :class:`~dct_tpu.compilecache.CachedProgram`
  (the trainer's fused epoch programs, the serving tier's jitted scorer,
  each MPMD stage program) captures ``compiled.cost_analysis()`` FLOPs /
  bytes-accessed and ``compiled.memory_analysis()`` HBM numbers at
  compile time (:func:`analyze_compiled`; the store-disabled path uses
  the pre-compile :func:`analyze_lowered` — a trace, no compile).
- **Measured windows** — the goodput ledger already times every
  dispatch per program key (``GoodputLedger.dispatch_stats``).
- **The join** (:func:`program_report`): analytic FLOPs x call count /
  measured seconds = achieved FLOPs/s; over the chip peak that is
  **live per-program MFU**; FLOPs / bytes accessed is the arithmetic
  intensity, and against the machine's FLOPs/byte ridge point it
  classifies the program **compute-bound** vs **memory-bound** — the
  roofline placement, per program, from artifacts instead of guesses.

Published three ways: ``roofline.program`` events at capture time and a
run-end ``roofline.report`` per program, ``dct_program_*`` gauge
families on the metrics plane (flops, bytes accessed, HBM peak, MFU,
arithmetic intensity), and the run inspector's "Roofline" section.

Cost-model caveats (documented in docs/OBSERVABILITY.md §roofline): XLA
counts algebraic FLOPs of the *optimized* HLO — fusion can eliminate
work, convolutions/matmuls count multiply-adds as 2 — so MFU here is a
*model*-FLOPs utilization consistent with the literature's convention,
not a hardware counter. Bytes accessed is the cost model's estimate of
operand traffic, not a DRAM counter. Both are exact enough to rank
programs and catch regressions, which is what this plane is for.
"""

from __future__ import annotations

import os
import threading

#: Best-effort HBM bandwidth per chip, bytes/sec, by device-kind
#: substring (same table style as profiling.chip_peak_flops). Public
#: figures: v2 700, v3 900, v4 1228, v5e 819, v5p 2765, v6e 1640 GB/s.
_HBM_GBPS_TABLE = (
    ("v6", 1640.0), ("v5p", 2765.0), ("v5 lite", 819.0), ("v5e", 819.0),
    ("v4", 1228.0), ("v3", 900.0), ("v2", 700.0),
)


def roofline_enabled() -> bool:
    """Master switch (``DCT_ROOFLINE``, default on). The capture cost is
    one ``cost_analysis`` call on the already-compiled executable — or,
    on the store-disabled path, one extra jit *trace* per program."""
    v = os.environ.get("DCT_ROOFLINE", "1").strip().lower()
    return v not in ("0", "false", "no", "off")


def chip_hbm_bytes_per_sec() -> float | None:
    """Best-effort HBM bandwidth per chip from the device kind (None
    when unknown — e.g. the CPU rig). Override with ``DCT_HBM_GBPS``."""
    env = os.environ.get("DCT_HBM_GBPS")
    if env:
        try:
            return float(env) * 1e9
        except ValueError:
            return None
    try:
        import jax

        kind = jax.devices()[0].device_kind.lower()
    except Exception:  # noqa: BLE001 — no backend = no bandwidth table
        return None
    for pat, gbps in _HBM_GBPS_TABLE:
        if pat in kind:
            return gbps * 1e9
    return None


def _normalize_cost(raw, source: str) -> dict | None:
    """One ``cost_analysis()`` result (dict, or list of per-device
    dicts) -> the normalized record. None when nothing usable."""
    if isinstance(raw, (list, tuple)):
        raw = raw[0] if raw else None
    if not isinstance(raw, dict):
        return None
    out = {"source": source}
    flops = raw.get("flops")
    if isinstance(flops, (int, float)) and flops >= 0:
        out["flops"] = float(flops)
    ba = raw.get("bytes accessed")
    if isinstance(ba, (int, float)) and ba >= 0:
        out["bytes_accessed"] = float(ba)
    tr = raw.get("transcendentals")
    if isinstance(tr, (int, float)) and tr > 0:
        out["transcendentals"] = float(tr)
    return out if len(out) > 1 else None


#: dtype -> roofline short name (anything unlisted keeps its full name).
_DTYPE_SHORT = {
    "float64": "f64", "float32": "f32", "float16": "f16",
    "bfloat16": "bf16", "int64": "i64", "int32": "i32", "int16": "i16",
    "int8": "i8", "uint8": "u8", "uint16": "u16", "uint32": "u32",
    "bool": "b1",
}


def dtype_summary(args) -> str:
    """The program's parameter/activation dtypes as dispatched: sorted
    unique short names of the call's array leaves, comma-joined — the
    roofline record's ``dtypes`` stamp, so a bf16-vs-f32
    ``bytes_accessed`` delta is attributable on one scrape. When dtype
    rules are active (``DCT_DTYPE_RULES``) the dispatched args are
    still the f32 masters (the cast happens inside the traced body), so
    the active rules digest is appended (``+rules:<digest>``) to keep
    the stamp honest about the compute precision."""
    names: set = set()
    try:
        import jax

        for leaf in jax.tree_util.tree_leaves(args):
            dt = getattr(leaf, "dtype", None)
            if dt is not None:
                names.add(_DTYPE_SHORT.get(str(dt), str(dt)))
    except Exception:  # noqa: BLE001 — accounting never fails a program
        return ""
    summary = ",".join(sorted(names))
    try:
        from dct_tpu.parallel.sharding_rules import (
            dtype_rules, dtype_rules_digest,
        )

        if dtype_rules():
            summary += f"+rules:{dtype_rules_digest()}"
    except Exception:  # noqa: BLE001 — a malformed env must not bite here
        pass
    return summary


def analyze_lowered(lowered) -> dict | None:
    """Cost analysis of a ``jax.stages.Lowered`` (pre-compile HLO): the
    capture path for programs the AOT store never compiles explicitly
    (store disabled — the default). No ``memory_analysis`` exists before
    compilation, so HBM fields are absent here. Never raises."""
    try:
        return _normalize_cost(lowered.cost_analysis(), "lowered")
    except Exception:  # noqa: BLE001 — accounting never fails a program
        return None


def analyze_compiled(compiled) -> dict | None:
    """Cost + memory analysis of a ``jax.stages.Compiled`` (or a
    deserialized AOT executable). Adds the HBM accounting: argument /
    output / temp / generated-code bytes and their peak-resident sum
    (aliased donation bytes subtracted — a donated input is not resident
    twice). Never raises; partial results are kept."""
    try:
        out = _normalize_cost(compiled.cost_analysis(), "compiled")
    except Exception:  # noqa: BLE001
        out = None
    try:
        ma = compiled.memory_analysis()
    except Exception:  # noqa: BLE001
        ma = None
    if ma is not None:
        mem = {}
        for field, key in (
            ("argument_size_in_bytes", "argument_bytes"),
            ("output_size_in_bytes", "output_bytes"),
            ("temp_size_in_bytes", "temp_bytes"),
            ("alias_size_in_bytes", "alias_bytes"),
            ("generated_code_size_in_bytes", "generated_code_bytes"),
        ):
            v = getattr(ma, field, None)
            if isinstance(v, int) and v >= 0:
                mem[key] = v
        if mem:
            peak = (
                mem.get("argument_bytes", 0)
                + mem.get("output_bytes", 0)
                + mem.get("temp_bytes", 0)
                - mem.get("alias_bytes", 0)
            )
            mem["hbm_peak_bytes"] = max(0, peak)
            out = {**(out or {"source": "compiled"}), **mem}
    return out


# ----------------------------------------------------------------------
# Host peak measurement: the bench's "never null" fallback. On rigs
# whose device kind has no peak-FLOPs table entry (the CPU fallback
# rig), MFU would stay null forever — exactly the staleness this plane
# retires. A dense f32 GEMM through the platform BLAS is the honest
# local peak: the best the hardware demonstrably sustains on the
# roofline's compute axis.

_PEAK_LOCK = threading.Lock()
_PEAK_CACHE: float | None = None


def measure_host_peak_flops(n: int = 512, reps: int = 5) -> float:
    """Measured dense-GEMM FLOPs/sec on THIS host (numpy/BLAS, float32),
    cached per process. ~tens of ms once."""
    global _PEAK_CACHE
    with _PEAK_LOCK:
        if _PEAK_CACHE is not None:
            return _PEAK_CACHE
        import time

        import numpy as np

        a = np.ones((n, n), np.float32)
        b = np.ones((n, n), np.float32)
        a @ b  # warm the BLAS thread pool
        best = float("inf")
        for _ in range(max(1, reps)):
            t0 = time.perf_counter()
            a @ b
            best = min(best, time.perf_counter() - t0)
        _PEAK_CACHE = 2.0 * n * n * n / max(best, 1e-9)
        return _PEAK_CACHE


def resolve_peak_flops() -> tuple[float | None, str]:
    """(peak FLOPs/sec per chip, source): the device table /
    ``DCT_PEAK_TFLOPS`` override when known, else the measured host GEMM
    peak — so a locally-computed MFU always has a denominator."""
    from dct_tpu.utils.profiling import chip_peak_flops

    peak = chip_peak_flops()
    if peak:
        source = (
            "DCT_PEAK_TFLOPS" if os.environ.get("DCT_PEAK_TFLOPS")
            else "device_table"
        )
        return peak, source
    try:
        return measure_host_peak_flops(), "measured_gemm"
    except Exception:  # noqa: BLE001 — no numpy = no denominator
        return None, "unknown"


# ----------------------------------------------------------------------
# The join: analytic cost x measured dispatch windows.


def classify(intensity: float | None, ridge: float | None) -> str:
    """Roofline placement: arithmetic intensity (FLOPs/byte) against the
    machine's ridge point (peak FLOPs/s over HBM bytes/s). Below the
    ridge the program cannot reach peak no matter how good the kernels
    are — it is bandwidth-bound."""
    if intensity is None or ridge is None:
        return "unknown"
    return "compute" if intensity >= ridge else "memory"


def program_report(
    costs: dict,
    dispatch_stats: dict | None = None,
    *,
    n_chips: int = 1,
    peak_flops: float | None = None,
    hbm_bytes_per_s: float | None = None,
    family: str = "",
    config_hash: str = "",
    mesh: str = "",
) -> list[dict]:
    """Join per-program analytic costs (``ExecutableStore.costs``) with
    the ledger's measured non-compile dispatch windows
    (``GoodputLedger.dispatch_stats``: key -> [count, seconds]) into one
    record per program: analytic FLOPs/bytes/HBM, call count + measured
    seconds, achieved FLOPs/s, **MFU**, arithmetic intensity, and the
    compute/memory-bound classification. Programs with no measured
    window (a scorer analyzed but never steadily dispatched) still get
    their analytic record — ``mfu`` stays absent, never wrong."""
    if peak_flops is None:
        from dct_tpu.utils.profiling import chip_peak_flops

        peak_flops = chip_peak_flops()
    if hbm_bytes_per_s is None:
        hbm_bytes_per_s = chip_hbm_bytes_per_sec()
    ridge = (
        peak_flops / hbm_bytes_per_s
        if peak_flops and hbm_bytes_per_s else None
    )
    out = []
    for program in sorted(costs):
        cost = costs[program]
        if not cost:
            continue
        rec = {
            "program": program,
            "family": family,
            "config_hash": config_hash,
            "mesh": mesh,
            **cost,
        }
        flops = cost.get("flops")
        ba = cost.get("bytes_accessed")
        intensity = (flops / ba) if flops and ba else None
        if intensity is not None:
            rec["arithmetic_intensity"] = round(intensity, 3)
        rec["bound"] = classify(intensity, ridge)
        stats = (dispatch_stats or {}).get(program)
        if stats:
            count, seconds = int(stats[0]), float(stats[1])
            rec["calls"] = count
            rec["seconds"] = round(seconds, 6)
            if flops and seconds > 0:
                achieved = flops * count / seconds
                rec["achieved_flops_per_s"] = round(achieved, 3)
                if peak_flops:
                    rec["mfu"] = round(
                        achieved / max(n_chips, 1) / peak_flops, 6
                    )
            if ba and seconds > 0 and hbm_bytes_per_s:
                rec["hbm_util"] = round(
                    ba * count / seconds
                    / max(n_chips, 1) / hbm_bytes_per_s, 6,
                )
        out.append(rec)
    return out


# ----------------------------------------------------------------------
# Metrics-plane families.


def add_roofline_metrics(reg, report: list[dict], labels: dict) -> None:
    """Stamp a :func:`program_report` into ``dct_program_*`` gauge
    families on ``reg`` (a MetricsRegistry). ``labels`` is the caller's
    base label set (run_id etc.); each series adds its program identity
    labels, and the MFU/intensity gauges carry the roofline ``bound``."""
    flops_g = reg.gauge(
        "dct_program_flops",
        "XLA cost-model FLOPs per dispatch of this compiled program.",
        agg="last",
    )
    bytes_g = reg.gauge(
        "dct_program_bytes_accessed",
        "XLA cost-model bytes accessed per dispatch.", agg="last",
    )
    hbm_g = reg.gauge(
        "dct_program_hbm_peak_bytes",
        "Peak resident HBM of the compiled program "
        "(arguments + outputs + temps - aliased).", agg="last",
    )
    mfu_g = reg.gauge(
        "dct_program_mfu",
        "Live model-FLOPs utilization: cost-model FLOPs x calls over "
        "measured dispatch seconds, per chip, over peak.", agg="last",
    )
    int_g = reg.gauge(
        "dct_program_arithmetic_intensity",
        "Cost-model FLOPs per byte accessed (roofline x-axis).",
        agg="last",
    )
    for rec in report:
        wl = {
            **labels,
            "program": rec.get("program", "?"),
            "family": rec.get("family", ""),
            "mesh": rec.get("mesh", ""),
        }
        # Precision attribution: one scrape separates the bf16
        # program's bytes from its f32 twin's. Unstamped records keep
        # the pre-dtype label set so their series identity is stable.
        if rec.get("dtypes"):
            wl["dtype"] = rec["dtypes"]
        if rec.get("flops") is not None:
            flops_g.set(rec["flops"], wl)
        if rec.get("bytes_accessed") is not None:
            bytes_g.set(rec["bytes_accessed"], wl)
        if rec.get("hbm_peak_bytes") is not None:
            hbm_g.set(rec["hbm_peak_bytes"], wl)
        bwl = {**wl, "bound": rec.get("bound", "unknown")}
        if rec.get("mfu") is not None:
            mfu_g.set(rec["mfu"], bwl)
        if rec.get("arithmetic_intensity") is not None:
            int_g.set(rec["arithmetic_intensity"], bwl)
