"""Run-inspector CLI: join events + spans + goodput + heartbeats into a
human-readable cycle report and write the Perfetto trace export.

Usage::

    python -m dct_tpu.observability.inspect <run_dir> [--run-id ID]
        [--out trace.json] [--no-trace]

``run_dir`` is any directory holding a run's observability artifacts —
the events dir itself, or a parent containing ``events.jsonl``,
``spans/*.jsonl`` and ``rank_*.json`` heartbeat files anywhere below it
(the layouts the trainer/launcher produce by default). The report:

1. resolves the run-correlation ID (``--run-id`` pins one; otherwise
   the newest ID seen in the event log);
2. reconstructs the cycle timeline: launch window, per-rank training
   windows, per-epoch metrics, checkpoint saves, deploy stages;
3. names every rank's final heartbeat state and progress;
4. prints the goodput/badput breakdown from the run-end summary event;
5. lists health incidents (``health.*`` events);
6. merges all span files into ``trace.json`` (Chrome-trace-event JSON,
   Perfetto-loadable) and prints how to open it.

Everything is read-only over the artifacts; missing surfaces degrade to
"(none found)" lines, never errors — the inspector must work on partial
runs, which is exactly when an operator reaches for it.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from dct_tpu.observability.trace_export import export_run


def _find_files(root: str, name_filter) -> list[str]:
    out = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames.sort()
        for fn in sorted(filenames):
            if name_filter(fn, dirpath):
                out.append(os.path.join(dirpath, fn))
    return out


def load_events(run_dir: str) -> list[dict]:
    from dct_tpu.observability.trace_export import read_jsonl

    recs = []
    for path in _find_files(
        run_dir, lambda fn, d: fn == "events.jsonl"
    ):
        recs.extend(read_jsonl(path, require_key="event"))
    recs.sort(key=lambda r: r.get("ts", 0.0))
    return recs


def load_heartbeats(run_dir: str) -> list[dict]:
    out = []
    for path in _find_files(
        run_dir,
        lambda fn, d: fn.startswith("rank_") and fn.endswith(".json"),
    ):
        try:
            with open(path) as f:
                rec = json.load(f)
        except (OSError, ValueError):
            continue
        if isinstance(rec, dict) and "rank" in rec and "phase" in rec:
            out.append(rec)
    out.sort(key=lambda r: int(r.get("rank", 0)))
    return out


def pick_run_id(events: list[dict], explicit: str | None) -> str | None:
    if explicit:
        return explicit
    latest: str | None = None
    latest_ts = -1.0
    for r in events:
        rid = r.get("run_id")
        if rid and r.get("ts", 0.0) >= latest_ts:
            latest, latest_ts = rid, r.get("ts", 0.0)
    return latest


def _fmt_ts(ts: float | None, t0: float | None) -> str:
    if ts is None or t0 is None:
        return "      ?"
    return f"+{ts - t0:7.2f}s"


def _fmt_num(v) -> str:
    if isinstance(v, (int, float)):
        return f"{v:.4f}" if isinstance(v, float) else str(v)
    return str(v)


def load_bench_record(run_dir: str) -> tuple[str, dict] | None:
    """Newest ``BENCH*.json`` under ``run_dir`` (rounds sort by name),
    or None. The cycle report surfaces its MFU — including the
    ``scaled_mfu_stale_reason`` a dead relay stamps — instead of
    silently omitting the number an operator will otherwise chase."""
    paths = _find_files(
        run_dir,
        lambda fn, d: fn.startswith("BENCH") and fn.endswith(".json"),
    )
    if not paths:
        return None
    path = sorted(paths, key=lambda p: os.path.basename(p))[-1]
    try:
        with open(path) as f:
            rec = json.load(f)
    except (OSError, ValueError):
        return os.path.basename(path), {}
    return os.path.basename(path), rec if isinstance(rec, dict) else {}


def _bench_mfu_lines(bench: tuple[str, dict] | None) -> list[str]:
    lines = ["", "Bench MFU:"]
    if bench is None:
        lines.append("  (no BENCH*.json record in the run dir)")
        return lines
    name, rec = bench
    parsed = rec.get("parsed")
    if not isinstance(parsed, dict):
        lines.append(
            f"  {name}: record present but unparsable (stdout "
            "overflowed the driver tail?) — no MFU to report"
        )
        return lines
    mfu = parsed.get("mfu")
    stale = parsed.get("scaled_mfu_stale")
    reason = parsed.get("scaled_mfu_stale_reason")
    if mfu is not None:
        line = f"  {name}: mfu={_fmt_num(mfu)}"
        source = parsed.get("mfu_source")
        if source:
            line += f" ({source})"
        if stale:
            # Post-roofline records: the headline is local, so staleness
            # only taints the scaled stanza's on-chip number.
            which = (
                "scaled on-chip MFU STALE"
                if source == "cost_model_local" else "STALE"
            )
            line += f" [{which}: {reason or 'reason unrecorded'}]"
        lines.append(line)
    elif stale or reason:
        why = reason or "no reason recorded"
        lines.append(
            f"  {name}: scaled MFU stale — {why} "
            "(prior rounds' numbers do not transfer)"
        )
    else:
        lines.append(
            f"  {name}: no MFU in the record "
            f"(platform={parsed.get('platform')}: CPU rounds carry "
            "no on-chip MFU)"
        )
    return lines


def build_report(
    events: list[dict],
    heartbeats: list[dict],
    spans: list[dict],
    run_id: str | None,
    trace_path: str | None,
    bench: tuple[str, dict] | None = None,
    lineage: list[dict] | None = None,
    incidents: list[dict] | None = None,
) -> str:
    """The cycle report as one printable string (pure function of the
    artifacts — unit-testable without capturing stdout)."""
    lines: list[str] = []
    ev = [e for e in events if run_id is None or e.get("run_id") == run_id]
    hb = [
        h for h in heartbeats
        if run_id is None or h.get("run_id") in (None, run_id)
    ]
    sp = [s for s in spans if run_id is None or s.get("trace_id") == run_id]
    t0 = ev[0]["ts"] if ev else (sp[0]["t0"] if sp else None)
    lines.append("=" * 72)
    lines.append(f"dct_tpu run inspector — run_id {run_id or '(unknown)'}")
    lines.append("=" * 72)
    lines.append(
        f"events: {len(ev)}   spans: {len(sp)}   "
        f"heartbeats: {len(hb)} rank file(s)"
    )

    # -- cycle timeline ------------------------------------------------
    lines.append("")
    lines.append("Cycle timeline (selected events):")
    interesting = {
        "launch_start", "launch_end", "fit_start", "fit_end",
        "fit_failed", "goodput_summary", "best_saved",
        "resume_state_saved", "run_start", "run_end",
        "deploy_new_slot", "shadow", "canary", "full_rollout",
        "rank_exit", "rank_stalled", "rank_missing",
    }
    # The cycle is no longer trainer-centric: serving, gating, SLO and
    # compile-accounting events belong on the same timeline (serve.*
    # stays OFF it — per-flush events would drown the launch story; the
    # Serving section below summarizes them instead).
    interesting_prefixes = (
        "health.", "deploy.", "slo.", "compile.", "restart.",
        # Always-on loop actors (docs/CONTINUOUS.md): rounds, ingested
        # generations and mid-run promotions are cycle landmarks.
        "loop.", "ingest.",
        # Multi-tenant scheduler (docs/SCHEDULER.md): leases, preempts
        # and tenant lifecycle are session landmarks.
        "sched.", "tenant.",
        # MPMD pipeline trainer (docs/PARALLELISM.md §MPMD): stage
        # lifecycle, cross-topology pivots, and transfer faults are
        # session landmarks (per-epoch mpmd.step_report stays off the
        # timeline — the MPMD section below summarizes it).
        "mpmd.",
        # Flight-recorder captures (docs/OBSERVABILITY.md §roofline):
        # an operator-triggered mid-run trace is a timeline landmark.
        # roofline.* stays off it — run-end batch records the Roofline
        # section below summarizes.
        "profile.",
        # Telemetry history plane (docs/OBSERVABILITY.md §9): anomaly
        # edges and assembled incident bundles are exactly the
        # landmarks an operator reads the timeline for.
        "anomaly.", "incident.",
        # Elastic serving (docs/SERVING.md §elasticity): pool deaths /
        # respawns / circuit-breaks, scale steps and (throttled) shed
        # episodes are rare and load-bearing — unlike per-flush
        # serve.batch_* they belong on the landmark timeline.
        "serve.pool_", "autoscale.", "admission.",
    )
    shown = 0
    for r in ev:
        name = r.get("event", "?")
        if name not in interesting and not name.startswith(
            interesting_prefixes
        ):
            continue
        if name == "mpmd.step_report":
            continue  # per-epoch; the MPMD section summarizes it
        who = (
            f"rank {r['rank']}" if r.get("rank") is not None else "host"
        )
        extra = ""
        if name == "launch_end":
            extra = f" returncodes={r.get('returncodes')}"
        if name.startswith("health."):
            extra = (
                f" value={r.get('value')} step={r.get('step')}"
                f" halt={r.get('halt')}"
            )
        if name == "deploy.gate":
            extra = (
                f" stage={r.get('stage')} decision={r.get('decision')}"
                f" reason={r.get('reason')}"
            )
        if name.startswith("slo."):
            extra = (
                f" slo={r.get('slo')} burn_fast={r.get('burn_fast')}"
                f" burn_slow={r.get('burn_slow')}"
            )
        if name == "compile.window":
            extra = (
                f" program={r.get('program')} "
                f"seconds={_fmt_num(r.get('seconds'))}"
            )
        if name == "loop.promoted":
            extra = (
                f" generation={r.get('generation')}"
                f" freshness_s={_fmt_num(r.get('freshness_s'))}"
            )
        if name == "ingest.processed":
            extra = (
                f" generation={r.get('generation')} mode={r.get('mode')}"
                f" rows={r.get('rows')}"
            )
        if name in ("sched.grant", "sched.release", "sched.preempt",
                    "tenant.parked"):
            extra = " " + " ".join(
                f"{k}={r[k]}" for k in (
                    "tenant", "wait_s", "waited_s", "outcome", "chip_s",
                    "waiter", "classification",
                )
                if r.get(k) is not None
            )
        lines.append(
            f"  {_fmt_ts(r.get('ts'), t0)}  "
            f"{r.get('component', '?'):10s} {who:8s} {name}{extra}"
        )
        shown += 1
    if not shown:
        lines.append("  (none found)")

    # -- per-epoch metrics ---------------------------------------------
    epochs = [r for r in ev if r.get("event") == "epoch_end"]
    lines.append("")
    lines.append("Epochs:")
    if epochs:
        for r in epochs:
            lines.append(
                f"  epoch {r.get('epoch')}: "
                f"train_loss={_fmt_num(r.get('train_loss'))} "
                f"val_loss={_fmt_num(r.get('val_loss'))} "
                f"val_acc={_fmt_num(r.get('val_acc'))} "
                f"goodput={_fmt_num(r.get('goodput_fraction'))}"
            )
    else:
        lines.append("  (none found)")

    # -- ranks ---------------------------------------------------------
    lines.append("")
    lines.append("Ranks (final heartbeat):")
    if hb:
        for h in hb:
            lines.append(
                f"  rank {h.get('rank')}: phase={h.get('phase')} "
                f"epoch={h.get('epoch')} step={h.get('step')} "
                f"pid={h.get('pid')}"
            )
    else:
        span_ranks = sorted(
            {s.get("rank") for s in sp if s.get("rank") is not None}
        )
        if span_ranks:
            for r in span_ranks:
                n = sum(1 for s in sp if s.get("rank") == r)
                lines.append(f"  rank {r}: {n} span(s), no heartbeat file")
        else:
            lines.append("  (none found)")

    # -- goodput -------------------------------------------------------
    lines.append("")
    lines.append("Goodput:")
    summaries = [r for r in ev if r.get("event") == "goodput_summary"]
    if summaries:
        s = summaries[-1]
        lines.append(
            f"  wall {_fmt_num(s.get('wall_seconds'))}s, "
            f"goodput_fraction {_fmt_num(s.get('goodput_fraction'))}"
        )
        for cat, sec in sorted((s.get("categories") or {}).items()):
            lines.append(f"    {cat:18s} {_fmt_num(sec)}s")
        ua = s.get("unattributed_seconds")
        if ua is not None:
            lines.append(f"    {'unattributed':18s} {_fmt_num(ua)}s")
    else:
        lines.append("  (no goodput_summary event)")

    # -- health --------------------------------------------------------
    lines.append("")
    lines.append("Health:")
    health = [
        r for r in ev if str(r.get("event", "")).startswith("health.")
    ]
    if health:
        for r in health:
            lines.append(
                f"  {r['event']}: value={r.get('value')} "
                f"step={r.get('step')} epoch={r.get('epoch')} "
                f"halt={r.get('halt')}"
            )
    else:
        lines.append("  (no health events — clean run)")

    # -- serving (micro-batcher + request-path events) ----------------
    lines.append("")
    lines.append("Serving:")
    flushes = [r for r in ev if r.get("event") == "serve.batch_flush"]
    berrors = [r for r in ev if r.get("event") == "serve.batch_error"]
    if flushes or berrors:
        rows = sum(int(r.get("rows") or 0) for r in flushes)
        reqs = sum(int(r.get("requests") or 0) for r in flushes)
        lines.append(
            f"  batch flushes: {len(flushes)} "
            f"({reqs} requests merged into {rows} rows"
            + (
                f", {reqs / len(flushes):.1f} req/flush"
                if flushes else ""
            )
            + f"); flush errors: {len(berrors)}"
        )
    else:
        lines.append(
            "  (no serve.* events — traffic untraced or none served; "
            "serving telemetry is opt-in via DCT_SERVE_TRACE)"
        )
    sheds = [r for r in ev if r.get("event") == "admission.shed"]
    scales = [
        r for r in ev
        if str(r.get("event", "")).startswith("autoscale.scale_")
    ]
    heals = [
        r for r in ev if r.get("event") == "serve.pool_respawn"
    ]
    if sheds or scales or heals:
        shed_total = sum(int(r.get("count") or 0) for r in sheds)
        ups = sum(
            1 for r in scales if r.get("event") == "autoscale.scale_up"
        )
        lines.append(
            f"  elasticity: {shed_total} shed "
            f"({len(sheds)} admission.shed records), "
            f"{ups} scale-up / {len(scales) - ups} scale-down, "
            f"{len(heals)} respawned workers"
        )

    # -- always-on loop -----------------------------------------------
    loop_ev = [
        r for r in ev
        if str(r.get("event", "")).startswith(("loop.", "ingest."))
    ]
    if loop_ev:
        lines.append("")
        lines.append("Continuous loop:")
        rounds = [r for r in loop_ev if r.get("event") == "loop.round"]
        ingests = [
            r for r in loop_ev if r.get("event") == "ingest.processed"
        ]
        promos = [r for r in loop_ev if r.get("event") == "loop.promoted"]
        held = [
            r for r in loop_ev if r.get("event") == "loop.promotion_held"
        ]
        lines.append(
            f"  rounds: {len(rounds)}; generations ingested: "
            f"{len(ingests)}; promotions: {len(promos)}; held: {len(held)}"
        )
        fresh = [
            r.get("freshness_s") for r in promos
            if isinstance(r.get("freshness_s"), (int, float))
        ]
        if fresh:
            lines.append(
                f"  freshness_s: last={_fmt_num(fresh[-1])} "
                f"mean={_fmt_num(sum(fresh) / len(fresh))} "
                f"worst={_fmt_num(max(fresh))}"
            )
        stops = [r for r in loop_ev if r.get("event") == "loop.stop"]
        if stops:
            s = stops[-1]
            lines.append(
                f"  stopped: reason={s.get('reason')} "
                f"goodput={_fmt_num(s.get('goodput'))} "
                f"wall={_fmt_num(s.get('wall_s'))}s"
            )

    # -- multi-tenant scheduler ---------------------------------------
    sched_ev = [
        r for r in ev
        if str(r.get("event", "")).startswith(("sched.", "tenant."))
    ]
    if sched_ev:
        lines.append("")
        lines.append("Tenants:")
        starts = [r for r in sched_ev if r.get("event") == "sched.start"]
        if starts:
            s = starts[-1]
            lines.append(
                f"  session: {len(s.get('tenants') or [])} tenant(s), "
                f"concurrent={s.get('concurrent')} "
                f"preempt_wait_s={s.get('preempt_wait_s')} "
                f"shared_cache={s.get('shared_cache')}"
            )
        names = sorted({
            r.get("tenant") for r in sched_ev if r.get("tenant")
        })
        for name in names:
            mine = [r for r in sched_ev if r.get("tenant") == name]
            grants = [r for r in mine if r["event"] == "sched.grant"]
            rels = [r for r in mine if r["event"] == "sched.release"]
            chip = sum(float(r.get("chip_s") or 0.0) for r in rels)
            waits = [
                float(r.get("wait_s") or 0.0) for r in grants
            ]
            preempted = sum(
                1 for r in rels if r.get("outcome") == "preempted"
            )
            restarts = sum(int(r.get("restarts") or 0) for r in rels)
            parked = [r for r in mine if r["event"] == "tenant.parked"]
            stops = [r for r in mine if r["event"] == "tenant.stop"]
            line = (
                f"  {name}: leases={len(rels)} "
                f"chip_s={chip:.2f}"
            )
            if waits:
                line += (
                    f" mean_wait_s={sum(waits) / len(waits):.2f}"
                )
            if preempted:
                line += f" preempted={preempted}"
            if restarts:
                line += f" healed_restarts={restarts}"
            if parked:
                line += (
                    f" PARKED ({parked[-1].get('classification')})"
                )
            if stops and stops[-1].get("promotions") is not None:
                line += f" promotions={stops[-1]['promotions']}"
            lines.append(line)
        sstops = [r for r in sched_ev if r.get("event") == "sched.stop"]
        if sstops:
            s = sstops[-1]
            lines.append(
                f"  stopped: reason={s.get('reason')} "
                f"rounds={s.get('total_rounds')} "
                f"preempts={s.get('preempts')} "
                f"wall={_fmt_num(s.get('wall_s'))}s"
            )

    # -- MPMD pipeline ------------------------------------------------
    mpmd_ev = [
        r for r in ev if str(r.get("event", "")).startswith("mpmd.")
    ]
    if mpmd_ev:
        lines.append("")
        lines.append("MPMD pipeline:")
        starts = [
            r for r in mpmd_ev if r.get("event") == "mpmd.stage_start"
        ]
        if starts:
            s = starts[-1]
            lines.append(
                f"  stages: {s.get('n_stages')} "
                f"schedule={s.get('schedule')}"
            )
        reports = [
            r for r in mpmd_ev if r.get("event") == "mpmd.step_report"
        ]
        if reports:
            last = reports[-1]
            lines.append(
                f"  epochs reported: {len(reports)}; last bubble: "
                f"steady={_fmt_num(last.get('steady_bubble'))} "
                f"step={_fmt_num(last.get('step_bubble'))} "
                f"analytic={_fmt_num(last.get('analytic_bubble'))}"
            )
            for st in last.get("stages") or []:
                lines.append(
                    f"    stage {st.get('stage')}: "
                    f"busy={_fmt_num(st.get('busy_s'))}s "
                    f"fill={_fmt_num(st.get('fill_s'))}s "
                    f"steady={_fmt_num(st.get('steady_s'))}s "
                    f"drain={_fmt_num(st.get('drain_s'))}s "
                    f"transfer_wait={_fmt_num(st.get('transfer_wait_s'))}s"
                )
        for r in mpmd_ev:
            if r.get("event") == "mpmd.pivot":
                lines.append(
                    f"  pivot: {r.get('direction')} "
                    f"@epochs={r.get('epochs_completed')}"
                )
            if r.get("event") == "mpmd.transfer_timeout":
                lines.append(
                    f"  TRANSFER TIMEOUT stage {r.get('stage')}: "
                    f"{str(r.get('error'))[:120]}"
                )

    # -- deploy gates / SLO -------------------------------------------
    lines.append("")
    lines.append("Gates & SLO:")
    gates = [r for r in ev if r.get("event") == "deploy.gate"]
    slo_ev = [
        r for r in ev
        if str(r.get("event", "")).startswith("slo.")
    ]
    for r in gates:
        lines.append(
            f"  gate {r.get('stage')}: {r.get('decision')} "
            f"({r.get('reason')})"
        )
    for r in slo_ev:
        lines.append(
            f"  {r['event']}: {r.get('slo')} "
            f"burn fast={_fmt_num(r.get('burn_fast'))} "
            f"slow={_fmt_num(r.get('burn_slow'))}"
        )
    if not gates and not slo_ev:
        lines.append("  (no deploy.gate or slo.* events)")

    # -- compile accounting -------------------------------------------
    lines.append("")
    lines.append("Compile windows (family/config-hash/mesh):")
    compiles = [r for r in ev if r.get("event") == "compile.window"]
    if compiles:
        for r in compiles:
            lines.append(
                f"  {r.get('program')}: {_fmt_num(r.get('seconds'))}s "
                f"x{r.get('count')} "
                f"[{r.get('family')}/{r.get('config_hash')}/"
                f"{r.get('mesh')}] "
                f"cache={r.get('cache', 'disabled')}"
            )
        total = sum(float(r.get("seconds") or 0.0) for r in compiles)
        by_cache: dict[str, int] = {}
        for r in compiles:
            c = str(r.get("cache", "disabled"))
            by_cache[c] = by_cache.get(c, 0) + int(r.get("count") or 1)
        cache_line = " / ".join(
            f"{k} {by_cache[k]}" for k in sorted(by_cache)
        )
        lines.append(
            f"  total compile: {total:.4f}s  (cache: {cache_line})"
        )
    else:
        lines.append("  (no compile.window events)")

    # -- roofline (cost-model efficiency accounting) ------------------
    lines.append("")
    lines.append("Roofline (XLA cost model x measured dispatch):")
    reports = [r for r in ev if r.get("event") == "roofline.report"]
    if not reports:
        # Fall back to the capture-time analytic records so a run that
        # died before the run-end join still shows its program costs.
        reports = [r for r in ev if r.get("event") == "roofline.program"]
    if reports:
        # Newest record per program name wins (a resumed session can
        # report a program twice).
        by_prog: dict[str, dict] = {}
        for r in reports:
            by_prog[str(r.get("program"))] = r
        for name in sorted(by_prog):
            r = by_prog[name]
            parts = [f"  {name}:"]
            if r.get("dtypes"):
                parts.append(f"dtype={r['dtypes']}")
            if r.get("flops") is not None:
                parts.append(f"flops={r['flops']:.4g}")
            if r.get("bytes_accessed") is not None:
                parts.append(f"bytes={r['bytes_accessed']:.4g}")
            if r.get("hbm_peak_bytes") is not None:
                parts.append(f"hbm_peak={r['hbm_peak_bytes']:.4g}")
            if r.get("arithmetic_intensity") is not None:
                parts.append(
                    f"intensity={r['arithmetic_intensity']:.4g}"
                )
            if r.get("mfu") is not None:
                parts.append(f"MFU={r['mfu']:.4g}")
            if r.get("bound") and r["bound"] != "unknown":
                parts.append(f"{r['bound']}-bound")
            lines.append(" ".join(parts))
    else:
        lines.append(
            "  (no roofline.* events — DCT_ROOFLINE=0, or a pre-"
            "roofline run)"
        )
    captures = [
        r for r in ev
        if str(r.get("event", "")).startswith("profile.capture")
    ]
    if captures:
        starts = sum(
            1 for r in captures if r["event"] == "profile.capture_start"
        )
        ends = [
            r for r in captures if r["event"] == "profile.capture_end"
        ]
        line = (
            f"  flight recorder: {starts} capture(s), "
            f"{len(ends)} completed"
        )
        if ends:
            line += f"; last trace: {ends[-1].get('dir')}"
        lines.append(line)

    # -- lineage -------------------------------------------------------
    if lineage:
        from dct_tpu.observability import lineage as _lineage

        lines.append("")
        lines.append("Lineage:")
        graph = _lineage.build_graph(lineage)
        kinds: dict[str, int] = {}
        for recs in graph["nodes"].values():
            kind = recs[-1].get("kind", "?")
            kinds[kind] = kinds.get(kind, 0) + 1
        counted = "  ".join(
            f"{k}={kinds[k]}" for k in sorted(kinds)
        )
        lines.append(
            f"  {len(graph['nodes'])} node(s), "
            f"{len(graph['edges'])} edge(s): {counted}"
        )
        loads = [
            r for r in lineage
            if r.get("type") == "node" and r.get("kind") == "model_load"
        ]
        if loads:
            head = max(loads, key=lambda r: r.get("ts") or 0.0)
            lines.append(f"  serving now: {head['id']}")
            anc = _lineage.ancestors(graph, head["id"])
            order = (
                "deploy_package", "gate_verdict", "eval_report",
                "checkpoint", "dataset_snapshot", "etl_basis",
                "ingest_delta",
            )
            for kind in order:
                hits = [
                    nid for nid in anc
                    if graph["nodes"][nid][-1].get("kind") == kind
                ]
                for nid in sorted(hits):
                    lines.append(f"    <- {nid}")
        lines.append(
            "  (query: python -m dct_tpu.observability.lineage "
            "trace|explain-serving|audit)"
        )

    # -- incidents -----------------------------------------------------
    if incidents:
        lines.append("")
        lines.append("Incidents:")
        for b in incidents:
            parts = [
                f"  {b.get('name', '?')}:",
                f"kind={b.get('kind', '?')}",
                f"signal={b.get('signal', '?')}",
            ]
            if b.get("lineage_id"):
                parts.append(f"serving={b['lineage_id']}")
            files = b.get("files") or []
            if files:
                parts.append(f"files={len(files)}")
                if "profile" in files:
                    parts.append("+profile")
            lines.append(" ".join(parts))
        lines.append(
            "  (inspect: python -m dct_tpu.observability.incident "
            "list|show <bundle>)"
        )

    # -- spans / trace -------------------------------------------------
    lines.append("")
    lines.append("Spans by component:")
    if sp:
        by_comp: dict[str, int] = {}
        for s in sp:
            by_comp[s.get("component", "?")] = (
                by_comp.get(s.get("component", "?"), 0) + 1
            )
        for comp in sorted(by_comp):
            lines.append(f"  {comp:12s} {by_comp[comp]}")
    else:
        lines.append("  (none found)")
    lines.extend(_bench_mfu_lines(bench))
    if trace_path:
        lines.append("")
        lines.append(f"Perfetto trace written: {trace_path}")
        lines.append(
            "  open https://ui.perfetto.dev and drag the file in "
            "(or chrome://tracing > Load)"
        )
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m dct_tpu.observability.inspect",
        description=(
            "Join a run's events, spans, goodput and heartbeats into a "
            "cycle report; write the Perfetto trace export."
        ),
    )
    parser.add_argument("run_dir", help="directory holding the run's logs")
    parser.add_argument(
        "--run-id", default=None,
        help="pin a run-correlation ID (default: newest in the event log)",
    )
    parser.add_argument(
        "--out", default=None,
        help="trace output path (default: <run_dir>/trace.json)",
    )
    parser.add_argument(
        "--no-trace", action="store_true",
        help="report only; skip the trace.json export",
    )
    args = parser.parse_args(argv)
    if not os.path.isdir(args.run_dir):
        print(f"error: {args.run_dir} is not a directory", file=sys.stderr)
        return 2

    events = load_events(args.run_dir)
    heartbeats = load_heartbeats(args.run_dir)
    if not heartbeats:
        # Default layout: heartbeats live in a SIBLING of the events
        # dir (logs/events vs logs/heartbeats), so the documented
        # `inspect logs/events` invocation must still find them.
        sibling = os.path.join(
            os.path.dirname(os.path.normpath(args.run_dir)), "heartbeats"
        )
        if os.path.isdir(sibling):
            heartbeats = load_heartbeats(sibling)
    run_id = pick_run_id(events, args.run_id)
    trace_path = None
    if args.no_trace:
        from dct_tpu.observability.trace_export import read_spans

        spans = read_spans(args.run_dir, trace_id=run_id)
    else:
        trace_path, spans = export_run(
            args.run_dir, out_path=args.out, trace_id=run_id
        )
    from dct_tpu.observability import lineage as _lineage

    lineage_records = _lineage.read_ledger(
        os.path.join(args.run_dir, _lineage.LEDGER_NAME)
    )
    from dct_tpu.observability import incident as _incident

    incident_dir = _incident._cli_dir(None)
    if not os.path.isdir(incident_dir):
        # Default layout: bundles live in a SIBLING of the events dir
        # (logs/events vs logs/incidents), same rule as heartbeats.
        incident_dir = os.path.join(
            os.path.dirname(os.path.normpath(args.run_dir)), "incidents"
        )
    bundles = (
        _incident.list_bundles(incident_dir)
        if os.path.isdir(incident_dir) else []
    )
    print(build_report(
        events, heartbeats, spans, run_id, trace_path,
        bench=load_bench_record(args.run_dir),
        lineage=lineage_records,
        incidents=bundles,
    ))
    return 0


if __name__ == "__main__":
    sys.exit(main())
