"""Cross-process distributed tracing: the platform-level span runtime.

PR 1 gave every record a run-correlation ID; spans add the *timeline*.
One continuous-training cycle is a tree of timed operations spread over
many processes — DAG task -> launcher -> N SPMD ranks (epochs, data
waits, checkpoint saves) -> serving/deploy — and the span runtime
records that tree so the trace exporter (:mod:`trace_export`) can
render the whole cycle as a single Perfetto-loadable timeline,
complementing the per-device ``jax.profiler`` trace with the
platform-level view the TPU-scale literature treats as an operator
surface.

ID contract (extends the ``DCT_RUN_ID`` contract of :mod:`events`):

- ``trace_id`` IS the run-correlation ID — no second identity to join;
- every span has a ``span_id`` (16 hex chars) and a ``parent_id``
  (``None`` for the trace root);
- a parent process exports its current span ID to children via the
  ``DCT_SPAN_ID`` environment variable (:meth:`SpanRecorder.child_env`);
  a child's top-level spans adopt that value as their parent, so the
  launcher's span is the parent of every rank's ``trainer.fit`` span
  across the process boundary.

Storage: per-process JSONL files under one spans directory (default
``<events_dir>/spans``) — ``rank_<r>.jsonl`` for rank processes,
``host_<pid>.jsonl`` for orchestrator-side ones — one single-line JSON
record per COMPLETED span (``O_APPEND``-atomic, like the event log).
Timestamps are wall-clock ``time.time()`` seconds: cross-process merge
needs one clock, and the hosts of a run share theirs (NTP-level skew is
visible in the trace rather than hidden — that is a feature).

Record schema::

    {"trace_id": "dct-...", "span_id": "8b1f...", "parent_id": "...|null",
     "name": "trainer.epoch", "component": "trainer", "rank": 0,
     "pid": 4242, "tid": 1, "t0": <unix s>, "t1": <unix s>,
     "attrs": {...}}

Telemetry must never fail the run: recording degrades to a no-op on OS
errors, and a disabled recorder still mints span IDs so propagation
(and tests over it) keep working with zero files written.
"""

from __future__ import annotations

import json
import os
import threading
import time
import uuid

from dct_tpu.observability.events import (
    _jsonable,
    _rank_from_env,
    current_run_id,
    observability_enabled,
)

#: Environment variable carrying the parent span ID across a process
#: spawn (the launcher exports it; rank processes adopt it).
SPAN_ENV = "DCT_SPAN_ID"


def mint_span_id() -> str:
    return uuid.uuid4().hex[:16]


def env_parent_span_id(env=None) -> str | None:
    """The parent span ID a launching process exported, if any."""
    return (env if env is not None else os.environ).get(SPAN_ENV) or None


class Span:
    """One in-flight timed operation; call :meth:`end` exactly once."""

    __slots__ = (
        "recorder", "name", "component", "span_id", "parent_id",
        "t0", "attrs", "_tid", "_ended",
    )

    def __init__(self, recorder, name, component, span_id, parent_id,
                 t0, attrs, tid):
        self.recorder = recorder
        self.name = name
        self.component = component
        self.span_id = span_id
        self.parent_id = parent_id
        self.t0 = t0
        self.attrs = attrs
        self._tid = tid
        self._ended = False

    def set(self, **attrs) -> "Span":
        self.attrs.update(attrs)
        return self

    def end(self, **attrs) -> None:
        if self._ended:
            return
        self._ended = True
        if attrs:
            self.attrs.update(attrs)
        # A span opened with SpanRecorder.open sits on its thread's
        # stack; ending it pops it (identity-checked: ending from
        # another thread, or out of order, never corrupts the stack).
        st = self.recorder._stack()
        if st and st[-1] is self:
            st.pop()
        self.recorder._record(self)


class SpanRecorder:
    """Per-process span writer with a thread-local span stack for
    implicit parenting (``path=None`` disables writes; IDs still mint)."""

    def __init__(
        self,
        path: str | None,
        *,
        trace_id: str,
        rank: int | None = None,
        clock=time.time,
        flush_interval: float = 0.0,
        max_records: int = 128,
        _appender=None,
    ):
        self.path = path
        self.trace_id = trace_id
        self.rank = rank
        self._clock = clock
        self._lock = threading.Lock()
        self._dead = False
        # One persistent-handle appender per file (see buffered.py);
        # for_trace() clones share it so two recorders over one file
        # never hold two competing buffers.
        self._appender = _appender
        if path and self._appender is None:
            from dct_tpu.observability.buffered import BufferedAppender

            self._appender = BufferedAppender(
                path, flush_interval=flush_interval, max_records=max_records
            )
        self._local = threading.local()
        # Parent for spans opened with no enclosing span on their thread:
        # the launching process's exported span, else the trace root.
        self.root_parent = env_parent_span_id()
        # Small stable per-thread ids for the exporter's ``tid`` column.
        self._tids: dict[int, int] = {}

    @property
    def enabled(self) -> bool:
        return bool(self.path) and not self._dead

    # -- parenting -----------------------------------------------------
    def _stack(self) -> list:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    def current_span_id(self) -> str | None:
        st = self._stack()
        return st[-1].span_id if st else self.root_parent

    def child_env(self, env: dict | None = None) -> dict:
        """Env additions that make spawned processes' top-level spans
        children of this process's current span (plus the trace ID, so
        an un-launched child still joins the same trace)."""
        out = dict(env or {})
        cur = self.current_span_id()
        if cur:
            out[SPAN_ENV] = cur
        # Authoritative, not setdefault: the child joins THIS trace even
        # when the inherited env still carries a stale DCT_RUN_ID.
        out["DCT_RUN_ID"] = self.trace_id
        return out

    def _tid(self) -> int:
        ident = threading.get_ident()
        with self._lock:
            if ident not in self._tids:
                self._tids[ident] = len(self._tids)
            return self._tids[ident]

    # -- span lifecycle ------------------------------------------------
    def start(
        self,
        name: str,
        *,
        component: str | None = None,
        parent_id: str | None = None,
        **attrs,
    ) -> Span:
        """Open a span WITHOUT pushing it on the thread stack — for
        operations whose end is reaped elsewhere (the launcher's
        per-rank spans) or that span threads."""
        return Span(
            self,
            name,
            component or name.split(".", 1)[0],
            mint_span_id(),
            parent_id if parent_id is not None else self.current_span_id(),
            self._clock(),
            attrs,
            self._tid(),
        )

    def open(
        self,
        name: str,
        *,
        component: str | None = None,
        parent_id: str | None = None,
        **attrs,
    ) -> Span:
        """Open a span AND push it on this thread's stack, for long
        windows that cannot be a ``with`` block (the trainer's whole-fit
        and per-epoch spans). Call :meth:`Span.end` to close."""
        sp = self.start(
            name, component=component, parent_id=parent_id, **attrs
        )
        self._stack().append(sp)
        return sp

    class _Ctx:
        __slots__ = ("recorder", "span")

        def __init__(self, recorder, span):
            self.recorder = recorder
            self.span = span

        def __enter__(self):
            self.recorder._stack().append(self.span)
            return self.span

        def __exit__(self, exc_type, exc, tb):
            st = self.recorder._stack()
            if st and st[-1] is self.span:
                st.pop()
            if exc_type is not None:
                self.span.attrs.setdefault("error", exc_type.__name__)
            self.span.end()
            return False

    def span(
        self,
        name: str,
        *,
        component: str | None = None,
        parent_id: str | None = None,
        **attrs,
    ):
        """Context-managed span, pushed on this thread's stack so nested
        ``span()`` calls parent to it automatically."""
        return self._Ctx(
            self,
            self.start(
                name, component=component, parent_id=parent_id, **attrs
            ),
        )

    def for_trace(self, trace_id: str | None) -> "SpanRecorder":
        """A recorder writing to the same file under a different trace
        ID (the deploy rollout adopts the shipped cycle's ID, exactly
        like its events do); same object when the ID already matches."""
        if not trace_id or trace_id == self.trace_id:
            return self
        other = SpanRecorder(
            self.path, trace_id=trace_id, rank=self.rank, clock=self._clock,
            _appender=self._appender,
        )
        other.root_parent = None  # foreign trace: no local parent
        return other

    # -- emission ------------------------------------------------------
    def _record(self, span: Span) -> None:
        if not self.enabled:
            return
        rec = {
            "trace_id": self.trace_id,
            "span_id": span.span_id,
            "parent_id": span.parent_id,
            "name": span.name,
            "component": span.component,
            "rank": self.rank,
            "pid": os.getpid(),
            "tid": span._tid,
            "t0": round(span.t0, 6),
            "t1": round(self._clock(), 6),
        }
        if span.attrs:
            rec["attrs"] = _jsonable(span.attrs)
        try:
            line = json.dumps(rec, allow_nan=False) + "\n"
        except ValueError:
            self._dead = True
            return
        if not self._appender.append(line):
            self._dead = True  # tracing degrades to silence, never raises

    def flush(self) -> None:
        """Drain buffered span records to disk (no-op when disabled)."""
        if self._appender is not None:
            self._appender.flush()

    def close(self) -> None:
        """Flush and release the file handle (the recorder stays usable)."""
        if self._appender is not None:
            self._appender.close()

    def set_write_through(self) -> None:
        """Flush and disable batching for the rest of the process."""
        if self._appender is not None:
            self._appender.set_write_through()


# ----------------------------------------------------------------------
# Default recorder plumbing, mirroring events.get_default(): layers with
# no config plumbing (checkpoint manager, serving handlers, DAG task
# callables) record through the process default; the trainer installs a
# config-built one.


def spans_dir_from(events_dir: str | None, spans_dir: str = "") -> str | None:
    """THE spans-directory resolution: explicit ``spans_dir`` wins, else
    ``<events_dir>/spans`` — one definition so every builder agrees."""
    if spans_dir:
        return spans_dir
    return os.path.join(events_dir, "spans") if events_dir else None


def span_file_name(rank: int | None) -> str:
    """Per-process file: ranks by rank (stable across restarts of the
    same rank), orchestrator-side processes by pid."""
    if rank is not None:
        return f"rank_{rank:05d}.jsonl"
    return f"host_{os.getpid()}.jsonl"


def recorder_from_config(cfg, *, rank: int | None = None) -> SpanRecorder:
    """Build the process recorder from an ``ObservabilityConfig`` and
    install it as the process default."""
    trace_id = cfg.run_id or current_run_id()
    directory = (
        spans_dir_from(cfg.events_dir, getattr(cfg, "spans_dir", ""))
        if cfg.enabled
        else None
    )
    rec = SpanRecorder(
        os.path.join(directory, span_file_name(rank)) if directory else None,
        trace_id=trace_id,
        rank=rank,
        flush_interval=getattr(cfg, "telemetry_flush_s", 0.0),
        max_records=getattr(cfg, "telemetry_flush_records", 128),
    )
    set_default(rec)
    return rec


_explicit: SpanRecorder | None = None
_cached: tuple[tuple, SpanRecorder] | None = None
_default_lock = threading.Lock()

_ENV_KEYS = (
    "DCT_OBSERVABILITY",
    "DCT_EVENTS_DIR",
    "DCT_SPANS_DIR",
    "DCT_RUN_ID",
    SPAN_ENV,
    "DCT_PROCESS_ID",
    "NODE_RANK",
    "DCT_TELEMETRY_FLUSH_S",
    "DCT_TELEMETRY_FLUSH_RECORDS",
)


def set_default(rec: SpanRecorder | None) -> None:
    global _explicit
    _explicit = rec


def get_default() -> SpanRecorder:
    """The process default recorder: the explicitly installed one, else
    an env-built one (rebuilt when the relevant env changes, so
    monkeypatched tests see their own sink)."""
    global _cached
    if _explicit is not None:
        return _explicit
    with _default_lock:
        trace_id = current_run_id()
        key = tuple(os.environ.get(k) for k in _ENV_KEYS)
        if _cached is not None and _cached[0] == key:
            return _cached[1]
        directory = (
            spans_dir_from(
                os.environ.get("DCT_EVENTS_DIR", "logs/events"),
                os.environ.get("DCT_SPANS_DIR", ""),
            )
            if observability_enabled()
            else None
        )
        from dct_tpu.observability.events import (
            env_flush_interval,
            env_flush_records,
        )

        rank = _rank_from_env()
        rec = SpanRecorder(
            os.path.join(directory, span_file_name(rank))
            if directory
            else None,
            trace_id=trace_id,
            rank=rank,
            flush_interval=env_flush_interval(),
            max_records=env_flush_records(),
        )
        _cached = (key, rec)
        return rec
