"""On-demand flight-recorder profiling: capture mid-run, never stop training.

The PR 1 :class:`~dct_tpu.utils.profiling.Profiler` is a *planned*
window — one configured epoch, armed before the run starts. Incidents
are not planned: when step time regresses at hour six, the trace you
need is the one you cannot have without a restart. The flight recorder
closes that gap with two asynchronous triggers the trainer polls at
span boundaries (one ``os.stat`` per span — nothing on the step path):

- **trigger file** (``DCT_PROFILE_TRIGGER``, default
  ``logs/profile.trigger``): ``touch`` it — or write a number of
  seconds into it — and every rank starts a ``jax.profiler`` trace at
  its next span boundary, into a per-rank capture directory under the
  trace dir. Each distinct file mtime fires exactly once, so one touch
  is one capture (per rank), however long the file lingers.
- **SIGUSR2**: same capture, signal-triggered, per process (installed
  in the main thread only; worker-thread trainers fall back to the
  file trigger).

A capture runs for ``DCT_PROF_CAPTURE_S`` (or the seconds written into
the trigger file) and stops at the first span boundary past the
deadline. Training math is untouched — the capture brackets dispatches
it never joins, so the loss trajectory is bitwise identical to an
untriggered run (pinned in tests/test_roofline.py).

The serving tier gets the synchronous form: ``GET
/debug/profile?seconds=N`` captures the live scoring process for N
seconds and replies with the trace directory
(:func:`capture_profile`). One capture at a time per process —
``jax.profiler`` supports a single session — concurrent requests get a
loud 409, never a corrupted trace.

Every capture is on the record: ``profile.capture_start`` /
``profile.capture_end`` (+ ``profile.capture_error``) events carry the
trigger source, the directory, and the wall seconds actually traced.
"""

from __future__ import annotations

import os
import threading
import time

#: One jax.profiler session per process: the recorder and the serving
#: endpoint share this gate, so triggers can never stack sessions.
_SESSION_LOCK = threading.Lock()


class CaptureBusy(RuntimeError):
    """A capture is already running in this process."""


def _start_trace(trace_dir: str) -> None:
    import jax.profiler

    os.makedirs(trace_dir, exist_ok=True)
    jax.profiler.start_trace(trace_dir)


def _stop_trace() -> None:
    import jax.profiler

    jax.profiler.stop_trace()


def capture_profile(trace_dir: str, seconds: float, *, emit=None) -> str:
    """Blocking capture: trace this process for ``seconds`` into a
    fresh timestamped directory under ``trace_dir`` and return it.
    Raises :class:`CaptureBusy` when a capture is already active."""
    if not _SESSION_LOCK.acquire(blocking=False):
        raise CaptureBusy("a profiler capture is already running")
    out = os.path.join(trace_dir, f"capture-{int(time.time() * 1e3)}")
    try:
        _start_trace(out)
        if emit:
            emit(
                "profile", "profile.capture_start",
                dir=out, seconds=seconds, trigger="endpoint",
            )
        time.sleep(max(0.0, float(seconds)))
        _stop_trace()
        if emit:
            emit(
                "profile", "profile.capture_end",
                dir=out, seconds=seconds, trigger="endpoint",
            )
    except CaptureBusy:
        raise
    except Exception:
        # A torn session must not wedge the process's only profiler
        # slot; stop is idempotent enough to try.
        try:
            _stop_trace()
        except Exception:  # noqa: BLE001 — already stopping on error
            pass
        raise
    finally:
        _SESSION_LOCK.release()
    return out


class FlightRecorder:
    """Span-boundary polled capture driver for the training loop.

    Construction never touches jax; everything is lazy so a disabled
    recorder (empty trigger path, no signal) costs nothing. ``poll()``
    is the only hot-path surface: one stat of the trigger file per call
    plus a flag read.
    """

    def __init__(
        self,
        trace_dir: str,
        *,
        trigger_path: str = "",
        capture_s: float = 5.0,
        rank: int = 0,
        emit=None,
        clock=time.monotonic,
    ):
        self.trace_dir = trace_dir
        self.trigger_path = trigger_path
        self.capture_s = max(0.05, float(capture_s))
        self.rank = int(rank)
        self._emit = emit
        self._clock = clock
        self._signal_flag = False
        self._consumed_mtime: int | None = None
        # A trigger observed while the profiler session was busy (the
        # planned Profiler holds the lock for its whole epoch): kept
        # PENDING and retried at every span boundary until the session
        # frees — an operator's touch is deferred, never dropped.
        self._pending: tuple | None = None
        self._busy_noted = False
        self._active_dir: str | None = None
        self._deadline = 0.0
        self._t_start = 0.0
        self._installed_handler = None

    # -- triggers ------------------------------------------------------
    def install_signal(self) -> "FlightRecorder":
        """Arm SIGUSR2 (main thread only — ``signal.signal`` raises
        elsewhere, and the recorder degrades to the file trigger)."""
        import signal

        def _on_usr2(_signum, _frame):
            self._signal_flag = True

        try:
            self._installed_handler = signal.signal(
                signal.SIGUSR2, _on_usr2
            )
        except (ValueError, OSError, AttributeError):
            self._installed_handler = None
        return self

    def _read_trigger(self) -> tuple | None:
        """Peek a freshly-fired trigger: ``(seconds, source, mtime)``
        (mtime None for the signal), or None. Deliberately does NOT
        mark the file mtime consumed — the caller consumes it only
        once a capture actually started, so a trigger landing while
        the session is busy defers instead of vanishing."""
        if self.trigger_path:
            try:
                mtime = os.stat(self.trigger_path).st_mtime_ns
            except OSError:
                mtime = None
            if mtime is not None and mtime != self._consumed_mtime:
                try:
                    with open(self.trigger_path) as f:
                        txt = f.read().strip()
                    seconds = float(txt) if txt else self.capture_s
                except (OSError, ValueError):
                    seconds = self.capture_s
                return seconds, "file", mtime
        if self._signal_flag:
            self._signal_flag = False
            return self.capture_s, "signal", None
        return None

    # -- the poll ------------------------------------------------------
    def poll(self, **ctx) -> None:
        """Called at span boundaries: start a pending capture, or stop
        an active one whose deadline passed. Never raises."""
        try:
            if self._active_dir is not None:
                if self._clock() >= self._deadline:
                    self._finish(**ctx)
                return
            if self._pending is None:
                self._pending = self._read_trigger()
            if self._pending is None:
                return
            seconds, trigger, mtime = self._pending
            outcome = self._begin(seconds, trigger, **ctx)
            if outcome != "busy":
                # Started, or failed terminally (unwritable dir): the
                # trigger is spent either way. Busy keeps it pending
                # for the next boundary.
                if mtime is not None:
                    self._consumed_mtime = mtime
                self._pending = None
                self._busy_noted = False
        except Exception:  # noqa: BLE001 — telemetry never fails the run
            pass

    def _begin(self, seconds: float, trigger: str, **ctx) -> str:
        if not _SESSION_LOCK.acquire(blocking=False):
            if not self._busy_noted:
                # Once per pending trigger — the retry itself is
                # silent, or a long planned window would spam one
                # error per span boundary.
                self._busy_noted = True
                self._note(
                    "profile.capture_error", trigger=trigger,
                    error="a profiler session is already running; "
                          "capture deferred to the next free span "
                          "boundary", **ctx,
                )
            return "busy"
        out = os.path.join(
            self.trace_dir,
            f"capture-{int(time.time() * 1e3)}-rank{self.rank}",
        )
        try:
            _start_trace(out)
        except Exception as e:  # noqa: BLE001 — a failed start releases
            _SESSION_LOCK.release()
            self._note(
                "profile.capture_error", trigger=trigger,
                error=f"{type(e).__name__}: {e}"[:200], **ctx,
            )
            return "failed"
        self._active_dir = out
        self._t_start = self._clock()
        self._deadline = self._t_start + max(0.05, float(seconds))
        self._note(
            "profile.capture_start", dir=out, seconds=seconds,
            trigger=trigger, **ctx,
        )
        return "started"

    def _finish(self, **ctx) -> None:
        out, self._active_dir = self._active_dir, None
        try:
            _stop_trace()
        finally:
            _SESSION_LOCK.release()
        self._note(
            "profile.capture_end", dir=out,
            seconds=round(self._clock() - self._t_start, 3), **ctx,
        )

    def close(self) -> None:
        """Crash-path hygiene: stop any active capture (the partial
        trace is kept — it covers exactly the window that died) and
        restore the previous SIGUSR2 handler."""
        try:
            if self._active_dir is not None:
                self._finish(at="close")
        except Exception:  # noqa: BLE001 — cleanup must not mask the exit
            pass
        if self._installed_handler is not None:
            import signal

            try:
                signal.signal(signal.SIGUSR2, self._installed_handler)
            except (ValueError, OSError):
                pass
            self._installed_handler = None

    def _note(self, event: str, **fields) -> None:
        if self._emit is None:
            return
        try:
            self._emit("profile", event, rank=self.rank, **fields)
        except Exception:  # noqa: BLE001 — telemetry never fails the run
            pass


def recorder_from_config(profile_cfg, *, rank: int = 0, emit=None,
                         install_signal: bool | None = None):
    """A :class:`FlightRecorder` off :class:`~dct_tpu.config.
    ProfileConfig`: per-rank captures under ``<trace_dir>``, the shared
    trigger file, SIGUSR2 armed when the config says so (and we are in
    the main thread — install degrades gracefully elsewhere)."""
    rec = FlightRecorder(
        profile_cfg.trace_dir,
        trigger_path=profile_cfg.trigger_path,
        capture_s=profile_cfg.capture_s,
        rank=rank,
        emit=emit,
    )
    arm = profile_cfg.sigusr2 if install_signal is None else install_signal
    if arm:
        rec.install_signal()
    return rec
