"""Buffered JSONL appending shared by the event log and span recorder.

The hot-loop cost of telemetry was never the bytes — it was the
``open()``/``close()`` pair around every record (the event log and the
span recorder each re-opened their file per emit, a syscall tax the
trainer's dispatch gap work made visible). This module gives both
writers one appender that:

- holds ONE persistent ``O_APPEND`` handle per file, and flushes each
  buffered line as its own small append ``write()`` — the practical
  per-record append atomicity concurrent writers (ranks sharing one
  ``events.jsonl``) relied on with per-record opens is preserved;
- optionally batches lines for up to ``flush_interval`` seconds (or
  ``max_records`` lines, whichever first) before writing — the trainer
  enables this via ``DCT_TELEMETRY_FLUSH_S`` so a busy span emits one
  ``write()`` instead of dozens;
- flushes on ``flush()``/``close()``, and registers every live appender
  for an ``atexit`` sweep, so a normal or ``sys.exit`` teardown never
  strands buffered records. Paths that bypass atexit (``os._exit`` in
  the fault injector's ``crash`` clauses) must call
  :func:`flush_all_appenders` first — :mod:`dct_tpu.resilience.faults`
  does.

Durability contract: with ``flush_interval <= 0`` (the constructor
default) every append reaches the OS before returning — identical
guarantees to the historical open-per-record behavior, minus the
syscalls. With a positive interval, at most ``flush_interval`` seconds
(or ``max_records`` lines) of telemetry is at risk to a SIGKILL; every
cooperative exit path flushes.

Failure contract (same as the writers it serves): any OS error kills
the appender for the rest of the process — telemetry degrades to
silence, never raises into training code.
"""

from __future__ import annotations

import atexit
import os
import threading
import time
import weakref

_live: "weakref.WeakSet[BufferedAppender]" = weakref.WeakSet()
_live_lock = threading.Lock()


def flush_all_appenders() -> None:
    """Flush every live appender (atexit hook; also called by code that
    is about to hard-exit the process, e.g. injected ``crash`` faults)."""
    with _live_lock:
        appenders = list(_live)
    for app in appenders:
        try:
            app.flush()
        except Exception:  # noqa: BLE001 — a dying appender must not
            pass  # block the others (or the exit) from flushing


atexit.register(flush_all_appenders)


class BufferedAppender:
    """Append-only line writer with a persistent handle and bounded
    buffering. Thread-safe; one instance per (writer, path)."""

    def __init__(
        self,
        path: str,
        *,
        flush_interval: float = 0.0,
        max_records: int = 128,
        clock=time.monotonic,
    ):
        self.path = path
        self.flush_interval = max(0.0, float(flush_interval))
        self.max_records = max(1, int(max_records))
        self._clock = clock
        self._buf: list[str] = []
        self._last_flush = clock()
        self._fh = None
        self._lock = threading.Lock()
        self._dead = False
        self._timer: threading.Timer | None = None
        with _live_lock:
            _live.add(self)

    @property
    def dead(self) -> bool:
        return self._dead

    @property
    def pending(self) -> int:
        """Buffered-but-unwritten line count (for tests/introspection)."""
        with self._lock:
            return len(self._buf)

    def append(self, line: str) -> bool:
        """Queue one newline-terminated line; returns False once the
        appender is dead (the caller should stop emitting)."""
        with self._lock:
            if self._dead:
                return False
            self._buf.append(line)
            if (
                self.flush_interval <= 0.0
                or len(self._buf) >= self.max_records
                or self._clock() - self._last_flush >= self.flush_interval
            ):
                return self._flush_locked()
            # Buffered: arm a one-shot daemon timer so the record's
            # time-at-risk is bounded by flush_interval even if no
            # further append ever arrives to piggyback the flush on.
            if self._timer is None:
                self._timer = threading.Timer(
                    self.flush_interval, self._timer_flush
                )
                self._timer.daemon = True
                self._timer.start()
            return True

    def flush(self) -> bool:
        with self._lock:
            return self._flush_locked()

    def set_write_through(self) -> None:
        """Flush and drop to interval 0 (every future append is
        synchronous). The trainer calls this when its hot loop ends so
        post-run emitters through the same process-default writer get
        read-after-emit visibility back."""
        with self._lock:
            self.flush_interval = 0.0
            self._flush_locked()

    def close(self) -> None:
        """Flush and release the handle (the appender stays usable: the
        next append reopens — close is for ordered teardown, not end of
        life)."""
        with self._lock:
            self._flush_locked()
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None

    def _timer_flush(self) -> None:
        with self._lock:
            self._flush_locked()

    # -- internals -----------------------------------------------------
    def _flush_locked(self) -> bool:
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
        if self._dead:
            self._buf.clear()
            return False
        if not self._buf:
            self._last_flush = self._clock()
            return True
        try:
            if self._fh is None:
                parent = os.path.dirname(self.path)
                if parent:
                    os.makedirs(parent, exist_ok=True)
                self._fh = open(self.path, "a")
            # One write()+flush PER LINE, not one blob for the batch:
            # several ranks share one events.jsonl, and concurrent
            # multi-KB appends can interleave mid-record on filesystems
            # without large-append atomicity (NFS-class shared log
            # dirs). A small single-line O_APPEND write keeps the
            # practical per-record append atomicity the old
            # open-per-record writers had; the batching still amortizes
            # everything else (open/close, locking, the emit-side work).
            for line in self._buf:
                self._fh.write(line)
                self._fh.flush()
        except (OSError, ValueError):
            self._dead = True
            self._buf.clear()
            return False
        self._buf.clear()
        self._last_flush = self._clock()
        return True
