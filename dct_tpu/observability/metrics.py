"""MetricsRegistry: the process-local half of the platform metrics plane.

Before this module every surface rolled its own metric state — the
serving server's ``_SlotMetrics`` dict-of-dicts, the trainer's
hand-built ``MetricFamily`` list in ``dump.py`` — which made
cross-process aggregation impossible: there was no common in-memory
shape to merge. The registry is that shape:

- **counter** — monotone; merges across processes by SUM.
- **gauge** — last-written value; each gauge declares its merge
  semantics (``sum`` / ``max`` / ``min`` / ``last``) because "sum"
  is wrong for a fraction and "last" is wrong for a debt total.
- **histogram** — Prometheus cumulative-bucket layout
  (:class:`~dct_tpu.observability.prometheus.HistogramAccumulator`);
  merges bucket-wise by SUM (valid because bucket boundaries are part
  of the metric identity — a mismatch is a hard error, not a quiet
  wrong answer).

Every metric is a family of label-keyed series (labels are sorted into
a canonical tuple, so ``{a,b}`` and ``{b,a}`` are one series). The
registry is thread-safe under one lock; ``snapshot()`` returns a plain
JSON-able dict (the wire format the aggregation layer publishes —
:mod:`dct_tpu.observability.aggregate`) and ``render()`` returns the
0.0.4 text exposition of the local state, byte-compatible with what
the ad-hoc paths produced.

Telemetry never fails the caller: metric mutation raises only on
programmer errors (unknown type, re-registration under a different
type), never on values.
"""

from __future__ import annotations

import threading
import time

from dct_tpu.observability.prometheus import (
    LATENCY_BUCKETS,
    HistogramAccumulator,
    MetricFamily,
    render,
)

#: Gauge merge semantics the aggregation layer understands.
GAUGE_AGGS = ("sum", "max", "min", "last")


def _label_key(labels: dict | None) -> tuple:
    """Canonical series key: sorted (name, value-as-str) pairs."""
    if not labels:
        return ()
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class _Metric:
    """One named family inside a registry (internal; callers go through
    the registry's ``counter``/``gauge``/``histogram`` constructors)."""

    __slots__ = ("name", "mtype", "help_text", "agg", "buckets", "series")

    def __init__(self, name, mtype, help_text, *, agg="sum",
                 buckets=LATENCY_BUCKETS):
        self.name = name
        self.mtype = mtype
        self.help_text = help_text
        self.agg = agg
        self.buckets = tuple(sorted(buckets)) if mtype == "histogram" else None
        # label key tuple -> float (counter/gauge) | HistogramAccumulator
        self.series: dict = {}


class Counter:
    def __init__(self, registry: "MetricsRegistry", metric: _Metric):
        self._r = registry
        self._m = metric

    def inc(self, amount: float = 1.0, labels: dict | None = None) -> None:
        key = _label_key(labels)
        with self._r._lock:
            self._m.series[key] = (
                self._m.series.get(key, 0.0) + float(amount)
            )


class Gauge:
    def __init__(self, registry: "MetricsRegistry", metric: _Metric):
        self._r = registry
        self._m = metric

    def set(self, value: float, labels: dict | None = None) -> None:
        with self._r._lock:
            self._m.series[_label_key(labels)] = float(value)


class Histogram:
    def __init__(self, registry: "MetricsRegistry", metric: _Metric):
        self._r = registry
        self._m = metric

    def observe(self, value: float, labels: dict | None = None) -> None:
        key = _label_key(labels)
        with self._r._lock:
            acc = self._m.series.get(key)
            if acc is None:
                acc = self._m.series[key] = HistogramAccumulator(
                    self._m.buckets
                )
            acc.observe(value)

    def accumulator(self, labels: dict | None = None) -> HistogramAccumulator:
        """The live accumulator behind one label set (created on first
        access) — a READ handle for callers that inspect counts
        directly (tests, diagnostics). Mutate through :meth:`observe`
        only: writes outside the registry lock could be snapshotted
        torn (non-monotone cumulative counts mid-increment)."""
        key = _label_key(labels)
        with self._r._lock:
            acc = self._m.series.get(key)
            if acc is None:
                acc = self._m.series[key] = HistogramAccumulator(
                    self._m.buckets
                )
            return acc


class MetricsRegistry:
    """Thread-safe metric store for ONE process of the platform.

    Constructors are idempotent (a second ``counter(name)`` returns a
    handle to the same family) but type/agg/bucket conflicts raise —
    two callers silently disagreeing about a metric's shape is exactly
    the aggregation bug this module exists to prevent.
    """

    def __init__(self, *, clock=time.time):
        self._lock = threading.Lock()
        self._metrics: dict[str, _Metric] = {}
        self._clock = clock

    # -- constructors --------------------------------------------------
    def _register(self, name, mtype, help_text, *, agg="sum",
                  buckets=LATENCY_BUCKETS) -> _Metric:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = _Metric(
                    name, mtype, help_text, agg=agg, buckets=buckets
                )
                return m
            if m.mtype != mtype:
                raise ValueError(
                    f"metric {name!r} already registered as {m.mtype}"
                )
            if mtype == "gauge" and m.agg != agg:
                raise ValueError(
                    f"gauge {name!r} already registered with agg={m.agg!r}"
                )
            if mtype == "histogram" and m.buckets != tuple(sorted(buckets)):
                raise ValueError(
                    f"histogram {name!r} already registered with "
                    "different buckets"
                )
            return m

    def counter(self, name: str, help_text: str = "") -> Counter:
        return Counter(self, self._register(name, "counter", help_text))

    def gauge(self, name: str, help_text: str = "",
              agg: str = "last") -> Gauge:
        if agg not in GAUGE_AGGS:
            raise ValueError(f"unknown gauge agg {agg!r}; known: {GAUGE_AGGS}")
        return Gauge(self, self._register(name, "gauge", help_text, agg=agg))

    def histogram(self, name: str, help_text: str = "",
                  buckets: tuple = LATENCY_BUCKETS) -> Histogram:
        return Histogram(
            self, self._register(name, "histogram", help_text,
                                 buckets=buckets)
        )

    # -- export --------------------------------------------------------
    def snapshot(self, *, proc: str, final: bool = False) -> dict:
        """The process's full metric state as one JSON-able dict — the
        wire format :mod:`~dct_tpu.observability.aggregate` publishes.
        ``final=True`` marks a terminal snapshot (batch process about to
        exit: the textfile pattern) which the staleness rules keep even
        after the pid dies."""
        import os

        with self._lock:
            metrics = []
            for m in self._metrics.values():
                entry = {
                    "name": m.name,
                    "type": m.mtype,
                    "help": m.help_text,
                }
                if m.mtype == "gauge":
                    entry["agg"] = m.agg
                if m.mtype == "histogram":
                    entry["buckets"] = list(m.buckets)
                    entry["samples"] = [
                        {
                            "labels": dict(key),
                            "counts": list(acc.counts),
                            "count": acc.count,
                            "sum": acc.sum,
                        }
                        for key, acc in m.series.items()
                    ]
                else:
                    entry["samples"] = [
                        {"labels": dict(key), "value": v}
                        for key, v in m.series.items()
                    ]
                metrics.append(entry)
        return {
            "proc": proc,
            "pid": os.getpid(),
            "ts": round(self._clock(), 6),
            "final": bool(final),
            "metrics": metrics,
        }

    def families(self) -> list[MetricFamily]:
        """The local state as renderable families (no ``proc`` label —
        that is the aggregation layer's job)."""
        with self._lock:
            fams = []
            for m in self._metrics.values():
                fam = MetricFamily(m.name, m.mtype, m.help_text)
                for key, v in m.series.items():
                    labels = dict(key) or None
                    if m.mtype == "histogram":
                        v.samples_into(fam, labels)
                    else:
                        fam.add(v, labels)
                fams.append(fam)
            return fams

    def render(self) -> str:
        """Local-process text exposition (0.0.4)."""
        fams = self.families()
        return render(fams) if fams else ""
