"""Structured event log with run-correlation IDs.

One continuous-training cycle spans many processes: the DAG/launcher, N
SPMD ranks, the tracking store, the deploy rollout. The reference
correlates them by eyeballing Airflow task timestamps; here every record
carries a **run-correlation ID** so ``grep <run_id> events.jsonl``
reconstructs the whole cycle.

ID contract (the launcher is the minter of record):

1. the DAG/launcher mints the ID (:func:`mint_run_id`) and exports it as
   ``DCT_RUN_ID`` into every rank's environment;
2. every in-process component resolves the same ID via
   :func:`current_run_id` (env first; a process that was never launched
   — unit tests, ad-hoc runs — mints its own and pins it in its env so
   all later components of that process agree);
3. records are single-line JSON appended with ``O_APPEND`` — atomic for
   lines under ``PIPE_BUF``, so concurrent ranks can safely share one
   ``events.jsonl``.

Record schema (every key always present, extras per event)::

    {"ts": <unix seconds>, "run_id": "dct-...", "rank": <int|null>,
     "component": "trainer|launcher|checkpoint|tracking|deploy|serving",
     "event": "...", ...}

``rank`` is null for orchestrator-side processes (launcher, DAG tasks).

Telemetry must never fail the run: any OS error while emitting disables
the log for the rest of the process instead of raising.
"""

from __future__ import annotations

import json
import math
import os
import threading
import time
import uuid


def _jsonable(value):
    """Strict-JSON scrub: a NaN val_loss must not poison the line for
    spec-compliant consumers (jq, Promtail), so non-finite floats become
    strings; containers recurse; anything exotic falls back to str."""
    if isinstance(value, float):
        return value if math.isfinite(value) else repr(value)
    if isinstance(value, dict):
        return {str(k): _jsonable(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_jsonable(v) for v in value]
    if value is None or isinstance(value, (str, int, bool)):
        return value
    return str(value)


def mint_run_id() -> str:
    return "dct-" + uuid.uuid4().hex[:12]


def current_run_id(*, mint: bool = True) -> str | None:
    """The process's run-correlation ID: ``DCT_RUN_ID`` if the launcher
    set one, else freshly minted AND pinned into this process's env so
    every later component (tracking, checkpointing) agrees on it."""
    rid = os.environ.get("DCT_RUN_ID")
    if rid:
        return rid
    if not mint:
        return None
    rid = mint_run_id()
    os.environ["DCT_RUN_ID"] = rid
    return rid


def _rank_from_env() -> int | None:
    for var in ("DCT_PROCESS_ID", "NODE_RANK"):
        raw = os.environ.get(var)
        if raw:
            try:
                return int(raw)
            except ValueError:
                pass
    return None


class EventLog:
    """Append-only JSONL emitter; ``path=None`` disables (all emits
    no-op but ``run_id`` stays resolvable for stamping other records).

    Writes go through one persistent-handle appender
    (:class:`dct_tpu.observability.buffered.BufferedAppender`) instead of
    an ``open()`` per record. ``flush_interval`` > 0 additionally batches
    records for up to that many seconds (bounded by ``max_records``);
    every cooperative exit path must then :meth:`flush`/:meth:`close` —
    the trainer does, and an ``atexit`` sweep backstops normal exits.
    The default (0) keeps per-record durability exactly as before."""

    def __init__(
        self,
        path: str | None,
        *,
        run_id: str,
        rank: int | None = None,
        clock=time.time,
        flush_interval: float = 0.0,
        max_records: int = 128,
    ):
        self.path = path
        self.run_id = run_id
        self.rank = rank
        self._clock = clock
        self._dead = False
        self._appender = None
        if path:
            from dct_tpu.observability.buffered import BufferedAppender

            self._appender = BufferedAppender(
                path, flush_interval=flush_interval, max_records=max_records
            )

    @property
    def enabled(self) -> bool:
        return bool(self.path) and not self._dead

    def emit(self, component: str, event: str, **fields) -> None:
        if not self.enabled:
            return
        rec = {
            "ts": round(self._clock(), 6),
            "run_id": self.run_id,
            "rank": self.rank,
            "component": component,
            "event": event,
        }
        rec.update(fields)
        try:
            line = json.dumps(_jsonable(rec), allow_nan=False) + "\n"
        except ValueError:
            self._dead = True
            return
        if not self._appender.append(line):
            # Full disk / unwritable dir / closed fd: telemetry degrades
            # to silence, training continues.
            self._dead = True

    def flush(self) -> None:
        """Drain any buffered records to disk (no-op when disabled)."""
        if self._appender is not None:
            self._appender.flush()

    def close(self) -> None:
        """Flush and release the file handle (the log stays usable)."""
        if self._appender is not None:
            self._appender.close()

    def set_write_through(self) -> None:
        """Flush and disable batching for the rest of the process (the
        trainer calls this when its hot loop ends: later emitters through
        the installed default get read-after-emit visibility back)."""
        if self._appender is not None:
            self._appender.set_write_through()


def observability_enabled(env=None) -> bool:
    """THE parse of ``DCT_OBSERVABILITY`` (default on), with the exact
    semantics of config._env's bool cast — one definition so the
    trainer, the launcher, and the env-built default log can never
    disagree about whether observability is enabled."""
    raw = (env if env is not None else os.environ).get("DCT_OBSERVABILITY")
    if raw is None:
        return True
    return raw.strip().lower() in ("1", "true", "yes", "on")


def event_log_from_config(cfg, *, rank: int | None = None) -> "EventLog":
    """Build the process event log from an ``ObservabilityConfig`` and
    install it as the process default so layers without config plumbing
    (checkpoint manager, tracking client) stamp the same run ID."""
    rid = cfg.run_id or current_run_id()
    path = (
        os.path.join(cfg.events_dir, "events.jsonl")
        if cfg.enabled and cfg.events_dir
        else None
    )
    log = EventLog(
        path,
        run_id=rid,
        rank=rank,
        flush_interval=getattr(cfg, "telemetry_flush_s", 0.0),
        max_records=getattr(cfg, "telemetry_flush_records", 128),
    )
    set_default(log)
    return log


def env_flush_interval(env=None) -> float:
    """THE parse of ``DCT_TELEMETRY_FLUSH_S`` for env-built writers —
    shared with spans.get_default so the two sinks buffer alike."""
    raw = (env if env is not None else os.environ).get(
        "DCT_TELEMETRY_FLUSH_S"
    )
    try:
        return max(0.0, float(raw)) if raw else 0.0
    except ValueError:
        return 0.0


def env_flush_records(env=None) -> int:
    """THE parse of ``DCT_TELEMETRY_FLUSH_RECORDS`` for env-built
    writers: the operator's telemetry-at-risk cap must bind every
    process of the run, not only the config-plumbed trainer."""
    raw = (env if env is not None else os.environ).get(
        "DCT_TELEMETRY_FLUSH_RECORDS"
    )
    try:
        return max(1, int(raw)) if raw else 128
    except ValueError:
        return 128


# ----------------------------------------------------------------------
# Process-default log: layers that have no config plumbing (checkpoint
# manager, tracking client) emit through this. The trainer installs the
# config-built log via event_log_from_config; standalone processes fall
# back to an env-built one (DCT_EVENTS_DIR / DCT_RUN_ID /
# DCT_OBSERVABILITY), rebuilt whenever those env vars change so
# monkeypatched tests see their own sink.

_explicit: EventLog | None = None
_cached: tuple[tuple, EventLog] | None = None
_default_lock = threading.Lock()

_ENV_KEYS = (
    "DCT_OBSERVABILITY",
    "DCT_EVENTS_DIR",
    "DCT_RUN_ID",
    "DCT_PROCESS_ID",
    "NODE_RANK",
    "DCT_TELEMETRY_FLUSH_S",
    "DCT_TELEMETRY_FLUSH_RECORDS",
)


def set_default(log: EventLog | None) -> None:
    global _explicit
    _explicit = log


def get_default() -> EventLog:
    global _cached
    if _explicit is not None:
        return _explicit
    with _default_lock:
        rid = current_run_id()
        key = tuple(os.environ.get(k) for k in _ENV_KEYS)
        if _cached is not None and _cached[0] == key:
            return _cached[1]
        events_dir = os.environ.get("DCT_EVENTS_DIR", "logs/events")
        enabled = observability_enabled() and events_dir
        log = EventLog(
            os.path.join(events_dir, "events.jsonl") if enabled else None,
            run_id=rid,
            rank=_rank_from_env(),
            flush_interval=env_flush_interval(),
            max_records=env_flush_records(),
        )
        _cached = (key, log)
        return log
