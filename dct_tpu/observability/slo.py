"""SLO monitoring over the aggregated metrics plane: declarative
objectives, multi-window burn rates, ``slo.alert`` events and
``dct_slo_*`` gauges.

A raw error counter tells an operator something broke; an SLO burn rate
tells them how fast the error budget is being spent and whether to act
now. The monitor evaluates declarative specs against the FLEET view
(:class:`~dct_tpu.observability.aggregate.MergedMetrics` — one process
alone would alert on 1/N of the truth) at every scrape:

Spec grammar (``DCT_SLO_SPEC``; semicolon-separated clauses, each
optionally prefixed ``name=``):

- ``availability:<objective>`` — server-fault error ratio over
  ``dct_request_errors_total / dct_requests_total``; objective is the
  success target (``0.999`` tolerates a 0.1% error budget).
- ``latency:<seconds>@<objective>`` — the fraction of requests slower
  than ``<seconds>`` (from the ``dct_request_latency_seconds`` bucket
  deltas) must stay under ``1 - objective``.
- ``goodput:<min_fraction>`` — the training fleet's worst
  ``dct_train_goodput_fraction`` gauge must stay at or above the floor.
- ``freshness:<max_age_s>`` — seconds since the cycle's last successful
  deploy (``full_rollout`` / ``deploy_new_slot`` on the event log) must
  stay under the budget: the continuous-training promise, measured.
  Stream-fed deployments (``DCT_INGEST_MODE=stream``) measure consumer
  lag instead — seconds the trainer's group trails the producer
  watermark, i.e. the arrival→trainable age of the oldest pending
  event.

Burn rate = (observed bad fraction) / (budgeted bad fraction); 1.0
means spending the budget exactly at the rate that exhausts it at the
objective horizon. Counter-backed specs evaluate over TWO windows
(``fast``/``slow``, the Google SRE multi-window pattern): the fast
window catches a cliff quickly, the slow window keeps one burst from
paging, and an alert fires only when BOTH burn above the threshold.
Gauge-backed specs (goodput, freshness) are instantaneous — their two
windows report the same value.

Alerts are edge-triggered: one ``slo.alert`` event on the transition
into burning (and one ``slo.resolved`` on the way out), while the
``dct_slo_alert_active`` gauge stays level-triggered for scrapers.
"""

from __future__ import annotations

import json
import os
import time
from collections import deque
from dataclasses import dataclass, field

from dct_tpu.observability.prometheus import MetricFamily

#: Events on the run log that mark a successful deploy (freshness spec).
DEPLOY_EVENTS = ("full_rollout", "deploy_new_slot")

KINDS = ("availability", "latency", "goodput", "freshness")

DEFAULT_SPEC = "availability:0.999;latency:0.5@0.95"


class SLOSpecError(ValueError):
    """A malformed ``DCT_SLO_SPEC`` clause (mis-speced monitoring is
    worse than none: it must fail loudly at parse time, not quietly
    at alert time)."""


@dataclass
class SLOSpec:
    name: str
    kind: str  # availability | latency | goodput | freshness
    objective: float  # success target (availability/latency), floor
    #                   (goodput); unused for freshness
    threshold: float = 0.0  # latency seconds | freshness max-age seconds

    @property
    def budget(self) -> float:
        """The tolerated bad fraction."""
        return max(1e-9, 1.0 - self.objective)


def parse_slo_spec(spec: str) -> list[SLOSpec]:
    """``DCT_SLO_SPEC`` grammar -> specs (module docstring)."""
    out: list[SLOSpec] = []
    for clause in (spec or "").split(";"):
        clause = clause.strip()
        if not clause:
            continue
        name = None
        if "=" in clause.split(":", 1)[0]:
            name, clause = clause.split("=", 1)
            name = name.strip()
        if ":" not in clause:
            raise SLOSpecError(
                f"SLO clause {clause!r} must be kind:params"
            )
        kind, params = (p.strip() for p in clause.split(":", 1))
        if kind not in KINDS:
            raise SLOSpecError(
                f"unknown SLO kind {kind!r}; known: {KINDS}"
            )
        try:
            if kind == "availability":
                sp = SLOSpec(name or kind, kind, float(params))
            elif kind == "latency":
                if "@" not in params:
                    raise ValueError("latency needs <seconds>@<objective>")
                secs, obj = params.split("@", 1)
                sp = SLOSpec(name or kind, kind, float(obj),
                             threshold=float(secs))
            elif kind == "goodput":
                sp = SLOSpec(name or kind, kind, float(params))
            else:  # freshness
                sp = SLOSpec(name or kind, kind, 0.0,
                             threshold=float(params))
        except ValueError as e:
            raise SLOSpecError(
                f"SLO clause {clause!r}: {e}"
            ) from e
        if kind != "freshness" and not 0.0 < sp.objective < 1.0:
            raise SLOSpecError(
                f"SLO clause {clause!r}: objective must be in (0, 1)"
            )
        if kind in ("latency", "freshness") and sp.threshold <= 0:
            raise SLOSpecError(
                f"SLO clause {clause!r}: threshold must be positive"
            )
        out.append(sp)
    return out


# ----------------------------------------------------------------------
# freshness source: the run's event log


_deploy_ts_cache: dict[str, tuple[tuple, float | None]] = {}


def last_deploy_ts(events_path: str | None) -> float | None:
    """Newest successful-deploy timestamp on the event log (cached by
    file identity — scrapes must not re-read a long log every time)."""
    if not events_path:
        return None
    try:
        st = os.stat(events_path)
    except OSError:
        return None
    key = (st.st_mtime_ns, st.st_size)
    cached = _deploy_ts_cache.get(events_path)
    if cached is not None and cached[0] == key:
        return cached[1]
    latest: float | None = None
    try:
        with open(events_path) as f:
            for line in f:
                # Cheap pre-filter before the JSON parse.
                if not any(e in line for e in DEPLOY_EVENTS):
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if rec.get("event") in DEPLOY_EVENTS:
                    ts = rec.get("ts")
                    if isinstance(ts, (int, float)):
                        latest = max(latest or ts, ts)
    except OSError:
        return None
    _deploy_ts_cache[events_path] = (key, latest)
    return latest


def stream_freshness_age() -> float | None:
    """Arrival→trainable age from stream consumer lag, or None when the
    deployment is not stream-fed (``DCT_INGEST_MODE`` != ``stream``).

    In stream mode "fresh" means the trainer's consumer group is keeping
    up with the producer watermark: seconds-behind IS the age of the
    oldest event not yet trainable, a strictly tighter signal than the
    deploy-event mtime proxy (a promotion can be recent while the
    consumer silently stalls). Falls back to the event-log source when
    the topic has no data yet."""
    if os.environ.get("DCT_INGEST_MODE", "poll") != "stream":
        return None
    stream_dir = os.environ.get("DCT_STREAM_DIR", "")
    if not stream_dir:
        return None
    from dct_tpu.stream.consumer import group_lag_seconds

    try:
        return group_lag_seconds(
            stream_dir,
            os.environ.get("DCT_STREAM_TOPIC", "events"),
            os.environ.get("DCT_STREAM_GROUP", "etl"),
        )
    except OSError:
        return None


# ----------------------------------------------------------------------
# monitor


def _latency_over_threshold(hist: dict, threshold: float) -> tuple:
    """(total_count, over_threshold_count) from a cumulative-bucket
    histogram dict. Only requests PROVABLY within the threshold count
    as under: the largest bucket boundary <= threshold stands in
    (conservative — a threshold between boundaries over-reports
    violations, never under-reports them; picking the boundary ABOVE
    would count a 0.4 s request as meeting a 0.3 s SLO)."""
    buckets = hist.get("buckets") or []
    counts = hist.get("counts") or []
    total = int(hist.get("count", 0))
    under = 0  # threshold below every boundary: nothing provably under
    for le, c in zip(buckets, counts):
        if le > threshold:
            break
        under = int(c)
    return total, max(0, total - under)


@dataclass
class _SpecState:
    history: deque = field(default_factory=lambda: deque(maxlen=4096))
    alerting: bool = False


class SLOMonitor:
    """Evaluates specs against each scrape's merged view; holds the
    windowed history per spec. One instance per serving process —
    state is process-local but the INPUT is the fleet view, so every
    process converges on the same verdict within a scrape interval."""

    def __init__(
        self,
        specs: list[SLOSpec],
        *,
        fast_window_s: float = 300.0,
        slow_window_s: float = 3600.0,
        burn_threshold: float = 1.0,
        clock=time.time,
        emit=None,
        events_path: str | None = None,
        history=None,
        on_alert=None,
    ):
        self.specs = list(specs)
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)
        self.burn_threshold = float(burn_threshold)
        self._clock = clock
        self._emit = emit
        self.events_path = events_path
        # When a timeseries.HistoryReader is supplied, cumulative-kind
        # burn windows come from the on-disk history — the same "what
        # happened over the last N seconds" every other consumer sees —
        # and the in-memory deque is only the no-data fallback. None
        # (the default) keeps the pre-ISSUE-17 in-memory behaviour.
        self.history = history
        # Called once per alert EDGE with the state dict (the incident
        # assembler's hook); never on re-evaluations while alerting.
        self._on_alert = on_alert
        self._state = {sp.name: _SpecState() for sp in self.specs}

    # -- per-kind observation -----------------------------------------
    def _observe_point(self, sp: SLOSpec, merged, now: float):
        """-> (cumulative-or-instant observation, is_cumulative)."""
        if sp.kind == "availability":
            total = merged.total("dct_requests_total")
            errors = merged.total("dct_request_errors_total") or 0.0
            if total is None:
                return None, True
            return (now, float(total), float(errors)), True
        if sp.kind == "latency":
            hist = merged.histogram_total("dct_request_latency_seconds")
            if hist is None:
                return None, True
            total, over = _latency_over_threshold(hist, sp.threshold)
            return (now, float(total), float(over)), True
        if sp.kind == "goodput":
            m = merged.metrics.get("dct_train_goodput_fraction")
            if not m or not m["totals"]:
                return None, False
            worst = min(float(v) for v in m["totals"].values())
            burn = (1.0 - worst) / sp.budget
            return (now, worst, burn), False
        # freshness — stream consumer lag when the deployment is
        # stream-fed (arrival→trainable seconds), deploy-event age
        # otherwise.
        lag_s = stream_freshness_age()
        if lag_s is not None:
            age = max(0.0, lag_s)
        else:
            ts = last_deploy_ts(self.events_path)
            if ts is None:
                return None, False
            age = max(0.0, now - ts)
        return (now, age, age / sp.threshold), False

    @staticmethod
    def _window_burn(history, now: float, window_s: float,
                     budget: float) -> float:
        """Burn over the trailing window from cumulative observations:
        (bad delta / total delta) / budget. With only one observation
        the window is empty — burn 0 (no evidence is not an alert)."""
        if len(history) < 2:
            return 0.0
        cur = history[-1]
        oldest = None
        for obs in history:
            if obs[0] >= now - window_s:
                oldest = obs
                break
        if oldest is None or oldest is cur:
            oldest = history[-2]
        d_total = cur[1] - oldest[1]
        d_bad = cur[2] - oldest[2]
        if d_total <= 0:
            return 0.0
        return max(0.0, d_bad / d_total) / budget

    def _history_burn(
        self, sp: SLOSpec, window_s: float, now: float
    ) -> float | None:
        """Burn over the trailing window from the on-disk history
        store; None when the store has no data for this spec (caller
        falls back to the in-memory observations)."""
        try:
            if sp.kind == "availability":
                total = self.history.counter_delta(
                    "dct_requests_total", window_s=window_s, now=now
                )
                if total is None or total <= 0:
                    return None
                bad = self.history.counter_delta(
                    "dct_request_errors_total", window_s=window_s, now=now
                ) or 0.0
                return max(0.0, bad / total) / sp.budget
            if sp.kind == "latency":
                got = self.history.hist_counts(
                    "dct_request_latency_seconds",
                    window_s=window_s, now=now,
                )
                if got is None:
                    return None
                buckets, deltas, total = got
                if total <= 0:
                    return None
                under = 0.0  # same conservative boundary rule as the
                for le, c in zip(buckets, deltas):  # instantaneous path
                    if le > sp.threshold:
                        break
                    under = c
                return max(0.0, (total - under) / total) / sp.budget
        except Exception:  # noqa: BLE001 — a torn segment or racing
            return None  # compaction falls back, never breaks a scrape
        return None

    # -- the scrape-time entry point -----------------------------------
    def evaluate(self, merged, *, now: float | None = None) -> list[dict]:
        """One evaluation pass: update histories, compute burn rates,
        emit edge-triggered ``slo.alert`` / ``slo.resolved`` events.
        Returns one state dict per spec."""
        now = self._clock() if now is None else now
        out = []
        for sp in self.specs:
            st = self._state[sp.name]
            point, cumulative = self._observe_point(sp, merged, now)
            if point is not None:
                st.history.append(point)
            if not st.history:
                out.append({
                    "slo": sp.name, "kind": sp.kind, "data": False,
                    "burn_fast": 0.0, "burn_slow": 0.0, "alerting": False,
                })
                continue
            if cumulative:
                burn_fast = burn_slow = None
                if self.history is not None:
                    burn_fast = self._history_burn(
                        sp, self.fast_window_s, now
                    )
                    burn_slow = self._history_burn(
                        sp, self.slow_window_s, now
                    )
                if burn_fast is None:
                    burn_fast = self._window_burn(
                        st.history, now, self.fast_window_s, sp.budget
                    )
                if burn_slow is None:
                    burn_slow = self._window_burn(
                        st.history, now, self.slow_window_s, sp.budget
                    )
            else:
                burn_fast = burn_slow = float(st.history[-1][2])
            alerting = (
                burn_fast >= self.burn_threshold
                and burn_slow >= self.burn_threshold
            )
            rec = {
                "slo": sp.name, "kind": sp.kind, "data": True,
                "burn_fast": round(burn_fast, 6),
                "burn_slow": round(burn_slow, 6),
                "alerting": alerting,
            }
            if alerting and not st.alerting:
                if self._emit is not None:
                    self._emit(
                        "slo", "slo.alert",
                        slo=sp.name, kind=sp.kind,
                        burn_fast=rec["burn_fast"],
                        burn_slow=rec["burn_slow"],
                        objective=sp.objective, threshold=sp.threshold,
                        burn_threshold=self.burn_threshold,
                        fast_window_s=self.fast_window_s,
                        slow_window_s=self.slow_window_s,
                    )
                if self._on_alert is not None:
                    try:
                        self._on_alert(rec)
                    except Exception:  # noqa: BLE001 — incident capture
                        pass  # never fails the scrape
            elif st.alerting and not alerting and self._emit is not None:
                self._emit(
                    "slo", "slo.resolved",
                    slo=sp.name, kind=sp.kind,
                    burn_fast=rec["burn_fast"], burn_slow=rec["burn_slow"],
                )
            st.alerting = alerting
            out.append(rec)
        return out

    def families(self, states: list[dict]) -> list[MetricFamily]:
        """The ``dct_slo_*`` gauges for the scrape body."""
        burn = MetricFamily(
            "dct_slo_burn_rate", "gauge",
            "Error-budget burn rate per SLO and window "
            "(1.0 = spending the budget exactly at objective rate).",
        )
        active = MetricFamily(
            "dct_slo_alert_active", "gauge",
            "1 while the SLO burns above threshold on both windows.",
        )
        for rec in states:
            burn.add(rec["burn_fast"], {"slo": rec["slo"], "window": "fast"})
            burn.add(rec["burn_slow"], {"slo": rec["slo"], "window": "slow"})
            active.add(1 if rec["alerting"] else 0, {"slo": rec["slo"]})
        return [burn, active]

    def render(self, merged, *, now: float | None = None) -> str:
        """Evaluate + render in one call (the scrape handler's path)."""
        states = self.evaluate(merged, now=now)
        return "\n".join(f.render() for f in self.families(states)) + "\n"
