"""Goodput/badput accounting: a wall-clock ledger for the training run.

"What fraction of the last run was productive training?" is the question
the TPU-scale training literature treats as first-class (the pjit/TPUv4
report decomposes wall time into compile vs. step vs. data stall) and
the reference platform cannot answer at all. The ledger classifies run
wall time into:

- ``train_step``        — jitted train dispatches after their program
  compiled (the fused train+eval path bills its validation pass here
  too: it runs inside the same dispatch);
- ``eval``              — standalone validation passes (eager path);
- ``compile``           — FIRST dispatch of each distinct program
  (detected by dispatch key: compile and first execution are one
  indivisible host call, and compile dominates it, so the whole first
  dispatch is billed here — the standard convention);
- ``checkpoint``        — deploy-tier writes, resume-state snapshots,
  artifact upload;
- ``data_wait``         — host batch assembly / H2D staging the device
  had to wait for (a prefetched span that is already resolved costs
  ~zero here: that is the point of the prefetch);
- ``startup_recovery``  — everything before the first epoch: dataset
  load, model init, state creation/sharding, resume restore.

Seconds not claimed by any category surface as ``unattributed_seconds``
in the summary — honest accounting, never silently absorbed. The clock
is injectable for tests.
"""

from __future__ import annotations

import time
from contextlib import contextmanager

CATEGORIES = (
    "train_step",
    "eval",
    "compile",
    "checkpoint",
    "data_wait",
    "startup_recovery",
)

#: The productive categories: goodput_fraction's numerator and the
#: ``goodput_``-prefixed tracker metrics use the SAME set, so the
#: fraction always equals sum(goodput_*_seconds) / wall_seconds. (On the
#: fused scan path eval runs inside the train dispatch and is billed to
#: train_step; ``eval`` gets real time only on the eager path.)
GOODPUT_CATEGORIES = ("train_step", "eval")

#: Canonical name for time the ledger could not attribute.
UNATTRIBUTED = "unattributed"


class GoodputLedger:
    """Accumulates per-category wall seconds between :meth:`start` and
    :meth:`summary`. Spans are main-thread sequential by construction
    (the trainer's loop), so categories never double-count."""

    def __init__(self, *, clock=time.perf_counter):
        self._clock = clock
        self.seconds: dict[str, float] = dict.fromkeys(CATEGORIES, 0.0)
        self._t0: float | None = None
        self._seen_dispatch_keys: set = set()
        self._epoch_walls: list[tuple[int, float]] = []
        self._last_report: tuple[float, dict] | None = None
        # Every window billed to `compile`, as (program key, seconds):
        # the raw material of the compile/restart accounting layer
        # (compile.* events + dct_compile_* series — ROADMAP item 5's
        # baseline numbers live here).
        self.compile_windows: list[tuple[str, float]] = []
        # Steady-state (post-compile) dispatch windows per program key:
        # key -> [count, seconds]. The measured half of the roofline
        # join (observability.roofline.program_report): analytic FLOPs
        # x count / seconds = achieved FLOPs/s = live per-program MFU.
        self.dispatch_stats: dict[str, list] = {}

    # -- clock surface (for callers that bracket non-contiguous code) --
    def clock(self) -> float:
        return self._clock()

    def start(self) -> None:
        if self._t0 is None:
            self._t0 = self._clock()

    def add(self, category: str, seconds: float) -> None:
        if category not in self.seconds:
            raise KeyError(
                f"unknown goodput category {category!r}; "
                f"known: {CATEGORIES}"
            )
        self.seconds[category] += max(0.0, float(seconds))

    @contextmanager
    def span(self, category: str):
        t0 = self._clock()
        try:
            yield
        finally:
            self.add(category, self._clock() - t0)

    # -- compile detection ---------------------------------------------
    def dispatch_category(self, category: str, key: str) -> str:
        """First time ``key`` is seen the dispatch is the program's
        compile+first-execution; bill it to ``compile``."""
        if key in self._seen_dispatch_keys:
            return category
        self._seen_dispatch_keys.add(key)
        return "compile"

    @contextmanager
    def dispatch(self, category: str, *, key: str | None = None):
        key = key or category
        cat = self.dispatch_category(category, key)
        t0 = self._clock()
        try:
            yield
        finally:
            sec = self._clock() - t0
            if cat == "compile":
                self.compile_windows.append((key, sec))
            else:
                st = self.dispatch_stats.setdefault(key, [0, 0.0])
                st[0] += 1
                st[1] += sec
            self.add(cat, sec)

    def add_dispatch(self, category: str, key: str, seconds: float) -> str:
        """Non-contextmanager form for dispatches whose timing window is
        interleaved with other code (the trainer's prefetch submit sits
        between the fused call and its block_until_ready). Returns the
        category the window was billed to, so callers that bill partial
        (host-blocking-only) windows can true up ``dispatch_stats``
        with the honest wall window afterwards."""
        cat = self.dispatch_category(category, key)
        if cat == "compile":
            self.compile_windows.append((key, float(seconds)))
        else:
            st = self.dispatch_stats.setdefault(key, [0, 0.0])
            st[0] += 1
            st[1] += float(seconds)
        self.add(cat, seconds)
        return cat

    def amend_dispatch_window(self, key: str, extra_seconds: float) -> None:
        """Widen the last-billed roofline window for ``key`` WITHOUT
        touching the goodput categories: the pipelined trainer bills
        only its host-blocking windows to the ledger (overlap is the
        mode's point), but the roofline join needs the true wall window
        per dispatch or MFU over-reports."""
        st = self.dispatch_stats.get(key)
        if st is not None:
            st[1] += max(0.0, float(extra_seconds))

    # -- epoch feed (EpochTimer calls this) ----------------------------
    def note_epoch(self, epoch: int, seconds: float) -> None:
        self._epoch_walls.append((int(epoch), float(seconds)))

    # -- reporting -----------------------------------------------------
    def wall_seconds(self) -> float:
        return 0.0 if self._t0 is None else self._clock() - self._t0

    def accounted_seconds(self) -> float:
        return sum(self.seconds.values())

    def epoch_report(self) -> dict:
        """Per-category seconds since the previous call (or since
        :meth:`start`): the per-epoch/per-span goodput record."""
        now = self._clock()
        if self._last_report is None:
            prev_t = self._t0 if self._t0 is not None else now
            prev = dict.fromkeys(CATEGORIES, 0.0)
        else:
            prev_t, prev = self._last_report
        delta = {c: self.seconds[c] - prev[c] for c in CATEGORIES}
        dt = max(0.0, now - prev_t)
        self._last_report = (now, dict(self.seconds))
        good = sum(delta[c] for c in GOODPUT_CATEGORIES)
        return {
            "seconds": dt,
            "categories": delta,
            "goodput_fraction": good / dt if dt > 0 else 0.0,
        }

    def summary(self) -> dict:
        """Run-end record: category seconds, wall clock, the productive
        fraction, and the honest remainder."""
        wall = self.wall_seconds()
        accounted = self.accounted_seconds()
        good = sum(self.seconds[c] for c in GOODPUT_CATEGORIES)
        return {
            "wall_seconds": wall,
            "accounted_seconds": accounted,
            f"{UNATTRIBUTED}_seconds": max(0.0, wall - accounted),
            "goodput_fraction": good / wall if wall > 0 else 0.0,
            "categories": dict(self.seconds),
            "epochs": len(self._epoch_walls),
        }

    def tracker_metrics(self) -> dict:
        """The summary flattened into scalar metrics, named so goodput
        regressions are queryable in the tracking store next to
        val_loss (``metrics.goodput_fraction DESC`` works like
        ``metrics.val_loss ASC``)."""
        s = self.summary()
        out = {
            "goodput_fraction": s["goodput_fraction"],
            "wall_seconds": s["wall_seconds"],
        }
        for cat, sec in s["categories"].items():
            # GOODPUT_CATEGORIES are the productive time; the rest is
            # overhead an operator wants driven toward zero.
            prefix = "goodput" if cat in GOODPUT_CATEGORIES else "badput"
            out[f"{prefix}_{cat}_seconds"] = sec
        out[f"badput_{UNATTRIBUTED}_seconds"] = s[f"{UNATTRIBUTED}_seconds"]
        return out


# ----------------------------------------------------------------------
# Compile/restart accounting (ROADMAP item 5's baseline numbers): the
# ledger's compile windows, grouped per program and stamped with the
# (family, config-hash, mesh) identity a future AOT compilation cache
# would key on — if the SAME identity keeps re-compiling across
# restarts/workers, that is exactly the debt a persistent cache erases.


def config_hash(cfg_dict: dict) -> str:
    """Stable 8-hex digest of a config mapping (sorted-key JSON, so
    field order never changes the identity)."""
    import hashlib
    import json

    blob = json.dumps(cfg_dict, sort_keys=True, default=str)
    return hashlib.sha1(blob.encode()).hexdigest()[:8]


def mesh_descriptor(mesh) -> str:
    """The mesh axis sizes as one label value (``data2_model1_seq1_
    pipe1``) — compile identity includes layout: the same model on a
    different mesh is a different XLA program. Accepts a live
    ``jax.sharding.Mesh`` (RESOLVED sizes — a config's ``data=-1``
    placeholder is not an identity) or a :class:`MeshConfig`."""
    shape = getattr(mesh, "shape", None)
    if shape:
        return "_".join(f"{k}{v}" for k, v in dict(shape).items())
    return (
        f"data{getattr(mesh, 'data', -1)}"
        f"_model{getattr(mesh, 'model', 1)}"
        f"_seq{getattr(mesh, 'seq', 1)}"
        f"_pipe{getattr(mesh, 'pipe', 1)}"
    )


def compile_report(
    windows: list[tuple[str, float]],
    *,
    family: str = "",
    config_hash: str = "",
    mesh: str = "",
    cache_states: dict | None = None,
    costs: dict | None = None,
) -> list[dict]:
    """Group raw ``(program, seconds)`` compile windows into one record
    per program, carrying the cache-key labels — the shape both the
    ``compile.window`` events and the ``dct_compile_*`` series use.

    ``cache_states`` maps program key -> ``hit``/``miss``/``disabled``
    (the AOT store's per-program resolution,
    :class:`dct_tpu.compilecache.ExecutableStore`); a program the store
    never fronted reports ``disabled`` — its window was a real XLA
    compile with no cache in the loop.

    ``costs`` maps program key -> the roofline analysis the store
    captured at compile time (``ExecutableStore.costs``): analytic
    FLOPs / bytes accessed / peak HBM ride the window record, so a
    ``compile.window`` event names not just what a program cost to
    build but what it costs to run."""
    grouped: dict[str, dict] = {}
    for program, sec in windows:
        g = grouped.setdefault(
            program,
            {
                "program": program,
                "family": family,
                "config_hash": config_hash,
                "mesh": mesh,
                "cache": (cache_states or {}).get(program, "disabled"),
                "count": 0,
                "seconds": 0.0,
            },
        )
        g["count"] += 1
        g["seconds"] += float(sec)
    out = list(grouped.values())
    for g in out:
        g["seconds"] = round(g["seconds"], 6)
        cost = (costs or {}).get(g["program"])
        if cost:
            for k in ("flops", "bytes_accessed", "hbm_peak_bytes"):
                if cost.get(k) is not None:
                    g[k] = cost[k]
    return out
