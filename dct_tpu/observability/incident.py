"""Auto-assembled incident bundles: the "what just happened" directory.

When the anomaly detector (:mod:`~dct_tpu.observability.detect`) or
the SLO monitor fires, the operator's next five commands are always
the same — slice the metric history around the edge, grep the event
log, find which deploy was live, maybe grab a profile. This module
runs those five commands automatically (ISSUE 17): one trigger becomes
one self-contained ``incidents/<stamp>-<signal>/`` directory:

    incident.json     trigger record, lineage id, manifest — written
                      LAST via tmp+``os.replace``, so its existence
                      marks a complete bundle
    timeseries.json   the surrounding history-store window
    events.jsonl      event records inside the window (all logs)
    spans.jsonl       span records inside the window
    lineage.json      the newest deploy_package / model_load node from
                      the PR 16 ledger (the "what was live" answer)
    profile/          optional (``DCT_INCIDENT_PROFILE=1``): a PR 14
                      flight-recorder capture fired at trigger time

Triggers are rate-limited per signal (``DCT_INCIDENT_COOLDOWN_S``) —
a flapping detector must not carpet the disk — and assembly runs on a
daemon thread: the scrape path and the detector poll loop only pay a
thread spawn. ``python -m dct_tpu.observability.incident`` lists,
shows and manually assembles bundles.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

from dct_tpu.observability.timeseries import HistoryReader, _write_json

_BUNDLE_MANIFEST = "incident.json"
#: Per-log tail bound when slicing events/spans — an incident window
#: never needs more, and an unbounded read of a week-long log would
#: make assembly cost proportional to uptime.
_TAIL_LINES = 4000


def default_incident_dir(ts_dir: str) -> str:
    """Sibling of the store (``.../ts`` → ``.../incidents``): bundles
    must not masquerade as a proc's segment directory."""
    parent = os.path.dirname(ts_dir.rstrip("/")) or "."
    return os.path.join(parent, "incidents")


def _tail_jsonl(path: str, start_ts: float, end_ts: float) -> list[dict]:
    out: list[dict] = []
    try:
        with open(path, "rb") as f:
            lines = f.readlines()[-_TAIL_LINES:]
    except OSError:
        return out
    for line in lines:
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        ts = rec.get("ts")
        if isinstance(ts, (int, float)) and start_ts <= ts <= end_ts:
            out.append(rec)
    return out


def _slice_logs(directory: str, start_ts: float, end_ts: float) -> list[dict]:
    recs: list[dict] = []
    try:
        names = sorted(os.listdir(directory))
    except OSError:
        return recs
    for name in names:
        if name.endswith(".jsonl"):
            recs.extend(
                _tail_jsonl(os.path.join(directory, name), start_ts, end_ts)
            )
    recs.sort(key=lambda r: r.get("ts", 0.0))
    return recs


def _active_lineage(ledger_path: str) -> dict | None:
    """The newest deploy_package (preferred) or model_load node — the
    'what was live when it broke' pointer the bundle names."""
    from dct_tpu.observability import lineage

    try:
        records = lineage.read_ledger(ledger_path)
    except Exception:  # noqa: BLE001
        return None
    best = None
    for rec in records:
        if rec.get("type") != "node":
            continue
        if rec.get("kind") == "deploy_package":
            best = rec
        elif rec.get("kind") == "model_load" and (
            best is None or best.get("kind") != "deploy_package"
        ):
            best = rec
    return best


class IncidentManager:
    """Trigger sink + bundle assembler for one arming process."""

    def __init__(
        self,
        directory: str,
        *,
        reader: HistoryReader | None = None,
        ts_dir: str | None = None,
        events_dir: str | None = None,
        spans_dir: str | None = None,
        lineage_path: str | None = None,
        window_s: float = 120.0,
        cooldown_s: float = 300.0,
        profile: bool = False,
        profile_s: float = 2.0,
        emit=None,
        clock=time.time,
    ):
        self.directory = directory
        if reader is None and ts_dir:
            reader = HistoryReader(ts_dir, clock=clock)
        self.reader = reader
        self.events_dir = events_dir
        self.spans_dir = spans_dir
        self.lineage_path = lineage_path
        self.window_s = float(window_s)
        self.cooldown_s = float(cooldown_s)
        self.profile = bool(profile)
        self.profile_s = float(profile_s)
        self._emit = emit
        self._clock = clock
        self._lock = threading.Lock()
        self._last_by_signal: dict[str, float] = {}
        self._threads: list[threading.Thread] = []
        self.assembled = 0

    @classmethod
    def from_env(cls, obs=None, *, reader=None, emit=None, clock=time.time):
        """Build from :class:`~dct_tpu.config.ObservabilityConfig`
        (read from env when not supplied); None when unarmed."""
        from dct_tpu.config import ObservabilityConfig
        from dct_tpu.observability import lineage

        if obs is None:
            obs = ObservabilityConfig.from_env()
        if not obs.ts_dir or not obs.incident:
            return None
        return cls(
            obs.incident_dir or default_incident_dir(obs.ts_dir),
            reader=reader,
            ts_dir=obs.ts_dir,
            events_dir=obs.events_dir,
            spans_dir=obs.spans_dir or os.path.join(obs.events_dir, "spans"),
            lineage_path=lineage.default_ledger_path(),
            window_s=obs.incident_window_s,
            cooldown_s=obs.incident_cooldown_s,
            profile=obs.incident_profile,
            profile_s=obs.incident_profile_s,
            emit=emit,
            clock=clock,
        )

    # -- triggers --------------------------------------------------------

    def on_anomaly(self, rec: dict) -> None:
        """``AnomalyDetector.on_anomaly`` callback."""
        self.trigger("anomaly", rec.get("signal", "unknown"), rec)

    def on_slo_alert(self, state: dict) -> None:
        """``SLOMonitor.on_alert`` callback."""
        self.trigger("slo", f"slo_{state.get('slo', 'unknown')}", state)

    def trigger(self, kind: str, signal: str, record: dict) -> bool:
        """Rate-limited async assembly; True when a bundle was started."""
        now = self._clock()
        with self._lock:
            last = self._last_by_signal.get(signal)
            if last is not None and now - last < self.cooldown_s:
                return False
            self._last_by_signal[signal] = now
            self._threads = [t for t in self._threads if t.is_alive()]
            t = threading.Thread(
                target=self._assemble_safe,
                args=(kind, signal, record, now),
                name=f"dct-incident-{signal}",
                daemon=True,
            )
            self._threads.append(t)
        t.start()
        return True

    def _assemble_safe(self, kind, signal, record, now) -> None:
        try:
            self.assemble(kind, signal, record, now=now)
        except Exception:  # noqa: BLE001 — incident capture never fails
            pass  # the run it is trying to explain

    # -- assembly --------------------------------------------------------

    def _bundle_dir(self, signal: str, now: float) -> str:
        stamp = time.strftime("%Y%m%dT%H%M%S", time.gmtime(now))
        safe = "".join(
            c if c.isalnum() or c in "-_." else "_" for c in signal
        )
        base = os.path.join(self.directory, f"{stamp}-{safe}")
        path, n = base, 1
        while os.path.exists(path):
            path = f"{base}.{n}"
            n += 1
        return path

    def assemble(
        self, kind: str, signal: str, record: dict, *,
        now: float | None = None,
    ) -> str | None:
        """Synchronous bundle build; returns the bundle path."""
        if now is None:
            now = self._clock()
        bundle = self._bundle_dir(signal, now)
        os.makedirs(bundle, exist_ok=True)
        start_ts = now - self.window_s
        files: list[str] = []

        if self.reader is not None:
            ts_slice = self.reader.slice(window_s=self.window_s, now=now)
            if _write_json(
                os.path.join(bundle, "timeseries.json"), ts_slice
            ):
                files.append("timeseries.json")

        for name, directory in (
            ("events.jsonl", self.events_dir),
            ("spans.jsonl", self.spans_dir),
        ):
            if not directory:
                continue
            recs = _slice_logs(directory, start_ts, now)
            if not recs:
                continue
            tmp = os.path.join(bundle, f"{name}.tmp.{os.getpid()}")
            try:
                with open(tmp, "w") as f:
                    for rec in recs:
                        f.write(json.dumps(rec) + "\n")
                os.replace(tmp, os.path.join(bundle, name))
                files.append(name)
            except OSError:
                pass

        lineage_node = None
        if self.lineage_path:
            lineage_node = _active_lineage(self.lineage_path)
            if lineage_node is not None and _write_json(
                os.path.join(bundle, "lineage.json"), lineage_node
            ):
                files.append("lineage.json")

        profile_dir = None
        if self.profile:
            profile_dir = self._capture_profile(bundle)
            if profile_dir:
                files.append("profile/")

        manifest = {
            "v": 1,
            "kind": kind,
            "signal": signal,
            "ts": now,
            "window_s": self.window_s,
            "start_ts": start_ts,
            "trigger": record,
            "lineage_id": (
                lineage_node.get("id") if lineage_node else None
            ),
            "files": files,
            "pid": os.getpid(),
        }
        # the manifest lands LAST: its presence == a complete bundle.
        if not _write_json(
            os.path.join(bundle, _BUNDLE_MANIFEST), manifest
        ):
            return None
        self.assembled += 1
        if self._emit is not None:
            try:
                self._emit(
                    "incident", "incident.assembled",
                    kind=kind, signal=signal, bundle=bundle,
                    lineage_id=manifest["lineage_id"],
                    files=files,
                )
            except Exception:  # noqa: BLE001
                pass
        return bundle

    def _capture_profile(self, bundle: str) -> str | None:
        """Fire the PR 14 flight recorder into the bundle; also touch
        the cross-process trigger file so training processes watching
        ``DCT_PROFILE_TRIGGER`` self-capture their side."""
        trigger = os.environ.get("DCT_PROFILE_TRIGGER")
        if trigger:
            try:
                os.makedirs(os.path.dirname(trigger) or ".", exist_ok=True)
                with open(trigger, "a"):
                    os.utime(trigger, None)
            except OSError:
                pass
        try:
            from dct_tpu.observability.capture import capture_profile

            out = os.path.join(bundle, "profile")
            capture_profile(out, self.profile_s, emit=self._emit)
            return out
        except Exception:  # noqa: BLE001 — no jax / profiler busy: the
            return None  # bundle is still useful without the capture

    def close(self) -> None:
        with self._lock:
            threads = list(self._threads)
        for t in threads:
            t.join(self.profile_s + 10.0)


# ----------------------------------------------------------------------
# reading bundles (inspector + CLI)


def list_bundles(directory: str) -> list[dict]:
    """Every complete bundle under ``directory``, oldest first."""
    out: list[dict] = []
    try:
        names = sorted(os.listdir(directory))
    except OSError:
        return out
    for name in names:
        path = os.path.join(directory, name, _BUNDLE_MANIFEST)
        try:
            with open(path) as f:
                manifest = json.load(f)
        except (OSError, ValueError):
            continue
        if isinstance(manifest, dict):
            manifest["bundle"] = os.path.join(directory, name)
            manifest["name"] = name
            out.append(manifest)
    out.sort(key=lambda m: m.get("ts", 0.0))
    return out


def _cli_dir(argv_dir: str | None) -> str:
    if argv_dir:
        return argv_dir
    from dct_tpu.config import ObservabilityConfig

    obs = ObservabilityConfig.from_env()
    if obs.incident_dir:
        return obs.incident_dir
    if obs.ts_dir:
        return default_incident_dir(obs.ts_dir)
    return "incidents"


def main(argv: list[str] | None = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    cmd = argv[0] if argv else "list"
    if cmd == "list":
        directory = _cli_dir(argv[1] if len(argv) > 1 else None)
        bundles = list_bundles(directory)
        if not bundles:
            print(f"incidents: none under {directory}")
            return 0
        for m in bundles:
            print(
                f"{m['name']}  kind={m.get('kind')} "
                f"signal={m.get('signal')} "
                f"lineage={m.get('lineage_id') or '-'} "
                f"files={len(m.get('files', []))}"
            )
        return 0
    if cmd == "show":
        if len(argv) < 2:
            print("usage: incident show <bundle-dir>", file=sys.stderr)
            return 2
        path = argv[1]
        if os.path.isdir(path):
            path = os.path.join(path, _BUNDLE_MANIFEST)
        try:
            with open(path) as f:
                print(json.dumps(json.load(f), indent=2, sort_keys=True))
        except (OSError, ValueError) as e:
            print(f"incident: cannot read {path}: {e}", file=sys.stderr)
            return 1
        return 0
    if cmd == "assemble":
        signal = argv[1] if len(argv) > 1 else "manual"
        mgr = IncidentManager.from_env()
        if mgr is None:
            print(
                "incident: plane unarmed (set DCT_TS_DIR, and leave "
                "DCT_INCIDENT=1)", file=sys.stderr,
            )
            return 1
        bundle = mgr.assemble("manual", signal, {"argv": argv})
        if bundle is None:
            print("incident: assembly failed", file=sys.stderr)
            return 1
        print(bundle)
        return 0
    print(
        "usage: python -m dct_tpu.observability.incident "
        "{list [dir] | show <bundle> | assemble [signal]}",
        file=sys.stderr,
    )
    return 2


if __name__ == "__main__":
    raise SystemExit(main())
